"""Core vocabulary: the ``rank`` / ``segments`` / ``local`` customization
points and the remote/distributed range concepts.

TPU-native re-design of the reference's L0 layer:

* CPOs ``lib::ranges::rank/segments/local`` with method -> ADL -> fallback
  resolution (reference ``include/dr/details/ranges.hpp:38-161``),
* concepts ``remote_range`` / ``distributed_range`` etc.
  (``include/dr/concepts/concepts.hpp:11-53``).

Resolution order here mirrors the reference: a ``__dr_rank__``-style method
on the object ("member function"), then a ``singledispatch`` registration
("ADL overload") so foreign types can participate, then a documented
fallback.  ``disable_rank`` (``ranges.hpp:15``) maps to the ``disable_rank``
class attribute.

On TPU, "rank" identifies the mesh position (device slot) owning a shard of
a ``jax.Array``; ``local()`` yields the device-resident shard values instead
of a raw pointer — arrays are immutable values, so local access is a read
of the current version, and writes go through the container's batched
update API (see SURVEY.md §7 hard-part 1).
"""

from __future__ import annotations

from functools import singledispatch
from typing import Any

__all__ = [
    "rank",
    "segments",
    "local",
    "rank_dispatch",
    "segments_dispatch",
    "local_dispatch",
    "is_remote_range",
    "is_distributed_range",
    "is_remote_contiguous_range",
    "is_distributed_contiguous_range",
    "has_rank",
    "has_segments",
]


# ---------------------------------------------------------------------------
# "ADL" dispatch tables: foreign types register here, like the reference's
# DR_RANGES_NAMESPACE ADL hooks (details/segments_tools.hpp:149-223).
# ---------------------------------------------------------------------------

@singledispatch
def rank_dispatch(obj: Any):
    raise TypeError(f"rank() is not available for {type(obj).__name__}")


@singledispatch
def segments_dispatch(obj: Any):
    raise TypeError(f"segments() is not available for {type(obj).__name__}")


@singledispatch
def local_dispatch(obj: Any):
    raise TypeError(f"local() is not available for {type(obj).__name__}")


# ---------------------------------------------------------------------------
# CPOs
# ---------------------------------------------------------------------------

def rank(obj: Any) -> int:
    """Owning mesh rank of a remote range / segment / iterator.

    Mirrors ``rank_fn_`` (ranges.hpp:38-68): member -> ADL -> iterator
    fallback (an object exposing a single segment delegates to it).
    """
    if getattr(type(obj), "disable_rank", False):
        raise TypeError(f"rank() disabled for {type(obj).__name__}")
    fn = getattr(obj, "__dr_rank__", None)
    if fn is not None:
        return fn() if callable(fn) else fn
    try:
        return rank_dispatch(obj)
    except TypeError:
        pass
    raise TypeError(f"rank() is not available for {type(obj).__name__}")


def segments(obj: Any):
    """Sequence of remote sub-ranges making up a distributed range.

    Mirrors ``segments_fn_`` (ranges.hpp:94-114).  Always returns a
    (possibly empty) list; an *empty* list is the misalignment signal
    (zip of misaligned ranges — segments_tools.hpp:117-121).
    """
    fn = getattr(obj, "__dr_segments__", None)
    if fn is not None:
        return list(fn())
    try:
        return list(segments_dispatch(obj))
    except TypeError:
        pass
    raise TypeError(f"segments() is not available for {type(obj).__name__}")


def local(obj: Any):
    """Device-local values of a remote range/segment.

    Mirrors ``local_fn_`` (ranges.hpp:133-161).  For a segment of a
    sharded ``jax.Array`` this returns the addressable shard slice (a jax
    array on the owning device) — the functional analog of the raw local
    pointer.  For host objects (numpy/lists) it is the identity, matching
    the reference fallback for non-remote iterators.
    """
    fn = getattr(obj, "__dr_local__", None)
    if fn is not None:
        return fn()
    try:
        return local_dispatch(obj)
    except TypeError:
        pass
    return obj  # identity fallback (ranges.hpp:150-155)


# ---------------------------------------------------------------------------
# Concepts (concepts/concepts.hpp:11-53) as runtime predicates.
# ---------------------------------------------------------------------------

def has_rank(obj: Any) -> bool:
    try:
        rank(obj)
        return True
    except TypeError:
        return False


def has_segments(obj: Any) -> bool:
    return getattr(obj, "__dr_segments__", None) is not None or _has_dispatch(
        segments_dispatch, obj
    )


def _has_dispatch(table, obj) -> bool:
    return table.dispatch(type(obj)) is not table.dispatch(object)


def is_remote_range(obj: Any) -> bool:
    """remote_range: a sized range with a rank (concepts.hpp:15-17)."""
    return _is_sized(obj) and has_rank(obj)


def is_distributed_range(obj: Any) -> bool:
    """distributed_range: sized range whose segments() are remote ranges
    (concepts.hpp:19-21)."""
    if not _is_sized(obj) or not has_segments(obj):
        return False
    segs = segments(obj)
    return all(is_remote_range(s) for s in segs)


def is_remote_contiguous_range(obj: Any) -> bool:
    """remote_contiguous_range (concepts.hpp:37-43): remote and backed by a
    contiguous local shard — here: ``local()`` yields an array."""
    if not is_remote_range(obj):
        return False
    loc = local(obj)
    return hasattr(loc, "shape") or hasattr(loc, "__array__")


def is_distributed_contiguous_range(obj: Any) -> bool:
    """distributed_contiguous_range (concepts.hpp:45-52)."""
    return is_distributed_range(obj) and all(
        is_remote_contiguous_range(s) for s in segments(obj)
    )


def _is_sized(obj: Any) -> bool:
    try:
        len(obj)
        return True
    except TypeError:
        return False
