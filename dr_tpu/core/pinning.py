"""Identity-stable cache keys.

Compiled-program caches throughout the package key on the identity of
Python objects (user callables, the runtime mesh).  A raw ``id()`` is only
stable while the object lives: once collected, the id can be recycled by a
later allocation, silently aliasing a different object's cache entry.
``pinned_id`` returns the id AND pins the object, so a live cache key can
never be recycled — independent of whether the cached artifact happens to
retain the object (jitted closures do today; AOT-compiled entries would
not).

Pins are a bounded LRU like the program caches themselves
(``DR_TPU_PIN_CAP``, default 65536 — two orders of magnitude above the
worst-case number of identities referenced by all live cache entries at
the default cache caps).  Eviction is amortized: the table may overshoot
the cap by 25% before a batch eviction brings it back, so a churning
workload pays one cache scan per cap/4 dispatches, not one per dispatch.
Touch discipline: every dispatch rebuilds its
key through ``pinned_id``, so a hot object's pin is always recent.
Soundness does NOT rely on the cap though: when a pin IS evicted, every
registered program cache drops the entries whose keys reference that
identity (``register_cache``), so a recycled id can never alias a stale
program — the evicted object's programs simply recompile if it ever
comes back.
"""

import weakref
from collections import OrderedDict

from ..utils.env import env_int

_pins: "OrderedDict[int, object]" = OrderedDict()
_caches: list = []  # weakref.ref of registered program caches


class PinnedId(int):
    """An ``int`` that knows it is an object identity.  Hashing and
    equality are inherited (cache keys behave exactly as before); the
    distinct TYPE lets consumers that compare keys ACROSS processes
    (utils/spmd_guard) canonicalize identities away without guessing
    from magnitude — ids are process-local, structure is not."""

    __slots__ = ()


def register_cache(cache) -> None:
    """Program caches register so pin eviction can purge the entries
    that reference the evicted identity (utils/spmd_guard.TappedCache
    does this on construction).  Held by weakref: dict subclasses are
    unhashable, so a WeakSet cannot hold them — a ref list can."""
    _caches.append(weakref.ref(cache))


def _key_mentions(key, idents) -> bool:
    if isinstance(key, PinnedId):
        return int(key) in idents
    if isinstance(key, (tuple, list, frozenset)):
        return any(_key_mentions(part, idents) for part in key)
    return False


def _purge(idents) -> None:
    live = []
    for ref in _caches:
        cache = ref()
        if cache is None:
            continue  # cache itself was collected; drop the ref
        live.append(ref)
        stale = [k for k in cache if _key_mentions(k, idents)]
        for k in stale:
            del cache[k]
    _caches[:] = live


def pinned_id(obj):
    """Stable identity key for ``obj`` (None passes through)."""
    if obj is None:
        return None
    i = id(obj)
    _pins[i] = obj          # insert or refresh
    _pins.move_to_end(i)
    cap = env_int("DR_TPU_PIN_CAP", 65536, floor=1024)
    # Amortized batch eviction: let the table overshoot by 25%, then
    # evict down to cap with ONE scan of the registered caches for the
    # whole batch.  Per-dispatch purge cost for identity-churning
    # workloads is O(total cached keys / (cap/4)) instead of a full
    # scan per dispatch.  The trigger depends only on dict length, so
    # SPMD processes evicting in dispatch order stay identical.
    if len(_pins) > cap + (cap >> 2):
        evicted = set()
        while len(_pins) > cap:
            old, _ = _pins.popitem(last=False)
            evicted.add(old)
        _purge(evicted)
    return PinnedId(i)
