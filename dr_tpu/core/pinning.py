"""Identity-stable cache keys.

Compiled-program caches throughout the package key on the identity of
Python objects (user callables, the runtime mesh).  A raw ``id()`` is only
stable while the object lives: once collected, the id can be recycled by a
later allocation, silently aliasing a different object's cache entry.
``pinned_id`` returns the id AND pins the object for the process lifetime,
so a key can never be recycled — independent of whether the cached
artifact happens to retain the object (jitted closures do today;
AOT-compiled entries would not).

Growth is bounded by the number of distinct pinned objects, the same
envelope as the program caches themselves (which never evict).
"""

_pins: dict = {}


class PinnedId(int):
    """An ``int`` that knows it is an object identity.  Hashing and
    equality are inherited (cache keys behave exactly as before); the
    distinct TYPE lets consumers that compare keys ACROSS processes
    (utils/spmd_guard) canonicalize identities away without guessing
    from magnitude — ids are process-local, structure is not."""

    __slots__ = ()


def pinned_id(obj):
    """Stable identity key for ``obj`` (None passes through)."""
    if obj is None:
        return None
    _pins.setdefault(id(obj), obj)
    return PinnedId(id(obj))
