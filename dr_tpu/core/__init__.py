from .vocabulary import (rank, segments, local, is_remote_range,
                         is_distributed_range)
from .segment import Segment, ZipSegment
