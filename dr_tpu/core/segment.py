"""Segment types: the value vocabulary returned by ``segments()``.

A ``Segment`` is the TPU analog of the reference's ``lib::remote_subrange``
(``include/dr/details/remote_subrange.hpp:13-37``) and of the per-rank
segment types ``dv_segment`` (``mhp/containers/distributed_vector.hpp:137-162``)
and ``device_span`` (``shp/device_span.hpp:43-84``): a contiguous slice of a
distributed container's logical index space owned by one mesh rank.

Design shift for TPU: a segment does not hold a pointer — it holds
``(base, rank, begin, end)`` metadata plus a lazy elementwise op chain (how
``transform_view`` segments stay distributed, reference
``views/transform.hpp:9-43``).  ``local()`` reads the current shard *value*;
mutation happens through the owning container's batched update API.
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import numpy as np

__all__ = ["Segment", "ZipSegment"]


class Segment:
    """Contiguous slice [begin, end) of ``base``'s logical space on ``rank``."""

    __slots__ = ("base", "_rank", "begin", "end", "ops")

    def __init__(self, base: Any, rank: int, begin: int, end: int,
                 ops: Tuple[Callable, ...] = ()):
        assert end >= begin
        self.base = base
        self._rank = rank
        self.begin = begin
        self.end = end
        self.ops = tuple(ops)

    # -- vocabulary protocol ------------------------------------------------
    def __dr_rank__(self) -> int:
        return self._rank

    def __dr_local__(self):
        """Device-resident values of this slice (no cross-device traffic)."""
        vals = self.base._local_values(self._rank, self.begin, self.end)
        for op in self.ops:
            vals = op(vals)
        return vals

    # -- sequence-ish surface ----------------------------------------------
    def __len__(self) -> int:
        return self.end - self.begin

    def __getitem__(self, key):
        if isinstance(key, slice):
            start, stop, step = key.indices(len(self))
            assert step == 1, "segments are contiguous"
            return Segment(self.base, self._rank, self.begin + start,
                           self.begin + stop, self.ops)
        return self.materialize()[key]

    def __iter__(self):
        return iter(self.materialize())

    def first(self, k: int) -> "Segment":
        return self[:k]

    def last(self, k: int) -> "Segment":
        return self[len(self) - k:]

    def subspan(self, offset: int, count: int) -> "Segment":
        return self[offset:offset + count]

    def with_op(self, op: Callable) -> "Segment":
        return Segment(self.base, self._rank, self.begin, self.end,
                       self.ops + (op,))

    def materialize(self) -> np.ndarray:
        """Host copy of this segment's values (the test-oracle path)."""
        vals = self.base._host_values(self.begin, self.end)
        for op in self.ops:
            vals = op(vals)
        return np.asarray(vals)

    def __repr__(self):
        return (f"Segment(rank={self._rank}, [{self.begin},{self.end})"
                f"{', ops' if self.ops else ''})")


class ZipSegment:
    """A rank-aligned tuple of equally-sized segments (one per zipped range).

    Analog of the reference's zipped segments (``shp/zip_view.hpp:149-206``):
    all parts share a rank and length, so elementwise work on the tuple stays
    on one device.
    """

    __slots__ = ("parts",)

    def __init__(self, *parts):
        assert parts
        n = len(parts[0])
        assert all(len(p) == n for p in parts), "zip segments must align"
        self.parts = tuple(parts)

    def __dr_rank__(self) -> int:
        from .vocabulary import rank
        return rank(self.parts[0])

    def __dr_local__(self):
        from .vocabulary import local
        return tuple(local(p) for p in self.parts)

    def __len__(self) -> int:
        return len(self.parts[0])

    def __getitem__(self, key):
        if isinstance(key, slice):
            return ZipSegment(*(p[key] for p in self.parts))
        return tuple(p.materialize()[key] for p in self.parts)

    def __iter__(self):
        mats = [p.materialize() for p in self.parts]
        return iter(zip(*mats))

    def materialize(self):
        return tuple(p.materialize() for p in self.parts)

    def __repr__(self):
        return f"ZipSegment(rank={self.__dr_rank__()}, n={len(self)})"
