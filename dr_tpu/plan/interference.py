"""Interference graph over recorded plan items — THE one place that
interprets declared footprints (docs/SPEC.md §23.1).

Every recorded item carries a declared read/write footprint
(:class:`_FusedOp.reads`/``writes`` in run-local SLOTS,
:class:`_Opaque.reads`/``writes`` in CONTAINERS, ``None`` = unknown
barrier).  Everything that REORDERS, DROPS, or SKIPS work based on
those declarations routes through this module:

* the §21 optimizer passes (merge disjointness, dce coverage,
  pushdown's linearized event stream),
* the ``flush_reads`` footprint-gated flush skip (§21.2),
* the plansan runtime verifier and serializability oracle (§23).

drlint rule R10 enforces the routing statically: outside this file, no
code under ``dr_tpu/plan/`` may read a ``.reads``/``.writes``
attribute — a future pass hand-rolling its own aliasing logic is a
lint finding before it is a miscompile.
"""

from __future__ import annotations

from typing import Optional

from . import PlanScalar, _Opaque, _Run

__all__ = [
    "op_reads", "op_writes", "op_read_slots", "op_write_slots",
    "op_footprint_key", "op_removable", "opaque_reads", "opaque_writes",
    "opaque_is_barrier", "remap", "item_touch", "queue_touches",
    "events", "scalar_producers", "op_scalar_producers",
    "view_containers", "Coverage",
]


# ---------------------------------------------------------------------------
# declared-footprint accessors
# ---------------------------------------------------------------------------

def op_reads(op) -> tuple:
    """Declared read SLOTS of a fused op (run-local numbering)."""
    return op.reads


def op_writes(op) -> tuple:
    """Declared write windows of a fused op: ``(slot, off, n, full)``
    tuples (``full`` = whole padded row rebuilt, a coverage killer)."""
    return op.writes


def op_read_slots(op) -> frozenset:
    """The read footprint as a slot set."""
    return frozenset(op.reads)


def op_write_slots(op) -> frozenset:
    """The written slots (window extents dropped)."""
    return frozenset(s for (s, _off, _n, _full) in op.writes)


def op_footprint_key(op) -> tuple:
    """Hashable identity of the op's DECLARED footprint — part of the
    plansan verify-cache key, so a re-declared footprint (the mutation
    battery) re-verifies the same program."""
    return (tuple(op.reads), tuple(op.writes))


def op_removable(op) -> bool:
    """May the dead-op pass even consider this op?  Pure, writes
    something, and has no dispatch-time ``pre`` side effects."""
    return op.pure and bool(op.writes) and op.pre is None


def opaque_reads(item) -> Optional[tuple]:
    """Declared read CONTAINERS of an opaque item (None = unknown)."""
    return item.reads


def opaque_writes(item) -> Optional[tuple]:
    """Declared ``(container, full)`` writes of an opaque item
    (None = unknown)."""
    return item.writes


def opaque_is_barrier(item) -> bool:
    """An opaque item with any unknown footprint is a barrier nothing
    reorders across or eliminates through."""
    return item.reads is None or item.writes is None


def remap(op, smap) -> tuple:
    """The op's declared footprint re-slotted through ``smap`` (source
    run slot -> merged run slot) — the merge pass's wrapper footprint
    comes from here, never hand-rolled."""
    return (tuple(smap[s] for s in op.reads),
            tuple((smap[s], off, n, full)
                  for (s, off, n, full) in op.writes))


# ---------------------------------------------------------------------------
# item-level aliasing queries
# ---------------------------------------------------------------------------

def item_touch(item) -> Optional[set]:
    """Every container id the item may read OR write; None = unknown
    (a barrier nothing reorders across)."""
    if isinstance(item, _Run):
        return {id(c) for c in item.conts}
    if opaque_is_barrier(item):
        return None
    ids = {id(c) for c in item.reads}
    ids.update(id(c) for c, _full in item.writes)
    return ids


def queue_touches(queue, cont) -> bool:
    """Could any queued item read or write ``cont``?  The §21.2
    footprint check ``flush_reads`` keys its skip on.  A run answers
    by slot membership; an opaque item with UNKNOWN footprints answers
    True — the conservative barrier."""
    cid = id(cont)
    for item in queue:
        if isinstance(item, _Run):
            if cid in item._cont_ids:
                return True
        else:
            touch = item_touch(item)
            if touch is None or cid in touch:
                return True
    return False


def view_containers(operand, _depth: int = 0) -> Optional[tuple]:
    """The distributed containers a VIEW operand ultimately reads,
    resolved through ``components``/``base`` chains (zip_view,
    subrange, transform, …).  ``None`` = some leaf is not a
    recognizable container, so the caller must keep the conservative
    barrier footprint.  Opaque record sites over view operands (gemv
    over a subrange/zip) declare real footprints through this helper
    instead of ``reads=None`` — the §21.2 ``flush_reads`` skip then
    stops worst-case flushing on every host touch."""
    if _depth > 8:
        return None
    comps = getattr(operand, "components", None)
    if comps is not None:
        out = []
        for c in comps:
            sub = view_containers(c, _depth + 1)
            if sub is None:
                return None
            out.extend(sub)
        return tuple(out)
    base = getattr(operand, "base", None)
    if base is not None:
        return view_containers(base, _depth + 1)
    if hasattr(operand, "__dr_segments__") and hasattr(operand, "__len__"):
        # a container leaf (or a self-generating range like iota,
        # whose id simply never aliases a queued container)
        return (operand,)
    return None


def op_scalar_producers(op) -> set:
    """Ids of the runs producing still-pending scalar operands THIS op
    fetches at dispatch — the plansan oracle's scalar dependency
    edges."""
    return {id(v._run) for v in op.vals
            if isinstance(v, PlanScalar) and v._val is None
            and v._run is not None}


def scalar_producers(run) -> set:
    """Ids of the runs producing still-pending scalar operands this
    run fetches at dispatch — it must execute AFTER every one of them,
    so no pass may move it past one."""
    out = set()
    for o in run.ops:
        out |= op_scalar_producers(o)
    return out


def events(q) -> list:
    """Linearized touch events, execution order: ``(kind, cont_id,
    item_index, op_or_None, full)`` with ``kind`` in {"r", "w",
    "barrier"} (barriers carry cont_id None)."""
    ev = []
    for qi, item in enumerate(q):
        if isinstance(item, _Opaque):
            if opaque_is_barrier(item):
                ev.append(("barrier", None, qi, None, False))
                continue
            for c in item.reads:
                ev.append(("r", id(c), qi, None, False))
            for c, full in item.writes:
                ev.append(("w", id(c), qi, None, full))
            continue
        for o in item.ops:
            for s in op_reads(o):
                ev.append(("r", id(item.conts[s]), qi, o, False))
            for (s, off, n, full) in op_writes(o):
                ev.append(("w", id(item.conts[s]), qi, o, full))
    return ev


# ---------------------------------------------------------------------------
# backward interval coverage (the dce pass's walk)
# ---------------------------------------------------------------------------

class Coverage:
    """Backward interval-coverage state over container cells: a pure
    op whose written windows are all overwritten before any read is
    dead; reads reset coverage; a kept op's write window extends
    coverage only when the op does not read that container (§21.2 —
    the mask-preserve argument).  A full-row victim (ghost-zeroing
    relational outputs) retires only under a full-row killer."""

    def __init__(self):
        self._cov: dict = {}

    def _cover(self, c, lo, hi, ghost) -> None:
        ent = self._cov.get(id(c))
        if ent is None:
            ent = self._cov[id(c)] = [[], False]
        if ghost:
            ent[1] = True
        if hi <= lo:
            return
        ivs = ent[0]
        ivs.append((lo, hi))
        ivs.sort()
        out = [ivs[0]]
        for a, b in ivs[1:]:
            la, lb = out[-1]
            if a <= lb:
                out[-1] = (la, max(lb, b))
            else:
                out.append((a, b))
        ent[0] = out

    def _is_covered(self, c, off, n, needs_ghost) -> bool:
        if n <= 0:
            return True  # an empty window writes nothing
        ent = self._cov.get(id(c))
        if ent is None:
            return False
        if needs_ghost and not ent[1]:
            return False
        for a, b in ent[0]:
            if a <= off and off + n <= b:
                return True
        return False

    def visit_opaque(self, item) -> None:
        """Fold an opaque item into the backward walk: a barrier
        clears everything; declared reads reset their containers;
        declared full writes of containers the item does not read
        extend ghost coverage."""
        if opaque_is_barrier(item):
            self._cov.clear()
            return
        for c in item.reads:
            self._cov.pop(id(c), None)
        rid = {id(c) for c in item.reads}
        for c, full in item.writes:
            if full and id(c) not in rid:
                self._cover(c, 0, len(c), True)

    def op_dead(self, run, op) -> bool:
        """Is this fused op's every written window already covered
        (overwritten before any read happens later in execution
        order)?  Only :func:`op_removable` ops qualify."""
        return op_removable(op) and all(
            self._is_covered(run.conts[s], off, n, full)
            for (s, off, n, full) in op_writes(op))

    def visit_op(self, run, op) -> None:
        """Fold a KEPT fused op into the walk: reads reset their
        containers; writes extend coverage only for containers the op
        does not read (the mask-preserve passthrough argument)."""
        rid = {id(run.conts[s]) for s in op_reads(op)}
        for s in op_reads(op):
            self._cov.pop(id(run.conts[s]), None)
        for (s, off, n, full) in op_writes(op):
            c = run.conts[s]
            if id(c) in rid:
                continue
            if full:
                self._cover(c, 0, len(c), True)
            else:
                self._cover(c, off, off + n, False)
