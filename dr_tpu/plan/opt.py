"""Plan optimizer: the pass pipeline run over a recorded queue at
flush (docs/SPEC.md §21).

The recorded queue is a LOGICAL plan — ops land in recording order,
runs split wherever an opaque op or a mesh change interrupts them, and
every capacity/config decision is whatever the caller or the code
default guessed.  This module rewrites the queue just before execution:

* **merge** — independent fusible runs over one mesh that were split
  only by recording order (an opaque op or another mesh's run between
  them) coalesce into ONE dispatch.  A run moves earlier only past
  items whose declared footprints are disjoint from every container it
  touches, and never past the producer of a scalar operand it
  consumes — so the merged program threads exactly the state the
  recorded order would have.
* **dce** — a pure op whose written window is fully overwritten before
  any read (backward interval-coverage walk, ghost-aware: a full-row
  killer is needed to retire a full-row victim) is eliminated; a run
  left empty disappears entirely.
* **pushdown** — a single-input same-dtype projection whose output
  container feeds ONLY a relational op and dies afterwards is re-homed
  into that op's scratch-sort copy (the op becomes a view-chain
  BoundOp the copy fuses), turning the intermediate materialization
  into a dead op the dce pass then removes.
* **capinfer** / **joinroute** — config-level passes consulted at op
  execution time: relational auto-capacity inference (probe + tuning
  DB hints, ``algorithms/relational.py``) and measured join-route
  thresholds (``dr_tpu/tuning.py``).  They register here so one knob
  family covers the whole pipeline.

Bit-identity contract (§21.3): every rewrite preserves the exact value
of every observable — container contents (owned cells AND the ghost
contract), resolved scalars, relational counts — against the
unoptimized flush.  Merge keeps the per-op seal+barrier discipline
(cross-op contraction stays pinned inside the merged program exactly
as across the split programs); dce removes only writes that are
provably overwritten before any read; pushdown routes the same op
through the same single cast.  ``DR_TPU_PLAN_OPT=0`` turns the whole
pipeline off, ``auto`` (the default) runs the rewrite passes that
never add work, ``all`` adds the probe/rewrite passes; any pass name
in ``DR_TPU_PLAN_OPT_DISABLE`` (csv) is skipped — the bisection knob
the fuzz battery sweeps.

Failure posture: an optimizer bug must never take a flush down — any
pass exception is caught, announced through ``warn_fallback``, and the
recorded queue executes unoptimized.
"""

from __future__ import annotations

from typing import List

from . import _FusedOp, _Opaque, _Run
from . import interference as _interf
from .. import obs as _obs
from ..utils.env import env_str

__all__ = ["optimize", "expand_items", "enabled", "mode", "PASSES",
           "PASS_NAMES"]

#: passes the default ``auto`` mode leaves OFF: they spend extra work
#: (probe dispatches, view rewrites) that only pays on relational
#: pipelines — ``all`` arms them
_AUTO_OFF = frozenset(("pushdown", "capinfer"))


def mode() -> str:
    """``DR_TPU_PLAN_OPT``: ``0``/``off`` disables every pass, ``all``
    arms every pass, anything else (default) is ``auto``."""
    raw = env_str("DR_TPU_PLAN_OPT", "auto").lower()
    if raw in ("0", "off", "none"):
        return "0"
    if raw == "all":
        return "all"
    return "auto"


def _disabled() -> set:
    return {s.strip().lower()
            for s in env_str("DR_TPU_PLAN_OPT_DISABLE").split(",")
            if s.strip()}


def enabled(name: str) -> bool:
    """Is pass ``name`` armed under the current mode + per-pass
    opt-outs?  The config-level passes (capinfer, joinroute) call this
    at op-execution time, so a sweep can flip them per call."""
    m = mode()
    if m == "0" or name in _disabled():
        return False
    if m == "auto" and name in _AUTO_OFF:
        return False
    return True


def expand_items(items) -> list:
    """Optimized queue items back to the RECORDED items they execute
    (merged/cloned runs carry ``_sources``) — the identity set the
    undo/replay/faulted-flush contracts are keyed on."""
    out = []
    for it in items:
        src = getattr(it, "_sources", None)
        if src is None:
            out.append(it)
        else:
            out.extend(expand_items(src))
    return out


# ---------------------------------------------------------------------------
# footprints — every aliasing/ordering query routes through
# plan/interference.py (drlint rule R10): a pass must not hand-roll
# its own footprint interpretation
# ---------------------------------------------------------------------------

class _Group:
    """A merge group under construction: runs in record order, merged
    into one program at materialization."""

    __slots__ = ("runs", "touch")

    def __init__(self, run):
        self.runs = [run]
        self.touch = {id(c) for c in run.conts}

    def add(self, run):
        self.runs.append(run)
        self.touch.update(id(c) for c in run.conts)


class _SubState:
    """List proxy translating a source run's slot numbering into the
    merged run's combined state list."""

    __slots__ = ("_s", "_m")

    def __init__(self, state, smap):
        self._s = state
        self._m = smap

    def __getitem__(self, i):
        return self._s[self._m[i]]

    def __setitem__(self, i, v):
        self._s[self._m[i]] = v


def _wrap(o: _FusedOp, smap, soff, wrapped) -> _FusedOp:
    """Re-slot one source op into the merged run: slots map through
    ``smap``, same-run scalar refs shift by ``soff`` (the merged souts
    list concatenates the sources' in order)."""
    spec2 = tuple(("r", s[1] + soff) if isinstance(s, tuple) else s
                  for s in o.spec)

    def emit(state, svals, souts, _o=o, _m=smap):
        _o.emit(_SubState(state, _m), svals, souts)

    reads2, writes2 = _interf.remap(o, smap)
    w = _FusedOp(o.name, ("mrg", o.key, smap, soff), emit, spec2,
                 o.vals, pre=o.pre, reads=reads2, writes=writes2,
                 pure=o.pure)
    # the wrapper executes the SOURCE op's emit — the plansan oracle
    # resolves executed identities back to recorded ops through src
    w.src = o
    # the wrapper copied the operand values; the SOURCE op's copy is
    # dropped once the whole pass has succeeded (deferred — clearing
    # here would gut the recorded queue the never-take-a-flush-down
    # fallback re-executes after a later pass failure), so the cached
    # merged program (whose closure pins the wrapper, which pins the
    # source op) cannot pin a container-sized splice array
    wrapped.append(o)
    return w


def _materialize(group: _Group) -> _Run:
    if len(group.runs) == 1:
        return group.runs[0]
    first = group.runs[0]
    m = _Run(first.mesh, first.axis)
    m._sources = list(group.runs)
    m._wrapped = wrapped = []
    for r in group.runs:
        smap = tuple(m.slot(c) for c in r.conts)
        soff = len(m.handles)
        m.handles.extend(r.handles)
        identity = soff == 0 and smap == tuple(range(len(r.conts)))
        for o in r.ops:
            m.ops.append(o if identity
                         else _wrap(o, smap, soff, wrapped))
    return m


def _pass_merge(q):
    """Coalesce independent same-mesh fusible runs (§21.2)."""
    out: List = []
    merged = 0
    for item in q:
        if not (isinstance(item, _Run) and item.ops):
            out.append(item)
            continue
        touch = {id(c) for c in item.conts}
        # producers of scalar operands this run fetches at dispatch:
        # it must execute AFTER them, so it cannot move past one
        pending = _interf.scalar_producers(item)
        target = None
        for j in range(len(out) - 1, -1, -1):
            prev = out[j]
            if isinstance(prev, _Group):
                runs, ptouch = prev.runs, prev.touch
            elif isinstance(prev, _Run):
                runs, ptouch = [prev], _interf.item_touch(prev)
            else:
                runs, ptouch = None, _interf.item_touch(prev)
            if runs is not None and runs[0].mesh is item.mesh \
                    and runs[0].axis == item.axis:
                if any(id(r) in pending for r in runs):
                    break  # scalar-dependent on the candidate itself
                target = j
                break
            # a middle item: this run may only move past it when their
            # footprints are disjoint and no scalar dependency exists
            if ptouch is None or (touch & ptouch):
                break
            if runs is not None and any(id(r) in pending for r in runs):
                break
        if target is None:
            out.append(item)
            continue
        prev = out[target]
        if isinstance(prev, _Group):
            prev.add(item)
        else:
            out[target] = g = _Group(prev)
            g.add(item)
        merged += 1
    final = [(_materialize(x) if isinstance(x, _Group) else x)
             for x in out]
    return final, merged


# ---------------------------------------------------------------------------
# dead-op elimination
# ---------------------------------------------------------------------------

def _clone_run(run: _Run, ops) -> _Run:
    nr = _Run(run.mesh, run.axis)
    nr.conts = run.conts          # slot numbering stays valid
    nr._cont_ids = run._cont_ids
    nr.handles = run.handles
    nr.ops = ops
    nr._sources = [run]
    return nr


def _pass_dce(q):
    """Backward coverage walk: a pure op whose written windows are all
    overwritten before any read dies; reads reset coverage; a kept
    op's write window extends coverage only when the op does not read
    that container (§21.2 — the mask-preserve argument).  A full-row
    victim (ghost-zeroing relational outputs) retires only under a
    full-row killer."""
    out_rev: List = []
    removed = 0
    cov = _interf.Coverage()
    for item in reversed(q):
        if isinstance(item, _Opaque):
            cov.visit_opaque(item)
            out_rev.append(item)
            continue
        kept = []
        changed = False
        for o in reversed(item.ops):
            if cov.op_dead(item, o):
                removed += 1
                changed = True
                continue
            cov.visit_op(item, o)
            kept.append(o)
        if not changed:
            out_rev.append(item)
        elif kept or item.handles:
            out_rev.append(_clone_run(item, list(reversed(kept))))
        # else: every op died and no handles — the run disappears
    return list(reversed(out_rev)), removed


# ---------------------------------------------------------------------------
# projection pushdown into the relational scratch-sort copy
# ---------------------------------------------------------------------------

def _pushdown_one(q, item, name, chain):
    """Try to push the producer of input channel ``name`` (a plain
    whole/sub-range over ``cont``) into the relational scratch copy.
    Returns True when the rewrite landed."""
    from ..views import views as _v
    cont, off, n, plain = chain
    if not plain or n <= 0:
        return False
    ev = _interf.events(q)
    qi = q.index(item)
    own = [i for i, e in enumerate(ev) if e[2] == qi]
    if not own:
        return False
    e0, e1 = min(own), max(own) + 1
    # --- backward: the LAST touch of cont before the opaque must be a
    # pushable transform covering the read window
    T = None
    t_pos = None
    for i in range(e0 - 1, -1, -1):
        kind, cid, _qj, o, _full = ev[i]
        if kind == "barrier":
            return False
        if cid != id(cont):
            continue
        if kind == "w" and o is not None and o.push is not None:
            a, t_off, t_n, _op, _sc = o.push
            if t_off <= off and t_off + t_n >= off + n \
                    and a is not cont:
                T, t_pos = o, i
        break
    if T is None:
        return False
    a, t_off, t_n, op, scalars = T.push
    # --- the transform's input must be write-free between T and the
    # opaque (its value at the opaque's flush position must equal its
    # value where T would have run), and nothing else may touch the
    # intermediate in between
    for i in range(t_pos + 1, e0):
        kind, cid, _qj, _o, _full = ev[i]
        if kind == "barrier":
            return False
        if cid == id(a) and kind == "w":
            return False
        if cid == id(cont):
            return False
    # --- forward deadness: cont must be fully overwritten (no read
    # first) after the opaque, else eliminating T would be observable
    dead = False
    for i in range(e1, len(ev)):
        kind, cid, _qj, _o, full = ev[i]
        if kind == "barrier":
            return False
        if cid != id(cont):
            continue
        if kind == "w" and full:
            dead = True
            break
        return False
    if not dead:
        return False  # never overwritten: observable at flush end
    # --- rewrite: the relational input becomes a view chain over the
    # transform's input; the scratch copy fuses the op (one cast on
    # both paths — bit-identical, §21.4)
    base = a if (off == 0 and n == len(a)) \
        else _v.subrange(a, off, off + n)
    item.meta["inputs"][name] = _v.transform(base, op, *scalars)
    item.meta["chains"][name] = (a, off, n, False)
    reads = []
    for _cname, ch in item.meta["chains"].items():
        if ch[0] not in reads:
            reads.append(ch[0])
    item.reads = tuple(reads)
    return True


def _pass_pushdown(q):
    pushes = 0
    for item in q:
        if not (isinstance(item, _Opaque) and isinstance(item.meta,
                                                        dict)):
            continue
        chains = item.meta.get("chains")
        if not chains:
            continue
        for name in list(chains):
            if _pushdown_one(q, item, name, chains[name]):
                pushes += 1
    return q, pushes


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

#: the §21 pass registry (drlint rule R7 checks it against the SPEC
#: table and the bit-identity fuzz arm): queue-rewrite passes carry
#: their implementation; config-level passes (consulted at op
#: execution through :func:`enabled`) register with None
PASSES = (
    ("pushdown", _pass_pushdown),
    ("dce", _pass_dce),
    ("merge", _pass_merge),
    ("capinfer", None),
    ("joinroute", None),
)

PASS_NAMES = tuple(n for n, _fn in PASSES)


def optimize(plan, queue, entry, parent=0):
    """Run the armed passes over ``queue``; returns the queue to
    execute.  Records the per-flush optimizer note in ``entry`` and an
    obs span under the flush (§21.5).  Never raises — a failed pass
    falls back to the recorded queue, announced."""
    if not queue or mode() == "0":
        return queue
    note = {"passes": [], "merged_runs": 0, "dce_ops": 0,
            "pushdowns": 0}
    q = list(queue)
    t0 = _obs.now()
    try:
        for pname, fn in PASSES:
            if fn is None or not enabled(pname):
                continue
            tp = _obs.now()
            q, nhits = fn(q)
            # per-pass span under the flush (§21.5): a traced run
            # shows where optimization time went, pass by pass
            _obs.complete(f"plan.opt.{pname}", tp, cat="plan",
                          parent=parent, hits=nhits)
            note["passes"].append(pname)
            if pname == "merge":
                note["merged_runs"] = nhits
            elif pname == "dce":
                note["dce_ops"] = nhits
            elif pname == "pushdown":
                note["pushdowns"] = nhits
        # the WHOLE pipeline succeeded: the wrapped source ops'
        # operand copies can drop now (deferred to here so a failed
        # pass — even one after merge — falls back to a recorded
        # queue whose ops still carry their operands), and the
        # cached merged programs (whose closures pin the wrappers,
        # which pin the sources) cannot pin container-sized arrays
        for item in q:
            for o in getattr(item, "_wrapped", ()):
                o.vals = []
    except Exception as e:  # pragma: no cover - defensive
        from ..utils.fallback import warn_fallback
        warn_fallback("plan", f"optimizer pass failed ({e!r}); "
                              "flushing the recorded queue unoptimized")
        note["error"] = repr(e)[:120]
        q = list(queue)
    for pname in ("capinfer", "joinroute"):
        if enabled(pname):
            note["passes"].append(pname)
    if note["passes"] or note.get("error"):
        entry["opt"] = note
    _obs.complete("plan.opt", t0, cat="plan", parent=parent,
                  passes="+".join(note["passes"]),
                  merged_runs=note["merged_runs"],
                  dce_ops=note["dce_ops"],
                  pushdowns=note["pushdowns"])
    if _obs.armed():
        _obs.count("plan.opt.merged_runs", note["merged_runs"])
        _obs.count("plan.opt.dce_ops", note["dce_ops"])
        _obs.count("plan.opt.pushdowns", note["pushdowns"])
    return q
