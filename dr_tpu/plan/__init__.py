"""Deferred execution plans: cross-algorithm dispatch fusion.

Every eager algorithm call is ONE dispatch through the tunneled relay —
a drifting tens-of-milliseconds constant that dominates small/medium
ops by up to 10x (docs/PERF.md round-2 lesson).  The bench-only ``*_n``
fused loops prove that chaining N ops into one program + one sync
erases that cost; this module makes the same shape reachable from the
public API::

    with dr_tpu.deferred() as p:
        dr_tpu.fill(a, 0.5)
        dr_tpu.for_each(a, scale, 1.5)
        dr_tpu.halo(a).exchange()
        dr_tpu.transform(a, b, shift, 2.0)
        total = dr_tpu.reduce(b)        # -> lazy PlanScalar
    print(float(total), p.explain())

Inside the region, calls to fill / iota / copy / for_each / transform /
reduce / transform_reduce / dot / inclusive_scan / exclusive_scan /
halo exchange+reduce / stencil_transform on segment-aligned containers
are RECORDED instead of dispatched.  The planner groups maximal
fusible runs (split on mesh changes and on opaque ops); each run
compiles into ONE jitted program cached in a :class:`TappedCache`
keyed by ``_traced_op_key``-style structural identity — BoundOp
scalars, fill values, and host splice arrays are fed as traced
operands, so re-recording the same structure with new values reuses
the compiled program (zero recompile, stable spmd_guard digest).

Flush points (executing the queue in record order):

* **region exit** — the normal path;
* **host materialization** — ``to_array`` / ``materialize`` / ``get`` /
  ``put`` / indexing / ``fence`` on a container, or resolving a
  :class:`PlanScalar`;
* **non-fusible ops** (sort, unaligned fallback routes) — the plan
  flushes, announces the cliff via ``warn_fallback("plan", ...)``
  (registry-routed, chaos-countable), and the op runs eagerly;
* explicit :meth:`Plan.flush`.

``gemv`` records as an ordered OPAQUE op (round 9, like
inclusive_scan): it dispatches through its own program at flush,
record order preserved, and the fusible runs around it stay fused —
no flush cliff, no warn_fallback.  The relational tier (round 14,
docs/SPEC.md §17.2) splits the same way: ``histogram``/``top_k``
have STATIC output shapes and record FUSIBLE
(:meth:`Plan.record_histogram` / :meth:`Plan.record_top_k`), while
``join``/``groupby_aggregate``/``unique`` record opaque and hand back
lazy ``DeferredCount`` handles.  A collective-eligible
``dr_tpu.redistribute`` records FUSED (round 16,
:meth:`Plan.record_redistribute`, docs/SPEC.md §18.3): the
container's layout metadata flips at record time so later recorded
ops key on the dst geometry, the data moves inside the fused run at
flush, and an UNDO log restores the metadata if the queue is dropped
before the move executed; the host-staged route stays an announced
flush point.

Mid-chain reductions ride the carry as device scalars: a recorded
reduce returns a :class:`PlanScalar` whose value is an output of the
fused program; a later recorded op in the SAME run that consumes it
references the in-program value directly (no dispatch, no sync), so an
N-op region costs one dispatch + one sync.

Semantics: a flush applies the queue in record order, so results are
bit-identical to the eager sequence (each recorded op reads the
threaded state its predecessors produced — exactly eager data flow).
Cross-op float contraction is PINNED: every value crossing an op
boundary is sealed (a runtime *1.0 plus lax.optimization_barrier), so
the backend cannot fuse one op's multiply into the next op's add as an
FMA the eager sequence never performed.  WITHIN one op the backend
keeps its usual contraction freedom — an op whose own body is a
multiply-add tree (stencil weight ops) may round a last ULP
differently between the eager and fused compilations of the same
math.  Ghost cells keep the same contract as eager where it is
specified; where eager leaves them unspecified the two paths may
differ.

Failure model: ``plan.flush`` is a registered fault site
(utils/faults — transient, program).  A fault at the flush boundary
drops the not-yet-executed suffix of the queue (containers keep their
pre-flush values for it; already-executed prefix runs stay applied) and
raises the classified error — never a hang, never silent corruption.
Unresolved :class:`PlanScalar` handles from a discarded queue raise on
resolution instead of returning stale numbers.

Optimizer (round 19, docs/SPEC.md §21): the recorded queue is a
LOGICAL plan.  At flush, ``plan/opt.py`` runs a pass pipeline over it
— merge independent fusible runs split only by recording order (fewer
dispatches per flush), eliminate dead ops whose writes are fully
overwritten before any read, push single-input projections into the
relational scratch-sort copy, infer relational output capacities from
key-cardinality probes, and pick the join merge route from measured
thresholds in the persisted tuning DB (``dr_tpu/tuning.py``).  Every
pass is bit-identical-by-construction; ``DR_TPU_PLAN_OPT=0|auto|all``
and per-pass ``DR_TPU_PLAN_OPT_DISABLE`` bisect them.

Observability: :meth:`Plan.explain` / :meth:`Plan.stats` report fused
runs, flush reasons, program-cache hits, and per-flush dispatch counts
from the spmd_guard tap (``utils.spmd_guard.dispatch_count``).  Under
``DR_TPU_TRACE=1`` every flush is additionally an obs span
(``plan.flush`` with ``plan.run``/``plan.opaque`` child spans, flush
reason and cache-hit attributes) and the plan counters land in the
metrics registry (docs/SPEC.md §15).
"""

from __future__ import annotations

from ..utils.env import env_str
from ..utils import sanitize as _sanitize
import threading as _threading
from contextlib import contextmanager
from typing import List, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..algorithms._common import owned_window_mask
from ..algorithms.elementwise import (_apply_chain_ops, _chain_scalars,
                                     _op_key, _traced_op_key)
from ..algorithms.reduce import _MONOIDS, _identity_for
from ..core.pinning import pinned_id
from .. import obs as _obs
from ..utils import faults as _faults
from ..utils import resilience as _resilience
from ..utils import spmd_guard as _guard
from ..utils.spmd_guard import TappedCache
from ..views import views as _v

__all__ = ["Plan", "PlanScalar", "deferred", "active", "flush_reads",
           "barrier"]

#: Fused-run program cache.  A TappedCache so (1) every flush lookup is
#: one counted dispatch on the spmd_guard trace, (2) the ``dispatch.cache``
#: fault site covers deferred dispatch too, and (3) pin eviction purges
#: entries whose keys reference dead op identities.
_plan_cache: dict = TappedCache()

#: The recording plan is PER-THREAD state: the serving daemon
#: (dr_tpu/serve) records batched requests into a plan on its dispatch
#: thread while the host thread may be inside its own deferred region
#: (bench's pipeline config next to a live in-process server).  A
#: process-global here would splice one thread's recorded ops into the
#: other's queue.  The program cache above stays shared — structural
#: keys are thread-agnostic.
_tls = _threading.local()


def _get_active() -> Optional["Plan"]:
    return getattr(_tls, "active", None)


def _set_active(p: Optional["Plan"]) -> None:
    _tls.active = p


def active() -> Optional["Plan"]:
    """The plan currently recording ON THIS THREAD, or None.  Returns
    None while a flush is executing so opaque thunks (and post-flush
    eager fallbacks) run eagerly instead of re-recording themselves."""
    p = _get_active()
    if p is None or p._flushing:
        return None
    return p


def flush_reads(reason: str = "host materialization",
                cont=None) -> None:
    """Flush the active plan (if any) before host-visible state is
    read or externally mutated — the container/runtime hooks call
    this.  With ``cont`` given, the flush is SKIPPED when the queue
    provably never touches that container (docs/SPEC.md §21.2 — the
    same footprints the optimizer keys on): a host write into a fresh
    container (the serve daemon building each batched request's
    operands) must not force the flush cliff on its batchmates'
    recorded ops.  Unknown footprints keep the conservative flush."""
    p = _get_active()
    if p is None or p._flushing or not p._queue:
        return
    if cont is not None and not p.queue_touches(cont):
        return
    p.flush(reason)


def barrier(what: str) -> None:
    """Non-fusible-op boundary: flush the active plan (if any) with a
    ``warn_fallback`` announcement before ``what`` dispatches eagerly."""
    p = active()
    if p is not None:
        p.nonfusible(what)


class PlanScalar:
    """Lazy scalar from a reduction recorded in a deferred region.

    Resolving it (``item()`` / ``float()`` / ``int()`` / ``bool()`` /
    ``device()``) flushes the owning plan if needed — host
    materialization is a flush point.  While still pending it can be
    passed as a scalar argument to later recorded ops: within the same
    fused run it rides the carry as an in-program device value; across
    runs it travels as a device-scalar operand — either way, no host
    round trip."""

    __slots__ = ("_plan", "_run", "_idx", "_val", "_post", "_broken")

    def __init__(self, plan: "Plan", run, idx: int):
        self._plan = plan
        self._run = run
        self._idx = idx
        self._val = None
        self._post = None
        self._broken = False

    def with_post(self, post) -> "PlanScalar":
        """Attach a host-side post-transform applied by :meth:`item`
        (``reduce(r, init=...)``'s init fold)."""
        self._post = post
        return self

    def device(self):
        """The RAW device scalar (flushes the plan if still pending).
        A handle carrying a host-side post (``reduce(r, init=...)``'s
        init fold) refuses this accessor — returning the raw reduction
        would silently drop the fold; resolve via :meth:`item`."""
        if self._post is not None:
            raise ValueError(
                "this deferred scalar carries a host-side init fold; "
                "resolve it with item()/float() instead of device()")
        if self._val is None and not self._broken:
            self._plan.flush("scalar read")
        if self._val is None:
            raise RuntimeError(
                "deferred scalar was discarded before it resolved "
                "(faulted flush or abandoned region)")
        return self._val

    def _raw(self):
        """Resolved raw device scalar (internal; post NOT applied)."""
        if self._val is None and not self._broken:
            self._plan.flush("scalar read")
        if self._val is None:
            raise RuntimeError(
                "deferred scalar was discarded before it resolved "
                "(faulted flush or abandoned region)")
        return self._val

    def item(self):
        v = self._raw().item()
        return self._post(v) if self._post is not None else v

    def __float__(self):
        return float(self.item())

    def __int__(self):
        return int(self.item())

    def __index__(self):
        return int(self.item())

    def __bool__(self):
        return bool(self.item())

    def __eq__(self, other):
        # without this, `reduce(a) == expected` inside a region would
        # silently compare object identity (always False) instead of
        # resolving — the one comparison that would not raise loudly
        if isinstance(other, PlanScalar):
            other = other.item()
        return self.item() == other

    # resolving inside hash() would be a hidden flush; unhashable keeps
    # the misuse loud (defining __eq__ clears the default anyway)
    __hash__ = None

    def __repr__(self):
        state = ("broken" if self._broken
                 else "pending" if self._val is None else repr(self._val))
        return f"PlanScalar({state})"


class _FusedOp:
    """One recorded fusible op: structural cache ``key``, trace-time
    ``emit(state, svals, souts)``, scalar ``spec`` ("t" = traced
    operand, ("r", i) = same-run scalar output i), this recording's
    traced ``vals`` (parallel to the "t" entries), and an optional
    ``pre`` dispatch-time hook (fired by ``_exec_run`` before the
    program-cache lookup — the fused analog of the eager dispatchers'
    fault-site fires, e.g. ``redistribute.exchange``).

    Optimizer footprint (docs/SPEC.md §21.2): ``reads`` is the tuple
    of run-local container SLOTS whose VALUES the op consumes;
    ``writes`` is a tuple of ``(slot, off, n, full)`` windows written
    (``full`` = the whole padded row is rebuilt, ghosts included —
    the op is a coverage KILLER for everything under it); ``pure``
    marks ops the dead-op pass may eliminate outright (no reduction
    handles, no ``pre`` side effects, no metadata flips).  The
    mask-preserve self-read of a windowed write (cells outside the
    mask pass through) is deliberately NOT in ``reads`` — the
    coverage analysis only credits a kept op's write window when the
    op does not read that container, which makes the passthrough
    cells either covered-later or untouched (§21.2's argument).
    ``push`` (transforms only) carries what the projection-pushdown
    pass needs to re-home the op onto a relational scratch copy."""

    __slots__ = ("name", "key", "emit", "spec", "vals", "pre",
                 "reads", "writes", "pure", "push", "src")

    def __init__(self, name, key, emit, spec=(), vals=(), pre=None,
                 reads=(), writes=(), pure=False, push=None):
        self.name = name
        self.key = key
        self.emit = emit
        self.spec = spec
        self.vals = list(vals)
        self.pre = pre
        self.reads = tuple(reads)
        self.writes = tuple(writes)
        self.pure = pure
        self.push = push
        #: the RECORDED op this one executes, when a pass re-slotted it
        #: into a merged run (opt._wrap) — the plansan oracle resolves
        #: executed identities back to record identities through it
        self.src = None


class _Run:
    """A maximal fusible run: ops over one mesh, containers in
    first-use slot order, reduction handles in scalar-output order."""

    def __init__(self, mesh, axis):
        self.mesh = mesh
        self.axis = axis
        self.ops: List[_FusedOp] = []
        self.conts = []
        self._cont_ids = {}
        self.handles: List[PlanScalar] = []

    def slot(self, cont) -> int:
        s = self._cont_ids.get(id(cont))
        if s is None:
            s = len(self.conts)
            self.conts.append(cont)
            self._cont_ids[id(cont)] = s
        return s


class _Opaque:
    """A recorded-but-not-fused op (inclusive_scan, stencil_iterate):
    deferred until flush, executed through its eager path there — it
    splits the fusible runs around it but keeps record order.

    Optimizer footprint (docs/SPEC.md §21.2): ``reads`` is the tuple
    of CONTAINERS whose values the thunk consumes, ``writes`` a tuple
    of ``(container, full)`` pairs (``full`` = the eager path rebuilds
    the whole container — a coverage killer, the relational outputs'
    shape).  ``None`` for either means UNKNOWN: the op is a barrier no
    pass may reorder across or eliminate through.  ``meta`` (dict or
    None) is the structured record the relational tier leaves for the
    pushdown/capinfer passes — the thunk re-reads ``meta`` at flush,
    so a pass may rewrite its entries in place."""

    __slots__ = ("name", "thunk", "reads", "writes", "meta")

    def __init__(self, name, thunk, reads=None, writes=None,
                 meta=None):
        self.name = name
        self.thunk = thunk
        self.reads = None if reads is None else tuple(reads)
        self.writes = None if writes is None else tuple(writes)
        self.meta = meta


class Plan:
    """A deferred execution plan: record algorithm calls, fuse maximal
    runs, flush as few dispatches as possible.  Use via
    :func:`deferred` or explicitly::

        p = dr_tpu.plan.Plan()
        with p.record():
            ...
        print(p.explain())
    """

    def __init__(self):
        self._queue: list = []
        self._flushing = False
        #: structured flush log consumed by explain()/stats()
        self.log: list = []
        #: elastic replay log (SPEC §16): one (queue_item, re-record
        #: thunk, reduce_handle|None) entry per recorded op, so a
        #: device loss MID-FLUSH can re-record the unexecuted suffix
        #: against the shrunken mesh — thunks re-invoke the record_*
        #: method with the original arguments and re-read container
        #: layouts at call time
        self._replay: list = []
        #: active only during an elastic replay: maps id(old pending
        #: PlanScalar) -> its re-recorded handle, so replayed consumers
        #: rewire onto the new run's in-program values
        self._subst: dict = {}
        #: undo log (SPEC §18.3): a recorded redistribute flips its
        #: container's LAYOUT METADATA at record time (so later
        #: recorded ops key on the new geometry) while the data moves
        #: at flush — one (queue_item, undo_thunk) entry per such op,
        #: run in reverse for every item a dropped queue never
        #: executed, restoring the pre-record metadata over the
        #: still-src-shaped data (the faulted-flush "containers keep
        #: their pre-flush values" contract)
        self._undo: list = []

    def _note_replay(self, thunk, handle=None) -> None:
        self._replay.append((self._queue[-1], thunk, handle))

    def _note_undo(self, thunk) -> None:
        self._undo.append((self._queue[-1], thunk))

    @staticmethod
    def _undo_items(undos, items) -> None:
        """Run the undo thunks of every UNEXECUTED queue item, newest
        first (two pending re-layouts of one container unwind in
        reverse record order).  Never raises — a failed undo is warned
        and the rest still unwind."""
        ids = {id(it) for it in items}
        for item, thunk in reversed(undos):
            if id(item) not in ids:
                continue
            try:
                thunk()
            except Exception as e:  # pragma: no cover - defensive
                from ..utils.fallback import warn_fallback
                warn_fallback("plan", f"redistribute undo failed "
                                      f"({e!r})")

    def _subst_scalars(self, values):
        """Map pending handles through the elastic replay substitution
        (identity outside a replay)."""
        if not self._subst:
            return list(values)
        return [self._subst.get(id(v), v) if isinstance(v, PlanScalar)
                else v for v in values]

    def queue_touches(self, cont) -> bool:
        """Could any queued item read or write ``cont``?  The §21.2
        footprint check :func:`flush_reads` keys its skip on; the
        aliasing answer comes from the one interference helper
        (``plan/interference.py``, drlint rule R10)."""
        from . import interference as _interf
        return _interf.queue_touches(self._queue, cont)

    # ------------------------------------------------------------ region
    @contextmanager
    def record(self):
        """Activate this plan for the enclosed block (on this thread);
        flushes on clean exit, discards pending (unexecuted) ops if the
        block raises."""
        if _get_active() is self:
            yield self
            return
        if _get_active() is not None:
            raise RuntimeError("another deferred plan is already "
                               "recording on this thread")
        _set_active(self)
        try:
            yield self
        except BaseException:
            self.discard("region error")
            raise
        else:
            self.flush("region exit")
        finally:
            _set_active(None)

    # --------------------------------------------------------- recording
    def _fusible_run(self, cont, values=()) -> _Run:
        """The open run for this container's mesh.  A mesh change ends
        the previous run (equal shard counts over different device sets
        cannot share one program) — and so does consuming a pending
        scalar of the open run that carries a HOST-side post
        (``reduce(r, init=...)``'s init fold): the fold cannot ride the
        in-program carry, so the producer run must execute first and
        the consumer reads the posted host value as an operand."""
        mesh = cont.runtime.mesh
        q = self._queue
        if q and isinstance(q[-1], _Run) and q[-1].mesh is mesh \
                and not any(isinstance(v, PlanScalar)
                            and v._run is q[-1] and v._val is None
                            and v._post is not None for v in values):
            return q[-1]
        run = _Run(mesh, cont.runtime.axis)
        q.append(run)
        return run

    def _scalar_spec(self, run: _Run, values):
        """Split scalar operands into the structural spec and this
        recording's traced values.  A still-pending PlanScalar of the
        SAME run becomes an in-program reference ("r", idx); everything
        else — plain values, resolved handles, pending handles of
        EARLIER runs — is a traced operand fetched at flush time."""
        spec, vals = [], []
        for v in values:
            if isinstance(v, PlanScalar) and v._run is run \
                    and v._val is None and v._post is None:
                spec.append(("r", v._idx))
            else:
                spec.append("t")
                vals.append(v)
        return tuple(spec), vals

    def record_generator(self, out_chain, gkind: str, value) -> bool:
        """fill / iota over an aligned output window; the scalar is a
        traced operand (streaming values reuse one program)."""
        cont = out_chain.cont
        value = self._subst_scalars([value])[0]
        if gkind == "fill" and not isinstance(value, PlanScalar):
            value = jnp.asarray(value, cont.dtype)  # eager fill's cast
        run = self._fusible_run(cont, [value])
        slot = run.slot(cont)
        layout, off, n = cont.layout, out_chain.off, out_chain.n
        spec, vals = self._scalar_spec(run, [value])
        key = ("gen", gkind, slot, layout, off, n, str(cont.dtype), spec)

        def emit(state, svals, souts):
            out_data = state[slot]
            mask, gid = owned_window_mask(layout, off, n)
            if gkind == "fill":
                v = jnp.broadcast_to(svals[0], out_data.shape)
            else:
                v = gid + svals[0]
            state[slot] = jnp.where(mask, v.astype(out_data.dtype),
                                    out_data)

        run.ops.append(_FusedOp(gkind, key, emit, spec, vals,
                                writes=((slot, off, n, False),),
                                pure=True))
        self._note_replay(
            lambda oc=out_chain, g=gkind, v=value:
            self.record_generator(oc, g, v))
        return True

    def record_transform(self, ins, out_chain, op, scalars,
                         with_index=False, name="transform") -> bool:
        """Aligned transform/for_each (the ``_window_program`` shape):
        view-chain BoundOp scalars and trailing op scalars ride as
        traced operands."""
        cont = out_chain.cont
        chain_sc = self._subst_scalars(_chain_scalars(ins))
        all_sc = list(chain_sc) + self._subst_scalars(scalars)
        run = self._fusible_run(cont, all_sc)
        out_slot = run.slot(cont)
        in_slots = tuple(run.slot(c.cont) for c in ins)
        in_ops = tuple(c.ops for c in ins)
        nchain = len(chain_sc)
        spec, vals = self._scalar_spec(run, all_sc)
        layout, off, n = cont.layout, out_chain.off, out_chain.n
        key = ("ew", out_slot, in_slots, layout, off, n,
               tuple(tuple(_traced_op_key(o) for o in ops)
                     for ops in in_ops),
               _op_key(op), with_index, str(cont.dtype), spec)

        def emit(state, svals, souts):
            sc_iter = iter(svals[:nchain])
            op_scalars = svals[nchain:]
            vals_in = [_apply_chain_ops(state[s], ops, sc_iter)
                       for s, ops in zip(in_slots, in_ops)]
            out_data = state[out_slot]
            mask, gid = owned_window_mask(layout, off, n)
            args = list(vals_in) + list(op_scalars)
            if with_index:
                v = op(gid, *args) if args else op(gid)
            else:
                v = op(*args) if args else op()
            v = jnp.broadcast_to(v, out_data.shape).astype(out_data.dtype)
            state[out_slot] = jnp.where(mask, v, out_data)

        # pushdown eligibility (docs/SPEC.md §21.4): a single-input
        # same-dtype windowed map with no view-chain ops and no
        # index/PlanScalar dependence can be re-homed into a relational
        # scratch-sort copy bit-identically (op → one cast, both paths)
        push = None
        if (len(ins) == 1 and not ins[0].ops and not with_index
                and jnp.dtype(ins[0].cont.dtype) == jnp.dtype(cont.dtype)
                and not any(isinstance(s, PlanScalar) for s in all_sc)):
            push = (ins[0].cont, off, n, op, tuple(scalars))
        run.ops.append(_FusedOp(
            name, key, emit, spec, vals, reads=in_slots,
            writes=((out_slot, off, n, False),), pure=True, push=push))
        self._note_replay(
            lambda i=ins, oc=out_chain, o=op, sc=tuple(scalars),
            wi=with_index, nm=name:
            self.record_transform(i, oc, o, sc, wi, nm))
        return True

    def record_zip_foreach(self, ins, outs, fn, scalars) -> bool:
        """Aligned for_each over a zip (the ``_zip_foreach_program``
        shape).  Zip components are outputs, so their chains carry no
        ops (the invariant the eager program asserts)."""
        conts = [oc.cont for oc in outs]
        scalars = self._subst_scalars(scalars)
        run = self._fusible_run(conts[0], scalars)
        out_slots = tuple(run.slot(c) for c in conts)
        in_slots = tuple(run.slot(ch.cont) for ch in ins)
        spec, vals = self._scalar_spec(run, list(scalars))
        cont = conts[0]
        layout, off, n = cont.layout, outs[0].off, outs[0].n
        key = ("zfe", out_slots, in_slots, layout, off, n,
               tuple(str(c.dtype) for c in conts), _op_key(fn), spec)

        def emit(state, svals, souts):
            vals_in = [state[s] for s in in_slots]
            new_vals = fn(*vals_in, *svals)
            mask, _gid = owned_window_mask(layout, off, n)
            for s, nv in zip(out_slots, new_vals):
                state[s] = jnp.where(mask, nv.astype(state[s].dtype),
                                     state[s])

        run.ops.append(_FusedOp(
            "for_each(zip)", key, emit, spec, vals, reads=in_slots,
            writes=tuple((s, off, n, False) for s in out_slots),
            pure=True))
        self._note_replay(
            lambda i=ins, o=outs, f=fn, sc=tuple(scalars):
            self.record_zip_foreach(i, o, f, sc))
        return True

    def record_reduce(self, chains, kind: str, zip_op=None) -> PlanScalar:
        """Classified-monoid reduce (single chain or the dot-pipeline
        transform-over-zip shape): the scalar result becomes a program
        output riding the carry — no mid-chain sync."""
        c0 = chains[0]
        cont = c0.cont
        chain_sc = self._subst_scalars(_chain_scalars(chains))
        zsc = self._subst_scalars(zip_op.scalars) \
            if isinstance(zip_op, _v.BoundOp) else []
        all_sc = list(chain_sc) + zsc
        run = self._fusible_run(cont, all_sc)
        slots = tuple(run.slot(c.cont) for c in chains)
        all_ops = tuple(c.ops for c in chains)
        nchain = len(chain_sc)
        spec, vals = self._scalar_spec(run, all_sc)
        layout, off, n = cont.layout, c0.off, c0.n
        key = ("red", slots, layout, off, n, kind,
               tuple(tuple(_traced_op_key(o) for o in ops)
                     for ops in all_ops),
               _traced_op_key(zip_op) if zip_op is not None else None,
               spec)
        vec_reduce = _MONOIDS[kind][0]

        def emit(state, svals, souts):
            sc_iter = iter(svals[:nchain])
            zip_scalars = svals[nchain:]
            vs = [_apply_chain_ops(state[s], ops, sc_iter)
                  for s, ops in zip(slots, all_ops)]
            if zip_op is None:
                v = vs[0]
            elif isinstance(zip_op, _v.BoundOp):
                v = zip_op.op(*vs, *zip_scalars)
            else:
                v = zip_op(*vs)
            mask, _gid = owned_window_mask(layout, off, n)
            souts.append(vec_reduce(
                jnp.where(mask, v, _identity_for(kind, v.dtype))))

        handle = PlanScalar(self, run, len(run.handles))
        run.handles.append(handle)
        run.ops.append(_FusedOp("reduce", key, emit, spec, vals,
                                reads=slots))
        self._note_replay(
            lambda ch=chains, k=kind, z=zip_op:
            self.record_reduce(ch, k, z), handle)
        return handle

    def record_splice(self, out_chain, values) -> bool:
        """Host array -> container window copy; the array is a traced
        operand (key carries shape+dtype only).  Mirrors the eager
        ``_write_window``/``assign_array`` route bit-for-bit, ghost
        zeroing included."""
        cont = out_chain.cont
        layout, off, n = cont.layout, out_chain.off, out_chain.n
        shp = tuple(getattr(values, "shape", ()))
        if shp != (n,):
            # the eager route raises from _write_window's windowed set;
            # the clipped gather below would silently corrupt instead
            raise ValueError(
                f"copy: source shape {shp} does not match the "
                f"destination window ({n},)")
        run = self._fusible_run(cont, [values])
        slot = run.slot(cont)
        total = len(cont)
        spec, vals = self._scalar_spec(run, [values])
        key = ("splice", slot, layout, off, n, str(cont.dtype),
               tuple(getattr(values, "shape", ())), spec)

        def emit(state, svals, souts):
            out_data = state[slot]
            dtype = out_data.dtype
            mask, gid = owned_window_mask(layout, off, n)
            if n > 0:
                take = jnp.take(svals[0], jnp.clip(gid - off, 0, n - 1))
                new = jnp.where(mask, take.astype(dtype), out_data)
            else:
                new = out_data
            owned, _ = owned_window_mask(layout, 0, total)
            state[slot] = jnp.where(owned, new, jnp.zeros((), dtype))

        # whole-container splice rebuilds every cell (ghosts zeroed):
        # a coverage KILLER; the windowed form preserves owned cells
        # outside the window (a self-read) and zeroes ghosts — kept
        # out of the dead-op pass entirely (pure=False)
        whole = (off == 0 and n == total)
        run.ops.append(_FusedOp(
            "copy(host)", key, emit, spec, vals,
            reads=() if whole else (slot,),
            writes=((slot, 0, total, True) if whole
                    else (slot, off, n, False),)))
        self._note_replay(
            lambda oc=out_chain, v=values: self.record_splice(oc, v))
        return True

    def record_halo(self, dv, kind: str, op=None, iters: int = 1) -> bool:
        """Halo exchange / exchange_n / ghost->owner reduce: the same
        shard_map bodies as the eager programs, inlined into the run."""
        run = self._fusible_run(dv)
        slot = run.slot(dv)
        hb = dv.halo_bounds
        knobs = (env_str("DR_TPU_HALO_NCARRY", "ghost"),
                 env_str("DR_TPU_HALO_DYNAMIC"))
        key = ("halo", kind, slot, dv.layout, hb.periodic, op, iters,
               knobs)
        nshards, seg = dv.nshards, dv.segment_size
        prev, nxt, periodic, n = hb.prev, hb.next, hb.periodic, len(dv)
        axis, mesh = dv.runtime.axis, dv.runtime.mesh

        def emit(state, svals, souts):
            from ..parallel import halo as _halo
            if kind == "exchange":
                body = _halo._exchange_body(axis, nshards, seg, prev,
                                            nxt, periodic, n)
            elif kind == "exchange_n":
                body = _halo._exchange_n_body(axis, nshards, seg, prev,
                                              nxt, periodic, n, iters)
            else:
                body = _halo._reduce_body(axis, nshards, seg, prev, nxt,
                                          periodic, op, n)
            shm = jax.shard_map(body, mesh=mesh, in_specs=P(axis, None),
                                out_specs=P(axis, None))
            state[slot] = shm(state[slot])

        run.ops.append(_FusedOp(f"halo.{kind}", key, emit,
                                reads=(slot,),
                                writes=((slot, 0, n, False),)))
        self._note_replay(
            lambda d=dv, k=kind, o=op, it=iters:
            self.record_halo(d, k, o, it))
        return True

    def record_stencil(self, in_cont, out_cont, layout, periodic,
                       prev, nxt, key_op, body_op, axis, mesh) -> bool:
        """One fused exchange+transform stencil step (the
        ``build_stencil_step`` body), inlined into the run."""
        run = self._fusible_run(out_cont)
        si, so = run.slot(in_cont), run.slot(out_cont)
        key = ("stencil", si, so, layout, periodic, prev, nxt, key_op,
               str(out_cont.dtype))

        def emit(state, svals, souts):
            from ..algorithms.stencil import build_stencil_step
            step = build_stencil_step(layout, periodic, body_op, prev,
                                      nxt, axis)
            shm = jax.shard_map(
                step, mesh=mesh,
                in_specs=(P(axis, None), P(axis, None)),
                out_specs=P(axis, None))
            state[so] = shm(state[si], state[so])

        run.ops.append(_FusedOp(
            "stencil", key, emit, reads=(si, so),
            writes=((so, 0, len(out_cont), False),)))
        # the replay thunk re-derives layout/axis/mesh from the LIVE
        # container (the recorded values would resurrect the dead mesh)
        self._note_replay(
            lambda ic=in_cont, oc=out_cont, per=periodic, pv=prev,
            nx=nxt, ko=key_op, bo=body_op:
            self.record_stencil(ic, oc, ic.layout, per, pv, nx, ko, bo,
                                ic.runtime.axis, ic.runtime.mesh))
        return True

    def record_redistribute(self, cont, new_dist, rt=None) -> bool:
        """Fused collective re-layout (docs/SPEC.md §18.3): the
        container's layout METADATA flips now — every op recorded
        after this one keys on the dst geometry — while its data keeps
        the src shape until the fused run executes the exchange body
        (``parallel/redistribute._exchange_body``) in record order.
        The undo log restores the src metadata if the queue is dropped
        before the move ran; the elastic replay thunk re-records
        against the CURRENT global runtime (re-reading the rescued
        container's layout at call time, the stencil discipline)."""
        from ..parallel import runtime as _rtmod
        target = rt or _rtmod.runtime()
        src_rt = cont.runtime
        src_dist = cont.distribution
        src_layout = cont.layout
        cont._rebind(target, new_dist, _data=cont._data)
        dst_layout = cont.layout
        run = self._fusible_run(cont)
        slot = run.slot(cont)
        dtype = cont.dtype
        axis, mesh = target.axis, target.mesh
        key = ("rdx", slot, src_layout, dst_layout, str(dtype))

        def emit(state, svals, souts):
            from ..parallel import redistribute as _rdx
            body = _rdx._exchange_body(axis, src_layout, dst_layout,
                                       jnp.dtype(dtype))
            shm = jax.shard_map(body, mesh=mesh, in_specs=P(axis, None),
                                out_specs=P(axis, None))
            state[slot] = shm(state[slot])

        def pre():
            from ..parallel import redistribute as _rdx
            _rdx.fire_exchange(src=str(src_layout), dst=str(dst_layout))
            _rdx.fire_ppermute(what="redistribute")
            _, moved = _rdx.plan_moves(src_layout, dst_layout)
            _obs.count("redistribute.bytes_moved",
                       moved * jnp.dtype(dtype).itemsize)

        run.ops.append(_FusedOp(
            "redistribute", key, emit, pre=pre, reads=(slot,),
            writes=((slot, 0, len(cont), False),)))
        self._note_undo(
            lambda c=cont, r=src_rt, d=src_dist:
            c._rebind(r, d, _data=c._data))
        self._note_replay(
            lambda c=cont, d=new_dist: self.record_redistribute(c, d))
        return True

    def record_histogram(self, in_chain, out_chain, lo, hi) -> bool:
        """Fusible relational histogram (docs/SPEC.md §17.2): the
        output shape is STATIC (bins = the out container), so the op
        fuses into the surrounding run — the shared
        ``relational._histogram_body`` shard-maps inside the fused
        program, with the view chain's BoundOp scalars and (lo, hi)
        as traced operands (a streamed range reuses one program)."""
        in_cont, out_cont = in_chain.cont, out_chain.cont
        all_sc = self._subst_scalars(
            _chain_scalars([in_chain]) + [lo, hi])
        run = self._fusible_run(out_cont, all_sc)
        si, so = run.slot(in_cont), run.slot(out_cont)
        spec, vals = self._scalar_spec(run, all_sc)
        in_layout, off, n = in_cont.layout, in_chain.off, in_chain.n
        out_layout, out_dtype = out_cont.layout, out_cont.dtype
        bins = out_chain.n
        ops = tuple(in_chain.ops)
        nsc = len(all_sc) - 2
        axis, mesh = out_cont.runtime.axis, out_cont.runtime.mesh
        # hist kernel-arm decision (docs/SPEC.md §22): resolved at
        # RECORD time through the same shared helper as the eager
        # program, and part of the fused-op key — a changed arm pick
        # is a different fused program
        from ..algorithms import relational as _rel
        kern = _rel._hist_kernel_decision(mesh, in_layout, bins)
        key = ("relhist", si, so, in_layout, off, n,
               tuple(_traced_op_key(o) for o in ops), str(in_cont.dtype),
               out_layout, str(out_dtype), bins, spec, tuple(kern))

        def emit(state, svals, souts):
            from ..algorithms import relational as _rel
            body = _rel._histogram_body(axis, in_layout, off, n, ops,
                                        nsc, out_layout, bins,
                                        jnp.dtype(out_dtype), kern=kern)
            shm = jax.shard_map(
                body, mesh=mesh,
                in_specs=(P(axis, None),) + (P(),) * (nsc + 2),
                out_specs=P(axis, None),
                check_vma=not kern.use)
            state[so] = shm(state[si], *svals)

        run.ops.append(_FusedOp(
            "histogram", key, emit, spec, vals, reads=(si,),
            writes=((so, 0, bins, True),), pure=True))
        self._note_replay(
            lambda ic=in_chain, oc=out_chain, l=lo, h=hi:
            self.record_histogram(ic, oc, l, h))
        return True

    def record_top_k(self, in_chain, ov_chain, oi_chain, largest,
                     merge) -> bool:
        """Fusible relational top-k (docs/SPEC.md §17.2): k is the out
        container's static length, so the op fuses into the
        surrounding run via the shared ``relational._top_k_body``.
        Under ``merge`` the out containers' CURRENT run state joins
        the candidate pool — record order gives it exactly the eager
        streaming semantics."""
        in_cont, ov_cont = in_chain.cont, ov_chain.cont
        oi_cont = oi_chain.cont if oi_chain is not None else None
        all_sc = self._subst_scalars(_chain_scalars([in_chain]))
        run = self._fusible_run(ov_cont, all_sc)
        si, sov = run.slot(in_cont), run.slot(ov_cont)
        soi = run.slot(oi_cont) if oi_cont is not None else None
        spec, vals = self._scalar_spec(run, all_sc)
        in_layout, off, n = in_cont.layout, in_chain.off, in_chain.n
        ov_layout, ov_dtype = ov_cont.layout, ov_cont.dtype
        oi_layout = oi_cont.layout if oi_cont is not None else None
        k = ov_chain.n
        ops = tuple(in_chain.ops)
        nsc = len(all_sc)
        axis, mesh = ov_cont.runtime.axis, ov_cont.runtime.mesh
        key = ("reltopk", si, sov, soi, in_layout, off, n,
               tuple(_traced_op_key(o) for o in ops),
               str(in_cont.dtype), ov_layout, str(ov_dtype), oi_layout,
               k, bool(largest), bool(merge), spec)

        def emit(state, svals, souts):
            from ..algorithms import relational as _rel
            body = _rel._top_k_body(axis, in_layout, off, n, ops, nsc,
                                    ov_layout, jnp.dtype(ov_dtype),
                                    oi_layout, k, largest, merge)
            nrows = (3 if soi is not None else 2) if merge else 1
            nout = 2 if soi is not None else 1
            shm = jax.shard_map(
                body, mesh=mesh,
                in_specs=(P(axis, None),) * nrows + (P(),) * nsc,
                out_specs=(P(axis, None),) * nout if nout > 1
                else P(axis, None))
            rows = [state[si]]
            if merge:
                rows.append(state[sov])
                if soi is not None:
                    rows.append(state[soi])
            outs = shm(*rows, *svals)
            if soi is not None:
                state[sov], state[soi] = outs
            else:
                state[sov] = outs

        tk_writes = ((sov, 0, k, True),)
        if soi is not None:
            tk_writes += ((soi, 0, k, True),)
        tk_reads = (si,)
        if merge:
            tk_reads += (sov,) + ((soi,) if soi is not None else ())
        run.ops.append(_FusedOp("top_k", key, emit, spec, vals,
                                reads=tk_reads, writes=tk_writes,
                                pure=True))
        self._note_replay(
            lambda ic=in_chain, vc=ov_chain, xc=oi_chain, lg=largest,
            mg=merge: self.record_top_k(ic, vc, xc, lg, mg))
        return True

    def record_opaque(self, name: str, thunk, reads=None, writes=None,
                      meta=None) -> bool:
        """Record a deferred-but-not-fused op (its eager path runs at
        flush, in record order); it closes the current fusible run.
        ``reads``/``writes``/``meta`` are the optimizer footprint
        (see :class:`_Opaque`); omitting them keeps the op a full
        barrier — correct, just opaque to the §21 passes."""
        self._queue.append(_Opaque(name, thunk, reads=reads,
                                   writes=writes, meta=meta))
        self._note_replay(
            lambda n=name, t=thunk, r=reads, w=writes, m=meta:
            self.record_opaque(n, t, r, w, m))
        return True

    def nonfusible(self, what: str) -> None:
        """A non-fusible op is about to dispatch eagerly: flush pending
        work (order!) and announce the perf cliff through the fallback
        registry — silent flushes in deferred mode would hide exactly
        the dispatch cost the region was opened to avoid."""
        if not self._queue:
            return
        from ..utils.fallback import warn_fallback
        warn_fallback("plan", f"non-fusible {what} forced a flush")
        self.flush(f"non-fusible: {what}")

    # ----------------------------------------------------------- flushing
    def flush(self, reason: str = "explicit") -> None:
        """Execute the recorded queue: one dispatch per fused run, the
        eager path for opaque ops, in record order.  On an error the
        unexecuted suffix is dropped (containers keep their pre-flush
        values for it) and pending handles break — never a hang."""
        if self._flushing or not self._queue:
            return
        queue, self._queue = self._queue, []
        replay, self._replay = self._replay, []
        undos, self._undo = self._undo, []
        self._flushing = True
        # obs span over the whole flush (SPEC §15): begin/end rather
        # than a context manager so the existing error bookkeeping
        # stays untouched; sid is 0 (and every obs call a no-op) while
        # tracing is off
        sid = _obs.begin("plan.flush", cat="plan", reason=reason,
                         items=len(queue))
        entry = {"reason": reason, "items": []}
        self.log.append(entry)
        # optimizer pass pipeline (docs/SPEC.md §21): the recorded
        # queue is the LOGICAL plan; the passes rewrite it into the
        # executed queue (merged runs carry ``_sources`` back to the
        # recorded items so the undo/replay/faulted-flush contracts
        # keep holding against record identities)
        from . import opt as _opt
        # plansan (SPEC §23): snapshot the recorded queue's dependency
        # structure BEFORE the passes run — pushdown rewrites opaque
        # footprints in place, so the oracle pins the originals now
        _plansan = None
        snap = None
        if _sanitize.installed():
            from . import plansan as _plansan
            snap = _plansan.snapshot(queue)
        exec_queue = _opt.optimize(self, queue, entry, parent=sid)
        d0 = _guard.dispatch_count()
        idx = 0
        try:
            # the injection sites fire BEFORE any dispatch: a faulted
            # flush executes nothing and containers stay consistent
            # (sanitize.verify fires on every flush, armed or not —
            # the chaos battery reaches it without DR_TPU_SANITIZE)
            _faults.fire("plan.flush")
            _faults.fire("sanitize.verify")
            if _plansan is not None:
                _plansan.check_serializable(snap, exec_queue)
            for idx, item in enumerate(exec_queue):
                di = _guard.dispatch_count()
                t0 = _obs.now()
                if isinstance(item, _Opaque):
                    if _plansan is not None:
                        with _plansan.watch(item):
                            item.thunk()
                    else:
                        item.thunk()
                    _obs.complete("plan.opaque", t0, cat="plan",
                                  parent=sid, op=item.name)
                    entry["items"].append(
                        {"kind": "opaque", "name": item.name,
                         "dispatches": _guard.dispatch_count() - di})
                else:
                    pre_ok = True
                    if _sanitize.installed():
                        # snapshot IMMEDIATELY before the run executes:
                        # a NaN that pre-dates the run (input data, or
                        # written by an earlier opaque op in this same
                        # queue) must not be blamed on its program
                        pre_ok = all(_sanitize.is_finite(c._data)
                                     for c in item.conts)
                    if _plansan is not None:
                        # shadow-verify the run's ops against their
                        # declared footprints before it dispatches
                        _plansan.verify_run(item)
                    hit = self._exec_run(item)
                    _obs.complete("plan.run", t0, cat="plan",
                                  parent=sid, ops=len(item.ops),
                                  cache_hit=hit)
                    entry["items"].append(
                        {"kind": "fused",
                         "ops": [o.name for o in item.ops],
                         "containers": len(item.conts),
                         "cache_hit": hit,
                         "dispatches": _guard.dispatch_count() - di})
                    if _sanitize.installed() and pre_ok:
                        # sanitizer finite sweep (SPEC §13.4) right
                        # after THIS run, against ITS output state —
                        # a later run overwriting the container must
                        # neither hide this run's NaN nor be blamed
                        # for its own on this run's ops.  A fused
                        # chain has no NaN-sentinel semantics; a run
                        # whose inputs were already non-finite is
                        # exempt (nothing to attribute).
                        ops = "+".join(o.name for o in item.ops)
                        for c in item.conts:
                            _sanitize.check_finite(
                                c._data,
                                f"container state (fused run {ops})")
                        for h in item.handles:
                            if h._val is not None:
                                _sanitize.check_finite(
                                    h._val,
                                    f"posted scalar (fused run {ops})")
        except _resilience.DeviceLostError as de:
            # elastic recovery (SPEC §16): shrink, re-record the
            # UNEXECUTED suffix against the rescued containers, flush
            # again.  The failed item never rebound its containers
            # (_exec_run rebinds only after the program returns; the
            # fault sites fire before dispatch), so the suffix replays
            # from consistent pre-fault state.  Pending redistributes
            # in the suffix UNDO first (metadata back over the
            # still-src-shaped data) so the rescue's host gathers read
            # a consistent container; the replay thunks re-record them
            # against the shrunken mesh.  The unexecuted suffix is
            # expanded back to RECORDED items (merged runs carry their
            # sources) so undo/replay match the record-time identities.
            suffix = _opt.expand_items(exec_queue[idx:])
            self._undo_items(undos, suffix)
            self._flushing = False
            try:
                recovered = self._elastic_recover(suffix, replay,
                                                  de, entry)
            except BaseException:
                # the replay itself died (a lost container under a
                # replayed op, a second loss past the shrink floor):
                # same cleanup as an unrecovered flush, new classified
                # cause
                self._break_handles(queue)
                entry["error"] = True
                raise
            if not recovered:
                self._break_handles(queue)
                entry["error"] = True
                raise
        except BaseException:
            self._undo_items(undos, _opt.expand_items(exec_queue[idx:]))
            self._break_handles(queue)
            entry["error"] = True
            raise
        finally:
            entry["dispatches"] = _guard.dispatch_count() - d0
            self._flushing = False
            _obs.end(sid, dispatches=entry["dispatches"],
                     error=bool(entry.get("error")))
            if _obs.armed():
                _obs.count("plan.flushes")
                for it in entry["items"]:
                    if it["kind"] == "fused":
                        _obs.count("plan.fused_ops", len(it["ops"]))
                    else:
                        _obs.count("plan.opaque_ops")

    @staticmethod
    def _break_handles(queue) -> None:
        """Break every still-pending handle of a dropped queue —
        resolving one raises instead of returning a stale number."""
        for item in queue:
            if isinstance(item, _Run):
                for h in item.handles:
                    if h._val is None:
                        h._broken = True
                        h._run = None

    def _elastic_recover(self, suffix, replay, err, entry) -> bool:
        """Device loss MID-FLUSH (docs/SPEC.md §16): shrink the mesh
        (``utils.elastic``), RE-RECORD the unexecuted queue suffix, and
        flush again.  The replay thunks re-invoke the original record_*
        calls against the rescued containers, re-reading layouts and
        meshes at call time — the fresh mesh re-keys every program, so
        spmd_guard sees a fresh canonical digest, and pending reduce
        handles re-link onto the new recording's values.  False when no
        rescue is possible (elastic off, shrink floor, nested loss):
        the caller then drops the queue classified — exactly the
        pre-elastic faulted-flush contract."""
        from ..utils import elastic as _elastic
        if not (_elastic.enabled() and _elastic.try_rescue(err)):
            return False
        suffix_ids = {id(it) for it in suffix}
        links = []
        replayed = 0
        self._subst = {}
        try:
            for item, thunk, old_h in replay:
                if id(item) not in suffix_ids:
                    continue
                new = thunk()
                replayed += 1
                if old_h is not None and isinstance(new, PlanScalar):
                    self._subst[id(old_h)] = new
                    links.append((old_h, new))
        finally:
            self._subst = {}
        entry["elastic_replayed"] = replayed
        self.flush("elastic replay")
        for old_h, new_h in links:
            old_h._val = new_h._val
            old_h._run = None
            old_h._broken = new_h._val is None
        return True

    def _exec_run(self, run: _Run) -> bool:
        # dispatch-time pre hooks (fault sites, counters) fire BEFORE
        # the program-cache lookup — the eager dispatchers' discipline:
        # an armed fault drops the whole run with containers untouched
        for o in run.ops:
            if o.pre is not None:
                o.pre()
        key = ("plan", pinned_id(run.mesh), run.axis,
               tuple((c.layout, str(c.dtype)) for c in run.conts),
               tuple(o.key for o in run.ops))
        prog = _plan_cache.get(key)
        hit = prog is not None
        if prog is None:
            ops = tuple(run.ops)
            nslots = len(run.conts)

            def seal(x, one):
                # Op boundaries are PROGRAM boundaries eagerly, but the
                # CPU backend contracts a producer op's multiply into a
                # consumer op's add as an FMA even across
                # lax.optimization_barrier — a last-ULP divergence from
                # the eager sequence.  Routing every inexact value that
                # crosses an op boundary through a multiply by a RUNTIME
                # 1.0 operand (a parameter, so nothing folds it) makes
                # any downstream contraction absorb the exact *1 instead
                # of the upstream multiply: results equal the eagerly-
                # rounded chain bit-for-bit, while WITHIN-op contraction
                # (which eager programs also perform) is untouched.
                if jnp.issubdtype(jnp.result_type(x), jnp.inexact):
                    return x * one.astype(x.dtype)
                return x

            def body(*args):
                state = list(args[:nslots])
                one = args[nslots]
                tail = iter(args[nslots + 1:])
                souts = []
                for o in ops:
                    svals = [souts[s[1]] if isinstance(s, tuple)
                             else next(tail) for s in o.spec]
                    before = list(state)
                    nsout = len(souts)
                    o.emit(state, svals, souts)
                    for i in range(nslots):
                        if state[i] is not before[i]:
                            state[i] = seal(state[i], one)
                    for j in range(nsout, len(souts)):
                        souts[j] = seal(souts[j], one)
                    # and pin HLO-level motion/fusion across the boundary
                    sealed = jax.lax.optimization_barrier(
                        tuple(state) + tuple(souts))
                    state = list(sealed[:nslots])
                    souts = list(sealed[nslots:])
                return tuple(state) + tuple(souts)

            prog = jax.jit(body, donate_argnums=tuple(range(nslots)))
            _plan_cache[key] = prog
        tail = []
        for o in run.ops:
            for v in o.vals:
                if isinstance(v, PlanScalar):
                    # posted handles resolve through item() so the
                    # host-side init fold is APPLIED, not dropped (the
                    # producer run has already executed — record order)
                    v = v.item() if v._post is not None else v._raw()
                tail.append(v)
        outs = prog(*[c._data for c in run.conts], jnp.float32(1.0),
                    *tail)
        # the cached program's closure pins this run's _FusedOp objects;
        # drop their operand values (a host splice array can be
        # container-sized) — only spec/emit are needed for later hits
        for o in run.ops:
            o.vals = []
        nslots = len(run.conts)
        for c, nd in zip(run.conts, outs[:nslots]):
            c._data = nd
        for h, val in zip(run.handles, outs[nslots:]):
            h._val = val
            h._run = None
        return hit

    def discard(self, reason: str = "discard") -> None:
        """Drop every pending item without executing it; pending
        handles break (resolving them raises instead of lying) and
        pending re-layouts undo their metadata flip."""
        queue, self._queue = self._queue, []
        self._replay = []
        undos, self._undo = self._undo, []
        self._undo_items(undos, queue)
        for item in queue:
            if isinstance(item, _Run):
                for h in item.handles:
                    h._broken = True
                    h._run = None
        if queue:
            self.log.append({"reason": reason, "items": [],
                             "discarded": len(queue), "dispatches": 0})

    # ------------------------------------------------------ observability
    @property
    def dispatches(self) -> int:
        """Total tap dispatches across this plan's flushes."""
        return sum(e.get("dispatches", 0) for e in self.log)

    def stats(self) -> dict:
        items = [i for e in self.log for i in e.get("items", [])]
        fused = [i for i in items if i["kind"] == "fused"]
        opts = [e.get("opt") for e in self.log if e.get("opt")]
        return {
            "flushes": len(self.log),
            "fused_runs": len(fused),
            "fused_ops": sum(len(i["ops"]) for i in fused),
            "opaque_ops": sum(1 for i in items if i["kind"] == "opaque"),
            "cache_hits": sum(1 for i in fused if i["cache_hit"]),
            "dispatches": self.dispatches,
            "opt": {
                "merged_runs": sum(o.get("merged_runs", 0)
                                   for o in opts),
                "dce_ops": sum(o.get("dce_ops", 0) for o in opts),
                "pushdowns": sum(o.get("pushdowns", 0) for o in opts),
            },
        }

    def explain(self) -> str:
        """Human-readable plan report: fused runs, flush reasons, and
        per-flush dispatch counts from the spmd_guard tap."""
        s = self.stats()
        lines = [
            f"plan: {s['flushes']} flush(es), {s['fused_runs']} fused "
            f"run(s) over {s['fused_ops']} op(s), {s['opaque_ops']} "
            f"opaque op(s), {s['dispatches']} dispatch(es), "
            f"{s['cache_hits']} program-cache hit(s)"]
        for e in self.log:
            tag = " [ERROR]" if e.get("error") else ""
            lines.append(f"  flush ({e['reason']}){tag}: "
                         f"{e.get('dispatches', 0)} dispatch(es)")
            o = e.get("opt")
            if o:
                lines.append(
                    f"    opt [{'+'.join(o.get('passes', ()))}]: "
                    f"{o.get('merged_runs', 0)} run(s) merged, "
                    f"{o.get('dce_ops', 0)} dead op(s) eliminated, "
                    f"{o.get('pushdowns', 0)} pushdown(s)")
            for it in e.get("items", []):
                if it["kind"] == "fused":
                    lines.append(
                        f"    fused run [{len(it['ops'])} ops, "
                        f"{it['containers']} container(s), "
                        f"{'hit' if it['cache_hit'] else 'compile'}]: "
                        + " -> ".join(it["ops"]))
                else:
                    lines.append(
                        f"    opaque {it['name']} "
                        f"({it['dispatches']} dispatch(es))")
            if e.get("discarded"):
                lines.append(
                    f"    discarded {e['discarded']} pending item(s)")
        return "\n".join(lines)


@contextmanager
def deferred():
    """Deferred-execution region: algorithm calls on segment-aligned
    containers record into a :class:`Plan` and flush (fused, usually
    ONE dispatch) at region exit or any host materialization.  Nesting
    re-enters the active plan (per thread — the serving daemon records
    on its dispatch thread independently of the host thread's region).
    Yields the plan for :meth:`Plan.explain` / :meth:`Plan.stats`."""
    p = _get_active()
    if p is not None:
        yield p
        return
    p = Plan()
    with p.record():
        yield p
    # elastic grow-back poll (docs/SPEC.md §16.6): the OUTERMOST region
    # exit — after the flush, with nothing recorded and nothing in
    # flight on this thread — is the sanctioned between-flushes moment
    # for re-admitting recovered devices.  One env check when
    # DR_TPU_ELASTIC_GROW is off or the session never shrank; never
    # raises (a failed probe/grow leaves the session on the small
    # mesh).  Skipped when the region body raised: the discard path
    # must surface the user's error, not a recovery side quest.
    from ..utils import elastic as _elastic
    _elastic.maybe_grow()
