"""plansan — the footprint-soundness runtime verifier (SPEC.md §23).

The §21 optimizer and the ``flush_reads`` flush-cliff skip TRUST the
read/write footprints recorded plan items declare; an under-declared
footprint is a silent miscompile.  drlint rule R9 proves the record
sites well-formed statically; this module is the runtime half, armed
under ``DR_TPU_SANITIZE=1`` and validated by machinery the optimizer
cannot influence:

* **Shadow verifier** — each fused run about to execute is replayed
  abstractly (``jax.eval_shape`` over the same emit closures with a
  tracking state proxy) and every slot an op actually touches is
  compared against its declared footprint; an opaque thunk runs under
  the container-access watcher (``utils/sanitize.watch_containers``)
  and every container it touches is compared against its declared
  containers.  Violations raise :class:`FootprintViolation` carrying
  the §15 trace-tail postmortem.

* **Conflict-serializability oracle** — :func:`snapshot` captures the
  dependency structure of the RECORDED queue before the pass pipeline
  runs (pushdown rewrites opaque footprints in place);
  :func:`check_serializable` then proves the EXECUTED queue preserves
  every read-write / write-read / write-write dependency among the
  surviving ops, plus every pending-scalar producer edge and every
  barrier ordering.  Dropped (dead-eliminated) ops are unconstrained —
  their absence is validated by the bit-identity fuzz battery, not by
  ordering.

All footprint interpretation routes through ``plan/interference.py``
(rule R10); this module only consumes its accessors.
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import List, Optional

from . import PlanScalar, _Opaque, _Run
from . import interference as _interf
from ..core.pinning import pinned_id
from ..utils import sanitize as _sanitize
from ..utils.fallback import warn_fallback
from ..utils.resilience import _obs_tail

__all__ = ["FAMILIES", "FAMILY_NAMES", "FootprintViolation",
           "SerializationViolation", "verify_run", "watch",
           "snapshot", "check_serializable"]


#: The op families whose record sites declare footprints — ONE name
#: per ``Plan.record_*`` method (drlint rule R9 closes this registry
#: against plan/__init__.py, the SPEC §23.2 table, the mutation
#: battery in tests/test_plansan.py, and the fuzz arm both ways).
FAMILIES = (
    ("generator", "record_generator"),
    ("transform", "record_transform"),
    ("zip_foreach", "record_zip_foreach"),
    ("reduce", "record_reduce"),
    ("splice", "record_splice"),
    ("halo", "record_halo"),
    ("stencil", "record_stencil"),
    ("redistribute", "record_redistribute"),
    ("histogram", "record_histogram"),
    ("top_k", "record_top_k"),
    ("opaque", "record_opaque"),
)

FAMILY_NAMES = tuple(n for n, _m in FAMILIES)


class FootprintViolation(_sanitize.SanitizeError):
    """A recorded item touched state outside its declared footprint —
    the under-declaration every §21 pass would silently miscompile
    on.  Carries the §15 trace-tail postmortem like every classified
    error."""

    def __init__(self, msg: str):
        super().__init__(msg)
        self.trace_tail = _obs_tail()


class SerializationViolation(_sanitize.SanitizeError):
    """The optimized queue broke a dependency of the recorded order —
    a §21 pass (or a future one) reordered conflicting work."""

    def __init__(self, msg: str):
        super().__init__(msg)
        self.trace_tail = _obs_tail()


# ---------------------------------------------------------------------------
# shadow verifier: fused runs
# ---------------------------------------------------------------------------

class _Tracker:
    """State proxy for the abstract replay: records which run slot
    each op actually reads/writes, attributed to the op index in
    ``cur``.  The merge pass's ``_SubState`` wrappers compose
    transparently — they translate slots and index right through."""

    __slots__ = ("_v", "_obs", "cur")

    def __init__(self, vals, obs):
        self._v = list(vals)
        self._obs = obs
        self.cur = 0

    def __getitem__(self, i):
        self._obs[self.cur][0].add(i)
        return self._v[i]

    def __setitem__(self, i, v):
        self._obs[self.cur][1].add(i)
        self._v[i] = v


def _abstract(v):
    """ShapeDtypeStruct standing in for one traced operand."""
    import jax
    import jax.numpy as jnp
    if isinstance(v, PlanScalar):
        raw = v._val
        if raw is not None and v._post is None:
            return jax.ShapeDtypeStruct(tuple(getattr(raw, "shape", ())),
                                        jnp.result_type(raw))
        # pending (resolves at dispatch) or posted (item() -> host
        # float): a weak f32 scalar, same as the dispatch operand
        return jax.ShapeDtypeStruct((), jnp.float32)
    shp = tuple(getattr(v, "shape", ()))
    try:
        dt = jnp.result_type(v)
    except Exception:
        dt = jnp.float32
    return jax.ShapeDtypeStruct(shp, dt)


def _replay(run) -> Optional[List[tuple]]:
    """Abstractly re-trace the run's op sequence and observe per-op
    slot access; None = replay infrastructure failed (the run stays
    unverified — the verifier must never break a flush on its own
    plumbing)."""
    import jax
    ops = tuple(run.ops)
    observed = [(set(), set()) for _ in ops]
    abs_state = [jax.ShapeDtypeStruct(c._data.shape, c._data.dtype)
                 for c in run.conts]
    abs_tail = []
    for o in ops:
        nt = sum(1 for s in o.spec if not isinstance(s, tuple))
        if nt != len(o.vals):
            # operand values already dropped (cached program executed
            # this recording) — nothing to replay with
            return None
        for v in o.vals:
            abs_tail.append(_abstract(v))
    nslots = len(run.conts)

    def body(*args):
        st = _Tracker(args[:nslots], observed)
        tail = iter(args[nslots:])
        souts: list = []
        for k, o in enumerate(ops):
            st.cur = k
            svals = [souts[s[1]] if isinstance(s, tuple) else next(tail)
                     for s in o.spec]
            o.emit(st, svals, souts)
        return tuple(st._v) + tuple(souts)

    try:
        jax.eval_shape(body, *abs_state, *abs_tail)
    except Exception as e:
        warn_fallback("plansan", f"shadow replay failed ({e!r}); run "
                      f"{'+'.join(o.name for o in ops)} unverified")
        return None
    return observed


#: program+footprint keys that already verified clean; successes only,
#: so a re-declared footprint (the mutation battery) re-verifies the
#: same emitted program instead of riding a stale pass.
_verified: set = set()
_VERIFIED_CAP = 1024


def _verify_key(run) -> tuple:
    return ("plansan", pinned_id(run.mesh), run.axis,
            tuple((c.layout, str(c.dtype)) for c in run.conts),
            tuple(o.key for o in run.ops),
            tuple(_interf.op_footprint_key(o) for o in run.ops))


def verify_run(run) -> None:
    """Shadow-verify one fused run IMMEDIATELY before it executes:
    every slot an op's emit actually touches must sit inside its
    declared footprint.  Reads of a declared-WRITE slot are allowed —
    the mask-preserve emit idiom reads the prior row to pass
    unowned/unmasked cells through, which §21.2 deliberately keeps out
    of ``reads``.  Window extents are not checked (slot granularity);
    the bit-identity fuzz battery owns that remainder."""
    key = _verify_key(run)
    if key in _verified:
        return
    observed = _replay(run)
    if observed is None:
        return
    for o, (rds, wts) in zip(run.ops, observed):
        allowed_w = _interf.op_write_slots(o)
        allowed_r = _interf.op_read_slots(o) | allowed_w
        bad_r = sorted(rds - allowed_r)
        bad_w = sorted(wts - allowed_w)
        if bad_r or bad_w:
            def name(s):
                c = run.conts[s]
                return f"slot {s} ({type(c).__name__}[{len(c)}])"
            what = "; ".join(
                [f"READ of {name(s)}" for s in bad_r]
                + [f"WRITE of {name(s)}" for s in bad_w])
            raise FootprintViolation(
                f"plan op {o.name!r} touched state outside its "
                f"declared footprint: {what} (declared reads="
                f"{tuple(_interf.op_reads(o))}, writes="
                f"{tuple(_interf.op_writes(o))}) — an under-declared "
                "footprint miscompiles under every §21 pass; fix the "
                "record site (rule R9)")
    if len(_verified) >= _VERIFIED_CAP:
        _verified.clear()
    _verified.add(key)


# ---------------------------------------------------------------------------
# shadow verifier: opaque thunks
# ---------------------------------------------------------------------------

@contextmanager
def watch(item):
    """Observe container access while an opaque item's thunk runs:
    every instrumented container the thunk reads must be a declared
    read (or declared write — read-modify-write), every rebind a
    declared write.  Containers BORN inside the thunk (relational
    scratch, elastic rescues) are exempt.  A declared barrier
    (``None`` footprint) is exempt entirely — it already pays the
    worst case in every pass.  Violations collect during the thunk
    and raise AFTER it completes, so the watcher never truncates the
    eager path mid-write."""
    reads = _interf.opaque_reads(item)
    writes = _interf.opaque_writes(item)
    if reads is None or writes is None:
        yield
        return
    allowed_w = {id(c) for c, _full in writes}
    allowed_r = {id(c) for c in reads} | allowed_w
    exempt: set = set()
    bad: list = []

    def on_access(kind, cont):
        cid = id(cont)
        if cid in exempt:
            return
        ok = allowed_r if kind == "r" else allowed_w
        if cid not in ok:
            exempt.add(cid)   # report each container once
            bad.append(("READ" if kind == "r" else "WRITE", cont))

    def on_born(cont):
        exempt.add(id(cont))

    with _sanitize.watch_containers(on_access, on_born):
        yield
    if bad:
        what = "; ".join(f"{k} of {type(c).__name__}[{len(c)}]"
                         for k, c in bad)
        raise FootprintViolation(
            f"opaque op {item.name!r} touched containers outside its "
            f"declared footprint: {what} — declare the container at "
            "the record site, or record the op as a barrier "
            "(reads=None/writes=None) and pay the worst case "
            "(rule R9)")


# ---------------------------------------------------------------------------
# conflict-serializability oracle
# ---------------------------------------------------------------------------

class _Node:
    """One recorded unit of work at op granularity: a fused op or a
    whole opaque item, with its footprint resolved to container ids at
    snapshot time."""

    __slots__ = ("ident", "name", "rd", "wr", "barrier",
                 "run_id", "needs")

    def __init__(self, ident, name, rd, wr, barrier, run_id,
                 needs):
        self.ident = ident
        self.name = name
        self.rd = rd
        self.wr = wr
        self.barrier = barrier
        self.run_id = run_id
        self.needs = needs


def snapshot(queue) -> List[_Node]:
    """Capture the recorded queue's dependency structure BEFORE the
    optimizer runs — the pushdown pass rewrites opaque footprints in
    place, so the oracle must pin the original declarations now."""
    nodes: List[_Node] = []
    for item in queue:
        if isinstance(item, _Run):
            rid = id(item)
            for o in item.ops:
                nodes.append(_Node(
                    o, o.name,
                    frozenset(id(item.conts[s])
                              for s in _interf.op_read_slots(o)),
                    frozenset(id(item.conts[s])
                              for s in _interf.op_write_slots(o)),
                    False, rid,
                    frozenset(_interf.op_scalar_producers(o))))
            continue
        if _interf.opaque_is_barrier(item):
            nodes.append(_Node(item, item.name, frozenset(),
                               frozenset(), True, None, frozenset()))
            continue
        w = frozenset(id(c) for c, _full
                      in _interf.opaque_writes(item))
        r = frozenset(id(c) for c in _interf.opaque_reads(item)) | w
        nodes.append(_Node(item, item.name, r, w, False, None,
                           frozenset()))
    return nodes


def _conflict(a: _Node, b: _Node) -> bool:
    if a.barrier or b.barrier:
        return True
    return bool((a.wr & b.rd) or (a.rd & b.wr) or (a.wr & b.wr))


def check_serializable(nodes: List[_Node], exec_queue) -> None:
    """Prove the executed queue is a conflict-preserving reordering of
    the recorded one: every RW/WR/WW-conflicting recorded pair that
    SURVIVES the passes keeps its record order, every surviving op
    still follows its pending-scalar producers, and barriers order
    against everything.  Dropped ops are unconstrained (the dce pass
    is validated by bit-identity, not ordering)."""
    pos: dict = {}
    counter = 0
    for item in exec_queue:
        if isinstance(item, _Run):
            for o in item.ops:
                src = o
                while src.src is not None:
                    src = src.src
                pos[id(src)] = counter
                counter += 1
        else:
            pos[id(item)] = counter
            counter += 1

    alive = [(i, n, pos.get(id(n.ident))) for i, n in enumerate(nodes)]
    alive = [(i, n, p) for i, n, p in alive if p is not None]
    for x in range(len(alive)):
        i, a, pa = alive[x]
        for y in range(x + 1, len(alive)):
            j, b, pb = alive[y]
            scalar_edge = a.run_id is not None and a.run_id in b.needs
            if not scalar_edge and not _conflict(a, b):
                continue
            if pa < pb:
                continue
            why = ("pending-scalar producer" if scalar_edge
                   else "barrier" if (a.barrier or b.barrier)
                   else "data")
            raise SerializationViolation(
                f"optimized flush broke a {why} dependency: recorded "
                f"op {j} ({b.name!r}) executes at position {pb}, "
                f"BEFORE recorded op {i} ({a.name!r}) at {pa} — a §21 "
                "pass reordered conflicting work (conflict-"
                "serializability oracle, SPEC §23.4)")
