"""dr_tpu — a TPU-native distributed-ranges framework.

A from-scratch re-design of Intel's *Distributed Ranges* capability set
(reference: sudhirverma/distributed-ranges) for TPU: distributed containers
whose ``segments()`` are shards of ``jax.Array``s on a device mesh,
segment-preserving views, STL-style distributed algorithms lowered to fused
XLA programs with mesh collectives, and halo (ghost-cell) exchange as
``lax.ppermute`` neighbor shifts over ICI.

Public surface (mirrors the reference's ``lib::`` / ``mhp::`` / ``shp::``
namespaces through one TPU backend, called ``thp``):

- runtime:   ``init / final / nprocs / devices / barrier / fence``
- vocabulary: ``rank / segments / local`` CPOs + concept predicates
- containers: ``distributed_vector``, ``distributed_span``, ``dense_matrix``,
  ``sparse_matrix``
- views:      ``views.take / drop / subrange / slice / zip / transform /
  enumerate``
- algorithms: ``fill / iota / copy / for_each / transform / reduce /
  transform_reduce / inclusive_scan / exclusive_scan / sort /
  sort_by_key / argsort / is_sorted / dot / gemv``
- relational: ``join / groupby_aggregate / unique / histogram /
  top_k`` — the distributed dataframe tier on the sort/scan backbone
  (docs/SPEC.md §17)
- halo:       ``halo_bounds``, ``span_halo``, ``halo(r)``, ``stencil``
- plans:      ``deferred`` / ``Plan`` — record algorithm chains, flush
  them as ONE fused dispatch (cross-algorithm dispatch fusion)
- elastic:    ``redistribute`` / ``elastic.rescue_session`` /
  ``elastic.grow_session`` — survive a mid-session device loss by
  shrinking the mesh and rescuing live state, then RE-ADMIT recovered
  devices/relays and move live state back onto the grown layout
  (docs/SPEC.md §16/§16.6; ``DR_TPU_ELASTIC=1`` arms automatic
  shrink-and-retry, ``DR_TPU_ELASTIC_GROW=1`` the symmetric grow-back
  polls)
"""

from .utils import jax_compat  # noqa: F401  (jax.shard_map shim, first)
from .utils import sanitize as _sanitize
_sanitize.install()  # no-op unless DR_TPU_SANITIZE=1 (docs/SPEC.md §13.4)
from . import obs
obs.install()  # no-op unless DR_TPU_TRACE=1 (docs/SPEC.md §15)
from .parallel.runtime import (init, final, finalize, runtime, nprocs,
                               devices, mesh, barrier, fence,
                               get_duplicated_devices)
from .parallel.halo import halo_bounds, span_halo, halo_ops
from .parallel.unstructured_halo import unstructured_halo
from .parallel.collectives import (communicator, rma_window, default_comm,
                                   init_distributed)
from .core.vocabulary import (rank, segments, local, is_remote_range,
                              is_distributed_range,
                              is_remote_contiguous_range,
                              is_distributed_contiguous_range)
from .core.segment import Segment, ZipSegment
from .containers.distributed_vector import distributed_vector, halo
from .containers.distribution import block_distribution, even_sizes
from .containers.partition import (tile, matrix_partition, block_cyclic,
                                   row_tiles, factor)
from .containers.dense_matrix import dense_matrix, matrix_entry, Index2D
from .containers.sparse_matrix import sparse_matrix, random_sparse_matrix
from .containers.distributed_span import distributed_span
from .containers.mdarray import (distributed_mdarray, distributed_mdspan,
                                 transpose)
from .utils.logging import drlog
from .utils.debug import print_range, print_matrix, range_details
from .utils import checkpoint
from .utils import elastic
from .utils.elastic import redistribute
from .utils import faults
from .utils import profiling
from .utils import resilience
from .utils import spmd_guard
from .ops.ring_attention import ring_attention, ring_attention_n
from .views import views
from .views.views import aligned, local_segments
from .algorithms.elementwise import (fill, iota, copy, copy_async, for_each,
                                     transform, to_numpy)
from .algorithms.reduce import (reduce, transform_reduce, dot, dot_n,
                                reduce_async, transform_reduce_async,
                                dot_async)
from .algorithms.scan import (inclusive_scan, exclusive_scan,
                              inclusive_scan_n)
from .algorithms.sort import sort, sort_by_key, argsort, is_sorted
from .algorithms.relational import (join, groupby_aggregate, unique,
                                    histogram, top_k, DeferredCount,
                                    join_auto, groupby_auto, unique_auto,
                                    AutoResult)
from .algorithms.stencil import stencil_transform, stencil_iterate
from .algorithms.stencil2d import (stencil2d_transform, stencil2d_iterate,
                                   stencil2d_n, heat_step_weights)
from .algorithms.gemv import gemv, gemv_n, flat_gemv, gemm, spmm, spmm_n
from . import plan
from . import tuning
from .plan import Plan, PlanScalar, deferred

__version__ = "0.1.0"

__all__ = [
    "init", "final", "finalize", "runtime", "nprocs", "devices", "mesh",
    "barrier", "fence", "get_duplicated_devices",
    "halo_bounds", "span_halo", "halo_ops", "halo",
    "rank", "segments", "local",
    "is_remote_range", "is_distributed_range",
    "is_remote_contiguous_range", "is_distributed_contiguous_range",
    "Segment", "ZipSegment",
    "distributed_vector", "block_distribution", "even_sizes",
    "views", "aligned", "local_segments",
    "fill", "iota", "copy", "copy_async", "for_each", "transform",
    "to_numpy", "reduce", "transform_reduce", "dot",
    "reduce_async", "transform_reduce_async", "dot_async",
    "inclusive_scan", "exclusive_scan",
    "join", "groupby_aggregate", "unique", "histogram", "top_k",
    "DeferredCount", "join_auto", "groupby_auto", "unique_auto",
    "AutoResult", "tuning",
    "stencil_transform", "stencil_iterate",
    "stencil2d_transform", "stencil2d_iterate", "heat_step_weights",
    "gemv", "flat_gemv", "gemm", "spmm",
    "tile", "matrix_partition", "block_cyclic", "row_tiles", "factor",
    "dense_matrix", "matrix_entry", "Index2D",
    "sparse_matrix", "random_sparse_matrix",
    "unstructured_halo", "communicator", "rma_window", "default_comm",
    "init_distributed", "distributed_span",
    "drlog", "print_range", "print_matrix", "range_details",
    "distributed_mdarray", "distributed_mdspan", "transpose",
    "checkpoint", "profiling", "spmd_guard", "faults", "resilience",
    "obs", "elastic", "redistribute",
    "ring_attention", "ring_attention_n",
    "dot_n", "inclusive_scan_n", "gemv_n", "spmm_n", "stencil2d_n",
    "plan", "Plan", "PlanScalar", "deferred",
]
