"""Pallas TPU kernel: single-HBM-pass chunked prefix sum.

The XLA matmul-cumsum (algorithms/scan.py `_matmul_cumsum`) needs two
full passes over the data: one producing the per-row prefixes and one
re-reading them for the carry fixup — ~16 B/element of HBM traffic
where the operation's floor is 8 B (read + write once).  This kernel
fuses everything into one pass: chunks stream through VMEM
(double-buffered DMA), each chunk's local prefix runs on the MXU
(multiply by an upper-triangular ones matrix), and the running carry
lives in an SMEM scratch that persists across the SEQUENTIAL TPU grid —
so the carry "fixup" is a free broadcast-add while the chunk is still
resident.

Layout: x viewed as (rows, 128) lane-blocked; flat order is row-major,
so the prefix decomposes HIERARCHICALLY (rows split into groups of 128):
  within-row lane prefix      (rows @ U128, upper-triangular ones, MXU)
  + within-group row offset   (row totals reshaped (G, 128), one
                               (G,128) @ Ustrict128 MXU matmul)
  + group offset              ((G, G) strictly-lower matvec — one tile)
  + chunk carry               (SMEM scalar across the sequential grid).
The round-2 kernel computed the row offset with ONE (R, R) strictly-
lower operator instead; its O(R^2) cost forced R=512 chunks and the
2048-step sequential grid ran per-step-overhead-bound at 148 GB/s
(19% of HBM).  The hierarchy caps every operator at one MXU tile, so
chunks grow until the DMA dominates.

Precision: the prefix operators are 0/1 matrices — EXACT in bf16 — so
``x @ U`` with x split into k bf16 terms (hi + residuals) costs k
DEFAULT-precision MXU passes and reconstructs the f32 product to term
precision (k=3 ~ f32-exact, the HIGHEST semantics at half the passes;
DR_TPU_SCAN_PASSES to sweep, 0 = plain f32 HIGHEST).

Reference workload: ``shp/algorithms/inclusive_scan.hpp:25-148``
(BASELINE.json config 3).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from jax.experimental import pallas as pl

from .stencil_pallas import _HAS_PLTPU, pltpu
from ..utils.env import env_str

__all__ = ["chunked_cumsum", "pick_chunk", "prefix_matrix",
           "supported"]

LANES = 128
_MAX_ROWS = 8192  # default chunk rows (hierarchical offsets: no (R, R)
# operator to bound — the cap is the 2x double-buffered VMEM footprint;
# R=8192 measured best on the v5e, tools/tune_scan3.log)


def supported() -> bool:
    return _HAS_PLTPU


def chunk_cap() -> int:
    """Chunk-rows cap, DR_TPU_SCAN_CHUNK-overridable (rounded down to a
    power of two, tolerant parse) for on-device tuning: larger chunks
    amortize the sequential grid's per-step overhead; the (R, R)
    matmul-variant offset operator and the 4*R KiB VMEM buffers push
    back.  Read per call — scan program caches key on it
    (algorithms/scan.py ``_kernel_variant``).  When the env var is
    unset, a measured ``scan.chunk`` winner in the persisted tuning
    DB (docs/SPEC.md §21.6, written by ``tune_tpu.py scan``) replaces
    the code default for this mesh's backend/shape context."""
    from ..utils.env import env_pow2, env_raw
    if env_raw("DR_TPU_SCAN_CHUNK") is None:
        from .. import tuning as _tuning
        v = _tuning.lookup("scan", "chunk")
        if v is not None:
            try:
                v = max(int(v), LANES)
                return max(LANES, 1 << (v.bit_length() - 1))
            except (TypeError, ValueError):
                pass
    return env_pow2("DR_TPU_SCAN_CHUNK", _MAX_ROWS, floor=LANES)


def pick_chunk(n: int):
    """Chunk rows R (power of two, R*128 divides n) or None -> caller
    falls back to the XLA path."""
    if n % LANES:
        return None
    rows = n // LANES
    R = chunk_cap()
    while R >= LANES:
        if rows % R == 0:
            return R
        R //= 2
    return None


@functools.lru_cache(maxsize=8)
def prefix_matrix(k: int):
    """Upper-triangular ones: (rows @ prefix_matrix)[i, j] =
    sum_{b<=j} rows[i, b].  Shared by this kernel and the XLA
    matmul-cumsum (algorithms/scan.py).  NUMPY on purpose (see
    stencil_matmul._operator): jnp here would leak a tracer through
    the cache."""
    return np.triu(np.ones((k, k), dtype=np.float32))


@functools.lru_cache(maxsize=8)
def _strict_lower(k: int):
    """(Lstrict @ col)[i] = sum_{r<i} col[r]: the exclusive group-offset
    operator (NUMPY, see prefix_matrix)."""
    return np.tril(np.ones((k, k), dtype=np.float32), -1)


@functools.lru_cache(maxsize=8)
def _strict_upper(k: int):
    """(rows @ Ustrict)[g, i] = sum_{i'<i} rows[g, i']: the exclusive
    within-group row-offset operator (NUMPY, see prefix_matrix)."""
    return np.triu(np.ones((k, k), dtype=np.float32), 1)


def scan_passes() -> int:
    """bf16 term count for the lane-prefix matmul (DR_TPU_SCAN_PASSES):
    k terms cost k DEFAULT MXU passes and keep ~8k mantissa bits of the
    input (the 0/1 operator is exact in bf16, so all error is in the
    split).  0 selects plain f32 HIGHEST (6 fused passes) — the default:
    the kernel is DMA-bound, HIGHEST measured fastest on the v5e (one
    fused op vs split casts + 3 dots, tools/tune_scan3.log), and it is
    the most accurate form."""
    from ..utils.env import env_int
    return min(env_int("DR_TPU_SCAN_PASSES", 0, floor=0), 3)


def _bf16_terms(x, k: int):
    """k bf16 terms summing to x (f32) to ~8k mantissa bits; the last
    term absorbs the running residual."""
    terms = []
    for _ in range(k - 1):
        t = x.astype(jnp.bfloat16)
        terms.append(t)
        x = x - t.astype(jnp.float32)
    terms.append(x.astype(jnp.bfloat16))
    return terms


def _chunk_prefix(x, u_ref, us_ref, lg_ref, carry_val, vpu, passes, G):
    """One chunk's inclusive prefix (f32) given the incoming carry;
    returns ``(out, chunk_total)``.  Shared by the manual-DMA and the
    auto-pipelined kernel bodies."""
    R = x.shape[0]
    if vpu:
        # log-step shifted adds on the vector unit (Hillis-Steele
        # along lanes; Mosaic has no cumsum primitive, but lane
        # rolls + masked adds lower fine)
        P1 = x
        lane = lax.broadcasted_iota(jnp.int32, x.shape, 1)
        d = 1
        while d < LANES:
            sh = pltpu.roll(P1, d, 1)
            P1 = P1 + jnp.where(lane >= d, sh, 0.0)
            d *= 2
    elif passes:
        # lane prefix within each 128-wide row: the 0/1 operator is
        # EXACT in bf16, so k split terms = k DEFAULT MXU passes
        # with ~8k-bit effective input mantissa
        P1 = None
        for t in _bf16_terms(x, passes):
            p = lax.dot_general(t, u_ref[:], (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
            P1 = p if P1 is None else P1 + p
    else:
        P1 = lax.dot_general(x, u_ref[:].astype(jnp.float32),
                             (((1,), (0,)), ((), ())),
                             precision=lax.Precision.HIGHEST,
                             preferred_element_type=jnp.float32)
    # hierarchical row offsets: totals regrouped (G, 128) so both
    # prefix operators stay single-tile MXU work at any R
    row_tot = P1[:, LANES - 1:LANES]              # (R, 1)
    t2 = row_tot.reshape(G, LANES)                # (G, 128)
    o2 = lax.dot_general(t2, us_ref[:], (((1,), (0,)), ((), ())),
                         precision=lax.Precision.HIGHEST,
                         preferred_element_type=jnp.float32)
    s = (o2[:, LANES - 1:LANES]
         + t2[:, LANES - 1:LANES])                # (G, 1) group sums
    go = lax.dot_general(lg_ref[:], s, (((1,), (0,)), ((), ())),
                         precision=lax.Precision.HIGHEST,
                         preferred_element_type=jnp.float32)  # (G, 1)
    off = o2 + go                                 # (G, 128) row offs
    out = (P1.reshape(G, LANES, LANES)
           + off[:, :, None] + carry_val).reshape(R, LANES)
    return out, go[G - 1, 0] + s[G - 1, 0]


@functools.lru_cache(maxsize=16)
def _build_grid(rows: int, R: int, dtype_name: str, interpret: bool,
                vpu: bool = False, passes: int = 3):
    """Auto-pipelined form: a sequential TPU grid over (R, 128) blocks
    with Mosaic's implicit double-buffered block DMA; only the carry is
    explicit state (SMEM scratch persists across grid steps).  Simpler
    than the manual-DMA form and lets the compiler overlap the i-1
    out-copy, the i compute, and the i+1 in-copy."""
    dtype = jnp.dtype(dtype_name)
    nch = rows // R
    G = R // LANES

    def kernel(c0_ref, u_ref, us_ref, lg_ref, x_ref, o_ref, carry):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _():
            carry[0, 0] = c0_ref[0, 0]

        x = x_ref[...].astype(jnp.float32)
        out, tot = _chunk_prefix(x, u_ref, us_ref, lg_ref, carry[0, 0],
                                 vpu, passes, G)
        o_ref[...] = out.astype(dtype)
        carry[0, 0] = carry[0, 0] + tot

    params = {}
    if not interpret:
        params["compiler_params"] = pltpu.CompilerParams(
            vmem_limit_bytes=100 * 2 ** 20,
            dimension_semantics=("arbitrary",))
    return pl.pallas_call(
        kernel,
        grid=(nch,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec((R, LANES), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((R, LANES), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, LANES), dtype),
        scratch_shapes=[pltpu.SMEM((1, 1), jnp.float32)],
        interpret=interpret,
        **params,
    )


@functools.lru_cache(maxsize=16)
def _build(rows: int, R: int, dtype_name: str, interpret: bool,
           vpu: bool = False, passes: int = 3):
    """Manual double-buffered DMA form (DR_TPU_SCAN_PIPE=manual);
    ``vpu=True`` swaps the lane-prefix matmul for a log-step
    Hillis-Steele on the vector unit (``pltpu.roll`` shifted adds) —
    same math, different unit; which wins on a given chip generation is
    an empirical question (DR_TPU_SCAN_KERNEL=vpu to select,
    tools/tune_tpu.py to measure)."""
    dtype = jnp.dtype(dtype_name)
    nch = rows // R
    G = R // LANES

    def kernel(c0_ref, u_ref, us_ref, lg_ref, x_hbm, out_hbm, vin, vout,
               carry, in_sem, out_sem):
        # carry lives in SMEM: scalar state across the sequential grid,
        # SEEDED from the caller's scalar (the distributed scan's
        # exclusive carry — folding it here saves the whole-array
        # fixup pass)
        i = pl.program_id(0)
        slot = lax.rem(i, 2)

        def in_dma(c, s):
            return pltpu.make_async_copy(
                x_hbm.at[pl.ds(c * R, R), :], vin.at[s], in_sem.at[s])

        def out_dma(c, s):
            return pltpu.make_async_copy(
                vout.at[s], out_hbm.at[pl.ds(c * R, R), :], out_sem.at[s])

        @pl.when(i == 0)
        def _():
            carry[0, 0] = c0_ref[0, 0]
            in_dma(0, 0).start()

        @pl.when(i + 1 < nch)
        def _():
            in_dma(i + 1, 1 - slot).start()

        in_dma(i, slot).wait()

        @pl.when(i >= 2)
        def _():
            out_dma(i - 2, slot).wait()

        x = vin[slot].astype(jnp.float32)
        out, tot = _chunk_prefix(x, u_ref, us_ref, lg_ref, carry[0, 0],
                                 vpu, passes, G)
        carry[0, 0] = carry[0, 0] + tot
        vout[slot] = out.astype(dtype)
        out_dma(i, slot).start()

        @pl.when(i == nch - 1)
        def _():
            out_dma(i, slot).wait()

        if nch > 1:
            @pl.when(i == nch - 1)
            def _():
                out_dma(i - 1, 1 - slot).wait()

    params = {}
    if not interpret:
        params["compiler_params"] = pltpu.CompilerParams(
            vmem_limit_bytes=100 * 2 ** 20)
    return pl.pallas_call(
        kernel,
        grid=(nch,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        out_shape=jax.ShapeDtypeStruct((rows, LANES), dtype),
        scratch_shapes=[
            pltpu.VMEM((2, R, LANES), dtype),
            pltpu.VMEM((2, R, LANES), dtype),
            pltpu.SMEM((1, 1), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=interpret,
        **params,
    )


def chunked_cumsum(x, *, carry=None, interpret: bool = False):
    """Inclusive add-scan of a 1-D float array in ONE HBM pass.

    ``carry`` (traced f32 scalar, default 0) seeds the running carry —
    the distributed scan passes its exclusive cross-shard carry here so
    no separate whole-array fixup pass ever touches HBM.

    Requires ``pick_chunk(len(x))`` to succeed (lane-blocked chunking);
    callers fall back to the XLA matmul-cumsum otherwise.
    ``DR_TPU_SCAN_KERNEL=vpu`` selects the Hillis-Steele (vector-unit)
    variant of the in-chunk prefix; default is the MXU matmul form."""
    n = x.shape[0]
    R = pick_chunk(n)
    assert R is not None, "no lane-aligned chunking for this length"
    rows = n // LANES
    G = R // LANES
    vpu = env_str("DR_TPU_SCAN_KERNEL").lower() == "vpu"
    passes = scan_passes()
    # default is the manual double-buffered pipeline: it has compiled
    # and run on hardware; the auto-grid form is opt-in
    # (DR_TPU_SCAN_PIPE=grid) until a chip compile proves it out
    grid = (env_str("DR_TPU_SCAN_PIPE").lower()
            == "grid")
    build = _build_grid if grid else _build
    fn = build(rows, R, str(x.dtype), interpret, vpu, passes)
    if vpu:
        # the vpu kernel never reads the lane-prefix operand
        U = jnp.zeros((1, 1), jnp.bfloat16)
    else:
        U = jnp.asarray(prefix_matrix(LANES),
                        jnp.bfloat16 if passes else jnp.float32)
    Us = jnp.asarray(_strict_upper(LANES), jnp.float32)
    Lg = jnp.asarray(_strict_lower(G), jnp.float32)
    c0 = jnp.zeros((1, 1), jnp.float32) if carry is None else \
        jnp.asarray(carry, jnp.float32).reshape(1, 1)
    return fn(c0, U, Us, Lg, x.reshape(rows, LANES)).reshape(n)
