"""Pallas TPU kernel: single-HBM-pass chunked prefix sum.

The XLA matmul-cumsum (algorithms/scan.py `_matmul_cumsum`) needs two
full passes over the data: one producing the per-row prefixes and one
re-reading them for the carry fixup — ~16 B/element of HBM traffic
where the operation's floor is 8 B (read + write once).  This kernel
fuses everything into one pass: chunks stream through VMEM
(double-buffered DMA), each chunk's local prefix runs on the MXU
(multiply by an upper-triangular ones matrix), and the running carry
lives in an SMEM scratch that persists across the SEQUENTIAL TPU grid —
so the carry "fixup" is a free broadcast-add while the chunk is still
resident.

Layout: x viewed as (rows, 128) lane-blocked; flat order is row-major,
so the prefix decomposes as
  within-row lane prefix      (rows @ U128, upper-triangular ones, MXU)
  + exclusive row offset      (Lstrict @ row_totals: strictly-LOWER
                               triangular ones on the sublane axis —
                               no cross-layout reshapes, all MXU)
  + chunk carry               (SMEM scalar across the sequential grid).

Reference workload: ``shp/algorithms/inclusive_scan.hpp:25-148``
(BASELINE.json config 3).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from jax.experimental import pallas as pl

from .stencil_pallas import _HAS_PLTPU, pltpu

__all__ = ["chunked_cumsum", "pick_chunk", "prefix_matrix",
           "supported"]

LANES = 128
_MAX_ROWS = 512  # default chunk rows: bounds the (R, R) row-offset operator


def supported() -> bool:
    return _HAS_PLTPU


def chunk_cap() -> int:
    """Chunk-rows cap, DR_TPU_SCAN_CHUNK-overridable (rounded down to a
    power of two, tolerant parse) for on-device tuning: larger chunks
    amortize the sequential grid's per-step overhead; the (R, R)
    matmul-variant offset operator and the 4*R KiB VMEM buffers push
    back.  Read per call — scan program caches key on it
    (algorithms/scan.py ``_kernel_variant``)."""
    from ..utils.env import env_pow2
    return env_pow2("DR_TPU_SCAN_CHUNK", _MAX_ROWS, floor=LANES)


def pick_chunk(n: int):
    """Chunk rows R (power of two, R*128 divides n) or None -> caller
    falls back to the XLA path."""
    if n % LANES:
        return None
    rows = n // LANES
    R = chunk_cap()
    while R >= LANES:
        if rows % R == 0:
            return R
        R //= 2
    return None


@functools.lru_cache(maxsize=8)
def prefix_matrix(k: int):
    """Upper-triangular ones: (rows @ prefix_matrix)[i, j] =
    sum_{b<=j} rows[i, b].  Shared by this kernel and the XLA
    matmul-cumsum (algorithms/scan.py).  NUMPY on purpose (see
    stencil_matmul._operator): jnp here would leak a tracer through
    the cache."""
    return np.triu(np.ones((k, k), dtype=np.float32))


@functools.lru_cache(maxsize=8)
def _strict_lower(k: int):
    """(Lstrict @ col)[i] = sum_{r<i} col[r]: the exclusive row-offset
    operator (NUMPY, see prefix_matrix)."""
    return np.tril(np.ones((k, k), dtype=np.float32), -1)


@functools.lru_cache(maxsize=16)
def _build(rows: int, R: int, dtype_name: str, interpret: bool,
           vpu: bool = False):
    """``vpu=True`` swaps the two MXU matmuls for log-step cumsums on
    the vector unit — same math, different unit; which wins on a given
    chip generation is an empirical question (DR_TPU_SCAN_KERNEL=vpu to
    select, tools/tune_tpu.py to measure)."""
    dtype = jnp.dtype(dtype_name)
    nch = rows // R

    def kernel(u_ref, lo_ref, x_hbm, out_hbm, vin, vout, carry, in_sem,
               out_sem):
        # carry lives in SMEM: scalar state across the sequential grid
        i = pl.program_id(0)
        slot = lax.rem(i, 2)

        def in_dma(c, s):
            return pltpu.make_async_copy(
                x_hbm.at[pl.ds(c * R, R), :], vin.at[s], in_sem.at[s])

        def out_dma(c, s):
            return pltpu.make_async_copy(
                vout.at[s], out_hbm.at[pl.ds(c * R, R), :], out_sem.at[s])

        @pl.when(i == 0)
        def _():
            carry[0, 0] = jnp.zeros((), jnp.float32)
            in_dma(0, 0).start()

        @pl.when(i + 1 < nch)
        def _():
            in_dma(i + 1, 1 - slot).start()

        in_dma(i, slot).wait()

        @pl.when(i >= 2)
        def _():
            out_dma(i - 2, slot).wait()

        x = vin[slot].astype(jnp.float32)
        if vpu:
            # log-step shifted adds on the vector unit; the f32 HIGHEST
            # matmuls cost 6 MXU passes each, which can exceed the DMA
            # floor — the VPU does the same prefix in ~7+9 vector steps
            P1 = jnp.cumsum(x, axis=1)
            row_tot = P1[:, LANES - 1:LANES]          # (R, 1)
            incl_rows = jnp.cumsum(row_tot, axis=0)   # (R, 1)
            excl_rows = incl_rows - row_tot
        else:
            # lane prefix within each 128-wide row (MXU)
            P1 = lax.dot_general(x, u_ref[:], (((1,), (0,)), ((), ())),
                                 precision=lax.Precision.HIGHEST,
                                 preferred_element_type=jnp.float32)
            row_tot = P1[:, LANES - 1:LANES]          # (R, 1)
            # exclusive row offsets on the SUBLANE axis: one (R, R)
            # strictly-lower matmul — no cross-layout reshapes
            excl_rows = lax.dot_general(
                lo_ref[:], row_tot, (((1,), (0,)), ((), ())),
                precision=lax.Precision.HIGHEST,
                preferred_element_type=jnp.float32)   # (R, 1)
        out = P1 + excl_rows + carry[0, 0]
        carry[0, 0] = (carry[0, 0] + excl_rows[R - 1, 0]
                       + row_tot[R - 1, 0])
        vout[slot] = out.astype(dtype)
        out_dma(i, slot).start()

        @pl.when(i == nch - 1)
        def _():
            out_dma(i, slot).wait()

        if nch > 1:
            @pl.when(i == nch - 1)
            def _():
                out_dma(i - 1, 1 - slot).wait()

    params = {}
    if not interpret:
        params["compiler_params"] = pltpu.CompilerParams(
            vmem_limit_bytes=100 * 2 ** 20)
    return pl.pallas_call(
        kernel,
        grid=(nch,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        out_shape=jax.ShapeDtypeStruct((rows, LANES), dtype),
        scratch_shapes=[
            pltpu.VMEM((2, R, LANES), dtype),
            pltpu.VMEM((2, R, LANES), dtype),
            pltpu.SMEM((1, 1), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=interpret,
        **params,
    )


def chunked_cumsum(x, *, interpret: bool = False):
    """Inclusive add-scan of a 1-D float array in ONE HBM pass.

    Requires ``pick_chunk(len(x))`` to succeed (lane-blocked chunking);
    callers fall back to the XLA matmul-cumsum otherwise.
    ``DR_TPU_SCAN_KERNEL=vpu`` selects the cumsum (vector-unit)
    variant of the in-chunk prefix; default is the MXU matmul form."""
    import os
    n = x.shape[0]
    R = pick_chunk(n)
    assert R is not None, "no lane-aligned chunking for this length"
    rows = n // LANES
    vpu = os.environ.get("DR_TPU_SCAN_KERNEL", "").strip().lower() == "vpu"
    fn = _build(rows, R, str(x.dtype), interpret, vpu)
    if vpu:
        # the vpu kernel never reads the matmul operands: ship 1x1
        # dummies instead of the (128,128)+(R,R) matrices (the whole
        # point of the variant is minimal VMEM/HBM traffic)
        U = L = jnp.zeros((1, 1), jnp.float32)
    else:
        U = jnp.asarray(prefix_matrix(LANES), jnp.float32)
        L = jnp.asarray(_strict_lower(R), jnp.float32)
    return fn(U, L, x.reshape(rows, LANES)).reshape(n)
