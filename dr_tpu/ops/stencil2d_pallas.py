"""Pallas TPU kernel: temporally-blocked 2-D (3x3) stencil.

The XLA 2-D heat loop (algorithms/stencil2d.py) pays full HBM traffic
plus shifted-slice relayouts every step (~100 GB/s logical on v5e, vs a
~310 GB/s elementwise floor).  This kernel processes full-width row
bands resident in VMEM and fuses ``T`` time steps per HBM pass: each
band is DMA'd in once with ``T`` halo rows above and below
(double-buffered, overlapping DMA with compute), stepped T times on the
VPU, and written back once.

Boundary contract (matches ``stencil2d_transform``'s interior-only
writes when both buffers share edge values, i.e. the usual
both-initialized-from-src setup): edge rows/columns are FROZEN — every
step rewrites them with their pre-step value (Dirichlet), interior
cells get the 3x3 weighted sum.

Row-padded layout: the kernel reads AND writes arrays with ``pad``
extra rows above and below, so a multi-block drive pads once and keeps
the layout across blocks — no per-pass re-pad traffic.  Pad-row
contents are irrelevant: the frozen edge rows stop the dependency cone
at the boundary, so pad garbage only ever feeds the trapezoid margin.

Geometry: band height H divides m; n is a multiple of 128 lanes; rows
per band DMA = H + 2T.  Reference workload: the 2-D mdspan heat
equation (BASELINE.json config 4; SURVEY.md §2.6).
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from jax.experimental import pallas as pl

from .stencil_pallas import (LANES, SUBLANES, _HAS_PLTPU, pltpu, supported,
                             tpu_roll)

__all__ = ["blocked_stencil2d", "blocked_stencil2d_padded", "pick_band",
           "supported"]


@functools.lru_cache(maxsize=32)
def _build(m: int, n: int, H: int, T: int, pad: int, weights: tuple,
           dtype_name: str, interpret: bool):
    """pallas_call: (m + 2*pad, n) padded array -> same-shape padded
    array with the owned rows stepped T times (pad >= T)."""
    dtype = jnp.dtype(dtype_name)
    w = np.asarray(weights, dtype=np.float64)
    assert w.shape == (3, 3)
    assert m % H == 0 and n % LANES == 0 and pad >= T
    nbands = m // H
    wrows = H + 2 * T

    def step_tile(u, interior):
        """One masked stencil step on a (wrows, n) VMEM tile; ``interior``
        is the precomputed keep-edges mask for this band."""
        acc = jnp.zeros_like(u, dtype=jnp.float32)
        for di in range(3):
            # row shift: tile rows are haloed, rolls are cheap sublane
            # rotates; wrapped rows are in the trapezoid margin
            ur = u if di == 1 else tpu_roll(u, 1 - di, 0, interpret)
            for dj in range(3):
                wij = float(w[di, dj])
                if wij == 0.0:
                    continue
                sh = ur if dj == 1 else tpu_roll(ur, 1 - dj, 1, interpret)
                acc = acc + wij * sh
        return jnp.where(interior, acc.astype(dtype), u)

    def kernel(in_hbm, out_hbm, vin, vout, in_sem, out_sem):
        i = pl.program_id(0)
        slot = lax.rem(i, 2)
        off = pad - T  # first padded row of band 0's DMA window

        def in_dma(b, s):
            return pltpu.make_async_copy(
                in_hbm.at[pl.ds(off + b * H, wrows), :], vin.at[s],
                in_sem.at[s])

        def out_dma(b, s):
            return pltpu.make_async_copy(
                vout.at[s], out_hbm.at[pl.ds(pad + b * H, H), :],
                out_sem.at[s])

        @pl.when(i == 0)
        def _():
            in_dma(0, 0).start()

        @pl.when(i + 1 < nbands)
        def _():
            in_dma(i + 1, 1 - slot).start()

        in_dma(i, slot).wait()

        @pl.when(i >= 2)
        def _():
            out_dma(i - 2, slot).wait()

        u = vin[slot]
        # freeze global edges: first/last original row, first/last
        # column (original row of tile row r is i*H + r - T).  Computed
        # once per band, reused every step.
        orig_row = (i * H - T) + lax.broadcasted_iota(jnp.int32, u.shape, 0)
        col = lax.broadcasted_iota(jnp.int32, u.shape, 1)
        interior = ((orig_row > 0) & (orig_row < m - 1)
                    & (col > 0) & (col < n - 1))
        u = lax.fori_loop(0, T, lambda t, x: step_tile(x, interior), u)
        vout[slot] = u[T:T + H, :]
        out_dma(i, slot).start()

        @pl.when(i == nbands - 1)
        def _():
            out_dma(i, slot).wait()

        if nbands > 1:
            @pl.when(i == nbands - 1)
            def _():
                out_dma(i - 1, 1 - slot).wait()

    params = {}
    if not interpret:
        # the per-step temporaries (rolled copies, f32 acc, masks) exceed
        # the default 16 MiB scoped-vmem limit at useful band sizes
        params["compiler_params"] = pltpu.CompilerParams(
            vmem_limit_bytes=100 * 2 ** 20)
    return pl.pallas_call(
        kernel,
        grid=(nbands,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        out_shape=jax.ShapeDtypeStruct((m + 2 * pad, n), dtype),
        scratch_shapes=[
            pltpu.VMEM((2, wrows, n), dtype),
            pltpu.VMEM((2, H, n), dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=interpret,
        **params,
    )


def pick_band(m: int, n: int, T: int,
              vmem_budget: int = 88 * 2 ** 20) -> int:
    """Largest band height H dividing m whose double-buffered in/out
    tiles plus ~5 working copies of the haloed tile fit the VMEM budget.
    H must divide m.  Sublane-aligned divisors (H % 8 == 0) are preferred
    outright — an aligned band DMAs whole (8, 128) tiles — and unaligned
    divisors are used only when no aligned one fits.  Raises when no
    divisor fits — pass an explicit ``band`` (or reshape) in that case."""
    def fits(H):
        return (7 * (H + 2 * T) + 2 * H) * n * 4 <= vmem_budget
    divisors = [h for h in range(1, m + 1) if m % h == 0 and fits(h)]
    aligned = [h for h in divisors if h % SUBLANES == 0]
    if aligned:
        return max(aligned)
    if divisors:
        return max(divisors)
    raise ValueError(
        f"no band height divides m={m} within the VMEM budget "
        f"(n={n}, T={T}); pass band= explicitly or pad the rows")


def blocked_stencil2d_padded(xp, m: int, weights, tsteps: int, pad: int,
                             *, band: int = None,
                             interpret: bool = False):
    """One T-step pass over a row-padded (m + 2*pad, n) array; returns
    the same padded layout (chain passes without re-padding)."""
    if not _HAS_PLTPU:
        raise RuntimeError("pallas TPU namespace unavailable")
    n = xp.shape[1]
    T = tsteps
    H = band or pick_band(m, n, T)
    assert m % H == 0, "band height must divide the row count"
    fn = _build(m, n, H, T, pad,
                tuple(map(tuple, np.asarray(weights, float))),
                str(xp.dtype), interpret)
    return fn(xp)


def blocked_stencil2d(x, weights: Sequence[Sequence[float]], tsteps: int,
                      *, band: int = None, interpret: bool = False):
    """Apply ``tsteps`` fused 3x3 stencil steps to a 2-D array with
    frozen (Dirichlet) edges.  Returns the stepped array.  One-shot
    convenience over :func:`blocked_stencil2d_padded`."""
    m, n = x.shape
    xp = jnp.pad(x, ((tsteps, tsteps), (0, 0)))
    out = blocked_stencil2d_padded(xp, m, weights, tsteps, tsteps,
                                   band=band, interpret=interpret)
    return out[tsteps:tsteps + m, :]
