"""Pallas TPU kernel: on-chip bitonic sort of one shard's key block.

The sample sort's LOCAL phase (``algorithms/sort.py`` phase 1) is the
profiled hot loop — ``lax.sort`` round-trips HBM per merge level, while
a shard's key block fits VMEM outright.  This kernel runs the whole
bitonic network on-chip: the block is viewed ``(M/128, 128)``
lane-blocked, every compare-exchange stage is one vectorized
min/max/select over the full tile, and the two partner mechanisms map
to the two on-chip data paths — stride ``j >= 128`` partners are a
leading-axis regroup ``(B, 2, j/128, 128)`` + half-swap (sublane
shuffle), stride ``j < 128`` partners are a lane roll (``pltpu.roll``)
masked by the butterfly direction.  The roll has no wraparound hazard:
a lane with bit ``j`` clear rolls down to ``lane + j < 128`` (no
carry), a lane with bit ``j`` set rolls up within the same 128 block.

Variants: keys-only, and key+index (the payload plan's ``gid``
channel).  The KV compare uses the FULL pair order ``(key, gid)`` —
valid gids are distinct, pad pairs are bitwise-identical — a total
order, so the network's output is THE unique sorted sequence and
matches ``lax.sort(num_keys=2)`` under either stability flag
bit-for-bit.  Keys-only sorts the monotone total-order ENCODING
(equal keys are bit-identical), so any comparison sort agrees.

Padding: blocks pad to a power of two with the dtype's maximum (the
encoding's ``big`` / the caller's pad key), which sorts to the tail
and slices off — the multiset is preserved, so bit-identity to the
XLA route survives the pad/slice round trip.

Arm registration: ``ops/kernels.py`` (``sort_local``,
``DR_TPU_SORT_LOCAL``); the XLA fallback is ``lax.sort``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from jax.experimental import pallas as pl

from .stencil_pallas import _HAS_PLTPU, pltpu

__all__ = ["supported", "eligible", "sort_keys", "sort_kv"]

LANES = 128
#: eligibility cap on the PADDED block: the network is O(M log^2 M)
#: compare-exchanges, statically unrolled — past this the XLA sort's
#: better asymptotics (and Mosaic's program size) win.  The queued
#: silicon ladder (tune_tpu.py kernels) is the empirical arbiter.
_MAX_ELEMS = 1 << 15


def supported() -> bool:
    return _HAS_PLTPU


def _padded(n: int) -> int:
    m = 2 * LANES
    while m < n:
        m *= 2
    return m


def eligible(n: int, key_dtype, *, interpret: bool = False) -> bool:
    """Static per-call eligibility: size within the VMEM/unroll cap and
    a key dtype the compare network handles on the target — 4-byte keys
    (the uint32 encoding, int32/uint32/f32-backed) on real TPUs; the
    interpret route additionally takes the x64 encodings (uint64/int64),
    which is how the CPU parity battery covers the wide-key path."""
    if n < 1 or _padded(n) > _MAX_ELEMS:
        return False
    dt = np.dtype(jnp.dtype(key_dtype).name)
    if dt.kind not in "iu":
        return False
    return dt.itemsize == 4 or (interpret and dt.itemsize == 8)


def _pad_max(dtype):
    return np.array(np.iinfo(np.dtype(jnp.dtype(dtype).name)).max,
                    np.dtype(jnp.dtype(dtype).name))


@functools.lru_cache(maxsize=32)
def _build(M: int, kv: bool, kdtype_name: str, interpret: bool):
    """One compiled bitonic network over an (M/128, 128) VMEM tile."""
    R = M // LANES
    dtype = jnp.dtype(kdtype_name)

    def _lane_roll(y, j):
        # jnp.roll lowers poorly on Mosaic; pltpu.roll(y, s, 1) shifts
        # lane c -> value from lane c - s (mod 128), so down-by-j is
        # shift 128 - j
        if interpret:
            return jnp.roll(y, -j, axis=1), jnp.roll(y, j, axis=1)
        return (pltpu.roll(y, LANES - j, 1), pltpu.roll(y, j, 1))

    def kernel(*refs):
        if kv:
            x_ref, g_ref, ox_ref, og_ref = refs
            g = g_ref[...]
        else:
            x_ref, ox_ref = refs
            g = None
        x = x_ref[...]
        row = lax.broadcasted_iota(jnp.int32, (R, LANES), 0)
        lane = lax.broadcasted_iota(jnp.int32, (R, LANES), 1)
        idx = row * LANES + lane
        k = 2
        while k <= M:
            j = k // 2
            while j >= 1:
                up = (idx & k) == 0
                keep_min = ((idx & j) == 0) == up
                if j >= LANES:
                    jr = j // LANES
                    B = R // (2 * jr)

                    def _swap(y, jr=jr, B=B):
                        # partner rows differ in idx bit j: regroup the
                        # leading axis and swap the two halves
                        y4 = y.reshape(B, 2, jr, LANES)
                        return jnp.concatenate(
                            [y4[:, 1:2], y4[:, 0:1]],
                            axis=1).reshape(R, LANES)

                    p = _swap(x)
                    pg = _swap(g) if kv else None
                else:
                    down = (lane & j) == 0
                    xd, xu = _lane_roll(x, j)
                    p = jnp.where(down, xd, xu)
                    if kv:
                        gd, gu = _lane_roll(g, j)
                        pg = jnp.where(down, gd, gu)
                if kv:
                    # full (key, gid) pair order: a TOTAL order (valid
                    # gids distinct, pad pairs identical), so the
                    # network output is the unique sorted sequence
                    a_le = (x < p) | ((x == p) & (g <= pg))
                    take_a = keep_min == a_le
                    x = jnp.where(take_a, x, p)
                    g = jnp.where(take_a, g, pg)
                else:
                    lo = jnp.minimum(x, p)
                    hi = jnp.maximum(x, p)
                    x = jnp.where(keep_min, lo, hi)
                j //= 2
            k *= 2
        ox_ref[...] = x
        if kv:
            og_ref[...] = g

    n_io = 2 if kv else 1
    dtypes = (dtype, jnp.int32) if kv else (dtype,)
    params = {}
    if not interpret:
        params["compiler_params"] = pltpu.CompilerParams(
            vmem_limit_bytes=100 * 2 ** 20)
    return pl.pallas_call(
        kernel,
        grid=(1,),
        in_specs=[pl.BlockSpec((R, LANES), lambda i: (0, 0))
                  for _ in range(n_io)],
        out_specs=[pl.BlockSpec((R, LANES), lambda i: (0, 0))
                   for _ in range(n_io)],
        out_shape=[jax.ShapeDtypeStruct((R, LANES), dt)
                   for dt in dtypes],
        interpret=interpret,
        **params,
    )


def sort_keys(keys, *, interpret: bool = False):
    """Ascending on-chip sort of a 1-D integer key block (the monotone
    encoding).  Caller checks :func:`eligible` first."""
    n = keys.shape[0]
    M = _padded(n)
    if M > n:
        keys = jnp.concatenate(
            [keys, jnp.full((M - n,), _pad_max(keys.dtype), keys.dtype)])
    out, = _build(M, False, str(keys.dtype), interpret)(
        keys.reshape(M // LANES, LANES))
    return out.reshape(M)[:n]


def sort_kv(keys, gid, *, interpret: bool = False):
    """Ascending on-chip sort of (key, gid) pairs by the full pair
    order; ``gid`` is the payload plan's int32 index channel.  Pads
    with (dtype max, INT32_MAX) — the sort family's (pad key, GMAX)
    convention — so the tail slices off exactly."""
    n = keys.shape[0]
    M = _padded(n)
    if M > n:
        keys = jnp.concatenate(
            [keys, jnp.full((M - n,), _pad_max(keys.dtype), keys.dtype)])
        gid = jnp.concatenate(
            [gid, jnp.full((M - n,), np.int32(np.iinfo(np.int32).max),
                           jnp.int32)])
    ox, og = _build(M, True, str(keys.dtype), interpret)(
        keys.reshape(M // LANES, LANES), gid.reshape(M // LANES, LANES))
    return ox.reshape(M)[:n], og.reshape(M)[:n]
