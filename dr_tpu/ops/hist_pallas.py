"""Pallas TPU kernel arm: histogram bucketed scatter-add.

The per-shard half of ``histogram`` (docs/SPEC.md §17) is a bincount —
``segment_sum`` of int32 0/1 counts over clipped bucket ids — i.e. ONE
integer-sum column of the masked-compare segmented reduce.  This
module is the thin arm wrapper over ``segred_pallas`` so the histogram
seam registers and tunes independently (``DR_TPU_HIST_IMPL`` — bucket
counts have their own size/shape regime) while sharing one kernel
body.  Integer sums are exact under any combine order, so the arm is
bit-identical to the scatter route for every input.

Arm registration: ``ops/kernels.py`` (``hist``, ``DR_TPU_HIST_IMPL``);
the XLA fallback is ``jax.ops.segment_sum``.
"""

from __future__ import annotations

from . import segred_pallas

__all__ = ["supported", "eligible", "bincount"]


def supported() -> bool:
    return segred_pallas.supported()


def eligible(n: int, bins: int) -> bool:
    import jax.numpy as jnp
    return segred_pallas.eligible(n, bins, ((jnp.int32, "sum"),))


def bincount(bucket, counts, bins: int, *, interpret: bool = False):
    """Sum int32 ``counts`` into ``bins`` buckets keyed by int32
    ``bucket`` ids; out-of-range ids contribute nothing."""
    return segred_pallas.segmented(
        bucket, bins, ((counts, "sum"),), interpret=interpret)[0]
