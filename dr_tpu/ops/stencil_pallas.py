"""Pallas TPU kernel: temporally-blocked 1-D stencil.

The XLA path (algorithms/stencil.py) is HBM-bound: every step reads and
writes the whole vector (2 x 4 bytes per element per step) and the
overlapping shifted-slice reads are not deduplicated.  This kernel fuses
``T`` time steps per HBM pass: each chunk is DMA'd HBM->VMEM once
(double-buffered, overlapping DMA with compute), stepped T times in VMEM,
and written back once — HBM traffic drops to ~(2 x 4 bytes) per element
per T steps.

TPU-native layout: the padded shard row (1, width) is viewed as
(width/128, 128) so every vreg is a full (8, 128) f32 tile (a (1, W) row
wastes 7/8 of each vreg's sublanes).  The flat 1-D shift x[i+s] becomes a
lane roll plus a sublane roll patching the wrapped lanes:

    B[r, l] = x[r, l+s]            l <  128-s   (lane roll)
    B[r, l] = x[r+1, l+s-128]      l >= 128-s   (row roll of the above)

Cross-shard: the container's halo width must be >= T*r; one ppermute
exchange per T-step block keeps ghosts fresh (algorithms/stencil.py
handles the exchange; this kernel is the per-shard compute).

Geometry (Mosaic tiling: f32 tiles are (8, 128), DMA slices must be
tile-aligned): halo % 1024 == 0 and seg % 1024 == 0 so windows start and
end on whole (8, 128) tiles.  Reference workload this accelerates:
``examples/mhp/stencil-1d.cpp:47-66``.
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax

from jax.experimental import pallas as pl

try:  # TPU-specific namespace; absent on pure-CPU installs
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

__all__ = ["blocked_stencil_row", "supported", "LANES", "ROW_ALIGN"]

LANES = 128
SUBLANES = 8
ROW_ALIGN = LANES * SUBLANES  # 1024: whole (8, 128) f32 tiles


def supported() -> bool:
    return _HAS_PLTPU


def tpu_roll(u, k: int, axis: int, interpret: bool):
    """jnp.roll(u, k, axis) that lowers through pltpu.roll on TPU
    (which requires a non-negative shift).  Shared by the 1-D and 2-D
    blocked kernels."""
    if interpret:
        return jnp.roll(u, k, axis=axis)
    return pltpu.roll(u, k % u.shape[axis], axis=axis)


def _flat_shift(x, s: int, interpret: bool):
    """B[f] = x_flat[f + s] over the row-major flattening of (R, 128).

    The wrapped tail/head rows hold garbage — callers keep a trapezoid
    margin (the halo rows) around the trusted core.
    """
    if s == 0:
        return x

    def roll(u, k, axis):
        return tpu_roll(u, k, axis, interpret)
    lane = lax.broadcasted_iota(jnp.int32, x.shape, 1)
    if s > 0:
        a = roll(x, -s, axis=1)
        b = roll(a, -1, axis=0)
        return jnp.where(lane < LANES - s, a, b)
    a = roll(x, -s, axis=1)
    b = roll(a, 1, axis=0)
    return jnp.where(lane >= -s, a, b)


@functools.lru_cache(maxsize=64)
def _build(width: int, seg: int, halo: int, weights: tuple, tsteps: int,
           chunk: int, dtype_name: str, interpret: bool):
    """pallas_call stepping one (width/128, 128) padded row ``tsteps``
    times; ghost cells must hold >= tsteps*r valid neighbor values."""
    r = (len(weights) - 1) // 2
    w = tuple(float(x) for x in weights)
    dtype = jnp.dtype(dtype_name)
    assert halo % ROW_ALIGN == 0 and seg % ROW_ALIGN == 0, (
        f"blocked stencil needs seg ({seg}) and halo ({halo}) aligned "
        f"to {ROW_ALIGN} (whole (8,128) f32 tiles)")
    assert halo >= tsteps * r, "halo narrower than the fused time block"
    rows_total = width // LANES
    seg_rows = seg // LANES
    hr = halo // LANES
    # chunk rows: largest tile-aligned divisor of seg_rows <= chunk/128
    crows = min(max(chunk // LANES, SUBLANES), seg_rows)
    crows -= crows % SUBLANES
    while seg_rows % crows:
        crows -= SUBLANES
    nchunks = seg_rows // crows
    wrows = crows + 2 * hr

    def weighted(u):
        acc = _flat_shift(u, -r, interpret) * w[0]
        for d in range(1, 2 * r + 1):
            acc = acc + _flat_shift(u, d - r, interpret) * w[d]
        return acc.astype(dtype)

    def kernel(in_hbm, out_hbm, vin, vout, in_sem, out_sem, gsem):
        i = pl.program_id(0)
        slot = lax.rem(i, 2)

        def in_dma(c, s):
            return pltpu.make_async_copy(
                in_hbm.at[pl.ds(c * crows, wrows), :],
                vin.at[s], in_sem.at[s])

        def out_dma(c, s):
            return pltpu.make_async_copy(
                vout.at[s],
                out_hbm.at[pl.ds(hr + c * crows, crows), :],
                out_sem.at[s])

        @pl.when(i == 0)
        def _():
            in_dma(0, 0).start()

        @pl.when(i + 1 < nchunks)
        def _():
            in_dma(i + 1, 1 - slot).start()

        in_dma(i, slot).wait()

        # the out-DMA that used this vout slot two chunks ago must be done
        @pl.when(i >= 2)
        def _():
            out_dma(i - 2, slot).wait()

        x = vin[slot]
        x = lax.fori_loop(0, tsteps, lambda t, u: weighted(u), x)
        vout[slot] = x[hr:hr + crows, :]
        out_dma(i, slot).start()

        # ghost rows pass through unchanged (stale until next exchange)
        @pl.when(i == 0)
        def _():
            g = pltpu.make_async_copy(
                vin.at[0, pl.ds(0, hr), :],
                out_hbm.at[pl.ds(0, hr), :], gsem)
            g.start()
            g.wait()

        @pl.when(i == nchunks - 1)
        def _():
            g = pltpu.make_async_copy(
                vin.at[slot, pl.ds(wrows - hr, hr), :],
                out_hbm.at[pl.ds(rows_total - hr, hr), :], gsem)
            g.start()
            g.wait()
            out_dma(i, slot).wait()

        if nchunks > 1:
            @pl.when(i == nchunks - 1)
            def _():
                out_dma(i - 1, 1 - slot).wait()

    return pl.pallas_call(
        kernel,
        grid=(nchunks,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        out_shape=jax.ShapeDtypeStruct((rows_total, LANES), dtype),
        scratch_shapes=[
            pltpu.VMEM((2, wrows, LANES), dtype),
            pltpu.VMEM((2, crows, LANES), dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA,
        ],
        interpret=interpret,
    )


def blocked_stencil_row(row, seg: int, halo: int,
                        weights: Sequence[float], tsteps: int,
                        chunk: int = 2 ** 17, interpret: bool = False):
    """Apply ``tsteps`` fused stencil steps to one padded (1, W) row.

    ``row``: (1, halo + seg + halo) array; ghosts must be pre-exchanged
    with width >= tsteps * r.  Returns the new row: owned cells hold the
    stepped values, ghost cells are passed through stale (re-exchange
    before the next block).  Geometry: seg and halo must be multiples of
    ``ROW_ALIGN`` (1024) — whole (8, 128) f32 tiles.
    """
    if not _HAS_PLTPU:
        raise RuntimeError("pallas TPU namespace unavailable")
    width = row.shape[-1]
    assert width == 2 * halo + seg
    fn = _build(width, seg, halo, tuple(float(x) for x in weights),
                tsteps, chunk, str(row.dtype), interpret)
    out = fn(row.reshape(width // LANES, LANES))
    return out.reshape(row.shape)
