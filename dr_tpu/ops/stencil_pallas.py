"""Pallas TPU kernel: temporally-blocked 1-D stencil.

The XLA path (algorithms/stencil.py) is HBM-bound: every step reads and
writes the whole vector (2 x 4 bytes per element per step).  This kernel
fuses ``T`` time steps per HBM pass: each grid chunk DMAs a window of
``C + 2*T*r`` elements HBM->VMEM, applies the weighted stencil T times in
VMEM (trapezoid scheme: the valid region shrinks by r per step, so the
window overlap pays for the fusion), and writes back C elements — HBM
traffic drops to ~(2 x 4 bytes) per element per T steps, an ~T-fold cut
in the bandwidth bill.

Cross-shard: the container's halo width must be >= T*r; one ppermute
exchange per T-step block keeps ghosts fresh (algorithms/stencil.py
handles the exchange; this kernel is the per-shard compute).

Kernel shape notes (see /opt/skills/guides/pallas_guide.md): rows are
(1, W) so the vector unit works along lanes; inputs stay in HBM/ANY and
chunks are DMA'd manually (overlapping windows can't be expressed with
disjoint BlockSpecs); weights are baked as Python floats (VPU immediate
operands).
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl

try:  # TPU-specific namespace; absent on pure-CPU installs
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

__all__ = ["blocked_stencil_row", "supported"]


def supported() -> bool:
    return _HAS_PLTPU


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


@functools.lru_cache(maxsize=64)
def _build(width: int, seg: int, halo: int, weights: tuple, tsteps: int,
           chunk: int, dtype_name: str, interpret: bool):
    """pallas_call computing ``tsteps`` stencil steps over one (1, width)
    padded row; ghost cells must hold >= tsteps*r valid neighbor values."""
    r = (len(weights) - 1) // 2
    w = tuple(float(x) for x in weights)
    dtype = jnp.dtype(dtype_name)
    win = chunk + 2 * halo  # DMA window per chunk
    nchunks = seg // chunk
    assert seg % chunk == 0

    def kernel(in_hbm, out_hbm, vin, vout, sem_in, sem_out):
        i = pl.program_id(0)
        start = i * chunk  # row coordinate of the window start
        cp_in = pltpu.make_async_copy(
            in_hbm.at[:, pl.ds(start, win)], vin, sem_in)
        cp_in.start()
        cp_in.wait()
        x = vin[:, :]
        # trapezoid: after step t, cells [r*(t+1), win - r*(t+1)) are valid
        for t in range(tsteps):
            core = x[:, 2 * r:] * w[2 * r]
            for d in range(2 * r):
                core = core + x[:, d:win - 2 * r + d] * w[d]
            x = jnp.concatenate(
                [x[:, :r], core, x[:, win - r:]], axis=1)
        vout[:, :] = x[:, halo:halo + chunk]
        cp_out = pltpu.make_async_copy(
            vout, out_hbm.at[:, pl.ds(start + halo, chunk)], sem_out)
        cp_out.start()
        cp_out.wait()

    grid = (nchunks,)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        out_shape=jax.ShapeDtypeStruct((1, width), dtype),
        scratch_shapes=[
            pltpu.VMEM((1, win), dtype),
            pltpu.VMEM((1, chunk), dtype),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
        input_output_aliases={},
        interpret=interpret,
    )


def blocked_stencil_row(row, seg: int, halo: int,
                        weights: Sequence[float], tsteps: int,
                        chunk: int = 8192, interpret: bool = False):
    """Apply ``tsteps`` fused stencil steps to one padded (1, W) row.

    ``row``: (1, halo + seg + halo) array; ghosts must be pre-exchanged
    with width >= tsteps * r.  Returns the new row: owned cells hold the
    stepped values, ghost cells are passed through stale (re-exchange
    before the next block).  ``seg`` must be a multiple of ``chunk``
    (callers pad; see algorithms/stencil.py fused path).
    """
    if not _HAS_PLTPU:
        raise RuntimeError("pallas TPU namespace unavailable")
    r = (len(weights) - 1) // 2
    assert halo >= tsteps * r, "halo narrower than the fused time block"
    width = row.shape[-1]
    assert width == 2 * halo + seg
    if seg % chunk:
        chunk = int(np.gcd(seg, chunk)) or seg
    fn = _build(width, seg, halo, tuple(float(x) for x in weights),
                tsteps, chunk, str(row.dtype), interpret)
    out = fn(row.reshape(1, width))
    # ghost regions: carry the input's values through
    out = out.at[:, :halo].set(row.reshape(1, width)[:, :halo])
    out = out.at[:, width - halo:].set(
        row.reshape(1, width)[:, width - halo:])
    return out.reshape(row.shape)
