"""Pallas TPU kernel: vectorized masked segmented reduce.

``jax.ops.segment_*`` lowers to a scatter — serialized combines through
HBM.  On-chip the same reduction is a masked COMPARE: the input block
(values + int32 segment ids) sits in VMEM once, the grid walks 128-wide
output-segment tiles, and each step builds the ``(128, n)`` membership
mask ``segid == tile_base + lane`` and reduces every requested monoid
column along the element axis — pure VPU work, no scatter, no HBM
round trip per segment.  Segment ids need NOT be sorted (histogram's
bucket ids reuse this kernel as-is); ids outside ``[0, nseg)``
(including the pad fill ``-1``) match no tile and contribute nothing.

Bit-identity to the XLA route: per segment both routes combine the SAME
multiset of elements with the same monoid — exact whenever the monoid
is combine-order-free at the bit level.  min/max are (any dtype —
identities and NaN/±0 select behavior verified equal to the
``segment_min``/``segment_max`` scatter); integer/bool sum and prod are
(modular); FLOAT sum/prod are NOT (association changes rounding), so
callers must not route float additive columns here — the dispatch
seams encode that in their eligibility, and :func:`eligible` enforces
it.  Empty segments produce the same identities the scatter route
fills with (+inf/max for min, -inf/lowest for max, 0 for sum, 1 for
prod).

Arm registration: ``ops/kernels.py`` (``segred``,
``DR_TPU_SEGRED_IMPL``); the XLA fallback is ``jax.ops.segment_*``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from jax.experimental import pallas as pl

from .stencil_pallas import _HAS_PLTPU, pltpu

__all__ = ["supported", "eligible", "segmented", "OPS"]

LANES = 128
#: input/segment-count cap: the (128, n) membership mask is the VMEM
#: footprint (n * 512 B at the cap) and the mask rebuild is O(nseg/128)
#: passes over the block — past this the scatter's O(n) wins.
_MAX_N = 1 << 15

OPS = ("sum", "prod", "min", "max")

#: monoids whose combine is bit-order-free only over exact dtypes:
#: float columns are ineligible for these (association changes
#: rounding); min/max are order-free for every dtype.
_EXACT_ONLY = ("sum", "prod")


def supported() -> bool:
    return _HAS_PLTPU


def eligible(n: int, nseg: int, cols) -> bool:
    """``cols`` is a sequence of ``(dtype, op)`` monoid columns."""
    if n < 1 or n > _MAX_N or nseg < 1 or nseg > _MAX_N:
        return False
    for dt, op in cols:
        if op not in OPS:
            return False
        kind = np.dtype(jnp.dtype(dt).name).kind
        if op in _EXACT_ONLY and kind not in "iub":
            return False
    return True


def _identity(op: str, dtype):
    dt = jnp.dtype(dtype)
    if op == "sum":
        return jnp.zeros((), dt)
    if op == "prod":
        return jnp.ones((), dt)
    if jnp.issubdtype(dt, jnp.inexact):
        v = jnp.inf if op == "min" else -jnp.inf
        return jnp.asarray(v, dt)
    info = np.iinfo(np.dtype(dt.name))
    return jnp.asarray(info.max if op == "min" else info.min, dt)


@functools.lru_cache(maxsize=32)
def _build(n_pad: int, ntiles: int, specs, interpret: bool):
    """``specs``: tuple of (dtype name, op) output columns.  Inputs are
    (1, n_pad) rows re-streamed whole per tile; outputs are
    (ntiles, 128) with one row per grid step."""

    def kernel(sid_ref, *refs):
        ncols = len(specs)
        t = pl.program_id(0)
        sid = sid_ref[...]                              # (1, n_pad)
        seg = t * LANES + lax.broadcasted_iota(
            jnp.int32, (LANES, 1), 0)
        m = sid == seg                                  # (128, n_pad)
        for i, (dtn, op) in enumerate(specs):
            v = refs[i][...]                            # (1, n_pad)
            ident = _identity(op, jnp.dtype(dtn))
            masked = jnp.where(m, v, ident)
            if op == "sum":
                r = jnp.sum(masked, axis=1)
            elif op == "prod":
                r = jnp.prod(masked, axis=1)
            elif op == "min":
                r = jnp.min(masked, axis=1)
            else:
                r = jnp.max(masked, axis=1)
            refs[ncols + i][...] = r.reshape(1, LANES)

    full = pl.BlockSpec((1, n_pad), lambda t: (0, 0))
    params = {}
    if not interpret:
        params["compiler_params"] = pltpu.CompilerParams(
            vmem_limit_bytes=100 * 2 ** 20,
            dimension_semantics=("arbitrary",))
    return pl.pallas_call(
        kernel,
        grid=(ntiles,),
        in_specs=[full] * (1 + len(specs)),
        out_specs=[pl.BlockSpec((1, LANES), lambda t: (t, 0))
                   for _ in specs],
        out_shape=[jax.ShapeDtypeStruct((ntiles, LANES), jnp.dtype(dtn))
                   for dtn, _ in specs],
        interpret=interpret,
        **params,
    )


def segmented(segid, nseg: int, cols, *, interpret: bool = False):
    """Segmented reduce of every ``(values, op)`` column in ``cols``
    over int32 ``segid`` into ``nseg`` segments; returns a tuple of
    ``(nseg,)`` arrays.  Ids outside ``[0, nseg)`` contribute nothing.
    Caller checks :func:`eligible` first."""
    n = segid.shape[0]
    n_pad = -(-n // LANES) * LANES
    if n_pad > n:
        # pad ids with -1: matches no output tile
        segid = jnp.concatenate(
            [segid, jnp.full((n_pad - n,), np.int32(-1), jnp.int32)])
    ntiles = -(-nseg // LANES)
    specs = tuple((str(v.dtype), op) for v, op in cols)
    vals = []
    for v, op in cols:
        if n_pad > n:
            v = jnp.concatenate(
                [v, jnp.full((n_pad - n,), _identity(op, v.dtype),
                             v.dtype)])
        vals.append(v.reshape(1, n_pad))
    outs = _build(n_pad, ntiles, specs, interpret)(
        segid.reshape(1, n_pad), *vals)
    if not isinstance(outs, (list, tuple)):
        outs = (outs,)
    return tuple(o.reshape(ntiles * LANES)[:nseg] for o in outs)
