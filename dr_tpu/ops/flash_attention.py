"""Pallas TPU kernel: fused flash-attention block update for ring attention.

The XLA blockwise path (ops/ring_attention.py) materializes the
(q_chunk, block) logits in HBM between the two einsums and re-reads the
running (m, l, acc) state per chunk — at S=8k that caps the MXU at a few
percent utilization.  This kernel fuses one full flash-attention update
(logits -> online softmax -> weighted V accumulation) over the K/V block
a ring step holds, entirely in VMEM:

- grid (B*h, S_local / bq): one q tile per cell, K/V of the whole held
  block resident in VMEM across the cell's inner loop;
- matmuls run on the MXU in bf16 with f32 accumulation
  (``preferred_element_type``), exp/normalization stays f32 on the VPU;
- the causal variant bounds the inner k loop by the q tile's GLOBAL
  position (ring offsets arrive via scalar prefetch), so future blocks
  cost nothing — the ~2x causal saving the XLA path only gets from
  masking FLOPs it already paid for;
- the running (m, l, acc) state is a kernel carry: ring step t feeds
  step t+1, and the final normalization (acc / l) happens once in XLA.

Reference lineage: the ring substrate of
``include/dr/details/halo.hpp:273-387`` (periodic neighbor exchange)
carried to its long-context conclusion (SURVEY.md §5); the blockwise
online softmax follows the flash/ring-attention literature (PAPERS.md).
"""

from __future__ import annotations

import functools
from ..utils.env import env_str

import jax
import jax.numpy as jnp
from jax import lax

from jax.experimental import pallas as pl

from .stencil_pallas import _HAS_PLTPU, pltpu

__all__ = ["flash_update", "pick_blocks", "resident_fits",
           "supported", "use_streaming"]

_NEG_INF = float("-inf")


def supported() -> bool:
    return _HAS_PLTPU


def pick_blocks(s: int, skv: int, d: int):
    """(bq, bk) for local seq length ``s`` against a ``skv``-long K/V
    block: the largest power-of-two tiles (bq <= 2048, bk <= 1024 —
    measured optimum on v5e; caps overridable via DR_TPU_FLASH_BQ /
    DR_TPU_FLASH_BK for on-device tuning) dividing the sequence
    lengths.  Returns None when no MXU-friendly tiling exists or the
    resident K/V block would overflow VMEM (callers fall back to the
    XLA path)."""
    def pick(n, cap, floor):
        b = cap
        while b >= floor:
            if n % b == 0:
                return b
            b //= 2
        return None
    if d % 128 or skv % 128:
        return None
    # beyond the resident-K/V VMEM budget the STREAMING variant takes
    # over (k-block grid dimension, Mosaic pipelines the tile DMAs), so
    # a large skv only gates when streaming is explicitly disabled
    if not use_streaming(skv, d) and not resident_fits(skv, d):
        return None
    from ..utils.env import env_pow2
    # round down to a power of two: pick() only guarantees the
    # sublane/lane tile alignment promised below for 2^k tiles
    cap_q = env_pow2("DR_TPU_FLASH_BQ", 2048)
    cap_k = env_pow2("DR_TPU_FLASH_BK", 1024)
    bq = pick(s, cap_q, 16)  # sublane-aligned q tile (bf16 tile: (16, 128))
    bk = pick(skv, cap_k, 128)  # lane-aligned k tile (logits last dim)
    if bq is None or bk is None:
        return None
    return bq, bk


def causal_computed_flops(s: int, skv: int, d: int, bq: int, bk: int,
                          q_off: int = 0, k_off: int = 0) -> int:
    """EXACT matmul flops both kernels execute for one causal update of
    a ``s``-long q shard against a ``skv``-long K/V block, per (B*h)
    slice — block-granular: a (bq, bk) cell runs fully when any of its
    rows can attend (diagonal cells overshoot the ideal triangle).
    Both kernels share the skip rule ``k_lo <= q_lo + bq - 1`` (resident
    ``hi`` bound / streaming ``pl.when``), so one counter serves both.
    Honest utilization for the tuning sweeps: ideal-triangle "effective"
    figures divide by ~half this, which is how a >100%-of-peak number
    can appear even with exact timing (docs/PERF.md round-4 note)."""
    nk = skv // bk
    cells = 0
    for iq in range(s // bq):
        q_hi = q_off + iq * bq + bq - 1     # last q row of the tile
        if q_hi < k_off:
            continue
        cells += min(nk, (q_hi - k_off) // bk + 1)
    return cells * 2 * 2 * bq * bk * d      # two MXU matmuls per cell


def _block_update(qv, kblk, vblk, m, l, acc, scale, causal, q_lo, k_lo):
    """One online-softmax update of (m, l, acc) against a K/V tile —
    the shared core of the resident and streaming kernels (a numerical
    fix here reaches both)."""
    logits = lax.dot_general(
        qv, kblk, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale
    if causal:
        qp = q_lo + lax.broadcasted_iota(jnp.int32, logits.shape, 0)
        kp = k_lo + lax.broadcasted_iota(jnp.int32, logits.shape, 1)
        logits = jnp.where(qp >= kp, logits, _NEG_INF)
    blk_max = jnp.max(logits, axis=-1, keepdims=True)
    new_m = jnp.maximum(m, blk_max)
    # new_m = -inf only when every k so far is masked; exp(x - safe_m)
    # then sees x = -inf and yields 0 rows on its own
    safe_m = jnp.where(new_m > _NEG_INF, new_m, 0.0)
    p = jnp.exp(logits - safe_m)                # masked -> exp(-inf)=0
    corr = jnp.exp(m - safe_m)                  # m=-inf -> 0
    pv = lax.dot_general(
        p.astype(jnp.bfloat16), vblk, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return (new_m, l * corr + jnp.sum(p, axis=-1, keepdims=True),
            acc * corr + pv)


def resident_fits(skv: int, d: int) -> bool:
    """Whole held K/V block (double-buffered bf16) within the VMEM
    budget — the resident kernel's eligibility bound (~64k tokens at
    d=128)."""
    return 2 * 2 * skv * d * 2 <= 64 * 2 ** 20


def use_streaming(skv: int, d: int) -> bool:
    """Kernel-variant selector (trace-time): streaming beyond the
    resident VMEM budget; DR_TPU_FLASH_STREAM=1/0 forces/forbids.
    Callers caching programs must key on this."""
    env = env_str("DR_TPU_FLASH_STREAM")
    if env == "1":
        return True
    if env == "0":
        return False
    return not resident_fits(skv, d)


@functools.lru_cache(maxsize=32)
def _build_streaming(BH: int, s: int, skv: int, d: int, bq: int, bk: int,
                     causal: bool, interpret: bool, group: int = 1):
    """Long-context variant: the K-block index is a GRID dimension, so
    Mosaic's pipeliner streams (bk, d) K/V tiles from HBM instead of
    holding the whole block in VMEM — sequence length is then bounded
    by HBM, not VMEM (the resident kernel's ~64k ceiling at d=128).

    The (m, l, acc) online-softmax state lives in the OUTPUT blocks,
    which map to the same (b, iq) slot for every ik — Mosaic keeps a
    revisited block VMEM-resident across the innermost steps, so the
    state never round-trips HBM within one q tile.  Causal q tiles
    skip the compute (not the tile fetch) of strictly-future K blocks.
    """
    nk = skv // bk
    scale = 1.0 / (d ** 0.5)

    def kernel(info, q_ref, k_ref, v_ref, mi_ref, li_ref, acci_ref,
               mo_ref, lo_ref, acco_ref):
        iq = pl.program_id(1)
        ik = pl.program_id(2)
        q_off = info[0]
        k_off = info[1]
        q_lo = q_off + iq * bq

        @pl.when(ik == 0)
        def _():
            # seed the revisited output state from the ring carries
            mo_ref[0] = mi_ref[0]
            lo_ref[0] = li_ref[0]
            acco_ref[0] = acci_ref[0]

        def update():
            new_m, new_l, new_acc = _block_update(
                q_ref[0], k_ref[0], v_ref[0], mo_ref[0], lo_ref[0],
                acco_ref[0], scale, causal, q_lo, k_off + ik * bk)
            mo_ref[0] = new_m
            lo_ref[0] = new_l
            acco_ref[0] = new_acc

        if causal:
            # skip the compute of strictly-future K blocks (every q row
            # in this tile precedes the block)
            pl.when(k_off + ik * bk <= q_lo + bq - 1)(update)
        else:
            update()

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(BH, s // bq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, ik, info: (b, i, 0)),
            pl.BlockSpec((1, bk, d),
                         lambda b, i, ik, info: (b // group, ik, 0)),
            pl.BlockSpec((1, bk, d),
                         lambda b, i, ik, info: (b // group, ik, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i, ik, info: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i, ik, info: (b, i, 0)),
            pl.BlockSpec((1, bq, d), lambda b, i, ik, info: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, 1), lambda b, i, ik, info: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i, ik, info: (b, i, 0)),
            pl.BlockSpec((1, bq, d), lambda b, i, ik, info: (b, i, 0)),
        ],
    )
    flops_per_cell = 2 * 2 * bq * bk * d
    params = {}
    if not interpret:
        params["compiler_params"] = pltpu.CompilerParams(
            vmem_limit_bytes=100 * 2 ** 20,
            dimension_semantics=("parallel", "arbitrary", "arbitrary"))
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        **params,
        out_shape=[
            jax.ShapeDtypeStruct((BH, s, 1), jnp.float32),
            jax.ShapeDtypeStruct((BH, s, 1), jnp.float32),
            jax.ShapeDtypeStruct((BH, s, d), jnp.float32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=flops_per_cell * BH * (s // bq) * nk
            // (2 if causal else 1),
            bytes_accessed=(BH * s * d * 2 * 2
                            + BH * (s // bq) * skv * d * 2 * 2
                            + BH * s * d * 4 * 2),
            transcendentals=BH * s * skv),
        interpret=interpret,
    )


@functools.lru_cache(maxsize=32)
def _build(BH: int, s: int, skv: int, d: int, bq: int, bk: int,
           causal: bool, interpret: bool, group: int = 1):
    """pallas_call: one flash update of (m, l, acc) against a K/V block.

    Inputs: info=[q_off, k_off] (scalar prefetch), q (BH, s, d) bf16,
    k/v (BH // group, skv, d) bf16, carries m/l (BH, s, 1) f32 (the
    trailing length-1 lane dim satisfies Mosaic block tiling AND is the
    compute layout of row stats), acc (BH, s, d) f32.  Outputs: updated
    m, l, acc.  ``group`` > 1 is grouped-query attention: q head b
    reads K/V head b // group — just an index map, no replication.
    """
    nk = skv // bk
    scale = 1.0 / (d ** 0.5)

    def kernel(info, q_ref, k_ref, v_ref, m_ref, l_ref, acc_ref,
               mo_ref, lo_ref, acco_ref):
        iq = pl.program_id(1)
        q_off = info[0]
        k_off = info[1]

        qv = q_ref[0]                                   # (bq, d) bf16
        m = m_ref[0]                                    # (bq, 1) f32
        l = l_ref[0]
        acc = acc_ref[0]                                # (bq, d) f32
        q_lo = q_off + iq * bq                          # global q position

        if causal:
            # only k blocks whose first position is <= the tile's last q
            # position can contribute; later blocks are skipped outright
            hi = jnp.clip((q_lo + bq - 1 - k_off) // bk + 1, 0, nk)
        else:
            hi = nk

        def body(ik, carry):
            m, l, acc = carry
            kblk = k_ref[0, pl.ds(ik * bk, bk), :]      # (bk, d) bf16
            vblk = v_ref[0, pl.ds(ik * bk, bk), :]
            return _block_update(qv, kblk, vblk, m, l, acc, scale,
                                 causal, q_lo, k_off + ik * bk)

        m, l, acc = lax.fori_loop(0, hi, body, (m, l, acc))
        mo_ref[0] = m
        lo_ref[0] = l
        acco_ref[0] = acc

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(BH, s // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, info: (b, i, 0)),
            pl.BlockSpec((1, skv, d),
                         lambda b, i, info: (b // group, 0, 0)),
            pl.BlockSpec((1, skv, d),
                         lambda b, i, info: (b // group, 0, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i, info: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i, info: (b, i, 0)),
            pl.BlockSpec((1, bq, d), lambda b, i, info: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, 1), lambda b, i, info: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i, info: (b, i, 0)),
            pl.BlockSpec((1, bq, d), lambda b, i, info: (b, i, 0)),
        ],
    )
    flops_per_cell = 2 * 2 * bq * skv * d  # two matmuls per k block
    if causal:
        flops_per_cell //= 2
    params = {}
    if not interpret:
        # resident K/V blocks + f32 logits exceed the default 16 MiB
        # scoped-vmem limit at useful tile sizes
        params["compiler_params"] = pltpu.CompilerParams(
            vmem_limit_bytes=100 * 2 ** 20)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        **params,
        out_shape=[
            jax.ShapeDtypeStruct((BH, s, 1), jnp.float32),
            jax.ShapeDtypeStruct((BH, s, 1), jnp.float32),
            jax.ShapeDtypeStruct((BH, s, d), jnp.float32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=flops_per_cell * BH * (s // bq),
            bytes_accessed=(BH * s * d * 2 * 2
                            + (BH // group) * skv * d * 2 * 2
                            + BH * s * d * 4 * 2),
            transcendentals=BH * s * skv),
        interpret=interpret,
    )


def flash_update(q, k, v, m, l, acc, q_off, k_off, *, causal: bool,
                 bq: int, bk: int, interpret: bool = False):
    """One ring step's flash update.  q (BH, s, d) and k/v
    (BHkv, skv, d) with BH % BHkv == 0 (grouped-query: q head b reads
    K/V head b // group) are bf16 (callers cast); m/l (BH, s, 1) and
    acc (BH, s, d) are the f32 running state; q_off/k_off are the
    GLOBAL sequence offsets of the q shard and the held K/V block
    (traced scalars under shard_map)."""
    BH, s, d = q.shape
    skv = k.shape[1]
    assert v.shape == k.shape, "k and v must share (heads, skv, d)"
    assert BH % k.shape[0] == 0, "q heads must be a multiple of kv heads"
    group = BH // k.shape[0]
    build = _build_streaming if use_streaming(skv, d) else _build
    fn = build(BH, s, skv, d, bq, bk, causal, interpret, group)
    info = jnp.stack([jnp.asarray(q_off, jnp.int32),
                      jnp.asarray(k_off, jnp.int32)])
    return fn(info, q, k, v, m, l, acc)
