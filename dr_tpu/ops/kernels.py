"""On-chip kernel-arm registry (docs/SPEC.md §22).

The per-shard hot loops of the sort/scan/segmented-reduce backbone each
have TWO lowerings: the portable XLA route (always present, always
correct — the fallback by construction) and a hand-written Pallas
kernel (``sort_pallas`` / ``segred_pallas`` / ``hist_pallas`` /
``scan_pallas``).  This module is the ONE decision point between them:
every dispatch seam calls :func:`use_kernel` and bakes the returned
:class:`Decision` into its program-cache key, so a changed arm pick is
a different cached program, never a silent retrace.

Selection precedence is the §21 rule — explicit env pin
(``auto|pallas|xla``) > persisted tuning-DB winner (``kernels.<arm>``,
written by ``tune_tpu.py kernels``) > code default ``auto``.  ``auto``
resolves by platform: Pallas on TPU when the call is eligible, XLA
everywhere else.  A ``pallas`` pin is FORCED — on a CPU mesh it runs
the kernel in Pallas interpret mode, which is how tier-1 and the fuzz
crank execute the real kernel bodies without silicon
(``test_fuzz_kernel_parity``).

Every decision fires the ``kernel.build`` fault site first; an armed
classified fault degrades the call to the XLA route (warn_fallback,
never a crash) — kernels are an OPTIMIZATION tier, the portable
lowering is the contract.

The ``ARMS`` table is a pure literal on purpose: ``tools/drlint.py``
R8 AST-parses it (the R7 plan-pass-registry pattern) and checks each
arm's env override, fallback declaration, fault-site guard, SPEC §22.1
row, and fuzz-parity coverage without importing jax.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

from ..utils.env import env_str
from ..utils.fallback import warn_fallback

__all__ = ["ARMS", "ARM_NAMES", "Decision", "NO_KERNEL", "use_kernel",
           "mesh_platform"]

# (arm, env override, kernel module, xla fallback, fault site) — one row
# per registered kernel arm; R8 closes this table against the kernel
# modules, the env registry, faults.SITES, and SPEC §22.1.
ARMS = (
    ("sort_local", "DR_TPU_SORT_LOCAL", "sort_pallas",
     "lax.sort", "kernel.build"),
    ("segred", "DR_TPU_SEGRED_IMPL", "segred_pallas",
     "jax.ops.segment_*", "kernel.build"),
    ("hist", "DR_TPU_HIST_IMPL", "hist_pallas",
     "jax.ops.segment_sum", "kernel.build"),
    ("scan", "DR_TPU_SCAN_IMPL", "scan_pallas",
     "matmul-cumsum", "kernel.build"),
)

ARM_NAMES = tuple(a[0] for a in ARMS)

_MODES = ("auto", "pallas", "xla")

# Literal env reads per arm: drlint R2's env inventory only sees
# constant first arguments, so each registered override is spelled out.
_ENV_READERS = {
    "sort_local": lambda: env_str("DR_TPU_SORT_LOCAL"),
    "segred": lambda: env_str("DR_TPU_SEGRED_IMPL"),
    "hist": lambda: env_str("DR_TPU_HIST_IMPL"),
    "scan": lambda: env_str("DR_TPU_SCAN_IMPL"),
}


class Decision(NamedTuple):
    """One resolved arm pick.  NOTE: a NamedTuple is always truthy —
    branch on ``.use``, and key program caches on ``tuple(decision)``."""
    use: bool
    interpret: bool


NO_KERNEL = Decision(False, False)


def mesh_platform(mesh) -> str:
    """The mesh's device platform ("cpu"/"tpu") — program builders hold
    a mesh, not the runtime."""
    return mesh.devices.flat[0].platform


def _supported(arm: str) -> bool:
    from . import hist_pallas, scan_pallas, segred_pallas, sort_pallas
    mod = {"sort_local": sort_pallas, "segred": segred_pallas,
           "hist": hist_pallas, "scan": scan_pallas}[arm]
    return mod.supported()


def _mode(arm: str) -> str:
    """env pin > tuning-DB winner > ``auto`` (tolerant: junk values in
    either source mean ``auto``, the §21 picker discipline)."""
    raw = _ENV_READERS[arm]().strip().lower()
    if raw in _MODES:
        return raw
    if raw:
        return "auto"
    from .. import tuning as _tuning
    v = _tuning.lookup("kernels", arm)
    if isinstance(v, str) and v.strip().lower() in _MODES:
        return v.strip().lower()
    return "auto"


def use_kernel(arm: str, platform: Optional[str] = None, *,
               runtime=None, eligible: bool = True) -> Decision:
    """Resolve one kernel-arm decision.

    ``platform`` is the mesh's device platform string; pass ``runtime``
    instead where one is handy.  ``eligible`` carries the arm-specific
    static eligibility (size caps, dtype support, layout shape) the
    caller computed — an ineligible call is XLA under every mode, a
    ``pallas`` pin included (the pin forces the kernel where it CAN
    run, it does not extend where it can).

    Fires the ``kernel.build`` fault site on EVERY decision (the chaos
    battery reaches it through any sort/groupby/histogram call); an
    armed classified fault degrades to the XLA route."""
    assert arm in _ENV_READERS, f"unregistered kernel arm {arm!r}"
    from ..utils import faults, resilience
    try:
        faults.fire("kernel.build", arm=arm)
    except resilience.ResilienceError as e:
        warn_fallback("kernels", f"{arm} kernel build faulted "
                                 f"({type(e).__name__}); xla route")
        return NO_KERNEL
    mode = _mode(arm)
    if mode == "xla" or not eligible or not _supported(arm):
        return NO_KERNEL
    if platform is None:
        platform = runtime.devices[0].platform
    on_tpu = platform == "tpu"
    if mode == "pallas":
        return Decision(True, not on_tpu)
    return Decision(on_tpu, False)
