"""Pallas TPU kernel: chunked dot product (fused multiply + reduce).

The dot workload (``examples/shp/dot_product.cpp:11-18`` — the driver
metric's transform_reduce config) reads two arrays once and reduces;
its HBM floor is 8 B/element.  The XLA fused reduce measured ~57% of
peak on the v5e (BENCH_r01), leaving real headroom — this kernel
streams both operands through VMEM with the same manual double-buffered
DMA template the scan kernel runs on hardware
(``scan_pallas._build``), folding each chunk's product-sum into an SMEM
f32 accumulator.  Per grid step the DMA engine moves 2 chunks in and
nothing out, so the kernel is purely read-bound.

``salt`` is a traced scalar added to ``y`` inside the kernel: the
``dot_n`` measurement loop perturbs successive rounds through it so
XLA cannot hoist or skip re-reading the operands — without paying the
separate elementwise pass a host-side ``y + salt`` would cost.

Default on TPU since the round-3 on-device A/B showed it beating the
XLA fused reduce by ~1.4x (tools/tune_dot.log); ``DR_TPU_DOT_IMPL=xla``
opts out.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from jax.experimental import pallas as pl

from .scan_pallas import LANES, pick_chunk
from .stencil_pallas import _HAS_PLTPU, pltpu
from ..utils.env import env_str

__all__ = ["chunked_dot", "supported", "use_dot_kernel"]


def supported() -> bool:
    return _HAS_PLTPU


def use_dot_kernel() -> bool:
    """Default ON since the round-3 on-device A/B (tools/tune_dot.log:
    759-822 GB/s vs the XLA fused reduce's 546-586 on the 2^27 bench
    shape — ~93% of the chip's 819 GB/s read bandwidth).
    ``DR_TPU_DOT_IMPL=xla`` opts out; read per call so tuning sweeps
    work in-process (callers key their program caches on it)."""
    val = env_str("DR_TPU_DOT_IMPL").lower()
    if val in ("", "pallas"):
        return True
    if val in ("xla", "off", "0", "none", "false"):
        return False
    from ..utils.fallback import warn_fallback
    warn_fallback("dot", f"DR_TPU_DOT_IMPL={val!r} not recognized "
                  "(expected 'pallas' or 'xla'); failing CLOSED to the "
                  "XLA path — anyone setting the variable is most "
                  "likely opting out of the kernel")
    return False


@functools.lru_cache(maxsize=16)
def _build(rows: int, R: int, dtype_name: str, interpret: bool):
    dtype = jnp.dtype(dtype_name)
    nch = rows // R

    def kernel(salt_ref, x_hbm, y_hbm, out_ref, vx, vy, acc, xs, ys):
        i = pl.program_id(0)
        slot = lax.rem(i, 2)

        def in_dma(hbm, v, sem, c, s):
            return pltpu.make_async_copy(
                hbm.at[pl.ds(c * R, R), :], v.at[s], sem.at[s])

        @pl.when(i == 0)
        def _():
            acc[0, 0] = jnp.zeros((), jnp.float32)
            in_dma(x_hbm, vx, xs, 0, 0).start()
            in_dma(y_hbm, vy, ys, 0, 0).start()

        @pl.when(i + 1 < nch)
        def _():
            in_dma(x_hbm, vx, xs, i + 1, 1 - slot).start()
            in_dma(y_hbm, vy, ys, i + 1, 1 - slot).start()

        in_dma(x_hbm, vx, xs, i, slot).wait()
        in_dma(y_hbm, vy, ys, i, slot).wait()
        x = vx[slot].astype(jnp.float32)
        y = vy[slot].astype(jnp.float32) + salt_ref[0, 0]
        acc[0, 0] = acc[0, 0] + jnp.sum(x * y)

        @pl.when(i == nch - 1)
        def _():
            out_ref[0, 0] = acc[0, 0]

    params = {}
    if not interpret:
        params["compiler_params"] = pltpu.CompilerParams(
            vmem_limit_bytes=100 * 2 ** 20)
    return pl.pallas_call(
        kernel,
        grid=(nch,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pl.ANY),
                  pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.SMEM),
        out_shape=jax.ShapeDtypeStruct((1, 1), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((2, R, LANES), dtype),
            pltpu.VMEM((2, R, LANES), dtype),
            pltpu.SMEM((1, 1), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=interpret,
        **params,
    )


def chunked_dot(x, y, *, salt=None, interpret: bool = False):
    """``sum(x * (y + salt))`` of two equal-length 1-D arrays in one
    read-only HBM pass; returns an f32 scalar.  Requires
    ``pick_chunk(len(x))`` (lane-blocked chunking) — callers fall back
    to the XLA fused reduce otherwise."""
    n = x.shape[0]
    assert y.shape == x.shape and x.dtype == y.dtype
    R = pick_chunk(n)
    assert R is not None, "no lane-aligned chunking for this length"
    rows = n // LANES
    fn = _build(rows, R, str(x.dtype), interpret)
    s = jnp.zeros((1, 1), jnp.float32) if salt is None else \
        jnp.asarray(salt, jnp.float32).reshape(1, 1)
    return fn(s, x.reshape(rows, LANES), y.reshape(rows, LANES))[0, 0]
