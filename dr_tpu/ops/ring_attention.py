"""Ring attention: sequence-parallel attention over the mesh ring.

The reference's halo subsystem is the structural substrate of
context/sequence parallelism (SURVEY.md §5 "Long-context"): 1-D
partitioned data with ring-shaped neighbor exchange.  This op makes the
long-context capability first-class: Q/K/V are sharded over the sequence
axis of the mesh, each shard computes blockwise attention against the K/V
block it currently holds, and K/V blocks rotate around the ring with
``lax.ppermute`` (ICI neighbor traffic) — compute on block i overlaps the
transfer of block i+1, the classic ring-attention schedule (Liu et al.;
the same shift pattern as parallel/halo.py).

Numerically-stable online softmax (flash-style running max/denominator)
keeps memory at O(block) regardless of total sequence length; the causal
variant masks by GLOBAL positions so results match single-device
attention exactly.

Round 9: the two hand-unrolled ring loops moved onto the SHARED
software-pipelined schedule (``parallel/pipeline.ring_pipeline``): the
``serial`` schedule reproduces the historical compute-then-rotate
order exactly, the default ``pipelined`` schedule issues each
rotation before the step's compute (double-buffered carry,
``optimization_barrier``-paired) — the same dataflow in the same
reduction order, so results are unchanged either way.  The resolved
schedule keys the program cache (``DR_TPU_RING_SCHEDULE`` A/B sweeps
rebuild), and dispatch routes through the ``collectives.ppermute``
fault site.
"""

from __future__ import annotations

import math
from ..utils.env import env_str

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.pinning import pinned_id
from ..parallel import pipeline as _pl
from ..parallel import runtime as _rt
from . import flash_attention as _fa
from ..utils.spmd_guard import TappedCache

__all__ = ["ring_attention", "ring_attention_n", "ring_self_attention"]

_cache: dict = TappedCache()


def _flash_viable(shape, dtype, rt) -> bool:
    """Flash kernel path: TPU backend, MXU-friendly tiling, bf16 q/k/v.

    float32 inputs keep the Precision.HIGH XLA path by default — the
    fused kernel computes in bf16 (f32 accumulation), and silently
    trading the input precision away would break the module's
    exact-match contract.  ``DR_TPU_RING_IMPL=flash`` opts f32 inputs
    into the kernel; ``DR_TPU_RING_IMPL=xla`` forces the XLA path."""
    impl = env_str("DR_TPU_RING_IMPL").lower()
    if impl == "xla":
        return False
    if not _fa.supported():
        return False
    if jnp.dtype(dtype) == jnp.dtype(jnp.float32):
        if impl != "flash":
            return False
    elif jnp.dtype(dtype) != jnp.dtype(jnp.bfloat16):
        return False
    B, s, h, d = shape
    if _fa.pick_blocks(s, s, d) is None:
        return False
    # gate on the RUNTIME's devices, not the process default backend
    # (a CPU-mesh runtime on a TPU-default host must take the XLA path)
    return rt.devices[0].platform == "tpu"


def _build_flash(mesh, axis, nshards, shape, causal, dtype,
                 interpret=False, hkv=None, schedule=None):
    """Ring schedule with the fused Pallas block kernel as the per-step
    compute: K/V blocks rotate via ppermute on the SHARED ring pipeline
    (parallel/pipeline.py — pipelined by default, overlapping each
    step's kernel with the next transfer), the (m, l, acc) online-
    softmax state is the carry, normalization happens once at the end.
    ``interpret`` runs the kernel interpreted (CPU-mesh validation of
    the multi-shard ring carries).  ``hkv`` < h is grouped-query
    attention: the kernel indexes the shared K/V heads directly, so the
    ring moves (and VMEM holds) only ``hkv`` heads."""
    B, s, h, d = shape
    hkv = h if hkv is None else hkv
    BH = B * h
    bq, bk = _fa.pick_blocks(s, s, d)

    def body(q, k, v):
        my = lax.axis_index(axis)
        # head-major (BH, s, d) once; bf16 feeds the MXU, f32 state
        qh = jnp.einsum("bshd->bhsd", q).reshape(BH, s, d)
        kh = jnp.einsum("bshd->bhsd", k).reshape(B * hkv, s, d)
        vh = jnp.einsum("bshd->bhsd", v).reshape(B * hkv, s, d)
        qh, kh, vh = (x.astype(jnp.bfloat16) for x in (qh, kh, vh))
        m = jnp.full((BH, s, 1), -jnp.inf, jnp.float32)
        l = jnp.zeros((BH, s, 1), jnp.float32)
        acc = jnp.zeros((BH, s, d), jnp.float32)
        q_off = my * s

        def step(t, carry, blocks):
            m, l, acc = carry
            kh, vh = blocks
            src = (my - t) % nshards
            return _fa.flash_update(
                qh, kh, vh, m, l, acc, q_off, src * s,
                causal=causal, bq=bq, bk=bk, interpret=interpret)

        m, l, acc = _pl.ring_pipeline(
            axis, nshards, (m, l, acc), (kh, vh), step,
            schedule=schedule)
        safe_l = jnp.where(l > 0, l, 1.0)
        out = (acc / safe_l).astype(dtype)
        return jnp.einsum("bhsd->bshd",
                          out.reshape(B, h, s, d))

    # check_vma=False: pallas_call outputs carry no varying-mesh-axis
    # metadata, so shard_map's vma check cannot type them
    shm = jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(None, axis), P(None, axis), P(None, axis)),
        out_specs=P(None, axis), check_vma=False)
    return jax.jit(shm)


def _repeat_heads_hmajor(x, group):
    """GQA on the XLA path: expand head-major (B, hkv, s, d) K/V blocks
    to the q head count (repeat along axis 1)."""
    return jnp.repeat(x, group, axis=1) if group > 1 else x


def _pick_q_chunk(B, s, h, budget_bytes=512 * 2 ** 20):
    """Largest q-chunk whose (B, h, qc, s) f32 logits fit the budget.
    The floor stays at 128 so high batch*heads configs keep an
    enforceable memory bound."""
    qc = s
    # halve only while the RESULT stays >= 128, so the floor holds even
    # when s is not a power of two (e.g. s=384 -> 192, not 96)
    while qc % 2 == 0 and qc >= 256 and B * h * qc * s * 4 > budget_bytes:
        qc //= 2
    return qc


def _build(mesh, axis, nshards, shape, causal, dtype, q_chunk=None,
           hkv=None, schedule=None):
    B, s, h, d = shape  # local block: (batch, seq_shard, heads, head_dim)
    group = 1 if hkv is None else h // hkv
    scale = 1.0 / math.sqrt(d)
    qc = min(q_chunk or _pick_q_chunk(B, s, h), s)
    while s % qc:
        qc -= 1  # honor the bound: largest divisor of s <= requested
    nqc = s // qc

    def body(q, k, v):
        my = lax.axis_index(axis)
        m = jnp.full((nqc, B, h, qc), -jnp.inf, jnp.float32)
        l = jnp.zeros((nqc, B, h, qc), jnp.float32)
        acc = jnp.zeros((nqc, B, h, qc, d), jnp.float32)
        # q chunked along seq, head-major: (nqc, B, h, qc, d)
        q_ch = jnp.einsum("bnqhd->nbhqd", q.reshape(B, nqc, qc, h, d))
        q_pos = (my * s + jnp.arange(s)).reshape(nqc, qc)

        def one_chunk(args, kT, vT, k_pos):
            """Online-softmax update of one q chunk against the held
            K/V block (flash-style running max/denominator).  kT/vT are
            head-major (B, h, s, d): transposed ONCE per ring step —
            letting the einsum re-transpose per chunk costs more HBM
            traffic than the attention itself."""
            q_c, qp, m_c, l_c, acc_c = args
            logits = jnp.einsum("bhqd,bhkd->bhqk", q_c, kT,
                                precision=lax.Precision.HIGH,
                                preferred_element_type=jnp.float32) * scale
            if causal:
                mask = qp[:, None] >= k_pos[None, :]
                logits = jnp.where(mask[None, None], logits, -jnp.inf)
            blk_max = jnp.max(logits, axis=-1)
            new_m = jnp.maximum(m_c, blk_max)
            # guard fully-masked rows (new_m == -inf)
            safe_m = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
            p = jnp.exp(logits - safe_m[..., None])
            p = jnp.where(jnp.isfinite(logits), p, 0.0)
            correction = jnp.where(jnp.isfinite(m_c),
                                   jnp.exp(m_c - safe_m), 0.0)
            l_c = l_c * correction + jnp.sum(p, axis=-1)
            acc_c = acc_c * correction[..., None] + jnp.einsum(
                "bhqk,bhkd->bhqd", p, vT,
                precision=lax.Precision.HIGH,
                preferred_element_type=jnp.float32)
            return new_m, l_c, acc_c

        def step(t, carry, blocks):
            m, l, acc = carry
            src = (my - t) % nshards  # whose block we hold this round
            k_pos = src * s + jnp.arange(s)
            # GQA: the ring moves only the hkv shared heads (ppermute is
            # layout-agnostic: the head-major blocks travel directly);
            # expand to the q head count just-in-time for the einsums
            kT = _repeat_heads_hmajor(blocks[0], group)
            vT = _repeat_heads_hmajor(blocks[1], group)
            if nqc == 1:
                m, l, acc = one_chunk(
                    (q_ch[0], q_pos[0], m[0], l[0], acc[0]),
                    kT, vT, k_pos)
                return m[None], l[None], acc[None]
            # chunked q bounds the (B, h, qc, s) logits regardless of
            # the local sequence length (long-context single chip)
            return lax.map(lambda a: one_chunk(a, kT, vT, k_pos),
                           (q_ch, q_pos, m, l, acc))

        # head-major ONCE; the ring carries the transposed blocks
        m, l, acc = _pl.ring_pipeline(
            axis, nshards, (m, l, acc),
            (jnp.einsum("bkhd->bhkd", k), jnp.einsum("bkhd->bhkd", v)),
            step, schedule=schedule)
        safe_l = jnp.where(l > 0, l, 1.0)
        out = (acc / safe_l[..., None]).astype(dtype)   # (nqc, B, h, qc, d)
        out = jnp.moveaxis(out, 0, 2).reshape(B, h, s, d)
        return jnp.einsum("bhqd->bqhd", out)

    shm = jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(None, axis), P(None, axis), P(None, axis)),
        out_specs=P(None, axis))
    return jax.jit(shm)


def ring_attention(q, k, v, *, causal: bool = False, runtime=None,
                   q_chunk: int = None):
    """Sequence-parallel attention.

    q/k/v: (batch, seq, heads, head_dim) jax arrays; ``seq`` is sharded
    over the mesh axis (the function shards unsharded inputs).  Returns
    the attention output with the same sharding.  ``q_chunk`` bounds the
    per-round logits to (batch, heads, q_chunk, block) — default picks
    the largest chunk under a fixed memory budget.
    """
    rt = runtime or _rt.runtime()
    B, S, h, d = q.shape
    hkv = k.shape[2]
    assert h % hkv == 0 and v.shape[2] == hkv, \
        "q heads must be a multiple of the (shared) kv heads"
    nshards = rt.nprocs
    assert S % nshards == 0, "seq length must divide the mesh"
    sharding = NamedSharding(rt.mesh, P(None, rt.axis))
    q, k, v = (jax.device_put(x, sharding) for x in (q, k, v))
    shape = (B, S // nshards, h, d)
    flash = q_chunk is None and _flash_viable(shape, q.dtype, rt)
    # the picked flash tiles key the program: the DR_TPU_FLASH_BQ/BK
    # caps may change between calls (tools/tune_tpu.py sweeps them)
    blocks = _fa.pick_blocks(shape[1], shape[1], d) if flash else None
    stream = _fa.use_streaming(shape[1], d) if flash else None
    sched = _pl.schedule_mode()
    _pl.fire_ppermute(op="ring_attention")
    key = ("ringattn", pinned_id(rt.mesh), shape, hkv, causal,
           str(q.dtype), q_chunk, flash, blocks, stream, sched)
    prog = _cache.get(key)
    if prog is None:
        if flash:
            prog = _build_flash(rt.mesh, rt.axis, nshards, shape, causal,
                                q.dtype, hkv=hkv, schedule=sched)
        else:
            prog = _build(rt.mesh, rt.axis, nshards, shape, causal,
                          q.dtype, q_chunk, hkv=hkv, schedule=sched)
        _cache[key] = prog
    return prog(q, k, v)


def ring_attention_n(q, k, v, iters: int, *, causal: bool = False,
                     runtime=None):
    """``iters`` chained ring-attention steps in ONE jitted program
    (v := attn(q, k, v) each round) — the measurement analog of
    ``span_halo.exchange_n`` (parallel/halo.py): per-step device time
    excludes the tunneled per-dispatch overhead entirely.  Returns the
    final output."""
    rt = runtime or _rt.runtime()
    B, S, h, d = q.shape
    assert k.shape[2] == h and v.shape[2] == h, \
        "ring_attention_n chains v through the output: heads must match"
    nshards = rt.nprocs
    assert S % nshards == 0, "seq length must divide the mesh"
    shape = (B, S // nshards, h, d)
    flash = _flash_viable(shape, q.dtype, rt)
    sharding = NamedSharding(rt.mesh, P(None, rt.axis))
    q, k, v = (jax.device_put(x, sharding) for x in (q, k, v))
    blocks = _fa.pick_blocks(shape[1], shape[1], d) if flash else None
    stream = _fa.use_streaming(shape[1], d) if flash else None
    sched = _pl.schedule_mode()
    _pl.fire_ppermute(op="ring_attention_n")
    key = ("ringattn_n", pinned_id(rt.mesh), shape, causal,
           str(q.dtype), flash, blocks, stream, int(iters), sched)
    prog = _cache.get(key)
    if prog is None:
        build = _build_flash if flash else _build
        one = build(rt.mesh, rt.axis, nshards, shape, causal, q.dtype,
                    schedule=sched)

        def many(q, k, v):
            return lax.fori_loop(
                0, iters, lambda _, vv: one(q, k, vv), v)

        prog = jax.jit(many)
        _cache[key] = prog
    return prog(q, k, v)


def ring_self_attention(x, wq, wk, wv, *, causal: bool = False,
                        runtime=None):
    """Convenience: project + ring-attend. x: (B, S, h*d) sharded on S."""
    B, S, hd = x.shape
    h, d = wq.shape[1], wq.shape[2]
    proj = lambda w: jnp.einsum("bse,ehd->bshd", x, w)
    return ring_attention(proj(wq), proj(wk), proj(wv), causal=causal,
                          runtime=runtime)
