"""Ring attention: sequence-parallel attention over the mesh ring.

The reference's halo subsystem is the structural substrate of
context/sequence parallelism (SURVEY.md §5 "Long-context"): 1-D
partitioned data with ring-shaped neighbor exchange.  This op makes the
long-context capability first-class: Q/K/V are sharded over the sequence
axis of the mesh, each shard computes blockwise attention against the K/V
block it currently holds, and K/V blocks rotate around the ring with
``lax.ppermute`` (ICI neighbor traffic) — compute on block i overlaps the
transfer of block i+1, the classic ring-attention schedule (Liu et al.;
the same shift pattern as parallel/halo.py).

Numerically-stable online softmax (flash-style running max/denominator)
keeps memory at O(block) regardless of total sequence length; the causal
variant masks by GLOBAL positions so results match single-device
attention exactly.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.pinning import pinned_id
from ..parallel import runtime as _rt

__all__ = ["ring_attention", "ring_self_attention"]

_cache: dict = {}


def _pick_q_chunk(B, s, h, budget_bytes=512 * 2 ** 20):
    """Largest q-chunk whose (B, h, qc, s) f32 logits fit the budget.
    The floor stays at 128 so high batch*heads configs keep an
    enforceable memory bound."""
    qc = s
    # halve only while the RESULT stays >= 128, so the floor holds even
    # when s is not a power of two (e.g. s=384 -> 192, not 96)
    while qc % 2 == 0 and qc >= 256 and B * h * qc * s * 4 > budget_bytes:
        qc //= 2
    return qc


def _build(mesh, axis, nshards, shape, causal, dtype, q_chunk=None):
    B, s, h, d = shape  # local block: (batch, seq_shard, heads, head_dim)
    scale = 1.0 / math.sqrt(d)
    ring = [(i, (i + 1) % nshards) for i in range(nshards)]
    qc = min(q_chunk or _pick_q_chunk(B, s, h), s)
    while s % qc:
        qc -= 1  # honor the bound: largest divisor of s <= requested
    nqc = s // qc

    def body(q, k, v):
        my = lax.axis_index(axis)
        m = jnp.full((nqc, B, h, qc), -jnp.inf, jnp.float32)
        l = jnp.zeros((nqc, B, h, qc), jnp.float32)
        acc = jnp.zeros((nqc, B, h, qc, d), jnp.float32)
        # q chunked along seq, head-major: (nqc, B, h, qc, d)
        q_ch = jnp.einsum("bnqhd->nbhqd", q.reshape(B, nqc, qc, h, d))
        q_pos = (my * s + jnp.arange(s)).reshape(nqc, qc)

        def one_chunk(args, kT, vT, k_pos):
            """Online-softmax update of one q chunk against the held
            K/V block (flash-style running max/denominator).  kT/vT are
            head-major (B, h, s, d): transposed ONCE per ring step —
            letting the einsum re-transpose per chunk costs more HBM
            traffic than the attention itself."""
            q_c, qp, m_c, l_c, acc_c = args
            logits = jnp.einsum("bhqd,bhkd->bhqk", q_c, kT,
                                precision=lax.Precision.HIGH,
                                preferred_element_type=jnp.float32) * scale
            if causal:
                mask = qp[:, None] >= k_pos[None, :]
                logits = jnp.where(mask[None, None], logits, -jnp.inf)
            blk_max = jnp.max(logits, axis=-1)
            new_m = jnp.maximum(m_c, blk_max)
            # guard fully-masked rows (new_m == -inf)
            safe_m = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
            p = jnp.exp(logits - safe_m[..., None])
            p = jnp.where(jnp.isfinite(logits), p, 0.0)
            correction = jnp.where(jnp.isfinite(m_c),
                                   jnp.exp(m_c - safe_m), 0.0)
            l_c = l_c * correction + jnp.sum(p, axis=-1)
            acc_c = acc_c * correction[..., None] + jnp.einsum(
                "bhqk,bhkd->bhqd", p, vT,
                precision=lax.Precision.HIGH,
                preferred_element_type=jnp.float32)
            return new_m, l_c, acc_c

        def step(t, carry):
            m, l, acc, kT, vT = carry
            src = (my - t) % nshards  # whose block we hold this round
            k_pos = src * s + jnp.arange(s)
            if nqc == 1:
                m, l, acc = one_chunk(
                    (q_ch[0], q_pos[0], m[0], l[0], acc[0]),
                    kT, vT, k_pos)
                m, l, acc = m[None], l[None], acc[None]
            else:
                # chunked q bounds the (B, h, qc, s) logits regardless of
                # the local sequence length (long-context single chip)
                m, l, acc = lax.map(
                    lambda a: one_chunk(a, kT, vT, k_pos),
                    (q_ch, q_pos, m, l, acc))
            # rotate K/V around the ring for the next round (ppermute is
            # layout-agnostic: the head-major blocks travel directly)
            kT = lax.ppermute(kT, axis, ring)
            vT = lax.ppermute(vT, axis, ring)
            return m, l, acc, kT, vT

        # head-major ONCE; the ring carries the transposed blocks
        carry = (m, l, acc, jnp.einsum("bkhd->bhkd", k),
                 jnp.einsum("bkhd->bhkd", v))
        for t in range(nshards):  # static unroll: overlaps compute + ICI
            carry = step(t, carry)
        m, l, acc, _, _ = carry
        safe_l = jnp.where(l > 0, l, 1.0)
        out = (acc / safe_l[..., None]).astype(dtype)   # (nqc, B, h, qc, d)
        out = jnp.moveaxis(out, 0, 2).reshape(B, h, s, d)
        return jnp.einsum("bhqd->bqhd", out)

    shm = jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(None, axis), P(None, axis), P(None, axis)),
        out_specs=P(None, axis))
    return jax.jit(shm)


def ring_attention(q, k, v, *, causal: bool = False, runtime=None,
                   q_chunk: int = None):
    """Sequence-parallel attention.

    q/k/v: (batch, seq, heads, head_dim) jax arrays; ``seq`` is sharded
    over the mesh axis (the function shards unsharded inputs).  Returns
    the attention output with the same sharding.  ``q_chunk`` bounds the
    per-round logits to (batch, heads, q_chunk, block) — default picks
    the largest chunk under a fixed memory budget.
    """
    rt = runtime or _rt.runtime()
    B, S, h, d = q.shape
    nshards = rt.nprocs
    assert S % nshards == 0, "seq length must divide the mesh"
    sharding = NamedSharding(rt.mesh, P(None, rt.axis))
    q, k, v = (jax.device_put(x, sharding) for x in (q, k, v))
    key = ("ringattn", pinned_id(rt.mesh), (B, S // nshards, h, d), causal,
           str(q.dtype), q_chunk)
    prog = _cache.get(key)
    if prog is None:
        prog = _build(rt.mesh, rt.axis, nshards,
                      (B, S // nshards, h, d), causal, q.dtype, q_chunk)
        _cache[key] = prog
    return prog(q, k, v)


def ring_self_attention(x, wq, wk, wv, *, causal: bool = False,
                        runtime=None):
    """Convenience: project + ring-attend. x: (B, S, h*d) sharded on S."""
    B, S, hd = x.shape
    h, d = wq.shape[1], wq.shape[2]
    proj = lambda w: jnp.einsum("bse,ehd->bshd", x, w)
    return ring_attention(proj(wq), proj(wk), proj(wv), causal=causal,
                          runtime=runtime)
