"""MXU-path temporally-blocked 1-D stencil: composed-operator matmuls.

The Pallas VMEM kernel (ops/stencil_pallas.py) is VPU compute-bound:
every fused step costs ~20 vector ops per element (lane/sublane rolls +
selects + the weighted sum), so its effective bandwidth plateaus near
8 bytes x VPU-throughput / ops-per-step — around 0.9 TB/s on v5e.

The MXU has ~2 orders of magnitude more FLOPs than the VPU.  To use it,
compose ``k`` stencil steps into ONE linear operator: the k-fold
convolution of the weight taps is again a Toeplitz band (half-width
``k*r``), and on the lane-blocked view ``X[:, j] = x[128j : 128j+128]``
the composed step touches the ``D = ceil(k*r / 128)`` nearest
128-columns each side:

    out_col_j = sum_{d=-D..D}  A_d @ X_col_{j+d}
    A_d[a, b] = c[(b + 128*d) - a],   c = taps(weights) ** (*k)

which is one (ncols, 128) x (128, (2D+1)*128) matmul plus 2D+1 shifted
adds.  Per element-step the MXU cost is (2D+1)*2*128/k FLOPs (24 at
k=32, D=1; 20 at k=128, D=2) versus the VPU path's ~20 vector ops per
element-step — the arithmetic moves to the unit with the FLOPs, and HBM
still sees one read + one write per ``k`` steps, so doubling D halves
the physical passes again.  Numerically the composed taps are computed
in float64 on the host, so one composed application is *more* accurate
than ``k`` sequential float32 steps.

Same contract as ``blocked_stencil_row``: the padded shard row arrives
with ghosts pre-exchanged to width >= k*r; owned cells are stepped ``k``
times, ghost cells pass through stale (re-exchange before the next
block).  Reference workload: ``examples/mhp/stencil-1d.cpp:47-66``.
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.env import env_int, env_str

__all__ = ["composed_taps", "matmul_stencil_row", "max_ksteps"]

LANES = 128


def composed_taps(weights: Sequence[float], k: int) -> np.ndarray:
    """k-fold convolution of the stencil taps (float64, length 2*k*r+1)."""
    c = np.array([1.0], dtype=np.float64)
    w = np.asarray(weights, dtype=np.float64)
    for _ in range(k):
        c = np.convolve(c, w)
    return c


def max_ksteps(radius: int, ncols: int | None = None) -> int:
    """Largest supported composable block: the band half-width ``k*r``
    may span up to ``ncols`` lane columns each side (D <= ncols;
    default 4, DR_TPU_MM_BAND_COLS overrides for on-device tuning).
    The round-3 sweep (tools/tune_stencil.log) measured the 4-column
    band at k=256 BETTER on both axes than the old 2-column default —
    phys 167 vs 153 GB/s, effective 21386 vs 9816 GB/s — the HIGH-
    emulated apply keeps the MXU under the DMA floor through 4
    columns."""
    if ncols is None:
        ncols = env_int("DR_TPU_MM_BAND_COLS", 4)
    return ncols * LANES // radius


def _cols_for(half_width: int) -> int:
    """Lane columns a band of the given half-width reaches each side."""
    return -(-half_width // LANES)


def band_cols(k: int, radius: int) -> int:
    """D: lane columns the composed band reaches each side."""
    return _cols_for(k * radius)


@functools.lru_cache(maxsize=64)
def _operator(weights: tuple, k: int, dtype_name: str):
    """(128, (2D+1)*128) stacked [A_-D | ... | A_0 | ... | A_+D]
    transposed for R @ W, where D = ceil(k*r / 128)."""
    c = composed_taps(weights, k)
    R = (len(c) - 1) // 2  # k * radius
    D = _cols_for(R)
    blocks = []
    for d in range(-D, D + 1):
        A = np.zeros((LANES, LANES), dtype=np.float64)
        a = np.arange(LANES)[:, None]
        b = np.arange(LANES)[None, :]
        s = b + LANES * d - a
        inband = np.abs(s) <= R
        A[inband] = c[(s + R)[inband]]
        blocks.append(A)
    W = np.concatenate(blocks, axis=0)  # ((2D+1)*128, 128)
    # cache a NUMPY array: a jnp conversion here would run inside the
    # caller's trace and leak a tracer through the lru_cache
    return np.ascontiguousarray(W.T).astype(dtype_name)  # (128, (2D+1)*128)


# matmul precision for the composed-operator apply.  HIGH (bf16x3 passes,
# f32 accumulate) measures within noise of DEFAULT and ~12% faster than
# HIGHEST, with composed-apply error ~1e-5 absolute over 128 steps
# (composing taps in float64 on the host already beats k sequential f32
# steps).  Overridable for experimentation.
_PRECISION = {
    "default": jax.lax.Precision.DEFAULT,
    "high": jax.lax.Precision.HIGH,
    "highest": jax.lax.Precision.HIGHEST,
}[env_str("DR_TPU_MM_PRECISION", "high").lower()]

# Mosaic (the Pallas TPU compiler) accepts only DEFAULT and HIGHEST dot
# precisions; HIGH exists only at the XLA level.  For f32 the kernel
# emulates HIGH itself (_dot_high_f32: bf16 hi/lo split, three DEFAULT
# dots with f32 accumulation — the same passes XLA's HIGH runs), which
# costs 3 MXU passes instead of HIGHEST's 6 and keeps the fused apply
# DMA-bound at wide bands.  Explicit DEFAULT/HIGHEST pass through.
_KERNEL_PRECISION = (jax.lax.Precision.HIGHEST
                     if _PRECISION == jax.lax.Precision.HIGH else _PRECISION)


def _bf16_split(x):
    """(hi, lo) bf16 parts of an f32 array: hi + lo reconstructs x to
    ~16 mantissa bits."""
    hi = x.astype(jnp.bfloat16)
    lo = (x - hi.astype(jnp.float32)).astype(jnp.bfloat16)
    return hi, lo


def _dot_default(x, y):
    return jax.lax.dot_general(
        x, y, (((1,), (0,)), ((), ())),
        precision=jax.lax.Precision.DEFAULT,
        preferred_element_type=jnp.float32)


def _dot_high_split(a_hi, a_lo, b_hi, b_lo):
    """The three significant bf16 cross terms accumulated in f32 on the
    MXU (hi*hi + hi*lo + lo*hi; lo*lo is below f32 rounding, exactly as
    XLA's HIGH drops it).  Shared by :func:`_dot_high_f32` and the
    fused kernel, so the accuracy test covers the shipped math."""
    return (_dot_default(a_hi, b_hi) + _dot_default(a_hi, b_lo)
            + _dot_default(a_lo, b_hi))


def _dot_high_f32(a, b):
    """bf16x3 emulation of Precision.HIGH for f32 operands: split each
    into a bf16 hi part and a bf16 residual, then
    :func:`_dot_high_split`."""
    a_hi, a_lo = _bf16_split(a)
    b_hi, b_lo = _bf16_split(b)
    return _dot_high_split(a_hi, a_lo, b_hi, b_lo)


def _emulate_high(dtype) -> bool:
    """The fused kernel emulates HIGH itself for f32 data (3 DEFAULT
    MXU passes vs HIGHEST's 6)."""
    return (_PRECISION == jax.lax.Precision.HIGH
            and jnp.dtype(dtype) == jnp.dtype(jnp.float32))

# rows per matmul chunk: bounds the (chunk, 384) product intermediate so
# billion-element rows don't triple HBM residency
_CHUNK_ROWS = env_int("DR_TPU_MM_CHUNK_ROWS", 2 ** 16)


def _apply(src, W, segc, D=1):
    """P-form composed apply on ``src`` = owned columns + ``D`` ghost
    columns each side: one (segc+2D, 128) x (128, (2D+1)*128) matmul
    plus 2D+1 shifted adds."""
    P = jax.lax.dot_general(
        src, W, (((1,), (0,)), ((), ())),
        precision=_PRECISION,
        preferred_element_type=jnp.promote_types(src.dtype, jnp.float32))
    # block i holds A_{i-D}; its contribution to out row j comes from
    # src row j + (i - D) + D = j + i
    out = P[0:segc, 0:LANES]
    for i in range(1, 2 * D + 1):
        out = out + P[i:segc + i, i * LANES:(i + 1) * LANES]
    return out


def _chunk_cap() -> int:
    """Fused-apply chunk cap (lane columns per DMA chunk): overridable
    per call via DR_TPU_MM_CHUNK_CAP for on-device tuning — the grid's
    per-step overhead amortizes with larger chunks until VMEM pressure
    pushes back.  Rounded down to a power of two (tolerant parse):
    _pick_chunk_rows halves the cap looking for a divisor, so a non-2^k
    cap would silently collapse the chunk size to ~1."""
    from ..utils.env import env_pow2  # pow2 only used here
    return env_pow2("DR_TPU_MM_CHUNK_CAP", 4096)


def _pick_chunk_rows(segc: int, cap: int | None = None):
    """Largest power-of-two chunk <= cap dividing the owned columns
    (always exists: 1 divides everything; large segments get large,
    DMA-efficient chunks)."""
    cr = _chunk_cap() if cap is None else cap
    while cr > 1:
        if segc % cr == 0:
            return cr
        cr //= 2
    return 1


@functools.lru_cache(maxsize=32)
def _pallas_apply(nrows: int, hc: int, segc: int, cr: int,
                  dtype_name: str, D: int = 1, interpret: bool = False):
    """Fused Pallas apply: the XLA P-form writes the (rows, (2D+1)*128)
    product through HBM and re-reads it for the shifted adds; this
    kernel keeps matmul + shifted add VMEM-resident so HBM sees exactly
    one read and one write per element per composed block.

    Operates on the (nrows, 128) lane-blocked view; owned columns
    [hc, hc+segc) are stepped in ``cr``-column chunks (double-buffered
    DMA).  Input and output are SEPARATE buffers — aliasing them would
    race chunk i's output write against chunk i+1's ghost-row prefetch
    at every chunk boundary — and the ghost columns pass through via
    two explicit side DMAs.  (The kernel body never uses the stencil
    weights; they arrive as the two W operands — pre-split bf16 halves
    under HIGH emulation, (W, dummy) otherwise — so geometry alone keys
    the compile cache.)"""
    from jax.experimental import pallas as pl
    from .stencil_pallas import pltpu

    dtype = jnp.dtype(dtype_name)
    emul = _emulate_high(dtype)
    nch = segc // cr
    wrows = cr + 2 * D  # D ghost lane-columns each side

    def kernel(w_ref, w2_ref, row_hbm, out_hbm, vin, vout, vghost,
               in_sem, out_sem, ghost_sem):
        i = pl.program_id(0)
        slot = jax.lax.rem(i, 2)

        def in_dma(c, s):
            return pltpu.make_async_copy(
                row_hbm.at[pl.ds(hc - D + c * cr, wrows), :], vin.at[s],
                in_sem.at[s])

        def out_dma(c, s):
            return pltpu.make_async_copy(
                vout.at[s], out_hbm.at[pl.ds(hc + c * cr, cr), :],
                out_sem.at[s])

        # stale pass-through of the halo columns, bounced through VMEM
        # (two legs per side: HBM->VMEM on the first cell, VMEM->HBM on
        # the last — direct HBM->HBM DMA is not a safe Mosaic bet)
        def ghost_in(g):
            lo = (0, hc + segc)[g]
            return pltpu.make_async_copy(
                row_hbm.at[pl.ds(lo, hc), :], vghost.at[g],
                ghost_sem.at[g])

        def ghost_out(g):
            lo = (0, hc + segc)[g]
            return pltpu.make_async_copy(
                vghost.at[g], out_hbm.at[pl.ds(lo, hc), :],
                ghost_sem.at[g])

        @pl.when(i == 0)
        def _():
            in_dma(0, 0).start()
            ghost_in(0).start()
            ghost_in(1).start()

        @pl.when(i + 1 < nch)
        def _():
            in_dma(i + 1, 1 - slot).start()

        in_dma(i, slot).wait()

        @pl.when(i >= 2)
        def _():
            out_dma(i - 2, slot).wait()

        src = vin[slot]
        if emul:
            # HIGH emulation: W arrives pre-split (hoisted out of the
            # grid loop); only the streaming chunk is split per step
            s_hi, s_lo = _bf16_split(src)
            P = _dot_high_split(s_hi, s_lo, w_ref[:], w2_ref[:])
        else:
            P = jax.lax.dot_general(
                src, w_ref[:], (((1,), (0,)), ((), ())),
                precision=_KERNEL_PRECISION,
                preferred_element_type=jnp.promote_types(
                    dtype, jnp.float32))
        out = P[0:cr, 0:LANES]
        for b in range(1, 2 * D + 1):
            out = out + P[b:cr + b, b * LANES:(b + 1) * LANES]
        vout[slot] = out.astype(dtype)
        out_dma(i, slot).start()

        @pl.when(i == nch - 1)
        def _():
            ghost_in(0).wait()
            ghost_in(1).wait()
            ghost_out(0).start()
            ghost_out(1).start()
            out_dma(i, slot).wait()
            ghost_out(0).wait()
            ghost_out(1).wait()

        if nch > 1:
            @pl.when(i == nch - 1)
            def _():
                out_dma(i - 1, 1 - slot).wait()

    return pl.pallas_call(
        kernel,
        grid=(nch,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        out_shape=jax.ShapeDtypeStruct((nrows, LANES), dtype),
        scratch_shapes=[
            pltpu.VMEM((2, wrows, LANES), dtype),
            pltpu.VMEM((2, cr, LANES), dtype),
            pltpu.VMEM((2, hc, LANES), dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=interpret,
        **({} if interpret else {"compiler_params": pltpu.CompilerParams(
            vmem_limit_bytes=100 * 2 ** 20)}),
    )


def matmul_stencil_row(row, seg: int, halo: int, weights: Sequence[float],
                       ksteps: int, impl: str = "xla"):
    """Apply ``ksteps`` composed stencil steps to one padded (1, W) row.

    ``row``: (1, halo + seg + halo); ghosts pre-exchanged with width
    >= ksteps * r.  seg and halo must be multiples of 128 (whole lane
    columns); the composed band may reach D = ceil(ksteps*r/128) lane
    columns each side.  Returns the new row (owned stepped, ghosts
    stale).  ``impl="pallas"`` (TPU callers) takes the fused VMEM
    apply.
    """
    r = (len(weights) - 1) // 2
    width = row.shape[-1]
    assert width == 2 * halo + seg
    assert seg % LANES == 0 and halo % LANES == 0, \
        "matmul stencil needs seg and halo aligned to 128 lanes"
    assert halo >= ksteps * r, "halo narrower than the composed block"
    D = band_cols(ksteps, r)
    dtype = row.dtype
    W = jnp.asarray(
        _operator(tuple(float(x) for x in weights), ksteps, str(dtype)))
    hc = halo // LANES
    segc = seg // LANES
    assert hc >= D  # follows from halo >= k*r and 128-alignment
    R = row.reshape(width // LANES, LANES)
    if impl.startswith("pallas"):
        cr = _pick_chunk_rows(segc)
        fn = _pallas_apply(width // LANES, hc, segc, cr, str(dtype), D,
                           interpret=impl == "pallas_interpret")
        if _emulate_high(dtype):
            W1, W2 = _bf16_split(W)  # hoisted: constant under the grid
        else:
            W1, W2 = W, jnp.zeros((1, 1), W.dtype)
        return fn(W1, W2, R).reshape(row.shape)
    cr = _CHUNK_ROWS
    if segc <= cr:
        out = _apply(R[hc - D: hc + segc + D], W, segc, D)
        R = R.at[hc:hc + segc].set(out.astype(dtype))
    else:
        # chunked: keeps the (cr, (2D+1)*128) intermediate VMEM/HBM-
        # bounded and lets XLA pipeline fetch/matmul/writeback down the
        # row
        nch, rem = divmod(segc, cr)
        R0 = R  # all chunks read the pre-step row, never partial updates

        def chunk(i):
            src = jax.lax.dynamic_slice(
                R0, (hc - D + i * cr, 0), (cr + 2 * D, LANES))
            return _apply(src, W, cr, D)
        outs = jax.lax.map(chunk, jnp.arange(nch))
        if rem:  # remainder chunk stays bounded too
            start = hc + nch * cr
            tail = _apply(R0[start - D: start + rem + D], W, rem, D)
        R = R.at[hc:hc + nch * cr].set(
            outs.reshape(nch * cr, LANES).astype(dtype))
        if rem:
            R = R.at[start:start + rem].set(tail.astype(dtype))
    return R.reshape(row.shape)
