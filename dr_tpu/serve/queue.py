"""Admission control + deadline-aware FIFO for the serving daemon.

The queue is the daemon's overload contract (docs/SPEC.md §14.2):

* **bounded depth** — once ``depth`` requests are queued, submission
  raises a classified :class:`ServerOverloaded` rejection, never a
  hang or an unbounded backlog;
* **per-tenant in-flight caps** — one chatty client cannot monopolize
  the resident claim: a tenant at its cap is rejected while others
  keep being admitted;
* **deadline shedding** — every request carries an absolute expiry;
  :meth:`AdmissionQueue.take_batch` returns expired (and cancelled)
  requests separately so the dispatcher sheds them BEFORE paying a
  device dispatch for work nobody is waiting on.

Transport-free on purpose: a :class:`Request` is just the op + its
operands + completion slots (an Event the submitter can wait on); the
daemon attaches connections and replies, tests submit directly.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import List, Optional, Tuple

from ..obs import metrics as _metrics
from ..obs import recorder as _rec
from ..utils.resilience import ServerOverloaded

__all__ = ["Request", "AdmissionQueue"]

#: always-live overload/shed counters (dr_tpu/obs metrics registry) —
#: these are request-rate events the serve ``stats`` op and
#: ``bench.py --serve`` report on every run, traced or not
_c_rejected = _metrics.counter("serve.rejected")
_c_shed = _metrics.counter("serve.shed")


class Request:
    """One admitted unit of work.

    ``expiry`` is an absolute ``time.monotonic()`` deadline (None =
    never sheds).  ``cancelled`` is set by the daemon when the
    submitting client disconnects mid-request — the dispatcher skips
    the work and the reply.  ``finish`` posts the result/error and
    wakes any in-process waiter."""

    __slots__ = ("op", "params", "arrays", "tenant", "expiry", "conn",
                 "rid", "cancelled", "result", "error", "_done",
                 "t_submit", "t_exec", "t0_ns", "span")

    def __init__(self, op: str, params: Optional[dict], arrays,
                 tenant: str = "default",
                 deadline_s: Optional[float] = None, rid=None):
        self.op = op
        self.params = dict(params or {})
        self.arrays = list(arrays or [])
        self.tenant = tenant
        self.expiry = (None if deadline_s is None
                       else time.monotonic() + float(deadline_s))
        self.conn = None
        self.rid = rid
        self.cancelled = False
        self.result = None
        self.error = None
        self._done = threading.Event()
        # observability (SPEC §15): queue-wait = dispatch start -
        # t_submit; t_exec is set once by the dispatcher; span is the
        # request's obs span id (0 untraced) and t0_ns the
        # recorder-clock creation time for the retroactive
        # queue-wait span
        self.t_submit = time.monotonic()
        self.t_exec = None
        self.t0_ns = _rec.now()
        self.span = 0

    def expired(self) -> bool:
        return self.expiry is not None and time.monotonic() > self.expiry

    def finish(self, result=None, error=None) -> None:
        self.result = result
        self.error = error
        self._done.set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    def __repr__(self):  # pragma: no cover - debugging aid
        state = ("done" if self._done.is_set()
                 else "cancelled" if self.cancelled else "pending")
        return f"Request({self.op!r}, tenant={self.tenant!r}, {state})"


class AdmissionQueue:
    """Bounded FIFO with per-tenant in-flight accounting.

    A tenant's in-flight count covers queued AND executing requests;
    :meth:`release` (called by the dispatcher as each request finishes)
    returns the slot.  Counters (``depth_hw``, ``shed``, ``rejected``,
    ``admitted``) feed the daemon's stats and the serve degradation
    markers."""

    def __init__(self, depth: int, tenant_cap: int):
        self.depth = int(depth)
        self.tenant_cap = int(tenant_cap)
        self._cv = threading.Condition()
        self._q: deque = deque()
        self._inflight: dict = {}
        self.depth_hw = 0
        self.shed = 0
        self.rejected = 0
        self.admitted = 0

    def __len__(self) -> int:
        with self._cv:
            return len(self._q)

    def submit(self, req: Request) -> None:
        """Admit ``req`` or raise :class:`ServerOverloaded` (classified,
        site ``serve.request``) — overload is a typed rejection the
        client can act on, never a hang."""
        with self._cv:
            if len(self._q) >= self.depth:
                self.rejected += 1
                _c_rejected.add()
                raise ServerOverloaded(
                    f"serve: queue depth cap {self.depth} reached — "
                    "back off and resubmit", site="serve.request")
            if self._inflight.get(req.tenant, 0) >= self.tenant_cap:
                self.rejected += 1
                _c_rejected.add()
                raise ServerOverloaded(
                    f"serve: tenant {req.tenant!r} is at its in-flight "
                    f"cap ({self.tenant_cap})", site="serve.request")
            self._q.append(req)
            self._inflight[req.tenant] = \
                self._inflight.get(req.tenant, 0) + 1
            self.admitted += 1
            self.depth_hw = max(self.depth_hw, len(self._q))
            self._cv.notify()

    def release(self, req: Request) -> None:
        """Return ``req``'s tenant slot (request left execution)."""
        with self._cv:
            left = self._inflight.get(req.tenant, 0) - 1
            if left > 0:
                self._inflight[req.tenant] = left
            else:
                self._inflight.pop(req.tenant, None)

    def take_batch(self, max_n: int, window_s: float,
                   stop: Optional[threading.Event] = None,
                   paused: Optional[threading.Event] = None,
                   ) -> Tuple[List[Request], List[Request]]:
        """Pop the next FIFO batch: blocks for the first request, then
        coalesces up to ``max_n`` arrivals within ``window_s`` (the
        batching window concurrent clients land in).  While ``paused``
        is set nothing is popped (requests keep queueing — the
        Server.hold() test/bench hook; the pause must live HERE, not in
        the dispatch loop, or a dispatcher already blocked waiting
        would pop a batch the moment one arrives, hold or no hold).
        Returns ``(live, dropped)`` — ``dropped`` holds expired and
        cancelled requests, already removed, for the dispatcher to
        shed (their tenant slots are NOT yet released; the dispatcher
        releases as it finishes/sheds each request)."""
        with self._cv:
            while not self._q or (paused is not None and paused.is_set()):
                if stop is not None and stop.is_set():
                    return [], []
                self._cv.wait(0.1)
            deadline = time.monotonic() + max(0.0, window_s)
            while len(self._q) < max_n:
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                self._cv.wait(left)
            batch = [self._q.popleft()
                     for _ in range(min(max_n, len(self._q)))]
        live, dropped = [], []
        for r in batch:
            if r.cancelled or r.expired():
                dropped.append(r)
                if not r.cancelled:
                    self.shed += 1
                    _c_shed.add()
            else:
                live.append(r)
        return live, dropped

    def stats(self) -> dict:
        with self._cv:
            return {"queued": len(self._q), "depth_hw": self.depth_hw,
                    "shed": self.shed, "rejected": self.rejected,
                    "admitted": self.admitted}
