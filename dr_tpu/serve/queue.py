"""Admission control + weighted-fair scheduling for the serving daemon.

The queue is the daemon's overload contract (docs/SPEC.md §14.2), and
— since the data-plane round (§19.4) — its ISOLATION contract too:

* **bounded depth** — once ``depth`` requests are queued, submission
  raises a classified :class:`ServerOverloaded` rejection, never a
  hang or an unbounded backlog;
* **per-tenant in-flight caps** — one chatty client cannot monopolize
  the resident claim: a tenant at its cap is rejected while others
  keep being admitted;
* **deadline shedding** — every request carries an absolute expiry;
  :meth:`AdmissionQueue.take_batch` returns expired (and cancelled)
  requests separately so the dispatcher sheds them BEFORE paying a
  device dispatch for work nobody is waiting on;
* **weighted-fair pop (§19.4)** — requests queue per tenant and
  :meth:`take_batch` drains them by deficit-weighted round-robin
  (``DR_TPU_SERVE_TENANT_WEIGHTS``, e.g. ``"gold:4,free:1"``;
  unlisted tenants weigh 1): each ring turn banks a tenant's weight
  into its deficit and pops one request per whole unit, so a heavy
  tenant's burst dilates its OWN queue-wait while a light tenant's
  requests keep landing near the front of every batch.  Order stays
  FIFO within a tenant; a tenant whose queue drains leaves the ring
  (no banking while idle — standard DRR).

Transport-free on purpose: a :class:`Request` is just the op + its
operands + completion slots (an Event the submitter can wait on); the
daemon attaches connections and replies, tests submit directly.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import List, Optional, Tuple

from ..obs import metrics as _metrics
from ..obs import recorder as _rec
from ..utils.env import env_str
from ..utils.resilience import ServerOverloaded

__all__ = ["Request", "AdmissionQueue", "parse_weights"]

#: always-live overload/shed counters (dr_tpu/obs metrics registry) —
#: these are request-rate events the serve ``stats`` op and
#: ``bench.py --serve`` report on every run, traced or not
_c_rejected = _metrics.counter("serve.rejected")
_c_shed = _metrics.counter("serve.shed")


class Request:
    """One admitted unit of work.

    ``expiry`` is an absolute ``time.monotonic()`` deadline (None =
    never sheds).  ``cancelled`` is set by the daemon when the
    submitting client disconnects mid-request — the dispatcher skips
    the work and the reply.  ``finish`` posts the result/error and
    wakes any in-process waiter."""

    __slots__ = ("op", "params", "arrays", "tenant", "expiry", "conn",
                 "rid", "cancelled", "result", "error", "_done",
                 "t_submit", "t_exec", "t0_ns", "span", "server",
                 "arena_ok")

    def __init__(self, op: str, params: Optional[dict], arrays,
                 tenant: str = "default",
                 deadline_s: Optional[float] = None, rid=None):
        self.op = op
        self.params = dict(params or {})
        self.arrays = list(arrays or [])
        self.tenant = tenant
        self.expiry = (None if deadline_s is None
                       else time.monotonic() + float(deadline_s))
        self.conn = None
        self.rid = rid
        self.cancelled = False
        self.result = None
        self.error = None
        self._done = threading.Event()
        # observability (SPEC §15): queue-wait = dispatch start -
        # t_submit; t_exec is set once by the dispatcher; span is the
        # request's obs span id (0 untraced) and t0_ns the
        # recorder-clock creation time for the retroactive
        # queue-wait span
        self.t_submit = time.monotonic()
        self.t_exec = None
        self.t0_ns = _rec.now()
        self.span = 0
        # daemon-side attachments (None for direct test submits): the
        # owning Server (resident-cache handlers reach their store
        # through it) and whether the client accepts arena replies
        self.server = None
        self.arena_ok = False

    def expired(self) -> bool:
        return self.expiry is not None and time.monotonic() > self.expiry

    def finish(self, result=None, error=None) -> None:
        self.result = result
        self.error = error
        self._done.set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    def __repr__(self):  # pragma: no cover - debugging aid
        state = ("done" if self._done.is_set()
                 else "cancelled" if self.cancelled else "pending")
        return f"Request({self.op!r}, tenant={self.tenant!r}, {state})"


def parse_weights(spec: str) -> dict:
    """Parse ``DR_TPU_SERVE_TENANT_WEIGHTS`` (``"tenant:weight,..."``)
    into ``{tenant: weight}``.  Tolerant like every env parse: a
    malformed entry is skipped, weights floor at a small positive
    value (a zero/negative weight would starve the tenant outright —
    the opposite of what this queue exists to prevent)."""
    out: dict = {}
    for raw in (spec or "").replace(";", ",").split(","):
        entry = raw.strip()
        if not entry or ":" not in entry:
            continue
        tenant, w = entry.rsplit(":", 1)
        try:
            out[tenant.strip()] = max(float(w), 1e-3)
        except ValueError:
            continue
    return out


class AdmissionQueue:
    """Bounded per-tenant queues behind a deficit-weighted round-robin
    pop, with per-tenant in-flight accounting.

    A tenant's in-flight count covers queued AND executing requests;
    :meth:`release` (called by the dispatcher as each request finishes)
    returns the slot.  Counters (``depth_hw``, ``shed``, ``rejected``,
    ``admitted``) feed the daemon's stats and the serve degradation
    markers."""

    def __init__(self, depth: int, tenant_cap: int,
                 weights: Optional[dict] = None):
        self.depth = int(depth)
        self.tenant_cap = int(tenant_cap)
        self.weights = dict(parse_weights(
            env_str("DR_TPU_SERVE_TENANT_WEIGHTS"))
            if weights is None else weights)
        self._cv = threading.Condition()
        self._subq: dict = {}           # tenant -> deque (FIFO within)
        self._ring: deque = deque()     # active tenants, DRR order
        self._deficit: dict = {}        # tenant -> banked pop credit
        self._qn = 0                    # total queued
        self._inflight: dict = {}
        self.depth_hw = 0
        self.shed = 0
        self.rejected = 0
        self.admitted = 0

    def __len__(self) -> int:
        with self._cv:
            return self._qn

    def weight(self, tenant: str) -> float:
        return self.weights.get(tenant, 1.0)

    def submit(self, req: Request) -> None:
        """Admit ``req`` or raise :class:`ServerOverloaded` (classified,
        site ``serve.request``) — overload is a typed rejection the
        client can act on, never a hang."""
        with self._cv:
            if self._qn >= self.depth:
                self.rejected += 1
                _c_rejected.add()
                raise ServerOverloaded(
                    f"serve: queue depth cap {self.depth} reached — "
                    "back off and resubmit", site="serve.request")
            if self._inflight.get(req.tenant, 0) >= self.tenant_cap:
                self.rejected += 1
                _c_rejected.add()
                raise ServerOverloaded(
                    f"serve: tenant {req.tenant!r} is at its in-flight "
                    f"cap ({self.tenant_cap})", site="serve.request")
            q = self._subq.get(req.tenant)
            if q is None:
                q = self._subq[req.tenant] = deque()
            if not q:
                # (re)joining the ring starts with a clean slate: an
                # idle tenant banks no credit (standard DRR)
                self._ring.append(req.tenant)
                self._deficit[req.tenant] = 0.0
            q.append(req)
            self._qn += 1
            self._inflight[req.tenant] = \
                self._inflight.get(req.tenant, 0) + 1
            self.admitted += 1
            self.depth_hw = max(self.depth_hw, self._qn)
            self._cv.notify()

    def idle(self) -> bool:
        """True when nothing is queued AND no admitted request is
        still executing (in-flight covers queued + dispatched until
        :meth:`release`) — the graceful-drain gate (SPEC §20.3)."""
        with self._cv:
            return self._qn == 0 and not self._inflight

    def release(self, req: Request) -> None:
        """Return ``req``'s tenant slot (request left execution)."""
        with self._cv:
            left = self._inflight.get(req.tenant, 0) - 1
            if left > 0:
                self._inflight[req.tenant] = left
            else:
                self._inflight.pop(req.tenant, None)

    def _pop_drr(self, max_n: int) -> List[Request]:
        """Drain up to ``max_n`` requests by deficit-weighted
        round-robin over the active-tenant ring (caller holds the
        lock).  Each ring turn banks the tenant's weight; one request
        pops per whole credit, FIFO within the tenant.  A drained
        tenant leaves the ring and forfeits its residue."""
        batch: List[Request] = []
        while len(batch) < max_n and self._qn > 0:
            if not self._ring:  # pragma: no cover - _qn implies a ring
                break
            tenant = self._ring[0]
            q = self._subq.get(tenant)
            if not q:
                self._ring.popleft()
                self._deficit.pop(tenant, None)
                self._subq.pop(tenant, None)
                continue
            # bank the tenant's weight; sub-unit weights accumulate
            # across turns until a whole credit pops (weights floor at
            # a positive value, so every tenant pops eventually)
            self._deficit[tenant] = \
                self._deficit.get(tenant, 0.0) + self.weight(tenant)
            while q and len(batch) < max_n \
                    and self._deficit[tenant] >= 1.0:
                batch.append(q.popleft())
                self._qn -= 1
                self._deficit[tenant] -= 1.0
            if not q:
                # drained: leave the ring AND drop the empty deque —
                # per-request tenant ids must not grow the table
                # forever (the tenant re-creates both on next submit)
                self._ring.popleft()
                self._deficit.pop(tenant, None)
                self._subq.pop(tenant, None)
            else:
                self._ring.rotate(-1)
        return batch

    def take_batch(self, max_n: int, window_s: float,
                   stop: Optional[threading.Event] = None,
                   paused: Optional[threading.Event] = None,
                   ) -> Tuple[List[Request], List[Request]]:
        """Pop the next batch: blocks for the first request, then
        coalesces up to ``max_n`` arrivals within ``window_s`` (the
        batching window concurrent clients land in) and drains them
        weighted-fair (:meth:`_pop_drr` — FIFO within a tenant, DRR
        across tenants).  While ``paused`` is set nothing is popped
        (requests keep queueing — the Server.hold() test/bench hook;
        the pause must live HERE, not in the dispatch loop, or a
        dispatcher already blocked waiting would pop a batch the
        moment one arrives, hold or no hold).  Returns ``(live,
        dropped)`` — ``dropped`` holds expired and cancelled requests,
        already removed, for the dispatcher to shed (their tenant
        slots are NOT yet released; the dispatcher releases as it
        finishes/sheds each request)."""
        with self._cv:
            while self._qn == 0 or (paused is not None
                                    and paused.is_set()):
                if stop is not None and stop.is_set():
                    return [], []
                self._cv.wait(0.1)
            deadline = time.monotonic() + max(0.0, window_s)
            while self._qn < max_n:
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                self._cv.wait(left)
            batch = self._pop_drr(max_n)
        live, dropped = [], []
        for r in batch:
            if r.cancelled or r.expired():
                dropped.append(r)
                if not r.cancelled:
                    self.shed += 1
                    _c_shed.add()
            else:
                live.append(r)
        return live, dropped

    def stats(self) -> dict:
        with self._cv:
            per_tenant = {t: len(q) for t, q in self._subq.items() if q}
            out = {"queued": self._qn, "depth_hw": self.depth_hw,
                   "shed": self.shed, "rejected": self.rejected,
                   "admitted": self.admitted}
            if per_tenant:
                out["tenant_queued"] = per_tenant
            if self.weights:
                out["tenant_weights"] = dict(self.weights)
            return out
