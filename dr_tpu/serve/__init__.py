"""dr_tpu.serve — one resident device claim, crash-safe multi-client
serving (docs/SPEC.md §14).

The tunnel relay allows exactly ONE TPU process; this package makes
that process a long-lived daemon (:class:`Server`) that claims the
backend once and multiplexes request streams from many thin
:class:`Client` processes over a local Unix-domain socket —
length-prefixed JSON/npy wire protocol (``protocol``), admission
control + deadline-aware FIFO (``queue``), request batching into one
deferred-plan flush, classified error serialization, and a watchdog
that degrades the claim to the CPU route when the relay dies
mid-session.  ``python -m dr_tpu.serve`` runs the daemon foreground.
"""

from .client import Client
from .daemon import (OPS, Server, daemon_alive, default_socket_path,
                     reset_state)
from .queue import AdmissionQueue, Request

__all__ = ["Server", "Client", "AdmissionQueue", "Request", "OPS",
           "daemon_alive", "default_socket_path", "reset"]


def reset() -> None:
    """Stop any live in-process servers and clear the serve env
    markers (the tests' between-test hygiene hook)."""
    reset_state()
