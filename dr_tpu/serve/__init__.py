"""dr_tpu.serve — one resident device claim, crash-safe multi-client
serving (docs/SPEC.md §14), on a zero-copy horizontally-scaled data
plane (§19).

The tunnel relay allows exactly ONE TPU process; this package makes
that process a long-lived daemon (:class:`Server`) that claims the
backend once and multiplexes request streams from many thin
:class:`Client` processes over a local Unix-domain socket —
length-prefixed JSON/npy wire protocol (``protocol``), admission
control + weighted-fair tenant scheduling (``queue``), request
batching into one deferred-plan flush, classified error
serialization, and a watchdog that degrades the claim to the CPU
route when the relay dies mid-session.  The data plane (§19) moves
bulk tensors through a shared-memory arena (``arena`` — the frame
carries metadata plus a handle, bytes move once), parks per-tenant
resident containers on the daemon (``resident`` + :class:`Ref`, no
per-request rebuild), and scales horizontally with N replicas behind
a consistent-hash router (``router``).  ``python -m dr_tpu.serve``
runs one daemon foreground.
"""

from .arena import Arena, ClientArena
from .client import (Client, Ref, reset_retry_budget,
                     shared_retry_budget)
from .daemon import (OPS, Server, daemon_alive, default_socket_path,
                     reset_state)
from .journal import Journal
from .queue import AdmissionQueue, Request
from .router import CircuitBreaker, HashRing, Router, RouterClient
from .resident import ResidentCache

__all__ = ["Server", "Client", "Ref", "AdmissionQueue", "Request",
           "OPS", "Arena", "ClientArena", "ResidentCache", "HashRing",
           "Router", "RouterClient", "CircuitBreaker", "Journal",
           "daemon_alive", "default_socket_path",
           "shared_retry_budget", "reset"]


def reset() -> None:
    """Stop any live in-process servers AND spawned fleets, clear the
    serve env markers, drop the shared retry budget, and unlink the
    journal files this process touched (the tests' between-test
    hygiene hook)."""
    from . import journal as _journal
    from . import router as _router
    _router.reset_state()  # fleets first: their daemons die with them
    reset_state()
    reset_retry_budget()
    _journal.reset_state()
