"""Replica router: N serving daemons behind a consistent-hash front
(docs/SPEC.md §19.3).

One daemon is one admission queue in front of one resident claim —
fleet throughput needs N of them.  The router is deliberately TINY:
a consistent-hash ring maps ``tenant → replica`` client-side (no
broker process, no extra hop on the data path), every replica shares
one ``DR_TPU_COMPILE_CACHE_DIR`` so the fleet warms each program
once, and tenant affinity keeps each tenant's resident containers and
arena traffic on one daemon.

On the one-TPU host the fleet is still real: replica 0 may hold the
device claim, replicas ≥ 1 are forced onto the CPU route (the relay
admits ONE process — §14), so the router is the multi-process
scale-out harness the real topology will reuse unchanged.

Failure contract: ``router.route`` is a registered fault site (fires
at every lookup, before any replica is touched); a DEAD replica
(``RelayDownError`` — nothing listening) is removed from the ring,
its tenants re-hash onto the survivors, and the event publishes the
``_DR_TPU_SERVE_ROUTER_*`` story markers ``degradation_story`` folds
into the serve chapter — re-homed tenants lose their resident cache
(it lived in the dead process) and simply rebuild on first use.
"""

from __future__ import annotations

import bisect
import hashlib
import os
import threading
from typing import Dict, List, Optional

from ..obs import metrics as _om
from ..utils import faults as _faults
from ..utils import resilience
from ..utils.env import env_int
from ..utils.fallback import warn_fallback
from .client import Client

__all__ = ["HashRing", "Router", "RouterClient"]

_c_routes = _om.counter("serve.router.routes")
_c_rehash = _om.counter("serve.router.rehashes")

#: Client op methods the router forwards (everything tenant-scoped);
#: control ops (stats/ping) have per-replica variants instead.
_FORWARD = ("request", "fill", "scale", "reduce", "dot", "scan",
            "sort", "join", "groupby", "unique", "top_k", "histogram",
            "put", "get", "drop")


def _digest(key: str) -> int:
    """Stable placement hash (process-independent — Python's ``hash``
    is salted per process, which would re-home every tenant on every
    restart)."""
    return int.from_bytes(
        hashlib.sha1(key.encode("utf-8")).digest()[:8], "big")


class HashRing:
    """Classic consistent hashing: each replica owns ``vnodes``
    points on a 64-bit ring; a tenant maps to the first point at or
    after its own hash.  Removing a replica re-homes ONLY the tenants
    that hashed to it — the property that makes a dead replica a
    bounded event instead of a full reshuffle."""

    def __init__(self, keys, vnodes: int = 64):
        self.vnodes = int(vnodes)
        self._points: List[int] = []
        self._owners: List[str] = []
        self._keys: List[str] = []
        for k in keys:
            self.add(k)

    def add(self, key: str) -> None:
        if key in self._keys:
            return
        self._keys.append(key)
        for v in range(self.vnodes):
            h = _digest(f"{key}#{v}")
            i = bisect.bisect(self._points, h)
            self._points.insert(i, h)
            self._owners.insert(i, key)

    def remove(self, key: str) -> None:
        if key not in self._keys:
            return
        self._keys.remove(key)
        keep = [(p, o) for p, o in zip(self._points, self._owners)
                if o != key]
        self._points = [p for p, _ in keep]
        self._owners = [o for _, o in keep]

    def keys(self) -> List[str]:
        return list(self._keys)

    def lookup(self, tenant: str) -> str:
        if not self._points:
            raise resilience.RelayDownError(
                "serve.router: no live replicas left on the ring",
                site="router.route")
        i = bisect.bisect(self._points, _digest(tenant)) \
            % len(self._points)
        return self._owners[i]


class Router:
    """Fleet harness: start N daemons on ``<base>.r<i>`` sockets.
    Replica 0 honors the caller's route request; replicas ≥ 1 are
    always CPU-route (one-TPU host rule).  ``spawn=True`` runs each
    replica as a real ``python -m dr_tpu.serve`` subprocess (the
    multi-process harness); default is in-process servers (tests,
    bench)."""

    def __init__(self, base_path: str, replicas: Optional[int] = None,
                 *, cpu: bool = True, spawn: bool = False, **server_kw):
        self.base = str(base_path)
        self.replicas = (env_int("DR_TPU_SERVE_REPLICAS", 2)
                         if replicas is None else int(replicas))
        self.cpu = bool(cpu)
        self.spawn = bool(spawn)
        self._server_kw = server_kw
        self._servers: list = []
        self._procs: list = []
        self._paths: List[str] = []

    def start(self) -> "Router":
        from .daemon import Server
        try:
            for i in range(self.replicas):
                path = f"{self.base}.r{i}"
                # one-TPU host: at most ONE replica may race for the
                # device claim — every replica past the first is
                # pinned to the CPU route regardless of the request
                cpu = self.cpu or i > 0
                if self.spawn:
                    self._procs.append(self._spawn(path, cpu))
                else:
                    self._servers.append(
                        Server(path, cpu=cpu,
                               **self._server_kw).start())
                self._paths.append(path)
        except BaseException:
            self.stop()
            raise
        return self

    def _spawn(self, path: str, cpu: bool):
        import json
        import subprocess
        import sys
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)  # frozen by sitecustomize
        argv = [sys.executable, "-m", "dr_tpu.serve", "--socket", path]
        if cpu:
            argv.append("--cpu")
        proc = subprocess.Popen(argv, env=env, stdout=subprocess.PIPE,
                                stderr=subprocess.DEVNULL, text=True)
        line = proc.stdout.readline()
        try:
            ready = json.loads(line) if line.strip() else {}
        except ValueError:
            ready = {}
        if ready.get("serving") != path:
            proc.kill()
            proc.wait(timeout=30)
            raise resilience.classified(
                f"serve.router: replica on {path} failed to start "
                f"({line!r})", site="router.route")
        return proc

    def paths(self) -> List[str]:
        return list(self._paths)

    def stop(self) -> None:
        for srv in self._servers:
            try:
                srv.stop()
            # drlint: ok[R5] fleet teardown best effort: one replica's failing stop must not strand the rest
            except Exception:  # pragma: no cover
                pass
        self._servers = []
        for proc in self._procs:
            try:
                proc.terminate()  # the daemon's SIGTERM handler stops
                proc.wait(timeout=30)  # cleanly (socket unlinked)
            except Exception:  # pragma: no cover - teardown
                proc.kill()
        self._procs = []
        self._paths = []


class RouterClient:
    """The tenant-facing front: holds one lazy :class:`Client` per
    replica and forwards every op to the replica the ring names for
    its tenant.  A dead replica re-hashes (classified story marker);
    when the LAST replica dies the ``RelayDownError`` surfaces — the
    caller's degrade signal, exactly like a single-daemon client."""

    def __init__(self, paths, *, tenant: str = "default",
                 vnodes: int = 64, **client_kw):
        self.tenant = tenant
        self._ring = HashRing(paths, vnodes=vnodes)
        self._client_kw = dict(client_kw)
        self._clients: Dict[str, Client] = {}
        self._lock = threading.Lock()
        self.rehashes = 0

    # ------------------------------------------------------------ routing
    def route(self, tenant: Optional[str] = None) -> str:
        """The replica socket the ring names for ``tenant`` (fault
        site ``router.route`` — fires before any replica is
        touched)."""
        t = tenant or self.tenant
        _faults.fire("router.route", tenant=t)
        _c_routes.add()
        return self._ring.lookup(t)

    def _client(self, path: str) -> Client:
        with self._lock:
            c = self._clients.get(path)
        if c is not None:
            return c
        c = Client(path, tenant=self.tenant, **self._client_kw)
        with self._lock:
            have = self._clients.setdefault(path, c)
        if have is not c:
            c.close()
        return have

    def _mark_dead(self, path: str, err) -> None:
        """Remove a dead replica from the ring and publish the story
        marker — its tenants re-hash onto the survivors (bounded by
        consistent hashing), losing only their resident cache."""
        self._ring.remove(path)
        self.rehashes += 1
        _c_rehash.add()
        with self._lock:
            c = self._clients.pop(path, None)
        if c is not None:
            c.close()
        os.environ["_DR_TPU_SERVE_ROUTER_DEAD"] = \
            str(env_int("_DR_TPU_SERVE_ROUTER_DEAD", 0, floor=0) + 1)
        os.environ["_DR_TPU_SERVE_ROUTER_REASON"] = \
            (f"replica {path} unreachable "
             f"({type(err).__name__}); tenants re-hashed onto "
             f"{len(self._ring.keys())} survivor(s)")[:200]
        warn_fallback("serve.router",
                      f"replica {path} unreachable; re-hashing its "
                      "tenants onto the survivors")

    def _call(self, name: str, args, kw):
        tenant = kw.get("tenant") or self.tenant
        while True:
            path = self.route(tenant)
            try:
                return getattr(self._client(path), name)(*args, **kw)
            except resilience.RelayDownError as e:
                # nothing listening: THIS replica is dead.  Re-hash
                # and retry on the survivors; the last death re-raises
                # (the ring lookup itself turns RelayDown).
                self._mark_dead(path, e)
            except resilience.ResilienceError as e:
                # a replica that died mid-exchange surfaces as a torn
                # frame / broken pipe on the CACHED connection, not a
                # RelayDown.  Business rejections (overload, deadline,
                # the daemon's own classified op errors) come from a
                # LIVE replica and re-raise; only a replica that also
                # fails the liveness probe re-hashes.
                from .daemon import daemon_alive
                if isinstance(e, (resilience.ServerOverloaded,
                                  resilience.DeadlineExpired)) \
                        or daemon_alive(path):
                    raise
                self._mark_dead(path, e)

    def __getattr__(self, name: str):
        if name in _FORWARD:
            def fwd(*args, _n=name, **kw):
                return self._call(_n, args, kw)
            fwd.__name__ = name
            return fwd
        raise AttributeError(name)

    # ------------------------------------------------------------- admin
    def live_replicas(self) -> List[str]:
        return self._ring.keys()

    def stats(self) -> Dict[str, dict]:
        """Per-replica daemon stats (live replicas only)."""
        out = {}
        for path in self._ring.keys():
            try:
                out[path] = self._client(path).stats()
            except resilience.ResilienceError as e:
                out[path] = {"error": repr(e)[:120]}
        return out

    def close(self) -> None:
        with self._lock:
            clients, self._clients = list(self._clients.values()), {}
        for c in clients:
            c.close()

    def __enter__(self) -> "RouterClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
