"""Replica router: N serving daemons behind a consistent-hash front
(docs/SPEC.md §19.3).

One daemon is one admission queue in front of one resident claim —
fleet throughput needs N of them.  The router is deliberately TINY:
a consistent-hash ring maps ``tenant → replica`` client-side (no
broker process, no extra hop on the data path), every replica shares
one ``DR_TPU_COMPILE_CACHE_DIR`` so the fleet warms each program
once, and tenant affinity keeps each tenant's resident containers and
arena traffic on one daemon.

On the one-TPU host the fleet is still real: replica 0 may hold the
device claim, replicas ≥ 1 are forced onto the CPU route (the relay
admits ONE process — §14), so the router is the multi-process
scale-out harness the real topology will reuse unchanged.

Failure contract: ``router.route`` is a registered fault site (fires
at every lookup, before any replica is touched); a DEAD replica
(``RelayDownError`` — nothing listening) is removed from the ring,
its tenants re-hash onto the survivors, and the event publishes the
``_DR_TPU_SERVE_ROUTER_*`` story markers ``degradation_story`` folds
into the serve chapter — re-homed tenants lose their resident cache
(it lived in the dead process) and simply rebuild on first use.

Control plane (docs/SPEC.md §20): replica death is no longer
permanent.  Each replica carries a client-side CIRCUIT BREAKER —
closed while healthy, OPEN once it fails (tenants re-hash away),
half-open probed on the seeded ``resilience.backoff_schedule`` (fault
site ``router.probe``, bounded at ``DR_TPU_SERVE_PROBES``) — and a
replica that answers its probe healthy re-joins the ring so its
tenants re-hash BACK.  A replica that announces a DRAIN
(``ServerDraining``) re-hashes the same way but BEFORE it dies.  In
spawn mode the :class:`Router` doubles as a passive supervisor
(polled, never a thread — the ``elastic.GrowSupervisor`` discipline):
``poll()`` respawns dead replica processes with the same bounded
backoff, and ``rolling_restart()`` drains + restarts the fleet one
replica at a time with zero classified client errors on the happy
path.
"""

from __future__ import annotations

import bisect
import hashlib
import os
import threading
import time
import weakref
from typing import Dict, List, Optional

from .. import obs as _obs
from ..obs import metrics as _om
from ..utils import faults as _faults
from ..utils import resilience
from ..utils.env import env_float, env_int
from ..utils.fallback import warn_fallback
from .client import Client, shared_retry_budget

__all__ = ["HashRing", "Router", "RouterClient", "CircuitBreaker"]

_c_routes = _om.counter("serve.router.routes")
_c_rehash = _om.counter("serve.router.rehashes")
_c_probes = _om.counter("serve.router.probes")
_c_recovered = _om.counter("serve.router.recovered")
_c_respawns = _om.counter("serve.router.respawns")

#: live Router fleets (serve.reset stops leaks between tests)
_live_routers: "weakref.WeakSet" = weakref.WeakSet()


def _bump_marker(name: str) -> None:
    os.environ[name] = str(env_int(name, 0, floor=0) + 1)


def replica_ready(path: str, timeout: float = 2.0) -> bool:
    """Health-check one replica: connectable AND answering pings AND
    not draining — the breaker-probe predicate (a draining daemon
    must read NOT ready, or a probe would re-admit a dying replica
    right after its drain announcement)."""
    try:
        c = Client(path, timeout=timeout)
    except resilience.ResilienceError:
        return False
    try:
        return not c.ping().get("draining")
    except resilience.ResilienceError:
        return False
    finally:
        c.close()


class _ProbeSchedule(resilience.ProbeTimer):
    """:class:`resilience.ProbeTimer` with the serve-sized knobs
    (SPEC §20.1): from ``DR_TPU_SERVE_PROBE_S`` doubling to
    ``DR_TPU_SERVE_PROBE_CAP_S``, bounded at ``DR_TPU_SERVE_PROBES``
    total — a replica that never comes back is not probed forever."""

    def __init__(self, *, seed: int = 0):
        super().__init__(env_float("DR_TPU_SERVE_PROBE_S", 0.5),
                         env_float("DR_TPU_SERVE_PROBE_CAP_S", 30.0),
                         env_int("DR_TPU_SERVE_PROBES", 16),
                         seed=seed)


class CircuitBreaker:
    """Per-replica breaker (SPEC §20.1): ``closed`` while healthy;
    ``trip()`` opens it (the replica leaves the ring); while open,
    :meth:`due` paces half-open probes on a :class:`_ProbeSchedule`;
    a healthy probe (:meth:`reset`) closes it — the replica re-joins
    the ring and its tenants re-hash back."""

    __slots__ = ("path", "state", "seed", "sched", "trips")

    def __init__(self, path: str, *, seed: int = 0):
        self.path = path
        self.seed = seed
        self.state = "closed"
        self.sched: Optional[_ProbeSchedule] = None
        self.trips = 0

    def trip(self) -> None:
        if self.state == "closed":
            self.trips += 1
        self.state = "open"
        self.sched = _ProbeSchedule(seed=self.seed)

    def due(self, now: Optional[float] = None) -> bool:
        return self.state == "open" and self.sched is not None \
            and self.sched.due(now)

    def exhausted(self) -> bool:
        return self.sched is not None and self.sched.exhausted()

    def reset(self) -> None:
        self.state = "closed"
        self.sched = None

#: Client op methods the router forwards (everything tenant-scoped);
#: control ops (stats/ping) have per-replica variants instead.
_FORWARD = ("request", "fill", "scale", "reduce", "dot", "scan",
            "sort", "join", "groupby", "unique", "top_k", "histogram",
            "put", "get", "drop")


def _digest(key: str) -> int:
    """Stable placement hash (process-independent — Python's ``hash``
    is salted per process, which would re-home every tenant on every
    restart)."""
    return int.from_bytes(
        hashlib.sha1(key.encode("utf-8")).digest()[:8], "big")


class HashRing:
    """Classic consistent hashing: each replica owns ``vnodes``
    points on a 64-bit ring; a tenant maps to the first point at or
    after its own hash.  Removing a replica re-homes ONLY the tenants
    that hashed to it — the property that makes a dead replica a
    bounded event instead of a full reshuffle."""

    def __init__(self, keys, vnodes: int = 64):
        self.vnodes = int(vnodes)
        self._points: List[int] = []
        self._owners: List[str] = []
        self._keys: List[str] = []
        for k in keys:
            self.add(k)

    def add(self, key: str) -> None:
        if key in self._keys:
            return
        self._keys.append(key)
        for v in range(self.vnodes):
            h = _digest(f"{key}#{v}")
            i = bisect.bisect(self._points, h)
            self._points.insert(i, h)
            self._owners.insert(i, key)

    def remove(self, key: str) -> None:
        if key not in self._keys:
            return
        self._keys.remove(key)
        keep = [(p, o) for p, o in zip(self._points, self._owners)
                if o != key]
        self._points = [p for p, _ in keep]
        self._owners = [o for _, o in keep]

    def keys(self) -> List[str]:
        return list(self._keys)

    def lookup(self, tenant: str) -> str:
        if not self._points:
            raise resilience.RelayDownError(
                "serve.router: no live replicas left on the ring",
                site="router.route")
        i = bisect.bisect(self._points, _digest(tenant)) \
            % len(self._points)
        return self._owners[i]


class Router:
    """Fleet harness: start N daemons on ``<base>.r<i>`` sockets.
    Replica 0 honors the caller's route request; replicas ≥ 1 are
    always CPU-route (one-TPU host rule).  ``spawn=True`` runs each
    replica as a real ``python -m dr_tpu.serve`` subprocess (the
    multi-process harness); default is in-process servers (tests,
    bench)."""

    def __init__(self, base_path: str, replicas: Optional[int] = None,
                 *, cpu: bool = True, spawn: bool = False, **server_kw):
        self.base = str(base_path)
        self.replicas = (env_int("DR_TPU_SERVE_REPLICAS", 2)
                         if replicas is None else int(replicas))
        self.cpu = bool(cpu)
        self.spawn = bool(spawn)
        self._server_kw = server_kw
        self._servers: list = []
        self._procs: list = []
        self._paths: List[str] = []
        # spawn-mode respawn supervisor state (SPEC §20.1): one
        # bounded probe schedule per dead replica index, polled —
        # never a thread
        self._respawn_scheds: Dict[int, _ProbeSchedule] = {}
        #: serializes proc mutation between the passive supervisor
        #: poll (riding client traffic threads) and an explicit
        #: restart_replica/rolling_restart — without it both can
        #: respawn the SAME dead index, racing two daemons for one
        #: socket and leaking whichever Popen handle loses the
        #: assignment
        self._spawn_lock = threading.Lock()
        self.respawns = 0
        self.restarts = 0

    def start(self) -> "Router":
        from .daemon import Server
        try:
            for i in range(self.replicas):
                path = f"{self.base}.r{i}"
                # one-TPU host: at most ONE replica may race for the
                # device claim — every replica past the first is
                # pinned to the CPU route regardless of the request
                cpu = self.cpu or i > 0
                if self.spawn:
                    self._procs.append(self._spawn(path, cpu))
                else:
                    self._servers.append(
                        Server(path, cpu=cpu,
                               **self._server_kw).start())
                self._paths.append(path)
        except BaseException:
            self.stop()
            raise
        _live_routers.add(self)
        return self

    def _spawn(self, path: str, cpu: bool):
        import json
        import subprocess
        import sys
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)  # frozen by sitecustomize
        argv = [sys.executable, "-m", "dr_tpu.serve", "--socket", path]
        if cpu:
            argv.append("--cpu")
        state_dir = self._server_kw.get("state_dir")
        if state_dir:
            argv += ["--state-dir", str(state_dir)]
        proc = subprocess.Popen(argv, env=env, stdout=subprocess.PIPE,
                                stderr=subprocess.DEVNULL, text=True)
        line = proc.stdout.readline()
        try:
            ready = json.loads(line) if line.strip() else {}
        except ValueError:
            ready = {}
        if ready.get("serving") != path:
            proc.kill()
            proc.wait(timeout=30)
            raise resilience.classified(
                f"serve.router: replica on {path} failed to start "
                f"({line!r})", site="router.route")
        return proc

    def paths(self) -> List[str]:
        return list(self._paths)

    # ------------------------------------------------- supervisor (§20.1)
    def poll(self) -> List[str]:
        """Passive spawn-mode supervisor poll: respawn dead replica
        processes with a bounded seeded-backoff probe budget (the
        ``elastic.GrowSupervisor`` discipline — owners poll between
        requests, no thread).  Returns the paths respawned this poll;
        never raises — a failed respawn is warned, counted, and
        backed off.  In-process fleets have no processes to supervise
        (:meth:`restart_replica` restarts those explicitly)."""
        out: List[str] = []
        if not self.spawn:
            return out
        if not self._spawn_lock.acquire(blocking=False):
            return out  # an explicit restart owns the procs right now
        try:
            now = time.monotonic()
            for i, proc in enumerate(self._procs):
                if proc is None or proc.poll() is None:
                    continue  # alive
                sched = self._respawn_scheds.get(i)
                if sched is None:
                    sched = self._respawn_scheds[i] = \
                        _ProbeSchedule(seed=i)
                    warn_fallback(
                        "serve.router",
                        f"replica {self._paths[i]} died (exit "
                        f"{proc.returncode}); respawn supervisor "
                        "armed")
                if not sched.due(now):
                    continue
                sched.advance(now)
                try:
                    self._procs[i] = self._spawn(self._paths[i],
                                                 self.cpu or i > 0)
                # drlint: ok[R5] poll() must NEVER raise into the client traffic it rides — a Popen OSError is a failed respawn like any classified one: warn and back off
                except Exception as e:
                    warn_fallback(
                        "serve.router",
                        f"respawn of {self._paths[i]} failed "
                        f"({type(e).__name__}); backing off "
                        f"({sched.probes}/{sched.budget})")
                    continue
                self._respawn_scheds.pop(i, None)
                self.respawns += 1
                _c_respawns.add()
                _bump_marker("_DR_TPU_SERVE_RESPAWNS")
                _obs.event("router.respawn", cat="serve",
                           path=self._paths[i])
                warn_fallback("serve.router",
                              f"replica {self._paths[i]} respawned; "
                              "its tenants re-hash back as breakers "
                              "re-admit it")
                out.append(self._paths[i])
        finally:
            self._spawn_lock.release()
        return out

    def restart_replica(self, i: int) -> str:
        """Restart replica ``i`` in place: drain it if it is alive
        (its routed tenants re-hash away BEFORE it dies), then start
        a fresh daemon on the same socket — which replays its
        resident-state journal when a state dir is armed.  The
        rolling-restart step; also the bench crash leg's respawn."""
        path = self._paths[i]
        cpu = self.cpu or i > 0
        if self.spawn:
            with self._spawn_lock:  # the supervisor poll yields
                proc = self._procs[i]
                if proc.poll() is None:
                    try:
                        with Client(path, timeout=30.0) as c:
                            c.drain()
                    except resilience.ResilienceError:
                        proc.terminate()  # SIGTERM drains (__main__)
                    try:
                        proc.wait(timeout=60)
                    except Exception:  # pragma: no cover - wedged
                        proc.kill()
                        proc.wait(timeout=30)
                self._procs[i] = self._spawn(path, cpu)
        else:
            from .daemon import Server
            srv = self._servers[i]
            try:
                srv.drain()
            except resilience.ResilienceError:
                srv.stop()  # faulted drain: hard stop, still restart
            self._servers[i] = Server(path, cpu=cpu,
                                      **self._server_kw).start()
        self._respawn_scheds.pop(i, None)
        self.restarts += 1
        return path

    def rolling_restart(self, *, ready_timeout: float = 60.0) \
            -> List[str]:
        """Drain-and-restart every replica ONE at a time (SPEC
        §20.3): each replica drains (routed clients re-hash its
        tenants onto the survivors before it exits), restarts, and
        must answer a health check before the next replica goes — so
        at least N-1 replicas serve at every moment and the happy
        path completes with ZERO classified client errors.  With a
        state dir armed each restarted replica replays its journal,
        so tenants' residents survive the whole roll."""
        out: List[str] = []
        for i in range(len(self._paths)):
            path = self.restart_replica(i)
            deadline = time.monotonic() + ready_timeout
            while not replica_ready(path):
                if time.monotonic() >= deadline:
                    raise resilience.classified(
                        f"serve.router: restarted replica {path} not "
                        f"serving within {ready_timeout}s",
                        site="router.probe")
                time.sleep(0.01)
            out.append(path)
        return out

    def stats(self) -> Dict[str, object]:
        """Fleet-supervisor counters (the per-daemon stats live on
        :meth:`RouterClient.stats`)."""
        if self.spawn:
            alive = [p for p, proc in zip(self._paths, self._procs)
                     if proc is not None and proc.poll() is None]
        else:
            alive = [s.path for s in self._servers
                     if not s._stopped.is_set()]
        return {"replicas": len(self._paths), "alive": alive,
                "respawns": self.respawns, "restarts": self.restarts,
                "pending_respawns": len(self._respawn_scheds)}

    def stop(self) -> None:
        for srv in self._servers:
            try:
                srv.stop()
            # drlint: ok[R5] fleet teardown best effort: one replica's failing stop must not strand the rest
            except Exception:  # pragma: no cover
                pass
        self._servers = []
        for proc in self._procs:
            try:
                proc.terminate()  # the daemon's SIGTERM handler drains
                proc.wait(timeout=30)  # cleanly (socket unlinked)
            except Exception:  # pragma: no cover - teardown
                proc.kill()
        self._procs = []
        self._paths = []
        self._respawn_scheds.clear()
        _live_routers.discard(self)


class RouterClient:
    """The tenant-facing front: holds one lazy :class:`Client` per
    replica and forwards every op to the replica the ring names for
    its tenant.  A dead replica re-hashes (classified story marker);
    when the LAST replica dies the ``RelayDownError`` surfaces — the
    caller's degrade signal, exactly like a single-daemon client.

    Control plane (SPEC §20): each replica carries a
    :class:`CircuitBreaker` — a death/drain opens it (tenants re-hash
    away) and bounded seeded-backoff half-open probes (fault site
    ``router.probe``) re-admit it to the ring once it answers healthy,
    so its tenants re-hash BACK.  ``router=`` attaches a spawn-mode
    :class:`Router` whose respawn supervisor is polled before each
    call; every Client this front creates shares ONE process-wide
    retry token budget (``budget=`` overrides)."""

    def __init__(self, paths, *, tenant: str = "default",
                 vnodes: int = 64, router: Optional[Router] = None,
                 budget=None, **client_kw):
        self.tenant = tenant
        self._ring = HashRing(paths, vnodes=vnodes)
        self._router = router
        self._budget = (shared_retry_budget() if budget is None
                        else budget)
        self._client_kw = dict(client_kw)
        self._client_kw.setdefault("budget", self._budget)
        self._breakers: Dict[str, CircuitBreaker] = {
            p: CircuitBreaker(p, seed=i)
            for i, p in enumerate(self._ring.keys())}
        self._clients: Dict[str, Client] = {}
        self._lock = threading.Lock()
        self.rehashes = 0
        self.recoveries = 0
        self.drain_rehashes = 0

    # ------------------------------------------------------------ routing
    def route(self, tenant: Optional[str] = None) -> str:
        """The replica socket the ring names for ``tenant`` (fault
        site ``router.route`` — fires before any replica is
        touched)."""
        t = tenant or self.tenant
        _faults.fire("router.route", tenant=t)
        _c_routes.add()
        return self._ring.lookup(t)

    def _client(self, path: str) -> Client:
        with self._lock:
            c = self._clients.get(path)
        if c is not None:
            return c
        c = Client(path, tenant=self.tenant, **self._client_kw)
        with self._lock:
            have = self._clients.setdefault(path, c)
        if have is not c:
            c.close()
        return have

    def _drop_client(self, path: str) -> None:
        with self._lock:
            c = self._clients.pop(path, None)
        if c is not None:
            c.close()

    def _mark_dead(self, path: str, err) -> None:
        """Remove a dead replica from the ring, OPEN its breaker (the
        probe schedule will re-admit it if it comes back — SPEC
        §20.1), and publish the story marker — its tenants re-hash
        onto the survivors (bounded by consistent hashing), losing
        only their resident cache."""
        self._ring.remove(path)
        self._breakers.setdefault(path, CircuitBreaker(path)).trip()
        self.rehashes += 1
        _c_rehash.add()
        self._drop_client(path)
        _bump_marker("_DR_TPU_SERVE_ROUTER_DEAD")
        os.environ["_DR_TPU_SERVE_ROUTER_REASON"] = \
            (f"replica {path} unreachable "
             f"({type(err).__name__}); tenants re-hashed onto "
             f"{len(self._ring.keys())} survivor(s)")[:200]
        warn_fallback("serve.router",
                      f"replica {path} unreachable; re-hashing its "
                      "tenants onto the survivors")

    def _mark_draining(self, path: str, err) -> None:
        """A replica ANNOUNCED its drain (SPEC §20.3): re-hash its
        tenants NOW — before it dies, not after — and open its
        breaker so the restarted daemon re-joins via the probe
        schedule.  A planned handoff: no dead-replica marker, no
        degradation reason."""
        self._ring.remove(path)
        self._breakers.setdefault(path, CircuitBreaker(path)).trip()
        self.drain_rehashes += 1
        _c_rehash.add()
        self._drop_client(path)
        _bump_marker("_DR_TPU_SERVE_ROUTER_DRAINED")
        _obs.event("router.drain_rehash", cat="serve", path=path)

    def _readmit(self, path: str) -> None:
        br = self._breakers.get(path)
        if br is not None:
            br.reset()
        self._ring.add(path)
        self.recoveries += 1
        _c_recovered.add()
        _bump_marker("_DR_TPU_SERVE_ROUTER_RECOVERED")
        warn_fallback("serve.router",
                      f"replica {path} healthy again; its tenants "
                      "re-hash back")

    def _maybe_probe(self, *, force: bool = False) -> None:
        """Half-open probes of OPEN replicas (SPEC §20.1): when a
        breaker's seeded-backoff probe is due, fire ``router.probe``
        and health-check the replica — a ready one re-joins the ring
        (tenants re-hash back), a failed or FAULTED probe counts and
        backs off, traffic stays on the survivors.  One dict scan
        when every breaker is closed.  ``force=True`` (the EMPTY-ring
        last resort — e.g. the instant mid-``rolling_restart`` when
        the drained replica just left and the restarted one is not
        re-admitted yet) probes every open breaker regardless of
        pacing or exhaustion, without advancing the paced schedule —
        a demand probe must not burn the budget."""
        now = time.monotonic()
        for path, br in list(self._breakers.items()):
            if force:
                if br.state != "open":
                    continue
            elif not br.due(now):
                continue
            else:
                br.sched.advance(now)
            ok = False
            try:
                _faults.fire("router.probe", path=path)
                ok = replica_ready(path)
            except resilience.ResilienceError as e:
                warn_fallback(
                    "serve.router",
                    f"probe of {path} failed classified "
                    f"({type(e).__name__}); backing off "
                    f"({br.sched.probes}/{br.sched.budget})")
            _c_probes.add()
            _obs.event("router.probe", cat="serve", path=path, ok=ok)
            if ok:
                self._readmit(path)

    def _call(self, name: str, args, kw):
        tenant = kw.get("tenant") or self.tenant
        if self._router is not None:
            self._router.poll()  # passive respawn supervisor (§20.1)
        self._maybe_probe()
        reconnected: set = set()
        forced_probe = False
        while True:
            try:
                path = self.route(tenant)
            except resilience.RelayDownError:
                # EMPTY ring: every replica is open.  Before surfacing
                # the fleet-wide death, demand-probe the open breakers
                # once — mid-rolling-restart the next replica's drain
                # can land before the previous restart's paced probe
                # re-admitted it, and the happy path owes the caller
                # zero classified errors (SPEC §20.3).
                if forced_probe or self._ring.keys():
                    raise
                forced_probe = True
                self._maybe_probe(force=True)
                if not self._ring.keys():
                    raise
                continue
            try:
                return getattr(self._client(path), name)(*args, **kw)
            except resilience.ServerDraining as e:
                # planned drain announcement: the tenant re-hashes
                # BEFORE the replica dies — no client-visible error
                self._mark_draining(path, e)
            except resilience.RelayDownError as e:
                # nothing listening: THIS replica is dead.  Re-hash
                # and retry on the survivors; the last death re-raises
                # (the ring lookup itself turns RelayDown).
                self._mark_dead(path, e)
            except resilience.ResilienceError as e:
                # a replica that died mid-exchange surfaces as a torn
                # frame / broken pipe on the CACHED connection, not a
                # RelayDown.  Business rejections (overload, deadline,
                # the daemon's own classified op errors) come from a
                # LIVE replica and re-raise; only a replica that also
                # fails the liveness probe re-hashes.
                from .daemon import daemon_alive
                if isinstance(e, (resilience.ServerOverloaded,
                                  resilience.DeadlineExpired)):
                    raise
                if not daemon_alive(path):
                    self._mark_dead(path, e)
                    continue
                if isinstance(e, resilience.TransientBackendError) \
                        and path not in reconnected \
                        and self._budget.spend():
                    # the daemon is ALIVE but the cached connection is
                    # invalidated (a restarted replica on the same
                    # socket, a reply lost to its stop): reconnect
                    # once and resubmit — without this a rolling
                    # restart leaves a permanently broken client in
                    # front of a healthy replica.  The resubmission is
                    # a RETRY and spends a budget token (§20.2): an
                    # exhausted bucket surfaces the error instead.
                    reconnected.add(path)
                    self._drop_client(path)
                    continue
                raise

    def __getattr__(self, name: str):
        if name in _FORWARD:
            def fwd(*args, _n=name, **kw):
                return self._call(_n, args, kw)
            fwd.__name__ = name
            return fwd
        raise AttributeError(name)

    # ------------------------------------------------------------- admin
    def live_replicas(self) -> List[str]:
        return self._ring.keys()

    def breaker_states(self) -> Dict[str, str]:
        """Per-replica breaker state (``closed`` / ``open``)."""
        return {p: br.state for p, br in self._breakers.items()}

    def stats(self) -> Dict[str, dict]:
        """Per-replica daemon stats (live replicas only)."""
        out = {}
        for path in self._ring.keys():
            try:
                out[path] = self._client(path).stats()
            except resilience.ResilienceError as e:
                out[path] = {"error": repr(e)[:120]}
        return out

    def close(self) -> None:
        with self._lock:
            clients, self._clients = list(self._clients.values()), {}
        for c in clients:
            c.close()

    def __enter__(self) -> "RouterClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def reset_state() -> None:
    """Stop every live fleet (spawned replica subprocesses included) —
    the between-test hygiene hook (serve.reset): a leaked spawn-mode
    supervisor must not keep respawning daemons into the next test."""
    for router in list(_live_routers):
        try:
            router.stop()
        # drlint: ok[R5] between-test teardown of a leaked fleet: a failing stop must not mask the test that leaked it
        except Exception:  # pragma: no cover - teardown best effort
            pass
