"""Crash-safe resident-state journal for the serving daemon
(docs/SPEC.md §20.4).

A daemon restart — planned drain or SIGKILL — used to lose every
tenant's resident containers (§19.2): the cache lived only in process
memory, so a respawned replica came back empty and every tenant paid
the rebuild again.  This module makes resident state durable with an
APPEND-ONLY journal of ``put``/``drop`` operations under
``DR_TPU_SERVE_STATE_DIR``: each record carries the op header (tenant,
name, content tag, generation) plus the npy payload bytes, written
with flush+fsync so a SIGKILL after the reply can lose at most the
record being written.  On start the daemon replays the journal into
its resident cache — a crashed or drained replica comes back serving
its tenants' residents bit-equal — then COMPACTS it (the live set
rewritten through the checkpoint.save discipline: same-directory temp
file, fsync, ``os.replace``), so the file length is bounded by the
live residents, not the put history.

Failure contract (fault site ``serve.journal``, chaos-swept):

* **torn tail** — a record cut short by a mid-write kill parses as a
  classified :class:`~..utils.resilience.CheckpointCorruptError`
  (:meth:`Journal.scan`); :meth:`Journal.replay` recovers CLEANLY by
  truncating the file back to the last whole record (counted,
  warned, ``_DR_TPU_SERVE_JOURNAL_TRUNCATED`` marker) — every record
  before the tear replays;
* **corrupt payload** — a crc32 mismatch classifies the same way (a
  bit-flipped resident must never be served as a silent wrong
  answer);
* **generation fence** — :meth:`claim` bumps a generation file
  (atomic replace) when a daemon takes ownership of the state next
  to its socket takeover; every append re-reads it, and a STALE
  daemon — one that lost the takeover race but is still running —
  gets a classified :class:`~..utils.resilience.ProgramError` on its
  next append instead of corrupting the new owner's journal.  The
  daemon treats a fenced journal as fatal: it can never serve.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import re
import struct
import zlib

import numpy as np

from ..utils import faults as _faults
from ..utils import resilience

__all__ = ["Journal", "journal_path", "reset_state"]

#: record prefix: header length (bytes), payload length (bytes),
#: payload crc32 — little-endian u32 each
_PREFIX = struct.Struct("<III")
#: header byte cap: a garbage prefix must not allocate gigabytes
_MAX_HEADER = 1 << 20

#: journal files touched by this process (the conftest disarm fixture
#: unlinks them between tests via reset_state — a test's resident
#: state must not leak into the next test's daemon start)
_touched: set = set()


def journal_path(state_dir: str, socket_path: str) -> str:
    """The journal file for the daemon on ``socket_path``: one file
    per socket under ``state_dir``, named from the socket path so
    replicas on ``<base>.r<i>`` sockets keep disjoint state.  The
    FULL path rides a hash suffix — two unrelated daemons whose
    sockets merely share a basename (one state dir, two run
    directories) must not share a journal or fence each other."""
    full = str(socket_path)
    slug = re.sub(r"[^A-Za-z0-9._-]+", "_",
                  os.path.basename(full)) or "daemon"
    tag = hashlib.sha1(full.encode("utf-8")).hexdigest()[:8]
    return os.path.join(str(state_dir), f"{slug}-{tag}.journal")


def reset_state() -> None:
    """Unlink every journal (and generation) file this process
    touched — the between-test hygiene hook (serve.reset)."""
    for path in list(_touched):
        for p in (path, path + ".gen", path + ".tmp"):
            try:
                if os.path.exists(p):
                    os.unlink(p)
            except OSError:  # pragma: no cover - teardown best effort
                pass
    _touched.clear()


class Journal:
    """One daemon's append-only resident-state journal."""

    def __init__(self, state_dir: str, socket_path: str):
        self.path = journal_path(state_dir, socket_path)
        self.gen_path = self.path + ".gen"
        os.makedirs(str(state_dir), exist_ok=True)
        self.generation = None
        self.fenced = False
        self.appends = 0
        self.replayed = 0
        self.truncated_bytes = 0
        #: (tenant, name) -> tag of entries known durable — lets a
        #: content-identical re-put skip the duplicate append while a
        #: journal that LOST the entry (truncated tail) still re-adds
        self._live: dict = {}
        _touched.add(self.path)

    # ---------------------------------------------------------- generation
    def read_generation(self) -> int:
        try:
            with open(self.gen_path, "r", encoding="utf-8") as fh:
                return int(fh.read().strip() or 0)
        except (OSError, ValueError):
            return 0

    def claim(self) -> int:
        """Take ownership of the state: bump the generation file
        (atomic temp+fsync+replace).  Called right after the socket
        takeover — socket ownership and journal ownership must be the
        same decision, or two daemons could both append."""
        gen = self.read_generation() + 1
        tmp = self.gen_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            fh.write(str(gen))
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, self.gen_path)
        self.generation = gen
        self.fenced = False
        return gen

    def _check_fence(self) -> None:
        """A newer daemon claimed the state since we did: this process
        is STALE and must never write (or serve) again."""
        if self.generation is None:
            raise resilience.ProgramError(
                "serve.journal: append before claim()",
                site="serve.journal")
        if self.read_generation() != self.generation:
            self.fenced = True
            raise resilience.ProgramError(
                f"serve.journal: generation fence — this daemon holds "
                f"generation {self.generation} but "
                f"{self.read_generation()} is current (a newer daemon "
                "took over the socket and the state); a stale daemon "
                "must stop serving", site="serve.journal")

    # -------------------------------------------------------------- append
    def append(self, op: str, tenant: str, name: str, tag: str = "",
               payload: bytes = b"") -> None:
        """Append one durable ``put``/``drop`` record: fence check,
        then write + flush + fsync — after this returns, a SIGKILL
        cannot lose the record."""
        _faults.fire("serve.journal", op=op, name=name)
        self._check_fence()
        header = json.dumps(
            {"op": op, "tenant": tenant, "name": name, "tag": tag,
             "gen": self.generation}).encode("utf-8")
        with open(self.path, "ab") as fh:
            fh.write(_PREFIX.pack(len(header), len(payload),
                                  zlib.crc32(payload)))
            fh.write(header)
            if payload:
                fh.write(payload)
            fh.flush()
            os.fsync(fh.fileno())
        self.appends += 1
        key = (tenant, name)
        if op == "put":
            self._live[key] = tag
        else:
            self._live.pop(key, None)

    def has(self, tenant: str, name: str, tag: str) -> bool:
        """True when a content-identical ``put`` is already durable
        (the re-put fast path skips the duplicate append)."""
        return self._live.get((tenant, name)) == tag

    # --------------------------------------------------------------- read
    def scan(self):
        """Parse every record STRICTLY: yields ``(header, payload,
        end_offset)`` tuples; a torn or corrupt record raises the
        classified :class:`CheckpointCorruptError` (carrying
        ``offset`` — the start of the bad record, i.e. the last good
        end)."""
        out = []
        if not os.path.exists(self.path):
            return out
        with open(self.path, "rb") as fh:
            data = fh.read()
        off = 0
        while off < len(data):
            if off + _PREFIX.size > len(data):
                raise self._corrupt(off, "torn record prefix")
            hlen, plen, crc = _PREFIX.unpack_from(data, off)
            if not 0 < hlen <= _MAX_HEADER:
                raise self._corrupt(off, f"header length {hlen}")
            end = off + _PREFIX.size + hlen + plen
            if end > len(data):
                raise self._corrupt(off, "torn record body")
            try:
                header = json.loads(
                    data[off + _PREFIX.size:
                         off + _PREFIX.size + hlen].decode("utf-8"))
            except (ValueError, UnicodeDecodeError) as e:
                raise self._corrupt(off, f"unreadable header ({e})")
            payload = data[off + _PREFIX.size + hlen:end]
            if zlib.crc32(payload) != crc:
                raise self._corrupt(off, "payload crc mismatch")
            out.append((header, payload, end))
            off = end
        return out

    def _corrupt(self, offset: int, why: str):
        err = resilience.CheckpointCorruptError(
            f"serve.journal: {self.path} is corrupt at byte {offset} "
            f"({why}) — truncate back to the last whole record to "
            "recover", site="serve.journal")
        err.offset = offset
        return err

    def replay(self) -> dict:
        """Replay into the live map ``{(tenant, name): (tag, payload
        bytes)}`` applying puts and drops in order.  A torn/corrupt
        TAIL recovers cleanly: the file is truncated back to the last
        whole record (``truncated_bytes`` counts the loss) and every
        record before it replays."""
        _faults.fire("serve.journal", op="replay")
        try:
            records = self.scan()
        except resilience.CheckpointCorruptError as e:
            good = getattr(e, "offset", 0)
            size = os.path.getsize(self.path)
            self.truncated_bytes += size - good
            with open(self.path, "r+b") as fh:
                fh.truncate(good)
            records = self.scan()  # the prefix is whole by construction
        live: dict = {}
        for header, payload, _end in records:
            key = (str(header.get("tenant", "default")),
                   str(header.get("name", "")))
            if header.get("op") == "put":
                live[key] = (str(header.get("tag", "")), payload)
            else:
                live.pop(key, None)
        self.replayed = len(live)
        self._live = {k: tag for k, (tag, _p) in live.items()}
        return live

    def compact(self, live: dict) -> None:
        """Rewrite the journal as exactly the live set, atomically
        (temp + fsync + ``os.replace`` — the checkpoint.save
        discipline): the file stays bounded by the resident set, and
        a kill mid-compaction leaves the previous journal intact."""
        _faults.fire("serve.journal", op="compact")
        self._check_fence()
        tmp = self.path + ".tmp"
        try:
            with open(tmp, "wb") as fh:
                for (tenant, name), (tag, payload) in live.items():
                    header = json.dumps(
                        {"op": "put", "tenant": tenant, "name": name,
                         "tag": tag, "gen": self.generation}
                    ).encode("utf-8")
                    fh.write(_PREFIX.pack(len(header), len(payload),
                                          zlib.crc32(payload)))
                    fh.write(header)
                    fh.write(payload)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, self.path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        self._live = {k: tag for k, (tag, _p) in live.items()}

    # -------------------------------------------------------------- admin
    def stats(self) -> dict:
        return {"path": self.path, "generation": self.generation,
                "appends": self.appends, "replayed": self.replayed,
                "truncated_bytes": self.truncated_bytes,
                "fenced": self.fenced, "live": len(self._live)}


def decode_payload(payload: bytes) -> np.ndarray:
    """One journal payload back to its array (npy, no pickles — the
    same rule as the wire and the arena)."""
    return np.load(io.BytesIO(payload), allow_pickle=False)
