"""Serving daemon: ONE resident device claim, crash-safe multi-client
serving.

The tunnel relay admits exactly one TPU process at a time, and a
client killed mid-claim can wedge the host relay for the whole session
(docs/ROUND2_NOTES.md).  This module turns that constraint into the
architecture Mesh-TensorFlow argues for (arXiv:1811.02084): one
long-lived process owns the claim — probed through
``runtime.probe_devices`` under the ``resilience.with_deadline``
watchdog, routed by the shared degradation router — and every other
process is a THIN client speaking the length-prefixed JSON/npy
protocol over a local Unix-domain socket.  Clients come and go (and
crash); the claim never moves.

Threads::

    accept loop ──> per-connection reader ──> AdmissionQueue
                                                   │ take_batch
                                             dispatch thread (ONE:
                                             all device work serializes
                                             here — the resident claim
                                             has a single owner)

The dispatcher coalesces the batchable requests of each queue pop into
ONE ``dr_tpu.deferred()`` region, so concurrent clients' ops flush as
one fused dispatch (dr_tpu/plan.py); non-fusible ops (sort) run
eagerly after the fused group, order preserved within the batch.
Robustness contract (chaos-swept via the ``serve.accept`` /
``serve.request`` / ``serve.flush`` fault sites):

* request errors are classified and SERIALIZED back to the client —
  they never kill the daemon;
* a client disconnect mid-request cancels its work cleanly (no reply,
  no poisoned claim);
* overload is a typed ``ServerOverloaded`` rejection (queue.py);
* expired requests are shed before dispatch;
* a relay death mid-session (RelayDownError, or a batch overrunning
  the flush watchdog) degrades the daemon to the CPU route through
  ``resilience.route_first_touch`` and publishes the serve markers
  ``degradation_story`` folds into ``detail.degraded``;
* a DEVICE death mid-batch (DeviceLostError, ``DR_TPU_ELASTIC=1``)
  shrinks the resident claim to the surviving mesh through the
  elastic layer (utils/elastic.py, SPEC §16) — the retry leg replays
  the batch on the shrunken mesh, handlers rebuild their containers,
  and no client is dropped; the shrink lands in ``stats()["shrinks"]``
  and the degradation story's ``shrink`` chapter;
* with ``DR_TPU_ELASTIC_GROW=1`` the degradation is SYMMETRIC (SPEC
  §16.6): a claim degraded to the CPU route re-probes the REQUESTED
  route with bounded seeded backoff BETWEEN batches — on the dispatch
  thread, the only moment the claim owner provably has nothing in
  flight — and re-promotes to the device route without dropping
  clients (``stats()["grows"]``, the story's ``grow`` chapter, fault
  sites ``device.recover``/``mesh.grow``).  A daemon STARTED on the
  CPU route by request (``--cpu`` / ``Server(cpu=True)``) is never
  probed: the requested route is pinned next to the degraded route,
  so the supervisor is a structural no-op there.  A shrunken mesh
  grows back the same way (the elastic module supervisor polls at
  each batch's deferred-region exit, and the dispatch loop diffs
  ``elastic.grow_count()`` exactly like shrinks);
* a stale socket file from a dead daemon is taken over at start; a
  LIVE daemon makes a second ``start()`` fail with a classified error
  before the newcomer can race the claim;
* control plane (SPEC §20): ``drain()`` (the ``drain`` wire op and
  the ``__main__`` SIGTERM handler) stops admitting — new compute
  requests get a classified ``ServerDraining`` a routed client treats
  as the re-hash-now signal — finishes the in-flight batches, flushes
  the resident-state journal, and exits; with
  ``DR_TPU_SERVE_STATE_DIR`` set, ``put``/``drop`` append to a
  crash-safe journal (serve/journal.py) replayed at the next start,
  so a drained or SIGKILLed replica comes back serving its tenants'
  residents bit-equal — behind a generation fence that stops a stale
  daemon which lost the socket takeover from ever serving again.
"""

from __future__ import annotations

import os
import socket
import tempfile
import threading
import time
import weakref

import numpy as np

from .. import obs as _obs
from ..obs import metrics as _om
from ..utils import elastic as _elastic
from ..utils import faults as _faults
from ..utils import resilience
from ..utils.env import env_float, env_int, env_str
from ..utils.fallback import warn_fallback
from . import arena as _arena
from . import journal as _journal
from . import protocol
from .queue import AdmissionQueue, Request
from .resident import ResidentCache, ResidentStub

#: always-live per-request latency split (dr_tpu/obs metrics, SPEC
#: §15): queue-wait (submit → dispatch pop), service (dispatch pop →
#: reply posted), and the shared batch-flush wall time.  Sampled on
#: every run — the ``stats`` wire op and ``bench.py --serve`` report
#: them next to the client-side percentiles, traced or not.
_h_queue_wait = _om.histogram("serve.queue_wait_ms")
_h_service = _om.histogram("serve.service_ms")
_h_flush = _om.histogram("serve.flush_ms")


#: distinct tenants granted their own histogram pair; past the cap
#: further names fold into one overflow bucket — tenant ids are
#: client-supplied strings, and per-request ids must not grow the
#: metrics registry (serialized on every stats op) without bound
_TENANT_HIST_CAP = 64
_tenant_hist_keys: set = set()


def _h_tenant(kind: str, tenant: str):
    """Per-tenant latency histogram (``serve.<kind>_ms.t.<tenant>``,
    docs/SPEC.md §19.4): the numbers that make weighted-fair isolation
    VISIBLE — a heavy tenant's queue-wait dilates, a light tenant's
    stays flat.  Registry get-or-create is one dict lookup; names
    beyond the first ``_TENANT_HIST_CAP`` distinct tenants share the
    ``__other__`` bucket."""
    if tenant not in _tenant_hist_keys:
        if len(_tenant_hist_keys) >= _TENANT_HIST_CAP:
            tenant = "__other__"
        _tenant_hist_keys.add(tenant)
    return _om.histogram(f"serve.{kind}_ms.t.{tenant}")

__all__ = ["Server", "default_socket_path", "daemon_alive",
           "reset_state", "OPS"]


def default_socket_path() -> str:
    """``DR_TPU_SERVE_SOCKET``, or a per-uid path under the system
    temp dir (Unix-domain socket paths are capped near 107 bytes, so
    the default stays short)."""
    return env_str("DR_TPU_SERVE_SOCKET") or os.path.join(
        tempfile.gettempdir(), f"dr_tpu_serve_{os.getuid()}.sock")


def daemon_alive(path: str, timeout: float = 2.0) -> bool:
    """True when SOMETHING holds the socket at ``path`` — an answering
    daemon, or one that has bound but is still claiming the backend (a
    tunneled claim can take minutes; a connect succeeds against the
    listen backlog before the accept loop runs).  Only a refused/failed
    CONNECT reads as dead: treating a slow claimer as dead would let a
    second daemon take over the socket and race the claim."""
    from .client import Client
    try:
        c = Client(path, timeout=timeout)
    except Exception:
        return False  # nothing listening: stale socket file
    try:
        c.ping()
    # drlint: ok[R5] liveness probe policy, not a degradation: a bound socket that cannot answer yet (daemon mid-claim) must still read as alive
    except Exception:
        pass  # bound but not serving yet: still alive
    finally:
        c.close()
    return True


# ---------------------------------------------------------------------------
# op registry
# ---------------------------------------------------------------------------
# module-level ops: plan program-cache keys pin callable identity, so
# per-request closures would recompile the same structure every batch

def _op_scale(x, a, b):
    return x * a + b


class _OpSpec:
    __slots__ = ("name", "narrays", "batchable", "handler", "validate")

    def __init__(self, name, narrays, batchable, handler, validate=None):
        self.name = name
        self.narrays = narrays
        self.batchable = batchable
        self.handler = handler
        #: intake-time request check (reader thread): a malformed
        #: request is rejected to ITS client before it can join — and
        #: poison — a fused batch
        self.validate = validate


def _vec(arr, mutate=False):
    """Operand to container: a resident reference (intake substituted
    a :class:`ResidentStub`) resolves to the CACHED container — no
    rebuild, no host→device transfer; a handler that MUTATES its
    operand gets a device-side scratch copy instead (the cache entry
    must keep answering later requests unchanged).  Plain arrays build
    fresh, as ever."""
    import dr_tpu
    cont = getattr(arr, "_dr_resident", None)
    if cont is not None:
        if not mutate:
            return cont
        scratch = dr_tpu.distributed_vector(len(cont), cont.dtype)
        dr_tpu.copy(cont, scratch)
        return scratch
    return dr_tpu.distributed_vector.from_array(
        np.ascontiguousarray(np.asarray(arr, np.float32)))


def _v_fill(req):
    if int(req.params.get("n", 0)) <= 0:
        raise resilience.ProgramError(
            f"serve: fill needs params.n >= 1, got "
            f"{req.params.get('n', 0)!r}", site="serve.request")


def _h_fill(req):
    import dr_tpu
    v = dr_tpu.distributed_vector(int(req.params["n"]), np.float32)
    dr_tpu.fill(v, float(req.params.get("value", 0.0)))
    return lambda: ({}, [dr_tpu.to_numpy(v)])


def _h_scale(req):
    import dr_tpu
    v = _vec(req.arrays[0], mutate=True)
    dr_tpu.for_each(v, _op_scale, float(req.params.get("a", 1.0)),
                    float(req.params.get("b", 0.0)))
    return lambda: ({}, [dr_tpu.to_numpy(v)])


def _h_reduce(req):
    import dr_tpu
    s = dr_tpu.reduce(_vec(req.arrays[0]))
    return lambda: ({"scalar": float(s)}, [])


def _v_vector(req):
    for a in req.arrays:
        if np.asarray(a).ndim != 1 or np.asarray(a).size == 0:
            raise resilience.ProgramError(
                f"serve: op {req.op!r} takes non-empty 1-D arrays, got "
                f"shape {np.asarray(a).shape}", site="serve.request")


def _v_dot(req):
    _v_vector(req)
    a, b = req.arrays
    if np.asarray(a).shape != np.asarray(b).shape:
        raise resilience.ProgramError(
            "serve: dot operands must share a shape",
            site="serve.request")


def _h_dot(req):
    import dr_tpu
    a, b = req.arrays
    s = dr_tpu.dot(_vec(a), _vec(b))
    return lambda: ({"scalar": float(s)}, [])


def _h_scan(req):
    import dr_tpu
    v = _vec(req.arrays[0])
    out = dr_tpu.distributed_vector(len(v), np.float32)
    dr_tpu.inclusive_scan(v, out)
    return lambda: ({}, [dr_tpu.to_numpy(out)])


def _h_sort(req):
    import dr_tpu
    v = _vec(req.arrays[0], mutate=True)
    dr_tpu.sort(v, descending=bool(req.params.get("descending", False)))
    return lambda: ({}, [dr_tpu.to_numpy(v)])


# --- relational layer (docs/SPEC.md §17.3): join/groupby/unique have
# data-dependent result sizes and run SOLO (they record opaque — solo
# keeps one request's big expansion out of its batchmates' flush);
# topk/histogram are static-shape FUSIBLE and batch into the shared
# deferred flush with the elementwise ops.  Result arrays come back
# trimmed to the real row count.

def _v_groupby(req):
    _v_vector(req)
    from ..algorithms.relational import AGGS
    a, b = req.arrays
    if np.asarray(a).shape != np.asarray(b).shape:
        raise resilience.ProgramError(
            "serve: groupby keys and values must share a shape",
            site="serve.request")
    if str(req.params.get("agg", "sum")) not in AGGS:
        raise resilience.ProgramError(
            f"serve: unknown groupby agg {req.params.get('agg')!r} "
            f"(known: {', '.join(AGGS)})", site="serve.request")


def _h_groupby(req):
    import dr_tpu
    k, v = _vec(req.arrays[0]), _vec(req.arrays[1])
    n = len(k)
    ok = dr_tpu.distributed_vector(n, np.float32)
    ov = dr_tpu.distributed_vector(n, np.float32)
    ng = dr_tpu.groupby_aggregate(k, v, ok, ov,
                                  agg=str(req.params.get("agg", "sum")))

    def fin():
        m = int(ng)
        return ({"count": m}, [dr_tpu.to_numpy(ok)[:m],
                               dr_tpu.to_numpy(ov)[:m]])
    return fin


def _h_unique(req):
    import dr_tpu
    v = _vec(req.arrays[0])
    out = dr_tpu.distributed_vector(len(v), np.float32)
    nu = dr_tpu.unique(v, out)

    def fin():
        m = int(nu)
        return ({"count": m}, [dr_tpu.to_numpy(out)[:m]])
    return fin


def _v_join(req):
    _v_vector(req)
    from ..algorithms.relational import JOIN_HOWS
    lk, lv, rk, rv = (np.asarray(a) for a in req.arrays)
    if lk.shape != lv.shape or rk.shape != rv.shape:
        raise resilience.ProgramError(
            "serve: join keys and values must share a shape per side",
            site="serve.request")
    if str(req.params.get("how", "inner")) not in JOIN_HOWS:
        raise resilience.ProgramError(
            f"serve: unknown join how {req.params.get('how')!r} "
            f"(known: {', '.join(JOIN_HOWS)})", site="serve.request")


def _h_join(req):
    import dr_tpu
    lk, lv = _vec(req.arrays[0]), _vec(req.arrays[1])
    rk, rv = _vec(req.arrays[2]), _vec(req.arrays[3])
    # default capacity covers the common feature-join shapes; a
    # many-to-many expansion beyond it raises the classified
    # capacity ProgramError back to THIS client (params.capacity
    # overrides for heavier fan-outs)
    cap = int(req.params.get("capacity",
                             4 * (len(lk) + len(rk))))
    ok = dr_tpu.distributed_vector(cap, np.float32)
    ol = dr_tpu.distributed_vector(cap, np.float32)
    orr = dr_tpu.distributed_vector(cap, np.float32)
    m = dr_tpu.join(lk, lv, rk, rv, ok, ol, orr,
                    how=str(req.params.get("how", "inner")),
                    fill=float(req.params.get("fill", 0.0)))

    def fin():
        c = int(m)
        return ({"count": c}, [dr_tpu.to_numpy(ok)[:c],
                               dr_tpu.to_numpy(ol)[:c],
                               dr_tpu.to_numpy(orr)[:c]])
    return fin


def _v_topk(req):
    _v_vector(req)
    if int(req.params.get("k", 0)) < 1:
        raise resilience.ProgramError(
            f"serve: topk needs params.k >= 1, got "
            f"{req.params.get('k', 0)!r}", site="serve.request")


def _h_topk(req):
    import dr_tpu
    v = _vec(req.arrays[0])
    k = int(req.params["k"])
    tv = dr_tpu.distributed_vector(k, np.float32)
    ti = dr_tpu.distributed_vector(k, np.int32)
    dr_tpu.top_k(v, tv, ti,
                 largest=bool(req.params.get("largest", True)))
    return lambda: ({}, [dr_tpu.to_numpy(tv), dr_tpu.to_numpy(ti)])


def _v_histogram(req):
    _v_vector(req)
    bins = int(req.params.get("bins", 0))
    lo = req.params.get("lo")
    hi = req.params.get("hi")
    if bins < 1 or lo is None or hi is None \
            or not float(hi) > float(lo):
        raise resilience.ProgramError(
            f"serve: histogram needs params bins >= 1 and hi > lo "
            f"(got bins={bins!r}, lo={lo!r}, hi={hi!r})",
            site="serve.request")


def _h_histogram(req):
    import dr_tpu
    v = _vec(req.arrays[0])
    out = dr_tpu.distributed_vector(int(req.params["bins"]), np.int32)
    dr_tpu.histogram(v, out, float(req.params["lo"]),
                     float(req.params["hi"]))
    return lambda: ({}, [dr_tpu.to_numpy(out)])


# --- resident container cache (docs/SPEC.md §19.2): put builds the
# tenant's container ONCE on the dispatch thread; later ops reference
# it by name (header refs) and skip the rebuild; get/drop read back /
# evict.  All three run solo — put/get move whole payloads and must
# not dilate their batchmates' fused flush.

def _name_of(req) -> str:
    return str(req.params["name"])


def _v_named(req):
    if not str(req.params.get("name", "")):
        raise resilience.ProgramError(
            f"serve: op {req.op!r} needs a nonempty params.name",
            site="serve.request")


def _v_put(req):
    _v_named(req)
    _v_vector(req)


def _h_put(req):
    entry, cached = req.server._resident.put(req.tenant, _name_of(req),
                                             req.arrays[0])
    # crash-safe durability (SPEC §20.4): journal the put before the
    # reply — once the client hears "ok" the entry survives a SIGKILL
    req.server._journal_put(req.tenant, _name_of(req), entry,
                            req.arrays[0])
    return lambda: ({"handle": _name_of(req), "tag": entry.tag,
                     "bytes": entry.nbytes, "cached": cached}, [])


def _h_get(req):
    import dr_tpu
    entry = req.server._resident.require(req.tenant, _name_of(req))
    arr = dr_tpu.to_numpy(entry.cont)
    return lambda: ({"tag": entry.tag}, [arr])


def _h_drop(req):
    dropped = req.server._resident.drop(req.tenant, _name_of(req))
    if dropped:
        req.server._journal_drop(req.tenant, _name_of(req))
    return lambda: ({"dropped": dropped}, [])


#: op name -> (operand count, batchable into one deferred flush?).
#: sort is NON-fusible (it would force the plan-flush cliff) and the
#: relational join/groupby/unique record OPAQUE with data-dependent
#: result sizes — all of these dispatch alone, after the batch's
#: fused group; topk/histogram are static-shape fusible and batch.
OPS = {
    "fill": _OpSpec("fill", 0, True, _h_fill, _v_fill),
    "scale": _OpSpec("scale", 1, True, _h_scale, _v_vector),
    "reduce": _OpSpec("reduce", 1, True, _h_reduce, _v_vector),
    "dot": _OpSpec("dot", 2, True, _h_dot, _v_dot),
    "scan": _OpSpec("scan", 1, True, _h_scan, _v_vector),
    "sort": _OpSpec("sort", 1, False, _h_sort, _v_vector),
    "join": _OpSpec("join", 4, False, _h_join, _v_join),
    "groupby": _OpSpec("groupby", 2, False, _h_groupby, _v_groupby),
    "unique": _OpSpec("unique", 1, False, _h_unique, _v_vector),
    "topk": _OpSpec("topk", 1, True, _h_topk, _v_topk),
    "histogram": _OpSpec("histogram", 1, True, _h_histogram,
                         _v_histogram),
    "put": _OpSpec("put", 1, False, _h_put, _v_put),
    "get": _OpSpec("get", 0, False, _h_get, _v_named),
    "drop": _OpSpec("drop", 0, False, _h_drop, _v_named),
}


class _Conn:
    """Per-connection daemon-side state: the socket, a write lock (the
    dispatcher and the reader both reply), and the pending-request set
    cancelled wholesale on disconnect."""

    __slots__ = ("sock", "lock", "pending", "closed", "__weakref__")

    def __init__(self, sock):
        self.sock = sock
        self.lock = threading.Lock()
        self.pending = set()
        self.closed = False


#: live in-process servers (tests/bench); serve.reset() stops leaks
_live_servers: "weakref.WeakSet" = weakref.WeakSet()

#: the env markers the daemon publishes for degradation_story (the
#: router markers are published by serve/router.py — cleared here so
#: one test's dead-replica story cannot leak into the next)
_MARKERS = ("_DR_TPU_SERVE_DEGRADED", "_DR_TPU_SERVE_QUEUE_DEPTH",
            "_DR_TPU_SERVE_SHED", "_DR_TPU_SERVE_RESTARTS",
            "_DR_TPU_SERVE_ROUTER_DEAD", "_DR_TPU_SERVE_ROUTER_REASON",
            # control plane (SPEC §20): drain/respawn/breaker/journal
            "_DR_TPU_SERVE_DRAINS", "_DR_TPU_SERVE_RESPAWNS",
            "_DR_TPU_SERVE_ROUTER_DRAINED",
            "_DR_TPU_SERVE_ROUTER_RECOVERED",
            "_DR_TPU_SERVE_JOURNAL_RECOVERED",
            "_DR_TPU_SERVE_JOURNAL_TRUNCATED")


def reset_state() -> None:
    """Stop every live in-process server and clear the serve env
    markers — the conftest autouse fixture calls this so one test's
    daemon (or its degradation story) cannot leak into the next."""
    for srv in list(_live_servers):
        try:
            srv.stop()
        # drlint: ok[R5] between-test teardown of a leaked server: a failing stop must not mask the test that leaked it
        except Exception:  # pragma: no cover - teardown best effort
            pass
    for m in _MARKERS:
        os.environ.pop(m, None)


class Server:
    """The resident daemon.  ``start()`` refuses/takes over the socket,
    claims the backend ONCE, and serves until ``stop()`` (or a client
    ``shutdown`` op).  In-process use (tests, bench)::

        srv = Server(path).start()
        try:
            with Client(path) as c:
                c.scale(x, a=2.0)
        finally:
            srv.stop()
    """

    def __init__(self, socket_path=None, *, queue_depth=None,
                 tenant_cap=None, batch_max=None, batch_window=None,
                 init_timeout=None, flush_deadline=None, cpu=False,
                 state_dir=None):
        self.path = socket_path or default_socket_path()
        # crash-safe resident-state journal (SPEC §20.4): armed by a
        # state directory (kwarg or DR_TPU_SERVE_STATE_DIR); None =
        # resident state stays process-memory-only, as before
        self.state_dir = (env_str("DR_TPU_SERVE_STATE_DIR") or None
                          if state_dir is None else str(state_dir))
        self._journal = None
        self._journal_errors = 0
        # graceful drain (SPEC §20.3)
        self._draining = threading.Event()
        self.drain_timeout = env_float("DR_TPU_SERVE_DRAIN_TIMEOUT",
                                       30.0)
        self._drains = 0
        self._drain_rejects = 0
        #: replies mid-write (dispatch thread): the drain gate must
        #: cover the reply send too — the queue slot releases BEFORE
        #: the reply hits the wire, and a drain that stopped in that
        #: window would tear the very reply it waited for
        self._finishing = 0
        #: the REQUESTED route, pinned at construction and persisted
        #: next to the degraded route (SPEC §16.6): a daemon started
        #: with --cpu asked for the CPU claim — the grow supervisor
        #: must never probe it for a device-route re-promotion
        self.cpu_requested = bool(cpu)
        self.requested_route = "cpu" if cpu else "device"
        self._route = None
        self._orig_platforms = None
        self._grow_sup = None
        #: mesh size before the FIRST shrink of the current degraded
        #: episode: a grow-back clears the degraded flag only once the
        #: claim is back to this size — a PARTIAL recovery must not
        #: report a healthy claim (the module supervisor keeps probing
        #: for the stragglers)
        self._pre_shrink_nprocs = None
        self.queue_depth = (env_int("DR_TPU_SERVE_QUEUE_DEPTH", 64)
                            if queue_depth is None else int(queue_depth))
        self.tenant_cap = (env_int("DR_TPU_SERVE_TENANT_CAP", 8)
                           if tenant_cap is None else int(tenant_cap))
        self.batch_max = (env_int("DR_TPU_SERVE_BATCH_MAX", 16)
                          if batch_max is None else int(batch_max))
        self.batch_window = (env_float("DR_TPU_SERVE_BATCH_WINDOW", 0.002)
                             if batch_window is None
                             else float(batch_window))
        self.init_timeout = (env_float("DR_TPU_SERVE_INIT_TIMEOUT", 420.0)
                             if init_timeout is None
                             else float(init_timeout))
        self.flush_deadline = (env_float("DR_TPU_SERVE_FLUSH_DEADLINE",
                                         120.0)
                               if flush_deadline is None
                               else float(flush_deadline))
        self.default_deadline = env_float("DR_TPU_SERVE_DEADLINE", 30.0)
        # serving data plane (docs/SPEC.md §19): the shared-memory
        # arena (created at start; None = inline-wire only) and the
        # per-tenant resident container cache
        self.arena_min = env_int("DR_TPU_SERVE_ARENA_MIN_BYTES",
                                 1 << 16)
        self._arena = None
        self._resident = ResidentCache()
        self._queue = AdmissionQueue(self.queue_depth, self.tenant_cap)
        self._stop = threading.Event()
        self._stopped = threading.Event()
        self._stop_lock = threading.Lock()
        self._hold = threading.Event()  # test/bench hook: park dispatch
        self._sock = None
        self._bound = False
        self._threads = []
        self._conns: "weakref.WeakSet" = weakref.WeakSet()
        self._lock = threading.Lock()
        self.degraded = None
        self.devices = None
        # counters
        self._requests = 0
        self._replies = 0
        self._errors = 0
        self._cancelled = 0
        self._accept_drops = 0
        self._flushes = 0
        self._batched = 0
        self._batch_hw = 0
        self._restarts = 0
        self._shrinks = 0
        self._grows = 0

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "Server":
        # socket refusal, then BIND, then claim: holding the socket
        # before the (minutes-long on a tunneled backend) claim closes
        # the window where a second daemon sees no socket and races
        # the device claim; daemon_alive treats bound-but-claiming as
        # alive, so the newcomer still refuses classified
        self._refuse_or_takeover()
        # the shared-memory arena (docs/SPEC.md §19.1) is pure host
        # state: created before the claim, destroyed at stop.  A host
        # without usable shared memory degrades to the inline wire —
        # the arena is an optimization, never a dependency.
        if env_int("DR_TPU_SERVE_ARENA", 1, floor=0):
            try:
                self._arena = _arena.Arena()
            except Exception as e:
                warn_fallback("serve", f"shared-memory arena "
                                       f"unavailable ({e!r}); serving "
                                       "on the inline wire only")
                self._arena = None
        self._bind()
        if self.state_dir:
            # journal ownership rides socket ownership (SPEC §20.4):
            # the generation bump happens right after the bind so a
            # stale daemon that lost the takeover is fenced from the
            # state the moment the new owner holds the socket
            try:
                self._journal = _journal.Journal(self.state_dir,
                                                 self.path)
                self._journal.claim()
            except (OSError, resilience.ResilienceError) as e:
                # an unwritable state dir degrades DURABILITY, never
                # the daemon (SPEC §20.4)
                self._journal = None
                self._journal_errors += 1
                warn_fallback("serve", f"resident journal unavailable "
                                       f"({e}); serving without "
                                       "resident durability")
        try:
            self._claim()
            self._replay_journal()
        except BaseException:
            self.stop()  # a failed claim must release the socket
            raise
        self._stop.clear()
        self._draining.clear()
        self._stopped.clear()
        for name, fn in (("serve-accept", self._accept_loop),
                         ("serve-dispatch", self._dispatch_loop)):
            t = threading.Thread(target=fn, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        _live_servers.add(self)
        return self

    def _refuse_or_takeover(self) -> None:
        if not os.path.exists(self.path):
            return
        if daemon_alive(self.path):
            raise resilience.ProgramError(
                f"serve: another daemon is already serving on "
                f"{self.path} — refusing to race its device claim",
                site="serve.accept")
        # dead daemon's leftover: announce and take the socket over
        warn_fallback("serve", "stale socket file taken over "
                               "(previous daemon died uncleanly)")
        os.unlink(self.path)

    def _claim(self) -> None:
        """Claim the backend ONCE: probe_devices under the deadline
        watchdog, with the shared dead-relay → CPU degradation route.
        The pre-claim platform is remembered so a later route
        re-promotion (SPEC §16.6) knows which platform to re-probe."""
        import jax
        import dr_tpu
        self._orig_platforms = \
            str(getattr(jax.config, "jax_platforms", "") or "")
        if self.cpu_requested:
            jax.config.update("jax_platforms", "cpu")
        devs, degraded = resilience.first_touch_or_cpu(
            self.init_timeout, tag="serve.claim")
        dr_tpu.init(devs)
        self.devices = devs
        self._route = "cpu" if (self.cpu_requested or degraded) \
            else "device"
        if degraded:
            self._mark_degraded(f"serve: claimed on the CPU route "
                                f"({degraded})")

    def _bind(self) -> None:
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            s.bind(self.path)
        except OSError as e:
            s.close()
            raise resilience.classified(
                f"serve: cannot bind {self.path}: {e!r}",
                site="serve.accept")
        s.listen(64)
        s.settimeout(0.2)  # keep the accept loop responsive to stop()
        self._sock = s
        self._bound = True

    def stop(self) -> None:
        """Stop serving: drain nothing, break the loops, close the
        socket, publish the serve markers.  Idempotent — the FIRST
        caller performs the teardown, later callers block until it
        completes (a `shutdown` op and the __main__ exit path both
        call here; returning early mid-teardown would let the process
        exit with the socket file still on disk)."""
        if not self._stop_lock.acquire(blocking=False):
            self._stopped.wait(timeout=10.0)
            return
        try:
            if self._stopped.is_set():
                return
            self._do_stop()
            self._stopped.set()
        finally:
            self._stop_lock.release()

    def _do_stop(self) -> None:
        self._stop.set()
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:  # pragma: no cover - already closed
                pass
        # close accepted connections too: shutdown() first — a plain
        # close() does not interrupt a reader thread already blocked
        # in recv(); shutdown delivers the EOF into the in-flight read
        for cs in list(self._conns):
            cs.closed = True
            try:
                cs.sock.shutdown(socket.SHUT_RDWR)
            except OSError:  # pragma: no cover - already gone
                pass
            try:
                cs.sock.close()
            except OSError:  # pragma: no cover - already closed
                pass
        for t in self._threads:
            if t is not threading.current_thread():
                t.join(timeout=5.0)
        self._threads = []
        # data-plane teardown: the arena segment is unlinked (a dead
        # daemon must not leak /dev/shm) and the resident cache is
        # dropped so its containers release device memory
        if self._arena is not None:
            self._arena.destroy()
            self._arena = None
        self._resident.clear()
        if self._bound:
            # only the daemon that BOUND the socket may unlink it: a
            # stop() after a refused start (the bench/tests
            # try/finally shape) must not delete the LIVE incumbent's
            # socket — that would re-open the claim race the refusal
            # exists to prevent
            self._bound = False
            try:
                if os.path.exists(self.path):
                    os.unlink(self.path)
            except OSError:  # pragma: no cover - teardown best effort
                pass
            self._publish_markers()
        _live_servers.discard(self)

    def drain(self, timeout=None, *, _fire=True) -> None:
        """Graceful drain (docs/SPEC.md §20.3; the ``drain`` wire op
        and the ``__main__`` SIGTERM handler land here): stop
        admitting — new compute requests are rejected with the
        classified ``ServerDraining`` a routed client treats as its
        re-hash-now signal — finish the in-flight batches, flush the
        resident-state journal (appends are fsync'd, so there is
        nothing left to lose), publish the markers, and stop.
        Bounded by ``timeout`` (default ``DR_TPU_SERVE_DRAIN_TIMEOUT``):
        a wedged batch must not pin the restart forever — on expiry
        ``stop()`` cancels whatever remains.  Idempotent: a second
        caller waits for the first drain to complete.  ``_fire=False``
        skips the fault fire — for callers (the wire op) that already
        fired it synchronously to deliver a classified rejection."""
        if _fire:
            _faults.fire("serve.drain", path=self.path)
        if self._draining.is_set() or self._stopped.is_set():
            self._stopped.wait(self.drain_timeout if timeout is None
                               else float(timeout))
            return
        self._draining.set()
        self._drains += 1
        os.environ["_DR_TPU_SERVE_DRAINS"] = \
            str(env_int("_DR_TPU_SERVE_DRAINS", 0, floor=0) + 1)
        _obs.event("serve.drain", cat="serve", path=self.path)
        deadline = time.monotonic() + (self.drain_timeout
                                       if timeout is None
                                       else float(timeout))
        while time.monotonic() < deadline:
            if self._queue.idle() and not self._finishing:
                break
            time.sleep(0.005)
        self.stop()

    def draining(self) -> bool:
        return self._draining.is_set()

    # ------------------------------------------------- resident journal
    def _replay_journal(self) -> None:
        """Replay the resident-state journal into the cache (SPEC
        §20.4): a drained or SIGKILLed replica comes back serving its
        tenants' residents bit-equal, then the journal compacts to
        the live set (atomic rewrite).  A torn tail truncates cleanly
        inside ``Journal.replay`` (marker published); any classified
        replay failure degrades to an EMPTY resident cache — a
        corrupt journal must never brick the daemon."""
        if self._journal is None:
            return
        try:
            live = self._journal.replay()
            for (tenant, name), (_tag, payload) in live.items():
                arr = _journal.decode_payload(payload)
                self._resident.put(tenant, name, arr)
        except (OSError, resilience.ResilienceError) as e:
            self._journal_errors += 1
            # entries replayed BEFORE the failure must not linger: a
            # partial resident set served as if whole is a silent
            # wrong answer — empty is the honest state
            self._resident.clear()
            warn_fallback("serve", f"resident journal replay failed "
                                   f"({e}); starting with an empty "
                                   "resident cache")
            return
        try:
            self._journal.compact(live)
        except (OSError, resilience.ResilienceError) as e:
            # compaction failed AFTER a complete replay: compact is
            # atomic temp+replace, so the old journal is intact on
            # disk and the replayed residents are whole — keep them
            self._journal_errors += 1
            warn_fallback("serve", f"resident journal compaction "
                                   f"failed ({e}); replayed residents "
                                   "kept, journal left as-is")
        if self._journal.replayed:
            os.environ["_DR_TPU_SERVE_JOURNAL_RECOVERED"] = \
                str(self._journal.replayed)
        if self._journal.truncated_bytes:
            os.environ["_DR_TPU_SERVE_JOURNAL_TRUNCATED"] = \
                str(self._journal.truncated_bytes)
            warn_fallback("serve", "resident journal tail was torn "
                                   f"({self._journal.truncated_bytes} "
                                   "bytes truncated); every record "
                                   "before the tear replayed")
        _obs.event("serve.journal.replay", cat="serve",
                   entries=self._journal.replayed,
                   truncated=self._journal.truncated_bytes)

    def _journal_put(self, tenant: str, name: str, entry, arr) -> None:
        """Journal one resident put (SPEC §20.4).  A generation-fence
        violation is fatal — the stale daemon stops serving and the
        classified error reaches the requesting client; any other
        journal failure degrades DURABILITY (warned, counted), never
        the request."""
        jr = self._journal
        if jr is None or jr.has(tenant, name, entry.tag):
            return
        try:
            jr.append("put", tenant, name, entry.tag,
                      _arena.npy_bytes(np.ascontiguousarray(
                          np.asarray(arr, np.float32))))
        except (OSError, resilience.ResilienceError) as e:
            self._journal_fail(e)

    def _journal_drop(self, tenant: str, name: str) -> None:
        jr = self._journal
        if jr is None:
            return
        try:
            jr.append("drop", tenant, name)
        except (OSError, resilience.ResilienceError) as e:
            self._journal_fail(e)

    def _journal_fail(self, e) -> None:
        self._journal_errors += 1
        if self._journal is not None and self._journal.fenced:
            # stale generation (SPEC §20.4): a newer daemon owns the
            # state — this daemon can never serve again.  Mark, stop
            # on a helper thread (we are ON the dispatch thread), and
            # re-raise so the requesting client sees the classified
            # error instead of a silently un-journaled put.
            self._mark_degraded(
                "serve: resident journal fenced (a newer daemon took "
                "over the socket and the state); stale daemon "
                "stopping")
            threading.Thread(target=self._fence_stop,
                             name="serve-fence-stop",
                             daemon=True).start()
            raise e
        warn_fallback("serve", f"resident journal append failed ({e});"
                               " durability degraded for this entry")

    def _fence_stop(self) -> None:
        """Stop a FENCED daemon — but only after the classified fence
        error (and anything else in flight) has hit the wire: a stop
        racing the reply write would hand the client a torn socket
        instead of the ProgramError that explains the death."""
        self._draining.set()  # a stale daemon must not admit more work
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if self._queue.idle() and not self._finishing:
                break
            time.sleep(0.005)
        self.stop()

    def wait(self, timeout=None) -> bool:
        """Block until the daemon is asked to stop (shutdown op /
        signal handler calling stop()); True when it was."""
        return self._stop.wait(timeout)

    def hold(self) -> None:
        """Park the dispatcher (requests queue but nothing executes) —
        the deterministic window the overload / shedding / batching
        tests and the bench's batch probe need."""
        self._hold.set()

    def release(self) -> None:
        self._hold.clear()

    # ------------------------------------------------------------ accepting
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            sock = self._sock
            if sock is None:
                break
            try:
                conn, _ = sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break  # listening socket closed under us: stopping
            try:
                _faults.fire("serve.accept")
            except resilience.ResilienceError:
                # classified accept fault: drop THIS connection, keep
                # serving — the client sees a classified close
                self._accept_drops += 1
                conn.close()
                continue
            cs = _Conn(conn)
            self._conns.add(cs)
            t = threading.Thread(target=self._client_loop, args=(cs,),
                                 name="serve-client", daemon=True)
            t.start()

    def _client_loop(self, cs: _Conn) -> None:
        try:
            while not self._stop.is_set():
                try:
                    header, arrays = protocol.recv_frame(cs.sock)
                except resilience.ResilienceError as e:
                    # the connection must close either way (framing is
                    # desynced), but a MALFORMED frame is the client's
                    # deterministic bug: serialize the ProgramError
                    # back first so the client does not misread the
                    # bare close as a retryable transient (§14.4)
                    if isinstance(e, resilience.ProgramError):
                        self._send(cs, protocol.error_header(e))
                    break
                except OSError:
                    break  # socket closed under us (stop() teardown)
                if header is None:
                    break  # clean client disconnect
                if not self._handle_frame(cs, header, arrays):
                    break
        finally:
            cs.closed = True
            with self._lock:
                pending = list(cs.pending)
            for req in pending:
                # client crash mid-request: cancel cleanly — the
                # dispatcher skips the work, the claim is untouched
                req.cancelled = True
            if pending:
                self._cancelled += len(pending)
            if self._arena is not None:
                # a crashed client's leases (request slots it never
                # sent, reply slots it never released) free wholesale
                self._arena.release_owner(cs)
            try:
                cs.sock.close()
            except OSError:  # pragma: no cover - already closed
                pass

    def _arena_required(self):
        if self._arena is None:
            raise resilience.TransientBackendError(
                "serve: this daemon runs without a shared-memory "
                "arena — use the inline wire", site="arena.map")
        return self._arena

    def _merge_operands(self, cs: _Conn, header: dict, arrays,
                        tenant: str):
        """Assemble a request's logical operand list from the three
        transports (docs/SPEC.md §19.1-.2): inline wire payloads,
        arena handles (``header["arena"]`` — mapped, then released:
        the bytes are copied out at intake), and resident references
        (``header["refs"]`` — resolved to stubs carrying the cached
        container, so the handler skips the rebuild).  An entry tagged
        ``keep`` is mapped but NOT released — the client holds the
        lease across requests (the §19.1 slot-lease cache; safe
        because ``map`` copies the bytes out before the reply, and
        the disconnect teardown still frees the slot wholesale)."""
        entries = header.get("arena")
        if entries is not None:
            ar = self._arena_required()
            it = iter(arrays)
            wire = []
            for e in entries:
                if e is None:
                    wire.append(next(it, None))
                else:
                    wire.append(ar.map(e))
                    if not e.get("keep"):
                        ar.release(e)
            if any(w is None for w in wire):
                raise resilience.ProgramError(
                    "serve: frame carries fewer inline payloads than "
                    "its arena map declares", site="arena.map")
            arrays = wire
        refs = header.get("refs")
        if refs is not None:
            it = iter(arrays)
            out = []
            for r in refs:
                if r is None:
                    out.append(next(it, None))
                else:
                    out.append(ResidentStub(
                        self._resident.require(tenant, str(r))))
            if any(a is None for a in out):
                raise resilience.ProgramError(
                    "serve: frame carries fewer payloads than its "
                    "refs list declares", site="serve.request")
            arrays = out
        return arrays

    def _handle_frame(self, cs: _Conn, header: dict, arrays) -> bool:
        """One request frame; returns False to close the connection."""
        op = str(header.get("op", ""))
        rid = header.get("id")
        rel = header.get("arena_release")
        if rel:
            # piggybacked releases from the client's last reply — a
            # bad handle is the client's deterministic bug, serialized
            # back before the op can run
            try:
                ar = self._arena_required()
                for h in rel:
                    ar.release(h)
            except Exception as e:
                self._errors += 1
                self._send(cs, protocol.error_header(
                    resilience.classified(e, site="arena.release"),
                    id=rid))
                return True
        if op == "ping":
            hdr = {"ok": True, "pong": True, "pid": os.getpid(),
                   "id": rid}
            if self._arena is not None:
                hdr["arena"] = {"name": self._arena.name,
                                "size": self._arena.size}
            if self._draining.is_set():
                # health checks must see a draining daemon as NOT
                # ready (SPEC §20.3): a breaker probe that re-admitted
                # a dying replica would defeat the drain announcement
                hdr["draining"] = True
            self._send(cs, hdr)
            return True
        if op == "drain":
            # graceful drain (SPEC §20.3): the fault site fires HERE,
            # before the ack — a faulted drain must reach the caller
            # classified (§20.5), not die in the helper thread after
            # a positive acknowledgement
            try:
                _faults.fire("serve.drain", path=self.path)
            except resilience.ResilienceError as e:
                self._send(cs, protocol.error_header(e, id=rid))
                return True
            self._send(cs, {"ok": True, "draining": True, "id": rid})
            threading.Thread(target=lambda: self.drain(_fire=False),
                             name="serve-drain", daemon=True).start()
            return False
        if op == "stats":
            self._send(cs, {"ok": True, "stats": self.stats(),
                            "id": rid})
            return True
        if op == "arena_alloc":
            try:
                ar = self._arena_required()
                sizes = (header.get("params") or {}).get("nbytes", [])
                slots = []
                try:
                    for nb in sizes:
                        slots.append(ar.alloc(int(nb), owner=cs))
                except BaseException:
                    for h in slots:  # all-or-nothing lease
                        ar.release(h)
                    raise
                self._send(cs, {"ok": True, "id": rid, "slots": slots})
            except Exception as e:
                self._errors += 1
                self._send(cs, protocol.error_header(
                    resilience.classified(e, site="arena.map"),
                    id=rid))
            return True
        if op == "arena_release":
            try:
                ar = self._arena_required()
                handles = (header.get("params") or {}).get("handles",
                                                           [])
                for h in handles:
                    ar.release(h)
                self._send(cs, {"ok": True, "id": rid,
                                "released": len(handles)})
            except Exception as e:
                self._errors += 1
                self._send(cs, protocol.error_header(
                    resilience.classified(e, site="arena.release"),
                    id=rid))
            return True
        if op == "shutdown":
            self._send(cs, {"ok": True, "stopping": True, "id": rid})
            threading.Thread(target=self.stop, name="serve-stop",
                             daemon=True).start()
            return False
        req = None
        try:
            _faults.fire("serve.request", op=op)
            if self._draining.is_set():
                # admission is closed: reject with the typed drain
                # signal — a routed client re-hashes the tenant onto
                # a live replica BEFORE this daemon dies (§20.3)
                self._drain_rejects += 1
                raise resilience.ServerDraining(
                    f"serve: daemon on {self.path} is draining — "
                    "re-route this tenant to a live replica",
                    site="serve.request")
            spec = OPS.get(op)
            if spec is None:
                raise resilience.ProgramError(
                    f"serve: unknown op {op!r} (known: "
                    f"{', '.join(sorted(OPS))})", site="serve.request")
            tenant = str(header.get("tenant", "default"))
            arrays = self._merge_operands(cs, header, arrays, tenant)
            if len(arrays) != spec.narrays:
                raise resilience.ProgramError(
                    f"serve: op {op!r} takes {spec.narrays} array(s), "
                    f"got {len(arrays)}", site="serve.request")
            deadline = header.get("deadline_s", self.default_deadline)
            req = Request(op, header.get("params"), arrays,
                          tenant=tenant,
                          deadline_s=(None if deadline is None
                                      else float(deadline)), rid=rid)
            req.server = self
            req.arena_ok = bool(header.get("arena_ok")) \
                and self._arena is not None
            if spec.validate is not None:
                spec.validate(req)
            req.conn = cs
            # the request's obs span opens at intake (reader thread)
            # and closes in _finish (dispatch thread); the flow start
            # lets the exporter draw the arrow into the batch-flush
            # span it lands in.  span stays 0 while tracing is off.
            req.span = _obs.begin("serve.request", cat="serve", op=op,
                                  tenant=req.tenant, rid=str(rid))
            _obs.flow(req.span, "s")
            with self._lock:
                cs.pending.add(req)
            self._queue.submit(req)
            self._requests += 1
        except Exception as e:
            # classified and serialized back — never kills the daemon
            ce = resilience.classified(e, site="serve.request")
            if req is not None:
                with self._lock:
                    cs.pending.discard(req)
                _obs.end(req.span, error=type(ce).__name__)
                req.span = 0
            self._errors += 1
            self._send(cs, protocol.error_header(ce, id=rid))
        return True

    # ----------------------------------------------------------- dispatching
    def _dispatch_loop(self) -> None:
        while not self._stop.is_set():
            live = []
            try:
                live, dropped = self._queue.take_batch(
                    self.batch_max, self.batch_window, stop=self._stop,
                    paused=self._hold)
                for req in dropped:
                    if req.cancelled:
                        self._queue.release(req)
                        # no reply is owed, but the obs span opened at
                        # intake must still close — a traced daemon
                        # with client churn would otherwise grow the
                        # open-span table without bound
                        _obs.end(req.span, error="cancelled")
                        req.span = 0
                        continue
                    self._finish(req, error=resilience.DeadlineExpired(
                        f"serve: request {req.op!r} expired after "
                        "queueing — shed before dispatch",
                        site="serve.request"))
                if not live:
                    continue
                fusible = [r for r in live
                           if OPS.get(r.op) and OPS[r.op].batchable]
                solo = [[r] for r in live
                        if not (OPS.get(r.op) and OPS[r.op].batchable)]
                for group in ([fusible] if fusible else []) + solo:
                    self._exec_group(group)
                # BETWEEN batches — the only moment the dispatch
                # thread provably owns no in-flight device work — poll
                # the grow supervisors (SPEC §16.6): route
                # re-promotion for a CPU-degraded claim, mesh grow-back
                # for a shrunken one.  Never raises, cheap when off.
                self._maybe_promote()
            except Exception as e:  # the dispatcher must never die: a
                # dead dispatch loop turns every later request into a
                # silent hang — fail what we hold, classified, and
                # keep serving
                ce = resilience.classified(e, site="serve.flush")
                for req in live:
                    if req.result is None and req.error is None:
                        self._finish(req, error=ce)

    def _exec_group(self, group, already_degraded=False) -> None:
        """Execute one compatible group: batchable ops coalesce into a
        single deferred-plan flush; errors are classified per the
        failure matrix (SPEC §14.4)."""
        import dr_tpu
        batchable = OPS[group[0].op].batchable
        # first execution of each request: stamp the dispatch start
        # and sample queue-wait (a degrade / poison-pill REPLAY keeps
        # the original stamp and must not re-observe), emitting the
        # retroactive queue-wait span under the request's span
        t_exec = time.monotonic()
        for req in group:
            if req.t_exec is None:
                req.t_exec = t_exec
                qw_ms = (t_exec - req.t_submit) * 1e3
                _h_queue_wait.observe(qw_ms)
                _h_tenant("queue_wait", req.tenant).observe(qw_ms)
                if req.span:
                    _obs.complete("serve.queue_wait", req.t0_ns,
                                  cat="serve", parent=req.span)

        def run():
            # the injection site fires INSIDE the retried body: a
            # transient here recovers on the retry leg, in process
            _faults.fire("serve.flush", ops=len(group))
            finishers = []
            if batchable:
                with dr_tpu.deferred():
                    for r in group:
                        finishers.append(OPS[r.op].handler(r))
                # region exited: the whole group flushed as ONE plan
            else:
                for r in group:
                    finishers.append(OPS[r.op].handler(r))
            return [f() for f in finishers]

        # the shared batch-flush span: every member request's span is
        # linked (args.links + flow finish events), so one client
        # request's trace tree reaches the fused dispatch it rode
        fid = _obs.begin("serve.batch_flush", cat="serve",
                         requests=len(group), batchable=batchable,
                         links=[r.span for r in group if r.span])
        for r in group:
            _obs.flow(r.span, "f")
        t_flush = time.monotonic()
        # a DeviceLostError inside the retried body triggers the
        # elastic shrink (resilience.retry, DR_TPU_ELASTIC=1; SPEC
        # §16): the batch REPLAYS on the shrunken mesh — handlers
        # rebuild their containers — and no client is dropped.  The
        # counter diff below turns a mid-batch shrink into the serve
        # chapter of the degradation story.
        shrinks0 = _elastic.shrink_count()
        grows0 = _elastic.grow_count()
        nprocs0 = dr_tpu.nprocs()
        try:
            try:
                results = resilience.with_deadline(
                    lambda: resilience.retry(run, attempts=2, base=0.01,
                                             seed=0),
                    self.flush_deadline, site="serve.flush", dump=False)
            finally:
                # sample EVERY flush, failures and deadline overruns
                # included — the slowest flushes are exactly the ones
                # that fail, and excluding them would bias the
                # reported percentiles low
                _h_flush.observe((time.monotonic() - t_flush) * 1e3)
                _obs.end(fid)
                # shrink detection lives HERE, not on the success
                # path: a shrink whose REPLAY then fails (deadline,
                # deterministic error) still changed the resident
                # claim and must land in stats/markers — and the
                # recursive replay paths below each re-sample, so a
                # shrink is counted exactly once
                shrunk = _elastic.shrink_count() - shrinks0
                if shrunk:
                    import dr_tpu
                    self._shrinks += shrunk
                    self.devices = dr_tpu.devices()
                    if self._pre_shrink_nprocs is None:
                        self._pre_shrink_nprocs = nprocs0
                    self._mark_degraded(
                        f"serve: device loss mid-batch; resident "
                        f"claim degraded to the {dr_tpu.nprocs()}"
                        "-device shrunken mesh")
                # the symmetric diff (SPEC §16.6): a grow-back riding
                # this batch's deferred-region exit (the elastic module
                # supervisor) changed the resident claim too
                grown = _elastic.grow_count() - grows0
                if grown:
                    import dr_tpu
                    self._grows += grown
                    self.devices = dr_tpu.devices()
                    self._note_grown()
            self._flushes += 1
            if batchable:
                self._batched += len(group)
                self._batch_hw = max(self._batch_hw, len(group))
            for req, res in zip(group, results):
                self._finish(req, result=res)
        except (resilience.RelayDownError, resilience.DeadlineExpired) \
                as e:
            if isinstance(e, resilience.DeadlineExpired) \
                    and not resilience.dead_relay():
                # the batch overran the watchdog but the relay is
                # ALIVE (slow compile, not a dead backend): its
                # abandoned worker thread may still be dispatching, so
                # replaying — or re-initing the runtime under it —
                # would race the one-dispatch-owner invariant.  Fail
                # the batch classified instead.
                ce = resilience.classified(e, site="serve.flush")
                for req in group:
                    self._finish(req, error=ce)
                return
            if already_degraded:
                ce = resilience.classified(e, site="serve.flush")
                for req in group:
                    self._finish(req, error=ce)
                return
            try:
                self._degrade(e)
            except resilience.ResilienceError as de:
                for req in group:
                    self._finish(req, error=de)
                return
            # the watchdog re-routed the claim: replay the batch once
            # on the degraded mesh (handlers rebuild their containers)
            self._exec_group(group, already_degraded=True)
        except Exception as e:
            ce = resilience.classified(e, site="serve.flush")
            if isinstance(ce, resilience.ProgramError) and len(group) > 1:
                # poison-pill isolation: a deterministic error in ONE
                # request must not fail its batchmates — re-run each
                # request alone so only the culprit sees the error
                for req in group:
                    self._exec_group([req],
                                     already_degraded=already_degraded)
                return
            for req in group:
                self._finish(req, error=ce)

    def _degrade(self, err) -> None:
        """Relay died mid-session: degrade the resident claim to the
        CPU route through the SHARED degradation router and keep
        serving — the daemon outlives its backend."""
        import jax
        import dr_tpu
        warn_fallback("serve", "relay died mid-session; daemon "
                               "degrading to the CPU route")
        jax.config.update("jax_platforms", "cpu")
        ft = resilience.route_first_touch(self.init_timeout,
                                          retried=True)
        if ft.decision != "ok":
            raise resilience.classified(
                f"serve: CPU degrade failed after relay death "
                f"({ft.err}); original: {err}", site="serve.flush")
        dr_tpu.init(ft.devices)
        self.devices = ft.devices
        self._restarts += 1
        self._route = "cpu"
        # each fresh degradation re-arms the full re-promotion probe
        # budget (the supervisor is passive — polled between batches)
        self._grow_sup = None
        self._mark_degraded(
            f"serve: relay died mid-session ({type(err).__name__}: "
            f"{err}); daemon restarted on the CPU route")

    def _maybe_promote(self) -> None:
        """Grow-back supervisor poll, BETWEEN batches on the dispatch
        thread (docs/SPEC.md §16.6).  Two recoveries ride here:

        * **mesh grow-back** — a session the elastic layer shrank
          polls the module supervisor for returned devices
          (``elastic.maybe_grow``, also reached at each batch's
          deferred-region exit);
        * **route re-promotion** — a claim degraded to the CPU route
          by relay death re-probes the REQUESTED route through this
          daemon's own bounded-backoff supervisor and re-promotes
          without dropping clients.

        Structural no-op for a CPU-REQUESTED daemon (``--cpu``): the
        requested route is pinned at construction, so a claim the
        operator asked to keep on CPU is never probed.  Never raises
        — a failed probe/grow leaves the session exactly where it was
        (classified, warned, backed off)."""
        rep = _elastic.maybe_grow()
        if rep is not None:
            import dr_tpu
            self._grows += 1
            self.devices = dr_tpu.devices()
            self._note_grown()
        if (self.cpu_requested or self._route != "cpu"
                or not _elastic.grow_enabled()
                # an unknown pre-claim platform (unset/auto) cannot be
                # re-probed honestly: route_first_touch would probe
                # whatever platform is current — the CPU mesh we just
                # degraded to — and report a false re-promotion
                or not self._orig_platforms):
            return
        if self._grow_sup is None:
            self._grow_sup = _elastic.GrowSupervisor()
        rep = self._grow_sup.poll(self._promote_attempt)
        if rep is not None:
            import dr_tpu
            self._grows += 1
            self._route = "device"
            self.devices = dr_tpu.devices()
            self.degraded = None
            self._pre_shrink_nprocs = None
            warn_fallback(
                "serve",
                f"relay recovered; resident claim re-promoted to the "
                f"{dr_tpu.nprocs()}-device route "
                f"(probe {self._grow_sup.probes}/"
                f"{self._grow_sup.budget})")

    def _promote_attempt(self):
        """One re-promotion probe of the REQUESTED route (the
        supervisor's attempt callable).  Fires ``device.recover``;
        restores the pre-claim platform and routes the first touch
        again — a still-dead relay is the cheap TCP fast path (None:
        not recovered yet, back off); a live one re-claims through
        ``elastic.grow_session`` (fault site ``mesh.grow``, container
        moves, grow markers).  On ANY failure the platform flips back
        to the CPU route before the classified error reaches the
        supervisor — the session keeps serving where it was."""
        import jax
        _faults.fire("device.recover", route="serve")
        jax.config.update("jax_platforms", self._orig_platforms or "cpu")
        ok = False
        try:
            ft = resilience.route_first_touch(self.init_timeout)
            if ft.decision != "ok":
                return None  # requested route still down: back off
            rep = _elastic.grow_session(
                devices=ft.devices, require_growth=False,
                reason="serve: relay recovered; resident claim "
                       "re-promoted to the device route")
            ok = True
            return rep
        finally:
            if not ok:
                jax.config.update("jax_platforms", "cpu")

    def _note_grown(self) -> None:
        """A mesh grow-back landed: clear the degraded flag only once
        the claim is back to its PRE-SHRINK size — a partial recovery
        (one of two lost devices returned) must keep reporting
        degraded while the module supervisor probes for the
        stragglers.  A claim still on the CPU route stays degraded
        regardless (the route promotion path owns that flag)."""
        import dr_tpu
        if self._route == "cpu":
            return
        if self._pre_shrink_nprocs is not None and \
                dr_tpu.nprocs() < self._pre_shrink_nprocs:
            return
        self._pre_shrink_nprocs = None
        self.degraded = None

    # ------------------------------------------------------------- replies
    def _finish(self, req: Request, result=None, error=None) -> None:
        self._finishing += 1
        try:
            self._finish_inner(req, result, error)
        finally:
            self._finishing -= 1

    def _finish_inner(self, req: Request, result=None,
                      error=None) -> None:
        self._queue.release(req)
        req.finish(result=result, error=error)
        if req.t_exec is not None:
            # service = dispatch start → reply posted (shed requests
            # never executed, so they carry no service sample)
            sv_ms = (time.monotonic() - req.t_exec) * 1e3
            _h_service.observe(sv_ms)
            _h_tenant("service", req.tenant).observe(sv_ms)
        if req.span:
            _obs.event("serve.reply", cat="serve", parent=req.span,
                       rid=str(req.rid),
                       outcome=(type(error).__name__ if error
                                else "ok"))
            _obs.end(req.span,
                     **({"error": type(error).__name__} if error
                        else {}))
            req.span = 0
        if error is not None:
            self._errors += 1
        cs = req.conn
        if cs is None:
            return  # direct in-process submit: the waiter reads slots
        with self._lock:
            cs.pending.discard(req)
        if req.cancelled or cs.closed:
            return
        if error is not None:
            self._send(cs, protocol.error_header(error, id=req.rid))
        else:
            extra, arrays = result
            hdr = {"ok": True, "id": req.rid, **extra}
            staged: list = []
            arrays = self._stage_reply(req, hdr, arrays, staged)
            self._send(cs, hdr, arrays)
            if staged and cs.closed:
                # the connection died between the closed-check above
                # and the send: its disconnect teardown may have run
                # release_owner BEFORE our put landed, so the staged
                # slots would leak — release them here; whichever
                # party ran second wins, the other reads "stale"
                ar = self._arena
                for h in staged:
                    try:
                        if ar is not None:
                            ar.release(h)
                    except resilience.ResilienceError:
                        pass  # the teardown's release won the race

    def _stage_reply(self, req: Request, hdr: dict, arrays,
                     staged: list):
        """Route reply payloads through the arena when the client
        accepts it (``arena_ok``) and the payload clears the
        ``DR_TPU_SERVE_ARENA_MIN_BYTES`` floor; small results and an
        exhausted arena stay on the inline wire (graceful — §19.1).
        Reply slots are owned by the client's connection: released by
        its next frame's piggyback, or wholesale at disconnect."""
        if not (req.arena_ok and self._arena is not None and arrays):
            return arrays
        entries, inline, used = [], [], False
        for a in arrays:
            a = np.asarray(a)
            if a.nbytes >= self.arena_min:
                try:
                    h = self._arena.put(_arena.npy_bytes(a),
                                        owner=req.conn)
                    entries.append(h)
                    staged.append(h)
                    used = True
                    continue
                except resilience.TransientBackendError:
                    _arena.note_fallback(
                        "reply arena exhausted; inline wire")
            entries.append(None)
            inline.append(a)
        if not used:
            return arrays
        hdr["arena_results"] = entries
        return inline

    def _send(self, cs: _Conn, header: dict, arrays=()) -> None:
        try:
            with cs.lock:
                # bound the write: a live-but-not-reading client whose
                # reply overflows the socket buffer must not pin the
                # ONE dispatch thread forever (every other tenant
                # would hang un-shed).  Per-operation timeout; restored
                # so the reader thread's recv stays blocking.
                cs.sock.settimeout(self.default_deadline)
                try:
                    protocol.send_frame(cs.sock, header, arrays)
                finally:
                    cs.sock.settimeout(None)
            if header.get("ok"):
                self._replies += 1
        except OSError:
            # client vanished (or stopped reading) between dispatch
            # and reply: cancel cleanly; the resident claim is
            # untouched.  socket.timeout is an OSError.  Close the
            # socket too so the reader thread unblocks.
            cs.closed = True
            self._cancelled += 1
            try:
                cs.sock.close()
            except OSError:  # pragma: no cover - already closed
                pass

    # ------------------------------------------------------------- stories
    def stats(self) -> dict:
        q = self._queue.stats()
        extra = {}
        if self._arena is not None:
            extra["arena"] = self._arena.stats()
        extra["resident"] = self._resident.stats()
        if self._journal is not None:
            extra["journal"] = {**self._journal.stats(),
                                "errors": self._journal_errors}
        return {"requests": self._requests, "replies": self._replies,
                **extra,
                "errors": self._errors, "cancelled": self._cancelled,
                "accept_drops": self._accept_drops,
                "flushes": self._flushes,
                "batched_requests": self._batched,
                "batch_hw": self._batch_hw,
                "restarts": self._restarts,
                "shrinks": self._shrinks,
                "grows": self._grows,
                "drains": self._drains,
                "draining": self._draining.is_set(),
                "drain_rejects": self._drain_rejects,
                "route": {"requested": self.requested_route,
                          "current": self._route},
                "degraded": self.degraded,
                # the obs metrics snapshot rides the stats wire op
                # (SPEC §15): the daemon-side queue-wait / service /
                # flush histograms, counters, and dispatch counts —
                # JSON-serializable by construction
                "obs": _obs.snapshot(), **q}

    def _mark_degraded(self, reason: str) -> None:
        self.degraded = reason
        self._publish_markers()

    def _publish_markers(self) -> None:
        """Publish the serve chapter of the degradation story as env
        markers (resilience.degradation_story folds them into
        detail.degraded; they survive a bench CPU-fallback re-exec the
        same way the _DR_TPU_BENCH_* markers do)."""
        if self.degraded:
            os.environ["_DR_TPU_SERVE_DEGRADED"] = self.degraded
        os.environ["_DR_TPU_SERVE_QUEUE_DEPTH"] = \
            str(self._queue.depth_hw)
        os.environ["_DR_TPU_SERVE_SHED"] = str(self._queue.shed)
        os.environ["_DR_TPU_SERVE_RESTARTS"] = str(self._restarts)
