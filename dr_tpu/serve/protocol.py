"""Wire protocol for the serving daemon: length-prefixed JSON + npy.

One frame is::

    u32 header_len (big-endian) | header (UTF-8 JSON) | payload bytes

The header's ``nbytes`` list gives the byte length of each npy payload
that follows (``numpy.save`` format, ``allow_pickle=False`` both ways —
a client must never be able to smuggle pickles into the resident
daemon).  Request headers carry ``op`` / ``params`` / ``tenant`` /
``deadline_s`` / optional ``id``; reply headers carry ``ok`` plus
either result fields (``scalar``, echoed ``id``) or a serialized
classified error.

Failure semantics: a clean EOF BETWEEN frames is a normal disconnect
(``recv_frame`` returns ``(None, None)``); EOF MID-frame is a torn
frame and raises a classified :class:`TransientBackendError` — the
wire-level analog of the torn checkpoint write.  Oversized or
malformed headers raise :class:`ProgramError` (deterministic, not
retryable).  Errors cross the wire as ``{"cls", "message", "site"}``
and :func:`raise_error` re-raises them as the matching taxonomy class,
so a client sees the SAME classified exception the daemon caught.
"""

from __future__ import annotations

import io
import json
import struct

import numpy as np

from ..utils import resilience

__all__ = ["send_frame", "recv_frame", "error_header", "raise_error",
           "MAX_HEADER", "MAX_PAYLOAD"]

#: header / single-payload byte caps: a garbage length prefix must not
#: make the daemon allocate gigabytes before the JSON parse can reject
MAX_HEADER = 1 << 20
MAX_PAYLOAD = 1 << 28


def _recv_exact(sock, n: int):
    """Exactly ``n`` bytes from ``sock``, or None on EOF at offset 0;
    a mid-read EOF raises the torn-frame transient."""
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            if not buf:
                return None
            raise resilience.TransientBackendError(
                f"serve: connection closed mid-frame ({len(buf)}/{n} "
                "bytes read — torn wire frame)", site="serve.request")
        buf += chunk
    return bytes(buf)


def send_frame(sock, header: dict, arrays=()) -> None:
    """Serialize and send one frame (header + npy payloads)."""
    payloads = []
    for a in arrays:
        bio = io.BytesIO()
        np.save(bio, np.asarray(a), allow_pickle=False)
        payloads.append(bio.getvalue())
    header = dict(header)
    header["nbytes"] = [len(p) for p in payloads]
    hb = json.dumps(header).encode("utf-8")
    if len(hb) > MAX_HEADER:
        raise resilience.ProgramError(
            f"serve: frame header is {len(hb)} bytes (cap {MAX_HEADER})",
            site="serve.request")
    sock.sendall(struct.pack(">I", len(hb)) + hb + b"".join(payloads))


def recv_frame(sock):
    """Receive one frame: ``(header, [np.ndarray, ...])``.

    ``(None, None)`` on a clean EOF before any frame byte; classified
    errors on torn/oversized/malformed frames (see module docstring).
    """
    raw = _recv_exact(sock, 4)
    if raw is None:
        return None, None
    (hlen,) = struct.unpack(">I", raw)
    if hlen == 0 or hlen > MAX_HEADER:
        raise resilience.ProgramError(
            f"serve: frame header length {hlen} outside (0, {MAX_HEADER}]",
            site="serve.request")
    hb = _recv_exact(sock, hlen)
    if hb is None:
        # EOF right after the length prefix: a torn frame (retryable
        # connection drop), NOT a malformed header
        raise resilience.TransientBackendError(
            "serve: connection closed after the length prefix "
            "(torn wire frame)", site="serve.request")
    try:
        header = json.loads(hb.decode("utf-8"))
    except Exception as e:
        raise resilience.ProgramError(
            f"serve: malformed frame header ({e!r})", site="serve.request")
    if not isinstance(header, dict):
        raise resilience.ProgramError(
            "serve: frame header is not a JSON object",
            site="serve.request")
    arrays = []
    for nb in header.get("nbytes", []):
        nb = int(nb)
        if nb <= 0 or nb > MAX_PAYLOAD:
            raise resilience.ProgramError(
                f"serve: payload length {nb} outside (0, {MAX_PAYLOAD}]",
                site="serve.request")
        blob = _recv_exact(sock, nb)
        if blob is None:
            raise resilience.TransientBackendError(
                "serve: connection closed before its declared payload "
                "(torn wire frame)", site="serve.request")
        try:
            arrays.append(np.load(io.BytesIO(blob), allow_pickle=False))
        except Exception as e:
            raise resilience.ProgramError(
                f"serve: undecodable npy payload ({e!r})",
                site="serve.request")
    return header, arrays


def error_header(err, **extra) -> dict:
    """Reply header carrying ``err`` classified for the wire."""
    ce = resilience.classified(err)
    hdr = {"ok": False,
           "error": {"cls": type(ce).__name__, "message": str(ce),
                     "site": ce.site}}
    hdr.update(extra)
    return hdr


def raise_error(header: dict):
    """Re-raise the classified error a reply header carries.  An
    unknown class name degrades to :class:`ProgramError` — the
    deterministic bucket — instead of guessing retryability."""
    info = header.get("error") or {}
    cls = getattr(resilience, str(info.get("cls", "")), None)
    if not (isinstance(cls, type)
            and issubclass(cls, resilience.ResilienceError)):
        cls = resilience.ProgramError
    raise cls(str(info.get("message", "serve: unspecified daemon error")),
              site=str(info.get("site", "")))
