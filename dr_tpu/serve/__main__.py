"""``python -m dr_tpu.serve`` — run the serving daemon foreground.

Prints ONE JSON ready line (``{"serving": <socket>, "pid": ...}``) once
the claim is held and the socket is listening, then serves until a
client ``shutdown`` op or SIGTERM/SIGINT; a start failure (double
daemon, failed claim) prints a classified error line and exits 1.

``--cpu`` forces the CPU platform via ``jax.config`` BEFORE backend
init — the env var alone is frozen by sitecustomize on this container
(CLAUDE.md), so subprocess tests and the fuzz-crank serve arm pass the
flag instead.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dr_tpu.serve",
        description="dr_tpu serving daemon (one resident device claim)")
    ap.add_argument("--socket", default=None,
                    help="Unix-domain socket path "
                         "(default: $DR_TPU_SERVE_SOCKET or the "
                         "per-uid temp path)")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU platform before backend init; "
                         "pins the REQUESTED route, so the grow "
                         "supervisor never probes this daemon for a "
                         "device-route re-promotion (docs/SPEC.md "
                         "§16.6)")
    ap.add_argument("--state-dir", default=None,
                    help="crash-safe resident-state journal directory "
                         "(docs/SPEC.md §20.4; default: "
                         "$DR_TPU_SERVE_STATE_DIR, unset = resident "
                         "state is process-memory only)")
    args = ap.parse_args(argv)
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    from ..utils import resilience
    from .daemon import Server
    srv = Server(args.socket, cpu=args.cpu, state_dir=args.state_dir)
    try:
        srv.start()
    except Exception as e:
        ce = resilience.classified(e)
        print(json.dumps({"serving": None,
                          "error": {"cls": type(ce).__name__,
                                    "message": str(ce)}}), flush=True)
        return 1
    print(json.dumps({"serving": srv.path, "pid": os.getpid()}),
          flush=True)

    def _term(signum, frame):  # pragma: no cover - signal path
        # SIGTERM is the GRACEFUL stop (SPEC §20.3): drain — stop
        # admitting, finish in-flight batches, flush the journal —
        # then exit.  On a helper thread: drain blocks up to the
        # drain timeout, and a signal handler must not.
        import threading

        def _drain():
            try:
                srv.drain()
            except resilience.ResilienceError:
                srv.stop()  # faulted drain: hard stop still exits

        threading.Thread(target=_drain, name="serve-sigterm-drain",
                         daemon=True).start()

    def _int(signum, frame):  # pragma: no cover - signal path
        srv.stop()  # SIGINT (^C): immediate stop, as before

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _int)
    srv.wait()
    srv.stop()
    print(json.dumps({"served": srv.stats()}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
