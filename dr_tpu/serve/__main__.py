"""``python -m dr_tpu.serve`` — run the serving daemon foreground.

Prints ONE JSON ready line (``{"serving": <socket>, "pid": ...}``) once
the claim is held and the socket is listening, then serves until a
client ``shutdown`` op or SIGTERM/SIGINT; a start failure (double
daemon, failed claim) prints a classified error line and exits 1.

``--cpu`` forces the CPU platform via ``jax.config`` BEFORE backend
init — the env var alone is frozen by sitecustomize on this container
(CLAUDE.md), so subprocess tests and the fuzz-crank serve arm pass the
flag instead.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dr_tpu.serve",
        description="dr_tpu serving daemon (one resident device claim)")
    ap.add_argument("--socket", default=None,
                    help="Unix-domain socket path "
                         "(default: $DR_TPU_SERVE_SOCKET or the "
                         "per-uid temp path)")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU platform before backend init; "
                         "pins the REQUESTED route, so the grow "
                         "supervisor never probes this daemon for a "
                         "device-route re-promotion (docs/SPEC.md "
                         "§16.6)")
    args = ap.parse_args(argv)
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    from ..utils import resilience
    from .daemon import Server
    srv = Server(args.socket, cpu=args.cpu)
    try:
        srv.start()
    except Exception as e:
        ce = resilience.classified(e)
        print(json.dumps({"serving": None,
                          "error": {"cls": type(ce).__name__,
                                    "message": str(ce)}}), flush=True)
        return 1
    print(json.dumps({"serving": srv.path, "pid": os.getpid()}),
          flush=True)

    def _term(signum, frame):  # pragma: no cover - signal path
        srv.stop()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    srv.wait()
    srv.stop()
    print(json.dumps({"served": srv.stats()}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
