"""Thin client for the serving daemon (dr_tpu/serve/daemon.py).

A client owns NO device claim: it speaks the length-prefixed JSON/npy
protocol over the daemon's Unix-domain socket, one request in flight
per connection (concurrency = more connections — the bench's load
generator runs one Client per worker thread).  Every failure surfaces
as a CLASSIFIED taxonomy error:

* nothing listening at the socket → ``RelayDownError`` (the daemon is
  this client's relay);
* the daemon dropped the connection / a torn reply frame / a socket
  timeout → ``TransientBackendError`` (reconnect and resubmit);
* a serialized daemon error → re-raised as the class the daemon
  caught (``ServerOverloaded``, ``DeadlineExpired``, ``DeviceOOM``,
  ``ProgramError``, …) via ``protocol.raise_error``.

Retry policy (``retries`` / ``DR_TPU_SERVE_CLIENT_RETRIES``, SPEC
§14.6): with more than one attempt armed, transient failures and
``ServerOverloaded`` rejections resubmit through the shared
seeded-backoff ``resilience.retry`` — bounded attempts, deadline-aware
(a retry that would land past the request's ``deadline_s`` is not
taken), reconnecting first when the failure invalidated the
connection.  The default is ONE attempt: an overload rejection is
information the caller may want to act on, so backoff is opt-in.
``RelayDownError`` (nothing listening) never retries — that is the
router's degrade signal, not a blip.  Every retry draws from the
process-wide shared token budget (``DR_TPU_SERVE_RETRY_BUDGET``, SPEC
§20.2): a fleet-wide failure drains the bucket once, fleet-wide, and
then fails fast classified instead of feeding a retry storm.
"""

from __future__ import annotations

import socket
import threading
from typing import Optional

import numpy as np

from ..utils import resilience
from ..utils.env import env_float, env_int
from . import arena as _arena
from . import protocol

__all__ = ["Client", "Ref", "shared_retry_budget", "reset_retry_budget"]

#: control ops that never stage payloads through the arena (they have
#: none, or they ARE the arena's own lease/release round trips)
_CONTROL_OPS = frozenset(
    ("ping", "stats", "shutdown", "drain", "arena_alloc",
     "arena_release"))

# ---------------------------------------------------------------------------
# shared retry budget (docs/SPEC.md §20.2)
# ---------------------------------------------------------------------------
# ONE bucket per process, drawn on by every Client and RouterClient
# retry: without it, per-request retries compose with the router's
# replica re-hash into an unbounded fleet-level retry multiplier — the
# storm that amplifies exactly the overload it is retrying through.

_budget_lock = threading.Lock()
_shared_budget: Optional[resilience.TokenBudget] = None


def shared_retry_budget() -> resilience.TokenBudget:
    """The process-wide retry :class:`~..utils.resilience.TokenBudget`
    (capacity ``DR_TPU_SERVE_RETRY_BUDGET``, refilled by
    ``DR_TPU_SERVE_RETRY_RATIO`` of a token per successful request)."""
    global _shared_budget
    with _budget_lock:
        if _shared_budget is None:
            _shared_budget = resilience.TokenBudget(
                env_int("DR_TPU_SERVE_RETRY_BUDGET", 8, floor=0),
                env_float("DR_TPU_SERVE_RETRY_RATIO", 0.1))
        return _shared_budget


def reset_retry_budget() -> None:
    """Drop the shared bucket (refilled lazily from env) — the
    between-test hygiene hook (serve.reset)."""
    global _shared_budget
    with _budget_lock:
        _shared_budget = None


class Ref:
    """A resident-container reference (docs/SPEC.md §19.2): pass in
    place of an array operand and the daemon substitutes the tenant's
    cached container — no payload on the wire, no container rebuild::

        c.put("features", x)
        c.reduce(Ref("features"))     # zero-copy repeat op
    """

    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = str(name)

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"Ref({self.name!r})"


class Client:
    """Synchronous connection to a serving daemon.

    ``timeout`` bounds every socket operation (default: the daemon's
    default request deadline + slack) — a wedged daemon costs a
    classified timeout, never an eternal hang."""

    def __init__(self, path: Optional[str] = None, *,
                 timeout: Optional[float] = None,
                 tenant: str = "default",
                 retries: Optional[int] = None,
                 arena: Optional[bool] = None,
                 budget: Optional[resilience.TokenBudget] = None):
        from .daemon import default_socket_path
        self.path = path or default_socket_path()
        self.tenant = tenant
        self.retries = max(1, env_int("DR_TPU_SERVE_CLIENT_RETRIES", 1)
                           if retries is None else int(retries))
        # every retry draws from ONE shared process-wide token bucket
        # (SPEC §20.2) unless the caller threads its own — the fix for
        # the per-request × per-replica retry multiplier
        self._budget = (shared_retry_budget() if budget is None
                        else budget)
        self._next_id = 0
        self._timeout = (env_float("DR_TPU_SERVE_DEADLINE", 30.0) + 10.0
                         if timeout is None else timeout)
        self._sock = None
        # shared-memory arena (docs/SPEC.md §19.1): None = auto (use
        # it when the daemon advertises one and a payload clears the
        # min-bytes floor), False = inline wire always.  Attachment is
        # lazy — a ping discovers the segment on first need.
        self._arena_want = (env_int("DR_TPU_SERVE_ARENA", 1,
                                    floor=0) != 0
                            if arena is None else bool(arena))
        self._arena_min = env_int("DR_TPU_SERVE_ARENA_MIN_BYTES",
                                  1 << 16)
        self._arena: Optional[_arena.ClientArena] = None
        self._arena_state = "unknown"  # unknown | on | off
        self._pending_release: list = []
        # slot-lease cache (docs/SPEC.md §19.1): granted request
        # leases are KEPT across requests (the ``keep`` wire marker)
        # and reused for same-shape payloads — the per-request
        # ``arena_alloc`` round trip disappears on steady traffic.
        # Keyed by the lease's aligned byte capacity; bounded by
        # DR_TPU_SERVE_LEASE_CACHE slots (0 disables), excess leases
        # release by piggyback.  The cache drops whenever the
        # connection does (the daemon's disconnect teardown frees the
        # owner's slots, so a held handle's generation may bump) and
        # on any ``arena.map``-classified reply (a stale-generation
        # handle must never be offered twice).
        self._lease_cap = env_int("DR_TPU_SERVE_LEASE_CACHE", 8,
                                  floor=0)
        self._lease_cache: dict = {}
        self.lease_hits = 0
        self.lease_misses = 0
        self._connect()
        if arena:  # explicit opt-in attaches eagerly (big REPLIES
            # can ride the arena even when no request payload does)
            self._ensure_arena()

    def _connect(self) -> None:
        """(Re)open the daemon connection; classified on failure.  A
        refused/absent socket is ``RelayDownError`` — the daemon is
        this client's relay, and retrying a dead one burns budget."""
        self._broken = None  # set to a reason once the conn desyncs
        # reply slots owed from the OLD connection free at the
        # daemon's disconnect teardown — releasing them on a fresh
        # connection would double-free a recycled slot
        self._pending_release = []
        # held request leases died with the old connection too (owner
        # teardown freed them; the slot ids may already be re-leased
        # at a bumped generation)
        self._lease_cache = {}
        # re-arm arena discovery: a reconnect after an invalidation
        # (whose close() detached the segment) must not leave a
        # long-lived retrying client on the inline wire forever
        if self._arena is None:
            self._arena_state = "unknown"
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.settimeout(self._timeout)
        try:
            self._sock.connect(self.path)
        except (ConnectionRefusedError, FileNotFoundError) as e:
            self._sock.close()
            self._sock = None
            raise resilience.RelayDownError(
                f"serve: no daemon listening at {self.path} ({e!r})",
                site="serve.request")
        except OSError as e:
            self._sock.close()
            self._sock = None
            raise resilience.classified(
                f"serve: cannot connect to {self.path}: {e!r}",
                site="serve.request")

    # ------------------------------------------------------------- plumbing
    def __enter__(self) -> "Client":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def close(self) -> None:
        # held leases die with the connection (owner teardown)
        self._lease_cache = {}
        if self._arena is not None:
            self._arena.close()
            self._arena = None
            self._arena_state = "off"
        if self._sock is None:
            return
        try:
            self._sock.close()
        except OSError:  # pragma: no cover - already closed
            pass

    def _invalidate(self, reason: str) -> None:
        self._broken = reason
        self.close()

    def request(self, op: str, arrays=(), params: Optional[dict] = None,
                *, deadline_s: Optional[float] = None,
                tenant: Optional[str] = None):
        """One request/reply round trip.  Returns the scalar result,
        the single result array, a list of arrays, or the raw reply
        header (control ops); raises the daemon's classified error.

        A timeout INVALIDATES the connection: the daemon's late reply
        would otherwise desynchronize the stream (the next request
        would read it as its own answer).  With ``retries`` armed the
        policy reconnects and resubmits through ``resilience.retry``
        (seeded backoff, overloads included, deadline-aware); at the
        default single attempt, reconnect with a fresh Client."""
        if self.retries <= 1:
            out = self._request_once(op, arrays, params,
                                     deadline_s=deadline_s,
                                     tenant=tenant)
            self._budget.note_success()
            return out

        def attempt():
            if self._broken or self._sock is None:
                self._connect()  # RelayDownError here is final: no
                # daemon means resubmission cannot land
            return self._request_once(op, arrays, params,
                                      deadline_s=deadline_s,
                                      tenant=tenant)

        out = resilience.retry(
            attempt, attempts=self.retries,
            retry_on=(resilience.TransientBackendError,
                      resilience.ServerOverloaded),
            deadline_s=deadline_s, budget=self._budget)
        self._budget.note_success()
        return out

    # ------------------------------------------------------- arena plumbing
    def _ensure_arena(self) -> None:
        """Discover + attach the daemon's arena once (lazy: the first
        payload that clears the min-bytes floor pays the one ping).
        Any failure turns the arena OFF for this client — inline wire,
        full function, counted fallback."""
        if self._arena_state != "unknown":
            return
        self._arena_state = "off"
        try:
            info = self._request_once("ping").get("arena")
            if info:
                self._arena = _arena.ClientArena(str(info["name"]),
                                                 int(info["size"]))
                self._arena_state = "on"
        except resilience.ResilienceError:
            raise  # connection-level failures are real errors
        except Exception as e:
            _arena.note_fallback(f"client attach failed ({e!r}); "
                                 "inline wire")

    def _lease_size(self, nbytes: int) -> int:
        """The aligned capacity a lease of ``nbytes`` rounds up to —
        the cache key (same-shape payloads land on the same size)."""
        return max(_arena.ALIGN,
                   (int(nbytes) + _arena.ALIGN - 1)
                   // _arena.ALIGN * _arena.ALIGN)

    def _cache_lease(self, handle: dict) -> None:
        """Return a still-held lease to the cache, or queue its
        release by piggyback when the cache is full."""
        if self._lease_cap > 0 and sum(
                len(v) for v in self._lease_cache.values()) \
                < self._lease_cap:
            self._lease_cache.setdefault(int(handle["nbytes"]),
                                         []).append(handle)
        else:
            self._pending_release.append(
                {"slot": handle["slot"],
                 "generation": handle["generation"]})

    def _drop_lease_cache(self) -> None:
        """Invalidate every held lease COLD — no releases queued (a
        stale release would poison the next request's piggyback);
        the daemon's disconnect teardown reaps the slots.  Queued
        reply releases drop too: a generation bump that invalidated a
        held lease may equally have invalidated an owed reply slot,
        and one stale handle in the piggyback fails the whole next
        request."""
        self._lease_cache = {}
        self._pending_release = []

    def _stage_arena(self, op, arrays):
        """Split a request's payloads between the arena and the inline
        wire: big payloads lease slots (one small ``arena_alloc``
        round trip), write their npy bytes ONCE into shared memory,
        and ride the header as handles; everything else stays inline.
        A cached lease of the right capacity skips the alloc round
        trip entirely (the ``keep`` discipline above).  Any arena
        failure (exhaustion transient, overload) falls back to
        fully-inline for THIS request.  Returns ``(inline_arrays,
        entries, held)`` — ``held`` are the leases to re-cache once
        the exchange settles."""
        if (op in _CONTROL_OPS or not self._arena_want
                or not arrays):
            return arrays, None, []
        sizes = [np.asarray(a).nbytes for a in arrays]
        big = [i for i, nb in enumerate(sizes)
               if nb >= self._arena_min]
        if not big:
            return arrays, None, []
        self._ensure_arena()
        if self._arena is None:
            return arrays, None, []
        payloads = {i: _arena.npy_bytes(arrays[i]) for i in big}
        handles = {}
        for i in big:
            pool = self._lease_cache.get(
                self._lease_size(len(payloads[i])))
            if pool:
                handles[i] = pool.pop()
                self.lease_hits += 1
        missing = [i for i in big if i not in handles]
        if missing:
            self.lease_misses += len(missing)
            try:
                slots = self._request_once(
                    "arena_alloc",
                    params={"nbytes": [len(payloads[i])
                                       for i in missing]})["slots"]
            except (resilience.TransientBackendError,
                    resilience.ServerOverloaded) as e:
                _arena.note_fallback(
                    f"lease failed ({type(e).__name__}); "
                    "inline wire for this request")
                for h in handles.values():  # reused leases survive
                    self._cache_lease(h)
                return arrays, None, []
            handles.update(zip(missing, slots))
        entries = [None] * len(arrays)
        keep = self._lease_cap > 0
        for i in big:
            entries[i] = self._arena.write(handles[i], payloads[i])
            if keep:
                entries[i]["keep"] = True
        inline = [a for i, a in enumerate(arrays) if i not in set(big)]
        # cache disabled: the daemon releases at intake (no keep), so
        # nothing is held past this request
        return inline, entries, list(handles.values()) if keep else []

    def _read_reply_arena(self, reply, rarrays):
        """Merge a reply's inline payloads with its arena results; the
        mapped handles queue for release (piggybacked on the next
        frame — the daemon's disconnect teardown covers the rest)."""
        entries = reply.get("arena_results")
        if entries is None:
            return rarrays
        if self._arena is None:
            raise resilience.ProgramError(
                "serve: daemon sent arena results to a client without "
                "an attached arena", site="arena.map")
        it = iter(rarrays)
        merged = []
        for e in entries:
            if e is None:
                merged.append(next(it))
            else:
                merged.append(self._arena.read(e))
                self._pending_release.append(
                    {"slot": e["slot"], "generation": e["generation"]})
        return merged

    def _request_once(self, op, arrays=(), params=None, *,
                      deadline_s=None, tenant=None, _stage=True):
        if self._broken:
            raise resilience.TransientBackendError(
                f"serve: connection invalidated ({self._broken}); "
                "reconnect to resubmit", site="serve.request")
        header = {"op": op, "params": params or {},
                  "tenant": tenant or self.tenant}
        orig = list(arrays)
        arrays = list(orig)
        if any(isinstance(a, Ref) for a in arrays):
            header["refs"] = [a.name if isinstance(a, Ref) else None
                              for a in arrays]
            arrays = [a for a in arrays if not isinstance(a, Ref)]
        if _stage:
            arrays, entries, held = self._stage_arena(op, arrays)
        else:
            entries, held = None, []
        if entries is not None:
            header["arena"] = entries
        if self._arena is not None and op not in _CONTROL_OPS:
            header["arena_ok"] = True
        if self._pending_release and op != "arena_alloc":
            header["arena_release"] = self._pending_release
            self._pending_release = []
        self._next_id += 1
        rid = self._next_id
        header["id"] = rid
        if deadline_s is not None:
            header["deadline_s"] = deadline_s
        try:
            return self._exchange(op, header, arrays, rid, held)
        except resilience.TransientBackendError as e:
            # a daemon-side transient AT MAP INTAKE (a cached lease
            # skipped the alloc round trip, so the fault lands there
            # now): the §19.1 contract — the arena is never a
            # correctness dependency — resends THIS request fully
            # inline; the held leases stay valid (keep discipline,
            # nothing released) and re-cache in the finally below
            if (entries is not None and _stage and not self._broken
                    and getattr(e, "site", "") == "arena.map"):
                _arena.note_fallback(
                    "daemon-side map transient; inline wire for "
                    "this request")
                return self._request_once(op, orig, params,
                                          deadline_s=deadline_s,
                                          tenant=tenant, _stage=False)
            raise
        finally:
            # the exchange settled (reply, error, or invalidation):
            # still-held leases go back to the cache while the
            # connection stands; a broken connection's leases died
            # with it (owner teardown) and drop cold
            if held and not self._broken and self._sock is not None:
                for h in held:
                    self._cache_lease(h)

    def _exchange(self, op, header, arrays, rid, held):
        try:
            protocol.send_frame(self._sock, header, arrays)
            reply, rarrays = protocol.recv_frame(self._sock)
        except resilience.ResilienceError:
            # torn/oversized/malformed mid-exchange: the stream
            # position is unknown (e.g. a rejected payload's bytes are
            # still unread), so the connection cannot be trusted for
            # another request
            self._invalidate("classified protocol error mid-exchange")
            raise
        except socket.timeout:
            self._invalidate(f"request {op!r} timed out")
            raise resilience.TransientBackendError(
                f"serve: request {op!r} timed out waiting for the "
                "daemon", site="serve.request")
        except OSError as e:
            # the connection died under the exchange (broken pipe /
            # reset when the daemon stopped): the same retryable
            # class as a torn wire frame — classified() would text-
            # match "broken pipe" into the deterministic bucket
            self._invalidate("socket error mid-request")
            raise resilience.TransientBackendError(
                f"serve: connection to {self.path} failed mid-request: "
                f"{e!r}", site="serve.request")
        if reply is None:
            raise resilience.TransientBackendError(
                "serve: daemon closed the connection before a reply "
                "(socket closed)", site="serve.request")
        if reply.get("id") not in (None, rid):
            # a reply for an EARLIER request (stream desync): refuse to
            # hand one request's data back as another's answer
            self._invalidate(
                f"reply id {reply.get('id')} != request id {rid}")
            raise resilience.TransientBackendError(
                "serve: reply stream desynchronized (stale reply id) — "
                "open a fresh Client", site="serve.request")
        if not reply.get("ok", False):
            try:
                protocol.raise_error(reply)
            except resilience.ProgramError as e:
                if held and getattr(e, "site", "") == "arena.map":
                    # generation-bump defense: a stale-handle map is
                    # the ONE way a held lease can be wrong — drop
                    # every cached lease cold (no releases: a stale
                    # release would poison the next request) and let
                    # the disconnect teardown reap the slots
                    self._drop_lease_cache()
                    held.clear()
                raise
        rarrays = self._read_reply_arena(reply, rarrays)
        if "scalar" in reply:
            return float(reply["scalar"])
        if rarrays:
            return rarrays[0] if len(rarrays) == 1 else rarrays
        return reply

    # ----------------------------------------------------------- op helpers
    def ping(self) -> dict:
        return self.request("ping")

    def stats(self) -> dict:
        """The daemon's full stats block (one ``stats`` wire op):
        request/reply/error counters, queue accounting, and the
        ``obs`` metrics snapshot — the daemon-side queue-wait /
        service / flush histograms (docs/SPEC.md §15)."""
        return self.request("stats")["stats"]

    def metrics(self) -> dict:
        """Just the parsed observability snapshot from the ``stats``
        wire op (``stats()["obs"]``): counters, gauges, and the
        per-request latency histograms the daemon samples."""
        return self.stats().get("obs", {})

    def route(self) -> dict:
        """The daemon's serving route,
        ``{"requested": "cpu"|"device", "current": ...}`` (docs/SPEC.md
        §16.6): a claim degraded to the CPU route by relay death
        re-promotes to the device route between batches when the grow
        supervisor is armed (``DR_TPU_ELASTIC_GROW=1``) — unless the
        CPU route was REQUESTED (``--cpu``), which pins it.
        ``stats()["grows"]`` counts completed re-promotions and mesh
        grow-backs."""
        return self.stats()["route"]

    def shutdown(self) -> dict:
        return self.request("shutdown")

    def drain(self) -> dict:
        """Ask the daemon to drain gracefully (SPEC §20.3): it stops
        admitting, finishes in-flight batches, flushes the resident
        journal, and exits.  Returns the acknowledgement; the daemon
        closes this connection once the drain is scheduled."""
        return self.request("drain")

    # ------------------------------------- resident cache (§19.2)
    def put(self, name: str, x, **kw) -> dict:
        """Park ``x`` as this tenant's resident container ``name`` on
        the daemon — built once, referenced by :class:`Ref` in later
        ops (zero payload, no rebuild).  Returns ``{"handle", "tag",
        "bytes", "cached"}``; ``cached`` True means identical content
        was already resident."""
        return self.request("put", [x], {"name": str(name)}, **kw)

    def get(self, name: str, **kw) -> np.ndarray:
        """Read a resident container back."""
        return self.request("get", params={"name": str(name)}, **kw)

    def drop(self, name: str, **kw) -> dict:
        """Evict a resident container (idempotent — the reply says
        whether anything was dropped)."""
        return self.request("drop", params={"name": str(name)}, **kw)

    def arena_active(self) -> bool:
        """True once this client is attached to the daemon's
        shared-memory arena (diagnostic)."""
        return self._arena is not None

    def fill(self, n: int, value: float = 0.0, **kw) -> np.ndarray:
        return self.request("fill", params={"n": int(n),
                                            "value": float(value)}, **kw)

    def scale(self, x, a: float = 1.0, b: float = 0.0, **kw) -> np.ndarray:
        return self.request("scale", [x], {"a": float(a),
                                           "b": float(b)}, **kw)

    def reduce(self, x, **kw) -> float:
        return self.request("reduce", [x], **kw)

    def dot(self, x, y, **kw) -> float:
        return self.request("dot", [x, y], **kw)

    def scan(self, x, **kw) -> np.ndarray:
        return self.request("scan", [x], **kw)

    def sort(self, x, descending: bool = False, **kw) -> np.ndarray:
        return self.request("sort", [x],
                            {"descending": bool(descending)}, **kw)

    # ------------------------------------------- relational layer (§17.3)
    def join(self, lk, lv, rk, rv, how: str = "inner",
             fill: float = 0.0, capacity=None, **kw):
        """Sort-merge join on the daemon; returns the TRIMMED
        ``[keys, left_values, right_values]`` row arrays.  A result
        beyond ``capacity`` (default ``4 * (len(lk) + len(rk))``)
        raises the daemon's classified capacity ``ProgramError``."""
        params = {"how": str(how), "fill": float(fill)}
        if capacity is not None:
            params["capacity"] = int(capacity)
        return self.request("join", [lk, lv, rk, rv], params, **kw)

    def groupby(self, keys, values, agg: str = "sum", **kw):
        """Group-by aggregate; returns trimmed
        ``[group_keys, aggregates]``."""
        return self.request("groupby", [keys, values],
                            {"agg": str(agg)}, **kw)

    def unique(self, x, **kw) -> np.ndarray:
        """Sorted distinct values (trimmed)."""
        return self.request("unique", [x], **kw)

    def top_k(self, x, k: int, largest: bool = True, **kw):
        """The k best elements; returns ``[values, indices]``
        best-first.  Batches into the shared deferred flush."""
        return self.request("topk", [x], {"k": int(k),
                                          "largest": bool(largest)},
                            **kw)

    def histogram(self, x, bins: int, lo: float, hi: float, **kw) \
            -> np.ndarray:
        """Fixed-bin histogram counts over ``[lo, hi]``.  Batches
        into the shared deferred flush."""
        return self.request("histogram", [x],
                            {"bins": int(bins), "lo": float(lo),
                             "hi": float(hi)}, **kw)
