"""Shared-memory tensor arena for the serving data plane (docs/SPEC.md
§19.1).

The inline wire serializes every tensor into the socket frame: npy
encode, kernel copy in, kernel copy out, npy decode — four traversals
of the payload per direction.  The arena moves the bulk bytes ONCE:
the daemon owns a ``multiprocessing.shared_memory`` segment, clients
write npy payloads straight into leased slots, and the protocol frame
carries only metadata plus an arena handle (the §18 copy discipline —
move bytes once, bound peak memory — applied to the host wire).

Handle lifecycle::

    arena_alloc (wire op) ──> slot leased (refs=1, generation bumped)
        client writes npy bytes at the slot's offset
    request frame carries {"slot", "generation", "len"}
        daemon maps (generation checked), decodes, releases
    reply results ride daemon-allocated slots the same way;
        the client releases them (piggybacked on its next frame,
        or wholesale when its connection closes)

Safety contract:

* **generation tags** — every lease of a slot id bumps its generation;
  a handle whose generation does not match the live lease (a recycled
  slot) is a classified :class:`ProgramError` (site ``arena.map``) —
  a stale handle can NEVER read another request's bytes;
* **ref-counted slots** — ``release`` drops a reference, the range is
  recycled at zero; every slot is owned by the connection that leased
  it, and a client crash releases its slots wholesale (the daemon's
  disconnect teardown), so a dead client cannot leak the arena dry;
* **exhaustion is a transient** — an ``alloc`` that does not fit
  raises :class:`TransientBackendError` (site ``arena.map``); the
  client absorbs it by falling back to the inline wire for that
  request (graceful: the arena is an optimization, never a
  correctness dependency);
* ``arena.map`` / ``arena.release`` are registered fault sites
  (§10.2): the chaos battery drives both against a live daemon.

Observability: ``serve.arena.mapped_bytes`` / ``serve.arena.maps`` /
``serve.arena.fallbacks`` counters and the ``serve.arena.in_use``
gauge ride the metrics registry into ``stats`` and ``bench.py
--serve``.
"""

from __future__ import annotations

import io
import threading
from typing import Optional

import numpy as np

from ..obs import metrics as _om
from ..utils import faults as _faults
from ..utils import resilience
from ..utils.env import env_int
from ..utils.fallback import warn_fallback

__all__ = ["Arena", "ClientArena", "attach", "npy_bytes", "load_npy",
           "ALIGN"]

#: slot alignment (cache-line multiple; npy headers are 64-padded too)
ALIGN = 64

#: segment names CREATED by this process (Arena.__init__): an attach
#: to one of these must NOT unregister it from the resource tracker —
#: in-process clients (tests, bench) would steal the creator's entry
#: and the final unlink would log a tracker KeyError
_OWNED: set = set()

_c_maps = _om.counter("serve.arena.maps")
_c_mapped_bytes = _om.counter("serve.arena.mapped_bytes")
_c_fallbacks = _om.counter("serve.arena.fallbacks")
_g_in_use = _om.gauge("serve.arena.in_use")


def npy_bytes(arr) -> bytes:
    """``arr`` in npy format (``allow_pickle=False`` — the same
    no-pickles rule as the inline wire)."""
    bio = io.BytesIO()
    np.save(bio, np.asarray(arr), allow_pickle=False)
    return bio.getvalue()


def load_npy(buf) -> np.ndarray:
    """Decode one npy payload from ``buf`` (bytes/memoryview)."""
    try:
        return np.load(io.BytesIO(bytes(buf)), allow_pickle=False)
    except Exception as e:
        raise resilience.ProgramError(
            f"arena: undecodable npy payload ({e!r})", site="arena.map")


class _Slot:
    __slots__ = ("sid", "offset", "nbytes", "generation", "refs",
                 "owner")

    def __init__(self, sid, offset, nbytes, generation, owner):
        self.sid = sid
        self.offset = offset
        self.nbytes = nbytes
        self.generation = generation
        self.refs = 1
        self.owner = owner


class Arena:
    """The daemon-side arena: ONE shared-memory segment plus the slot
    table.  Thread-safe (reader threads lease/map, the dispatch thread
    writes replies, disconnect teardown releases wholesale)."""

    def __init__(self, nbytes: Optional[int] = None):
        from multiprocessing import shared_memory
        self.size = (env_int("DR_TPU_SERVE_ARENA_BYTES", 1 << 26)
                     if nbytes is None else int(nbytes))
        self._shm = shared_memory.SharedMemory(create=True,
                                               size=self.size)
        self.name = self._shm.name
        _OWNED.add(self.name)
        self._lock = threading.Lock()
        self._slots: dict = {}          # sid -> _Slot
        self._gens: dict = {}           # sid -> last generation leased
        self._free = [(0, self.size)]   # sorted (offset, size) ranges
        #: released slot ids, recycled FIRST: generations actually
        #: engage (a stale handle meets its old sid at a new
        #: generation) and _gens stays bounded by the slot high-water
        #: mark instead of growing one entry per alloc forever
        self._free_sids: list = []
        self._next_sid = 0
        self.in_use = 0
        self.high_water = 0
        self.allocs = 0
        self.exhaustions = 0

    # ------------------------------------------------------------ ranges
    def _take_range(self, need: int) -> Optional[int]:
        """First-fit over the free list (caller holds the lock)."""
        for i, (off, size) in enumerate(self._free):
            if size >= need:
                if size == need:
                    del self._free[i]
                else:
                    self._free[i] = (off + need, size - need)
                return off
        return None

    def _give_range(self, off: int, size: int) -> None:
        """Insert and coalesce (caller holds the lock)."""
        free = self._free
        lo, hi = 0, len(free)
        while lo < hi:
            mid = (lo + hi) // 2
            if free[mid][0] < off:
                lo = mid + 1
            else:
                hi = mid
        free.insert(lo, (off, size))
        # coalesce with neighbours
        if lo + 1 < len(free) and off + size == free[lo + 1][0]:
            free[lo] = (off, size + free[lo + 1][1])
            del free[lo + 1]
        if lo > 0 and free[lo - 1][0] + free[lo - 1][1] == off:
            free[lo - 1] = (free[lo - 1][0],
                            free[lo - 1][1] + free[lo][1])
            del free[lo]

    # ------------------------------------------------------------- leases
    def alloc(self, nbytes: int, owner=None) -> dict:
        """Lease a slot of at least ``nbytes``; returns the handle the
        wire carries (``slot`` / ``generation`` / ``offset`` /
        ``nbytes``).  Exhaustion raises the classified transient the
        client's inline fallback absorbs."""
        _faults.fire("arena.map", op="alloc", nbytes=int(nbytes))
        need = max(ALIGN, (int(nbytes) + ALIGN - 1) // ALIGN * ALIGN)
        with self._lock:
            off = self._take_range(need)
            if off is None:
                self.exhaustions += 1
                raise resilience.TransientBackendError(
                    f"arena: exhausted ({self.in_use}/{self.size} bytes"
                    f" leased, {need} requested) — fall back to the "
                    "inline wire and release outstanding handles",
                    site="arena.map")
            if self._free_sids:
                sid = self._free_sids.pop()
            else:
                sid = self._next_sid
                self._next_sid += 1
            gen = self._gens.get(sid, 0) + 1
            self._gens[sid] = gen
            self._slots[sid] = _Slot(sid, off, need, gen, owner)
            self.in_use += need
            self.high_water = max(self.high_water, self.in_use)
            self.allocs += 1
            _g_in_use.set(self.in_use)
            return {"slot": sid, "generation": gen, "offset": off,
                    "nbytes": need}

    def _live(self, handle: dict, site: str) -> _Slot:
        try:
            sid = int(handle["slot"])
            gen = int(handle["generation"])
        except (KeyError, TypeError, ValueError):
            raise resilience.ProgramError(
                f"arena: malformed handle {handle!r}", site=site)
        slot = self._slots.get(sid)
        if slot is None or slot.generation != gen or slot.refs <= 0:
            raise resilience.ProgramError(
                f"arena: stale handle (slot {sid} generation {gen} is "
                "not leased — the slot was released and recycled)",
                site=site)
        return slot

    def view(self, handle: dict, length: Optional[int] = None):
        """The slot's writable memoryview (generation-checked)."""
        with self._lock:
            slot = self._live(handle, "arena.map")
            n = slot.nbytes if length is None else int(length)
            if n < 0 or n > slot.nbytes:
                raise resilience.ProgramError(
                    f"arena: declared length {n} exceeds the slot's "
                    f"{slot.nbytes}-byte lease", site="arena.map")
            return self._shm.buf[slot.offset:slot.offset + n]

    def map(self, handle: dict) -> np.ndarray:
        """Decode the npy payload a handle points at (the daemon-side
        request intake path).  Fault site ``arena.map``."""
        _faults.fire("arena.map", op="map")
        n = int(handle.get("len", 0))
        arr = load_npy(self.view(handle, n))
        _c_maps.add()
        _c_mapped_bytes.add(n)
        return arr

    def put(self, data: bytes, owner=None) -> dict:
        """Lease + write in one step (the daemon's reply path); the
        returned handle carries ``len`` = the real payload length."""
        handle = self.alloc(len(data), owner=owner)
        self._shm.buf[handle["offset"]:handle["offset"] + len(data)] = \
            data
        handle["len"] = len(data)
        return handle

    def retain(self, handle: dict) -> None:
        with self._lock:
            self._live(handle, "arena.map").refs += 1

    def release(self, handle: dict) -> None:
        """Drop one reference; the range recycles at zero.  Fault site
        ``arena.release``; a bad handle is classified — a double
        release must not silently free a RE-leased slot."""
        _faults.fire("arena.release")
        with self._lock:
            slot = self._live(handle, "arena.release")
            slot.refs -= 1
            if slot.refs <= 0:
                del self._slots[slot.sid]
                self._free_sids.append(slot.sid)
                self.in_use -= slot.nbytes
                self._give_range(slot.offset, slot.nbytes)
                _g_in_use.set(self.in_use)

    def release_owner(self, owner) -> int:
        """Release every slot ``owner`` holds (disconnect teardown —
        a crashed client cannot leak the arena dry).  Returns the
        count released.  Never raises."""
        freed = 0
        with self._lock:
            for sid in [s for s, slot in self._slots.items()
                        if slot.owner is owner]:
                slot = self._slots.pop(sid)
                self._free_sids.append(sid)
                self.in_use -= slot.nbytes
                self._give_range(slot.offset, slot.nbytes)
                freed += 1
            if freed:
                _g_in_use.set(self.in_use)
        return freed

    # -------------------------------------------------------------- admin
    def stats(self) -> dict:
        with self._lock:
            return {"size": self.size, "in_use": self.in_use,
                    "high_water": self.high_water,
                    "slots": len(self._slots), "allocs": self.allocs,
                    "exhaustions": self.exhaustions}

    def destroy(self) -> None:
        """Close AND unlink the segment (daemon teardown)."""
        _OWNED.discard(self.name)
        try:
            self._shm.close()
            self._shm.unlink()
        except (OSError, FileNotFoundError):  # pragma: no cover
            pass


def attach(name: str):
    """Attach to an existing segment by name.  Python 3.10's
    ``SharedMemory`` registers even ATTACH-mode segments with the
    resource tracker, which then unlinks the daemon's live arena when
    the CLIENT exits — unregister FOREIGN attaches so only the
    creating daemon owns the segment's lifetime (an attach to a
    segment this very process created keeps the creator's one
    registration intact)."""
    from multiprocessing import shared_memory
    shm = shared_memory.SharedMemory(name=name)
    if name not in _OWNED:
        try:
            from multiprocessing import resource_tracker
            resource_tracker.unregister(shm._name, "shared_memory")
        # drlint: ok[R5] lifetime-bookkeeping best effort, not a degradation: an unregister miss only re-arms the tracker's own (noisy but harmless) cleanup
        except Exception:  # pragma: no cover - tracker internals moved
            pass
    return shm


class ClientArena:
    """The client-side view of a daemon's arena: attach by name, write
    request payloads into leased slots, read reply payloads out.  The
    client LEASES over the wire (``arena_alloc``) and only touches
    bytes here — generation checks stay on the daemon."""

    def __init__(self, name: str, size: int):
        self.name = name
        self.size = int(size)
        self._shm = attach(name)

    def write(self, handle: dict, data: bytes) -> dict:
        """Write ``data`` into the leased slot; returns the handle
        with ``len`` stamped (what the request frame carries)."""
        off, cap = int(handle["offset"]), int(handle["nbytes"])
        if len(data) > cap:
            raise resilience.ProgramError(
                f"arena: payload of {len(data)} bytes exceeds the "
                f"{cap}-byte lease", site="arena.map")
        self._shm.buf[off:off + len(data)] = data
        out = dict(handle)
        out["len"] = len(data)
        return out

    def read(self, handle: dict) -> np.ndarray:
        """Decode the npy payload a REPLY handle points at."""
        off, n = int(handle["offset"]), int(handle["len"])
        if off < 0 or n < 0 or off + n > self.size:
            raise resilience.ProgramError(
                f"arena: reply handle {handle!r} is outside the "
                f"{self.size}-byte segment", site="arena.map")
        return load_npy(self._shm.buf[off:off + n])

    def close(self) -> None:
        try:
            self._shm.close()
        except (OSError, BufferError):  # pragma: no cover
            pass


def note_fallback(reason: str) -> None:
    """Count (and once-per-reason warn) an arena → inline-wire
    fallback — the graceful-degradation leg of the §19.1 contract."""
    _c_fallbacks.add()
    warn_fallback("serve.arena", reason)
