"""Per-tenant resident container cache for the serving daemon
(docs/SPEC.md §19.2).

Every inline request rebuilds its operands as fresh containers —
host→device placement per request, the dominant cost for repeated ops
over the SAME data.  ``put`` builds the container ONCE on the daemon's
dispatch thread and parks it under ``(tenant, name)``; later requests
reference it by name (``refs`` in the frame header) and skip the
rebuild entirely.  ``get`` reads it back, ``drop`` evicts.

Semantics:

* **content-tagged** — ``put`` returns a content tag (sha1 over raw
  bytes + dtype + shape); re-putting identical content under the same
  name is a HIT (no rebuild, the tag proves it), re-putting different
  content replaces the entry;
* **LRU bytes budget** — ``DR_TPU_SERVE_RESIDENT_BYTES`` bounds the
  cache; inserts evict least-recently-used entries, and a single
  value larger than the whole budget is a classified
  :class:`ProgramError` (site ``serve.request``);
* **tenant-scoped** — names are namespaced by tenant: one tenant can
  neither read nor evict-by-name another's data (the LRU sweep is
  global — capacity is a shared resource, isolation is for CONTENT);
* **elastic ride-along (§16)** — resident containers are ordinary
  registered containers: a mid-session shrink rescues/restores them
  with everything else, a lost one is POISONED and every later use
  raises the classified ``DeviceLostError`` to the requesting client
  (never a silent wrong answer); grow-backs re-admit them through the
  standard container walk.

Observability: ``serve.resident.hits`` / ``.misses`` / ``.evictions``
counters and the ``serve.resident.bytes`` gauge ride the metrics
registry into ``stats`` and ``bench.py --serve``.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict

import numpy as np

from ..obs import metrics as _om
from ..utils import resilience
from ..utils.env import env_int

__all__ = ["ResidentCache", "ResidentStub", "Entry"]

_c_hits = _om.counter("serve.resident.hits")
_c_misses = _om.counter("serve.resident.misses")
_c_evictions = _om.counter("serve.resident.evictions")
_g_bytes = _om.gauge("serve.resident.bytes")


class Entry:
    __slots__ = ("cont", "nbytes", "tag", "shape", "dtype")

    def __init__(self, cont, nbytes, tag, shape, dtype):
        self.cont = cont
        self.nbytes = int(nbytes)
        self.tag = tag
        self.shape = tuple(shape)
        self.dtype = dtype


class ResidentStub(np.ndarray):
    """A shape/dtype stand-in the intake path substitutes for a
    resident reference: validators see an ordinary ndarray of the
    resident's geometry, while the handlers' ``_vec`` resolves
    ``_dr_resident`` to the cached container and never reads the stub
    cells (``np.empty`` — allocation is virtual, content is garbage by
    design)."""

    def __new__(cls, entry: Entry):
        obj = np.empty(entry.shape, entry.dtype).view(cls)
        obj._dr_resident = entry.cont
        return obj


def _content_tag(arr: np.ndarray) -> str:
    h = hashlib.sha1()
    h.update(str(arr.dtype).encode())
    h.update(str(arr.shape).encode())
    h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()[:16]


class ResidentCache:
    """The daemon's ``(tenant, name) -> Entry`` LRU.  Thread-safe:
    intake (reader threads) resolves references while the dispatch
    thread puts/evicts."""

    def __init__(self, budget: int = None):
        self.budget = (env_int("DR_TPU_SERVE_RESIDENT_BYTES", 1 << 28)
                       if budget is None else int(budget))
        self._lock = threading.Lock()
        self._entries: "OrderedDict" = OrderedDict()
        self.bytes = 0
        self.puts = 0
        self.put_hits = 0
        self.evictions = 0

    # ------------------------------------------------------------- reads
    def get(self, tenant: str, name: str):
        """The entry, or None (counts the hit/miss either way)."""
        key = (tenant, name)
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                _c_misses.add()
                return None
            self._entries.move_to_end(key)
            _c_hits.add()
            return entry

    def require(self, tenant: str, name: str) -> Entry:
        entry = self.get(tenant, name)
        if entry is None:
            raise resilience.ProgramError(
                f"serve: no resident container {name!r} for tenant "
                f"{tenant!r} — put() it first (or it was evicted/"
                "dropped)", site="serve.request")
        return entry

    # ------------------------------------------------------------ writes
    def put(self, tenant: str, name: str, arr) -> "tuple[Entry, bool]":
        """Build-and-park (or re-tag) ``arr`` under ``(tenant,
        name)``; returns ``(entry, cached)`` — ``cached`` True when
        identical content was already resident and no rebuild ran.
        Runs on the dispatch thread (the container build is device
        work)."""
        arr = np.ascontiguousarray(np.asarray(arr, np.float32))
        tag = _content_tag(arr)
        key = (tenant, name)
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None and entry.tag == tag:
                self._entries.move_to_end(key)
                self.put_hits += 1
                _c_hits.add()
                return entry, True
        if arr.nbytes > self.budget:
            raise resilience.ProgramError(
                f"serve: resident value of {arr.nbytes} bytes exceeds "
                f"the cache budget DR_TPU_SERVE_RESIDENT_BYTES="
                f"{self.budget}", site="serve.request")
        import dr_tpu
        cont = dr_tpu.distributed_vector.from_array(arr)
        entry = Entry(cont, arr.nbytes, tag, arr.shape, arr.dtype)
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self.bytes -= old.nbytes
            self._entries[key] = entry
            self.bytes += entry.nbytes
            self.puts += 1
            # LRU sweep: evict oldest until under budget (never the
            # entry just inserted — it is the newest by construction)
            while self.bytes > self.budget and len(self._entries) > 1:
                _k, victim = self._entries.popitem(last=False)
                self.bytes -= victim.nbytes
                self.evictions += 1
                _c_evictions.add()
            _g_bytes.set(self.bytes)
        return entry, False

    def drop(self, tenant: str, name: str) -> bool:
        with self._lock:
            entry = self._entries.pop((tenant, name), None)
            if entry is None:
                return False
            self.bytes -= entry.nbytes
            _g_bytes.set(self.bytes)
            return True

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self.bytes = 0
            _g_bytes.set(0)

    # -------------------------------------------------------------- admin
    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries), "bytes": self.bytes,
                    "budget": self.budget, "puts": self.puts,
                    "put_hits": self.put_hits,
                    "evictions": self.evictions}
