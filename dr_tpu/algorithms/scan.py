"""Distributed prefix scan: ``inclusive_scan`` / ``exclusive_scan``.

Reference: the 3-phase multi-GPU scan (``shp/algorithms/inclusive_scan.hpp:
25-148``) — (1) per-segment scan, (2) scan of per-segment totals on the root
device, (3) per-segment carry fixup — with host event.wait() barriers
between phases.

TPU re-design: ONE jitted ``shard_map`` program per layout — local
``lax.associative_scan`` over the owned (masked) cells, ``all_gather`` of
segment totals over the mesh axis, an exclusive fold of preceding totals
(the carry), and the broadcast fixup — all fused by XLA, no host barriers
(SURVEY.md §2.5 "Distributed prefix scan").
"""

from __future__ import annotations

import operator
from ..utils.env import env_str
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ._common import (combine_for, first_nonempty, identityless_fold,
                      owned_window_mask, uniform_layout, window_geometry,
                      working_geometry)
from ..views import views as _v
from .elementwise import (_Chain, _apply_chain_ops, _chain_scalars,
                          _op_key, _out_chain, _plan_active, _prog_cache,
                          _resolve, _traced_op_key, _write_window)
from .reduce import _classify_op, _identity_for
from ..core.pinning import pinned_id

__all__ = ["inclusive_scan", "exclusive_scan", "inclusive_scan_n"]


_BLOCK = 1024  # whole f32 vreg rows (8 sublanes x 128 lanes)
_MM_BLOCK = 128  # cumsum-as-matmul block width (measured TPU optimum:
# narrower blocks cut the n*C MXU FLOPs; recursion depth stays trivial)


from ..ops.scan_pallas import prefix_matrix as _prefix_matrix


def _matmul_cumsum(x, ident):
    """Inclusive add-scan via the MXU: prefix sums along a _MM_BLOCK-wide
    axis are one multiply by an upper-triangular ones matrix
    ((rows @ U)[i, j] = sum_{b<=j} rows[i, b]), plus a recursive scan of
    the per-row totals.  ~4x the VPU blocked scan's throughput on TPU;
    each prefix is an independent f32-accumulated dot, so accuracy
    matches (or beats) the sequential fold."""
    C = _MM_BLOCK
    n = x.shape[0]
    pad = (-n) % C
    if pad:
        x = jnp.concatenate([x, jnp.full((pad,), ident, x.dtype)])
    rows = x.reshape(-1, C)
    U = jnp.asarray(_prefix_matrix(C), x.dtype)
    rs = jax.lax.dot_general(
        rows, U, (((1,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGH,
        preferred_element_type=jnp.promote_types(x.dtype, jnp.float32))
    rs = rs.astype(x.dtype)
    carry = _blocked_scan(jnp.add, rs[:, -1], ident, kind="add")
    carry = jnp.concatenate(
        [jnp.full((1,), ident, x.dtype), carry[:-1]])
    return (rs + carry[:, None]).reshape(-1)[:n]


def _blocked_scan(combine, x, ident, kind=None):
    """Inclusive scan of a 1-D array via (rows, 1024) blocking.

    ``lax.associative_scan`` over a flat 2^27-element axis emits ~27
    levels of full-size slice/concat intermediates, which can exhaust the
    TPU compiler; scanning lane-blocked rows needs only 10 shallow levels
    on tile-aligned 2-D arrays plus a recursive scan of the per-row
    totals.  Requires an identity element; callers without one fall back
    to the flat scan.  Floating add-scans take the MXU matmul form.
    """
    n = x.shape[0]
    if ident is None or n <= 2 * _BLOCK:
        return lax.associative_scan(combine, x)
    if kind == "add" and jnp.issubdtype(x.dtype, jnp.floating):
        return _matmul_cumsum(x, ident)
    pad = (-n) % _BLOCK
    if pad:
        x = jnp.concatenate([x, jnp.full((pad,), ident, x.dtype)])
    rows = x.reshape(-1, _BLOCK)
    rs = lax.associative_scan(combine, rows, axis=1)
    carry = _blocked_scan(combine, rs[:, -1], ident, kind)
    carry = jnp.concatenate(
        [jnp.full((1,), ident, x.dtype), carry[:-1]])
    return combine(carry[:, None], rs).reshape(-1)[:n]


def _use_scan_kernel(layout, kind, in_dtype, runtime):
    """The ``scan`` kernel-arm decision (docs/SPEC.md §22) — ONE
    decision point through the arm registry (``ops/kernels.use_kernel``:
    ``DR_TPU_SCAN_IMPL`` pin > tuning-DB winner > auto-by-platform)
    instead of the old per-call flag checks.  Eligibility is the
    single-pass Pallas chunked cumsum's hot case: add-scan over
    f32-accumulable INPUT data (f32/bf16/f16 — the kernel accumulates
    in f32, so integer exactness and f64 precision must take the XLA
    path), uniform lane-chunkable layout.  Returns a
    :class:`..ops.kernels.Decision`; ``DR_TPU_SCAN_IMPL=pallas`` on a
    CPU mesh runs the kernel in interpret mode (the parity battery's
    route)."""
    from ..ops import kernels, scan_pallas
    from ._common import f32_accumulable
    eligible = (uniform_layout(layout)  # the kernel tiles uniform rows
                and f32_accumulable(in_dtype)
                and kind == "add"
                and scan_pallas.pick_chunk(layout[1]) is not None)
    return kernels.use_kernel("scan", runtime=runtime,
                              eligible=eligible)


def _kernel_variant():
    """Trace-time kernel knobs (DR_TPU_SCAN_KERNEL variant,
    DR_TPU_SCAN_CHUNK cap, DR_TPU_SCAN_PASSES split depth): part of
    every program cache key so A/B sweeps rebuild instead of reusing
    the other configuration's cached program."""
    from ..ops import scan_pallas
    return (env_str("DR_TPU_SCAN_KERNEL").lower(),
            env_str("DR_TPU_SCAN_PIPE").lower(),
            scan_pallas.chunk_cap(), scan_pallas.scan_passes())


def _scan_program(mesh, axis, layout, kind, op, exclusive, dtype,
                  use_kernel=None, window=None, aliased=False,
                  ops=(), out_layout=None, out_window=None):
    """``window=(off, wn)`` scans ONLY the logical subrange (round 4):
    with an identity op, the window scan IS the whole-container scan of
    an identity-masked input — cells before the window contribute the
    identity to every window prefix — so the same phases run unchanged;
    identityless ops run in WINDOW coordinates instead (static window
    geometry + the empty-shard-skipping fold — no identity needed).
    Either way the output row blends scanned window cells into the OUT
    container's original row (the program takes out's data as a second,
    donated argument, or one aliased argument for in-place forms).

    Round-5 extensions:

    - ``ops``: a view chain's elementwise op stack, fused into the
      program — applied to the extracted slice BEFORE any identity
      masking (the masks live in the post-op domain, where the scan
      identity is meaningful).  BoundOp ops key on op identity + scalar
      COUNT and feed their values as TRACED trailing operands (round 6;
      the _custom_reduce_program convention), so a streamed coefficient
      reuses ONE compiled program instead of re-jitting per value.
    - ``out_layout``/``out_window``: a MISMATCHED destination (different
      offsets, or a different distribution on the same mesh).  The scan
      then always runs in WINDOW coordinates; the scanned values
      realign from the in-window's per-shard geometry to the
      out-window's by one static masked all_to_all (the sort family's
      rebalance pattern) and blend through the OUT container's mask."""
    from ..ops import kernels
    kern = use_kernel if use_kernel is not None else kernels.NO_KERNEL
    mismatched = out_window is not None
    key = ("scan", pinned_id(mesh), axis, layout, kind, _op_key(op) if kind is None
           else None, exclusive, str(dtype), tuple(kern),
           _kernel_variant() if kern.use else None, window, aliased,
           tuple(_traced_op_key(f) for f in ops), out_layout, out_window)
    prog = _prog_cache.get(key)
    if prog is not None:
        return prog

    nshards, S, cap, prev, nxt, n, starts, sizes = \
        working_geometry(layout)
    combine = combine_for(kind, op)
    wgeom = False
    if window is not None:
        wmask_c = jnp.asarray(np.asarray(
            owned_window_mask(layout, *window)[0]))
        width = prev + cap + nxt
        if kind is None or mismatched:
            # identityless window: no value can mask outside cells —
            # run the phases in WINDOW coordinates instead (the sort
            # family's approach): the window's shard intersections are
            # static uneven geometry, each shard reads its slice at a
            # static offset, and the identityless uneven machinery
            # (real totals at local[valid-1], empty-shard-skipping
            # fold) needs no identity anywhere.  Mismatched in/out
            # geometries ALWAYS take window coordinates — they are the
            # common coordinate system the realign maps between.
            _, S, _, _, _, n, starts, sizes, wstart = \
                window_geometry(layout, *window)
            woff_c = jnp.asarray(wstart, jnp.int32)
            wgeom = True
    starts_c = jnp.asarray(starts, jnp.int32)
    sizes_c = jnp.asarray(sizes, jnp.int32)
    if mismatched:
        # destination-side static geometry (its own layout and window)
        oL = out_layout or layout
        _, oS, ocap, oprev, onxt, _, ostarts, osizes, owstart = \
            window_geometry(oL, *out_window)
        owidth = oprev + ocap + onxt
        owoff_c = jnp.asarray(owstart, jnp.int32)
        omask_c = jnp.asarray(np.asarray(
            owned_window_mask(oL, *out_window)[0]))
        ostarts_c = jnp.asarray(ostarts, jnp.int32)
        osizes_c = jnp.asarray(osizes, jnp.int32)
        same_geom = (np.array_equal(ostarts, starts)
                     and np.array_equal(osizes, sizes))
    # pad cells exist when the ceil layout overshoots n OR any shard of
    # an uneven distribution is narrower than the working width: skip
    # the masking pass (a whole extra HBM read-modify) when exact.
    exact = (bool((np.asarray(sizes) == S).all()) and nshards * S == n
             and window is None)
    # BoundOp chain scalars arrive as traced trailing operands
    nsc = sum(len(o.scalars) for o in ops if isinstance(o, _v.BoundOp))

    def body(blk, *rest):  # (1, width) one shard row (+ out + scalars)
        out_blk = rest[:len(rest) - nsc]
        chain_scalars = rest[len(rest) - nsc:]
        ident = _identity_for(kind, dtype) if kind is not None else None
        r = lax.axis_index(axis)
        if wgeom:
            # my window slice at a per-shard static offset; the
            # clipped tail is discarded by the nvalid mask downstream
            idx = jnp.clip(prev + woff_c[r] + jnp.arange(S), 0,
                           width - 1)
            x = jnp.take(blk[0], idx)
        else:
            x = blk[0, prev:prev + S]
        # the view chain's elementwise stack, fused (round 5); masks
        # below live in the POST-op domain, where the scan identity is
        # meaningful.  BoundOp coefficients are traced (round 6).
        x = _apply_chain_ops(x, ops, iter(chain_scalars))
        if window is not None and not wgeom:
            # outside-window cells become the identity: every window
            # prefix then sees only window contributions
            x = jnp.where(wmask_c[r, prev:prev + S], x, ident)
        elif ident is not None and not exact:
            nvalid = jnp.minimum(sizes_c[r],
                                 jnp.clip(n - starts_c[r], 0, S))
            x = jnp.where(jnp.arange(S) < nvalid, x, ident)
        if kern.use:
            # carry-seeded kernel: compute each shard's TOTAL first (a
            # cheap reduction read), fold the preceding totals, and
            # hand the carry to the kernel — the scan itself is then
            # the ONLY full read+write pass; the round-2 form paid a
            # third whole-array pass for the carry fixup
            from ..ops import scan_pallas
            if nshards == 1:
                scanned = scan_pallas.chunked_cumsum(
                    x, interpret=kern.interpret)
            else:
                # f32 totals regardless of input dtype: the kernel's
                # carry seed is f32, and a bf16-rounded cross-shard
                # carry would poison every later shard's prefixes
                totals = lax.all_gather(
                    jnp.sum(x, dtype=jnp.float32), axis)  # (nshards,)
                masked = jnp.where(jnp.arange(nshards) < r, totals,
                                   jnp.zeros((), totals.dtype))
                carry = jnp.sum(masked)
                scanned = scan_pallas.chunked_cumsum(
                    x, carry=carry, interpret=kern.interpret)
        else:
            local = _blocked_scan(combine, x,
                                  ident if kind is not None else None,
                                  kind)
            # exclusive fold of totals from ranks < r  ->  my carry
            if ident is not None:
                # pads are masked to the identity, so position S-1
                # carries each shard's REAL total even when the shard
                # is narrower than the working width (or empty)
                totals = lax.all_gather(local[-1], axis)  # (nshards,)
                masked = jnp.where(jnp.arange(nshards) < r, totals,
                                   ident)
                carry = lax.associative_scan(combine, masked)[-1]
                if exclusive:
                    # seed locally instead of via ppermute: out[j] =
                    # carry ∘ (ident, local[0], ..., local[j-1]) — the
                    # same values, one fewer collective, and correct
                    # across EMPTY shards (the carry already folds
                    # every preceding shard's total; for r = 0 it IS
                    # the identity, so the fold is unconditional)
                    local = jnp.concatenate(
                        [jnp.full((1,), ident, local.dtype),
                         local[:-1]])
                    scanned = combine(carry, local)
                else:
                    scanned = jnp.where(r > 0, combine(carry, local),
                                        local)
            else:
                # no identity: fold sequentially with lax.fori_loop.
                # Trailing pad cells never affect a local scan's valid
                # prefix, so `local` is correct as-is; only the TOTALS
                # need care.  Uniform ceil layouts read local[-1] (only
                # the last shard is short, and nobody folds its total);
                # uneven layouts read each shard's REAL total at
                # local[valid-1] and skip empty shards, seeding the
                # fold at the FIRST nonempty shard (static: sizes are
                # python ints), so no identity is ever required.
                if (exact or uniform_layout(layout)) \
                        and not wgeom:
                    totals = lax.all_gather(local[-1], axis)

                    def fold(i, acc):
                        return jnp.where(i < r, combine(acc, totals[i]),
                                         acc)
                    carry = lax.fori_loop(1, nshards, fold, totals[0])
                    scanned = jnp.where(r > 0, combine(carry, local),
                                        local)
                else:
                    nvalid = jnp.minimum(sizes_c[r],
                                         jnp.clip(n - starts_c[r], 0, S))
                    mine = local[jnp.clip(nvalid - 1, 0, S - 1)]
                    totals = lax.all_gather(mine, axis)
                    first_nz = first_nonempty(sizes)
                    ue_carry = identityless_fold(
                        combine, totals, sizes_c, nshards, first_nz,
                        upto=r)
                    scanned = jnp.where(r > first_nz,
                                        combine(ue_carry, local), local)
        if exclusive and (kern.use or kind is None):
            if kind is None and (wgeom or not
                                 (exact or uniform_layout(layout))):
                # uneven identityless: my first exclusive value is the
                # global prefix through the nearest preceding NONEMPTY
                # shard — exactly ue_carry (its fold skips empty
                # shards, which a neighbor ppermute could not).  The
                # first nonempty shard seeds the fallback's dtype zero
                # (overwritten when exclusive_scan folds an init).
                shifted = jnp.roll(scanned, 1)
                scanned = shifted.at[0].set(
                    jnp.where(r > first_nz, ue_carry,
                              jnp.zeros((), scanned.dtype)))
            else:
                # positional shift with the previous shard's last value
                # via ppermute — valid on uniform ceil layouts (a
                # nonempty shard's predecessor is always full there);
                # the identity-bearing XLA path above seeds locally
                # instead
                shifted = jnp.roll(scanned, 1)
                prev_rank_last = lax.ppermute(
                    scanned[-1], axis,
                    [(i, i + 1) for i in range(nshards - 1)])
                first = prev_rank_last if ident is None else \
                    jnp.where(r > 0, prev_rank_last, ident)
                scanned = shifted.at[0].set(first)
        if window is not None:
            # blend: window cells take the scanned value, everything
            # else keeps the OUT container's original content (for the
            # in-place form, the input row IS the out row — a second
            # argument would trip donation aliasing)
            keep = blk[0] if aliased else out_blk[0][0]
            if mismatched:
                # window-coordinate results live on the IN-window's
                # shard geometry; destination cells follow the OUT
                # window's.  Each window position is owned by exactly
                # one source shard under the in-geometry, so one
                # static masked all_to_all + column sum re-homes every
                # value (the sort family's rebalance pattern), and the
                # blend runs through the OUT container's mask.
                sc = scanned.astype(dtype)
                if not same_geom:
                    gpos_o = ostarts_c[:, None] + jnp.arange(oS)[None, :]
                    dest_ok = jnp.arange(oS)[None, :] < osizes_c[:, None]
                    idxl = gpos_o - starts_c[r]
                    own = dest_ok & (idxl >= 0) & (idxl < sizes_c[r])
                    send = jnp.where(
                        own, jnp.take(sc, jnp.clip(idxl, 0, S - 1)),
                        jnp.zeros((), sc.dtype))
                    sc = jnp.sum(lax.all_to_all(send, axis, 0, 0),
                                 axis=0)
                ocol_idx = jnp.clip(
                    jnp.arange(owidth) - oprev - owoff_c[r], 0, oS - 1)
                vals = jnp.take(sc, ocol_idx)
                return jnp.where(omask_c[r], vals, keep)[None]
            if wgeom:
                # re-address window-coordinate results per column
                col_idx = jnp.clip(
                    jnp.arange(width) - prev - woff_c[r], 0, S - 1)
                vals = jnp.take(scanned.astype(dtype), col_idx)
                return jnp.where(wmask_c[r], vals, keep)[None]
            full = jnp.zeros((prev + cap + nxt,), dtype) \
                .at[prev:prev + S].set(scanned.astype(dtype))
            return jnp.where(wmask_c[r], full, keep)[None]
        if prev == 0 and nxt == 0 and cap == S:
            # halo-free row: the scan IS the whole padded row — no
            # zeros+set copy pass (one fewer HBM pass on the hot path)
            return scanned.astype(dtype)[None]
        out = jnp.zeros((1, prev + cap + nxt), dtype)
        return out.at[0, prev:prev + S].set(scanned.astype(dtype))

    # check_vma=False only for the kernel path: pallas outputs carry no
    # varying-mesh-axis metadata
    nin = 1 if window is None or aliased else 2
    shmapped = jax.shard_map(body, mesh=mesh,
                             in_specs=(P(axis, None),) * nin
                             + (P(),) * nsc,
                             out_specs=P(axis, None),
                             check_vma=not kern.use)
    # donate the OUT buffer the window blend rebinds (the aliased form
    # donates its single in/out row)
    donate = () if window is None else ((0,) if aliased else (1,))
    prog = jax.jit(shmapped, donate_argnums=donate)
    _prog_cache[key] = prog
    return prog


def _scan_footprint(in_r, out):
    """Optimizer footprint of a recorded-opaque scan (SPEC §21.2):
    input chain containers are read; the out container is read AND
    window-written (never a coverage killer — ``_write_window``
    preserves cells outside the window).  Unresolvable shapes stay a
    full barrier (None, None)."""
    try:
        ins = _resolve(in_r)
        oc = _out_chain(out)
    except Exception:
        return None, None
    if ins is None or oc is None:
        return None, None
    reads = {id(c.cont): c.cont for c in ins}
    reads[id(oc.cont)] = oc.cont
    return tuple(reads.values()), ((oc.cont, False),)


def _scan(in_r, out, op, init, exclusive):
    if op is None:
        op = operator.add
    kind = _classify_op(op)
    out_chain = _out_chain(out)
    ins = _resolve(in_r)
    if ins is not None and len(ins) == 1 and ins[0].n != out_chain.n:
        # transform's window convention (elementwise.py): a LARGER out
        # window narrows to the input length; a smaller one is a clear
        # error at the call site, not a broadcast crash downstream
        if out_chain.n < ins[0].n:
            raise ValueError(
                f"scan output window too small ({out_chain.n} < "
                f"{ins[0].n})")
        out_chain = _Chain(out_chain.cont, out_chain.off, ins[0].n,
                           out_chain.ops)
    single = ins is not None and len(ins) == 1
    c = ins[0] if single else None
    if single and c.n == 0:
        return out  # empty window: nothing to scan, nothing to seed
    same_mesh = (single and
                 c.cont.runtime.mesh == out_chain.cont.runtime.mesh)
    full = (
        single and same_mesh
        and c.off == 0 and out_chain.off == 0
        and c.cont.layout == out_chain.cont.layout
        # the shard_map program handles any uniform ceil layout, and
        # uneven block distributions for EVERY op: identity ops mask
        # pads; identityless ops read real totals at local[valid-1]
        # with an empty-shard-skipping fold (round 4 — the exclusive
        # variant seeds shard boundaries from that same fold, so no
        # identity is ever required).  View-chain ops fuse into the
        # program (round 5).
        and c.n == len(c.cont)
        # the fast program rebuilds the whole output array, so the output
        # window must cover the whole container too
        and out_chain.n == len(out_chain.cont)
    )
    # aligned subrange windows run the SAME program for every op
    # (round 4: identity-masked input, or window coordinates for
    # identityless ops)
    win_ok = (
        not full and single and same_mesh
        and c.cont.layout == out_chain.cont.layout
        and c.off == out_chain.off
    )
    # mismatched in/out windows or distributions on ONE mesh run the
    # window-coordinate program with a realign into the destination
    # geometry (round 5)
    mis_ok = not full and not win_ok and single and same_mesh
    if full or win_ok or mis_ok:
        mesh = c.cont.runtime.mesh
        dt = out_chain.cont.dtype
        aliased = (not full) and c.cont is out_chain.cont
        # view-chain ops make the post-op dtype program-defined; the
        # Pallas kernel's f32-accumulation contract is keyed on the
        # INPUT dtype, so chains conservatively take the XLA path.
        # The MISMATCHED route is gated off too (ADVICE r5 high): it
        # forces window-coordinate geometry whose per-shard slice
        # length comes from window_geometry and is generally not
        # lane-aligned — chunked_cumsum's pick_chunk assertion would
        # crash at trace time on TPU.
        from ..ops import kernels
        use_kernel = _use_scan_kernel(
            c.cont.layout, kind, c.cont.dtype, c.cont.runtime) \
            if (not c.ops) and not mis_ok else kernels.NO_KERNEL
        prog = _scan_program(
            mesh, c.cont.runtime.axis, c.cont.layout, kind, op,
            exclusive, dt, use_kernel=use_kernel,
            window=None if full else (c.off, c.n), aliased=aliased,
            ops=tuple(c.ops),
            out_layout=out_chain.cont.layout if mis_ok else None,
            out_window=(out_chain.off, out_chain.n) if mis_ok else None)
        svals = [jnp.asarray(s) for s in _chain_scalars([c])]
        out_chain.cont._data = prog(c.cont._data, *svals) \
            if full or aliased \
            else prog(c.cont._data, out_chain.cont._data, *svals)
        scanned = None
    elif single:
        # DIFFERENT MESHES: scan natively on the input's runtime, then
        # reshard the result into the destination window through the
        # redistribution engine's cross-mesh transport
        # (parallel/redistribute.reshard_copy, docs/SPEC.md §18 — the
        # same XLA-resharding class as before, now with the engine's
        # fault site/span/bytes counter; the scan collectives stay
        # native; round 5)
        from ..containers.distributed_vector import distributed_vector
        from ..parallel.redistribute import reshard_copy
        scratch = distributed_vector(c.n, dtype=out_chain.cont.dtype,
                                     runtime=c.cont.runtime)
        _scan(in_r, scratch, op, None, exclusive)
        reshard_copy(scratch, out)
        scanned = None
    else:
        from ..utils.fallback import warn_fallback
        warn_fallback("scan", "multi-component or host (non-distributed) input range")
        arr = in_r.to_array() if hasattr(in_r, "to_array") \
            else jnp.asarray(in_r)
        combine = combine_for(kind, op)
        scanned = _blocked_scan(
            combine, arr,
            _identity_for(kind, arr.dtype) if kind is not None else None,
            kind)
        if exclusive:
            ident = (_identity_for(kind, arr.dtype) if kind is not None
                     else arr[0] * 0)
            scanned = jnp.concatenate(
                [ident[None].astype(arr.dtype), scanned[:-1]])
        _write_window(out_chain, scanned[:out_chain.n])
    if init is not None:
        # std::inclusive_scan init semantics: init folds into every
        # prefix (position 0 included) — one fused pass, windows too
        _scan_apply_init(out, init, op, set_first=False)
    return out


def inclusive_scan(in_r, out, op: Callable = None, init=None):
    """Distributed inclusive prefix scan
    (shp/algorithms/inclusive_scan.hpp:25-148).  Inside
    ``dr_tpu.deferred()`` the scan is recorded OPAQUE: deferred until
    flush (record order preserved) but dispatched through its own
    program rather than fused into the neighboring run."""
    p = _plan_active()
    if p is not None:
        reads, writes = _scan_footprint(in_r, out)
        p.record_opaque(
            "inclusive_scan",
            lambda: _scan(in_r, out, op, init, exclusive=False),
            reads=reads, writes=writes)
        return out
    return _scan(in_r, out, op, init, exclusive=False)


def inclusive_scan_n(in_v, out, iters: int):
    """``iters`` chained add-scans in ONE jitted program (the
    ``span_halo.exchange_n`` measurement analog): each round scans the
    previous round's output, so per-op device time excludes the
    tunneled per-dispatch overhead and no extra elementwise pass skews
    the per-op traffic.  Values grow without bound (inf arithmetic
    runs at full speed on TPU): ``out`` is a timing aid, NOT
    cumsum(in)."""
    from ..plan import flush_reads
    flush_reads("inclusive_scan_n")  # direct _data access below
    ins = _resolve(in_v)
    out_chain = _out_chain(out)
    assert (ins is not None and len(ins) == 1 and not ins[0].ops
            and ins[0].off == 0 and out_chain.off == 0
            and ins[0].cont.layout == out_chain.cont.layout
            and uniform_layout(ins[0].cont.layout)
            and ins[0].n == len(ins[0].cont)
            and out_chain.n == len(out_chain.cont)), \
        "inclusive_scan_n takes two whole uniform-layout containers"
    c = ins[0]
    mesh = c.cont.runtime.mesh
    dtype = out_chain.cont.dtype
    use_kernel = _use_scan_kernel(c.cont.layout, "add", c.cont.dtype,
                                  c.cont.runtime)
    key = ("scan_n", pinned_id(mesh), c.cont.layout, str(dtype),
           int(iters), tuple(use_kernel),
           _kernel_variant() if use_kernel.use else None)
    prog = _prog_cache.get(key)
    if prog is None:
        one = _scan_program(
            mesh, c.cont.runtime.axis, c.cont.layout, "add", None,
            False, dtype, use_kernel=use_kernel)

        def many(d):
            return lax.fori_loop(0, iters, lambda _, x: one(x), d)

        prog = jax.jit(many)
        _prog_cache[key] = prog
    out_chain.cont._data = prog(c.cont._data)
    return out


def exclusive_scan(in_r, out, init=0, op: Callable = None):
    """Exclusive variant (std::exclusive_scan surface; the reference spec
    names it, doc/spec/source/algorithms/).  Deferred regions record it
    opaque, like :func:`inclusive_scan`."""
    p = _plan_active()
    if p is not None:
        reads, writes = _scan_footprint(in_r, out)
        p.record_opaque(
            "exclusive_scan",
            lambda: _exclusive_scan_eager(in_r, out, init, op),
            reads=reads, writes=writes)
        return out
    return _exclusive_scan_eager(in_r, out, init, op)


def _exclusive_scan_eager(in_r, out, init, op):
    out = _scan(in_r, out, op, None, exclusive=True)
    # exclusive scan seeds with init at position 0 and folds into the
    # rest.  Skippable only for the add identity: an UNCLASSIFIED op
    # (kind None) has no identity, so even init=0 must be applied —
    # op(0, x) need not equal x.
    kind = _classify_op(op)  # None op classifies as "add"
    skip = init is None or (kind == "add"
                            and isinstance(init, (int, float))
                            and init == 0)
    if not skip:
        _scan_apply_init(out, init, op)
    return out


def _scan_apply_init(out, init, op, set_first=True):
    """Fold ``init`` into a scan result: every covered position takes
    ``op(init, prefix)`` (exact by associativity); with ``set_first``
    (the exclusive-scan form) the first covered position is set to
    ``init`` EXACTLY — the scan program seeds it with the op identity
    when one exists, but an unclassified op's pseudo-identity (zero)
    would make ``op(init, 0)`` wrong there.  Inclusive init folds pass
    ``set_first=False`` (init folds into EVERY prefix).

    Both whole-container AND window outputs fold in ONE fused
    shard_map pass (round 4; init is a traced scalar, so loop-varying
    inits reuse the cached program): windows fold only masked cells,
    and the first covered position's owning shard + local column are
    static."""
    if op is None:
        op = operator.add
    kind = _classify_op(op)
    combine = combine_for(kind, op)
    chain = _out_chain(out)
    cont = chain.cont
    if chain.n == 0:
        return
    mesh = cont.runtime.mesh
    axis = cont.runtime.axis
    full = chain.off == 0 and chain.n == len(cont)
    window = None if full else (chain.off, chain.n)
    key = ("scan_init", pinned_id(mesh), axis, cont.layout, kind,
           _op_key(op) if kind is None else None, str(cont.dtype),
           window, set_first)
    prog = _prog_cache.get(key)
    if prog is None:
        nshards, S, cap, prev, nxt, n, starts, sizes = \
            working_geometry(cont.layout)
        starts_np = np.asarray(starts)
        sizes_np = np.asarray(sizes)
        off0 = chain.off
        # the shard owning the first covered position, and its local
        # column — STATIC (the first shard whose window contains off0)
        owner = next((i for i in range(nshards)
                      if sizes_np[i] > 0
                      and starts_np[i] <= off0 < starts_np[i]
                      + sizes_np[i]), 0)
        col0 = prev + (off0 - int(starts_np[owner]))
        if window is not None:
            wmask_c = jnp.asarray(np.asarray(
                owned_window_mask(cont.layout, *window)[0]))

        def body(blk, iv):
            r = lax.axis_index(axis)
            if window is None:
                x = blk[0, prev:prev + S]
                folded = combine(iv, x)
                if set_first:
                    # same owner predicate as the window path below:
                    # leading zero-size shards share start==0 with the
                    # owner and must not touch their pad cells
                    folded = folded.at[col0 - prev].set(
                        jnp.where(r == owner, iv, folded[col0 - prev]))
                if prev == 0 and nxt == 0 and cap == S:
                    return folded.astype(blk.dtype)[None]
                out_row = jnp.zeros((1, prev + cap + nxt), blk.dtype)
                return out_row.at[0, prev:prev + S].set(
                    folded.astype(blk.dtype))
            row = blk[0]
            folded = jnp.where(wmask_c[r], combine(iv, row),
                               row).astype(blk.dtype)
            if set_first:
                folded = folded.at[col0].set(
                    jnp.where(lax.axis_index(axis) == owner, iv,
                              folded[col0]).astype(blk.dtype))
            return folded[None]

        shm = jax.shard_map(body, mesh=mesh,
                            in_specs=(P(axis, None), P()),
                            out_specs=P(axis, None))
        prog = jax.jit(shm, donate_argnums=0)
        _prog_cache[key] = prog
    cont._data = prog(cont._data, jnp.asarray(init, cont.dtype))
