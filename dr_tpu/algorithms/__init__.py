from .elementwise import (fill, iota, copy, copy_async, for_each, transform,
                          to_numpy)
from .reduce import reduce, transform_reduce, dot
from .scan import inclusive_scan, exclusive_scan
from .stencil import stencil_transform, stencil_iterate
