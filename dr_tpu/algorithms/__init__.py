from .elementwise import (fill, iota, copy, copy_async, for_each, transform,
                          to_numpy)
from .reduce import (reduce, transform_reduce, dot, reduce_async,
                     transform_reduce_async, dot_async)
from .scan import inclusive_scan, exclusive_scan
from .sort import sort, sort_by_key, argsort, is_sorted
from .relational import (join, groupby_aggregate, unique, histogram,
                         top_k)
from .stencil import (stencil_transform, stencil_iterate,
                      stencil_iterate_blocked,
                      stencil_iterate_matmul)
from .stencil2d import stencil2d_transform, stencil2d_iterate, \
    heat_step_weights
from .gemv import gemv, flat_gemv, gemm, spmm
