"""Elementwise distributed algorithms: fill / iota / copy / for_each /
transform.

Reference behavior being matched (``include/dr/mhp/algorithms/
cpu_algorithms.hpp:14-94,148-167`` and ``shp/algorithms/for_each.hpp``,
``shp/copy.hpp``): every algorithm is collective and has two paths —

* **aligned fast path**: all operands share a segment layout, so the whole
  pipeline runs shard-local with zero communication.  Here that is ONE
  cached jitted XLA program over the padded ``(nshards, width)`` arrays:
  the view chain's ops, the user op, and the masked window write all fuse.
* **fallback**: the reference falls back to rank-0 serial element RMA
  (cpu_algorithms.hpp:44-54 — its known-slow path).  We instead evaluate
  through logical arrays and let XLA/GSPMD insert the resharding
  collectives — still compiled, still parallel, just with comm.

Mutation contract (SURVEY.md §7 hard-part 1): algorithms REBIND the output
container's array version; views write through to their base container.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ._common import owned_window_mask
from ..containers.distributed_vector import distributed_vector
from ..core.pinning import pinned_id
from ..views import views as _v

__all__ = ["fill", "iota", "copy", "copy_async", "for_each", "transform",
           "to_numpy"]


# ---------------------------------------------------------------------------
# chain resolution: view pipeline -> (container, offset, length, ops)
# ---------------------------------------------------------------------------

# Stable cache key for user callables and meshes (see core/pinning.py).
_op_key = pinned_id


def _plan_active():
    """The recording deferred plan, if any (dr_tpu/plan.py).  Lazy
    import: plan builds on this module, so the dependency must point
    this way only at call time."""
    from ..plan import active
    return active()


def _traced_op_key(op):
    """Cache key for a chain op in the SPECIALIZED program paths (the
    ones that feed BoundOp scalars as traced operands): a BoundOp keys
    on its underlying op + scalar COUNT, so streaming values reuse the
    program.  Paths that CALL ops directly (materialization, generic
    reduce) must keep ``_op_key`` — identity keying bakes the values,
    which is correct there."""
    if isinstance(op, _v.BoundOp):
        return ("bnd", pinned_id(op.op), len(op.scalars))
    return pinned_id(op)


def _chain_scalars(chains):
    """BoundOp scalar values across all chain ops, in the deterministic
    (chain-major, op-order) sequence the program bodies consume."""
    out = []
    for c in chains:
        for o in c.ops:
            if isinstance(o, _v.BoundOp):
                out.extend(o.scalars)
    return out


def _apply_chain_ops(v, ops, sc_iter):
    """Apply a chain's ops; BoundOp ops draw their scalars (traced) from
    ``sc_iter`` in the :func:`_chain_scalars` order."""
    for o in ops:
        if isinstance(o, _v.BoundOp):
            v = o.op(v, *[next(sc_iter) for _ in o.scalars])
        else:
            v = o(v)
    return v


class _Chain:
    __slots__ = ("cont", "off", "n", "ops")

    def __init__(self, cont, off, n, ops):
        self.cont = cont
        self.off = off
        self.n = n
        self.ops = tuple(ops)

    @property
    def key(self):
        return (pinned_id(self.cont.runtime.mesh), self.cont.layout,
                self.off, self.n, tuple(_traced_op_key(op) for op in self.ops))


def _resolve(r) -> Optional[Tuple[_Chain, ...]]:
    """Resolve ``r`` into per-leaf chains over containers, or None."""
    if isinstance(r, distributed_vector):
        return (_Chain(r, 0, len(r), ()),)
    if isinstance(r, _v.subrange):
        inner = _resolve(r.base)
        if inner is None:
            return None
        return tuple(_Chain(c.cont, c.off + r.start, len(r), c.ops)
                     for c in inner)
    if isinstance(r, _v.transform):
        inner = _resolve(r.base)
        if inner is None:
            return None
        if len(inner) == 1:
            c = inner[0]
            return (_Chain(c.cont, c.off, c.n, c.ops + (r.op,)),)
        return None  # transform-over-zip handled by the caller's op fusion
    if isinstance(r, _v.zip_view):
        chains = []
        for comp in r.components:
            inner = _resolve(comp)
            if inner is None or len(inner) != 1:
                return None
            chains.append(inner[0])
        n = len(r)
        return tuple(_Chain(c.cont, c.off, n, c.ops) for c in chains)
    return None


def _fast_aligned(ins: Tuple[_Chain, ...], out: _Chain) -> bool:
    """Aligned == same MESH, same layout, same window offset: segment
    (rank, size) lists are then pairwise equal, the mhp::aligned
    condition.  Mesh equality matters beyond the layout: equal shard
    counts over different device sets cannot share one program
    (round-5 review finding)."""
    return all(c.cont.layout == out.cont.layout and c.off == out.off
               and c.cont.runtime.mesh == out.cont.runtime.mesh
               for c in ins)


# ---------------------------------------------------------------------------
# fused elementwise programs
# ---------------------------------------------------------------------------

from ..utils.spmd_guard import TappedCache

# Shared program cache for the algorithm layer.  Every dispatch does a
# get/setdefault here FIRST (hit or miss), so the lookup doubles as the
# SPMD dispatch-order tap (utils/spmd_guard); the per-module caches in
# halo/collectives/containers/ring_attention are TappedCaches too.
_prog_cache: dict = TappedCache()


def _window_program(out_chain: _Chain, in_keys, in_ops, op, with_index,
                    alias_mask=(), nscalars=0):
    """Cached program: out_data <- masked-window write of
    op(chains(in_data...)) over padded shard arrays.  ``alias_mask[i]``
    marks inputs that ARE the output container (in-place for_each): they
    read the donated buffer instead of being passed twice.  The last
    ``nscalars`` program arguments are TRACED scalars appended to the
    op's arguments — per-call values (a CG loop's alpha/beta) reuse one
    compiled program instead of baking each closure into a new one."""
    cont = out_chain.cont
    off, n = out_chain.off, out_chain.n
    # chain-op BoundOp scalars arrive FIRST in the scalar tail, then the
    # public transform scalars; nscalars counts both
    nchain = sum(len(o.scalars) for ops in in_ops for o in ops
                 if isinstance(o, _v.BoundOp))
    key = ("ew", cont.layout, off, n, in_keys,
           tuple(tuple(_traced_op_key(o) for o in ops) for ops in in_ops),
           _op_key(op), with_index, alias_mask, nscalars, str(cont.dtype))
    prog = _prog_cache.get(key)
    if prog is not None:
        return prog

    def body(out_data, *rest):
        extra_datas = rest[:len(rest) - nscalars]
        scalars = rest[len(rest) - nscalars:]
        sc_iter = iter(scalars[:nchain])
        op_scalars = scalars[nchain:]
        it = iter(extra_datas)
        in_datas = [out_data if aliased else next(it)
                    for aliased in alias_mask] if alias_mask else []
        vals_in = [_apply_chain_ops(d, ops, sc_iter)
                   for d, ops in builtin_zip(in_datas, in_ops)]
        # global index of every padded cell (halo/pad cells -> out of window)
        mask, gid = owned_window_mask(cont.layout, off, n)
        args = (list(vals_in) + list(op_scalars))
        if with_index:
            vals = op(gid, *args) if args else op(gid)
        else:
            vals = op(*args) if args else op()
        vals = jnp.broadcast_to(vals, out_data.shape).astype(out_data.dtype)
        return jnp.where(mask, vals, out_data)

    prog = jax.jit(body, donate_argnums=0)
    _prog_cache[key] = prog
    return prog


builtin_zip = zip
builtin_enumerate = enumerate


def _run_fused(ins: Tuple[_Chain, ...], out_chain: _Chain, op,
               with_index=False, scalars=()) -> None:
    out_cont = out_chain.cont
    alias_mask = tuple(c.cont is out_cont for c in ins)
    all_scalars = _chain_scalars(ins) + list(scalars)
    prog = _window_program(
        out_chain,
        tuple(c.cont.layout for c in ins),
        tuple(c.ops for c in ins),
        op, with_index, alias_mask, len(all_scalars))
    extra = [c.cont._data for c in ins if c.cont is not out_cont]
    # scalars keep their own (weak) dtype so the op computes in the same
    # promoted type as the fallback path; the window write casts to the
    # container dtype either way
    svals = [jnp.asarray(s) for s in all_scalars]
    out_cont._data = prog(out_cont._data, *extra, *svals)


def _write_window(out_chain: _Chain, values) -> None:
    """Fallback write: splice values into the container's logical array."""
    cont = out_chain.cont
    if isinstance(values, jax.Array) and values.sharding.device_set \
            != frozenset(cont.runtime.devices):
        # cross-MESH write (e.g. the sort_by_key reshard route): a
        # committed array from another device mesh cannot enter this
        # mesh's programs — explicit transfer first (XLA resharding;
        # same-device-set sharding mismatches need no help, GSPMD
        # reshards inside the program)
        values = jax.device_put(
            values, cont.runtime.sharding(None))
    arr = cont.to_array()
    arr = arr.at[out_chain.off:out_chain.off + out_chain.n].set(
        values.astype(cont.dtype))
    cont.assign_array(arr)


def _out_chain(out) -> _Chain:
    res = _resolve(out)
    if res is None or len(res) != 1 or res[0].ops:
        raise TypeError(
            "output must be a distributed_vector or a subrange view over one")
    return res[0]


# ---------------------------------------------------------------------------
# public algorithms
# ---------------------------------------------------------------------------

def _generator_program(out_chain: _Chain, kind: str):
    """Cached fill/iota program; the scalar is a traced argument so repeated
    calls with different values reuse one compiled program."""
    cont = out_chain.cont
    key = ("gen", kind, cont.layout, out_chain.off, out_chain.n,
           str(cont.dtype))
    prog = _prog_cache.get(key)
    if prog is not None:
        return prog
    layout, off, n = cont.layout, out_chain.off, out_chain.n

    def body(out_data, scalar):
        mask, gid = owned_window_mask(layout, off, n)
        if kind == "fill":
            vals = jnp.broadcast_to(scalar, out_data.shape)
        else:
            vals = gid + scalar
        return jnp.where(mask, vals.astype(out_data.dtype), out_data)

    prog = jax.jit(body, donate_argnums=0)
    _prog_cache[key] = prog
    return prog


def fill(r, value) -> None:
    """Collective fill (cpu_algorithms.hpp:14-28; shp/copy.hpp:147-174)."""
    out = _out_chain(r)
    p = _plan_active()
    if p is not None:
        p.record_generator(out, "fill", value)
        return
    prog = _generator_program(out, "fill")
    out.cont._data = prog(out.cont._data, jnp.asarray(value, out.cont.dtype))


def iota(r, start=0) -> None:
    """Collective iota (cpu_algorithms.hpp:83-94).  The reference routes
    every element through rank-0 RMA; here it is one sharded program."""
    out = _out_chain(r)
    p = _plan_active()
    if p is not None:
        p.record_generator(out, "iota", start - out.off)
        return
    prog = _generator_program(out, "iota")
    out.cont._data = prog(out.cont._data,
                          jnp.asarray(start - out.off))


def transform(in_r, out, op: Callable, *scalars) -> None:
    """Collective transform (cpu_algorithms.hpp:148-167).  ``op`` is a
    jax-traceable elementwise callable; over a zip input it receives one
    argument per component.  Trailing ``*scalars`` are appended to the
    op's arguments as TRACED values: pass loop-varying coefficients
    (a CG iteration's alpha/beta) here — with a module-level ``op`` the
    compiled program is reused across calls, where a fresh closure per
    value would compile (and pin) a new program every iteration."""
    out_chain = _out_chain(out)
    ins = _resolve(in_r)
    n = len(in_r)
    assert out_chain.n >= n, "output window too small"
    if n < out_chain.n:
        # narrow via a NEW chain: the key property must always reflect
        # the window actually written (VERDICT r1 noted the in-place
        # narrow as a future cache-key footgun)
        out_chain = _Chain(out_chain.cont, out_chain.off, n,
                           out_chain.ops)
    if ins is not None and _fast_aligned(ins, out_chain):
        p = _plan_active()
        if p is not None:
            p.record_transform(ins, out_chain, op, scalars)
            return
        _run_fused(ins, out_chain, op, scalars=scalars)
        return
    p = _plan_active()
    if p is not None:
        # the materialize route cannot fuse into a deferred run
        p.nonfusible("transform (unaligned/materialize route)")
    # fallback: logical-array evaluation; XLA inserts the resharding
    arr = in_r.to_array() if hasattr(in_r, "to_array") else jnp.asarray(in_r)
    vals = op(*arr, *scalars) if isinstance(arr, tuple) \
        else op(arr, *scalars)
    _write_window(out_chain, vals[:out_chain.n] if vals.shape[0] != out_chain.n
                  else vals)


def copy(src, dst) -> None:
    """Collective copy (cpu_algorithms.hpp:36-54; shp/copy.hpp:16-138).
    Accepts host arrays on either side like the shp host<->device overloads."""
    if isinstance(src, (np.ndarray, jax.Array, list, tuple)) and \
            not hasattr(src, "__dr_segments__"):
        out = _out_chain(dst)
        p = _plan_active()
        if p is not None:
            p.record_splice(out, jnp.asarray(src, out.cont.dtype))
            return
        _write_window(out, jnp.asarray(src, out.cont.dtype))
        return
    if isinstance(dst, np.ndarray):
        vals = to_numpy(src)
        dst[:len(vals)] = vals
        return
    transform(src, dst, _identity)


def _identity(x):
    return x


def copy_async(src, dst):
    """shp::copy_async parity: JAX dispatch is already asynchronous; the
    returned handle's .wait() blocks (event-join, shp/copy.hpp:116-138)."""
    copy(src, dst)

    class _Event:
        def __init__(self, cont):
            self._cont = cont

        def wait(self):
            if hasattr(self._cont, "block_until_ready"):
                self._cont.block_until_ready()
    tgt = dst if hasattr(dst, "block_until_ready") else None
    return _Event(tgt if tgt is not None else dst)


def for_each(r, fn: Callable, *scalars) -> None:
    """Collective in-place for_each (cpu_algorithms.hpp:63-74;
    shp/algorithms/for_each.hpp:16-92).

    Semantic shift for immutable arrays: ``fn`` is PURE — it receives the
    element value(s) and returns the new value(s); over a zip range it
    returns a tuple (one entry per component) to write back.  Trailing
    ``*scalars`` are TRACED arguments appended to ``fn``'s, exactly as
    in :func:`transform`."""
    if isinstance(r, _v.zip_view):
        outs = [_out_chain(c) for c in r.components]
        ins = _resolve(r)
        if ins is not None and all(_fast_aligned(ins, oc) for oc in outs):
            p = _plan_active()
            if p is not None:
                p.record_zip_foreach(ins, outs, fn, scalars)
                return
            conts = [oc.cont for oc in outs]
            # inputs that are also outputs read the donated buffers
            alias = tuple(
                next((i for i, c in builtin_enumerate(conts)
                      if c is ch.cont), -1) for ch in ins)
            # zip components are all OUTPUTS (_out_chain rejects ops),
            # so these chains can never carry BoundOps — only the public
            # fn scalars flow through
            prog = _zip_foreach_program(ins, outs, fn, alias,
                                        len(scalars))
            extra = [ch.cont._data for ch, a in builtin_zip(ins, alias)
                     if a < 0]
            svals = [jnp.asarray(sv) for sv in scalars]
            datas = prog(*[c._data for c in conts], *extra, *svals)
            for cont, nd in builtin_zip(conts, datas):
                cont._data = nd
            return
        p = _plan_active()
        if p is not None:
            p.nonfusible("for_each (misaligned zip route)")
        arrs = r.to_array()
        vals = fn(*arrs, *scalars)
        if not isinstance(vals, tuple):
            raise TypeError("for_each over zip: fn must return a tuple")
        for oc, v in builtin_zip(outs, vals):
            _write_window(oc, v)
        return
    transform(r, r, fn, *scalars)


def _zip_foreach_program(ins, outs, fn, alias, nscalars=0):
    key = ("zfe", tuple(c.key for c in ins), tuple(o.key for o in outs),
           _op_key(fn), alias, nscalars)
    prog = _prog_cache.get(key)
    if prog is not None:
        return prog
    k = len(outs)
    cont = outs[0].cont
    off, n = outs[0].off, outs[0].n
    in_ops = tuple(c.ops for c in ins)
    # The body below applies chain ops by DIRECT CALL, which bakes any
    # BoundOp scalar values into the compiled program — while _Chain.key
    # keys BoundOps by scalar COUNT.  Pairing the two would silently
    # reuse stale scalars, so enforce the invariant _out_chain provides
    # (zip components are outputs and outputs carry no ops).
    assert not any(isinstance(o, _v.BoundOp) for ops in in_ops
                   for o in ops), \
        "zip for_each chains must not carry BoundOps (value-baking body)"

    def body(*datas):
        out_datas = datas[:k]
        extra_datas = datas[k:len(datas) - nscalars]
        fn_scalars = datas[len(datas) - nscalars:]
        it = iter(extra_datas)
        in_datas = [out_datas[a] if a >= 0 else next(it) for a in alias]
        vals_in = []
        for data, ops in builtin_zip(in_datas, in_ops):
            v = data
            for o in ops:
                v = o(v)
            vals_in.append(v)
        new_vals = fn(*vals_in, *fn_scalars)
        mask, _gid = owned_window_mask(cont.layout, off, n)
        return tuple(
            jnp.where(mask, nv.astype(od.dtype), od)
            for od, nv in builtin_zip(out_datas, new_vals))

    prog = jax.jit(body, donate_argnums=tuple(range(k)))
    _prog_cache[key] = prog
    return prog


def to_numpy(r) -> np.ndarray:
    """Materialize a distributed range on the host (test-oracle path).
    Valid on every process in multi-host runs (utils/host.py)."""
    from ..utils.host import to_host
    if hasattr(r, "to_array"):
        arr = r.to_array()
        if isinstance(arr, tuple):
            return tuple(to_host(a) for a in arr)
        return to_host(arr)
    return np.asarray(r)
