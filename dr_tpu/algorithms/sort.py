"""Distributed sort — regular-sample sort over the mesh.

Beyond-parity surface: the reference snapshot (v0.1) ships no
distributed sort (its spec and later revisions of the proposal name
one), so this is designed TPU-first rather than re-designed: ONE jitted
``shard_map`` program per layout doing

1. local sort of the owned (masked) cells — the monotone key encoding
   (64-bit sign-flip for f64) is FUSED into the same program, and with
   a payload only the GLOBAL INDEX rides as a tiebreak channel (the
   payload itself never enters a sort — see phase 6),
2. splitter selection by REGULAR SAMPLING — each shard contributes
   ``p-1`` evenly spaced elements of its sorted run, the ``p*(p-1)``
   samples are ``all_gather``-ed and the global splitters are the
   evenly spaced elements of their sorted order (the classic bound:
   every destination bucket then holds fewer than ``2*seg`` elements,
   which only affects balance — correctness never depends on it),
3. bucket exchange as ONE ``all_to_all`` of a ``(p, seg)`` send matrix
   (row ``d`` = my elements belonging to shard ``d``, padded with the
   dtype's maximum).  The sorted run makes every destination's bucket a
   CONTIGUOUS slice (round 6), so the matrix is a shifted take with
   FRONT-ALIGNED rows, its per-destination counts come from ``p``
   searchsorteds, and ONE ``all_gather`` of the count vector replaces
   the old count ``all_to_all`` plus the rebalance-side ``all_gather``.
   A single source's bucket can never exceed its own ``seg`` elements,
   so the matrix is overflow-free BY CONSTRUCTION — no variable-length
   transport needed under XLA's static shapes,
4. local merge (one ``lax.sort`` of the received matrix — every
   sorted channel set is a TOTAL order, so no stable comparator: see
   "comparator discipline" below), and
5. rebalance back to the uniform block layout: the counts matrix gives
   exclusive offsets, each source pre-places its elements at their
   destination-window positions in a second ``(p, seg)`` matrix, and
   after a second ``all_to_all`` each output cell is the SUM of its
   column — every global position is covered by exactly one source, so
   masked-sum assembly replaces the scatter TPU doesn't like,
6. (key-value only, round 6 "single-exchange payload plan") payload
   move: the rebalanced GLOBAL-INDEX channel IS the sort permutation in
   destination coordinates, so each payload channel moves ONCE — one
   ``all_gather`` of the request indices plus one masked ``all_to_all``
   per channel — instead of riding the local sort, the bucket exchange,
   the merge, and the rebalance as a data channel.

Comparator discipline (round 6): XLA's VARIADIC sort (multiple
operands) costs several times its single-channel form, and stable
comparators cost more than unstable ones on the structured inputs the
hot path actually sees (the merge's concatenated sorted runs, chained
re-sorts of sorted data).  Keys-only sorts therefore run ONE channel
unstable (duplicates are bit-identical — placement among equals is
unobservable) and key-value sorts run exactly two channels — (key,
global index), a TOTAL order, so unstable is still exact and the old
explicit-stability flag is unnecessary.  ``DR_TPU_SORT_STABLE=1``
forces stable comparators back on for A/B sweeps (tune_tpu.py sort).

Descending order costs nothing extra: phase 5's index map places
element ``g`` of the ascending order at global position ``n-1-g``.

PHASE PROFILING (round 6): ``_sort_program`` takes ``stop_after`` — a
phase name from :data:`SORT_PHASES` / :data:`SORTKV_PHASES` — and
builds the SAME program truncated after that phase (returning a row of
the normal output shape derived from the last phase's values, so the
``sort_phases_n`` / ``sort_by_key_phases_n`` fused loops can chain it).
``utils.profiling.profile_phases`` turns consecutive truncations into a
per-phase time breakdown; bench.py emits it into the bench JSON detail
and ``tools/tune_tpu.py sort`` prints the ladder.

Uneven ``block_distribution`` layouts (including zero-size "team"
shards) run the SAME program: the geometry enters as static per-shard
starts/sizes, phase 5 rebalances into the destination distribution's
windows, and the bucket matrices stay overflow-free (a source's bucket
never exceeds its own real count).  Subrange windows run the SAME
program in window-relative coordinates (round 4): the window's shard
intersections are static uneven geometry, and a masked row blend
leaves outside cells untouched bit-exactly.  float64 keys run the
SAME program through a 64-bit sign-flip encoding (round 5; exact —
only reachable on x64-enabled CPU meshes, TPU has no f64).
The write target must be a ``distributed_vector`` or a subrange window
over one; transform views and other read-only ranges are rejected with
``TypeError`` (sorting them in place has no meaning).
"""

from __future__ import annotations

from ..utils.env import env_flag

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ._common import (owned_window_mask, window_geometry,
                      working_geometry)
from .elementwise import (_apply_chain_ops, _chain_scalars, _out_chain,
                          _prog_cache, _resolve, _traced_op_key)
from ..core.pinning import pinned_id
from ..ops import kernels, sort_pallas
from ..views import views as _v

__all__ = ["sort", "sort_by_key", "argsort", "is_sorted",
           "SORT_PHASES", "SORTKV_PHASES"]


def _plan_barrier(what: str) -> None:
    """Sort-family ops are NON-FUSIBLE in deferred regions (ISSUE 3):
    flush the active plan (warn_fallback-announced) before dispatching
    eagerly, so the recorded prefix lands first and in order.  Lazy
    delegation to the ONE implementation in dr_tpu/plan.py."""
    from ..plan import barrier
    barrier(what)


_NAN_KEY = np.uint32(0xFFFFFFFE)  # after +inf (numpy sorts NaNs last)
_PAD_KEY = np.uint32(0xFFFFFFFF)  # strictly after every real key
# 64-bit twins for real float64 keys (only reachable on x64-enabled CPU
# meshes — TPU has no f64; with x64 disabled a "float64" container
# stores f32 and takes the 32-bit path, which is then exact)
_NAN_KEY64 = np.uint64(0xFFFFFFFFFFFFFFFE)
_PAD_KEY64 = np.uint64(0xFFFFFFFFFFFFFFFF)

# program phases, in execution order (profiling vocabulary; the last
# name denotes the FULL program).  p == 1 meshes have no collective
# phases: every truncation beyond local_sort runs the full program.
SORT_PHASES = ("local_sort", "splitter", "exchange", "merge",
               "rebalance")
SORTKV_PHASES = ("local_sort", "splitter", "exchange", "merge",
                 "rebalance", "payload")


def _stable_override() -> bool:
    """``DR_TPU_SORT_STABLE=1`` forces stable comparators on every
    ``lax.sort`` in the family (A/B knob for ``tune_tpu.py sort``);
    part of every program cache key so in-process sweeps rebuild."""
    return env_flag("DR_TPU_SORT_STABLE")


def _encode(x, distinct_zeros=False):
    """Monotone total-order sort key.

    Floats map through the IEEE sign-flip trick to ``uint32`` (bf16/f16
    upcast exactly first; real f64 arrays — x64-enabled meshes only —
    through the same trick at 64 bits, so f64 pairs closer than an f32
    ulp keep their exact order), with every NaN canonicalized to
    ``_NAN_KEY`` — after +inf, matching numpy's NaNs-last order, and
    BEFORE the pad sentinel, so the positional validity mask stays
    exact even for NaN data.  Integers are their own keys (the pad
    sentinel is the dtype max; real values equal to it merely tie with
    padding, and ties among equals cannot change the sorted output).

    ``distinct_zeros``: the sign-flip trick already orders -0.0
    (0x7FFFFFFF) just before +0.0 (0x80000000) — a valid sort order
    that round-trips the zero's sign through :func:`_decode`.  Keys-
    only ``sort()`` uses it so the output is a bit-exact permutation of
    the input.  Default OFF collapses both zeros to ONE key so they
    tie: ``sort_by_key`` needs IEEE-equal keys to keep numpy-stable
    tie order, and ``is_sorted`` must not report ``[0.0, -0.0]`` as
    unsorted."""
    if x.dtype == jnp.dtype(np.float64):
        b = jax.lax.bitcast_convert_type(x, jnp.uint64)
        k = jnp.where(b >> 63 == 1, ~b, b | jnp.uint64(1 << 63))
        if not distinct_zeros:
            k = jnp.where(x == 0, jnp.uint64(1 << 63), k)
        return jnp.where(jnp.isnan(x), _NAN_KEY64, k), _PAD_KEY64
    if jnp.issubdtype(x.dtype, jnp.floating):
        b = jax.lax.bitcast_convert_type(x.astype(jnp.float32),
                                         jnp.uint32)
        k = jnp.where(b >> 31 == 1, ~b, b | jnp.uint32(0x80000000))
        if not distinct_zeros:
            k = jnp.where(x == 0, jnp.uint32(0x80000000), k)
        return jnp.where(jnp.isnan(x), _NAN_KEY, k), _PAD_KEY
    return x, jnp.array(jnp.iinfo(x.dtype).max, x.dtype)


def _kernel_key_dtype(dtype):
    """Static mirror of :func:`_encode`'s output dtype for the ACTUAL
    array storage (declared 64-bit containers store 32-bit when x64 is
    off) — the sort_local kernel arm's eligibility is decided before
    any array exists."""
    dt = jnp.dtype(dtype)
    x64 = bool(jax.config.jax_enable_x64)
    if jnp.issubdtype(dt, jnp.floating):
        return np.dtype(np.uint64) \
            if (dt == jnp.dtype(np.float64) and x64) \
            else np.dtype(np.uint32)
    ndt = np.dtype(dt.name) if dt.kind in "iub" else np.dtype(dt)
    if ndt.kind in "iu" and ndt.itemsize == 8 and not x64:
        ndt = np.dtype(ndt.name.replace("64", "32"))
    return ndt


def _decode(k, dtype):
    """Inverse of :func:`_encode` (NaN payload/sign canonicalized);
    the key WIDTH picks the float branch — a declared-f64 container on
    an x64-disabled mesh stores f32 and round-trips through uint32."""
    if k.dtype == jnp.dtype(np.uint64):
        b = jnp.where(k >> 63 == 1, k ^ jnp.uint64(1 << 63), ~k)
        x = jax.lax.bitcast_convert_type(b, jnp.float64)
        return jnp.where(k == _NAN_KEY64, jnp.float64(jnp.nan),
                         x).astype(dtype)
    if jnp.issubdtype(jnp.dtype(dtype), jnp.floating):
        b = jnp.where(k >> 31 == 1, k ^ jnp.uint32(0x80000000), ~k)
        x = jax.lax.bitcast_convert_type(b, jnp.float32)
        return jnp.where(k == _NAN_KEY, jnp.float32(jnp.nan),
                         x).astype(dtype)
    return k.astype(dtype)


def _pack_row(row, layout, dtype):
    """Place a working-width row back into a padded shard row."""
    p, S, cap, prev, nxt, n, starts, sizes = working_geometry(layout)
    if prev == 0 and nxt == 0 and cap == S:
        return row.astype(dtype)[None]
    out = jnp.zeros((1, prev + cap + nxt), dtype)
    return out.at[0, prev:prev + S].set(row.astype(dtype))


def _sort_program(mesh, axis, layout, dtype, descending,
                  pay_layout=None, pay_dtype=None, window=None,
                  pay_window=None, aliased=False, stop_after=None):
    """The sample-sort program; with ``pay_layout`` set it carries a
    stable key-value sort — the keys travel with the original GLOBAL
    INDEX as an explicit tiebreak channel, and the payload moves ONCE
    at the end through the rebalanced index channel (phase 6, the
    round-6 single-exchange payload plan; the round-5 form dragged the
    payload through the local sort, the exchange, the merge, and the
    rebalance as a data channel — on XLA's costly variadic sort path).

    ``window=(off, wn)`` sorts ONLY the logical subrange [off, off+wn)
    in place (round 4 — windows used to materialize): the window's
    shard intersections form a static uneven geometry the same phases
    run over, each shard reads its slice at a static per-shard offset,
    and the output row blends sorted window cells with untouched
    originals through the static owned_window_mask.

    ``aliased`` (round 5): key and payload windows live in ONE
    container — the program takes a single donated row, reads both
    windows from it (both slices come from the ORIGINAL row), and
    blends both results into that one row, payload LAST — so
    overlapping windows deterministically take the payload value,
    the same order the old sequential fallback wrote.

    ``stop_after`` (round 6, profiling aid): a phase name from
    :data:`SORT_PHASES` / :data:`SORTKV_PHASES` truncates the program
    after that phase.  The truncated program still returns rows of the
    normal output shape — the key row is derived from the last phase's
    values (mixed so XLA can neither fold nor dead-code-eliminate the
    phase work), the payload row passes through untouched — so the
    fused ``*_phases_n`` loops chain it and the marginal method prices
    each prefix; consecutive differences are the per-phase costs."""
    phases = SORTKV_PHASES if pay_layout is not None else SORT_PHASES
    if stop_after is not None:
        assert stop_after in phases, (stop_after, phases)
        if stop_after == phases[-1]:
            stop_after = None  # the full program IS the last phase
    stable = _stable_override()
    # kernel-arm decision (docs/SPEC.md §22): the sort_local Pallas
    # bitonic replaces phase 1's lax.sort when picked.  Resolved HERE,
    # before the cache lookup, so the pick is part of the program's
    # identity and the kernel.build fault site fires per dispatch.
    kdt = _kernel_key_dtype(dtype)
    S_el = (working_geometry(layout)[1] if window is None
            else window_geometry(layout, *window)[1])
    kern = kernels.use_kernel(
        "sort_local", kernels.mesh_platform(mesh),
        eligible=sort_pallas.eligible(S_el, kdt, interpret=True))
    if kern.use and not sort_pallas.eligible(S_el, kdt,
                                             interpret=kern.interpret):
        kern = kernels.NO_KERNEL  # wide keys are interpret-only
    key = ("sort", pinned_id(mesh), axis, layout, str(dtype),
           bool(descending), pay_layout,
           str(pay_dtype) if pay_layout else None, window, pay_window,
           aliased, stop_after, stable, tuple(kern),
           # x64 state changes the traced key width for declared-f64
           # containers (uint32 under x64-off, uint64 under x64-on)
           bool(jax.config.jax_enable_x64))
    prog = _prog_cache.get(key)
    if prog is not None:
        return prog

    # general geometry: uniform ceil layouts AND uneven
    # block_distributions share one program shape — S is the max owned
    # width, starts/sizes the per-shard logical windows
    if window is None:
        p, S, cap, prev, nxt, n, starts, sizes = working_geometry(layout)
        wstart = None
    else:
        p, S, cap, prev, nxt, n, starts, sizes, wstart = \
            window_geometry(layout, *window)
        width = prev + cap + nxt
        woff_c = jnp.asarray(wstart, jnp.int32)
        mask_c = jnp.asarray(
            np.asarray(owned_window_mask(layout, *window)[0]))
    pprev = pay_layout[2] if pay_layout else 0
    starts_c = jnp.asarray(starts, jnp.int32)
    sizes_c = jnp.asarray(sizes, jnp.int32)
    if pay_layout is not None and window is not None:
        # windowed key-value sort (round 4): the payload window has its
        # OWN static geometry — extraction offsets, the phase-5 index
        # rebalance destination, the phase-6 gather ownership, and the
        # output blend mask all come from it, in window coordinates
        _, Sp, pcap2, pprev2, pnxt2, _, pstarts, psizes, pwstart = \
            window_geometry(pay_layout, *pay_window)
        pwidth = pprev2 + pcap2 + pnxt2
        pwoff_c = jnp.asarray(pwstart, jnp.int32)
        pay_mask_c = jnp.asarray(np.asarray(
            owned_window_mask(pay_layout, *pay_window)[0]))
        pstarts_c = jnp.asarray(pstarts, jnp.int32)
        psizes_c = jnp.asarray(psizes, jnp.int32)
    elif pay_layout is not None:
        # the payload may carry a DIFFERENT block distribution (round
        # 4): its own static geometry drives the index rebalance and
        # the gather ownership test — nothing realigns on entry any
        # more (round 6: the payload is only ever read by the gather)
        _, Sp, _, _, _, _, pstarts, psizes = working_geometry(pay_layout)
        pstarts_c = jnp.asarray(pstarts, jnp.int32)
        psizes_c = jnp.asarray(psizes, jnp.int32)
    else:
        Sp = S

    GMAX = np.int32(np.iinfo(np.int32).max)

    def body(blk, *pay):  # padded shard rows: keys (+ payload)
        if aliased:
            pay = (blk,)  # payload window read from the SAME row
        r = lax.axis_index(axis)
        if window is None:
            raw = blk[0, prev:prev + S]
        else:
            # my window slice, at a per-shard static offset (traced
            # via the constant table); clip keeps the take in range,
            # the nvalid mask discards the clipped tail
            idx = jnp.clip(prev + woff_c[r] + jnp.arange(S), 0,
                           width - 1)
            raw = jnp.take(blk[0], idx)
        # keys-only sort is a bit-exact permutation (distinct -0.0/+0.0
        # keys); key-value sort collapses the zeros so ties keep
        # numpy-stable original order
        kv, big = _encode(raw, distinct_zeros=not pay)
        nvalid = jnp.minimum(sizes_c[r],
                             jnp.clip(n - starts_c[r], 0, S))
        gid = starts_c[r] + jnp.arange(S)
        local_ok = jnp.arange(S) < nvalid
        kv = jnp.where(local_ok, kv, big)       # mask pad cells

        def pay_vec(v):
            # payload cells in their OWN (window) coordinates; only the
            # phase-6 gather ever reads them
            if window is not None:
                pidx = jnp.clip(pprev2 + pwoff_c[r] + jnp.arange(Sp),
                                0, pwidth - 1)
                return jnp.take(v[0], pidx)
            return v[0, pprev:pprev + Sp]

        def pay_gather(perm):
            # phase 6: move each payload channel ONCE.  ``perm`` holds,
            # per destination slot of MY payload window, the original
            # window position whose payload lands there (the rebalanced
            # global-index channel).  Every position is owned by exactly
            # one source shard under the payload distribution, so one
            # all_gather of the request indices + one masked all_to_all
            # per channel assembles the result (the rebalance pattern).
            rows = [pay_vec(v) for v in pay]
            if p == 1:
                ok = jnp.arange(Sp) < psizes_c[r]
                return [jnp.where(ok,
                                  jnp.take(vr, jnp.clip(perm, 0,
                                                        Sp - 1)),
                                  jnp.zeros((), vr.dtype))
                        for vr in rows]
            G = lax.all_gather(perm, axis)                   # (p, Sp)
            idxl = G - pstarts_c[r]
            dest_ok = jnp.arange(Sp)[None, :] < psizes_c[:, None]
            own = dest_ok & (idxl >= 0) & (idxl < psizes_c[r])
            outs = []
            for vr in rows:
                send = jnp.where(
                    own, jnp.take(vr, jnp.clip(idxl, 0, Sp - 1)),
                    jnp.zeros((), vr.dtype))
                outs.append(jnp.sum(lax.all_to_all(send, axis, 0, 0),
                                    axis=0))
            return outs

        def finish(kvec, pay_res=None):
            # shared output tail: decode + window blend / row pack.
            # ``pay_res=None`` with a payload means a TRUNCATED
            # program: the payload rows pass through untouched (honest
            # — no phase before "payload" touches them).
            if window is not None:
                decoded = _decode(kvec, dtype)
                col_idx = jnp.clip(
                    jnp.arange(width) - prev - woff_c[r], 0, S - 1)
                krow = jnp.where(mask_c[r], jnp.take(decoded, col_idx),
                                 blk[0])[None]
                if not pay:
                    return krow
                if pay_res is None:
                    return krow if aliased else (krow, pay[0])
                pcol_idx = jnp.clip(
                    jnp.arange(pwidth) - pprev2 - pwoff_c[r], 0,
                    Sp - 1)
                if aliased:
                    # both windows blend into the ONE row: the key
                    # blend carries untouched originals outside its
                    # window, and the payload blend composes LAST — on
                    # overlapping windows the payload value
                    # deterministically wins, the order the old
                    # sequential fallback wrote (this blend ORDER is
                    # load-bearing, see sort_by_key)
                    return jnp.where(
                        pay_mask_c[r],
                        jnp.take(pay_res[0].astype(pay_dtype),
                                 pcol_idx),
                        krow[0])[None]
                prows = []
                for rowv, src in zip(pay_res, pay):
                    prows.append(jnp.where(
                        pay_mask_c[r],
                        jnp.take(rowv.astype(pay_dtype), pcol_idx),
                        src[0])[None])
                return (krow, *prows)
            kout = _pack_row(_decode(kvec, dtype), layout, dtype)
            if not pay:
                return kout
            if pay_res is None:
                return kout if aliased else (kout, pay[0])
            return (kout,) + tuple(
                _pack_row(rowv, pay_layout, pay_dtype)
                for rowv in pay_res)

        # --- phase 1: local sort, key-encode fused.  Keys-only: ONE
        # unstable channel (duplicates are bit-identical).  Key-value:
        # (key, global index) — a TOTAL order, so unstable is exact,
        # and the index channel does double duty: (a) real elements
        # sort before pad slots among EQUAL keys — an integer key equal
        # to the dtype-max pad sentinel would otherwise let a pad
        # displace the real element in the merge; (b) key ties keep
        # original global order exactly (numpy-stable).
        if kern.use:
            # the on-chip bitonic (ops/sort_pallas) — keys-only output
            # equals lax.sort on the encoding (equal keys are bit-
            # identical), KV output equals it under EITHER stability
            # flag (the (key, gid) pair order is total)
            if pay:
                xs, gs = sort_pallas.sort_kv(
                    kv, jnp.where(local_ok, gid, GMAX).astype(
                        jnp.int32), interpret=kern.interpret)
            else:
                xs = sort_pallas.sort_keys(kv,
                                           interpret=kern.interpret)
                gs = None
        else:
            if pay:
                vals = (kv, jnp.where(local_ok, gid, GMAX).astype(
                    jnp.int32))
            else:
                vals = (kv,)
            srt = lax.sort(vals, dimension=0, num_keys=len(vals),
                           is_stable=stable)
            xs = srt[0]
            gs = srt[1] if pay else None
        if stop_after == "local_sort":
            # value-mix the secondary channel in so XLA cannot narrow
            # the variadic sort to a single-operand one
            X = xs if not pay else xs.at[0].set(
                jnp.minimum(xs[0], gs[0].astype(xs.dtype)))
            return finish(X)

        if p == 1:
            # no collective phases exist: every later truncation is
            # the full program.  Pads sorted to the end; reverse, then
            # rotate them back outside the logical window.
            if descending:
                xs = jnp.roll(xs[::-1], nvalid - S)
                if pay:
                    gs = jnp.roll(gs[::-1], nvalid - S)
            if not pay:
                return finish(xs)
            return finish(xs, pay_gather(gs))

        # --- phase 2: regular samples -> global splitters (positions
        # scale with MY real count; a short shard samples its real
        # keys, an EMPTY one contributes pad sentinels — either way
        # only bucket balance is affected, never correctness).  The
        # classic p-1 samples per shard stay: the overflow-free
        # exchange bound hangs off them, and the measured phase cost
        # is noise-level (docs/PERF.md round-6 table).
        samp = jnp.take(xs, (jnp.arange(1, p) * nvalid) // p)
        allsamp = lax.all_gather(samp, axis).reshape(-1)  # (p(p-1),)
        spl = jnp.sort(allsamp)[jnp.arange(1, p) * (p - 1) - 1]
        if stop_after == "splitter":
            X = xs.at[0].set(jnp.minimum(xs[0], spl[0]))
            if pay:
                # keep the index channel alive here too, or XLA strips
                # the unused operand and the phase-1 sort compiles
                # single-channel — the ladder would then misattribute
                # the variadic-sort cost to the exchange phase
                X = X.at[1].set(jnp.minimum(X[1], gs[0].astype(X.dtype)))
            return finish(X)

        # --- phase 3: bucket exchange.  xs is sorted, so destination
        # d's elements form ONE CONTIGUOUS run (round 6): the send
        # matrix is a shifted take with front-aligned rows, the
        # per-destination counts are p searchsorteds into the monotone
        # bucket vector, and ONE all_gather of the count vector yields
        # both my merged length and the global offsets (the round-5
        # form paid a count all_to_all here plus a second all_gather
        # in the rebalance).  A source's bucket can't exceed its own
        # real count (<= S): overflow-free by construction.
        bucket = jnp.searchsorted(spl, xs, side="right")  # (S,) nondec
        dd = jnp.arange(p)
        lo = jnp.minimum(jnp.searchsorted(bucket, dd, side="left"),
                         nvalid)
        hi = jnp.minimum(jnp.searchsorted(bucket, dd, side="right"),
                         nvalid)
        cnts = (hi - lo).astype(jnp.int32)                # (p,)
        sidx = jnp.clip(lo[:, None] + jnp.arange(S)[None, :], 0, S - 1)
        in_run = jnp.arange(S)[None, :] < cnts[:, None]
        send = jnp.where(in_run, jnp.take(xs, sidx), big)
        recv = lax.all_to_all(send, axis, 0, 0)           # (p, S)
        C = lax.all_gather(cnts, axis)                    # (p, p)
        cnt = jnp.sum(C[:, r])       # my merged run's true length
        if pay:
            # the index channel pads at GMAX so pad slots stay AFTER
            # real elements under the 2-key merge
            grecv = lax.all_to_all(
                jnp.where(in_run, jnp.take(gs, sidx), GMAX),
                axis, 0, 0)
        if stop_after == "exchange":
            X = jnp.minimum(xs, recv[r])
            X = X.at[0].set(jnp.minimum(X[0], cnt.astype(X.dtype)))
            if pay:
                X = X.at[1].set(jnp.minimum(
                    X[1], grecv[r, 0].astype(X.dtype)))
            return finish(X)

        # --- phase 4: local merge.  The flattened recv is source-major
        # and each source row keeps its local sorted order front-
        # aligned, so stability composes; the channel set is a total
        # order either way (see module docstring), so the comparator
        # stays unstable.
        flat = recv.reshape(-1)
        if pay:
            msrt = lax.sort((flat, grecv.reshape(-1)), dimension=0,
                            num_keys=2, is_stable=stable)
            merged, gidm = msrt
        else:
            merged = lax.sort((flat,), dimension=0, num_keys=1,
                              is_stable=stable)[0]
            gidm = None
        if stop_after == "merge":
            X = merged[::p]  # strided sample keeps the value spread
            X = X.at[0].set(jnp.minimum(X[0], cnt.astype(X.dtype)))
            if pay:
                X = X.at[1].set(jnp.minimum(X[1],
                                            gidm[0].astype(X.dtype)))
            return finish(X)

        # --- phase 5: rebalance to the DESTINATION layout by
        # masked-sum assembly: shard d's window is [starts[d],
        # starts[d] + sizes[d]) — per CHANNEL geometry, so the index
        # channel lands directly in the PAYLOAD distribution's windows
        allcnt = jnp.sum(C, axis=0)                       # (p,)
        off = jnp.sum(jnp.where(jnp.arange(p) < r, allcnt, 0))

        def rebalance(m, dstarts, dsizes, Sd):
            gpos = dstarts[:, None] \
                + jnp.arange(Sd)[None, :]                 # (p, Sd)
            dest_ok = jnp.arange(Sd)[None, :] < dsizes[:, None]
            want = (n - 1 - gpos) if descending else gpos
            idx = want - off       # my local index for that cell
            ok = dest_ok & (idx >= 0) & (idx < cnt)
            gidx = jnp.clip(idx, 0, p * S - 1)
            s2 = jnp.where(ok, jnp.take(m, gidx),
                           jnp.zeros((), m.dtype))
            return jnp.sum(lax.all_to_all(s2, axis, 0, 0), axis=0)

        kreb = rebalance(merged, starts_c, sizes_c, S)
        if not pay:
            return finish(kreb)
        # the rebalanced index channel IS the sort permutation, homed
        # in the payload distribution's own windows
        gperm = rebalance(gidm, pstarts_c, psizes_c, Sp)
        if stop_after == "rebalance":
            return finish(kreb.at[0].set(
                jnp.minimum(kreb[0], gperm[0].astype(kreb.dtype))))
        # --- phase 6: single payload move ---
        return finish(kreb, pay_gather(gperm))

    nin = 1 if pay_layout is None or aliased else 2
    # check_vma=False under the kernel arm: shard_map has no
    # replication rule for pallas_call (the scan kernel's precedent)
    shmapped = jax.shard_map(
        body, mesh=mesh, in_specs=(P(axis, None),) * nin,
        out_specs=P(axis, None) if pay_layout is None or aliased
        else (P(axis, None),) * 2,
        check_vma=not kern.use)
    # in-place rebind: donate the input buffers like the other in-place
    # cached programs (elementwise/gemv/stencil)
    prog = jax.jit(shmapped, donate_argnums=tuple(range(nin)))
    _prog_cache[key] = prog
    return prog


def sort(r, *, descending: bool = False):
    """Sort a distributed range in place (rebinding), ascending by
    default.  ``r`` must be a ``distributed_vector`` or a subrange
    window over one (the write target).  Whole containers AND subrange
    windows — uniform or uneven block distributions — run the single
    sample-sort shard_map program (windows in window-relative
    coordinates with a masked row blend, round 4).  Every dtype is
    native (round 5): f64 keys encode through the 64-bit sign-flip
    trick on x64-enabled meshes, exactly."""
    _plan_barrier("sort")
    chain = _out_chain(r)
    cont = chain.cont
    full = chain.off == 0 and chain.n == len(cont)
    if chain.n == 0:
        return r
    prog = _sort_program(
        cont.runtime.mesh, cont.runtime.axis, cont.layout,
        cont.dtype, descending,
        window=None if full else (chain.off, chain.n))
    cont._data = prog(cont._data)
    return r


def sort_by_key(keys, values, *, descending: bool = False):
    """STABLE key-value sort: reorder ``values`` by ``keys`` (both in
    place, rebinding).  Ties keep their original global order; with
    ``descending`` the whole ascending order is reversed, ties
    included.  Arguments are ``distributed_vector``\\ s or subrange
    windows over them, with equal logical lengths.  Same-mesh channels
    run ONE shard_map program whatever their distributions, windows,
    or dtypes (f64 included — 64-bit key encoding, round 5); disjoint
    windows of one container run an aliased single-row variant;
    different meshes (mismatched shard counts) reshard the payload
    onto the key runtime, sort natively there, and reshard back.
    EVERY shape is native (round 5): overlapping windows of one
    container compose their blends payload-last, the deterministic
    order the old sequential fallback used.  The payload itself moves
    exactly ONCE (round 6): it never rides a sort or the bucket
    exchange — the rebalanced global-index channel drives one gather."""
    _plan_barrier("sort_by_key")
    kc = _out_chain(keys)
    vc = _out_chain(values)
    if kc.n != vc.n:
        raise ValueError(
            f"keys and values must have equal length ({kc.n} != {vc.n})")
    kcont, vcont = kc.cont, vc.cont
    # one shard_map program spans both containers, so they must share
    # a MESH (runtime identity is too strict — re-init'd runtimes over
    # the same devices still align; shard count alone is too loose —
    # equal counts over different device sets would crash the jit)
    same_mesh = kcont.runtime.mesh == vcont.runtime.mesh
    full = (kc.off == 0 and vc.off == 0
            and kc.n == len(kcont) and vc.n == len(vcont)
            # distributions MAY differ (round 4): the rebalanced index
            # channel lands in the payload's own windows and the gather
            # honors its ownership — no realignment anywhere
            and same_mesh)
    if kc.n == 0:
        return keys, values
    if kcont is vcont and kc.off == vc.off:
        # keys ARE the values (same window of one container): sorting
        # the keys reorders the payload identically — plain sort
        sort(keys, descending=descending)
        return keys, values
    # ANY two windows of one container blend into a single donated row
    # (round 5): both window slices are extracted from the ORIGINAL
    # row before either blend, and the payload blend composes LAST —
    # exactly the old sequential fallback's write order, so overlap
    # cells deterministically take the payload value
    aliased = kcont is vcont
    win_ok = (not full
              and (aliased or (same_mesh and kcont is not vcont)))
    if full or win_ok:
        kw = None if full else (kc.off, kc.n)
        prog = _sort_program(kcont.runtime.mesh, kcont.runtime.axis,
                             kcont.layout, kcont.dtype, descending,
                             pay_layout=vcont.layout,
                             pay_dtype=vcont.dtype,
                             window=kw,
                             pay_window=None if full
                             else (vc.off, vc.n),
                             aliased=aliased)
        if aliased:
            kcont._data = prog(kcont._data)
        else:
            kcont._data, vcont._data = prog(kcont._data, vcont._data)
        return keys, values
    # DIFFERENT MESHES (mismatched shard counts, or equal counts over
    # different device sets) take the reshard route (round 5 — this
    # used to be the argsort materialize): the payload reshards onto
    # the key runtime through the redistribution engine's cross-mesh
    # transport (parallel/redistribute.reshard_copy — same fault
    # site, span, and bytes counter as every re-layout, docs/SPEC.md
    # §18; the move itself stays the XLA-resharding class the
    # elementwise fallback uses), the sample-sort runs NATIVELY there
    # with the keys never leaving their shards, and the reordered
    # payload reshards back into its own windows.  This is the LAST
    # remaining route — every same-mesh shape is native.
    from ..containers.distributed_vector import distributed_vector
    from ..parallel.redistribute import reshard_copy
    scratch = distributed_vector(vc.n, dtype=vcont.dtype,
                                 runtime=kcont.runtime)
    reshard_copy(values, scratch)
    sort_by_key(keys, scratch, descending=descending)
    reshard_copy(scratch, values)
    return keys, values


def sort_n(v, iters: int):
    """``iters`` chained whole-container sorts in ONE jitted program
    (the ``inclusive_scan_n`` measurement analog): per-sort device
    time then excludes the tunneled per-dispatch overhead.  After the
    first round the data is already sorted — ``lax.sort``'s
    sorting-network cost is data-independent on TPU, so the marginal
    rounds still price the real program (on CPU meshes the comparator
    sorts run FASTER on sorted data; docs/PERF.md round 6 records the
    gap).  Timing aid for bench.py; the final content is simply the
    sorted input."""
    _plan_barrier("sort_n")
    chain = _out_chain(v)
    cont = chain.cont
    assert chain.off == 0 and chain.n == len(cont), \
        "sort_n takes a whole container"
    mesh, axis = cont.runtime.mesh, cont.runtime.axis
    key = ("sort_n", pinned_id(mesh), axis, cont.layout,
           str(cont.dtype), int(iters), _stable_override(),
           bool(jax.config.jax_enable_x64))
    prog = _prog_cache.get(key)
    if prog is None:
        one = _sort_program(mesh, axis, cont.layout, cont.dtype, False)

        def many(d):
            # jit-of-jit inlines `one`; its donation applies only at
            # top-level dispatch, so the loop carry is clean
            return lax.fori_loop(0, iters, lambda _, x: one(x), d)

        prog = jax.jit(many, donate_argnums=0)
        _prog_cache[key] = prog
    cont._data = prog(cont._data)
    return v


def sort_by_key_n(keys, values, iters: int):
    """``iters`` chained key-value sorts in ONE jitted program (see
    :func:`sort_n`)."""
    _plan_barrier("sort_by_key_n")
    kc = _out_chain(keys)
    vc = _out_chain(values)
    kcont, vcont = kc.cont, vc.cont
    assert (kc.off == 0 and vc.off == 0 and kc.n == len(kcont)
            and vc.n == len(vcont)
            and kcont.runtime.mesh == vcont.runtime.mesh), \
        "sort_by_key_n takes two whole same-mesh containers"
    mesh, axis = kcont.runtime.mesh, kcont.runtime.axis
    key = ("sortkv_n", pinned_id(mesh), axis, kcont.layout,
           str(kcont.dtype), vcont.layout, str(vcont.dtype), int(iters),
           _stable_override(), bool(jax.config.jax_enable_x64))
    prog = _prog_cache.get(key)
    if prog is None:
        one = _sort_program(mesh, axis, kcont.layout, kcont.dtype,
                            False, pay_layout=vcont.layout,
                            pay_dtype=vcont.dtype)

        def many(kd, vd):
            return lax.fori_loop(0, iters, lambda _, kv: one(*kv),
                                 (kd, vd))

        prog = jax.jit(many, donate_argnums=(0, 1))
        _prog_cache[key] = prog
    kcont._data, vcont._data = prog(kcont._data, vcont._data)
    return keys, values


def sort_phases_n(v, stop_after, iters: int):
    """``iters`` chained PHASE-TRUNCATED keys-only sorts in ONE jitted
    program (profiling aid — see :data:`SORT_PHASES` and
    ``utils.profiling.profile_phases``).  The container's content after
    a truncated run is a phase-dependent value mix, NOT a sorted range;
    use scratch data."""
    _plan_barrier("sort_phases_n")
    chain = _out_chain(v)
    cont = chain.cont
    assert chain.off == 0 and chain.n == len(cont), \
        "sort_phases_n takes a whole container"
    mesh, axis = cont.runtime.mesh, cont.runtime.axis
    key = ("sortph_n", pinned_id(mesh), axis, cont.layout,
           str(cont.dtype), stop_after, int(iters), _stable_override(),
           bool(jax.config.jax_enable_x64))
    prog = _prog_cache.get(key)
    if prog is None:
        one = _sort_program(mesh, axis, cont.layout, cont.dtype, False,
                            stop_after=stop_after)

        def many(d):
            return lax.fori_loop(0, iters, lambda _, x: one(x), d)

        prog = jax.jit(many, donate_argnums=0)
        _prog_cache[key] = prog
    cont._data = prog(cont._data)
    return v


def sort_by_key_phases_n(keys, values, stop_after, iters: int):
    """Key-value twin of :func:`sort_phases_n` (see
    :data:`SORTKV_PHASES`).  Truncations before the "payload" phase
    leave the payload container bit-untouched — honest accounting: no
    earlier phase reads or moves it."""
    _plan_barrier("sort_by_key_phases_n")
    kc = _out_chain(keys)
    vc = _out_chain(values)
    kcont, vcont = kc.cont, vc.cont
    assert (kc.off == 0 and vc.off == 0 and kc.n == len(kcont)
            and vc.n == len(vcont)
            and kcont.runtime.mesh == vcont.runtime.mesh), \
        "sort_by_key_phases_n takes two whole same-mesh containers"
    mesh, axis = kcont.runtime.mesh, kcont.runtime.axis
    key = ("sortkvph_n", pinned_id(mesh), axis, kcont.layout,
           str(kcont.dtype), vcont.layout, str(vcont.dtype),
           stop_after, int(iters), _stable_override(),
           bool(jax.config.jax_enable_x64))
    prog = _prog_cache.get(key)
    if prog is None:
        one = _sort_program(mesh, axis, kcont.layout, kcont.dtype,
                            False, pay_layout=vcont.layout,
                            pay_dtype=vcont.dtype,
                            stop_after=stop_after)

        def many(kd, vd):
            return lax.fori_loop(0, iters, lambda _, kv: one(*kv),
                                 (kd, vd))

        prog = jax.jit(many, donate_argnums=(0, 1))
        _prog_cache[key] = prog
    kcont._data, vcont._data = prog(kcont._data, vcont._data)
    return keys, values


def argsort(r, *, descending: bool = False):
    """The stable sort permutation of ``r`` as a new int32
    ``distributed_vector`` (``r`` itself is left untouched): index
    ``i`` of the result holds the original position of the ``i``-th
    element of the sorted order — ``sort_by_key`` over a scratch copy
    of the keys with an iota payload.  READ-ONLY in ``r``: transform
    views and other single-component ranges are accepted (the copy
    fuses the view chain)."""
    _plan_barrier("argsort")
    from ..containers.distributed_vector import distributed_vector
    from .elementwise import copy as _copy, iota
    res = _resolve(r)
    if res is None or len(res) != 1:
        raise TypeError("argsort takes a single distributed range")
    chain = res[0]
    scratch = distributed_vector(chain.n, dtype=chain.cont.dtype,
                                 runtime=chain.cont.runtime)
    _copy(r, scratch)
    idx = distributed_vector(chain.n, dtype=np.int32,
                             runtime=chain.cont.runtime)
    iota(idx, 0)
    sort_by_key(scratch, idx, descending=descending)
    return idx


def _is_sorted_program(mesh, axis, layout, dtype, pinned, window=None,
                       ops=()):
    # view-chain ops key through _traced_op_key and feed their BoundOp
    # scalars as TRACED trailing operands (round 6 — the round-5 form
    # keyed on object identity and baked the values, recompiling per
    # streamed coefficient; _custom_reduce_program's convention)
    key = ("is_sorted", pinned, axis, layout, str(dtype), window,
           tuple(_traced_op_key(f) for f in ops),
           bool(jax.config.jax_enable_x64))
    prog = _prog_cache.get(key)
    if prog is not None:
        return prog

    if window is None:
        p, S, cap, prev, nxt, n, starts, sizes = working_geometry(layout)
        wstart = None
    else:
        p, S, cap, prev, nxt, n, starts, sizes, wstart = \
            window_geometry(layout, *window)
        width = prev + cap + nxt
        woff_c = jnp.asarray(wstart, jnp.int32)
    starts_c = jnp.asarray(starts, jnp.int32)
    sizes_c = jnp.asarray(sizes, jnp.int32)
    nsc = sum(len(o.scalars) for o in ops if isinstance(o, _v.BoundOp))

    def body(blk, *scalars):
        r = lax.axis_index(axis)
        if window is None:
            raw = blk[0, prev:prev + S]
        else:
            idx = jnp.clip(prev + woff_c[r] + jnp.arange(S), 0,
                           width - 1)
            raw = jnp.take(blk[0], idx)
        # view-chain op stack, fused (round 5; BoundOp scalars traced)
        raw = _apply_chain_ops(raw, ops, iter(scalars))
        k, big = _encode(raw)
        nvalid = jnp.minimum(sizes_c[r],
                             jnp.clip(n - starts_c[r], 0, S))
        k = jnp.where(jnp.arange(S) < nvalid, k, big)
        # pads are the key max and trail the reals, so the local
        # vector compare stays monotone across the real->pad boundary
        local_ok = jnp.all(k[:-1] <= k[1:]) if S > 1 else jnp.bool_(True)
        # boundary check, empty-shard-safe: sorted <=> every shard is
        # locally sorted AND the max over all PREVIOUS shards' last
        # real keys <= my first real key (empty shards contribute the
        # key-domain minimum, i.e. no constraint)
        small = jnp.zeros((), k.dtype) \
            if jnp.issubdtype(k.dtype, jnp.unsignedinteger) \
            else jnp.array(jnp.iinfo(k.dtype).min, k.dtype)
        last = jnp.where(nvalid > 0,
                         k[jnp.clip(nvalid - 1, 0, S - 1)], small)
        lasts = lax.all_gather(last, axis)           # (p,)
        prevmax = jnp.max(jnp.where(jnp.arange(p) < r, lasts, small))
        first_ok = jnp.logical_or(nvalid == 0, prevmax <= k[0])
        ok = jnp.logical_and(local_ok, first_ok)
        return lax.psum(jnp.logical_not(ok).astype(jnp.int32), axis)

    shmapped = jax.shard_map(body, mesh=mesh,
                             in_specs=(P(axis, None),) + (P(),) * nsc,
                             out_specs=P())
    prog = jax.jit(shmapped)
    _prog_cache[key] = prog
    return prog


def is_sorted(r) -> bool:
    """True when the range is ascending (``std::is_sorted``; NaNs
    count as largest, numpy order).  READ-ONLY in ``r``.  Whole
    containers AND subrange windows (uniform or uneven
    distributions) run one fused shard_map program (local vector
    compare + one boundary all_gather; windows in window coordinates —
    round 4; f64 through the exact 64-bit key encoding, and transform-
    view chains with the op stack fused into the program — BoundOp
    coefficients as traced operands, so streams reuse one program,
    round 6)."""
    _plan_barrier("is_sorted")
    res = _resolve(r)
    if res is not None and len(res) != 1:
        raise TypeError("is_sorted takes a single-component range")
    chain = res[0] if res is not None else None
    if chain is not None:
        cont = chain.cont
        if chain.n == 0:
            return True
        full = chain.off == 0 and chain.n == len(cont)
        prog = _is_sorted_program(
            cont.runtime.mesh, cont.runtime.axis, cont.layout,
            cont.dtype, pinned_id(cont.runtime.mesh),
            window=None if full else (chain.off, chain.n),
            ops=chain.ops)
        svals = [jnp.asarray(s) for s in _chain_scalars([chain])]
        return int(prog(cont._data, *svals)) == 0
    raise TypeError("is_sorted takes a distributed range")
