"""Distributed sort — regular-sample sort over the mesh.

Beyond-parity surface: the reference snapshot (v0.1) ships no
distributed sort (its spec and later revisions of the proposal name
one), so this is designed TPU-first rather than re-designed: ONE jitted
``shard_map`` program per layout doing

1. local ``jnp.sort`` of the owned (masked) cells,
2. splitter selection by REGULAR SAMPLING — each shard contributes
   ``p-1`` evenly spaced elements of its sorted run, the ``p*(p-1)``
   samples are ``all_gather``-ed and the global splitters are the
   evenly spaced elements of their sorted order (the classic bound:
   every destination bucket then holds fewer than ``2*seg`` elements,
   which only affects balance — correctness never depends on it),
3. bucket exchange as ONE ``all_to_all`` of a ``(p, seg)`` send matrix
   (row ``d`` = my elements belonging to shard ``d``, padded with the
   dtype's maximum).  A single source's bucket can never exceed its own
   ``seg`` elements, so the matrix is overflow-free BY CONSTRUCTION —
   no variable-length transport needed under XLA's static shapes,
4. local merge (``jnp.sort`` of the received matrix), and
5. rebalance back to the uniform block layout: run lengths are
   ``all_gather``-ed into exclusive offsets, each source pre-places its
   elements at their destination-window positions in a second
   ``(p, seg)`` matrix, and after a second ``all_to_all`` each output
   cell is the SUM of its column — every global position is covered by
   exactly one source, so masked-sum assembly replaces the scatter TPU
   doesn't like.

Descending order costs nothing extra: phase 5's index map places
element ``g`` of the ascending order at global position ``n-1-g``.

The fallback (subrange windows, uneven block distributions, float64)
materializes the logical array, sorts it with XLA's global sort, and
splices it back — correct everywhere, collective-optimal nowhere.
The write target must be a ``distributed_vector`` or a subrange window
over one; transform views and other read-only ranges are rejected with
``TypeError`` (sorting them in place has no meaning).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ._common import uniform_layout
from .elementwise import _out_chain, _prog_cache, _write_window
from ..core.pinning import pinned_id

__all__ = ["sort"]


_NAN_KEY = np.uint32(0xFFFFFFFE)  # after +inf (numpy sorts NaNs last)
_PAD_KEY = np.uint32(0xFFFFFFFF)  # strictly after every real key


def _encode(x):
    """Monotone total-order sort key.

    Floats map through the IEEE sign-flip trick to ``uint32`` (bf16/f16
    upcast exactly first), with every NaN canonicalized to ``_NAN_KEY``
    — after +inf, matching numpy's NaNs-last order, and BEFORE the pad
    sentinel, so the positional validity mask stays exact even for NaN
    data.  Integers are their own keys (the pad sentinel is the dtype
    max; real values equal to it merely tie with padding, and ties
    among equals cannot change the sorted output)."""
    if jnp.issubdtype(x.dtype, jnp.floating):
        b = jax.lax.bitcast_convert_type(x.astype(jnp.float32),
                                         jnp.uint32)
        k = jnp.where(b >> 31 == 1, ~b, b | jnp.uint32(0x80000000))
        return jnp.where(jnp.isnan(x), _NAN_KEY, k), _PAD_KEY
    return x, jnp.array(jnp.iinfo(x.dtype).max, x.dtype)


def _decode(k, dtype):
    """Inverse of :func:`_encode` (NaN payload/sign canonicalized)."""
    if jnp.issubdtype(jnp.dtype(dtype), jnp.floating):
        b = jnp.where(k >> 31 == 1, k ^ jnp.uint32(0x80000000), ~k)
        x = jax.lax.bitcast_convert_type(b, jnp.float32)
        return jnp.where(k == _NAN_KEY, jnp.float32(jnp.nan),
                         x).astype(dtype)
    return k.astype(dtype)


def _sort_program(mesh, axis, layout, dtype, descending):
    key = ("sort", pinned_id(mesh), axis, layout, str(dtype),
           bool(descending))
    prog = _prog_cache.get(key)
    if prog is not None:
        return prog

    nshards, seg, prev, nxt, n = layout
    p = nshards

    def body(blk):  # (1, prev+seg+nxt) — one shard row
        key, big = _encode(blk[0, prev:prev + seg])
        r = lax.axis_index(axis)
        gid = r * seg + jnp.arange(seg)
        key = jnp.where(gid < n, key, big)      # mask ceil-layout pads
        xs = jnp.sort(key)
        nvalid = jnp.clip(n - r * seg, 0, seg)  # my real element count

        if p == 1:
            out_row = xs if not descending else xs[::-1]
            # single shard: pads sorted to the end (or start); rotate
            # them back outside the logical window
            out_row = jnp.roll(out_row, nvalid - seg) if descending \
                else out_row
        else:
            # 2. regular samples -> global splitters
            samp = xs[(jnp.arange(1, p) * seg) // p]          # (p-1,)
            allsamp = lax.all_gather(samp, axis).reshape(-1)  # (p(p-1),)
            spl = jnp.sort(allsamp)[jnp.arange(1, p) * (p - 1) - 1]
            # 3. bucket exchange ((p, seg) send matrix, one all_to_all)
            bucket = jnp.searchsorted(spl, xs, side="right")  # (seg,)
            vmask = jnp.arange(seg) < nvalid
            mine = (bucket[None, :] == jnp.arange(p)[:, None]) \
                & vmask[None, :]
            send = jnp.where(mine, xs[None, :], big)
            cnts = jnp.sum(mine, axis=1, dtype=jnp.int32)     # (p,)
            recv = lax.all_to_all(send, axis, 0, 0)           # (p, seg)
            rcnt = lax.all_to_all(cnts[:, None], axis, 0, 0)  # (p, 1)
            # 4. local merge; cnt = my sorted run's true length
            merged = jnp.sort(recv.reshape(-1))               # (p*seg,)
            cnt = jnp.sum(rcnt)
            # 5. rebalance to the block layout by masked-sum assembly
            allcnt = lax.all_gather(cnt, axis)                # (p,)
            off = jnp.sum(jnp.where(jnp.arange(p) < r, allcnt, 0))
            gpos = jnp.arange(p)[:, None] * seg \
                + jnp.arange(seg)[None, :]                    # (p, seg)
            want = (n - 1 - gpos) if descending else gpos
            idx = want - off               # my local index for that cell
            ok = (idx >= 0) & (idx < cnt)
            send2 = jnp.where(
                ok, jnp.take(merged, jnp.clip(idx, 0, p * seg - 1)),
                jnp.zeros((), merged.dtype))
            recv2 = lax.all_to_all(send2, axis, 0, 0)
            out_row = jnp.sum(recv2, axis=0)  # exactly-one coverage
        out_row = _decode(out_row, dtype)
        if prev == 0 and nxt == 0:
            return out_row[None]
        out = jnp.zeros((1, prev + seg + nxt), dtype)
        return out.at[0, prev:prev + seg].set(out_row)

    shmapped = jax.shard_map(body, mesh=mesh, in_specs=P(axis, None),
                             out_specs=P(axis, None))
    # in-place rebind: donate the input buffer like the other in-place
    # cached programs (elementwise/gemv/stencil)
    prog = jax.jit(shmapped, donate_argnums=0)
    _prog_cache[key] = prog
    return prog


def sort(r, *, descending: bool = False):
    """Sort a distributed range in place (rebinding), ascending by
    default.  ``r`` must be a ``distributed_vector`` or a subrange
    window over one (the write target); whole uniform-layout containers
    take the single-program sample-sort fast path, everything else the
    materialize-and-splice fallback."""
    chain = _out_chain(r)
    cont = chain.cont
    full = (chain.off == 0 and chain.n == len(cont)
            and uniform_layout(cont.layout)
            # the key encoding upcasts floats through f32: exact for
            # f32/bf16/f16, lossy for f64 — f64 takes the fallback
            and jnp.dtype(cont.dtype) != jnp.dtype(np.float64))
    if full:
        prog = _sort_program(cont.runtime.mesh, cont.runtime.axis,
                             cont.layout, cont.dtype, descending)
        cont._data = prog(cont._data)
        return r
    arr = cont.to_array()
    win = jnp.sort(arr[chain.off:chain.off + chain.n])
    if descending:
        win = win[::-1]
    _write_window(chain, win)
    return r
