"""Distributed sort — regular-sample sort over the mesh.

Beyond-parity surface: the reference snapshot (v0.1) ships no
distributed sort (its spec and later revisions of the proposal name
one), so this is designed TPU-first rather than re-designed: ONE jitted
``shard_map`` program per layout doing

1. local ``jnp.sort`` of the owned (masked) cells,
2. splitter selection by REGULAR SAMPLING — each shard contributes
   ``p-1`` evenly spaced elements of its sorted run, the ``p*(p-1)``
   samples are ``all_gather``-ed and the global splitters are the
   evenly spaced elements of their sorted order (the classic bound:
   every destination bucket then holds fewer than ``2*seg`` elements,
   which only affects balance — correctness never depends on it),
3. bucket exchange as ONE ``all_to_all`` of a ``(p, seg)`` send matrix
   (row ``d`` = my elements belonging to shard ``d``, padded with the
   dtype's maximum).  A single source's bucket can never exceed its own
   ``seg`` elements, so the matrix is overflow-free BY CONSTRUCTION —
   no variable-length transport needed under XLA's static shapes,
4. local merge (``jnp.sort`` of the received matrix), and
5. rebalance back to the uniform block layout: run lengths are
   ``all_gather``-ed into exclusive offsets, each source pre-places its
   elements at their destination-window positions in a second
   ``(p, seg)`` matrix, and after a second ``all_to_all`` each output
   cell is the SUM of its column — every global position is covered by
   exactly one source, so masked-sum assembly replaces the scatter TPU
   doesn't like.

Descending order costs nothing extra: phase 5's index map places
element ``g`` of the ascending order at global position ``n-1-g``.

Uneven ``block_distribution`` layouts (including zero-size "team"
shards) run the SAME program: the geometry enters as static per-shard
starts/sizes, phase 5 rebalances into the destination distribution's
windows, and the bucket matrices stay overflow-free (a source's bucket
never exceeds its own real count).  Subrange windows run the SAME
program in window-relative coordinates (round 4): the window's shard
intersections are static uneven geometry, and a masked row blend
leaves outside cells untouched bit-exactly.  float64 keys run the
SAME program through a 64-bit sign-flip encoding (round 5; exact —
only reachable on x64-enabled CPU meshes, TPU has no f64).
The write target must be a ``distributed_vector`` or a subrange window
over one; transform views and other read-only ranges are rejected with
``TypeError`` (sorting them in place has no meaning).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ._common import (owned_window_mask, window_geometry,
                      working_geometry)
from .elementwise import _out_chain, _prog_cache, _resolve
from ..core.pinning import pinned_id

__all__ = ["sort", "sort_by_key", "argsort", "is_sorted"]


_NAN_KEY = np.uint32(0xFFFFFFFE)  # after +inf (numpy sorts NaNs last)
_PAD_KEY = np.uint32(0xFFFFFFFF)  # strictly after every real key
# 64-bit twins for real float64 keys (only reachable on x64-enabled CPU
# meshes — TPU has no f64; with x64 disabled a "float64" container
# stores f32 and takes the 32-bit path, which is then exact)
_NAN_KEY64 = np.uint64(0xFFFFFFFFFFFFFFFE)
_PAD_KEY64 = np.uint64(0xFFFFFFFFFFFFFFFF)


def _encode(x, distinct_zeros=False):
    """Monotone total-order sort key.

    Floats map through the IEEE sign-flip trick to ``uint32`` (bf16/f16
    upcast exactly first; real f64 arrays — x64-enabled meshes only —
    through the same trick at 64 bits, so f64 pairs closer than an f32
    ulp keep their exact order), with every NaN canonicalized to
    ``_NAN_KEY`` — after +inf, matching numpy's NaNs-last order, and
    BEFORE the pad sentinel, so the positional validity mask stays
    exact even for NaN data.  Integers are their own keys (the pad
    sentinel is the dtype max; real values equal to it merely tie with
    padding, and ties among equals cannot change the sorted output).

    ``distinct_zeros``: the sign-flip trick already orders -0.0
    (0x7FFFFFFF) just before +0.0 (0x80000000) — a valid sort order
    that round-trips the zero's sign through :func:`_decode`.  Keys-
    only ``sort()`` uses it so the output is a bit-exact permutation of
    the input.  Default OFF collapses both zeros to ONE key so they
    tie: ``sort_by_key`` needs IEEE-equal keys to keep numpy-stable
    tie order, and ``is_sorted`` must not report ``[0.0, -0.0]`` as
    unsorted."""
    if x.dtype == jnp.dtype(np.float64):
        b = jax.lax.bitcast_convert_type(x, jnp.uint64)
        k = jnp.where(b >> 63 == 1, ~b, b | jnp.uint64(1 << 63))
        if not distinct_zeros:
            k = jnp.where(x == 0, jnp.uint64(1 << 63), k)
        return jnp.where(jnp.isnan(x), _NAN_KEY64, k), _PAD_KEY64
    if jnp.issubdtype(x.dtype, jnp.floating):
        b = jax.lax.bitcast_convert_type(x.astype(jnp.float32),
                                         jnp.uint32)
        k = jnp.where(b >> 31 == 1, ~b, b | jnp.uint32(0x80000000))
        if not distinct_zeros:
            k = jnp.where(x == 0, jnp.uint32(0x80000000), k)
        return jnp.where(jnp.isnan(x), _NAN_KEY, k), _PAD_KEY
    return x, jnp.array(jnp.iinfo(x.dtype).max, x.dtype)


def _decode(k, dtype):
    """Inverse of :func:`_encode` (NaN payload/sign canonicalized);
    the key WIDTH picks the float branch — a declared-f64 container on
    an x64-disabled mesh stores f32 and round-trips through uint32."""
    if k.dtype == jnp.dtype(np.uint64):
        b = jnp.where(k >> 63 == 1, k ^ jnp.uint64(1 << 63), ~k)
        x = jax.lax.bitcast_convert_type(b, jnp.float64)
        return jnp.where(k == _NAN_KEY64, jnp.float64(jnp.nan),
                         x).astype(dtype)
    if jnp.issubdtype(jnp.dtype(dtype), jnp.floating):
        b = jnp.where(k >> 31 == 1, k ^ jnp.uint32(0x80000000), ~k)
        x = jax.lax.bitcast_convert_type(b, jnp.float32)
        return jnp.where(k == _NAN_KEY, jnp.float32(jnp.nan),
                         x).astype(dtype)
    return k.astype(dtype)


def _pack_row(row, layout, dtype):
    """Place a working-width row back into a padded shard row."""
    p, S, cap, prev, nxt, n, starts, sizes = working_geometry(layout)
    if prev == 0 and nxt == 0 and cap == S:
        return row.astype(dtype)[None]
    out = jnp.zeros((1, prev + cap + nxt), dtype)
    return out.at[0, prev:prev + S].set(row.astype(dtype))


def _sort_program(mesh, axis, layout, dtype, descending,
                  pay_layout=None, pay_dtype=None, window=None,
                  pay_window=None, aliased=False):
    """The sample-sort program; with ``pay_layout`` set it carries a
    payload row through every phase (stable key-value sort — the
    payload rides the same collectives, tie order preserved by
    ``is_stable`` sorts and the source-major merge order).

    ``window=(off, wn)`` sorts ONLY the logical subrange [off, off+wn)
    in place (round 4 — windows used to materialize): the window's
    shard intersections form a static uneven geometry the same phases
    run over, each shard reads its slice at a static per-shard offset,
    and the output row blends sorted window cells with untouched
    originals through the static owned_window_mask.

    ``aliased`` (round 5): key and payload windows live in ONE
    container — the program takes a single donated row, reads both
    windows from it (both slices come from the ORIGINAL row), and
    blends both results into that one row, payload LAST — so
    overlapping windows deterministically take the payload value,
    the same order the old sequential fallback wrote."""
    key = ("sort", pinned_id(mesh), axis, layout, str(dtype),
           bool(descending), pay_layout,
           str(pay_dtype) if pay_layout else None, window, pay_window,
           aliased,
           # x64 state changes the traced key width for declared-f64
           # containers (uint32 under x64-off, uint64 under x64-on)
           bool(jax.config.jax_enable_x64))
    prog = _prog_cache.get(key)
    if prog is not None:
        return prog

    # general geometry: uniform ceil layouts AND uneven
    # block_distributions share one program shape — S is the max owned
    # width, starts/sizes the per-shard logical windows
    if window is None:
        p, S, cap, prev, nxt, n, starts, sizes = working_geometry(layout)
        wstart = None
    else:
        p, S, cap, prev, nxt, n, starts, sizes, wstart = \
            window_geometry(layout, *window)
        width = prev + cap + nxt
        woff_c = jnp.asarray(wstart, jnp.int32)
        mask_c = jnp.asarray(
            np.asarray(owned_window_mask(layout, *window)[0]))
    pprev = pay_layout[2] if pay_layout else 0
    starts_c = jnp.asarray(starts, jnp.int32)
    sizes_c = jnp.asarray(sizes, jnp.int32)
    if pay_layout is not None and window is not None:
        # windowed key-value sort (round 4): the payload window has its
        # OWN static geometry — extraction offsets, realign source, the
        # phase-5 destination, and the output blend mask all come from
        # it, exactly the mixed-distribution machinery in window
        # coordinates
        _, Sp, pcap2, pprev2, pnxt2, _, pstarts, psizes, pwstart = \
            window_geometry(pay_layout, *pay_window)
        pwidth = pprev2 + pcap2 + pnxt2
        pwoff_c = jnp.asarray(pwstart, jnp.int32)
        pay_mask_c = jnp.asarray(np.asarray(
            owned_window_mask(pay_layout, *pay_window)[0]))
        same_dist = (np.array_equal(pstarts, starts)
                     and np.array_equal(psizes, sizes))
        pstarts_c = jnp.asarray(pstarts, jnp.int32)
        psizes_c = jnp.asarray(psizes, jnp.int32)
    elif pay_layout is not None:
        # the payload may carry a DIFFERENT block distribution (round
        # 4): its own static geometry drives an input realignment to
        # key coordinates and the phase-5 rebalance into its own
        # windows — the materialize fallback is gone
        _, Sp, _, _, _, _, pstarts, psizes = working_geometry(pay_layout)
        same_dist = (np.array_equal(pstarts, starts)
                     and np.array_equal(psizes, sizes))
        pstarts_c = jnp.asarray(pstarts, jnp.int32)
        psizes_c = jnp.asarray(psizes, jnp.int32)
    else:
        Sp, same_dist = S, True

    GMAX = np.int32(np.iinfo(np.int32).max)

    def body(blk, *pay):  # padded shard rows: keys (+ payload)
        if aliased:
            pay = (blk,)  # payload window read from the SAME row
        r = lax.axis_index(axis)
        if window is None:
            raw = blk[0, prev:prev + S]
        else:
            # my window slice, at a per-shard static offset (traced
            # via the constant table); clip keeps the take in range,
            # the nvalid mask discards the clipped tail
            idx = jnp.clip(prev + woff_c[r] + jnp.arange(S), 0,
                           width - 1)
            raw = jnp.take(blk[0], idx)
        # keys-only sort is a bit-exact permutation (distinct -0.0/+0.0
        # keys); key-value sort collapses the zeros so ties keep
        # numpy-stable original order
        key, big = _encode(raw, distinct_zeros=not pay)
        nvalid = jnp.minimum(sizes_c[r],
                             jnp.clip(n - starts_c[r], 0, S))
        gid = starts_c[r] + jnp.arange(S)
        local_ok = jnp.arange(S) < nvalid
        key = jnp.where(local_ok, key, big)     # mask pad cells

        def realign(vrow):
            # payload cells (own-distribution local order, width Sp) ->
            # key coordinates: destination slot (d, j) holds global
            # position kstarts[d]+j, owned by exactly one source under
            # the payload distribution — masked-sum assembly over one
            # all_to_all, the same pattern as phase 5
            gpos_k = starts_c[:, None] + jnp.arange(S)[None, :]
            dest_ok = jnp.arange(S)[None, :] < sizes_c[:, None]
            idxl = gpos_k - pstarts_c[r]
            own = dest_ok & (idxl >= 0) & (idxl < psizes_c[r])
            send = jnp.where(own,
                             jnp.take(vrow, jnp.clip(idxl, 0, Sp - 1)),
                             jnp.zeros((), vrow.dtype))
            return jnp.sum(lax.all_to_all(send, axis, 0, 0), axis=0)

        if pay and window is not None:
            def pay_raw(v):
                pidx = jnp.clip(pprev2 + pwoff_c[r] + jnp.arange(Sp),
                                0, pwidth - 1)
                return jnp.take(v[0], pidx)
            pay_vecs = tuple(
                pay_raw(v) if same_dist else realign(pay_raw(v))
                for v in pay)
        elif same_dist:
            pay_vecs = tuple(v[0, pprev:pprev + S] for v in pay)
        else:
            pay_vecs = tuple(realign(v[0, pprev:pprev + Sp])
                             for v in pay)
        vals = (key,) + pay_vecs
        nkeys = 1
        if pay:
            # SECONDARY sort key: the original global index, with pads
            # at int32 max.  Two jobs: (a) real elements sort before
            # pad slots among EQUAL keys — an integer key equal to the
            # dtype-max pad sentinel would otherwise let a pad displace
            # the real element's payload in the merge; (b) key ties
            # keep original global order exactly (numpy-stable).
            vals = (key, jnp.where(local_ok, gid, GMAX).astype(
                jnp.int32)) + vals[1:]
            nkeys = 2
        srt = lax.sort(vals, dimension=0, num_keys=nkeys,
                       is_stable=True)
        xs, ps = srt[0], srt[1:]

        if p == 1:
            if descending:
                # pads sorted to the end; reverse, then rotate them
                # back outside the logical window
                outs = [jnp.roll(v[::-1], nvalid - S)
                        for v in (xs, *ps)]
            else:
                outs = [xs, *ps]
            if pay:
                del outs[1]  # the gid channel is not an output
        else:
            # 2. regular samples -> global splitters (positions scale
            # with MY real count; a short shard samples its real keys,
            # an EMPTY one contributes pad sentinels — either way only
            # bucket balance is affected, never correctness)
            samp = jnp.take(xs, (jnp.arange(1, p) * nvalid) // p)
            allsamp = lax.all_gather(samp, axis).reshape(-1)  # (p(p-1),)
            spl = jnp.sort(allsamp)[jnp.arange(1, p) * (p - 1) - 1]
            # 3. bucket exchange ((p, S) send matrices, one
            # all_to_all per channel).  A source's bucket can't exceed
            # its own real count (<= S): overflow-free by construction.
            bucket = jnp.searchsorted(spl, xs, side="right")  # (S,)
            vmask = jnp.arange(S) < nvalid
            mine = (bucket[None, :] == jnp.arange(p)[:, None]) \
                & vmask[None, :]
            send = jnp.where(mine, xs[None, :], big)
            cnts = jnp.sum(mine, axis=1, dtype=jnp.int32)     # (p,)
            recv = lax.all_to_all(send, axis, 0, 0)           # (p, S)
            rcnt = lax.all_to_all(cnts[:, None], axis, 0, 0)  # (p, 1)
            # pad values per channel: the gid channel pads at GMAX so
            # pad slots stay AFTER real elements under the 2-key merge
            ppad = [jnp.asarray(GMAX)] + \
                [jnp.zeros((), q.dtype) for q in ps[1:]] if pay else []
            precv = [lax.all_to_all(
                jnp.where(mine, q[None, :], pv), axis, 0, 0)
                for q, pv in zip(ps, ppad)]
            # 4. stable local merge; cnt = my run's true length.  The
            # flattened recv is source-major and each source row keeps
            # its local sorted order, so stability composes; with a
            # payload the global index is the explicit tiebreak.
            msrt = lax.sort((recv.reshape(-1),)
                            + tuple(q.reshape(-1) for q in precv),
                            dimension=0, num_keys=nkeys,
                            is_stable=True)
            merged = msrt[0]
            pmerged = msrt[2:] if pay else msrt[1:]
            cnt = jnp.sum(rcnt)
            # 5. rebalance to the DESTINATION layout by masked-sum
            # assembly: shard d's window is [starts[d], starts[d] +
            # sizes[d]) — per CHANNEL geometry, so a payload carrying a
            # different distribution lands directly in its own windows
            allcnt = lax.all_gather(cnt, axis)                # (p,)
            off = jnp.sum(jnp.where(jnp.arange(p) < r, allcnt, 0))

            def rebalance(m, dstarts, dsizes, Sd):
                gpos = dstarts[:, None] \
                    + jnp.arange(Sd)[None, :]                 # (p, Sd)
                dest_ok = jnp.arange(Sd)[None, :] < dsizes[:, None]
                want = (n - 1 - gpos) if descending else gpos
                idx = want - off       # my local index for that cell
                ok = dest_ok & (idx >= 0) & (idx < cnt)
                gidx = jnp.clip(idx, 0, p * S - 1)
                s2 = jnp.where(ok, jnp.take(m, gidx),
                               jnp.zeros((), m.dtype))
                return jnp.sum(lax.all_to_all(s2, axis, 0, 0), axis=0)
            # pmerged is nonempty only with a payload, whose channels
            # rebalance into the PAYLOAD geometry (== the key geometry
            # when the distributions match)
            outs = [rebalance(merged, starts_c, sizes_c, S)] \
                + [rebalance(q, pstarts_c, psizes_c, Sp)
                   for q in pmerged]
        if window is not None:
            # blend: window cells take their sorted value (the window-
            # coordinate result, re-addressed per full-row column),
            # everything else keeps the original row — per channel,
            # each through its own container's window mask
            decoded = _decode(outs[0], dtype)
            col_idx = jnp.clip(jnp.arange(width) - prev - woff_c[r],
                               0, S - 1)
            krow = jnp.where(mask_c[r], jnp.take(decoded, col_idx),
                             blk[0])[None]
            if not pay:
                return krow
            pcol_idx = jnp.clip(
                jnp.arange(pwidth) - pprev2 - pwoff_c[r], 0, Sp - 1)
            if aliased:
                # both windows blend into the ONE row: the key blend
                # carries untouched originals outside its window, and
                # the payload blend composes LAST — on overlapping
                # windows the payload value deterministically wins,
                # the order the old sequential fallback wrote (this
                # blend ORDER is load-bearing, see sort_by_key)
                return jnp.where(
                    pay_mask_c[r],
                    jnp.take(outs[1].astype(pay_dtype), pcol_idx),
                    krow[0])[None]
            prows = []
            for row, src in zip(outs[1:], pay):
                prows.append(jnp.where(
                    pay_mask_c[r],
                    jnp.take(row.astype(pay_dtype), pcol_idx),
                    src[0])[None])
            return (krow, *prows)
        out_rows = [_pack_row(_decode(outs[0], dtype), layout, dtype)]
        for row in outs[1:]:
            out_rows.append(_pack_row(row, pay_layout, pay_dtype))
        return out_rows[0] if not pay else tuple(out_rows)

    nin = 1 if pay_layout is None or aliased else 2
    shmapped = jax.shard_map(
        body, mesh=mesh, in_specs=(P(axis, None),) * nin,
        out_specs=P(axis, None) if pay_layout is None or aliased
        else (P(axis, None),) * 2)
    # in-place rebind: donate the input buffers like the other in-place
    # cached programs (elementwise/gemv/stencil)
    prog = jax.jit(shmapped, donate_argnums=tuple(range(nin)))
    _prog_cache[key] = prog
    return prog


def sort(r, *, descending: bool = False):
    """Sort a distributed range in place (rebinding), ascending by
    default.  ``r`` must be a ``distributed_vector`` or a subrange
    window over one (the write target).  Whole containers AND subrange
    windows — uniform or uneven block distributions — run the single
    sample-sort shard_map program (windows in window-relative
    coordinates with a masked row blend, round 4).  Every dtype is
    native (round 5): f64 keys encode through the 64-bit sign-flip
    trick on x64-enabled meshes, exactly."""
    chain = _out_chain(r)
    cont = chain.cont
    full = chain.off == 0 and chain.n == len(cont)
    if chain.n == 0:
        return r
    prog = _sort_program(
        cont.runtime.mesh, cont.runtime.axis, cont.layout,
        cont.dtype, descending,
        window=None if full else (chain.off, chain.n))
    cont._data = prog(cont._data)
    return r


def sort_by_key(keys, values, *, descending: bool = False):
    """STABLE key-value sort: reorder ``values`` by ``keys`` (both in
    place, rebinding).  Ties keep their original global order; with
    ``descending`` the whole ascending order is reversed, ties
    included.  Arguments are ``distributed_vector``\\ s or subrange
    windows over them, with equal logical lengths.  Same-mesh channels
    run ONE shard_map program whatever their distributions, windows,
    or dtypes (f64 included — 64-bit key encoding, round 5); disjoint
    windows of one container run an aliased single-row variant;
    different meshes (mismatched shard counts) reshard the payload
    onto the key runtime, sort natively there, and reshard back.
    EVERY shape is native (round 5): overlapping windows of one
    container compose their blends payload-last, the deterministic
    order the old sequential fallback used."""
    kc = _out_chain(keys)
    vc = _out_chain(values)
    if kc.n != vc.n:
        raise ValueError(
            f"keys and values must have equal length ({kc.n} != {vc.n})")
    kcont, vcont = kc.cont, vc.cont
    # one shard_map program spans both containers, so they must share
    # a MESH (runtime identity is too strict — re-init'd runtimes over
    # the same devices still align; shard count alone is too loose —
    # equal counts over different device sets would crash the jit)
    same_mesh = kcont.runtime.mesh == vcont.runtime.mesh
    full = (kc.off == 0 and vc.off == 0
            and kc.n == len(kcont) and vc.n == len(vcont)
            # distributions MAY differ (round 4): the program realigns
            # the payload to key coordinates on entry and rebalances it
            # into its own windows on exit
            and same_mesh)
    if kc.n == 0:
        return keys, values
    if kcont is vcont and kc.off == vc.off:
        # keys ARE the values (same window of one container): sorting
        # the keys reorders the payload identically — plain sort
        sort(keys, descending=descending)
        return keys, values
    # ANY two windows of one container blend into a single donated row
    # (round 5): both window slices are extracted from the ORIGINAL
    # row before either blend, and the payload blend composes LAST —
    # exactly the old sequential fallback's write order, so overlap
    # cells deterministically take the payload value
    aliased = kcont is vcont
    win_ok = (not full
              and (aliased or (same_mesh and kcont is not vcont)))
    if full or win_ok:
        kw = None if full else (kc.off, kc.n)
        prog = _sort_program(kcont.runtime.mesh, kcont.runtime.axis,
                             kcont.layout, kcont.dtype, descending,
                             pay_layout=vcont.layout,
                             pay_dtype=vcont.dtype,
                             window=kw,
                             pay_window=None if full
                             else (vc.off, vc.n),
                             aliased=aliased)
        if aliased:
            kcont._data = prog(kcont._data)
        else:
            kcont._data, vcont._data = prog(kcont._data, vcont._data)
        return keys, values
    # DIFFERENT MESHES (mismatched shard counts, or equal counts over
    # different device sets) take the reshard route (round 5 — this
    # used to be the argsort materialize): the payload reshards onto
    # the key runtime (two collective copies, the same XLA-resharding
    # class the elementwise fallback uses), the sample-sort runs
    # NATIVELY there with the keys never leaving their shards, and the
    # reordered payload reshards back into its own windows.  This is
    # the LAST remaining route — every same-mesh shape is native.
    from ..containers.distributed_vector import distributed_vector
    from .elementwise import copy as _copy
    scratch = distributed_vector(vc.n, dtype=vcont.dtype,
                                 runtime=kcont.runtime)
    _copy(values, scratch)
    sort_by_key(keys, scratch, descending=descending)
    _copy(scratch, values)
    return keys, values


def sort_n(v, iters: int):
    """``iters`` chained whole-container sorts in ONE jitted program
    (the ``inclusive_scan_n`` measurement analog): per-sort device
    time then excludes the tunneled per-dispatch overhead.  After the
    first round the data is already sorted — ``lax.sort``'s
    sorting-network cost is data-independent on TPU, so the marginal
    rounds still price the real program.  Timing aid for bench.py; the
    final content is simply the sorted input."""
    chain = _out_chain(v)
    cont = chain.cont
    assert chain.off == 0 and chain.n == len(cont), \
        "sort_n takes a whole container"
    mesh, axis = cont.runtime.mesh, cont.runtime.axis
    key = ("sort_n", pinned_id(mesh), axis, cont.layout,
           str(cont.dtype), int(iters), bool(jax.config.jax_enable_x64))
    prog = _prog_cache.get(key)
    if prog is None:
        one = _sort_program(mesh, axis, cont.layout, cont.dtype, False)

        def many(d):
            # jit-of-jit inlines `one`; its donation applies only at
            # top-level dispatch, so the loop carry is clean
            return lax.fori_loop(0, iters, lambda _, x: one(x), d)

        prog = jax.jit(many, donate_argnums=0)
        _prog_cache[key] = prog
    cont._data = prog(cont._data)
    return v


def sort_by_key_n(keys, values, iters: int):
    """``iters`` chained key-value sorts in ONE jitted program (see
    :func:`sort_n`)."""
    kc = _out_chain(keys)
    vc = _out_chain(values)
    kcont, vcont = kc.cont, vc.cont
    assert (kc.off == 0 and vc.off == 0 and kc.n == len(kcont)
            and vc.n == len(vcont)
            and kcont.runtime.mesh == vcont.runtime.mesh), \
        "sort_by_key_n takes two whole same-mesh containers"
    mesh, axis = kcont.runtime.mesh, kcont.runtime.axis
    key = ("sortkv_n", pinned_id(mesh), axis, kcont.layout,
           str(kcont.dtype), vcont.layout, str(vcont.dtype), int(iters),
           bool(jax.config.jax_enable_x64))
    prog = _prog_cache.get(key)
    if prog is None:
        one = _sort_program(mesh, axis, kcont.layout, kcont.dtype,
                            False, pay_layout=vcont.layout,
                            pay_dtype=vcont.dtype)

        def many(kd, vd):
            return lax.fori_loop(0, iters, lambda _, kv: one(*kv),
                                 (kd, vd))

        prog = jax.jit(many, donate_argnums=(0, 1))
        _prog_cache[key] = prog
    kcont._data, vcont._data = prog(kcont._data, vcont._data)
    return keys, values


def argsort(r, *, descending: bool = False):
    """The stable sort permutation of ``r`` as a new int32
    ``distributed_vector`` (``r`` itself is left untouched): index
    ``i`` of the result holds the original position of the ``i``-th
    element of the sorted order — ``sort_by_key`` over a scratch copy
    of the keys with an iota payload.  READ-ONLY in ``r``: transform
    views and other single-component ranges are accepted (the copy
    fuses the view chain)."""
    from ..containers.distributed_vector import distributed_vector
    from .elementwise import copy as _copy, iota
    res = _resolve(r)
    if res is None or len(res) != 1:
        raise TypeError("argsort takes a single distributed range")
    chain = res[0]
    scratch = distributed_vector(chain.n, dtype=chain.cont.dtype,
                                 runtime=chain.cont.runtime)
    _copy(r, scratch)
    idx = distributed_vector(chain.n, dtype=np.int32,
                             runtime=chain.cont.runtime)
    iota(idx, 0)
    sort_by_key(scratch, idx, descending=descending)
    return idx


def _is_sorted_program(mesh, axis, layout, dtype, pinned, window=None,
                       ops=()):
    from .elementwise import _op_key
    key = ("is_sorted", pinned, axis, layout, str(dtype), window,
           tuple(_op_key(f) for f in ops),
           bool(jax.config.jax_enable_x64))
    prog = _prog_cache.get(key)
    if prog is not None:
        return prog

    if window is None:
        p, S, cap, prev, nxt, n, starts, sizes = working_geometry(layout)
        wstart = None
    else:
        p, S, cap, prev, nxt, n, starts, sizes, wstart = \
            window_geometry(layout, *window)
        width = prev + cap + nxt
        woff_c = jnp.asarray(wstart, jnp.int32)
    starts_c = jnp.asarray(starts, jnp.int32)
    sizes_c = jnp.asarray(sizes, jnp.int32)

    def body(blk):
        r = lax.axis_index(axis)
        if window is None:
            raw = blk[0, prev:prev + S]
        else:
            idx = jnp.clip(prev + woff_c[r] + jnp.arange(S), 0,
                           width - 1)
            raw = jnp.take(blk[0], idx)
        for f in ops:  # view-chain op stack, fused (round 5)
            raw = f(raw)
        k, big = _encode(raw)
        nvalid = jnp.minimum(sizes_c[r],
                             jnp.clip(n - starts_c[r], 0, S))
        k = jnp.where(jnp.arange(S) < nvalid, k, big)
        # pads are the key max and trail the reals, so the local
        # vector compare stays monotone across the real->pad boundary
        local_ok = jnp.all(k[:-1] <= k[1:]) if S > 1 else jnp.bool_(True)
        # boundary check, empty-shard-safe: sorted <=> every shard is
        # locally sorted AND the max over all PREVIOUS shards' last
        # real keys <= my first real key (empty shards contribute the
        # key-domain minimum, i.e. no constraint)
        small = jnp.zeros((), k.dtype) \
            if jnp.issubdtype(k.dtype, jnp.unsignedinteger) \
            else jnp.array(jnp.iinfo(k.dtype).min, k.dtype)
        last = jnp.where(nvalid > 0,
                         k[jnp.clip(nvalid - 1, 0, S - 1)], small)
        lasts = lax.all_gather(last, axis)           # (p,)
        prevmax = jnp.max(jnp.where(jnp.arange(p) < r, lasts, small))
        first_ok = jnp.logical_or(nvalid == 0, prevmax <= k[0])
        ok = jnp.logical_and(local_ok, first_ok)
        return lax.psum(jnp.logical_not(ok).astype(jnp.int32), axis)

    shmapped = jax.shard_map(body, mesh=mesh, in_specs=P(axis, None),
                             out_specs=P())
    prog = jax.jit(shmapped)
    _prog_cache[key] = prog
    return prog


def is_sorted(r) -> bool:
    """True when the range is ascending (``std::is_sorted``; NaNs
    count as largest, numpy order).  READ-ONLY in ``r``.  Whole
    containers AND subrange windows (uniform or uneven
    distributions) run one fused shard_map program (local vector
    compare + one boundary all_gather; windows in window coordinates —
    round 4; f64 through the exact 64-bit key encoding, and transform-
    view chains with the op stack fused into the program, round 5)."""
    res = _resolve(r)
    if res is not None and len(res) != 1:
        raise TypeError("is_sorted takes a single-component range")
    chain = res[0] if res is not None else None
    if chain is not None:
        cont = chain.cont
        if chain.n == 0:
            return True
        full = chain.off == 0 and chain.n == len(cont)
        prog = _is_sorted_program(
            cont.runtime.mesh, cont.runtime.axis, cont.layout,
            cont.dtype, pinned_id(cont.runtime.mesh),
            window=None if full else (chain.off, chain.n),
            ops=chain.ops)
        return int(prog(cont._data)) == 0
    raise TypeError("is_sorted takes a distributed range")
