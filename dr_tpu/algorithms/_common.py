"""Shared helpers for the algorithm layer: layout geometry, owned-cell
masking, and monoid combine tables (used by elementwise, reduce, and scan
programs)."""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax

__all__ = ["layout_geometry", "owned_window_mask", "uniform_layout",
           "window_geometry", "working_geometry",
           "double_buffered_loop", "combine_for",
           "MONOID_COMBINE", "f32_accumulable", "on_tpu"]


def f32_accumulable(dtype) -> bool:
    """True for input dtypes the Pallas kernels may accumulate in f32
    without changing semantics (integer exactness and f64 precision
    must keep the XLA paths).  Shared gate for the scan and dot kernel
    families."""
    return jnp.dtype(dtype) in (jnp.dtype(jnp.float32),
                                jnp.dtype(jnp.bfloat16),
                                jnp.dtype(jnp.float16))


def on_tpu(runtime) -> bool:
    """Mosaic compiles for TPU only (interpret-mode tests monkeypatch
    around this at the call sites)."""
    return runtime.devices[0].platform == "tpu"


def double_buffered_loop(step, steps, x, y):
    """Run ``steps`` applications of ``y' = step(x, y)`` with buffer
    swapping, returning (final, other).

    Two steps per fori_loop iteration keep the carry order (x, y) stable —
    a swapped carry forces XLA to copy both arrays every iteration
    (2x HBM traffic and 2x peak memory).  The odd remainder runs outside
    the loop with a trace-level swap.
    """
    def two(i, xy):
        u, v = xy
        v = step(u, v)
        u = step(v, u)
        return (u, v)
    x, y = lax.fori_loop(0, steps // 2, two, (x, y))
    if steps % 2:
        y = step(x, y)
        x, y = y, x
    return x, y

MONOID_COMBINE = {
    "add": jnp.add,
    "mul": jnp.multiply,
    "min": jnp.minimum,
    "max": jnp.maximum,
}


def combine_for(kind, op):
    """Elementwise combine fn for a classified monoid, else the user op."""
    return MONOID_COMBINE[kind] if kind is not None else op


def uniform_layout(layout) -> bool:
    """True when the layout is the default ceil-division block layout
    (layout[1] is the int segment size).  Uneven ``block_distribution``
    layouts carry a tagged size tuple instead."""
    return isinstance(layout[1], int)


def layout_geometry(layout):
    """(nshards, capacity, prev, nxt, n, starts, sizes) for any layout.

    ``capacity`` is the physical owned width of every padded shard row;
    ``starts[r]``/``sizes[r]`` give rank r's logical window.  For uniform
    layouts sizes is seg everywhere (the tail masking happens via
    ``gid < n``); for uneven layouts they come from the distribution.
    """
    nshards, seg, prev, nxt, n = layout
    if isinstance(seg, tuple):  # ("b", s0, s1, ...) — block_distribution
        sizes = np.asarray(seg[1:], dtype=np.int64)
        cap = max(int(sizes.max(initial=0)), prev, nxt, 1)
        starts = np.concatenate(([0], np.cumsum(sizes)[:-1]))
    else:
        sizes = np.full(nshards, seg, dtype=np.int64)
        cap = seg
        starts = np.arange(nshards, dtype=np.int64) * seg
    return nshards, cap, prev, nxt, n, starts, sizes


def working_geometry(layout):
    """(p, S, cap, prev, nxt, n, starts, sizes) with S = the max OWNED
    width — the working row width for geometry-general shard programs
    (sort, scan).  ``cap`` additionally absorbs halo widths; the
    physical row is ``prev + cap + nxt`` with ``cap >= S``, so slicing
    ``[prev, prev + S)`` always stays in range and covers every real
    cell of every shard."""
    p, cap, prev, nxt, n, starts, sizes = layout_geometry(layout)
    S = max(int(sizes.max(initial=0)), 1)
    return p, S, cap, prev, nxt, n, starts, sizes


def owned_window_mask(layout, off, n):
    """(mask, gid) over the padded (nshards, width) cell grid.

    ``gid`` is each cell's global logical index; ``mask`` selects owned
    cells inside the logical window [off, off+n) and under the container's
    logical size (pad/halo cells excluded).  This is the single source of
    truth for the pad-and-mask rule (SURVEY.md §7 hard-part 3), for both
    uniform and uneven block distributions.
    """
    nshards, cap, prev, nxt, total_n, starts, sizes = layout_geometry(layout)
    width = prev + cap + nxt
    col = jnp.arange(width)[None, :]
    local = col - prev
    owned = (local >= 0) & (local < jnp.asarray(sizes)[:, None])
    gid = jnp.asarray(starts)[:, None] + local
    mask = owned & (gid >= off) & (gid < off + n) & (gid < total_n)
    return mask, gid


def window_geometry(layout, off, wn):
    """Window-coordinate geometry: the logical window [off, off+wn)
    intersected with each shard's owned span.  Everything is STATIC
    (numpy over the layout's python ints): ``wstart`` is each shard's
    local offset of its window slice, ``wsize`` its width, ``vstarts``
    the exclusive prefix of widths — i.e. the window re-expressed as an
    uneven block distribution of length ``wn``, which the sample-sort
    program already speaks natively."""
    p, _, cap, prev, nxt, n, starts, sizes = working_geometry(layout)
    starts = np.asarray(starts)
    sizes = np.asarray(sizes)
    wstart = np.clip(off - starts, 0, sizes)
    wsize = np.clip(off + wn - starts, 0, sizes) - wstart
    vstarts = np.concatenate(([0], np.cumsum(wsize)[:-1]))
    S = max(int(wsize.max(initial=0)), 1)
    return p, S, cap, prev, nxt, wn, vstarts, wsize, wstart


def effective_sizes(starts, sizes, n):
    """TRUE per-shard valid counts for geometries whose reported sizes
    are NOMINAL (working_geometry's uniform ceil layouts): a shard
    whose window lies at or beyond ``n`` owns zero cells, whatever its
    nominal width says.  Window geometries are already clipped — do
    not re-clip them.  ONE home for the rule, next to
    :func:`first_nonempty` / :func:`identityless_fold` (round-5 fuzz
    finding: folding a nominal-but-empty shard's pad "total" poisoned
    a product to 0.0)."""
    import numpy as np
    return np.minimum(np.asarray(sizes),
                      np.clip(n - np.asarray(starts), 0, None))


def first_nonempty(sizes) -> int:
    """The statically-known first nonempty shard — the identityless
    fold's seed.  ONE home for the rule (reduce and scan both use it);
    an all-empty geometry seeds shard 0 (whose total is never read by
    a caller that checked n > 0)."""
    nonempty = [i for i in range(len(sizes)) if sizes[i] > 0]
    return nonempty[0] if nonempty else 0


def identityless_fold(op, totals, sizes_c, nshards, first_nz, upto=None):
    """In-order fold of per-shard totals for IDENTITYLESS ops, skipping
    empty shards — the machinery the scan and custom-reduce programs
    share (one home so the subtle seeding/skip rules cannot drift).

    ``totals`` is the all_gather'ed per-shard real totals, ``first_nz``
    the statically-known first nonempty shard (the fold's seed — no
    identity element is ever needed).  ``upto=None`` folds EVERY
    nonempty shard (a global reduce); ``upto=r`` folds only shards
    before ``r`` (a scan carry)."""
    import jax.numpy as jnp
    from jax import lax

    def fold(i, acc):
        use = sizes_c[i] > 0 if upto is None \
            else jnp.logical_and(i < upto, sizes_c[i] > 0)
        return jnp.where(use, op(acc, totals[i]), acc)
    return lax.fori_loop(first_nz + 1, nshards, fold, totals[first_nz])
