"""Shared helpers for the algorithm layer: owned-cell masking and monoid
combine tables (used by elementwise, reduce, and scan programs)."""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["owned_window_mask", "combine_for", "MONOID_COMBINE"]

MONOID_COMBINE = {
    "add": jnp.add,
    "mul": jnp.multiply,
    "min": jnp.minimum,
    "max": jnp.maximum,
}


def combine_for(kind, op):
    """Elementwise combine fn for a classified monoid, else the user op."""
    return MONOID_COMBINE[kind] if kind is not None else op


def owned_window_mask(layout, off, n):
    """(mask, gid) over the padded (nshards, width) cell grid.

    ``gid`` is each cell's global logical index; ``mask`` selects owned
    cells inside the logical window [off, off+n) and under the container's
    logical size (pad/halo cells excluded).  This is the single source of
    truth for the pad-and-mask rule (SURVEY.md §7 hard-part 3).
    """
    nshards, seg, prev, nxt, total_n = layout
    width = prev + seg + nxt
    col = jnp.arange(width)[None, :]
    row = jnp.arange(nshards)[:, None]
    owned = (col >= prev) & (col < prev + seg)
    gid = row * seg + (col - prev)
    mask = owned & (gid >= off) & (gid < off + n) & (gid < total_n)
    return mask, gid
