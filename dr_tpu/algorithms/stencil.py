"""Fused 1-D stencil: halo exchange + neighborhood transform in ONE
XLA program — the framework's north-star workload.

Reference workload (``examples/mhp/stencil-1d.cpp:47-66``): per step,
``mhp::halo(in).exchange()`` (MPI messages) then ``mhp::transform`` with an
op reading raw-pointer neighbors.  The TPU re-design fuses both into a
single jitted ``shard_map`` program per step: ``lax.ppermute`` edge shifts
feed ghost cells, the neighborhood transform reads statically-shifted
slices of the padded row, and XLA overlaps the collective with compute.
``stencil_iterate`` goes further and runs S steps inside one program with
``lax.fori_loop`` double-buffering — zero host round-trips per step, the
shape a multi-step MPI stencil can never reach.

The stencil op is either a weight vector (w[-prev..+next], the linear
case that maps to pure VPU work) or a jax-traceable ``fn(*shifted)`` over
the ``prev+next+1`` shifted neighborhood arrays.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ._common import double_buffered_loop, uniform_layout
from .elementwise import (_op_key, _out_chain, _plan_active, _prog_cache,
                          _resolve)
from ..core.pinning import pinned_id
from ..parallel.halo import _ring_perms
from ..utils import spmd_guard
from ..utils.env import env_str

__all__ = ["stencil_transform", "stencil_iterate", "build_stencil_step",
           "stencil_iterate_blocked", "stencil_iterate_matmul"]


def _shift_window(row, d, prev, seg):
    """Neighborhood slice at offset d: element j -> row[prev + j + d]."""
    return lax.slice_in_dim(row, prev + d, prev + d + seg, axis=0)


def build_stencil_step(layout, periodic, op, prev, nxt, axis):
    """Un-jitted shard_map body for one fused exchange+transform step.

    ``layout`` is the container layout (nshards, seg, prev, nxt, n); the
    body maps one padded row (1, width) -> one output row.  Usable under
    jit directly or inside fori_loop (see stencil_iterate).
    """
    nshards, seg, hprev, hnxt, n = layout
    assert hprev >= prev and hnxt >= nxt, "halo narrower than stencil radius"
    tail = n - (nshards - 1) * seg
    fwd, bwd = _ring_perms(nshards, periodic)

    def step(in_blk, out_blk):
        idx = lax.axis_index(axis)
        valid = jnp.where(idx == nshards - 1, tail, seg)
        row = in_blk[0]
        # --- fused halo exchange (parallel/halo.py semantics) ---
        if hprev and (nshards > 1 or periodic):
            send = lax.dynamic_slice_in_dim(row, hprev + valid - hprev,
                                            hprev, axis=0)
            recv = lax.ppermute(send[None], axis, fwd)[0]
            got = jnp.bool_(periodic) if periodic else idx > 0
            row = row.at[:hprev].set(jnp.where(got, recv, row[:hprev]))
        if hnxt and (nshards > 1 or periodic):
            send = row[hprev: hprev + hnxt]
            recv = lax.ppermute(send[None], axis, bwd)[0]
            got = jnp.bool_(periodic) if periodic else idx < nshards - 1
            old = lax.dynamic_slice_in_dim(row, hprev + valid, hnxt, axis=0)
            row = lax.dynamic_update_slice_in_dim(
                row, jnp.where(got, recv, old), hprev + valid, axis=0)
        # --- neighborhood transform over shifted slices ---
        shifted = [_shift_window(row, d, hprev, seg)
                   for d in range(-prev, nxt + 1)]
        vals = op(*shifted)
        # interior mask: positions with a full neighborhood
        gid = idx * seg + jnp.arange(seg)
        if periodic:
            mask = gid < n
        else:
            mask = (gid >= prev) & (gid < n - nxt)
        body = jnp.where(mask, vals.astype(out_blk.dtype),
                         out_blk[0, hprev:hprev + seg])
        return out_blk.at[0, hprev:hprev + seg].set(body)

    return step


def _weights_op(weights, dtype):
    w = tuple(float(x) for x in np.asarray(weights).ravel())

    def op(*shifted):
        acc = shifted[0] * w[0]
        for wi, s in zip(w[1:], shifted[1:]):
            acc = acc + s * wi
        return acc
    return op, w


def stencil_transform(in_dv, out_dv, op: Union[Callable, Sequence[float]],
                      radius: Optional[int] = None) -> None:
    """One fused halo-exchange + stencil-transform step.

    ``op``: weight vector of length prev+next+1, or fn over shifted arrays.
    The stencil radius defaults to the container's halo bounds.
    """
    ic = _resolve(in_dv)
    oc = _out_chain(out_dv)
    assert ic is not None and len(ic) == 1 and not ic[0].ops and \
        ic[0].off == 0 and ic[0].n == len(ic[0].cont), \
        "stencil input must be a whole distributed_vector"
    cont = ic[0].cont
    assert oc.off == 0 and oc.n == len(oc.cont) and \
        oc.cont.layout == cont.layout, \
        "stencil output must be a whole aligned distributed_vector"
    assert uniform_layout(cont.layout), \
        "stencils require the uniform block distribution"
    hb = cont.halo_bounds
    prev = nxt = radius if radius is not None else None
    if callable(op):
        key_op = _op_key(op)
        body_op = op
        if prev is None:
            prev, nxt = hb.prev, hb.next
    else:
        body_op, key_op = _weights_op(op, cont.dtype)
        if prev is None:
            # weight vectors fix the radius themselves; the halo may be wider
            prev = nxt = (len(key_op) - 1) // 2
        assert hb.prev >= prev and hb.next >= nxt, \
            "halo narrower than the weight-stencil radius"
    p = _plan_active()
    if p is not None:
        # one fused exchange+transform step joins the deferred run
        p.record_stencil(cont, oc.cont, cont.layout, hb.periodic,
                         prev, nxt, key_op, body_op,
                         cont.runtime.axis, cont.runtime.mesh)
        return
    key = ("stencil", pinned_id(cont.runtime.mesh), cont.layout, hb.periodic,
           prev, nxt, key_op, str(cont.dtype))
    prog = _prog_cache.get(key)
    if prog is None:
        step = build_stencil_step(cont.layout, hb.periodic, body_op,
                                  prev, nxt, cont.runtime.axis)
        shmapped = jax.shard_map(
            step, mesh=cont.runtime.mesh,
            in_specs=(P(cont.runtime.axis, None), P(cont.runtime.axis, None)),
            out_specs=P(cont.runtime.axis, None))
        prog = jax.jit(shmapped, donate_argnums=1)
        _prog_cache[key] = prog
    out_dv._data = prog(cont._data, out_dv._data)


def stencil_iterate(a_dv, b_dv, op: Union[Callable, Sequence[float]],
                    steps: int):
    """Run ``steps`` fused stencil steps with double buffering inside ONE
    jitted program (lax.fori_loop) — no host dispatch per step.

    Returns the container holding the final state (a for even step counts,
    b for odd), mirroring the reference's buffer swap loop
    (stencil-1d.cpp:54-58).
    """
    p = _plan_active()
    if p is not None:
        # already one dispatch for S steps: record OPAQUE (deferred in
        # order, dispatched through its own program at flush)
        p.record_opaque("stencil_iterate",
                        lambda: stencil_iterate(a_dv, b_dv, op, steps),
                        reads=(a_dv, b_dv),
                        writes=((a_dv, False), (b_dv, False)))
        return a_dv
    cont = a_dv
    assert b_dv.layout == cont.layout
    assert uniform_layout(cont.layout), \
        "stencils require the uniform block distribution"
    hb = cont.halo_bounds
    if callable(op):
        key_op = _op_key(op)
        body_op = op
        prev, nxt = hb.prev, hb.next
    else:
        body_op, key_op = _weights_op(op, cont.dtype)
        # the stencil radius is set by the weight vector, which may be
        # narrower than the container's halo (e.g. wide blocked-path halos)
        rad = (len(key_op) - 1) // 2
        prev = nxt = rad
        assert hb.prev >= rad and hb.next >= rad, \
            "halo narrower than the weight-stencil radius"
    key = ("stencil_it", pinned_id(cont.runtime.mesh), cont.layout, hb.periodic,
           key_op, steps, str(cont.dtype))
    prog = _prog_cache.get(key)
    if prog is None:
        step = build_stencil_step(cont.layout, hb.periodic, body_op,
                                  prev, nxt, cont.runtime.axis)

        def loop(a, b):
            return double_buffered_loop(step, steps, a, b)

        shmapped = jax.shard_map(
            loop, mesh=cont.runtime.mesh,
            in_specs=(P(cont.runtime.axis, None), P(cont.runtime.axis, None)),
            out_specs=(P(cont.runtime.axis, None), P(cont.runtime.axis, None)))
        prog = jax.jit(shmapped, donate_argnums=(0, 1))
        _prog_cache[key] = prog
    fin, other = prog(a_dv._data, b_dv._data)
    a_dv._data, b_dv._data = fin, other
    return a_dv


def stencil_iterate_blocked(dv, weights, steps: int, *, time_block: int = 8,
                            chunk: int = 2 ** 17, interpret=None):
    """Temporally-blocked stencil: T steps fused per HBM pass via the
    Pallas kernel (ops/stencil_pallas.py), with ONE ppermute halo exchange
    per T-step block instead of per step — both the HBM traffic and the
    ICI message count drop ~T-fold versus stencil_iterate.

    Requirements: periodic ring (every cell computed — the context-
    parallel shape), halo width >= time_block * radius, and equal full
    shards (n divisible by nshards * segment alignment).  Returns ``dv``
    stepped ``steps`` times.
    """
    p = _plan_active()
    if p is not None:
        p.record_opaque(
            "stencil_iterate_blocked",
            lambda: stencil_iterate_blocked(dv, weights, steps,
                                            time_block=time_block,
                                            chunk=chunk,
                                            interpret=interpret),
            reads=(dv,), writes=((dv, False),))
        return dv
    cont = dv
    hb = cont.halo_bounds
    r = (len(weights) - 1) // 2
    nshards, seg, prev, nxt, n = cont.layout
    assert hb.periodic, "blocked stencil runs on the periodic ring"
    assert prev == nxt and prev >= time_block * r, \
        "halo width must cover time_block * radius"
    assert n == nshards * seg, "blocked stencil needs equal full shards"
    # one ppermute hop supplies at most seg fresh neighbor elements; a
    # deeper time block would read the sender's own stale ghosts
    assert time_block * r <= seg, \
        "time_block * radius exceeds the per-shard segment"
    if interpret is None:
        interpret = cont.runtime.devices[0].platform != "tpu"

    w = tuple(float(x) for x in weights)
    key = ("stencil_blk", pinned_id(cont.runtime.mesh), cont.layout, w,
           time_block, chunk, bool(interpret), str(cont.dtype))
    return _blocked_drive(
        cont, key, steps, time_block,
        lambda nst: _make_blocked_prog(cont, w, nst, chunk, interpret))


def _blocked_drive(cont, key, steps, block, make_prog):
    """Shared drive loop for the temporally-blocked paths: cache one
    program per fused step count (full block + remainder) and apply."""
    progs = _prog_cache.setdefault(key, {})
    nfull, rest = divmod(steps, block)
    if nfull and block not in progs:
        progs[block] = make_prog(block)
        spmd_guard.note_compile(key + (block,))
    if rest and rest not in progs:
        progs[rest] = make_prog(rest)
        spmd_guard.note_compile(key + (rest,))
    data = cont._data
    for _ in range(nfull):
        data = progs[block](data)
    if rest:
        data = progs[rest](data)
    cont._data = data
    return cont


def stencil_iterate_matmul(dv, weights, steps: int, *, k_block: int = 32):
    """Temporally-blocked stencil on the MXU (ops/stencil_matmul.py):
    ``k_block`` steps composed into one banded-Toeplitz operator applied
    as lane-column matmuls, with ONE ppermute halo exchange per block.

    Same contract as :func:`stencil_iterate_blocked` (periodic ring,
    equal full shards, halo width >= k_block * radius); additionally
    k_block <= max_ksteps(radius) — the composed band may span up to
    four lane columns each side by default (DR_TPU_MM_BAND_COLS moves
    the cap).  Returns ``dv`` stepped ``steps`` times.
    """
    p = _plan_active()
    if p is not None:
        p.record_opaque(
            "stencil_iterate_matmul",
            lambda: stencil_iterate_matmul(dv, weights, steps,
                                           k_block=k_block),
            reads=(dv,), writes=((dv, False),))
        return dv
    from ..ops import stencil_matmul
    cont = dv
    hb = cont.halo_bounds
    r = (len(weights) - 1) // 2
    nshards, seg, prev, nxt, n = cont.layout
    assert hb.periodic, "blocked stencil runs on the periodic ring"
    assert prev == nxt and prev >= k_block * r, \
        "halo width must cover k_block * radius"
    assert n == nshards * seg, "blocked stencil needs equal full shards"
    assert k_block <= stencil_matmul.max_ksteps(r), \
        "composed band exceeds the supported lane-column reach"
    assert k_block * r <= seg, \
        "k_block * radius exceeds the per-shard segment"
    # surface the matmul path's lane-alignment preconditions here, at the
    # API level, instead of as an assertion inside the shard_map trace
    la = stencil_matmul.LANES
    assert seg % la == 0, (
        f"stencil_iterate_matmul requires the per-shard segment "
        f"({seg}) to be a multiple of {la} lanes")
    assert prev % la == 0, (
        f"stencil_iterate_matmul requires the halo width ({prev}) "
        f"to be a multiple of {la} lanes")

    w = tuple(float(x) for x in weights)
    # impl resolves from env at build time: key on it so flipping
    # DR_TPU_MM_IMPL between calls rebuilds instead of silently reusing
    # the chunk cap is a trace-time constant of the fused apply: key on
    # it so DR_TPU_MM_CHUNK_CAP sweeps rebuild instead of reusing stale
    # programs
    key = ("stencil_mm", pinned_id(cont.runtime.mesh), cont.layout, w, k_block,
           str(cont.dtype), _matmul_impl(cont), stencil_matmul._chunk_cap())
    return _blocked_drive(cont, key, steps, k_block,
                          lambda nst: _make_matmul_prog(cont, w, nst))


def _ring_exchange_full(blk, seg, halo_w, axis, nshards):
    """Periodic full-width ghost refresh for the blocked paths: both edge
    slices of the owned block move one hop around the ring."""
    fwd, bwd = _ring_perms(nshards, True)
    width = 2 * halo_w + seg
    send_f = blk[:, halo_w + seg - halo_w: halo_w + seg]
    blk = blk.at[:, :halo_w].set(lax.ppermute(send_f, axis, fwd))
    send_b = blk[:, halo_w: 2 * halo_w]
    blk = blk.at[:, width - halo_w:].set(lax.ppermute(send_b, axis, bwd))
    return blk


def _matmul_impl(cont) -> str:
    """Composed-operator apply implementation: the fused VMEM Pallas
    apply on TPU (one HBM read + write per composed block instead of
    the P-form's ~4x), the XLA P-form elsewhere or on request
    (DR_TPU_MM_IMPL=pallas|xla)."""
    from ..ops import stencil_pallas
    impl = env_str("DR_TPU_MM_IMPL").lower()
    if impl in ("pallas", "xla"):
        return impl
    return "pallas" if (
        stencil_pallas.supported()
        and cont.runtime.devices[0].platform == "tpu") else "xla"


def _make_matmul_prog(cont, weights, ksteps):
    from ..ops import stencil_matmul
    nshards, seg, prev, nxt, n = cont.layout
    halo_w = prev
    axis = cont.runtime.axis
    impl = _matmul_impl(cont)

    def body(blk):
        blk = _ring_exchange_full(blk, seg, halo_w, axis, nshards)
        return stencil_matmul.matmul_stencil_row(
            blk, seg, halo_w, weights, ksteps, impl=impl)

    # check_vma=False: pallas_call outputs carry no varying-mesh-axis
    # annotation, which the default shard_map checker rejects
    shm = jax.shard_map(body, mesh=cont.runtime.mesh,
                        in_specs=P(axis, None), out_specs=P(axis, None),
                        check_vma=(impl != "pallas"))
    return jax.jit(shm, donate_argnums=0)


def _make_blocked_prog(cont, weights, tsteps, chunk, interpret):
    from ..ops import stencil_pallas
    nshards, seg, prev, nxt, n = cont.layout
    halo_w = prev
    axis = cont.runtime.axis
    w = tuple(float(x) for x in weights)

    def body(blk):
        blk = _ring_exchange_full(blk, seg, halo_w, axis, nshards)
        return stencil_pallas.blocked_stencil_row(
            blk, seg, halo_w, w, tsteps, chunk=chunk, interpret=interpret)

    # check_vma=False: pallas_call outputs carry no varying-mesh-axis
    # annotation, which the default shard_map checker rejects
    shm = jax.shard_map(body, mesh=cont.runtime.mesh,
                        in_specs=P(axis, None), out_specs=P(axis, None),
                        check_vma=False)
    # donation lets the ghost-column updates write in place instead of
    # copying the whole padded row per T-step block
    return jax.jit(shm, donate_argnums=0)
