"""Relational analytics on the sort/scan backbone: distributed
``join`` / ``groupby_aggregate`` / ``unique`` / ``histogram`` /
streaming ``top_k``.

This is the first multi-op COMPOSITE tier built on the backbone rather
than inside it (ROADMAP item 4, "Distributed Ranges" as an
STL-of-distributed-data model — arXiv:2406.00158): the sample-sort
single-exchange payload plan supplies the global order, boundary-flag
scans find the group structure, and the segment-aware masked-sum
assembly (the sort family's rebalance pattern) re-homes per-group
partials into each output's own block distribution.  Each op is ONE
cached jitted ``shard_map`` program per layout (dispatched through the
tapped program cache, so ``dispatch.cache``/``device.lost`` ride every
call), correct eager AND deferred-plan-recordable, classified through
the existing error taxonomy, and traced as an ``obs`` span with
per-phase attrs (``tools/trace_view.py`` shows where a join spends its
time).  docs/SPEC.md §17 is the spec.

Algorithm shapes
----------------

* ``groupby_aggregate(keys, values, out_keys, out_vals, agg)`` —
  non-mutating: key/value chains copy into fresh uniform SCRATCH
  containers and stable-sort by key (``sort_by_key``, the round-6
  single-exchange plan).  One program then (1) boundary-flags the
  sorted keys (one ``all_gather`` of p shard-boundary keys — a group
  is a run, a run crossing a shard boundary continues segment 0), (2)
  segmented-reduces each shard's runs (``jax.ops.segment_*`` over the
  static ≤ seg+1 local segments — the bucketed scatter-add of the
  reduce path), and (3) re-homes the per-run partials into the OUT
  containers' own block distributions by one masked ``all_to_all`` +
  per-column monoid combine per channel (a group split across shards
  merges its partials there; the representative key rides a
  min-combine channel — exact, every contributor holds the same key).
  Group ``i`` of the sorted-distinct key order lands at OUT position
  ``i``; positions ``>= ngroups`` are ZERO.  Returns ``ngroups``.
* ``unique(r, out)`` — the groupby machinery, keys channel only.
* ``join(lk, lv, rk, rv, out_keys, out_lv, out_rv, how=...)`` —
  sort-merge join, TWO merge routes behind one contract (bit-identical
  rows, docs/SPEC.md §18.4).  Both sides sort natively (scratch,
  non-mutating).  Small combined sides (``nl + nr`` at or under
  ``DR_TPU_JOIN_BROADCAST_MAX``) take the BROADCAST merge: one program
  ``all_gather``\\ s the sorted sides (per-device memory O(n_l +
  n_r)), counts each left row's matches by two ``searchsorted``\\ s on
  the monotone key encoding, prefix-sums the counts into output
  offsets (the scan backbone's shape), and every OUT shard
  materializes exactly its own window of the expanded rows.  Above
  the threshold the merge re-homes on the bounded-memory REPARTITION
  exchange (arXiv:2112.01075's recipe on the shared ring machinery —
  ROADMAP item 1 landed): the sorted left side is already
  position-partitioned, each shard's key range is its own block's
  [first, last] keys, a one-dispatch probe sizes the per-shard
  contiguous right partition (pow2-quantized ``rcap``), the right
  blocks rotate once around the ring with each shard scattering only
  its own key range (ONE block in flight — never a full-side
  replica), and producer-side masked ``all_to_all`` assembly lands
  every out window bit-exactly.  ``how="left"``/``"right"`` ride
  presence flags: unmatched rows emit ``fill`` on the missing side.
  Output rows are ordered by (key, left position, right position);
  positions ``>= count`` are ZERO.  Returns the row count.
* ``histogram(r, out, lo, hi)`` — fixed ``bins = len(out)`` buckets:
  per-shard bucketed scatter-add (``segment_sum``) + one ``psum``;
  bucket ``i`` covers ``[lo + i*w, lo + (i+1)*w)`` with
  ``w = (hi-lo)/bins`` and the right edge ``hi`` INCLUSIVE in the
  last bucket (numpy's rule); out-of-range values are dropped.
* ``top_k(r, out_vals, out_idx=None, largest=True, merge=False)`` —
  ``k = len(out_vals)``: per-shard (value, index) 2-key sort over the
  monotone encoding, ``all_gather`` of p*k candidates, one global
  2-key sort.  Ties break toward the SMALLER index.  ``merge=True``
  folds the CURRENT contents of ``out_vals``/``out_idx`` into the
  candidate pool, so chaining calls over successive windows streams a
  running top-k without re-reading old windows.  Unfilled slots hold
  the dtype's finite worst value (``finfo/iinfo`` min for largest,
  max for smallest — never inf, so the sanitizer's finite sweep keeps
  meaning) and index ``INT32_MAX``.

Deferred plans (docs/SPEC.md §11/§17.2): ``histogram`` and ``top_k``
have STATIC output shapes and record FUSIBLE (they fuse into the
surrounding run — ``plan.record_histogram``/``record_top_k``, elastic
replay included); ``join``/``groupby_aggregate``/``unique`` have
data-dependent result counts and record ORDERED OPAQUE (the gemv
discipline: own dispatch at flush, record order preserved, no flush
cliff, no warn) — their count returns a lazy :class:`DeferredCount`
resolving on host materialization.

Failure matrix: API misuse (wrong range kinds, mismatched dtypes or
meshes, unknown ``agg``/``how``) raises ``TypeError``/``ValueError``
at the call site, BEFORE anything records or dispatches; a result
that overflows the caller's output capacity raises a classified
``resilience.ProgramError`` AFTER the program ran (the first
``capacity`` rows are valid, the message names the real size);
backend faults ride the existing sites (``dispatch.cache`` /
``device.lost`` on every program dispatch, ``plan.flush`` for
deferred runs) and surface classified like every other algorithm.

Key equality is the sort family's monotone total-order encoding:
``-0.0 == +0.0``, every NaN is ONE key (NaN keys group together and
JOIN each other — numpy's NaNs-last order, unlike pandas' NaN-drop),
f64 keys are exact on x64-enabled meshes.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ._common import owned_window_mask, working_geometry
from ..core.pinning import pinned_id
from .elementwise import (_apply_chain_ops, _chain_scalars, _out_chain,
                          _plan_active, _prog_cache, _resolve,
                          _traced_op_key, copy as _copy)
from .reduce import _identity_for
from .sort import _decode, _encode, _kernel_key_dtype
from ..ops import hist_pallas, kernels, segred_pallas
from .. import obs as _obs
from ..parallel.pipeline import fire_ppermute, ring_pipeline
from ..utils import resilience as _resilience
from ..utils.env import env_int, env_raw
from ..views import views as _v

__all__ = ["join", "groupby_aggregate", "unique", "histogram", "top_k",
           "join_auto", "groupby_auto", "unique_auto", "AutoResult",
           "DeferredCount", "AGGS", "JOIN_HOWS", "last_join_route"]

#: supported groupby aggregations (docs/SPEC.md §17.1)
AGGS = ("sum", "min", "max", "count", "mean")
#: supported join flavors (docs/SPEC.md §17.1; ``outer`` landed with
#: the data-plane round — presence-flag UNION on both merge routes)
JOIN_HOWS = ("inner", "left", "right", "outer")

_GMAX = np.int32(np.iinfo(np.int32).max)


class DeferredCount:
    """Lazy result count from a relational op recorded OPAQUE in a
    deferred region (``join``/``groupby_aggregate``/``unique``).
    Resolving it (``item()`` / ``int()`` / ``float()`` / ``bool()`` /
    ``==``) flushes the owning plan if still pending — host
    materialization is a flush point, the ``PlanScalar`` contract.  A
    count whose flush was discarded (faulted flush, abandoned region)
    raises instead of returning a stale number."""

    __slots__ = ("_plan", "_box")

    def __init__(self, plan, box):
        self._plan = plan
        self._box = box

    def item(self) -> int:
        if not self._box:
            self._plan.flush("relational count read")
        if not self._box:
            raise RuntimeError(
                "deferred relational count was discarded before it "
                "resolved (faulted flush or abandoned region)")
        return int(self._box[-1])

    def __int__(self):
        return self.item()

    def __index__(self):
        return self.item()

    def __float__(self):
        return float(self.item())

    def __bool__(self):
        return bool(self.item())

    def __eq__(self, other):
        if isinstance(other, DeferredCount):
            other = other.item()
        return self.item() == other

    # resolving inside hash() would be a hidden flush (PlanScalar rule)
    __hash__ = None

    def __repr__(self):
        state = repr(self._box[-1]) if self._box else "pending"
        return f"DeferredCount({state})"


# ---------------------------------------------------------------------------
# shared plumbing
# ---------------------------------------------------------------------------

class _InChain:
    """A resolved input chain bundled with the ORIGINAL range object
    (``view``) so the scratch copy fuses the whole view pipeline."""

    __slots__ = ("cont", "off", "n", "ops", "view")

    def __init__(self, chain, view):
        self.cont = chain.cont
        self.off = chain.off
        self.n = chain.n
        self.ops = chain.ops
        self.view = view


def _single_chain(r, what: str):
    """Resolve ``r`` into ONE distributed container chain or raise."""
    chains = _resolve(r) if not isinstance(r, _v.zip_view) else None
    if chains is None or len(chains) != 1:
        raise TypeError(
            f"{what} takes a single distributed range (a "
            "distributed_vector or a view chain over one)")
    return chains[0]


def _in_chain(r, what: str) -> _InChain:
    return _InChain(_single_chain(r, what), r)


def _whole_out(out, what: str):
    """Output containers must be WHOLE non-empty distributed_vectors
    (the relational programs rebuild the full padded rows)."""
    chain = _out_chain(out)
    if chain.off != 0 or chain.n != len(chain.cont):
        raise TypeError(f"{what}: output must be a whole "
                        "distributed_vector (windows are not supported)")
    if chain.n == 0:
        raise TypeError(f"{what}: output container must be non-empty")
    return chain


def _worst(dtype, largest: bool):
    """The dtype's FINITE worst value in the requested order — the
    top_k empty-slot sentinel (finite so the DR_TPU_SANITIZE plan-flush
    sweep keeps meaning; a real value equal to it merely ties and
    loses to any real index)."""
    dt = jnp.dtype(dtype)
    if jnp.issubdtype(dt, jnp.floating):
        fi = jnp.finfo(dt)
        return jnp.array(fi.min if largest else fi.max, dt)
    ii = jnp.iinfo(dt)
    return jnp.array(ii.min if largest else ii.max, dt)


def _dest_geometry(layout):
    """Static destination-side geometry for the output assembly:
    ``(So, starts_c, sizes_c)`` — slot ``t`` of shard ``d`` holds
    result position ``starts[d] + t`` while ``t < sizes[d]``."""
    _, So, _, _, _, _, starts, sizes = working_geometry(layout)
    return (So, jnp.asarray(np.asarray(starts), jnp.int32),
            jnp.asarray(np.asarray(sizes), jnp.int32))


def _pack_out_row(vals, live, layout, r):
    """Place per-slot values (``(So,)`` in result coordinates for
    shard ``r``) into a full padded shard row, zeroing pad/halo/tail
    cells — the whole-container analog of the sort family's
    ``_pack_row``.  ``live`` masks the slots actually written."""
    _, So, cap, oprev, onxt, _, _, sizes = working_geometry(layout)
    sizes_c = jnp.asarray(np.asarray(sizes), jnp.int32)
    owidth = oprev + cap + onxt
    col = jnp.arange(owidth) - oprev
    colc = jnp.clip(col, 0, So - 1)
    ok = (col >= 0) & (col < sizes_c[r]) & jnp.take(live, colc)
    return jnp.where(ok, jnp.take(vals, colc),
                     jnp.zeros((), vals.dtype))[None]


def _sorted_scratch(chain: _InChain, vchain=None, *, sid=0,
                    phase="sort"):
    """Copy key (and value) chains into fresh UNIFORM scratch
    containers on the key runtime and stable-sort by key — the
    non-mutating backbone step every relational op starts from.
    Returns ``(skeys, svals_or_None, n)``; for ``n == 0`` the scratch
    is a masked-off single cell (the programs take the REAL count as a
    static parameter)."""
    from ..containers.distributed_vector import distributed_vector
    from .sort import sort as _sort, sort_by_key as _sort_by_key
    t0 = _obs.now()
    n = chain.n
    rt = chain.cont.runtime
    cap = max(n, 1)
    sk = distributed_vector(cap, dtype=chain.cont.dtype, runtime=rt)
    sv = None
    if vchain is not None:
        sv = distributed_vector(cap, dtype=vchain.cont.dtype,
                                runtime=rt)
    if n:
        _copy(chain.view, sk)
        if sv is not None:
            _copy(vchain.view, sv)
            _sort_by_key(sk, sv)
        else:
            _sort(sk)
    _obs.complete("relational.phase", t0, cat="relational", parent=sid,
                  phase=phase, n=n)
    return sk, sv, n


def _raise_capacity(what: str, need: int, cap: int) -> None:
    raise _resilience.ProgramError(
        f"{what}: result has {need} rows but the output containers "
        f"hold only {cap} — the first {cap} rows are valid; size the "
        "outputs for the worst case or pre-aggregate")


def _opaque_meta(kind: str, inputs: dict, outs) -> dict:
    """The structured record a deferred relational op leaves on its
    opaque queue item (docs/SPEC.md §21.2): ``inputs`` maps channel
    name -> the view argument (the THUNK re-reads this dict at flush,
    so the pushdown pass may rewrite entries in place), ``chains``
    summarizes each channel as ``(container, off, n, plain)`` for the
    pass's eligibility checks, and ``outs`` are the containers the
    eager body rebuilds wholesale (full-coverage writes)."""
    chains = {}
    for name, view in inputs.items():
        ch = _single_chain(view, kind)
        chains[name] = (ch.cont, ch.off, ch.n, not ch.ops)
    return {"kind": kind, "inputs": dict(inputs), "chains": chains,
            "outs": tuple(outs)}


def _meta_footprint(meta):
    """(reads, writes) the plan optimizer keys on: every input chain's
    container is read; every out container is rebuilt wholesale."""
    reads = []
    for _name, ch in meta["chains"].items():
        if ch[0] not in reads:
            reads.append(ch[0])
    return tuple(reads), tuple((c, True) for c in meta["outs"])


# ---------------------------------------------------------------------------
# groupby_aggregate / unique
# ---------------------------------------------------------------------------

def _acc_dtype(vdtype):
    """Aggregation accumulator dtype: low-precision floats accumulate
    in f32 (the scan kernel's rule); everything else keeps its own."""
    dt = jnp.dtype(vdtype)
    if jnp.issubdtype(dt, jnp.inexact):
        return jnp.promote_types(dt, jnp.float32)
    return dt


def _groupby_program(mesh, axis, klayout, kdtype, vlayout, vdtype,
                     ok_layout, ok_dtype, ov_layout, ov_dtype, agg,
                     nreal):
    """One fused program: boundary flags -> local segmented reduce ->
    masked all_to_all partial combine into each OUT distribution.
    ``vlayout`` is None for ``values=None`` (count), ``ov_layout``
    None for the keys-only form (``unique``).  ``nreal`` is the REAL
    element count (the scratch capacity is max(n, 1))."""
    p, S, cap, prev, nxt, ncap, starts, sizes = \
        working_geometry(klayout)
    assert prev == 0 and nxt == 0 and cap == S, \
        "groupby scratch must be a fresh halo-free uniform container"
    has_vals = vlayout is not None
    has_ov = ov_layout is not None
    acc = _acc_dtype(vdtype) if has_vals else jnp.int32
    nseg = S + 1

    # segred kernel-arm decision (docs/SPEC.md §22): the masked-compare
    # Pallas reduce replaces the jax.ops.segment_* scatter when picked.
    # The monoid columns are EXACT both routes by construction — the
    # key channel is a min, the count an int32 sum, and a float-
    # accumulated sum/mean column makes the call ineligible (float
    # addition is combine-order-sensitive).  64-bit columns (x64 key
    # encodings, f64 accumulators) are interpret-only.
    kdt = _kernel_key_dtype(kdtype)
    cols_dt = [(kdt, "min"), (np.int32, "sum")]
    if has_vals and agg in ("sum", "mean"):
        cols_dt.append((acc, "sum"))
    elif has_vals and agg in ("min", "max"):
        cols_dt.append((acc, agg))
    kern = kernels.use_kernel(
        "segred", kernels.mesh_platform(mesh),
        eligible=segred_pallas.eligible(S, nseg, cols_dt))
    if kern.use and not kern.interpret and any(
            jnp.dtype(dt).itemsize == 8 for dt, _ in cols_dt):
        kern = kernels.NO_KERNEL  # wide columns are interpret-only

    key = ("relgb", pinned_id(mesh), axis, klayout, str(kdtype),
           vlayout, str(vdtype) if vlayout is not None else None,
           ok_layout, str(ok_dtype),
           ov_layout, str(ov_dtype) if ov_layout is not None else None,
           agg, int(nreal), tuple(kern),
           bool(jax.config.jax_enable_x64))
    prog = _prog_cache.get(key)
    if prog is not None:
        return prog

    def body(kblk, *rest):
        r = lax.axis_index(axis)
        x = kblk[0]                                    # (S,)
        kenc, big = _encode(x)
        nvalid = jnp.clip(nreal - r * S, 0, S)
        valid = jnp.arange(S) < nvalid
        kenc = jnp.where(valid, kenc, big)
        # boundary flags: uniform ceil layouts have only TRAILING
        # short shards, so any nonempty shard's predecessor is FULL
        # and its last real key sits at position S-1 — one p-wide
        # all_gather finds every cross-shard group continuation
        lasts = lax.all_gather(kenc[S - 1], axis)      # (p,)
        prevk = lasts[jnp.maximum(r - 1, 0)]
        first = jnp.where(r == 0, valid[0],
                          valid[0] & (kenc[0] != prevk))
        flags = jnp.concatenate(
            [first[None].astype(jnp.int32),
             (valid[1:] & (kenc[1:] != kenc[:-1])).astype(jnp.int32)])
        segid = jnp.cumsum(flags)      # 0 = continuation of prev shard
        m = segid[S - 1]               # my run count
        counts = lax.all_gather(m, axis)               # (p,)
        gid_off = jnp.sum(jnp.where(jnp.arange(p) < r, counts, 0))
        ng = jnp.sum(counts)

        # local segmented reduce over the static <= S+1 run segments —
        # the bucketed scatter-add of the reduce path.  My segment j
        # holds global group id gid_off - 1 + j (segment 0 continues
        # the previous shard's open group).
        if kern.use:
            # ONE masked-compare kernel call computes exactly the
            # columns this agg reads (the XLA route computes all and
            # lets dead-code elimination drop the rest)
            cols = [(jnp.where(valid, kenc, big), "min"),
                    (valid.astype(jnp.int32), "sum")]
            if has_vals:
                vacc = rest[0][0].astype(acc)
                if agg in ("sum", "mean"):
                    cols.append((jnp.where(valid, vacc,
                                           jnp.zeros((), acc)), "sum"))
                elif agg == "min":
                    cols.append((jnp.where(
                        valid, vacc, _identity_for("min", acc)), "min"))
                elif agg == "max":
                    cols.append((jnp.where(
                        valid, vacc, _identity_for("max", acc)), "max"))
            res = segred_pallas.segmented(
                segid.astype(jnp.int32), nseg, tuple(cols),
                interpret=kern.interpret)
            pkey, pcnt = res[0], res[1]
            if has_vals and agg in ("sum", "mean"):
                psum_ = res[2]
            elif has_vals and agg == "min":
                pmin = res[2]
            elif has_vals and agg == "max":
                pmax = res[2]
        else:
            pkey = jax.ops.segment_min(jnp.where(valid, kenc, big),
                                       segid, num_segments=nseg)
            pcnt = jax.ops.segment_sum(valid.astype(jnp.int32), segid,
                                       num_segments=nseg)
            if has_vals:
                vacc = rest[0][0].astype(acc)
                psum_ = jax.ops.segment_sum(
                    jnp.where(valid, vacc, jnp.zeros((), acc)), segid,
                    num_segments=nseg)
                pmin = jax.ops.segment_min(
                    jnp.where(valid, vacc, _identity_for("min", acc)),
                    segid, num_segments=nseg)
                pmax = jax.ops.segment_max(
                    jnp.where(valid, vacc, _identity_for("max", acc)),
                    segid, num_segments=nseg)

        def assemble(layout, partial, ident, combine):
            """Re-home per-run partials into ``layout``'s windows: one
            masked all_to_all (the sort family's rebalance pattern) +
            a per-column monoid combine — a group split across shard
            boundaries merges its partials here.  Identity sends
            (empty segment 0, empty shards) are absorbed exactly."""
            So, starts_c, sizes_c = _dest_geometry(layout)
            ogid = starts_c[:, None] + jnp.arange(So)[None, :]
            slot_ok = jnp.arange(So)[None, :] < sizes_c[:, None]
            idx = ogid - (gid_off - 1)
            have = slot_ok & (idx >= 0) & (idx <= m)
            send = jnp.where(have,
                             jnp.take(partial,
                                      jnp.clip(idx, 0, nseg - 1)),
                             ident)
            recv = lax.all_to_all(send, axis, 0, 0)  # row s = from s
            return combine(recv, axis=0)             # (So,) my slots

        def live_for(layout):
            So, starts_c, _ = _dest_geometry(layout)
            return (starts_c[r] + jnp.arange(So)) < ng

        akey = assemble(ok_layout, pkey, big, jnp.min)
        klive = live_for(ok_layout)
        # decode through the KEY dtype (the encoding's inverse is
        # dtype-directed), THEN cast to the out container's dtype —
        # decoding a float encoding as int would emit garbage keys
        keyvals = _decode(akey, kdtype).astype(ok_dtype)
        keyvals = jnp.where(klive, keyvals, jnp.zeros((), ok_dtype))
        okrow = _pack_out_row(keyvals, klive, ok_layout, r)
        if not has_ov:
            return okrow, ng
        acnt = assemble(ov_layout, pcnt, jnp.zeros((), jnp.int32),
                        jnp.sum)
        if agg == "count":
            av = acnt
        elif agg == "min":
            av = assemble(ov_layout, pmin, _identity_for("min", acc),
                          jnp.min)
        elif agg == "max":
            av = assemble(ov_layout, pmax, _identity_for("max", acc),
                          jnp.max)
        else:  # sum / mean
            av = assemble(ov_layout, psum_, jnp.zeros((), acc),
                          jnp.sum)
            if agg == "mean":
                av = av / jnp.maximum(acnt, 1).astype(av.dtype)
        vlive = live_for(ov_layout)
        av = jnp.where(vlive, av.astype(ov_dtype),
                       jnp.zeros((), ov_dtype))
        return okrow, _pack_out_row(av, vlive, ov_layout, r), ng

    nin = 2 if has_vals else 1
    nout = 2 if has_ov else 1
    # check_vma=False: ``ng`` folds the same all_gather'ed count
    # vector identically on every shard, so the P() output IS
    # replicated — the static checker cannot prove it (the
    # _custom_reduce_program precedent)
    shm = jax.shard_map(body, mesh=mesh,
                        in_specs=(P(axis, None),) * nin,
                        out_specs=(P(axis, None),) * nout + (P(),),
                        check_vma=False)
    prog = jax.jit(shm)
    _prog_cache[key] = prog
    return prog


def _check_groupby(keys, values, out_keys, out_values):
    """The FULL groupby argument validation — run at the call site
    (deferred regions included, §17.5) AND again by the eager body at
    flush (replayed thunks re-resolve)."""
    kc = _in_chain(keys, "groupby_aggregate")
    vc = _in_chain(values, "groupby_aggregate") \
        if values is not None else None
    okc = _whole_out(out_keys, "groupby_aggregate")
    ovc = _whole_out(out_values, "groupby_aggregate") \
        if out_values is not None else None
    if vc is not None and vc.n != kc.n:
        raise ValueError(
            f"groupby_aggregate: keys and values must have equal "
            f"length ({kc.n} != {vc.n})")
    if ovc is not None and ovc.n != okc.n:
        # unequal capacities would let the smaller side silently drop
        # rows the returned count claims exist (the join contract)
        raise ValueError(
            f"groupby_aggregate: out_keys and out_values must share "
            f"one capacity ({okc.n} != {ovc.n})")
    rt = kc.cont.runtime
    for oc, nm in ((okc, "out_keys"), (ovc, "out_values")):
        if oc is not None and oc.cont.runtime.mesh != rt.mesh:
            raise TypeError(
                f"groupby_aggregate: {nm} must live on the keys' mesh")
    return kc, vc, okc, ovc


def _groupby_sorted(rt, sid, sk, sv, n, ok_cont, ov_cont, agg) -> int:
    """The aggregate half of a groupby, over the ALREADY-SORTED key
    (and value) scratch — shared by the caller-capacity and the §21.4
    auto-capacity paths (sort once, probe, allocate, aggregate).
    Capacity enforcement stays with the caller."""
    t0 = _obs.now()
    prog = _groupby_program(
        rt.mesh, rt.axis, sk.layout, sk.dtype,
        sv.layout if sv is not None else None,
        sv.dtype if sv is not None else None,
        ok_cont.layout, ok_cont.dtype,
        ov_cont.layout if ov_cont is not None else None,
        ov_cont.dtype if ov_cont is not None else None,
        agg, n)
    args = [sk._data] + ([sv._data] if sv is not None else [])
    outs = prog(*args)
    if ov_cont is not None:
        ok_cont._data, ov_cont._data, ngd = outs
    else:
        ok_cont._data, ngd = outs
    ng = int(ngd)
    _obs.complete("relational.phase", t0, cat="relational",
                  parent=sid, phase="aggregate", groups=ng)
    return ng


def _groupby_eager(keys, values, out_keys, out_values, agg) -> int:
    kc, vc, okc, ovc = _check_groupby(keys, values, out_keys,
                                      out_values)
    rt = kc.cont.runtime
    what = "unique" if ovc is None else f"groupby[{agg}]"
    sid = _obs.begin("relational.groupby", cat="relational", agg=agg,
                     n=kc.n)
    ng = -1
    try:
        sk, sv, n = _sorted_scratch(kc, vc, sid=sid)
        ng = _groupby_sorted(rt, sid, sk, sv, n, okc.cont,
                             ovc.cont if ovc is not None else None,
                             agg)
        if ng > okc.n:
            _raise_capacity(what, ng, okc.n)
        return ng
    finally:
        _obs.end(sid, groups=ng)


def groupby_aggregate(keys, values, out_keys, out_values,
                      agg: str = "sum"):
    """Distributed group-by: aggregate ``values`` per distinct key.

    Non-mutating in ``keys``/``values``.  The distinct keys land in
    ``out_keys[0:ngroups]`` in SORTED order with the aggregate at the
    matching ``out_values`` position (both whole distributed_vectors —
    the capacity; positions ``>= ngroups`` are zero); returns
    ``ngroups`` (a lazy :class:`DeferredCount` inside
    ``dr_tpu.deferred()``, where the op records ordered-opaque).
    ``agg`` is one of ``sum`` / ``min`` / ``max`` / ``count`` /
    ``mean`` (``count`` accepts ``values=None``).  A result larger
    than the capacity raises a classified ``ProgramError`` after the
    program ran (the first ``len(out_keys)`` groups are valid)."""
    if agg not in AGGS:
        raise ValueError(f"groupby_aggregate: unknown agg {agg!r} "
                         f"(known: {', '.join(AGGS)})")
    if values is None and agg != "count":
        raise ValueError(
            f"groupby_aggregate: agg {agg!r} needs values "
            "(only 'count' accepts values=None)")
    # validate NOW — API misuse must raise at the call site whether or
    # not a plan is recording — then defer the dispatch when one is
    # (out_values=None is only the internal unique form)
    kc, vc, okc, ovc = _check_groupby(keys, values, out_keys,
                                      out_values)
    p = _plan_active()
    if p is not None:
        box: list = []
        inputs = {"keys": keys}
        if values is not None:
            inputs["values"] = values
        meta = _opaque_meta(
            "groupby", inputs,
            (okc.cont,) + ((ovc.cont,) if ovc is not None else ()))
        reads, writes = _meta_footprint(meta)
        p.record_opaque(
            "groupby_aggregate",
            lambda m=meta, ok=out_keys, ov=out_values, a=agg:
            box.append(_groupby_eager(m["inputs"]["keys"],
                                      m["inputs"].get("values"),
                                      ok, ov, a)),
            reads=reads, writes=writes, meta=meta)
        return DeferredCount(p, box)
    return _groupby_eager(keys, values, out_keys, out_values, agg)


def unique(r, out):
    """Sorted distinct values of ``r`` into ``out[0:count]`` (a whole
    distributed_vector; positions ``>= count`` are zero).  Returns the
    distinct count (lazy :class:`DeferredCount` in deferred regions).
    Keys-only ``groupby_aggregate`` machinery — same sort backbone,
    same capacity contract."""
    _in_chain(r, "unique")
    okc = _whole_out(out, "unique")
    p = _plan_active()
    if p is not None:
        box: list = []
        meta = _opaque_meta("unique", {"r": r}, (okc.cont,))
        reads, writes = _meta_footprint(meta)
        p.record_opaque(
            "unique",
            lambda m=meta, ok=out:
            box.append(_groupby_eager(m["inputs"]["r"], None, ok,
                                      None, "count")),
            reads=reads, writes=writes, meta=meta)
        return DeferredCount(p, box)
    return _groupby_eager(r, None, out, None, "count")


# ---------------------------------------------------------------------------
# join
# ---------------------------------------------------------------------------

def _join_program(mesh, axis, llayout, lkdtype, lvdtype, rlayout,
                  rkdtype, rvdtype, ok_layout, ok_dtype, ol_layout,
                  ol_dtype, or_layout, or_dtype, nl, nr, left_outer,
                  right_outer=False):
    """Sorted-merge join program over the SORTED scratch sides.  Each
    shard all_gathers the sorted (key, value) channels (broadcast
    sorted-merge, memory O(nl + nr) per device — see the module
    docstring), counts matches per left row with two searchsorteds on
    the monotone encoding, prefix-sums the expansion offsets, and
    materializes exactly its own window of the expanded rows per OUT
    distribution.  ``right_outer`` (the ``how="outer"`` union,
    docs/SPEC.md §17.1) adds the UNMATCHED right rows as a second
    emitter stream: a 3-key sort of the combined (key, source,
    position) emitter list interleaves them into the key order — a
    key present on both sides never has unmatched rows, so the
    (key, left position, right position) contract extends to (key,
    source, position) without ambiguity."""
    key = ("reljoin", pinned_id(mesh), axis, llayout, str(lkdtype),
           str(lvdtype), rlayout, str(rkdtype), str(rvdtype),
           ok_layout, str(ok_dtype), ol_layout, str(ol_dtype),
           or_layout, str(or_dtype), int(nl), int(nr),
           bool(left_outer), bool(right_outer),
           bool(jax.config.jax_enable_x64))
    prog = _prog_cache.get(key)
    if prog is not None:
        return prog

    p, Sl, *_ = working_geometry(llayout)
    _, Sr, *_ = working_geometry(rlayout)
    NL, NR = p * Sl, p * Sr

    def body(lkb, lvb, rkb, rvb, fillv):
        r = lax.axis_index(axis)
        LK = lax.all_gather(lkb[0], axis).reshape(-1)   # (NL,)
        LV = lax.all_gather(lvb[0], axis).reshape(-1)
        RK = lax.all_gather(rkb[0], axis).reshape(-1)   # (NR,)
        RV = lax.all_gather(rvb[0], axis).reshape(-1)
        kl, bigl = _encode(LK)
        kr, bigr = _encode(RK)
        lvalid = jnp.arange(NL) < nl
        rvalid = jnp.arange(NR) < nr
        kl = jnp.where(lvalid, kl, bigl)
        kr = jnp.where(rvalid, kr, bigr)
        # match counts per left row: two searchsorteds on the monotone
        # encoding.  Real rows occupy positions [0, nr) of the sorted
        # channel, pads [nr, NR) — clamping the window to nr keeps an
        # INTEGER key equal to the pad sentinel (iinfo.max — the
        # encoding cannot put pads strictly after it) from counting
        # the pad rows as matches (round-16 fix; float encodings order
        # pads strictly last and are unaffected)
        lo = jnp.minimum(jnp.searchsorted(kr, kl, side="left"), nr)
        hi = jnp.minimum(jnp.searchsorted(kr, kl, side="right"), nr)
        cnt = jnp.where(lvalid, (hi - lo).astype(jnp.int32), 0)
        if left_outer:
            rows = jnp.where(lvalid, jnp.maximum(cnt, 1), 0)
        else:
            rows = cnt

        if not right_outer:
            offs = jnp.cumsum(rows)                     # inclusive
            M = offs[NL - 1]

            def out_channel(layout, produce, dtype):
                """My window of the expanded rows under ``layout``:
                result row j expands left element i = first index
                whose inclusive offset exceeds j, at in-group
                position j - exclusive_offset(i)."""
                So, starts_c, _sizes = _dest_geometry(layout)
                j = starts_c[r] + jnp.arange(So)
                live = j < M
                i = jnp.clip(jnp.searchsorted(offs, j, side="right"),
                             0, NL - 1)
                base = jnp.take(offs, i) - jnp.take(rows, i)
                matched = jnp.take(cnt, i) > 0
                rpos = jnp.clip(jnp.take(lo, i) + (j - base), 0,
                                NR - 1)
                vals = produce(i, rpos, matched)
                vals = jnp.where(live, vals.astype(dtype),
                                 jnp.zeros((), dtype))
                return _pack_out_row(vals, live, layout, r)

            okrow = out_channel(ok_layout,
                                lambda i, rp, mt: jnp.take(LK, i),
                                ok_dtype)
            olrow = out_channel(ol_layout,
                                lambda i, rp, mt: jnp.take(LV, i),
                                ol_dtype)
            orrow = out_channel(
                or_layout,
                lambda i, rp, mt: jnp.where(
                    mt, jnp.take(RV, rp).astype(or_dtype),
                    fillv.astype(or_dtype)),
                or_dtype)
            return okrow, olrow, orrow, M

        # ---- right_outer: the presence-flag UNION.  A right row is
        # unmatched when no left key equals it (clamped searchsorteds
        # on the sorted LEFT channel — the mirror of the count above).
        lo_l = jnp.minimum(jnp.searchsorted(kl, kr, side="left"), nl)
        hi_l = jnp.minimum(jnp.searchsorted(kl, kr, side="right"), nl)
        rrows = jnp.where(rvalid & (hi_l == lo_l), 1, 0) \
            .astype(jnp.int32)
        # combined emitter list, sorted by (key, source, position):
        # source 0 = a left row (emitting its match expansion, or the
        # left-outer fill row), source 1 = an unmatched right row.
        # Pads carry zero emit counts and sort harmlessly last.
        K = jnp.concatenate([kl, kr])
        SRC = jnp.concatenate([jnp.zeros(NL, jnp.int32),
                               jnp.ones(NR, jnp.int32)])
        PIDX = jnp.concatenate([jnp.arange(NL, dtype=jnp.int32),
                                jnp.arange(NR, dtype=jnp.int32)])
        EC = jnp.concatenate([rows.astype(jnp.int32), rrows])
        _ks, ssrc, spidx, sec = lax.sort((K, SRC, PIDX, EC),
                                         dimension=0, num_keys=3)
        coffs = jnp.cumsum(sec)
        NE = NL + NR
        M = coffs[NE - 1]

        def out_channel(layout, produce_left, produce_right, dtype):
            So, starts_c, _sizes = _dest_geometry(layout)
            j = starts_c[r] + jnp.arange(So)
            live = j < M
            e = jnp.clip(jnp.searchsorted(coffs, j, side="right"), 0,
                         NE - 1)
            src_e = jnp.take(ssrc, e)
            pi = jnp.take(spidx, e)
            q = j - (jnp.take(coffs, e) - jnp.take(sec, e))
            i = jnp.clip(pi, 0, NL - 1)          # left emitter fields
            rpos = jnp.clip(jnp.take(lo, i) + q, 0, NR - 1)
            matched = jnp.take(cnt, i) > 0
            lvals = produce_left(i, rpos, matched)
            rvals = produce_right(jnp.clip(pi, 0, NR - 1))
            vals = jnp.where(src_e == 0, lvals.astype(dtype),
                             rvals.astype(dtype))
            vals = jnp.where(live, vals, jnp.zeros((), dtype))
            return _pack_out_row(vals, live, layout, r)

        okrow = out_channel(ok_layout,
                            lambda i, rp, mt: jnp.take(LK, i),
                            lambda jr_: jnp.take(RK, jr_), ok_dtype)
        olrow = out_channel(
            ol_layout, lambda i, rp, mt: jnp.take(LV, i),
            lambda jr_: jnp.broadcast_to(fillv.astype(ol_dtype),
                                         jr_.shape), ol_dtype)
        orrow = out_channel(
            or_layout,
            lambda i, rp, mt: jnp.where(
                mt, jnp.take(RV, rp).astype(or_dtype),
                fillv.astype(or_dtype)),
            lambda jr_: jnp.take(RV, jr_), or_dtype)
        return okrow, olrow, orrow, M

    # check_vma=False: ``M`` derives from the same all_gather'ed
    # channels on every shard (replicated, unprovable statically —
    # the _custom_reduce_program precedent)
    shm = jax.shard_map(body, mesh=mesh,
                        in_specs=(P(axis, None),) * 4 + (P(),),
                        out_specs=(P(axis, None),) * 3 + (P(),),
                        check_vma=False)
    prog = jax.jit(shm)
    _prog_cache[key] = prog
    return prog


def _broadcast_max() -> int:
    """``DR_TPU_JOIN_BROADCAST_MAX`` (docs/SPEC.md §18.4): combined
    sorted-side row count up to which ``join`` keeps the broadcast
    sorted-merge (per-device memory O(nl + nr), one program, the
    small-side fast path).  Above it — with more than one shard and
    both sides non-empty — the merge re-homes on the bounded-memory
    repartition exchange.  ``0`` forces the repartition path (the
    fuzz/regression arms' switch).

    Route selection from measured data (§21.4, the ``joinroute``
    pass): when the env var is UNSET, a ``join.broadcast_max`` entry
    in the persisted tuning DB (``dr_tpu/tuning.py`` — written by the
    ``tune_tpu.py`` crossover sweep, matched on this mesh's
    backend/shape context) replaces the code default — sweep winners
    become data, not code edits.  An explicit env pin always wins
    (the operator's override), and a disabled pass or missing/corrupt
    DB falls back to the code default."""
    if env_raw("DR_TPU_JOIN_BROADCAST_MAX") is None:
        from ..plan import opt as _opt
        if _opt.enabled("joinroute"):
            from .. import tuning as _tuning
            v = _tuning.lookup("join", "broadcast_max")
            if v is not None:
                try:
                    return max(0, int(v))
                except (TypeError, ValueError):
                    pass
    return env_int("DR_TPU_JOIN_BROADCAST_MAX", 1 << 18, floor=0)


#: how the LAST eager join routed — bench/regression introspection
#: (docs/SPEC.md §18.4); read through :func:`last_join_route`
_LAST_JOIN_ROUTE: dict = {}


def last_join_route() -> dict:
    """Copy of the last eager join's routing record: ``impl``
    (``broadcast`` / ``partition``), side sizes, and the per-device
    gathered-channel rows — ``broadcast`` gathers both full sides
    (``nl + nr`` rows per device), ``partition`` holds only the local
    left block plus the ``rcap``-bounded right partition.  The
    acceptance regression asserts the partition program's gathered
    channel stays under the full side."""
    return dict(_LAST_JOIN_ROUTE)


def _set_join_route(**kw) -> None:
    _LAST_JOIN_ROUTE.clear()
    _LAST_JOIN_ROUTE.update(kw)


def _partition_bounds(axis, r, kl, krow, nvr, p):
    """Trace-time key-range partition plan, shared by the probe and
    merge programs (docs/SPEC.md §18.4): shard ``d``'s key range is
    ``[firsts[d], lasts[d]]`` — its own sorted left block's first and
    last REAL encoded keys (pads already masked to the big sentinel in
    ``kl``, so an empty left shard owns the empty range).  A right row
    belongs to every shard whose range covers its key (a boundary key
    spanning two left shards replicates to both); since both sides are
    sorted, each shard's right partition is the CONTIGUOUS global
    slice ``[starts[d], ends[d])``, found by two searchsorteds per
    shard plus one psum — O(p log S) per device, no data moves."""
    Sl = kl.shape[0]
    firsts = lax.all_gather(kl[0], axis)               # (p,)
    lasts = lax.all_gather(kl[Sl - 1], axis)
    # pads sort to the big sentinel, so a partially-valid shard's last
    # REAL key is the minimum of the row suffix... the row is sorted
    # ascending with pads big-masked at the tail: the last real key is
    # kl[nvalid-1]; all_gather of a dynamic index is fine trace-side
    below = jnp.minimum(
        jnp.searchsorted(krow, firsts, side="left"), nvr)
    thru = jnp.minimum(
        jnp.searchsorted(krow, lasts, side="right"), nvr)
    starts = lax.psum(below, axis)                     # (p,) global
    ends = lax.psum(thru, axis)
    return firsts, lasts, starts, ends


def _outer_partition_bounds(axis, kl, krow, nvr, p, nl, Sl):
    """The ``how="outer"`` repartition plan (docs/SPEC.md §17.1): the
    inner plan's per-shard right windows EXTENDED so every real right
    key has exactly ONE owning shard — the gap below shard ``d``'s
    left range belongs to ``d`` (exclusive of ``lasts[d-1]``: a
    boundary key spanning two left shards still replicates for
    matching, but only its LOWER shard owns its unmatched emission —
    vacuous, since a spanning key is matched), and everything above
    the last real left key belongs to the LAST NONEMPTY left shard.
    Empty left shards (always trailing — uniform ceil scratch) own
    nothing and emit nothing.  The windows stay CONTIGUOUS global
    slices, so the same ring scatter and rcap bound apply."""
    firsts = lax.all_gather(kl[0], axis)               # (p,)
    lasts = lax.all_gather(kl[Sl - 1], axis)
    # static left geometry: per-shard valid counts and the last
    # nonempty shard index (nl >= 1 on the partition route)
    nvls = np.minimum(np.maximum(nl - np.arange(p) * Sl, 0), Sl)
    last_ne = int(np.nonzero(nvls)[0].max())
    ne = jnp.asarray(nvls > 0)
    idx = jnp.arange(p)
    lastprev = jnp.concatenate([lasts[:1], lasts[:-1]])
    below_first = jnp.minimum(
        jnp.searchsorted(krow, firsts, side="left"), nvr)
    below_prev = jnp.minimum(
        jnp.searchsorted(krow, lastprev, side="right"), nvr)
    below = jnp.where(idx == 0, 0,
                      jnp.minimum(below_first, below_prev))
    thru = jnp.minimum(jnp.searchsorted(krow, lasts, side="right"),
                       nvr)
    thru = jnp.where(idx == last_ne, nvr, thru)
    below = jnp.where(ne, below, 0)
    thru = jnp.where(ne, thru, 0)
    starts = lax.psum(below, axis)                     # (p,) global
    ends = lax.psum(thru, axis)
    return firsts, lasts, starts, ends, last_ne, ne


def _mask_sorted_keys(kb, n, S, r):
    """Encode one sorted scratch key row and mask its pad tail to the
    big sentinel: ``(masked_enc, big, nvalid)``."""
    enc, big = _encode(kb[0])
    nvalid = jnp.clip(n - r * S, 0, S)
    return jnp.where(jnp.arange(S) < nvalid, enc, big), big, nvalid


def _last_real(kl, nvl, S):
    """The last REAL key of a masked sorted row (big when empty)."""
    return kl[jnp.clip(nvl - 1, 0, S - 1)]


def _join_partition_probe_program(mesh, axis, llayout, lkdtype,
                                  rlayout, rkdtype, nl, nr,
                                  outer=False):
    """The repartition planner's ONE device round trip: per-shard
    right-partition windows ``(starts, ends)`` under the left key
    ranges — the host reads ``max(ends - starts)`` and keys the merge
    program on the pow2-quantized partition capacity (bounded
    recompiles across key distributions).  ``outer`` probes the
    EXTENDED ownership windows (every real right key covered exactly
    once — :func:`_outer_partition_bounds`)."""
    key = ("reljoinplan", pinned_id(mesh), axis, llayout, str(lkdtype),
           rlayout, str(rkdtype), int(nl), int(nr), bool(outer),
           bool(jax.config.jax_enable_x64))
    prog = _prog_cache.get(key)
    if prog is not None:
        return prog
    p, Sl, *_ = working_geometry(llayout)
    _, Sr, *_ = working_geometry(rlayout)

    def body(lkb, rkb):
        r = lax.axis_index(axis)
        kl, _bigl, nvl = _mask_sorted_keys(lkb, nl, Sl, r)
        kl = kl.at[Sl - 1].set(_last_real(kl, nvl, Sl))
        krow, _bigr, nvr = _mask_sorted_keys(rkb, nr, Sr, r)
        if outer:
            _f, _l, starts, ends, _ln, _ne = _outer_partition_bounds(
                axis, kl, krow, nvr, p, nl, Sl)
        else:
            _f, _l, starts, ends = _partition_bounds(axis, r, kl, krow,
                                                     nvr, p)
        return starts, ends

    shm = jax.shard_map(body, mesh=mesh,
                        in_specs=(P(axis, None),) * 2,
                        out_specs=(P(), P()), check_vma=False)
    prog = jax.jit(shm)
    _prog_cache[key] = prog
    return prog


def _join_partition_program(mesh, axis, llayout, lkdtype, lvdtype,
                            rlayout, rkdtype, rvdtype, ok_layout,
                            ok_dtype, ol_layout, ol_dtype, or_layout,
                            or_dtype, nl, nr, left_outer, rcap,
                            right_outer=False):
    """Bounded-memory repartition sorted-merge (docs/SPEC.md §18.4,
    arXiv:2112.01075's recipe spent on the join's memory wall).  The
    broadcast program all_gathers BOTH sorted sides onto every device
    — O(nl + nr) per device, the wall at production row counts.  Here
    the LEFT side is already position-partitioned (the sorted scratch
    IS the uniform global order), each shard's key range is its own
    left block's [first, last] keys, and the RIGHT side's matching
    contiguous slice — at most ``rcap`` rows, probed beforehand —
    arrives over ``ring_pipeline`` (one right block in flight per hop,
    never an accumulated replica).  Each shard merges ONLY its own
    partition (two searchsorteds + local offsets), the global offsets
    come from one p-wide all_gather, and every out shard's window is
    assembled producer-side through one masked all_to_all per channel
    with bit-exact producer SELECTION (no arithmetic combine).  Row
    order, values, and the returned count are bit-identical to the
    broadcast program."""
    key = ("reljoinpart", pinned_id(mesh), axis, llayout, str(lkdtype),
           str(lvdtype), rlayout, str(rkdtype), str(rvdtype),
           ok_layout, str(ok_dtype), ol_layout, str(ol_dtype),
           or_layout, str(or_dtype), int(nl), int(nr),
           bool(left_outer), bool(right_outer), int(rcap),
           bool(jax.config.jax_enable_x64))
    prog = _prog_cache.get(key)
    if prog is not None:
        return prog

    p, Sl, *_ = working_geometry(llayout)
    _, Sr, *_ = working_geometry(rlayout)

    def body_outer(lkb, lvb, rkb, rvb, fillv):
        """The ``how="outer"`` repartition merge (§17.1): the inner
        body's machinery with (a) the EXTENDED ownership windows
        (:func:`_outer_partition_bounds` — every real right key lands
        in exactly one shard's partition for unmatched emission, on
        top of the match-range replication), (b) a raw right-key
        channel riding the ring (unmatched rows emit their key
        bit-exactly, no decode round trip), and (c) the combined
        (key, source, position) emitter sort of the broadcast outer
        body, partition-local — global order follows because shard
        windows tile the key space in order."""
        r = lax.axis_index(axis)
        lkraw = lkb[0]
        lv = lvb[0]
        klq, _bigl, nvl = _mask_sorted_keys(lkb, nl, Sl, r)
        # the RANGE row ends at the last REAL key (the §18.4 memory
        # bound); the QUERY/match base keeps the sorted masked row
        kl = klq.at[Sl - 1].set(_last_real(klq, nvl, Sl))
        krow, bigr, nvr = _mask_sorted_keys(rkb, nr, Sr, r)
        firsts, lasts, starts, ends, last_ne, ne = \
            _outer_partition_bounds(axis, kl, krow, nvr, p, nl, Sl)
        start_me = starts[r]
        end_me = ends[r]
        size_me = end_me - start_me

        rbk0 = jnp.full((rcap,), bigr, krow.dtype)
        rbraw0 = jnp.zeros((rcap,), rkb.dtype)
        rbv0 = jnp.zeros((rcap,), rvb.dtype)

        def scatter(t, carry, blocks):
            bk, braw, bv = blocks
            s = (r - t) % p
            g = s * Sr + jnp.arange(Sr)
            # POSITION-window membership: on sorted data the global
            # slice [start_me, end_me) IS the extended key predicate
            inw = (g < nr) & (g >= start_me) & (g < end_me)
            idx = jnp.where(inw, g - start_me, rcap)
            return (carry[0].at[idx].set(bk, mode="drop"),
                    carry[1].at[idx].set(braw, mode="drop"),
                    carry[2].at[idx].set(bv, mode="drop"))

        rbk, rbraw, rbv = ring_pipeline(
            axis, p, (rbk0, rbraw0, rbv0),
            (krow, rkb[0], rvb[0]), scatter)

        # --- left-row match counts on my partition (the inner body's
        # shape; searchsorted finds the matching window by KEY, so the
        # extra ownership rows at the partition's edges are inert)
        lvalid = jnp.arange(Sl) < nvl
        lo = jnp.minimum(jnp.searchsorted(rbk, kl, side="left"),
                         size_me)
        hi = jnp.minimum(jnp.searchsorted(rbk, kl, side="right"),
                         size_me)
        cnt = jnp.where(lvalid, (hi - lo).astype(jnp.int32), 0)
        rows = jnp.where(lvalid, jnp.maximum(cnt, 1), 0)  # left outer

        # --- unmatched OWNED right rows in my partition: matched-ness
        # is decidable locally (an owned key inside my left range is
        # present in MY block iff it is present at all; an owned key
        # outside it — gap below, tail above — matches nowhere)
        tpos = jnp.arange(rcap)
        in_part = tpos < size_me
        lo_l = jnp.minimum(jnp.searchsorted(klq, rbk, side="left"),
                           nvl)
        hi_l = jnp.minimum(jnp.searchsorted(klq, rbk, side="right"),
                           nvl)
        own_lo = jnp.take(lasts, jnp.maximum(r - 1, 0))
        owned = in_part & jnp.take(ne, r) \
            & ((r == 0) | (rbk > own_lo)) \
            & ((r == last_ne) | (rbk <= jnp.take(lasts, r)))
        rrows = jnp.where(owned & (hi_l == lo_l), 1, 0) \
            .astype(jnp.int32)

        # --- combined emitter sort, partition-local (the broadcast
        # outer body's (key, source, position) order)
        NE = Sl + rcap
        K = jnp.concatenate([klq, rbk])
        SRC = jnp.concatenate([jnp.zeros(Sl, jnp.int32),
                               jnp.ones(rcap, jnp.int32)])
        PIDX = jnp.concatenate([jnp.arange(Sl, dtype=jnp.int32),
                                jnp.arange(rcap, dtype=jnp.int32)])
        EC = jnp.concatenate([rows.astype(jnp.int32), rrows])
        _ks, ssrc, spidx, sec = lax.sort((K, SRC, PIDX, EC),
                                         dimension=0, num_keys=3)
        coffs = jnp.cumsum(sec)                       # local inclusive
        my_total = coffs[NE - 1]
        totals = lax.all_gather(my_total, axis)       # (p,)
        ctot = jnp.cumsum(totals)
        base_me = ctot[r] - my_total
        M = ctot[p - 1]

        def out_channel(layout, produce_left, produce_right, dtype):
            So, starts_c, _sizes = _dest_geometry(layout)
            j = starts_c[:, None] + jnp.arange(So)[None, :]
            mine = (j >= base_me) & (j < base_me + my_total)
            jl = j - base_me
            e = jnp.clip(jnp.searchsorted(coffs, jl, side="right"),
                         0, NE - 1)
            src_e = jnp.take(ssrc, e)
            pi = jnp.take(spidx, e)
            q = jl - (jnp.take(coffs, e) - jnp.take(sec, e))
            i = jnp.clip(pi, 0, Sl - 1)
            rpos = jnp.clip(jnp.take(lo, i) + q, 0, rcap - 1)
            matched = jnp.take(cnt, i) > 0
            lvals = produce_left(i, rpos, matched)
            rvals = produce_right(jnp.clip(pi, 0, rcap - 1))
            vals = jnp.where(src_e == 0, lvals.astype(dtype),
                             rvals.astype(dtype))
            send = jnp.where(mine, vals, jnp.zeros((), dtype))
            recv = lax.all_to_all(send, axis, 0, 0)   # row s = from s
            jt = starts_c[r] + jnp.arange(So)
            ps = jnp.clip(jnp.searchsorted(ctot, jt, side="right"),
                          0, p - 1)
            got = jnp.take_along_axis(recv, ps[None, :], axis=0)[0]
            live = jt < M
            got = jnp.where(live, got, jnp.zeros((), dtype))
            return _pack_out_row(got, live, layout, r)

        okrow = out_channel(ok_layout,
                            lambda i, rp, mt: jnp.take(lkraw, i),
                            lambda jr_: jnp.take(rbraw, jr_),
                            ok_dtype)
        olrow = out_channel(
            ol_layout, lambda i, rp, mt: jnp.take(lv, i),
            lambda jr_: jnp.broadcast_to(fillv.astype(ol_dtype),
                                         jr_.shape), ol_dtype)
        orrow = out_channel(
            or_layout,
            lambda i, rp, mt: jnp.where(
                mt, jnp.take(rbv, rp).astype(or_dtype),
                fillv.astype(or_dtype)),
            lambda jr_: jnp.take(rbv, jr_), or_dtype)
        return okrow, olrow, orrow, M

    def body(lkb, lvb, rkb, rvb, fillv):
        r = lax.axis_index(axis)
        lkraw = lkb[0]
        lv = lvb[0]
        kl, _bigl, nvl = _mask_sorted_keys(lkb, nl, Sl, r)
        # a partially-valid shard's range must end at its last REAL
        # key, not the pad sentinel (which would claim every larger
        # right key for this shard — correct but memory-unbounded)
        kl = kl.at[Sl - 1].set(_last_real(kl, nvl, Sl))
        krow, bigr, nvr = _mask_sorted_keys(rkb, nr, Sr, r)
        firsts, lasts, starts, ends = _partition_bounds(
            axis, r, kl, krow, nvr, p)
        start_me = starts[r]

        # --- repartition exchange: rotate the right (key, value)
        # blocks around the ring; each shard scatters the rows inside
        # ITS key range at their global-order offset into the
        # rcap-bounded partition buffers (positions are unique and
        # order-independent → bit-identical across ring schedules)
        rbk0 = jnp.full((rcap,), bigr, krow.dtype)
        rbv0 = jnp.zeros((rcap,), rvb.dtype)

        def scatter(t, carry, blocks):
            bk, bv = blocks
            s = (r - t) % p
            g = s * Sr + jnp.arange(Sr)
            inr = (g < nr) & (bk >= firsts[r]) & (bk <= lasts[r])
            idx = jnp.where(inr, g - start_me, rcap)
            return (carry[0].at[idx].set(bk, mode="drop"),
                    carry[1].at[idx].set(bv, mode="drop"))

        rbk, rbv = ring_pipeline(axis, p, (rbk0, rbv0),
                                 (krow, rvb[0]), scatter)

        # --- local merge on my partition (the broadcast body's
        # searchsorted/offsets shape, partition-local).  The clamp to
        # my REAL partition size keeps an integer key equal to the pad
        # sentinel from matching the buffer's big-sentinel tail (the
        # broadcast body's nr clamp, partition-local).
        size_me = ends[r] - start_me
        lvalid = jnp.arange(Sl) < nvl
        lo = jnp.minimum(jnp.searchsorted(rbk, kl, side="left"),
                         size_me)
        hi = jnp.minimum(jnp.searchsorted(rbk, kl, side="right"),
                         size_me)
        cnt = jnp.where(lvalid, (hi - lo).astype(jnp.int32), 0)
        if left_outer:
            rows = jnp.where(lvalid, jnp.maximum(cnt, 1), 0)
        else:
            rows = cnt
        offs = jnp.cumsum(rows)                       # local inclusive
        my_total = offs[Sl - 1]
        totals = lax.all_gather(my_total, axis)       # (p,)
        ctot = jnp.cumsum(totals)
        base_me = ctot[r] - my_total
        M = ctot[p - 1]

        def out_channel(layout, produce, dtype):
            """Producer-side window assembly: for every destination
            shard's out slot I produced, compute the row value from my
            local data into the masked all_to_all send buffer; the
            receiver SELECTS each slot's unique producer row (cumsum
            of totals names it) — a bit-exact move, no sum combine."""
            So, starts_c, _sizes = _dest_geometry(layout)
            j = starts_c[:, None] + jnp.arange(So)[None, :]
            mine = (j >= base_me) & (j < base_me + my_total)
            jl = j - base_me
            i = jnp.clip(jnp.searchsorted(offs, jl, side="right"),
                         0, Sl - 1)
            base_i = jnp.take(offs, i) - jnp.take(rows, i)
            matched = jnp.take(cnt, i) > 0
            rpos = jnp.clip(jnp.take(lo, i) + (jl - base_i), 0,
                            rcap - 1)
            vals = produce(i, rpos, matched)
            send = jnp.where(mine, vals.astype(dtype),
                             jnp.zeros((), dtype))
            recv = lax.all_to_all(send, axis, 0, 0)   # row s = from s
            jt = starts_c[r] + jnp.arange(So)
            ps = jnp.clip(jnp.searchsorted(ctot, jt, side="right"),
                          0, p - 1)
            got = jnp.take_along_axis(recv, ps[None, :], axis=0)[0]
            live = jt < M
            got = jnp.where(live, got, jnp.zeros((), dtype))
            return _pack_out_row(got, live, layout, r)

        okrow = out_channel(ok_layout,
                            lambda i, rp, mt: jnp.take(lkraw, i),
                            ok_dtype)
        olrow = out_channel(ol_layout,
                            lambda i, rp, mt: jnp.take(lv, i),
                            ol_dtype)
        orrow = out_channel(
            or_layout,
            lambda i, rp, mt: jnp.where(
                mt, jnp.take(rbv, rp).astype(or_dtype),
                fillv.astype(or_dtype)),
            or_dtype)
        return okrow, olrow, orrow, M

    # check_vma=False: ``M`` folds the same all_gather'ed totals
    # identically on every shard (the broadcast program's precedent)
    shm = jax.shard_map(body_outer if right_outer else body, mesh=mesh,
                        in_specs=(P(axis, None),) * 4 + (P(),),
                        out_specs=(P(axis, None),) * 3 + (P(),),
                        check_vma=False)
    prog = jax.jit(shm)
    _prog_cache[key] = prog
    return prog


def _check_join_sides(lk, lv, rk, rv):
    """Side-only join validation (shared by :func:`join` and the §21.4
    auto-capacity form, which has no caller outputs to check)."""
    lkc = _in_chain(lk, "join")
    lvc = _in_chain(lv, "join")
    rkc = _in_chain(rk, "join")
    rvc = _in_chain(rv, "join")
    if lkc.n != lvc.n or rkc.n != rvc.n:
        raise ValueError(
            f"join: keys and values must have equal length per side "
            f"({lkc.n} != {lvc.n} or {rkc.n} != {rvc.n})")
    if jnp.dtype(lkc.cont.dtype) != jnp.dtype(rkc.cont.dtype):
        raise TypeError(
            f"join: key dtypes must match ({lkc.cont.dtype} != "
            f"{rkc.cont.dtype})")
    if rkc.cont.runtime.mesh != lkc.cont.runtime.mesh:
        raise TypeError("join: right keys must live on the left keys' "
                        "mesh")
    return lkc, lvc, rkc, rvc


def _check_join(lk, lv, rk, rv, out_keys, out_lv, out_rv):
    """The FULL join argument validation — run at the call site
    (deferred regions included, §17.5) AND again by the eager body at
    flush.  Symmetric in the sides, so the right-join swap passes the
    same checks."""
    lkc, lvc, rkc, rvc = _check_join_sides(lk, lv, rk, rv)
    okc = _whole_out(out_keys, "join")
    olc = _whole_out(out_lv, "join")
    orc = _whole_out(out_rv, "join")
    if olc.n != okc.n or orc.n != okc.n:
        raise ValueError("join: the three output containers must "
                         "share one capacity")
    rt = lkc.cont.runtime
    for c, nm in ((rkc, "right keys"), (okc, "out_keys"),
                  (olc, "out_left"), (orc, "out_right")):
        if c.cont.runtime.mesh != rt.mesh:
            raise TypeError(f"join: {nm} must live on the left keys' "
                            "mesh")
    return lkc, lvc, rkc, rvc, okc, olc, orc


def _join_eager(lk, lv, rk, rv, out_keys, out_lv, out_rv, how,
                fill) -> int:
    if how == "right":
        # a right join IS the left join with the sides swapped: the
        # output keys follow the right side's sorted order and the
        # fill lands on the LEFT value column
        return _join_eager(rk, rv, lk, lv, out_keys, out_rv, out_lv,
                           "left", fill)
    lkc, lvc, rkc, rvc, okc, olc, orc = _check_join(
        lk, lv, rk, rv, out_keys, out_lv, out_rv)
    cap = okc.n
    rt = lkc.cont.runtime
    sid = _obs.begin("relational.join", cat="relational", how=how,
                     n_left=lkc.n, n_right=rkc.n)
    m = -1
    try:
        if (lkc.n == 0 and not (how == "outer" and rkc.n > 0)) \
                or (how == "inner" and rkc.n == 0):
            # no left rows (or inner against an empty right): zero
            # rows — zero the outputs so the tail contract holds.  An
            # OUTER join with an empty left but a nonempty right falls
            # through: the union program emits every right row filled
            from .elementwise import fill as _fill
            t0 = _obs.now()
            for oc in (out_keys, out_lv, out_rv):
                _fill(oc, 0)
            m = 0
            _obs.complete("relational.phase", t0, cat="relational",
                          parent=sid, phase="empty")
            return 0
        slk, slv, nl = _sorted_scratch(lkc, lvc, sid=sid,
                                       phase="sort_left")
        srk, srv, nr = _sorted_scratch(rkc, rvc, sid=sid,
                                       phase="sort_right")
        m = _merge_sorted(rt, sid, slk, slv, nl, srk, srv, nr,
                          okc.cont, olc.cont, orc.cont, how, fill)
        if m > cap:
            _raise_capacity(f"join[{how}]", m, cap)
        return m
    finally:
        _obs.end(sid, rows=m)


def _merge_sorted(rt, sid, slk, slv, nl, srk, srv, nr, ok_cont,
                  ol_cont, or_cont, how, fill) -> int:
    """The merge half of a join, over the ALREADY-SORTED scratch sides
    (the §21.4 capinfer refactor: the auto-capacity path sorts once,
    probes the count, allocates, and merges — no double sort).
    Routes broadcast vs repartition (docs/SPEC.md §18.4), runs the
    program, rebinds the out containers, and returns the row count —
    capacity enforcement stays with the caller (it knows the
    contract's wording)."""
    p_sh, Sl, *_ = working_geometry(slk.layout)
    _, Sr, *_ = working_geometry(srk.layout)
    # routing (docs/SPEC.md §18.4): small combined sides keep the
    # broadcast sorted-merge (one program, O(nl+nr) per device);
    # above the threshold the merge re-homes on the bounded-memory
    # repartition exchange — each device merges only its own left
    # block against the probed, rcap-bounded right partition
    left_outer = how in ("left", "outer")
    right_outer = how == "outer"
    use_partition = (p_sh > 1 and nl > 0 and nr > 0
                     and nl + nr > _broadcast_max())
    if use_partition:
        t0 = _obs.now()
        fire_ppermute(what="join.partition")
        probe = _join_partition_probe_program(
            rt.mesh, rt.axis, slk.layout, slk.dtype,
            srk.layout, srk.dtype, nl, nr, outer=right_outer)
        starts, ends = probe(slk._data, srk._data)
        part = np.asarray(ends) - np.asarray(starts)
        mx = max(int(part.max(initial=0)), 1)
        # pow2-quantized partition capacity: bounded recompiles
        # across key distributions, never beyond the full side
        rcap = min(1 << (mx - 1).bit_length(), p_sh * Sr)
        _obs.complete("relational.phase", t0, cat="relational",
                      parent=sid, phase="partition_plan",
                      rcap=rcap)
        t0 = _obs.now()
        prog = _join_partition_program(
            rt.mesh, rt.axis, slk.layout, slk.dtype, slv.dtype,
            srk.layout, srk.dtype, srv.dtype,
            ok_cont.layout, ok_cont.dtype,
            ol_cont.layout, ol_cont.dtype,
            or_cont.layout, or_cont.dtype,
            nl, nr, left_outer, rcap, right_outer=right_outer)
        _set_join_route(impl="partition", nl=nl, nr=nr,
                        nshards=p_sh, rcap=rcap,
                        gathered_rows_per_device=Sl + rcap)
    else:
        t0 = _obs.now()
        prog = _join_program(
            rt.mesh, rt.axis, slk.layout, slk.dtype, slv.dtype,
            srk.layout, srk.dtype, srv.dtype,
            ok_cont.layout, ok_cont.dtype,
            ol_cont.layout, ol_cont.dtype,
            or_cont.layout, or_cont.dtype,
            nl, nr, left_outer, right_outer=right_outer)
        _set_join_route(impl="broadcast", nl=nl, nr=nr,
                        nshards=p_sh,
                        gathered_rows_per_device=p_sh * (Sl + Sr))
    ok_cont._data, ol_cont._data, or_cont._data, md = prog(
        slk._data, slv._data, srk._data, srv._data,
        jnp.asarray(fill, or_cont.dtype))
    m = int(md)
    _obs.complete("relational.phase", t0, cat="relational",
                  parent=sid, phase="merge", rows=m,
                  route="partition" if use_partition
                  else "broadcast")
    return m


def join(left_keys, left_values, right_keys, right_values, out_keys,
         out_left, out_right, *, how: str = "inner", fill=0):
    """Distributed sort-merge join (docs/SPEC.md §17.1).

    Matches ``left_keys`` against ``right_keys`` (same key dtype, the
    sort family's total-order equality) and writes one row per match
    pair — ``out_keys[i]`` the key, ``out_left[i]`` /
    ``out_right[i]`` the two sides' values — ordered by (key, left
    position, right position).  Duplicate keys expand many-to-many,
    exactly pandas ``merge`` row multiplicity.  ``how="left"`` /
    ``"right"`` additionally emit every unmatched row of that side
    with ``fill`` on the missing value column (presence flags);
    ``how="outer"`` emits the UNION — unmatched rows of BOTH sides,
    ``fill`` on whichever value column is absent, interleaved in key
    order (a key present on both sides has no unmatched rows, so the
    ordering contract stays total); ``how="inner"`` is the default.
    Non-mutating in the inputs; the
    three whole-container outputs share one capacity, positions
    ``>= count`` are zero.  Returns the row count (lazy
    :class:`DeferredCount` inside ``dr_tpu.deferred()``, where the op
    records ordered-opaque); a result beyond the capacity raises a
    classified ``ProgramError`` after the program ran."""
    if how not in JOIN_HOWS:
        raise ValueError(f"join: unknown how {how!r} "
                         f"(known: {', '.join(JOIN_HOWS)})")
    # validate NOW — API misuse must raise at the call site whether or
    # not a plan is recording (§17.5)
    _lkc, _lvc, _rkc, _rvc, okc, olc, orc = _check_join(
        left_keys, left_values, right_keys, right_values,
        out_keys, out_left, out_right)
    p = _plan_active()
    if p is not None:
        box: list = []
        meta = _opaque_meta(
            "join",
            {"lk": left_keys, "lv": left_values,
             "rk": right_keys, "rv": right_values},
            (okc.cont, olc.cont, orc.cont))
        reads, writes = _meta_footprint(meta)
        p.record_opaque(
            "join",
            lambda m=meta, ok=out_keys, ol=out_left, orr=out_right,
            h=how, f=fill:
            box.append(_join_eager(m["inputs"]["lk"],
                                   m["inputs"]["lv"],
                                   m["inputs"]["rk"],
                                   m["inputs"]["rv"],
                                   ok, ol, orr, h, f)),
            reads=reads, writes=writes, meta=meta)
        return DeferredCount(p, box)
    return _join_eager(left_keys, left_values, right_keys,
                       right_values, out_keys, out_left, out_right,
                       how, fill)


# ---------------------------------------------------------------------------
# capacity inference (docs/SPEC.md §21.4 — the capinfer pass)
# ---------------------------------------------------------------------------

def _join_count_program(mesh, axis, llayout, lkdtype, rlayout,
                        rkdtype, nl, nr, left_outer, right_outer):
    """Count-only join probe over the SORTED key channels: the
    broadcast merge's row arithmetic with no value gathers and no
    output assembly — one small program whose scalar is the exact
    result row count.  The auto-capacity path runs it on the scratch
    it already sorted, so inference costs one probe dispatch, not a
    second sort."""
    key = ("reljoincnt", pinned_id(mesh), axis, llayout, str(lkdtype),
           rlayout, str(rkdtype), int(nl), int(nr), bool(left_outer),
           bool(right_outer), bool(jax.config.jax_enable_x64))
    prog = _prog_cache.get(key)
    if prog is not None:
        return prog
    p, Sl, *_ = working_geometry(llayout)
    _, Sr, *_ = working_geometry(rlayout)
    NL, NR = p * Sl, p * Sr

    def body(lkb, rkb):
        LK = lax.all_gather(lkb[0], axis).reshape(-1)    # (NL,)
        RK = lax.all_gather(rkb[0], axis).reshape(-1)    # (NR,)
        kl, bigl = _encode(LK)
        kr, bigr = _encode(RK)
        lvalid = jnp.arange(NL) < nl
        rvalid = jnp.arange(NR) < nr
        kl = jnp.where(lvalid, kl, bigl)
        kr = jnp.where(rvalid, kr, bigr)
        # the broadcast body's count shape, nr-clamped (§18.4's
        # integer-pad-sentinel rule)
        lo = jnp.minimum(jnp.searchsorted(kr, kl, side="left"), nr)
        hi = jnp.minimum(jnp.searchsorted(kr, kl, side="right"), nr)
        cnt = jnp.where(lvalid, (hi - lo).astype(jnp.int32), 0)
        if left_outer:
            rows = jnp.where(lvalid, jnp.maximum(cnt, 1), 0)
        else:
            rows = cnt
        M = jnp.sum(rows)
        if right_outer:
            lo_l = jnp.minimum(jnp.searchsorted(kl, kr, side="left"),
                               nl)
            hi_l = jnp.minimum(jnp.searchsorted(kl, kr, side="right"),
                               nl)
            M = M + jnp.sum(jnp.where(rvalid & (hi_l == lo_l), 1, 0)
                            .astype(jnp.int32))
        return M

    # check_vma=False: M folds the same gathered channels identically
    # on every shard (the _join_program precedent)
    shm = jax.shard_map(body, mesh=mesh,
                        in_specs=(P(axis, None),) * 2,
                        out_specs=P(), check_vma=False)
    prog = jax.jit(shm)
    _prog_cache[key] = prog
    return prog


def _group_count_program(mesh, axis, klayout, kdtype, nreal):
    """Count-only groupby probe over ONE sorted key scratch: the
    boundary-flag count of :func:`_groupby_program` with no segmented
    reduce and no output assembly — the exact distinct-group count."""
    key = ("relgbcnt", pinned_id(mesh), axis, klayout, str(kdtype),
           int(nreal), bool(jax.config.jax_enable_x64))
    prog = _prog_cache.get(key)
    if prog is not None:
        return prog
    _p, S, cap, prev, nxt, *_rest = working_geometry(klayout)
    assert prev == 0 and nxt == 0 and cap == S, \
        "group-count probe runs on the fresh uniform scratch"

    def body(kblk):
        r = lax.axis_index(axis)
        kenc, big = _encode(kblk[0])
        nvalid = jnp.clip(nreal - r * S, 0, S)
        valid = jnp.arange(S) < nvalid
        kenc = jnp.where(valid, kenc, big)
        lasts = lax.all_gather(kenc[S - 1], axis)
        prevk = lasts[jnp.maximum(r - 1, 0)]
        first = jnp.where(r == 0, valid[0],
                          valid[0] & (kenc[0] != prevk))
        flags = jnp.concatenate(
            [first[None].astype(jnp.int32),
             (valid[1:] & (kenc[1:] != kenc[:-1])).astype(jnp.int32)])
        return lax.psum(jnp.sum(flags), axis)

    shm = jax.shard_map(body, mesh=mesh, in_specs=(P(axis, None),),
                        out_specs=P(), check_vma=False)
    prog = jax.jit(shm)
    _prog_cache[key] = prog
    return prog


def _pow2_cap(n: int) -> int:
    """Pow2-quantized output capacity (the rcap discipline): bounded
    program recompiles across nearby result sizes."""
    n = max(int(n), 1)
    return 1 << (n - 1).bit_length()


def _capinfer_enabled() -> bool:
    from ..plan import opt as _opt
    return _opt.enabled("capinfer")


def _cap_hint(kind: str, base: int):
    """Capacity hint for an auto-sized relational output: the
    measured rows/input ratio from the tuning DB (plus the in-process
    session overlay the last auto run noted), widened by a 1.25
    safety margin.  None = no hint — the caller probes exact."""
    if base <= 0:
        return None
    from .. import tuning as _tuning
    r = _tuning.lookup("relational", "cap_ratio_" + kind)
    try:
        r = float(r) if r is not None else None
    except (TypeError, ValueError):
        r = None
    if r is None:
        return None
    return max(1, int(np.ceil(r * base * 1.25)))


def _note_ratio(kind: str, base: int, m: int) -> None:
    """Session-note the observed rows/input ratio so the NEXT auto op
    of this shape skips the probe; ``tune_tpu.py relational`` persists
    the same ratios into the DB for future processes."""
    if base > 0:
        from .. import tuning as _tuning
        _tuning.note("relational", "cap_ratio_" + kind,
                     max(m, 1) / base)


class AutoResult:
    """Lazily-resolved result of an auto-capacity relational op
    (§21.4): the output containers are allocated at execution from the
    inferred capacity, so inside ``dr_tpu.deferred()`` they exist only
    after the flush.  Resolution (``count`` / ``containers`` /
    ``arrays()`` / ``int()``) flushes the owning plan if still
    pending; a result whose flush was discarded raises instead of
    lying (the DeferredCount contract)."""

    __slots__ = ("_plan", "_box")

    def __init__(self, plan, box):
        self._plan = plan
        self._box = box

    def _resolve(self):
        if not self._box and self._plan is not None:
            self._plan.flush("relational auto result read")
        if not self._box:
            raise RuntimeError(
                "auto relational result was discarded before it "
                "resolved (faulted flush or abandoned region)")
        return self._box[-1]

    @property
    def count(self) -> int:
        return int(self._resolve()[1])

    @property
    def containers(self) -> tuple:
        """The allocated output containers (capacity-padded)."""
        return self._resolve()[0]

    def arrays(self):
        """Materialized outputs TRIMMED to the real row count."""
        conts, m = self._resolve()
        from .elementwise import to_numpy as _tonp
        return [_tonp(c)[:m] for c in conts]

    def __int__(self):
        return self.count

    def __repr__(self):
        state = (f"count={self._box[-1][1]}" if self._box
                 else "pending")
        return f"AutoResult({state})"


def _fresh_outs(rt, dtypes, cap):
    from ..containers.distributed_vector import distributed_vector
    return tuple(distributed_vector(cap, dtype=dt, runtime=rt)
                 for dt in dtypes)


def _join_auto_eager(lk, lv, rk, rv, how, fill):
    if how == "right":
        conts, m = _join_auto_eager(rk, rv, lk, lv, "left", fill)
        ok, orr, ol = conts  # swap the value channels back
        return (ok, ol, orr), m
    lkc, lvc, rkc, rvc = _check_join_sides(lk, lv, rk, rv)
    rt = lkc.cont.runtime
    dtypes = (lkc.cont.dtype, lvc.cont.dtype, rvc.cont.dtype)
    sid = _obs.begin("relational.join", cat="relational", how=how,
                     auto=True, n_left=lkc.n, n_right=rkc.n)
    m = -1
    try:
        if (lkc.n == 0 and not (how == "outer" and rkc.n > 0)) \
                or (how == "inner" and rkc.n == 0):
            from .elementwise import fill as _fill
            conts = _fresh_outs(rt, dtypes, 1)
            for c in conts:
                _fill(c, 0)
            m = 0
            return conts, 0
        slk, slv, nl = _sorted_scratch(lkc, lvc, sid=sid,
                                       phase="sort_left")
        srk, srv, nr = _sorted_scratch(rkc, rvc, sid=sid,
                                       phase="sort_right")
        base = nl + nr
        exact = None
        if _capinfer_enabled():
            cap = _cap_hint("join_" + how, base)
            if cap is None:
                t0 = _obs.now()
                prog = _join_count_program(
                    rt.mesh, rt.axis, slk.layout, slk.dtype,
                    srk.layout, srk.dtype, nl, nr,
                    how in ("left", "outer"), how == "outer")
                exact = int(prog(slk._data, srk._data))
                cap = exact
                _obs.complete("relational.phase", t0,
                              cat="relational", parent=sid,
                              phase="cap_probe", rows=exact)
        else:
            # the pass is off: the pre-§21 caller-guess shape
            cap = 4 * base
        cap = _pow2_cap(cap)
        conts = _fresh_outs(rt, dtypes, cap)
        m = _merge_sorted(rt, sid, slk, slv, nl, srk, srv, nr,
                          *conts, how, fill)
        if m > cap:
            # a hinted (or guessed) capacity undershot: re-home on the
            # exact count and re-merge — never a classified overflow
            # on the auto path (the §21.4 contract)
            cap = _pow2_cap(m)
            conts = _fresh_outs(rt, dtypes, cap)
            m2 = _merge_sorted(rt, sid, slk, slv, nl, srk, srv, nr,
                               *conts, how, fill)
            assert m2 == m, "join count drifted between merges"
        _note_ratio("join_" + how, base, m)
        return conts, m
    finally:
        _obs.end(sid, rows=m)


def _groupby_auto_eager(keys, values, agg, keys_only=False):
    kc = _in_chain(keys, "groupby_aggregate")
    vc = _in_chain(values, "groupby_aggregate") \
        if values is not None else None
    if vc is not None and vc.n != kc.n:
        raise ValueError(
            f"groupby_aggregate: keys and values must have equal "
            f"length ({kc.n} != {vc.n})")
    rt = kc.cont.runtime
    if vc is None:
        vdt = jnp.int32                       # count channel
    elif agg == "mean":
        vdt = _acc_dtype(vc.cont.dtype)       # keeps the fold exact
    else:
        vdt = vc.cont.dtype
    sid = _obs.begin("relational.groupby", cat="relational", agg=agg,
                     auto=True, n=kc.n)
    ng = -1
    try:
        sk, sv, n = _sorted_scratch(kc, vc, sid=sid)
        if _capinfer_enabled():
            cap = _cap_hint("groupby", n)
            if cap is None:
                t0 = _obs.now()
                prog = _group_count_program(rt.mesh, rt.axis,
                                            sk.layout, sk.dtype, n)
                cap = int(prog(sk._data))
                _obs.complete("relational.phase", t0,
                              cat="relational", parent=sid,
                              phase="cap_probe", groups=cap)
        else:
            cap = n                           # the worst-case guess
        cap = _pow2_cap(min(cap, max(n, 1)))
        while True:
            ok = _fresh_outs(rt, (kc.cont.dtype,), cap)[0]
            ov = None if keys_only \
                else _fresh_outs(rt, (vdt,), cap)[0]
            ng = _groupby_sorted(rt, sid, sk, sv, n, ok, ov, agg)
            if ng <= cap:
                break
            cap = _pow2_cap(ng)  # hint undershot: exact retry
        _note_ratio("groupby", n, ng)
        outs = (ok,) if ov is None else (ok, ov)
        return outs, ng
    finally:
        _obs.end(sid, groups=ng)


def join_auto(left_keys, left_values, right_keys, right_values, *,
              how: str = "inner", fill=0):
    """:func:`join` with INFERRED output capacity (docs/SPEC.md
    §21.4, the ``capinfer`` pass): the outputs are allocated from a
    key-cardinality probe on the already-sorted scratch (or a
    tuning-DB ratio hint that skips the probe; an undershot hint
    re-merges at the exact count — never a classified overflow).
    Returns an :class:`AutoResult`; with the pass disabled the
    capacity falls back to the pre-§21 ``4 * (nl + nr)`` guess."""
    if how not in JOIN_HOWS:
        raise ValueError(f"join: unknown how {how!r} "
                         f"(known: {', '.join(JOIN_HOWS)})")
    _check_join_sides(left_keys, left_values, right_keys,
                      right_values)
    p = _plan_active()
    if p is not None:
        box: list = []
        meta = _opaque_meta(
            "join",
            {"lk": left_keys, "lv": left_values,
             "rk": right_keys, "rv": right_values}, ())
        reads, _w = _meta_footprint(meta)
        p.record_opaque(
            "join(auto)",
            lambda m=meta, h=how, f=fill:
            box.append(_join_auto_eager(m["inputs"]["lk"],
                                        m["inputs"]["lv"],
                                        m["inputs"]["rk"],
                                        m["inputs"]["rv"], h, f)),
            reads=reads, writes=(), meta=meta)
        return AutoResult(p, box)
    box = [_join_auto_eager(left_keys, left_values, right_keys,
                            right_values, how, fill)]
    return AutoResult(None, box)


def groupby_auto(keys, values, agg: str = "sum"):
    """:func:`groupby_aggregate` with INFERRED output capacity
    (§21.4): out containers sized from the distinct-key count probe
    (or the tuning-DB ratio hint).  Returns an :class:`AutoResult`
    over ``(out_keys, out_values)``."""
    if agg not in AGGS:
        raise ValueError(f"groupby_aggregate: unknown agg {agg!r} "
                         f"(known: {', '.join(AGGS)})")
    if values is None and agg != "count":
        raise ValueError(
            f"groupby_aggregate: agg {agg!r} needs values "
            "(only 'count' accepts values=None)")
    kc = _in_chain(keys, "groupby_aggregate")
    if values is not None:
        # §17.5 discipline: API misuse raises at the CALL SITE, not
        # inside the deferred flush (where it would classify away the
        # whole batch and point the traceback at the wrong place)
        vc = _in_chain(values, "groupby_aggregate")
        if vc.n != kc.n:
            raise ValueError(
                f"groupby_aggregate: keys and values must have equal "
                f"length ({kc.n} != {vc.n})")
    p = _plan_active()
    if p is not None:
        box: list = []
        inputs = {"keys": keys}
        if values is not None:
            inputs["values"] = values
        meta = _opaque_meta("groupby", inputs, ())
        reads, _w = _meta_footprint(meta)
        p.record_opaque(
            "groupby(auto)",
            lambda m=meta, a=agg:
            box.append(_groupby_auto_eager(m["inputs"]["keys"],
                                           m["inputs"].get("values"),
                                           a)),
            reads=reads, writes=(), meta=meta)
        return AutoResult(p, box)
    box = [_groupby_auto_eager(keys, values, agg)]
    return AutoResult(None, box)


def unique_auto(r):
    """:func:`unique` with INFERRED output capacity (§21.4).  Returns
    an :class:`AutoResult` over ``(out,)``."""
    _in_chain(r, "unique")
    p = _plan_active()
    if p is not None:
        box: list = []
        meta = _opaque_meta("unique", {"r": r}, ())
        reads, _w = _meta_footprint(meta)
        p.record_opaque(
            "unique(auto)",
            lambda m=meta:
            box.append(_groupby_auto_eager(m["inputs"]["r"], None,
                                           "count", keys_only=True)),
            reads=reads, writes=(), meta=meta)
        return AutoResult(p, box)
    box = [_groupby_auto_eager(r, None, "count", keys_only=True)]
    return AutoResult(None, box)


# ---------------------------------------------------------------------------
# histogram
# ---------------------------------------------------------------------------

def _hist_kernel_decision(mesh, in_layout, bins):
    """The ``hist`` kernel-arm decision (docs/SPEC.md §22) for one
    histogram program shape — shared by the eager program and the
    deferred-plan record so both key their caches on it."""
    _p, _S, cap, prev, nxt, _n, _st, _sz = working_geometry(in_layout)
    return kernels.use_kernel(
        "hist", kernels.mesh_platform(mesh),
        eligible=hist_pallas.eligible(prev + cap + nxt, int(bins)))


def _histogram_body(axis, in_layout, off, n, ops, nsc, out_layout,
                    bins, out_dtype, kern=kernels.NO_KERNEL):
    """The histogram shard body — shared verbatim between the eager
    program below and the deferred-plan fusible emit
    (``plan.record_histogram``).  ``scalars`` = the view chain's
    BoundOp values then (lo, hi), all TRACED (a streamed range reuses
    one program).  ``kern`` routes the bucketed scatter-add through
    the ``hist`` Pallas arm (exact: integer sums)."""
    So, starts_c, _sizes = _dest_geometry(out_layout)

    def body(blk, *scalars):
        r = lax.axis_index(axis)
        sc_iter = iter(scalars[:nsc])
        lo, hi = scalars[nsc], scalars[nsc + 1]
        x = _apply_chain_ops(blk[0], ops, sc_iter)
        mask, _gid = owned_window_mask(in_layout, off, n)
        pt = jnp.promote_types(x.dtype, jnp.float32)
        xv = x.astype(pt)
        lov = lo.astype(pt)
        hiv = hi.astype(pt)
        # bucket = floor((x - lo) * bins / (hi - lo)), right edge
        # INCLUSIVE in the last bucket (numpy's rule); out-of-range
        # values drop out of the in-range mask
        b = jnp.floor((xv - lov) * bins / (hiv - lov)) \
            .astype(jnp.int32)
        inr = mask[r] & (xv >= lov) & (xv <= hiv)
        bc = jnp.clip(jnp.where(inr, b, 0), 0, bins - 1)
        cnt = jnp.where(inr, 1, 0).astype(jnp.int32)
        if kern.use:
            local = hist_pallas.bincount(bc.astype(jnp.int32), cnt,
                                         bins,
                                         interpret=kern.interpret)
        else:
            local = jax.ops.segment_sum(cnt, bc, num_segments=bins)
        total = lax.psum(local, axis)                  # (bins,)
        t = starts_c[r] + jnp.arange(So)
        live = t < bins
        vals = jnp.where(live,
                         jnp.take(total, jnp.clip(t, 0, bins - 1))
                         .astype(out_dtype),
                         jnp.zeros((), out_dtype))
        return _pack_out_row(vals, live, out_layout, r)

    return body


def _histogram_program(mesh, axis, in_layout, off, n, in_dtype, ops,
                       out_layout, out_dtype, bins):
    nsc = sum(len(o.scalars) for o in ops if isinstance(o, _v.BoundOp))
    kern = _hist_kernel_decision(mesh, in_layout, bins)
    key = ("relhist", pinned_id(mesh), axis, in_layout, off, n,
           str(in_dtype), tuple(_traced_op_key(o) for o in ops),
           out_layout, str(out_dtype), int(bins), tuple(kern))
    prog = _prog_cache.get(key)
    if prog is not None:
        return prog
    body = _histogram_body(axis, in_layout, off, n, ops, nsc,
                           out_layout, bins, out_dtype, kern=kern)
    # check_vma=False under the kernel arm: shard_map has no
    # replication rule for pallas_call
    shm = jax.shard_map(body, mesh=mesh,
                        in_specs=(P(axis, None),) + (P(),) * (nsc + 2),
                        out_specs=P(axis, None),
                        check_vma=not kern.use)
    prog = jax.jit(shm)
    _prog_cache[key] = prog
    return prog


def histogram(r, out, lo, hi):
    """Fixed-bin histogram of a distributed range (docs/SPEC.md
    §17.1): ``bins = len(out)`` equal buckets over ``[lo, hi]``
    (right edge inclusive in the last bucket, numpy's rule;
    out-of-range values are dropped), counts cast to ``out``'s dtype.
    Input view chains fuse; ``lo``/``hi`` are traced operands, so a
    streamed range reuses ONE compiled program.  STATIC output shape:
    inside ``dr_tpu.deferred()`` the op records FUSIBLE into the
    surrounding run.  Returns ``out``."""
    if isinstance(lo, (int, float, np.number)) \
            and isinstance(hi, (int, float, np.number)) \
            and not (float(hi) > float(lo)):
        raise ValueError(f"histogram: need hi > lo (got [{lo}, {hi}])")
    chain = _single_chain(r, "histogram")
    oc = _whole_out(out, "histogram")
    if oc.cont.runtime.mesh != chain.cont.runtime.mesh:
        raise TypeError("histogram: out must live on the input's mesh")
    p = _plan_active()
    if p is not None:
        p.record_histogram(chain, oc, lo, hi)
        return out
    sid = _obs.begin("relational.histogram", cat="relational",
                     n=chain.n, bins=oc.n)
    try:
        rt = chain.cont.runtime
        prog = _histogram_program(
            rt.mesh, rt.axis, chain.cont.layout, chain.off, chain.n,
            chain.cont.dtype, tuple(chain.ops), oc.cont.layout,
            oc.cont.dtype, oc.n)
        svals = [jnp.asarray(s) for s in _chain_scalars([chain])]
        oc.cont._data = prog(chain.cont._data, *svals,
                             jnp.asarray(lo), jnp.asarray(hi))
        return out
    finally:
        _obs.end(sid)


# ---------------------------------------------------------------------------
# top_k
# ---------------------------------------------------------------------------

def _top_k_body(axis, in_layout, off, n, ops, nsc, ov_layout, ov_dtype,
                oi_layout, k, largest, merge):
    """The top-k shard body — shared between the eager program and the
    deferred-plan fusible emit (``plan.record_top_k``).  Signature:
    ``body(in_row[, ov_row[, oi_row]], *chain_scalars)`` — the out
    rows are inputs only under ``merge`` (their current contents join
    the candidate pool)."""
    has_idx = oi_layout is not None
    p, S, *_ = working_geometry(in_layout)
    sentinel = _worst(ov_dtype, largest)

    def order_of(vals):
        # ascending 'order' = best first: the monotone encoding,
        # bit-inverted for largest (a monotone reversal for uints AND
        # two's-complement ints alike)
        enc, _big = _encode(vals)
        return (~enc) if largest else enc

    def body(blk, *rest):
        r = lax.axis_index(axis)
        nrows = ((3 if has_idx else 2) if merge else 1) - 1
        sc_iter = iter(rest[nrows:])
        x = _apply_chain_ops(blk[0], ops, sc_iter)
        mask, gid = owned_window_mask(in_layout, off, n)
        xv = jnp.where(mask[r], x.astype(ov_dtype), sentinel)
        # indices are positions WITHIN the input range (window-local)
        gv = jnp.where(mask[r], (gid[r] - off).astype(jnp.int32),
                       _GMAX)
        if merge:
            ovb = rest[0]
            omask, _og = owned_window_mask(ov_layout, 0, k)
            mv = jnp.where(omask[r], ovb[0].astype(ov_dtype), sentinel)
            if has_idx:
                mg = jnp.where(omask[r], rest[1][0].astype(jnp.int32),
                               _GMAX)
            else:
                mg = jnp.full(mv.shape, _GMAX, jnp.int32)
            xv = jnp.concatenate([xv, mv])
            gv = jnp.concatenate([gv, mg])
        # per-shard 2-key sort (order, index): exact tie discipline —
        # equal values keep the smaller index first; masked/pad cells
        # are real sentinel values and sort last naturally
        srt = lax.sort((order_of(xv), gv, xv), dimension=0, num_keys=2)
        kk = min(k, xv.shape[0])
        Go = lax.all_gather(srt[0][:kk], axis).reshape(-1)  # (p*kk,)
        Gg = lax.all_gather(srt[1][:kk], axis).reshape(-1)
        Gv = lax.all_gather(srt[2][:kk], axis).reshape(-1)
        if p * kk < k:
            pad = k - p * kk
            Go = jnp.concatenate(
                [Go, jnp.full((pad,), jnp.iinfo(Go.dtype).max,
                              Go.dtype)])
            Gg = jnp.concatenate(
                [Gg, jnp.full((pad,), _GMAX, jnp.int32)])
            Gv = jnp.concatenate(
                [Gv, jnp.full((pad,), sentinel, ov_dtype)])
        gs = lax.sort((Go, Gg, Gv), dimension=0, num_keys=2)
        res_g, res_v = gs[1][:k], gs[2][:k]

        Sov, ov_starts, _ = _dest_geometry(ov_layout)
        t = ov_starts[r] + jnp.arange(Sov)
        live = t < k
        tc = jnp.clip(t, 0, k - 1)
        ovrow = _pack_out_row(
            jnp.where(live, jnp.take(res_v, tc), sentinel), live,
            ov_layout, r)
        if not has_idx:
            return ovrow
        Soi, oi_starts, _ = _dest_geometry(oi_layout)
        ti = oi_starts[r] + jnp.arange(Soi)
        ilive = ti < k
        tic = jnp.clip(ti, 0, k - 1)
        oirow = _pack_out_row(
            jnp.where(ilive, jnp.take(res_g, tic), _GMAX), ilive,
            oi_layout, r)
        return ovrow, oirow

    return body


def _top_k_program(mesh, axis, in_layout, off, n, in_dtype, ops,
                   ov_layout, ov_dtype, oi_layout, k, largest, merge):
    nsc = sum(len(o.scalars) for o in ops if isinstance(o, _v.BoundOp))
    key = ("reltopk", pinned_id(mesh), axis, in_layout, off, n,
           str(in_dtype), tuple(_traced_op_key(o) for o in ops),
           ov_layout, str(ov_dtype), oi_layout, int(k), bool(largest),
           bool(merge), bool(jax.config.jax_enable_x64))
    prog = _prog_cache.get(key)
    if prog is not None:
        return prog
    body = _top_k_body(axis, in_layout, off, n, ops, nsc, ov_layout,
                       ov_dtype, oi_layout, k, largest, merge)
    has_idx = oi_layout is not None
    nrows = (3 if has_idx else 2) if merge else 1
    nout = 2 if has_idx else 1
    shm = jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(axis, None),) * nrows + (P(),) * nsc,
        out_specs=(P(axis, None),) * nout if nout > 1
        else P(axis, None))
    # under merge the out rows are rebuilt wholesale: donate them
    donate = tuple(range(1, nrows)) if merge else ()
    prog = jax.jit(shm, donate_argnums=donate)
    _prog_cache[key] = prog
    return prog


def _top_k_chains(r, out_vals, out_idx):
    chain = _single_chain(r, "top_k")
    ovc = _whole_out(out_vals, "top_k")
    oic = _whole_out(out_idx, "top_k") if out_idx is not None else None
    k = ovc.n
    if oic is not None:
        if oic.n != k:
            raise ValueError(
                f"top_k: out_idx length {oic.n} != k ({k})")
        if jnp.dtype(oic.cont.dtype) != jnp.dtype(np.int32):
            raise TypeError("top_k: out_idx must be int32")
    mesh = chain.cont.runtime.mesh
    for oc, nm in ((ovc, "out_vals"), (oic, "out_idx")):
        if oc is not None and oc.cont.runtime.mesh != mesh:
            raise TypeError(f"top_k: {nm} must live on the input's "
                            "mesh")
    return chain, ovc, oic


def top_k(r, out_vals, out_idx=None, *, largest: bool = True,
          merge: bool = False):
    """The ``k = len(out_vals)`` best elements of a distributed range,
    best-first (descending values for ``largest=True``; ties keep the
    smaller index).  ``out_idx`` (optional, int32, length k) receives
    each element's position WITHIN ``r`` (window-local for subranges).
    When fewer than k elements exist, trailing slots hold the dtype's
    finite worst value and index ``INT32_MAX``.

    ``merge=True`` folds the CURRENT ``out_vals``/``out_idx`` contents
    into the candidate pool — streaming top-k over windows::

        top_k(v[0:w], vals, idx)                   # first window
        top_k(v[w:2*w], vals, idx, merge=True)     # running top-k...

    (window-local indices then mix across windows; ride an iota
    payload through the values if global positions are needed).
    STATIC output shape: inside ``dr_tpu.deferred()`` the op records
    FUSIBLE into the surrounding run.  Returns ``out_vals``."""
    chain, ovc, oic = _top_k_chains(r, out_vals, out_idx)
    if merge and oic is not None \
            and oic.cont.layout != ovc.cont.layout:
        # the merged candidate pool pairs each CURRENT value with its
        # index BY SLOT through one shared ownership mask — split
        # layouts would mispair them (or crash on width mismatch)
        raise TypeError(
            "top_k: merge=True needs out_vals and out_idx on ONE "
            "layout (their current contents pair by slot)")
    p = _plan_active()
    if p is not None:
        p.record_top_k(chain, ovc, oic, largest, merge)
        return out_vals
    sid = _obs.begin("relational.top_k", cat="relational", n=chain.n,
                     k=ovc.n, largest=largest, merge=merge)
    try:
        rt = chain.cont.runtime
        prog = _top_k_program(
            rt.mesh, rt.axis, chain.cont.layout, chain.off, chain.n,
            chain.cont.dtype, tuple(chain.ops), ovc.cont.layout,
            ovc.cont.dtype,
            oic.cont.layout if oic is not None else None,
            ovc.n, largest, merge)
        svals = [jnp.asarray(s) for s in _chain_scalars([chain])]
        rows = [chain.cont._data]
        if merge:
            rows.append(ovc.cont._data)
            if oic is not None:
                rows.append(oic.cont._data)
        outs = prog(*rows, *svals)
        if oic is not None:
            ovc.cont._data, oic.cont._data = outs
        else:
            ovc.cont._data = outs
        return out_vals
    finally:
        _obs.end(sid)
