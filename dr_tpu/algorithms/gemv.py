"""Distributed matrix products: sparse ``gemv`` and dense ``gemm``.

``gemv(c, a, b)``: c += A·b for a row-tiled sparse A — the reference's
SpMV (``shp/algorithms/gemv.hpp:16-73``): it replicates b to every device
and launches one nnz-parallel kernel per row tile.  TPU re-design: one
``shard_map`` program — b arrives replicated (XLA broadcast over ICI), each
shard does a vectorized gather ``vals * b[cols]`` plus a ``segment_sum``
onto its tile's rows (padded-COO layout: no scalar loops, fixed shapes),
and the result lands already block-sharded as the output vector's shard.
Improvement over the reference: no ``grid_shape[1]==1`` assert needed at
call sites (the container is row-tiled by construction) and accumulation
is well-defined (segment_sum, not racy +=).

``gemm(a, b)``: dense matmul on 2-D tiled matrices — ``jnp.matmul`` under
jit over sharded operands; GSPMD emits the SUMMA-style collectives and the
MXU does the FLOPs.  (The reference has no dense gemm — natural on TPU, so
it ships.)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ._common import uniform_layout
from .elementwise import _prog_cache
from ..core.pinning import pinned_id
from ..containers.distributed_vector import distributed_vector
from ..containers.dense_matrix import dense_matrix
from ..containers.sparse_matrix import sparse_matrix

__all__ = ["gemv", "gemv_n", "flat_gemv", "gemm", "spmm"]


def _gemv_program(mesh, axis, nshards, th, K, m, seg_out, width_out, prev_out):
    key = ("gemv", pinned_id(mesh), axis, nshards, th, K, m, seg_out, width_out,
           prev_out)
    prog = _prog_cache.get(key)
    if prog is not None:
        return prog

    def body(c_blk, vals, rows, cols, b):
        # one shard: c_blk (1, width), vals/rows/cols (1, K), b (n,) replicated
        contrib = vals[0] * b[cols[0]]
        local = jax.ops.segment_sum(contrib, rows[0], num_segments=th)
        # add into the owned window (tile rows == output segment rows)
        upd = c_blk[0, prev_out:prev_out + seg_out] + local.astype(c_blk.dtype)
        return c_blk.at[0, prev_out:prev_out + seg_out].set(upd)

    shmapped = jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(axis, None), P(axis, None), P(axis, None),
                  P(axis, None), P()),
        out_specs=P(axis, None))
    prog = jax.jit(shmapped, donate_argnums=0)
    _prog_cache[key] = prog
    return prog


def _gather_w() -> int:
    """b-slice width per gather (measured TPU sweet spot).  Read per
    call so DR_TPU_GATHER_W sweeps work in-process — but note the ELL
    program caches do NOT key on it; clear caches (fresh process) or
    vary the layout between sweep points."""
    from ..utils.env import env_int
    return env_int("DR_TPU_GATHER_W", 16)
_ELL_CHUNK = 2 ** 13  # tile rows per lax.map chunk (bounds intermediates)


def _ell_local(vals0, cols0, b, th, kmax):
    """One shard's ELL contraction: (th,) row sums of vals * b[cols].

    TPU scatter-adds (segment_sum) and per-element gathers both serialize
    (~4 ns/element); gathering W-wide slices of b and selecting the lane
    with a one-hot compare amortizes the per-gather cost ~2.5x, and the
    fixed (th, kmax) ELL shape makes the multiply + row-sum dense VPU
    work.  b is padded to a multiple of W so every slice is in range."""
    W = _gather_w()
    pad = (-b.shape[0]) % W
    bp = jnp.concatenate([b, jnp.zeros((pad,), b.dtype)]) if pad else b
    B2 = bp.reshape(-1, W)
    q, r = cols0 // W, cols0 % W

    def block(args):
        v, qs, rs = args
        gathered = B2[qs]                       # (ch, kmax, W)
        oh = rs[..., None] == jax.lax.broadcasted_iota(
            jnp.int32, rs.shape + (W,), rs.ndim)
        return (v * (gathered * oh).sum(-1)).sum(-1)

    ch = _ELL_CHUNK
    if th > ch:
        nch, rem = divmod(th, ch)
        body_rows = nch * ch
        local = jax.lax.map(
            block, (vals0[:body_rows].reshape(nch, ch, kmax),
                    q[:body_rows].reshape(nch, ch, kmax),
                    r[:body_rows].reshape(nch, ch, kmax))).reshape(
                        body_rows)
        if rem:  # remainder rows in one bounded tail block
            tail = block((vals0[body_rows:], q[body_rows:],
                          r[body_rows:]))
            local = jnp.concatenate([local, tail])
    else:
        local = block((vals0, q, r))
    return local


def _bcsr_local(bvals0, bcols0, b, seg_out):
    """One shard's BCSR contraction: (seg_out,) row sums from dense
    (8, 128) tiles — ONE 128-slice gather of b per tile plus an MXU
    einsum; dynamic indices drop from one-per-nnz to one-per-tile
    (VERDICT r1 item 6).  bvals0 (nbr, kb, 8, 128), bcols0 (nbr, kb)."""
    BW = 128
    pad = (-b.shape[0]) % BW
    bp = jnp.concatenate([b, jnp.zeros((pad,), b.dtype)]) if pad else b
    g = bp.reshape(-1, BW)[bcols0]            # (nbr, kb, BW)
    local = jnp.einsum(
        "rkbc,rkc->rb", bvals0, g,
        preferred_element_type=jnp.promote_types(b.dtype, jnp.float32))
    return local.reshape(-1)[:seg_out]


def _gemv_bcsr_program(mesh, axis, nshards, nbr, kb, seg_out, prev_out):
    """SpMV over the block-ELL (BCSR) layout (see :func:`_bcsr_local`)."""
    key = ("gemv_bcsr", pinned_id(mesh), axis, nshards, nbr, kb,
           seg_out, prev_out)
    prog = _prog_cache.get(key)
    if prog is not None:
        return prog

    def body(c_blk, bvals, bcols, b):
        local = _bcsr_local(bvals[0], bcols[0], b, seg_out)
        upd = c_blk[0, prev_out:prev_out + seg_out] + \
            local.astype(c_blk.dtype)
        return c_blk.at[0, prev_out:prev_out + seg_out].set(upd)

    shmapped = jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(axis, None), P(axis, None, None, None, None),
                  P(axis, None, None), P()),
        out_specs=P(axis, None))
    prog = jax.jit(shmapped, donate_argnums=0)
    _prog_cache[key] = prog
    return prog


def _gemv_ell_program(mesh, axis, nshards, th, kmax, seg_out, prev_out):
    """Scatter-free SpMV over the row-grouped (ELL) layout
    (see :func:`_ell_local`)."""
    key = ("gemv_ell", pinned_id(mesh), axis, nshards, th, kmax, seg_out, prev_out)
    prog = _prog_cache.get(key)
    if prog is not None:
        return prog

    def body(c_blk, vals, cols, b):
        # one shard: vals/cols (1, th, kmax), b (n,) replicated
        local = _ell_local(vals[0], cols[0], b, th, kmax)
        upd = c_blk[0, prev_out:prev_out + seg_out] + local.astype(c_blk.dtype)
        return c_blk.at[0, prev_out:prev_out + seg_out].set(upd)

    shmapped = jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(axis, None), P(axis, None, None), P(axis, None, None),
                  P()),
        out_specs=P(axis, None))
    prog = jax.jit(shmapped, donate_argnums=0)
    _prog_cache[key] = prog
    return prog


def gemv_n(c: distributed_vector, a: sparse_matrix, b, iters: int):
    """``iters`` chained SpMVs in ONE jitted program (the exchange_n /
    dot_n measurement analog): each round perturbs b by a scalar of the
    running output (times 1e-38) so XLA can neither hoist the
    contraction nor skip re-reading b.  Accumulates into ``c`` like
    ``iters`` gemv calls (up to the negligible perturbation)."""
    from ..plan import flush_reads
    flush_reads("gemv_n")  # reads c._data directly: pending writes first
    assert isinstance(a, sparse_matrix) and a.grid_shape[1] == 1
    m, n = a.shape
    b_arr = b.to_array() if hasattr(b, "to_array") else jnp.asarray(b)
    assert b_arr.shape == (n,)
    rt = a.runtime
    assert (isinstance(c, distributed_vector)
            and uniform_layout(c.layout)
            and c.nshards == a.nshards and c.segment_size == a.tile_rows
            and c.runtime is rt), "gemv_n needs the aligned fast path"
    th = a.tile_rows
    seg_out, prev_out = c.segment_size, c.halo_bounds.prev
    bcsr = a.ensure_bcsr()      # same layout priority as gemv
    have_ell = bcsr or a.ensure_ell()  # side effects survive python -O
    assert have_ell, "gemv_n needs a grouped (BCSR/ELL) fast path"
    kdim = a._bcsr_kb if bcsr else a._ell_width
    key = ("gemv_n", pinned_id(rt.mesh), rt.axis, a.nshards, th,
           kdim, bcsr, seg_out, prev_out, int(iters))
    prog = _prog_cache.get(key)
    if prog is None:
        if bcsr:
            def local_of(vals, cols, b):
                return _bcsr_local(vals[0], cols[0], b, seg_out)

            in_specs = (P(rt.axis, None),
                        P(rt.axis, None, None, None, None),
                        P(rt.axis, None, None), P())
        else:
            def local_of(vals, cols, b):
                return _ell_local(vals[0], cols[0], b, th, kdim)

            in_specs = (P(rt.axis, None), P(rt.axis, None, None),
                        P(rt.axis, None, None), P())

        def body(c_blk, vals, cols, b):
            def it(_, cb):
                s = cb[0, prev_out] * jnp.asarray(1e-38, b.dtype)
                local = local_of(vals, cols, b + s)
                upd = (cb[0, prev_out:prev_out + seg_out]
                       + local.astype(cb.dtype))
                return cb.at[0, prev_out:prev_out + seg_out].set(upd)
            return jax.lax.fori_loop(0, iters, it, c_blk)

        shmapped = jax.shard_map(
            body, mesh=rt.mesh, in_specs=in_specs,
            out_specs=P(rt.axis, None))
        prog = jax.jit(shmapped, donate_argnums=0)
        _prog_cache[key] = prog
    if bcsr:
        c._data = prog(c._data, a._bcsr_vals, a._bcsr_cols, b_arr)
    else:
        c._data = prog(c._data, a._ell_vals, a._ell_cols, b_arr)
    return c


def _gemv2d_bcsr_program(rt, grid, th, tw, nbr, kb, m, n):
    """SpMV on a 2-D tile grid over the block-ELL (BCSR) layout: each
    tile runs the dense-tile MXU contraction (:func:`_bcsr_local`)
    against its LOCAL b slice, then partials ``psum`` over the mesh
    columns.  The layout the MXU likes, on the grid the reference's
    ``grid_shape[1]==1`` assert forbids (gemv.hpp:21)."""
    gp, gq = grid
    mesh2 = rt.mesh2d(grid)
    key = ("gemv2d_bcsr", pinned_id(mesh2), grid, th, tw, nbr, kb, m, n)
    prog = _prog_cache.get(key)
    if prog is not None:
        return prog

    def body(bvals, bcols, b2):
        # per device: bvals (1, 1, nbr, kb, 8, 128), bcols (1, 1, nbr, kb),
        # b2 (1, tw) — the tile's own column window (cols are tile-local)
        local = _bcsr_local(bvals[0, 0], bcols[0, 0], b2[0], th)
        y = jax.lax.psum(local, "mc")
        return y[None]                               # (1, th)

    shm = jax.shard_map(
        body, mesh=mesh2,
        in_specs=(P("mr", "mc", None, None, None, None),
                  P("mr", "mc", None, None), P("mc", None)),
        out_specs=P("mr", None))

    def run(bvals, bcols, b):
        v6 = bvals.reshape(gp, gq, nbr, kb, *bvals.shape[-2:])
        c4 = bcols.reshape(gp, gq, nbr, kb)
        pad = gq * tw - b.shape[0]
        bp = jnp.pad(b, (0, pad)) if pad else b
        return shm(v6, c4, bp.reshape(gq, tw)).reshape(-1)[:m]

    prog = jax.jit(run)
    _prog_cache[key] = prog
    return prog


def _gemv2d_ell_program(rt, grid, th, tw, kmax, m, n):
    """SpMV on a 2-D tile grid: per-tile dense ELL contraction against
    the tile's LOCAL b slice, then a ``psum`` of partials over the mesh
    columns — the collective the reference's ``grid_shape[1]==1`` assert
    avoids (gemv.hpp:21)."""
    gp, gq = grid
    mesh2 = rt.mesh2d(grid)
    key = ("gemv2d", pinned_id(mesh2), grid, th, tw, kmax, m, n)
    prog = _prog_cache.get(key)
    if prog is not None:
        return prog

    def body(vals, cols, b2):
        # per device: vals/cols (1, 1, th, kmax), b2 (1, tw)
        bloc = b2[0]
        contrib = vals[0, 0] * bloc[cols[0, 0]]      # (th, kmax)
        y = jax.lax.psum(contrib.sum(-1), "mc")
        return y[None]                               # (1, th)

    shm = jax.shard_map(
        body, mesh=mesh2,
        in_specs=(P("mr", "mc", None, None), P("mr", "mc", None, None),
                  P("mc", None)),
        out_specs=P("mr", None))

    def run(ell_vals, ell_cols, b):
        v4 = ell_vals.reshape(gp, gq, th, kmax)
        c4 = ell_cols.reshape(gp, gq, th, kmax)
        pad = gq * tw - b.shape[0]
        bp = jnp.pad(b, (0, pad)) if pad else b
        return shm(v4, c4, bp.reshape(gq, tw)).reshape(-1)[:m]

    prog = jax.jit(run)
    _prog_cache[key] = prog
    return prog


def _ell_local_mm(vals0, cols0, B, th, kmax):
    """One shard's ELL contraction against MULTIPLE vectors: (th, nv)
    row sums of vals * B[cols, :].  Same W-slice gather as
    :func:`_ell_local`, but each gathered slice now feeds ``nv`` MACs —
    the gather-ISSUE cost (the random-SpMV bottleneck, docs/PERF.md
    roofline) is paid once per entry regardless of nv.  The slice
    width shrinks with nv so BYTES per gathered slice stay near the
    single-vector sweet spot (the round-2 W sweep showed gather cost
    growing with slice bytes past ~64 B); DR_TPU_SPMM_W overrides for
    on-chip sweeps."""
    nv = B.shape[1]
    from ..utils.env import env_int
    W = env_int("DR_TPU_SPMM_W", max(2, _gather_w() // max(1, nv // 2)))
    pad = (-B.shape[0]) % W
    Bp = jnp.concatenate([B, jnp.zeros((pad, nv), B.dtype)]) if pad else B
    B3 = Bp.reshape(-1, W, nv)
    q, r = cols0 // W, cols0 % W

    def block(args):
        v, qs, rs = args
        gathered = B3[qs]                       # (ch, kmax, W, nv)
        oh = rs[..., None] == jax.lax.broadcasted_iota(
            jnp.int32, rs.shape + (W,), rs.ndim)
        picked = jnp.einsum("ekwv,ekw->ekv", gathered,
                            oh.astype(B.dtype))
        return jnp.einsum("ekv,ek->ev", picked, v)

    ch = max(1, _ELL_CHUNK // max(1, nv))  # bound the (ch,kmax,W,nv) temp
    if th > ch:
        nch, rem = divmod(th, ch)
        body_rows = nch * ch
        local = jax.lax.map(
            block, (vals0[:body_rows].reshape(nch, ch, kmax),
                    q[:body_rows].reshape(nch, ch, kmax),
                    r[:body_rows].reshape(nch, ch, kmax))).reshape(
                        body_rows, nv)
        if rem:
            tail = block((vals0[body_rows:], q[body_rows:],
                          r[body_rows:]))
            local = jnp.concatenate([local, tail])
    else:
        local = block((vals0, q, r))
    return local


def _bcsr_local_mm(bvals0, bcols0, B, seg_out):
    """One shard's BCSR contraction against multiple vectors: (seg_out,
    nv) from dense (8, 128) tiles — one 128-row slice gather of B per
    tile, MXU einsum carries the extra vectors."""
    BW = 128
    nv = B.shape[1]
    pad = (-B.shape[0]) % BW
    Bp = jnp.concatenate([B, jnp.zeros((pad, nv), B.dtype)]) if pad else B
    g = Bp.reshape(-1, BW, nv)[bcols0]        # (nbr, kb, BW, nv)
    local = jnp.einsum(
        "rkbc,rkcv->rbv", bvals0, g,
        preferred_element_type=jnp.promote_types(B.dtype, jnp.float32))
    return local.reshape(-1, nv)[:seg_out]


def _local_mm_parts(rt, a, th, kdim, bcsr):
    """(local_fn, in_specs, device_args) for one shard's multi-vector
    contraction — shared by spmm and spmm_n.  local_fn closes over the
    INT width, never the matrix: the process-lifetime program cache
    must not pin device buffers through the body closure."""
    if bcsr:
        def local_of(vals, cols, B):
            return _bcsr_local_mm(vals[0], cols[0], B, th)
        in_specs = (P(rt.axis, None, None, None, None),
                    P(rt.axis, None, None), P())
        args = (a._bcsr_vals, a._bcsr_cols)
    else:
        def local_of(vals, cols, B, kdim=kdim):
            return _ell_local_mm(vals[0], cols[0], B, th, kdim)
        in_specs = (P(rt.axis, None, None),
                    P(rt.axis, None, None), P())
        args = (a._ell_vals, a._ell_cols)
    return local_of, in_specs, args


def _spmm_w_key():
    """Cache-key component for the SpMM gather width: the raw env
    override (not env_int, whose floor collapses unset and '1') plus
    the DR_TPU_GATHER_W value the default derives from — in-process W
    sweeps must rebuild, not reuse the first-traced program."""
    import os
    return (os.environ.get("DR_TPU_SPMM_W", ""), _gather_w())


def _spmm2d_program(rt, grid, th, tw, kdim, bcsr, m, n, nv):
    """SpMM on a 2-D tile grid: per-tile multi-vector contraction
    (:func:`_bcsr_local_mm` / :func:`_ell_local_mm`) against the tile's
    LOCAL B row-window, then partials ``psum`` over the mesh columns —
    the spmm analog of :func:`_gemv2d_bcsr_program`."""
    gp, gq = grid
    mesh2 = rt.mesh2d(grid)
    key = ("spmm2d", pinned_id(mesh2), grid, th, tw, kdim, bcsr, m, n,
           nv, _spmm_w_key())
    prog = _prog_cache.get(key)
    if prog is not None:
        return prog

    cspec = P("mr", "mc", None, None)
    if bcsr:
        def local_of(vals, cols, B2):
            return _bcsr_local_mm(vals[0, 0], cols[0, 0], B2[0], th)
        vspec = P("mr", "mc", None, None, None, None)
    else:
        def local_of(vals, cols, B2, kdim=kdim):
            return _ell_local_mm(vals[0, 0], cols[0, 0], B2[0], th,
                                 kdim)
        vspec = cspec

    def body(vals, cols, B2):
        y = jax.lax.psum(local_of(vals, cols, B2), "mc")
        return y[None]                               # (1, th, nv)

    shm = jax.shard_map(
        body, mesh=mesh2,
        in_specs=(vspec, cspec, P("mc", None, None)),
        out_specs=P("mr", None, None))

    def run(vals, cols, B):
        shape = vals.shape
        v = vals.reshape(gp, gq, *shape[1:])
        c4 = cols.reshape(gp, gq, *cols.shape[1:])
        pad = gq * tw - B.shape[0]
        Bp = jnp.pad(B, ((0, pad), (0, 0))) if pad else B
        return shm(v, c4, Bp.reshape(gq, tw, -1)).reshape(
            -1, B.shape[1])[:m]

    prog = jax.jit(run)
    _prog_cache[key] = prog
    return prog


def spmm(a: sparse_matrix, b) -> jax.Array:
    """A·B for a row-tiled sparse A and a DENSE (n, nv) right-hand side
    — the multi-vector SpMV.  Returns the (m, nv) product as an array.

    Beyond-parity surface (the reference ships only the single-vector
    ``gemv``, shp/algorithms/gemv.hpp:16-73) and the practical answer to
    the random-pattern SpMV roofline (docs/PERF.md): the per-entry
    gather-issue cost that bounds single-vector SpMV at ~2-4 GFLOP/s on
    this chip is paid ONCE per entry here and amortized over ``nv``
    right-hand sides, so aggregate throughput scales with nv until HBM
    bandwidth binds."""
    assert isinstance(a, sparse_matrix)
    m, n = a.shape
    B = b.to_array() if hasattr(b, "to_array") else jnp.asarray(b)
    assert B.ndim == 2 and B.shape[0] == n, \
        f"spmm needs a ({n}, nv) dense right-hand side, got {B.shape}"
    if a._vals is None:
        return jnp.zeros((m, B.shape[1]), a.dtype)
    rt = a.runtime
    nv = B.shape[1]
    bcsr = a.grid_shape[1] == 1 and a.ensure_bcsr()
    if a.grid_shape[1] == 1 and (bcsr or a.ensure_ell()):
        th = a.tile_rows
        kdim = a._bcsr_kb if bcsr else a._ell_width
        key = ("spmm", pinned_id(rt.mesh), rt.axis, a.nshards, th,
               kdim, bcsr, nv, m, _spmm_w_key())
        local_of, in_specs, args = _local_mm_parts(rt, a, th, kdim,
                                                   bcsr)
        prog = _prog_cache.get(key)
        if prog is None:
            shm = jax.shard_map(local_of, mesh=rt.mesh,
                                in_specs=in_specs,
                                out_specs=P(rt.axis, None))
            prog = jax.jit(shm)
            _prog_cache[key] = prog
        return prog(*args, B)[:m]
    if a.grid_shape[1] > 1:
        bcsr2 = a.ensure_bcsr()
        if bcsr2 or a.ensure_ell():
            prog = _spmm2d_program(
                rt, a.grid_shape, a.tile_rows, a.tile_cols,
                a._bcsr_kb if bcsr2 else a._ell_width, bcsr2,
                m, n, nv)
            args = (a._bcsr_vals, a._bcsr_cols) if bcsr2 \
                else (a._ell_vals, a._ell_cols)
            return prog(*args, B)
    # degenerate layouts: one flat gemv per column (correct everywhere)
    cols = [flat_gemv(a, B[:, j]) for j in range(nv)]
    return jnp.stack(cols, axis=1)


def spmm_n(a: sparse_matrix, b, iters: int) -> jax.Array:
    """``iters`` chained SpMMs in ONE jitted program (the gemv_n
    measurement analog): each round perturbs B by a scalar of the
    running product (times 1e-38) so XLA can neither hoist the
    contraction nor skip re-reading B.  Returns the last product."""
    assert isinstance(a, sparse_matrix) and a.grid_shape[1] == 1
    m, n = a.shape
    B = b.to_array() if hasattr(b, "to_array") else jnp.asarray(b)
    assert B.ndim == 2 and B.shape[0] == n
    rt = a.runtime
    nv = B.shape[1]
    bcsr = a.ensure_bcsr()
    have_ell = bcsr or a.ensure_ell()  # side effects survive python -O
    assert have_ell, "spmm_n needs a grouped (BCSR/ELL) fast path"
    th = a.tile_rows
    kdim = a._bcsr_kb if bcsr else a._ell_width
    key = ("spmm_n", pinned_id(rt.mesh), rt.axis, a.nshards, th, kdim,
           bcsr, nv, m, int(iters), _spmm_w_key())
    local_of, in_specs, args = _local_mm_parts(rt, a, th, kdim, bcsr)
    prog = _prog_cache.get(key)
    if prog is None:
        def body(vals, cols, B):
            # both local bodies accumulate in (at least) f32: the loop
            # carry must match that promoted dtype, not B's
            out_dt = jnp.promote_types(B.dtype, jnp.float32)

            def it(_, y):
                s = y[0, 0] * jnp.asarray(1e-38, B.dtype)
                return local_of(vals, cols, B + s).astype(out_dt)
            # seed the carry VARYING over the mesh axis (zeros alone are
            # replicated and shard_map's vma check rejects the loop)
            y0 = jnp.zeros((th, nv), out_dt) \
                + 0 * vals[(0,) * vals.ndim].astype(out_dt)
            return jax.lax.fori_loop(0, iters, it, y0)

        shm = jax.shard_map(body, mesh=rt.mesh, in_specs=in_specs,
                            out_specs=P(rt.axis, None))
        prog = jax.jit(shm)
        _prog_cache[key] = prog
    return prog(*args, B)[:m]


def gemv(c: distributed_vector, a: sparse_matrix, b) -> distributed_vector:
    """c += A·b (reference gemv semantics: accumulate into c,
    gemv.hpp:45-66)."""
    # gemv is NON-FUSIBLE in deferred regions (ISSUE 3): flush the
    # recorded prefix (order!) before dispatching eagerly
    from ..plan import barrier as _plan_barrier
    _plan_barrier("gemv")
    assert isinstance(a, sparse_matrix)
    m, n = a.shape
    assert len(c) == m, "output length must equal matrix rows"
    b_arr = b.to_array() if hasattr(b, "to_array") else jnp.asarray(b)
    assert b_arr.shape == (n,)
    if a._vals is None:
        return c  # empty matrix: nothing to add
    rt = a.runtime
    if a.grid_shape[1] > 1:
        # 2-D tile grid: partial SpMV per tile + psum over mesh columns
        if a.ensure_bcsr():
            prog = _gemv2d_bcsr_program(rt, a.grid_shape, a.tile_rows,
                                        a.tile_cols, a._bcsr_nbr,
                                        a._bcsr_kb, m, n)
            y = prog(a._bcsr_vals, a._bcsr_cols, b_arr)
        elif a.ensure_ell():
            prog = _gemv2d_ell_program(rt, a.grid_shape, a.tile_rows,
                                       a.tile_cols, a._ell_width, m, n)
            y = prog(a._ell_vals, a._ell_cols, b_arr)
        else:
            y = flat_gemv(a, b_arr)
        c.assign_array(c.to_array() + y.astype(c.dtype))
        return c
    # shard r of c must hold exactly tile r's rows — which also requires
    # the uniform ceil layout (an uneven distribution can match nshards
    # and capacity while owning different row ranges)
    fast = (isinstance(c, distributed_vector)
            and uniform_layout(c.layout)
            and c.nshards == a.nshards and c.segment_size == a.tile_rows
            and c.runtime is rt)
    if fast:
        if a.ensure_bcsr():
            # block-structured: dense-tile MXU path, one gather per tile
            prog = _gemv_bcsr_program(rt.mesh, rt.axis, a.nshards,
                                      a._bcsr_nbr,
                                      a._bcsr_kb, c.segment_size,
                                      c.halo_bounds.prev)
            c._data = prog(c._data, a._bcsr_vals, a._bcsr_cols, b_arr)
            return c
        if a.ensure_ell():
            prog = _gemv_ell_program(rt.mesh, rt.axis, a.nshards,
                                     a.tile_rows, a._ell_width,
                                     c.segment_size, c.halo_bounds.prev)
            c._data = prog(c._data, a._ell_vals, a._ell_cols, b_arr)
            return c
        prog = _gemv_program(rt.mesh, rt.axis, a.nshards, a.tile_rows,
                             a._vals.shape[1], m, c.segment_size,
                             c.block_width, c.halo_bounds.prev)
        c._data = prog(c._data, a._vals, a._rows, a._cols, b_arr)
        return c
    # fallback: global scatter-add through the logical array
    y = flat_gemv(a, b_arr)
    arr = c.to_array() + y
    c.assign_array(arr)
    return c


def flat_gemv(a: sparse_matrix, b_arr) -> jax.Array:
    """A·b as a logical (m,) array (no output container needed).

    Handles any tile grid: per-tile local indices get their tile's
    row/col offsets back; pad entries carry value 0 so clamped
    out-of-range gathers/scatters contribute nothing."""
    if a._vals is None:
        return jnp.zeros((a.shape[0],), a.dtype)
    gp, gq = a.grid_shape
    th, tw = a.tile_rows, a.tile_cols
    t = jnp.arange(a.nshards, dtype=jnp.int32)[:, None]
    rows_g = (a._rows + (t // gq) * th).reshape(-1)
    cols_g = (a._cols + (t % gq) * tw).reshape(-1)
    contrib = (a._vals.reshape(-1)
               * jnp.take(jnp.asarray(b_arr), cols_g, mode="clip"))
    return jnp.zeros((a.shape[0],), a.dtype).at[rows_g].add(contrib)


def gemm(a: dense_matrix, b: dense_matrix,
         out: dense_matrix = None) -> dense_matrix:
    """Dense C = A·B on 2-D tiled matrices (MXU path)."""
    m, k = a.shape
    k2, n = b.shape
    assert k == k2
    if out is None:
        out = dense_matrix((m, n), a.dtype, runtime=a.runtime)
    key = ("gemm", pinned_id(a.runtime.mesh), a.shape, b.shape, str(a.dtype))
    prog = _prog_cache.get(key)
    if prog is None:
        prog = jax.jit(lambda x, y: jnp.matmul(
            x, y, preferred_element_type=jnp.float32))
        _prog_cache[key] = prog
    out.assign_array(prog(a.to_array(), b.to_array()).astype(out.dtype))
    return out
