"""Distributed matrix products: sparse ``gemv`` and dense ``gemm``.

``gemv(c, a, b)``: c += A·b for a row-tiled sparse A — the reference's
SpMV (``shp/algorithms/gemv.hpp:16-73``): it replicates b to every device
and launches one nnz-parallel kernel per row tile.  TPU re-design: one
``shard_map`` program — b arrives replicated (XLA broadcast over ICI), each
shard does a vectorized gather ``vals * b[cols]`` plus a ``segment_sum``
onto its tile's rows (padded-COO layout: no scalar loops, fixed shapes),
and the result lands already block-sharded as the output vector's shard.
Improvement over the reference: no ``grid_shape[1]==1`` assert needed at
call sites (the container is row-tiled by construction) and accumulation
is well-defined (segment_sum, not racy +=).

``gemm(a, b)``: dense matmul on 2-D tiled matrices — ``jnp.matmul`` under
jit over sharded operands; GSPMD emits the SUMMA-style collectives and the
MXU does the FLOPs.  (The reference has no dense gemm — natural on TPU, so
it ships.)

Round 9 — the sparse hot-path overhaul:

* **Format dispatch** honors the container's build-time AUTOSELECT
  (sparse_matrix._decide_format: csr / ell / bcsr from the row-length
  distribution) with a ``DR_TPU_SPMV_FORMAT`` dispatch-time override
  (``ring`` opts into the rotating-b schedule).
* **Ring programs** (``_gemv_ring_program``): b is block-sharded and
  rotates around the mesh ring (parallel/pipeline.ring_pipeline,
  software-pipelined by default) while each shard contracts its
  per-step ELL bucket (sparse_matrix.ensure_ring) against the held
  window — compute for step t overlaps the transfer for step t+1.
  ``stop_after`` truncations (:data:`SPMV_PHASES`) drive the sparse
  phase ladder (``gemv_phases_n``), the sort round's profiling
  discipline applied here.
* **Gather mode**: the grouped contractions pick per-element gathers
  off-TPU and the W-slice one-hot trick on TPU (``_gather_mode``).
* Inside ``dr_tpu.deferred()`` regions ``gemv`` records as an ordered
  OPAQUE op (like inclusive_scan) instead of forcing a plan flush.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ._common import uniform_layout
from .elementwise import _prog_cache
from ..core.pinning import pinned_id
from ..containers.distributed_vector import distributed_vector
from ..containers.dense_matrix import dense_matrix
from ..containers.sparse_matrix import sparse_matrix
from ..parallel import pipeline as _pl
from ..utils.env import env_str

__all__ = ["gemv", "gemv_n", "gemv_phases_n", "flat_gemv", "gemm",
           "spmm", "SPMV_PHASES"]

#: ring-SpMV phase ladder (profiling truncations; see
#: :func:`_gemv_ring_program` and utils/profiling.profile_phases):
#: "local_compute" = every bucket contraction, no transfers;
#: "rotate" = + the ring ppermutes; "combine" = + the full-window
#: accumulate into c (= the full program).
SPMV_PHASES = ("local_compute", "rotate", "combine")


def _pick_format(a) -> str:
    """Dispatch-time SpMV layout choice: ``DR_TPU_SPMV_FORMAT``
    (csr / ell / bcsr / ring) overrides the container's build-time
    autoselect (``sparse_matrix.format``).  Read per call so in-process
    sweeps work; every program the choice routes to has its own cache
    key, so switching formats never reuses a stale program.  Between
    the env pin and the autoselect sits the persisted tuning DB
    (docs/SPEC.md §21.6): a measured ``spmv.format`` winner for this
    mesh's backend/shape context (written by ``tune_tpu.py spmv``)
    replaces the heuristic — an ineligible recorded format still
    falls down the dispatch chain like a forced one (§12.2)."""
    env = env_str("DR_TPU_SPMV_FORMAT").lower()
    if env in ("csr", "ell", "bcsr", "ring"):
        return env
    from .. import tuning as _tuning
    v = _tuning.lookup("spmv", "format")
    if isinstance(v, str) and v.lower() in ("csr", "ell", "bcsr",
                                            "ring"):
        return v.lower()
    return a._format


def viable_formats(a) -> dict:
    """Which SpMV layouts a forced ``DR_TPU_SPMV_FORMAT`` would
    actually run for ``a``: an ineligible forced format falls back
    down the dispatch chain (SPEC §12.2), so the bench / tune format
    ladders use this map to TAG forced-but-ineligible rungs instead of
    recording the fallback arm's number under the forced label."""
    return {"csr": True, "ell": a.ensure_ell(),
            "bcsr": a.ensure_bcsr(), "ring": a.ensure_ring()}


def resolved_format(a) -> str:
    """The arm the 1-D gemv/gemv_n dispatch will ACTUALLY run for
    ``a`` right now: :func:`_pick_format` (env override or autoselect)
    resolved down the fallback chain exactly as the dispatchers do —
    the honest value for an artifact's chosen-format tag (a pinned but
    ineligible format must not label the fallback arm's number)."""
    fmt = _pick_format(a)
    if fmt == "ring" and a.ensure_ring():
        return "ring"
    if fmt == "bcsr" and a.ensure_bcsr():
        return "bcsr"
    if fmt != "csr" and a.ensure_ell():
        return "ell"
    return "csr"


def resolved_spmm_format(a) -> str:
    """:func:`resolved_format` for the spmm_n dispatch, which has only
    the grouped arms: a forced/autoselected csr or ring resolves to the
    ELL path (see spmm_n's docstring) — the honest value for the
    ``spmm_format`` artifact tag, owned here so the label can never
    drift from the dispatch."""
    fmt = _pick_format(a)
    return "bcsr" if fmt == "bcsr" and a.ensure_bcsr() else "ell"


def _gather_mode(rt) -> str:
    """Gather strategy for the grouped (ELL/ring) contractions:
    ``slice`` = W-wide slice + one-hot select (amortizes the TPU's
    serialized per-element gather issue ~2.5x, docs/PERF.md roofline);
    ``direct`` = plain per-element gather — the right call off-TPU,
    where gathers are cheap and the one-hot trick just multiplies the
    FLOPs by W.  ``DR_TPU_GATHER_MODE`` in {auto, slice, direct}
    overrides; auto resolves from the runtime's platform.  Keyed into
    every program cache that threads it."""
    m = env_str("DR_TPU_GATHER_MODE", "auto").lower()
    if m in ("slice", "direct"):
        return m
    from . import _common
    return "slice" if _common.on_tpu(rt) else "direct"


def _combine_mode() -> str:
    """Cross-tile partial combine for the 2-D grid programs:
    ``psum`` (default — XLA's all-reduce, the measured winner) or
    ``ring`` (pipeline.ring_combine — the rotate-collect arm for the
    DR_TPU_SPMV_COMBINE A/B on chip)."""
    m = env_str("DR_TPU_SPMV_COMBINE").lower()
    return m if m in ("psum", "ring") else "psum"


def _gemv_program(mesh, axis, nshards, th, K, m, seg_out, width_out, prev_out):
    key = ("gemv", pinned_id(mesh), axis, nshards, th, K, m, seg_out, width_out,
           prev_out)
    prog = _prog_cache.get(key)
    if prog is not None:
        return prog

    def body(c_blk, vals, rows, cols, b):
        # one shard: c_blk (1, width), vals/rows/cols (1, K), b (n,) replicated
        contrib = vals[0] * b[cols[0]]
        local = jax.ops.segment_sum(contrib, rows[0], num_segments=th)
        # add into the owned window (tile rows == output segment rows)
        upd = c_blk[0, prev_out:prev_out + seg_out] + local.astype(c_blk.dtype)
        return c_blk.at[0, prev_out:prev_out + seg_out].set(upd)

    shmapped = jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(axis, None), P(axis, None), P(axis, None),
                  P(axis, None), P()),
        out_specs=P(axis, None))
    prog = jax.jit(shmapped, donate_argnums=0)
    _prog_cache[key] = prog
    return prog


def _gather_w() -> int:
    """b-slice width per gather (measured TPU sweet spot).  Read per
    call so DR_TPU_GATHER_W sweeps work in-process; the slice-mode
    program caches key on it (round 9), so sweep points rebuild
    instead of reusing the first-traced width."""
    from ..utils.env import env_int
    return env_int("DR_TPU_GATHER_W", 16)
_ELL_CHUNK = 2 ** 13  # tile rows per lax.map chunk (bounds intermediates)


def _ell_local(vals0, cols0, b, th, kmax, mode="slice"):
    """One shard's ELL contraction: (th,) row sums of vals * b[cols].

    TPU scatter-adds (segment_sum) and per-element gathers both serialize
    (~4 ns/element); gathering W-wide slices of b and selecting the lane
    with a one-hot compare amortizes the per-gather cost ~2.5x, and the
    fixed (th, kmax) ELL shape makes the multiply + row-sum dense VPU
    work.  b is padded to a multiple of W so every slice is in range.

    ``mode="direct"`` (:func:`_gather_mode` — the off-TPU resolution)
    skips the slice trick: one plain gather per entry, no W-fold FLOP
    multiplication.  Bit-identical to the slice path (the one-hot
    select adds exact zeros)."""
    if mode == "direct":
        return (vals0 * jnp.take(b, cols0)).sum(-1)
    W = _gather_w()
    pad = (-b.shape[0]) % W
    bp = jnp.concatenate([b, jnp.zeros((pad,), b.dtype)]) if pad else b
    B2 = bp.reshape(-1, W)
    q, r = cols0 // W, cols0 % W

    def block(args):
        v, qs, rs = args
        gathered = B2[qs]                       # (ch, kmax, W)
        oh = rs[..., None] == jax.lax.broadcasted_iota(
            jnp.int32, rs.shape + (W,), rs.ndim)
        return (v * (gathered * oh).sum(-1)).sum(-1)

    ch = _ELL_CHUNK
    if th > ch:
        nch, rem = divmod(th, ch)
        body_rows = nch * ch
        local = jax.lax.map(
            block, (vals0[:body_rows].reshape(nch, ch, kmax),
                    q[:body_rows].reshape(nch, ch, kmax),
                    r[:body_rows].reshape(nch, ch, kmax))).reshape(
                        body_rows)
        if rem:  # remainder rows in one bounded tail block
            tail = block((vals0[body_rows:], q[body_rows:],
                          r[body_rows:]))
            local = jnp.concatenate([local, tail])
    else:
        local = block((vals0, q, r))
    return local


def _bcsr_local(bvals0, bcols0, b, seg_out):
    """One shard's BCSR contraction: (seg_out,) row sums from dense
    (8, 128) tiles — ONE 128-slice gather of b per tile plus an MXU
    einsum; dynamic indices drop from one-per-nnz to one-per-tile
    (VERDICT r1 item 6).  bvals0 (nbr, kb, 8, 128), bcols0 (nbr, kb)."""
    BW = 128
    pad = (-b.shape[0]) % BW
    bp = jnp.concatenate([b, jnp.zeros((pad,), b.dtype)]) if pad else b
    g = bp.reshape(-1, BW)[bcols0]            # (nbr, kb, BW)
    local = jnp.einsum(
        "rkbc,rkc->rb", bvals0, g,
        preferred_element_type=jnp.promote_types(b.dtype, jnp.float32))
    return local.reshape(-1)[:seg_out]


def _gemv_bcsr_program(mesh, axis, nshards, nbr, kb, seg_out, prev_out):
    """SpMV over the block-ELL (BCSR) layout (see :func:`_bcsr_local`)."""
    key = ("gemv_bcsr", pinned_id(mesh), axis, nshards, nbr, kb,
           seg_out, prev_out)
    prog = _prog_cache.get(key)
    if prog is not None:
        return prog

    def body(c_blk, bvals, bcols, b):
        local = _bcsr_local(bvals[0], bcols[0], b, seg_out)
        upd = c_blk[0, prev_out:prev_out + seg_out] + \
            local.astype(c_blk.dtype)
        return c_blk.at[0, prev_out:prev_out + seg_out].set(upd)

    shmapped = jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(axis, None), P(axis, None, None, None, None),
                  P(axis, None, None), P()),
        out_specs=P(axis, None))
    prog = jax.jit(shmapped, donate_argnums=0)
    _prog_cache[key] = prog
    return prog


def _gemv_ell_program(mesh, axis, nshards, th, kmax, seg_out, prev_out,
                      mode):
    """Scatter-free SpMV over the row-grouped (ELL) layout
    (see :func:`_ell_local`)."""
    key = ("gemv_ell", pinned_id(mesh), axis, nshards, th, kmax, seg_out,
           prev_out, mode, _gather_w() if mode == "slice" else 0)
    prog = _prog_cache.get(key)
    if prog is not None:
        return prog

    def body(c_blk, vals, cols, b):
        # one shard: vals/cols (1, th, kmax), b (n,) replicated
        local = _ell_local(vals[0], cols[0], b, th, kmax, mode=mode)
        upd = c_blk[0, prev_out:prev_out + seg_out] + local.astype(c_blk.dtype)
        return c_blk.at[0, prev_out:prev_out + seg_out].set(upd)

    shmapped = jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(axis, None), P(axis, None, None), P(axis, None, None),
                  P()),
        out_specs=P(axis, None))
    prog = jax.jit(shmapped, donate_argnums=0)
    _prog_cache[key] = prog
    return prog


def _gemv_ring_program(rt, nshards, th, kr, bw, seg_out, prev_out, mode,
                       schedule, stop_after, iters):
    """Ring-scheduled SpMV (round 9): b is BLOCK-sharded over the mesh
    and rotates around the ring (``parallel/pipeline.ring_pipeline`` —
    double-buffered pipelined schedule by default, ``serial`` for the
    A/B) while each shard contracts its per-step ELL bucket
    (``sparse_matrix.ensure_ring``) against the held window.  Compute
    for step t overlaps the ICI transfer for step t+1 — the overlap the
    replicated-b programs cannot express (they pay one XLA broadcast of
    ALL of b up front).  The two schedules run the same dataflow in the
    same reduction order, so their results are bit-identical
    (fuzz-pinned, tests/test_pipeline.py).

    ``stop_after`` (profiling — the sort round's truncation
    discipline): a :data:`SPMV_PHASES` name cuts the program after that
    phase.  ``local_compute`` contracts every bucket against the
    shard's OWN window (full FLOPs, zero transfers); ``rotate`` runs
    the full ring loop but writes only a reduced scalar (skipping the
    full-window combine while keeping every contraction live);
    ``combine`` (= the full program) adds the window accumulate into
    c.  ``iters`` > 1 chains rounds under
    ``fori_loop`` with the gemv_n perturbation so XLA can neither hoist
    nor skip; ``iters == 1`` is the exact eager program (no
    perturbation)."""
    axis = rt.axis
    if stop_after == SPMV_PHASES[-1]:
        stop_after = None  # the full program IS the last phase
    key = ("gemv_ring", pinned_id(rt.mesh), axis, nshards, th, kr, bw,
           seg_out, prev_out, mode, schedule, stop_after, int(iters),
           _gather_w() if mode == "slice" else 0)
    prog = _prog_cache.get(key)
    if prog is not None:
        return prog
    restore = iters > 1  # fused loops must restart from the origin

    def body(c_blk, rvals, rcols, b2):
        # one shard: c_blk (1, width), rvals/rcols (1, P, th, kr),
        # b2 (1, bw) — the shard's own b window at step 0
        def round_(cb, bb):
            def contract(t, carry, blk):
                local = _ell_local(rvals[0, t], rcols[0, t], blk[0],
                                   th, kr, mode=mode)
                return carry + local

            # seed VARYING over the mesh axis (zeros alone are
            # replicated and shard_map's vma check rejects the carry)
            y0 = jnp.zeros((th,), jnp.float32) + 0.0 * bb[0, 0]
            if stop_after == "local_compute":
                y = y0
                for t in range(nshards):
                    y = contract(t, y, bb)
                bb_out = bb
            elif restore:
                y, bb_out = _pl.ring_pipeline(
                    axis, nshards, y0, bb, contract,
                    schedule=schedule, restore_blocks=True)
            else:
                y = _pl.ring_pipeline(axis, nshards, y0, bb, contract,
                                      schedule=schedule)
                bb_out = bb
            if stop_after == "rotate":
                # full ring math, scalar write: y.sum() keeps EVERY
                # row's contraction live (a y[0]-only write would let
                # XLA dead-code most of the compute and the ladder
                # would misattribute it to the next phase); the
                # full-window accumulate is the NEXT phase's marginal
                upd0 = cb[0, prev_out] + y.sum().astype(cb.dtype)
                return cb.at[0, prev_out].set(upd0), bb_out
            upd = cb[0, prev_out:prev_out + seg_out] + \
                y[:seg_out].astype(cb.dtype)
            return cb.at[0, prev_out:prev_out + seg_out].set(upd), bb_out

        if iters == 1:
            out, _ = round_(c_blk, b2)
            return out

        def it(_, carry):
            cb, bb = carry
            s = cb[0, prev_out] * jnp.asarray(1e-38, b2.dtype)
            return round_(cb, bb + s)

        out, _ = jax.lax.fori_loop(0, iters, it, (c_blk, b2))
        return out

    shmapped = jax.shard_map(
        body, mesh=rt.mesh,
        in_specs=(P(axis, None), P(axis, None, None, None),
                  P(axis, None, None, None), P(axis, None)),
        out_specs=P(axis, None))

    def run(c_data, rvals, rcols, b):
        pad = nshards * bw - b.shape[0]
        bp = jnp.pad(b, (0, pad)) if pad else b
        return shmapped(c_data, rvals, rcols, bp.reshape(nshards, bw))

    prog = jax.jit(run, donate_argnums=0)
    _prog_cache[key] = prog
    return prog


def _ring_fast_args(c, a, b):
    """Shared validation for the ring dispatchers: the aligned fast
    path (shard r of c holds tile r's rows) plus a built ring layout.
    Returns ``(rt, b_arr, seg_out, prev_out)``."""
    assert isinstance(a, sparse_matrix) and a.grid_shape[1] == 1
    m, n = a.shape
    b_arr = b.to_array() if hasattr(b, "to_array") else jnp.asarray(b)
    assert b_arr.shape == (n,)
    rt = a.runtime
    assert (isinstance(c, distributed_vector)
            and uniform_layout(c.layout)
            and c.nshards == a.nshards and c.segment_size == a.tile_rows
            and c.runtime is rt), "fused gemv needs the aligned fast path"
    return rt, b_arr, c.segment_size, c.halo_bounds.prev


def gemv_phases_n(c: distributed_vector, a: sparse_matrix, b,
                  stop_after: str, iters: int):
    """``iters`` fused rounds of the ring SpMV truncated after
    ``stop_after`` (:data:`SPMV_PHASES`) — the profiling aid behind
    bench's ``detail.spmv_phases_gflops`` and the tune_tpu.py spmv
    ladder (utils/profiling.profile_phases differences consecutive
    truncations; the per-dispatch constant and shared prefix work
    cancel).  Requires the ring layout (``a.ensure_ring()``)."""
    from ..plan import flush_reads
    flush_reads("gemv_phases_n")  # reads c._data directly
    assert stop_after in SPMV_PHASES, (stop_after, SPMV_PHASES)
    have_ring = a.ensure_ring()  # side effects survive python -O
    assert have_ring, \
        "gemv_phases_n profiles the ring schedule (ensure_ring)"
    rt, b_arr, seg_out, prev_out = _ring_fast_args(c, a, b)
    _pl.fire_ppermute(op="gemv_phases_n")
    prog = _gemv_ring_program(rt, a.nshards, a.tile_rows, a._ring_kr,
                              a._ring_bw, seg_out, prev_out,
                              _gather_mode(rt), _pl.schedule_mode(),
                              stop_after, int(iters))
    c._data = prog(c._data, a._ring_vals, a._ring_cols, b_arr)
    return c


def gemv_n(c: distributed_vector, a: sparse_matrix, b, iters: int):
    """``iters`` chained SpMVs in ONE jitted program (the exchange_n /
    dot_n measurement analog): each round perturbs b by a scalar of the
    running output (times 1e-38) so XLA can neither hoist the
    contraction nor skip re-reading b.  Accumulates into ``c`` like
    ``iters`` gemv calls (up to the negligible perturbation)."""
    from ..plan import flush_reads
    flush_reads("gemv_n")  # reads c._data directly: pending writes first
    rt, b_arr, seg_out, prev_out = _ring_fast_args(c, a, b)
    th = a.tile_rows
    fmt = _pick_format(a)
    mode = _gather_mode(rt)
    if fmt == "ring" and a.ensure_ring():
        _pl.fire_ppermute(op="gemv_n")
        prog = _gemv_ring_program(rt, a.nshards, th, a._ring_kr,
                                  a._ring_bw, seg_out, prev_out, mode,
                                  _pl.schedule_mode(), None, int(iters))
        c._data = prog(c._data, a._ring_vals, a._ring_cols, b_arr)
        return c
    bcsr = fmt == "bcsr" and a.ensure_bcsr()
    ell = (not bcsr) and fmt != "csr" and a.ensure_ell()
    if not (bcsr or ell):
        # csr (padded-COO segment-sum) fused loop — the format ladder
        # needs every arm measurable, not just the grouped fast paths
        assert a._vals is not None, "gemv_n needs a built matrix"
        K = a._vals.shape[1]
        key = ("gemv_n_csr", pinned_id(rt.mesh), rt.axis, a.nshards,
               th, K, seg_out, prev_out, int(iters))
        prog = _prog_cache.get(key)
        if prog is None:
            def body(c_blk, vals, rows, cols, b):
                def it(_, cb):
                    s = cb[0, prev_out] * jnp.asarray(1e-38, b.dtype)
                    contrib = vals[0] * (b + s)[cols[0]]
                    local = jax.ops.segment_sum(contrib, rows[0],
                                                num_segments=th)
                    upd = (cb[0, prev_out:prev_out + seg_out]
                           + local.astype(cb.dtype))
                    return cb.at[0, prev_out:prev_out + seg_out].set(upd)
                return jax.lax.fori_loop(0, iters, it, c_blk)

            shmapped = jax.shard_map(
                body, mesh=rt.mesh,
                in_specs=(P(rt.axis, None), P(rt.axis, None),
                          P(rt.axis, None), P(rt.axis, None), P()),
                out_specs=P(rt.axis, None))
            prog = jax.jit(shmapped, donate_argnums=0)
            _prog_cache[key] = prog
        c._data = prog(c._data, a._vals, a._rows, a._cols, b_arr)
        return c
    kdim = a._bcsr_kb if bcsr else a._ell_width
    key = ("gemv_n", pinned_id(rt.mesh), rt.axis, a.nshards, th,
           kdim, bcsr, seg_out, prev_out, int(iters), mode,
           _gather_w() if (ell and mode == "slice") else 0)
    prog = _prog_cache.get(key)
    if prog is None:
        if bcsr:
            def local_of(vals, cols, b):
                return _bcsr_local(vals[0], cols[0], b, seg_out)

            in_specs = (P(rt.axis, None),
                        P(rt.axis, None, None, None, None),
                        P(rt.axis, None, None), P())
        else:
            def local_of(vals, cols, b):
                return _ell_local(vals[0], cols[0], b, th, kdim,
                                  mode=mode)

            in_specs = (P(rt.axis, None), P(rt.axis, None, None),
                        P(rt.axis, None, None), P())

        def body(c_blk, vals, cols, b):
            def it(_, cb):
                s = cb[0, prev_out] * jnp.asarray(1e-38, b.dtype)
                local = local_of(vals, cols, b + s)
                upd = (cb[0, prev_out:prev_out + seg_out]
                       + local.astype(cb.dtype))
                return cb.at[0, prev_out:prev_out + seg_out].set(upd)
            return jax.lax.fori_loop(0, iters, it, c_blk)

        shmapped = jax.shard_map(
            body, mesh=rt.mesh, in_specs=in_specs,
            out_specs=P(rt.axis, None))
        prog = jax.jit(shmapped, donate_argnums=0)
        _prog_cache[key] = prog
    if bcsr:
        c._data = prog(c._data, a._bcsr_vals, a._bcsr_cols, b_arr)
    else:
        c._data = prog(c._data, a._ell_vals, a._ell_cols, b_arr)
    return c


def _combine2d(local, gq, combine, schedule):
    """The 2-D grid programs' cross-column partial combine: ``psum``
    (default) or the ring all-gather + canonical-order sum
    (pipeline.ring_combine) — the rotate-collect arm whose serial vs
    pipelined schedules are bit-identical."""
    if combine == "ring":
        return _pl.ring_combine("mc", gq, local, schedule=schedule)
    return jax.lax.psum(local, "mc")


def _shm2d(body, mesh2, in_specs, combine, nout):
    """shard_map wrapper for the 2-D programs (``nout`` = the body
    output's rank): the ring combine's output is bitwise-replicated
    over the mesh columns but still VARIES there in shard_map's vma
    typing, so its out_specs keep the ``mc`` axis (run() slices
    column 0)."""
    if combine == "ring":
        return jax.shard_map(
            lambda *a: body(*a)[None], mesh=mesh2, in_specs=in_specs,
            out_specs=P("mr", "mc", *([None] * (nout - 1))))
    return jax.shard_map(body, mesh=mesh2, in_specs=in_specs,
                         out_specs=P("mr", *([None] * (nout - 1))))


def _gemv2d_bcsr_program(rt, grid, th, tw, nbr, kb, m, n):
    """SpMV on a 2-D tile grid over the block-ELL (BCSR) layout: each
    tile runs the dense-tile MXU contraction (:func:`_bcsr_local`)
    against its LOCAL b slice, then partials combine over the mesh
    columns (``psum`` by default; ``DR_TPU_SPMV_COMBINE=ring`` takes
    the pipelined ring arm).  The layout the MXU likes, on the grid the
    reference's ``grid_shape[1]==1`` assert forbids (gemv.hpp:21)."""
    gp, gq = grid
    mesh2 = rt.mesh2d(grid)
    combine = _combine_mode()
    schedule = _pl.schedule_mode()
    key = ("gemv2d_bcsr", pinned_id(mesh2), grid, th, tw, nbr, kb, m, n,
           combine, schedule if combine == "ring" else "")
    prog = _prog_cache.get(key)
    if prog is not None:
        return prog

    def body(bvals, bcols, b2):
        # per device: bvals (1, 1, nbr, kb, 8, 128), bcols (1, 1, nbr, kb),
        # b2 (1, tw) — the tile's own column window (cols are tile-local)
        local = _bcsr_local(bvals[0, 0], bcols[0, 0], b2[0], th)
        y = _combine2d(local, gq, combine, schedule)
        return y[None]                               # (1, th)

    shm = _shm2d(body, mesh2,
                 (P("mr", "mc", None, None, None, None),
                  P("mr", "mc", None, None), P("mc", None)), combine,
                 nout=2)

    def run(bvals, bcols, b):
        v6 = bvals.reshape(gp, gq, nbr, kb, *bvals.shape[-2:])
        c4 = bcols.reshape(gp, gq, nbr, kb)
        pad = gq * tw - b.shape[0]
        bp = jnp.pad(b, (0, pad)) if pad else b
        out = shm(v6, c4, bp.reshape(gq, tw))
        if combine == "ring":
            out = out[:, 0]  # bitwise-identical across mesh columns
        return out.reshape(-1)[:m]

    prog = jax.jit(run)
    _prog_cache[key] = prog
    return prog


def _gemv2d_ell_program(rt, grid, th, tw, kmax, m, n):
    """SpMV on a 2-D tile grid: per-tile dense ELL contraction against
    the tile's LOCAL b slice, then partials combine over the mesh
    columns (psum / ring, ``DR_TPU_SPMV_COMBINE``) — the collective the
    reference's ``grid_shape[1]==1`` assert avoids (gemv.hpp:21)."""
    gp, gq = grid
    mesh2 = rt.mesh2d(grid)
    combine = _combine_mode()
    schedule = _pl.schedule_mode()
    key = ("gemv2d", pinned_id(mesh2), grid, th, tw, kmax, m, n,
           combine, schedule if combine == "ring" else "")
    prog = _prog_cache.get(key)
    if prog is not None:
        return prog

    def body(vals, cols, b2):
        # per device: vals/cols (1, 1, th, kmax), b2 (1, tw)
        bloc = b2[0]
        contrib = vals[0, 0] * bloc[cols[0, 0]]      # (th, kmax)
        y = _combine2d(contrib.sum(-1), gq, combine, schedule)
        return y[None]                               # (1, th)

    shm = _shm2d(body, mesh2,
                 (P("mr", "mc", None, None), P("mr", "mc", None, None),
                  P("mc", None)), combine, nout=2)

    def run(ell_vals, ell_cols, b):
        v4 = ell_vals.reshape(gp, gq, th, kmax)
        c4 = ell_cols.reshape(gp, gq, th, kmax)
        pad = gq * tw - b.shape[0]
        bp = jnp.pad(b, (0, pad)) if pad else b
        out = shm(v4, c4, bp.reshape(gq, tw))
        if combine == "ring":
            out = out[:, 0]  # bitwise-identical across mesh columns
        return out.reshape(-1)[:m]

    prog = jax.jit(run)
    _prog_cache[key] = prog
    return prog


def _ell_local_mm(vals0, cols0, B, th, kmax, mode="slice"):
    """One shard's ELL contraction against MULTIPLE vectors: (th, nv)
    row sums of vals * B[cols, :].  Same W-slice gather as
    :func:`_ell_local`, but each gathered slice now feeds ``nv`` MACs —
    the gather-ISSUE cost (the random-SpMV bottleneck, docs/PERF.md
    roofline) is paid once per entry regardless of nv.  The slice
    width shrinks with nv so BYTES per gathered slice stay near the
    single-vector sweet spot (the round-2 W sweep showed gather cost
    growing with slice bytes past ~64 B); DR_TPU_SPMM_W overrides for
    on-chip sweeps.  ``mode="direct"`` is the off-TPU plain-gather
    resolution (see :func:`_ell_local`)."""
    nv = B.shape[1]
    if mode == "direct":
        return jnp.einsum("ekv,ek->ev", jnp.take(B, cols0, axis=0),
                          vals0)
    from ..utils.env import env_int
    W = env_int("DR_TPU_SPMM_W", max(2, _gather_w() // max(1, nv // 2)))
    pad = (-B.shape[0]) % W
    Bp = jnp.concatenate([B, jnp.zeros((pad, nv), B.dtype)]) if pad else B
    B3 = Bp.reshape(-1, W, nv)
    q, r = cols0 // W, cols0 % W

    def block(args):
        v, qs, rs = args
        gathered = B3[qs]                       # (ch, kmax, W, nv)
        oh = rs[..., None] == jax.lax.broadcasted_iota(
            jnp.int32, rs.shape + (W,), rs.ndim)
        picked = jnp.einsum("ekwv,ekw->ekv", gathered,
                            oh.astype(B.dtype))
        return jnp.einsum("ekv,ek->ev", picked, v)

    ch = max(1, _ELL_CHUNK // max(1, nv))  # bound the (ch,kmax,W,nv) temp
    if th > ch:
        nch, rem = divmod(th, ch)
        body_rows = nch * ch
        local = jax.lax.map(
            block, (vals0[:body_rows].reshape(nch, ch, kmax),
                    q[:body_rows].reshape(nch, ch, kmax),
                    r[:body_rows].reshape(nch, ch, kmax))).reshape(
                        body_rows, nv)
        if rem:
            tail = block((vals0[body_rows:], q[body_rows:],
                          r[body_rows:]))
            local = jnp.concatenate([local, tail])
    else:
        local = block((vals0, q, r))
    return local


def _bcsr_local_mm(bvals0, bcols0, B, seg_out):
    """One shard's BCSR contraction against multiple vectors: (seg_out,
    nv) from dense (8, 128) tiles — one 128-row slice gather of B per
    tile, MXU einsum carries the extra vectors."""
    BW = 128
    nv = B.shape[1]
    pad = (-B.shape[0]) % BW
    Bp = jnp.concatenate([B, jnp.zeros((pad, nv), B.dtype)]) if pad else B
    g = Bp.reshape(-1, BW, nv)[bcols0]        # (nbr, kb, BW, nv)
    local = jnp.einsum(
        "rkbc,rkcv->rbv", bvals0, g,
        preferred_element_type=jnp.promote_types(B.dtype, jnp.float32))
    return local.reshape(-1, nv)[:seg_out]


def _local_mm_parts(rt, a, th, kdim, bcsr, mode):
    """(local_fn, in_specs, device_args) for one shard's multi-vector
    contraction — shared by spmm and spmm_n.  local_fn closes over the
    INT width, never the matrix: the process-lifetime program cache
    must not pin device buffers through the body closure."""
    if bcsr:
        def local_of(vals, cols, B):
            return _bcsr_local_mm(vals[0], cols[0], B, th)
        in_specs = (P(rt.axis, None, None, None, None),
                    P(rt.axis, None, None), P())
        args = (a._bcsr_vals, a._bcsr_cols)
    else:
        def local_of(vals, cols, B, kdim=kdim):
            return _ell_local_mm(vals[0], cols[0], B, th, kdim,
                                 mode=mode)
        in_specs = (P(rt.axis, None, None),
                    P(rt.axis, None, None), P())
        args = (a._ell_vals, a._ell_cols)
    return local_of, in_specs, args


def _spmm_w_key():
    """Cache-key component for the SpMM gather width: the raw env
    override (not env_int, whose floor collapses unset and '1') plus
    the DR_TPU_GATHER_W value the default derives from — in-process W
    sweeps must rebuild, not reuse the first-traced program."""
    return (env_str("DR_TPU_SPMM_W"), _gather_w())


def _spmm2d_program(rt, grid, th, tw, kdim, bcsr, m, n, nv, mode):
    """SpMM on a 2-D tile grid: per-tile multi-vector contraction
    (:func:`_bcsr_local_mm` / :func:`_ell_local_mm`) against the tile's
    LOCAL B row-window, then partials combine over the mesh columns
    (psum / ring, ``DR_TPU_SPMV_COMBINE``) — the spmm analog of
    :func:`_gemv2d_bcsr_program`."""
    gp, gq = grid
    mesh2 = rt.mesh2d(grid)
    combine = _combine_mode()
    schedule = _pl.schedule_mode()
    key = ("spmm2d", pinned_id(mesh2), grid, th, tw, kdim, bcsr, m, n,
           nv, _spmm_w_key(), mode, combine,
           schedule if combine == "ring" else "")
    prog = _prog_cache.get(key)
    if prog is not None:
        return prog

    cspec = P("mr", "mc", None, None)
    if bcsr:
        def local_of(vals, cols, B2):
            return _bcsr_local_mm(vals[0, 0], cols[0, 0], B2[0], th)
        vspec = P("mr", "mc", None, None, None, None)
    else:
        def local_of(vals, cols, B2, kdim=kdim):
            return _ell_local_mm(vals[0, 0], cols[0, 0], B2[0], th,
                                 kdim, mode=mode)
        vspec = cspec

    def body(vals, cols, B2):
        y = _combine2d(local_of(vals, cols, B2), gq, combine, schedule)
        return y[None]                               # (1, th, nv)

    shm = _shm2d(body, mesh2, (vspec, cspec, P("mc", None, None)),
                 combine, nout=3)

    def run(vals, cols, B):
        shape = vals.shape
        v = vals.reshape(gp, gq, *shape[1:])
        c4 = cols.reshape(gp, gq, *cols.shape[1:])
        pad = gq * tw - B.shape[0]
        Bp = jnp.pad(B, ((0, pad), (0, 0))) if pad else B
        out = shm(v, c4, Bp.reshape(gq, tw, -1))
        if combine == "ring":
            out = out[:, 0]  # bitwise-identical across mesh columns
        return out.reshape(-1, B.shape[1])[:m]

    prog = jax.jit(run)
    _prog_cache[key] = prog
    return prog


def spmm(a: sparse_matrix, b) -> jax.Array:
    """A·B for a row-tiled sparse A and a DENSE (n, nv) right-hand side
    — the multi-vector SpMV.  Returns the (m, nv) product as an array.

    Beyond-parity surface (the reference ships only the single-vector
    ``gemv``, shp/algorithms/gemv.hpp:16-73) and the practical answer to
    the random-pattern SpMV roofline (docs/PERF.md): the per-entry
    gather-issue cost that bounds single-vector SpMV at ~2-4 GFLOP/s on
    this chip is paid ONCE per entry here and amortized over ``nv``
    right-hand sides, so aggregate throughput scales with nv until HBM
    bandwidth binds."""
    assert isinstance(a, sparse_matrix)
    m, n = a.shape
    B = b.to_array() if hasattr(b, "to_array") else jnp.asarray(b)
    assert B.ndim == 2 and B.shape[0] == n, \
        f"spmm needs a ({n}, nv) dense right-hand side, got {B.shape}"
    if a._vals is None:
        return jnp.zeros((m, B.shape[1]), a.dtype)
    rt = a.runtime
    nv = B.shape[1]
    fmt = _pick_format(a)      # "ring" has no spmm form: grouped path
    mode = _gather_mode(rt)
    bcsr = a.grid_shape[1] == 1 and fmt == "bcsr" and a.ensure_bcsr()
    if a.grid_shape[1] == 1 and fmt != "csr" and \
            (bcsr or a.ensure_ell()):
        th = a.tile_rows
        kdim = a._bcsr_kb if bcsr else a._ell_width
        key = ("spmm", pinned_id(rt.mesh), rt.axis, a.nshards, th,
               kdim, bcsr, nv, m, _spmm_w_key(), mode)
        local_of, in_specs, args = _local_mm_parts(rt, a, th, kdim,
                                                   bcsr, mode)
        prog = _prog_cache.get(key)
        if prog is None:
            shm = jax.shard_map(local_of, mesh=rt.mesh,
                                in_specs=in_specs,
                                out_specs=P(rt.axis, None))
            prog = jax.jit(shm)
            _prog_cache[key] = prog
        return prog(*args, B)[:m]
    if a.grid_shape[1] > 1 and fmt != "csr":
        bcsr2 = fmt == "bcsr" and a.ensure_bcsr()
        if bcsr2 or a.ensure_ell():
            if _combine_mode() == "ring":
                _pl.fire_ppermute(op="spmm")
            prog = _spmm2d_program(
                rt, a.grid_shape, a.tile_rows, a.tile_cols,
                a._bcsr_kb if bcsr2 else a._ell_width, bcsr2,
                m, n, nv, mode)
            args = (a._bcsr_vals, a._bcsr_cols) if bcsr2 \
                else (a._ell_vals, a._ell_cols)
            return prog(*args, B)
    # degenerate layouts: one flat gemv per column (correct everywhere)
    cols = [flat_gemv(a, B[:, j]) for j in range(nv)]
    return jnp.stack(cols, axis=1)


def spmm_n(a: sparse_matrix, b, iters: int) -> jax.Array:
    """``iters`` chained SpMMs in ONE jitted program (the gemv_n
    measurement analog): each round perturbs B by a scalar of the
    running product (times 1e-38) so XLA can neither hoist the
    contraction nor skip re-reading B.  Returns the last product.

    NOTE: unlike gemv_n there is no csr (segment-sum) fused-loop arm —
    a forced ``DR_TPU_SPMV_FORMAT=csr`` or ``ring`` runs the grouped
    ELL/BCSR program here, so a ladder measuring through spmm_n must
    gate its rungs on :func:`viable_formats` (csr/ring rungs would
    secretly remeasure the grouped arm)."""
    assert isinstance(a, sparse_matrix) and a.grid_shape[1] == 1
    m, n = a.shape
    B = b.to_array() if hasattr(b, "to_array") else jnp.asarray(b)
    assert B.ndim == 2 and B.shape[0] == n
    rt = a.runtime
    nv = B.shape[1]
    fmt = _pick_format(a)
    mode = _gather_mode(rt)
    bcsr = fmt == "bcsr" and a.ensure_bcsr()
    have_ell = bcsr or a.ensure_ell()  # side effects survive python -O
    assert have_ell, "spmm_n needs a grouped (BCSR/ELL) fast path"
    th = a.tile_rows
    kdim = a._bcsr_kb if bcsr else a._ell_width
    key = ("spmm_n", pinned_id(rt.mesh), rt.axis, a.nshards, th, kdim,
           bcsr, nv, m, int(iters), _spmm_w_key(), mode)
    local_of, in_specs, args = _local_mm_parts(rt, a, th, kdim, bcsr,
                                               mode)
    prog = _prog_cache.get(key)
    if prog is None:
        def body(vals, cols, B):
            # both local bodies accumulate in (at least) f32: the loop
            # carry must match that promoted dtype, not B's
            out_dt = jnp.promote_types(B.dtype, jnp.float32)

            def it(_, y):
                s = y[0, 0] * jnp.asarray(1e-38, B.dtype)
                return local_of(vals, cols, B + s).astype(out_dt)
            # seed the carry VARYING over the mesh axis (zeros alone are
            # replicated and shard_map's vma check rejects the loop)
            y0 = jnp.zeros((th, nv), out_dt) \
                + 0 * vals[(0,) * vals.ndim].astype(out_dt)
            return jax.lax.fori_loop(0, iters, it, y0)

        shm = jax.shard_map(body, mesh=rt.mesh, in_specs=in_specs,
                            out_specs=P(rt.axis, None))
        prog = jax.jit(shm)
        _prog_cache[key] = prog
    return prog(*args, B)[:m]


def gemv(c: distributed_vector, a: sparse_matrix, b) -> distributed_vector:
    """c += A·b (reference gemv semantics: accumulate into c,
    gemv.hpp:45-66).  Layout dispatch honors the container's
    autoselected format with the ``DR_TPU_SPMV_FORMAT`` override
    (:func:`_pick_format`); ``ring`` takes the pipelined rotating-b
    schedule (:func:`_gemv_ring_program`)."""
    # inside a deferred region gemv records as an ordered OPAQUE op
    # (round 9; like inclusive_scan): it dispatches through its own
    # program at flush, record order preserved — the surrounding
    # fusible runs stay fused instead of paying a full plan flush
    from ..plan import active as _plan_active
    p = _plan_active()
    if p is not None:
        # footprint (SPEC §21.2): gemv ACCUMULATES into c (c += A·b),
        # so c is read and written, never a coverage killer.  A plain
        # host array b is never written by queued ops; a view operand
        # resolves its base-container chain through the ONE
        # interference helper; anything unresolvable stays a FULL
        # BARRIER so no pass may eliminate or reorder its producers
        if isinstance(b, distributed_vector):
            reads, writes = (c, b), ((c, False),)
        elif isinstance(b, (np.ndarray, jnp.ndarray)) or np.isscalar(b):
            reads, writes = (c,), ((c, False),)
        else:
            from ..plan import interference as _interf
            conts = _interf.view_containers(b)
            if conts is not None:
                reads, writes = (c,) + conts, ((c, False),)
            else:
                reads = writes = None
        p.record_opaque("gemv", lambda: gemv(c, a, b),
                        reads=reads, writes=writes)
        return c
    assert isinstance(a, sparse_matrix)
    m, n = a.shape
    assert len(c) == m, "output length must equal matrix rows"
    b_arr = b.to_array() if hasattr(b, "to_array") else jnp.asarray(b)
    assert b_arr.shape == (n,)
    if a._vals is None:
        return c  # empty matrix: nothing to add
    rt = a.runtime
    fmt = _pick_format(a)
    if a.grid_shape[1] > 1:
        # 2-D tile grid: partial SpMV per tile + a cross-column combine
        ring_combine = _combine_mode() == "ring"
        if fmt == "bcsr" and a.ensure_bcsr():
            if ring_combine:
                _pl.fire_ppermute(op="gemv2d")
            prog = _gemv2d_bcsr_program(rt, a.grid_shape, a.tile_rows,
                                        a.tile_cols, a._bcsr_nbr,
                                        a._bcsr_kb, m, n)
            y = prog(a._bcsr_vals, a._bcsr_cols, b_arr)
        elif fmt != "csr" and a.ensure_ell():
            if ring_combine:
                _pl.fire_ppermute(op="gemv2d")
            prog = _gemv2d_ell_program(rt, a.grid_shape, a.tile_rows,
                                       a.tile_cols, a._ell_width, m, n)
            y = prog(a._ell_vals, a._ell_cols, b_arr)
        else:
            y = flat_gemv(a, b_arr)
        c.assign_array(c.to_array() + y.astype(c.dtype))
        return c
    # shard r of c must hold exactly tile r's rows — which also requires
    # the uniform ceil layout (an uneven distribution can match nshards
    # and capacity while owning different row ranges)
    fast = (isinstance(c, distributed_vector)
            and uniform_layout(c.layout)
            and c.nshards == a.nshards and c.segment_size == a.tile_rows
            and c.runtime is rt)
    if fast:
        if fmt == "ring" and a.ensure_ring():
            # rotating-b ring schedule: compute overlaps the transfers
            _pl.fire_ppermute(op="gemv")
            prog = _gemv_ring_program(rt, a.nshards, a.tile_rows,
                                      a._ring_kr, a._ring_bw,
                                      c.segment_size,
                                      c.halo_bounds.prev,
                                      _gather_mode(rt),
                                      _pl.schedule_mode(), None, 1)
            c._data = prog(c._data, a._ring_vals, a._ring_cols, b_arr)
            return c
        if fmt == "bcsr" and a.ensure_bcsr():
            # block-structured: dense-tile MXU path, one gather per tile
            prog = _gemv_bcsr_program(rt.mesh, rt.axis, a.nshards,
                                      a._bcsr_nbr,
                                      a._bcsr_kb, c.segment_size,
                                      c.halo_bounds.prev)
            c._data = prog(c._data, a._bcsr_vals, a._bcsr_cols, b_arr)
            return c
        if fmt != "csr" and a.ensure_ell():
            prog = _gemv_ell_program(rt.mesh, rt.axis, a.nshards,
                                     a.tile_rows, a._ell_width,
                                     c.segment_size, c.halo_bounds.prev,
                                     _gather_mode(rt))
            c._data = prog(c._data, a._ell_vals, a._ell_cols, b_arr)
            return c
        prog = _gemv_program(rt.mesh, rt.axis, a.nshards, a.tile_rows,
                             a._vals.shape[1], m, c.segment_size,
                             c.block_width, c.halo_bounds.prev)
        c._data = prog(c._data, a._vals, a._rows, a._cols, b_arr)
        return c
    # fallback: global scatter-add through the logical array
    y = flat_gemv(a, b_arr)
    arr = c.to_array() + y
    c.assign_array(arr)
    return c


def flat_gemv(a: sparse_matrix, b_arr) -> jax.Array:
    """A·b as a logical (m,) array (no output container needed).

    Handles any tile grid: per-tile local indices get their tile's
    row/col offsets back; pad entries carry value 0 so clamped
    out-of-range gathers/scatters contribute nothing."""
    if a._vals is None:
        return jnp.zeros((a.shape[0],), a.dtype)
    gp, gq = a.grid_shape
    th, tw = a.tile_rows, a.tile_cols
    t = jnp.arange(a.nshards, dtype=jnp.int32)[:, None]
    rows_g = (a._rows + (t // gq) * th).reshape(-1)
    cols_g = (a._cols + (t % gq) * tw).reshape(-1)
    contrib = (a._vals.reshape(-1)
               * jnp.take(jnp.asarray(b_arr), cols_g, mode="clip"))
    return jnp.zeros((a.shape[0],), a.dtype).at[rows_g].add(contrib)


def gemm(a: dense_matrix, b: dense_matrix,
         out: dense_matrix = None) -> dense_matrix:
    """Dense C = A·B on 2-D tiled matrices (MXU path)."""
    m, k = a.shape
    k2, n = b.shape
    assert k == k2
    if out is None:
        out = dense_matrix((m, n), a.dtype, runtime=a.runtime)
    key = ("gemm", pinned_id(a.runtime.mesh), a.shape, b.shape, str(a.dtype))
    prog = _prog_cache.get(key)
    if prog is None:
        prog = jax.jit(lambda x, y: jnp.matmul(
            x, y, preferred_element_type=jnp.float32))
        _prog_cache[key] = prog
    out.assign_array(prog(a.to_array(), b.to_array()).astype(out.dtype))
    return out
