"""2-D stencil (heat equation) over a tiled dense_matrix.

The BASELINE.json config-4 workload: "2D mdspan heat-equation stencil,
tiled segments on a 2D TPU mesh".  The reference only documents the
mdspan surface (SURVEY.md §2.6; the not-built example
``examples/mhp/transpose-cpu.cpp``); on TPU the idiomatic form is shifted
slices of ONE 2-D sharded array under jit — GSPMD materializes the
inter-tile halo exchanges along both mesh axes automatically, so the
"ghost cell" machinery is the compiler's job, not the container's.

``stencil2d_iterate`` runs all steps device-side via lax.fori_loop with
double buffering, like its 1-D sibling.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ._common import double_buffered_loop
from .elementwise import _prog_cache
from ..core.pinning import pinned_id
from ..utils import spmd_guard
from ..containers.dense_matrix import dense_matrix

__all__ = ["stencil2d_transform", "stencil2d_iterate",
           "stencil2d_iterate_blocked", "stencil2d_n",
           "heat_step_weights"]


def heat_step_weights(alpha: float = 0.25):
    """Classic 5-point heat kernel: u += alpha * laplacian(u)."""
    return [[0.0, alpha, 0.0],
            [alpha, 1.0 - 4.0 * alpha, alpha],
            [0.0, alpha, 0.0]]


def _build_step(m, n, mm, nn, weights, dtype):
    w = np.asarray(weights, dtype=np.float64)
    kh, kw = w.shape
    assert kh % 2 == 1 and kw % 2 == 1
    rh, rw = kh // 2, kw // 2

    def step(cur, out):
        u = cur[:m, :n]
        acc = jnp.zeros((m - 2 * rh, n - 2 * rw), dtype)
        for di in range(kh):
            for dj in range(kw):
                wij = float(w[di, dj])
                if wij == 0.0:
                    continue
                acc = acc + wij * u[di:m - 2 * rh + di, dj:n - 2 * rw + dj]
        return out.at[rh:m - rh, rw:n - rw].set(acc)

    return step


def _fold_ops(mat: dense_matrix):
    """The container's folding permutation (dense_matrix.fold_ops), with
    the matrix's sharding constrained on the fold result so the stored
    layout stays 2-D block-sharded inside the program."""
    from ..containers.dense_matrix import fold_ops
    unfold, fold = fold_ops(mat._grid, mat._slots, mat._tshape, *mat.shape)

    def fold_sharded(lg):
        return lax.with_sharding_constraint(fold(lg), mat._sharding)

    return unfold, fold_sharded


def stencil2d_transform(in_mat: dense_matrix, out_mat: dense_matrix,
                        weights: Sequence[Sequence[float]]) -> None:
    """One interior stencil step: out[i,j] = sum w[di,dj]*in[i+di,j+dj].

    Edges (positions without a full neighborhood) keep out_mat's values,
    matching the 1-D interior contract."""
    assert in_mat.shape == out_mat.shape and in_mat.layout == out_mat.layout
    m, n = in_mat.shape
    mm, nn = in_mat._data.shape
    key = ("st2", pinned_id(in_mat.runtime.mesh), in_mat.layout,
           tuple(map(tuple, np.asarray(weights))), str(in_mat.dtype))
    prog = _prog_cache.get(key)
    if prog is None:
        if in_mat.is_block:
            step = _build_step(m, n, mm, nn, weights, in_mat.dtype)
        else:
            # cyclic storage: compute on the logical array, re-fold the
            # result — one unfold/fold pair per program, not per step
            lstep = _build_step(m, n, m, n, weights, in_mat.dtype)
            unfold, fold = _fold_ops(in_mat)

            def step(din, dout):
                return fold(lstep(unfold(din), unfold(dout)))
        prog = jax.jit(step, donate_argnums=1)
        _prog_cache[key] = prog
    out_mat._data = prog(in_mat._data, out_mat._data)


def stencil2d_iterate_blocked(a: dense_matrix, weights, steps: int, *,
                              time_block: int = 16, band: int = None,
                              interpret=None) -> dense_matrix:
    """Temporally-blocked 2-D stencil (ops/stencil2d_pallas.py): T steps
    fused per HBM pass over VMEM-resident row bands.

    Contract: 3x3 weights, frozen (Dirichlet) edges — equivalent to
    ``stencil2d_iterate`` when both its buffers share edge values (the
    usual both-from-src setup).  Requires the matrix on a single device
    (the bench shape); multi-tile grids use the XLA path.
    """
    from ..ops import stencil2d_pallas
    assert np.asarray(weights).shape == (3, 3), "blocked path is 3x3"
    m, n = a.shape
    assert a.grid_shape == (1, 1) and a.is_block, \
        "blocked 2-D stencil runs on a single-tile matrix"
    if interpret is None:
        interpret = a.runtime.devices[0].platform != "tpu"
    pad = time_block  # covers the remainder block too (rest < time_block)
    key = ("st2blk", pinned_id(a.runtime.mesh), a.layout, m, n,
           tuple(map(tuple, np.asarray(weights))), time_block, band,
           bool(interpret), str(a.dtype))
    progs = _prog_cache.setdefault(key, {})

    def make(tsteps):
        def run(xp):
            return stencil2d_pallas.blocked_stencil2d_padded(
                xp, m, weights, tsteps, pad, band=band,
                interpret=interpret)
        return jax.jit(run)

    if "pad" not in progs:
        progs["pad"] = jax.jit(
            lambda x: jnp.pad(x, ((pad, pad), (0, 0))))
        progs["unpad"] = jax.jit(lambda xp: xp[pad:pad + m, :])
        spmd_guard.note_compile(key + ("pad",))
        spmd_guard.note_compile(key + ("unpad",))
    nfull, rest = divmod(steps, time_block)
    if nfull and time_block not in progs:
        progs[time_block] = make(time_block)
        spmd_guard.note_compile(key + (time_block,))
    if rest and rest not in progs:
        progs[rest] = make(rest)
        spmd_guard.note_compile(key + (rest,))
    # pad ONCE and keep the padded layout across blocks: pad-row contents
    # are irrelevant (frozen edges stop the dependency cone), so chained
    # passes pay no re-pad traffic
    data = progs["pad"](a._data)
    for _ in range(nfull):
        data = progs[time_block](data)
    if rest:
        data = progs[rest](data)
    a._data = progs["unpad"](data)
    return a


def stencil2d_n(a: dense_matrix, weights, iters: int, *,
                time_block: int = 16) -> dense_matrix:
    """``iters`` full time-blocks of the blocked 2-D stencil in ONE
    jitted program (the 2-D member of the ``*_n`` measurement family,
    docs/PERF.md "measurement lesson"): pad, ``lax.fori_loop`` over the
    Pallas block kernel, unpad — so per-block device time excludes the
    tunneled per-dispatch constant entirely.  Applies exactly
    ``iters * time_block`` steps with the same frozen-edge contract as
    :func:`stencil2d_iterate_blocked`."""
    from ..ops import stencil2d_pallas
    assert np.asarray(weights).shape == (3, 3), "blocked path is 3x3"
    m, n = a.shape
    assert a.grid_shape == (1, 1) and a.is_block, \
        "blocked 2-D stencil runs on a single-tile matrix"
    interpret = a.runtime.devices[0].platform != "tpu"
    pad = time_block
    key = ("st2n", pinned_id(a.runtime.mesh), a.layout, m, n,
           tuple(map(tuple, np.asarray(weights))), time_block,
           bool(interpret), str(a.dtype), int(iters))
    prog = _prog_cache.get(key)
    if prog is None:
        def run(x):
            xp = jnp.pad(x, ((pad, pad), (0, 0)))

            def body(_, d):
                return stencil2d_pallas.blocked_stencil2d_padded(
                    d, m, weights, time_block, pad, interpret=interpret)

            xp = jax.lax.fori_loop(0, iters, body, xp)
            return xp[pad:pad + m, :]

        prog = jax.jit(run)
        _prog_cache[key] = prog
    a._data = prog(a._data)
    return a


def stencil2d_iterate(a: dense_matrix, b: dense_matrix,
                      weights, steps: int) -> dense_matrix:
    """``steps`` fused 2-D stencil steps, double-buffered in one program."""
    assert a.shape == b.shape and a.layout == b.layout
    m, n = a.shape
    mm, nn = a._data.shape
    key = ("st2it", pinned_id(a.runtime.mesh), a.layout,
           tuple(map(tuple, np.asarray(weights))), steps, str(a.dtype))
    prog = _prog_cache.get(key)
    if prog is None:
        if a.is_block:
            step = _build_step(m, n, mm, nn, weights, a.dtype)

            def loop(x, y):
                return double_buffered_loop(step, steps, x, y)
        else:
            # cyclic storage: unfold once, iterate on the logical
            # array, fold both buffers back at the end
            lstep = _build_step(m, n, m, n, weights, a.dtype)
            unfold, fold = _fold_ops(a)

            def loop(x, y):
                fin, oth = double_buffered_loop(
                    lstep, steps, unfold(x), unfold(y))
                return fold(fin), fold(oth)

        prog = jax.jit(loop, donate_argnums=(0, 1))
        _prog_cache[key] = prog
    fin, other = prog(a._data, b._data)
    a._data, b._data = fin, other
    return a
