"""Distributed reductions: ``reduce`` / ``transform_reduce`` / ``dot``.

Reference behavior (``mhp/algorithms/cpu_algorithms.hpp:103-140``;
``shp/algorithms/reduce.hpp:42-124``): per-segment local reduction, then a
gather of partials and a host-side fold — with the result valid only on the
root rank (a documented asymmetry).  TPU re-design: one jitted program —
masked per-shard reduction fused with the view pipeline, then ``psum``-style
cross-shard combination by XLA — and the result is a host scalar valid
everywhere (single controller), removing the root-only asymmetry.

``transform_reduce`` is the spec'd-but-unimplemented reference algorithm
(``doc/spec/source/algorithms/transform_reduce.rst``; expressed in code as
``transform_view | reduce``, ``examples/shp/dot_product.cpp:11-18``) and the
driver metric workload — so it gets a first-class fused implementation.
"""

from __future__ import annotations

import operator
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from ._common import owned_window_mask
from .elementwise import (_apply_chain_ops, _chain_scalars, _op_key,
                          _plan_active, _prog_cache, _resolve,
                          _traced_op_key)
from ..views import views as _v

__all__ = ["reduce", "transform_reduce", "dot",
           "reduce_async", "transform_reduce_async", "dot_async", "dot_n",
           "dot_kernel_eligible"]


# known monoids: (jnp vector-reduce, identity)
_MONOIDS = {
    "add": (jnp.sum, 0),
    "mul": (jnp.prod, 1),
    "min": (jnp.min, None),
    "max": (jnp.max, None),
}


def _classify_op(op) -> Optional[str]:
    if op is None or op is operator.add or op is jnp.add:
        return "add"
    if op is operator.mul or op is jnp.multiply:
        return "mul"
    if op is min or op is jnp.minimum:
        return "min"
    if op is max or op is jnp.maximum:
        return "max"
    return None


def _identity_for(kind: str, dtype):
    if kind == "add":
        return jnp.zeros((), dtype)
    if kind == "mul":
        return jnp.ones((), dtype)
    if kind == "min":
        return jnp.array(jnp.inf if jnp.issubdtype(dtype, jnp.floating)
                         else jnp.iinfo(dtype).max, dtype)
    if kind == "max":
        return jnp.array(-jnp.inf if jnp.issubdtype(dtype, jnp.floating)
                         else jnp.iinfo(dtype).min, dtype)
    raise ValueError(kind)


def _fused_reduce_program(chains, kind, zip_op=None):
    """Masked fused reduce over padded shard arrays — zero reshaping,
    zero gather: XLA lowers the cross-shard combine to an all-reduce.
    Multi-chain (zip) inputs are combined elementwise by ``zip_op`` before
    the reduction, so ``dot`` reads each input exactly once.

    BoundOp chain/zip ops feed their scalars as TRACED trailing operands
    (call through :func:`_call_fused_reduce`), so a coefficient stream
    through a view pipeline reuses one compiled program."""
    key = ("red", tuple(c.key for c in chains), kind,
           _traced_op_key(zip_op) if zip_op is not None else None)
    prog = _prog_cache.get(key)
    if prog is not None:
        return prog
    c0 = chains[0]
    layout, off, n = c0.cont.layout, c0.off, c0.n
    vec_reduce, _ = _MONOIDS[kind]
    all_ops = tuple(c.ops for c in chains)
    nchain = sum(len(o.scalars) for ops in all_ops for o in ops
                 if isinstance(o, _v.BoundOp))
    nds = len(chains)

    def body(*args):
        datas = args[:nds]
        sc_iter = iter(args[nds:nds + nchain])
        zip_scalars = args[nds + nchain:]
        vals = [_apply_chain_ops(d, ops, sc_iter)
                for d, ops in zip(datas, all_ops)]
        if zip_op is None:
            v = vals[0]
        elif isinstance(zip_op, _v.BoundOp):
            v = zip_op.op(*vals, *zip_scalars)
        else:
            v = zip_op(*vals)
        mask, _gid = owned_window_mask(layout, off, n)
        ident = _identity_for(kind, v.dtype)
        return vec_reduce(jnp.where(mask, v, ident))

    prog = jax.jit(body)
    _prog_cache[key] = prog
    return prog


_KIND_TO_SEGRED = {"add": "sum", "mul": "prod", "min": "min",
                   "max": "max"}


def _storage_dtype(dtype):
    """The dtype a declared container actually STORES: 64-bit declares
    narrow to their 32-bit counterparts when x64 is off."""
    dt = jnp.dtype(dtype)
    if not jax.config.jax_enable_x64 and dt.itemsize == 8 \
            and dt.kind in "iuf":
        return jnp.dtype(dt.name.replace("64", "32"))
    return dt


def _reduce_kernel_decision(chains, kind, zip_op):
    """The ``segred`` kernel-arm decision (docs/SPEC.md §22) for the
    fused monoid reduce: the masked-compare Pallas kernel (one segment)
    replaces the XLA vector reduce for PLAIN single-container chains
    whose monoid is combine-order-free at the bit level — min/max over
    any dtype, add/mul over exact (integer/bool) dtypes; float
    accumulation is order-sensitive and stays on XLA.  View-chain ops
    and zip combines can change the traced dtype, so they keep the XLA
    route too."""
    from ..ops import kernels, segred_pallas
    from ._common import uniform_layout
    if zip_op is not None or len(chains) != 1 or chains[0].ops:
        return kernels.NO_KERNEL
    c0 = chains[0]
    if not uniform_layout(c0.cont.layout):
        return kernels.NO_KERNEL  # uneven layouts carry size tuples
    nshards, seg, prev, nxt, total_n = c0.cont.layout
    width = prev + seg + nxt
    dt = _storage_dtype(c0.cont.dtype)
    kern = kernels.use_kernel(
        "segred", runtime=c0.cont.runtime,
        eligible=segred_pallas.eligible(
            width, 1, ((dt, _KIND_TO_SEGRED[kind]),)))
    if kern.use and not kern.interpret and dt.itemsize == 8:
        return kernels.NO_KERNEL  # wide columns are interpret-only
    return kern


def _kernel_reduce_program(chain, kind, kern):
    """The segred-arm twin of :func:`_fused_reduce_program`: one
    shard_map program — per-shard masked kernel reduce (one segment) +
    one all_gather and the same monoid fold over the p partials.  Exact
    for every eligible monoid (see :func:`_reduce_kernel_decision`), so
    bit-identical to the XLA route."""
    from jax import lax
    from jax.sharding import PartitionSpec as P
    from ..ops import segred_pallas
    c0 = chain
    key = ("redk", c0.key, kind, tuple(kern))
    prog = _prog_cache.get(key)
    if prog is not None:
        return prog
    rt = c0.cont.runtime
    layout, off, n = c0.cont.layout, c0.off, c0.n
    vec_reduce, _ = _MONOIDS[kind]
    op = _KIND_TO_SEGRED[kind]

    def body(blk):
        r = lax.axis_index(rt.axis)
        mask, _gid = owned_window_mask(layout, off, n)
        v = blk[0]
        ident = _identity_for(kind, v.dtype)
        masked = jnp.where(mask[r], v, ident)
        seg0 = jnp.zeros((v.shape[0],), jnp.int32)
        local = segred_pallas.segmented(
            seg0, 1, ((masked, op),), interpret=kern.interpret)[0][0]
        totals = lax.all_gather(local, rt.axis)      # (p,)
        return vec_reduce(totals)

    # check_vma=False: every shard folds the same gathered totals (the
    # _custom_reduce_program precedent), and shard_map has no
    # replication rule for pallas_call anyway
    shm = jax.shard_map(body, mesh=rt.mesh,
                        in_specs=(P(rt.axis, None),),
                        out_specs=P(), check_vma=False)
    prog = jax.jit(shm)
    _prog_cache[key] = prog
    return prog


def _call_fused_reduce(chains, kind, zip_op=None):
    """Build + invoke the fused reduce with the BoundOp scalar tail."""
    kern = _reduce_kernel_decision(chains, kind, zip_op)
    if kern.use:
        return _kernel_reduce_program(chains[0], kind, kern)(
            chains[0].cont._data)
    scal = _chain_scalars(chains)
    if isinstance(zip_op, _v.BoundOp):
        scal = scal + list(zip_op.scalars)
    svals = [jnp.asarray(s) for s in scal]
    return _fused_reduce_program(chains, kind, zip_op)(
        *[c.cont._data for c in chains], *svals)


def _zip_reduce_chains(r):
    """(chains, zip_op) when ``r`` is a transform over a zip of aligned
    same-window container chains — the dot-product pipeline shape
    (``examples/shp/dot_product.cpp:11-18``) — else None."""
    if not (isinstance(r, _v.transform) and isinstance(r.base, _v.zip_view)):
        return None
    chains = _resolve(r.base)
    if not chains:
        return None
    c0 = chains[0]
    if not all(c.cont.layout == c0.cont.layout and c.off == c0.off
               and c.n == c0.n for c in chains[1:]):
        return None
    return chains, r.op


def _custom_reduce_program(mesh, axis, layout, op, ops, window):
    """Fused reduce for UNCLASSIFIED (identityless) ops — round 5; this
    shape used to materialize silently.  The scan family's identityless
    machinery, without building the scan array: each shard folds its
    valid cells with ``lax.associative_scan`` (``std::reduce`` already
    requires associativity) and reads its REAL total at
    ``local[valid-1]``; the cross-shard fold walks the gathered totals
    skipping empty shards, seeded at the statically-known first
    nonempty shard — no identity element is ever needed.  View-chain
    ``ops`` fuse like everywhere else; ``window`` runs in window
    coordinates (the sort family's static geometry)."""
    from ._common import (effective_sizes, first_nonempty,
                          identityless_fold, window_geometry,
                          working_geometry)
    from ..core.pinning import pinned_id
    key = ("gredd", pinned_id(mesh), axis, layout, _op_key(op),
           tuple(_traced_op_key(f) for f in ops), window)
    prog = _prog_cache.get(key)
    if prog is not None:
        return prog
    from jax import lax
    from jax.sharding import PartitionSpec as P
    if window is None:
        nshards, S, cap, prev, nxt, n, starts, sizes = \
            working_geometry(layout)
        wstart = None
        # working_geometry reports NOMINAL widths for uniform ceil
        # layouts; the fold's skip predicate needs TRUE emptiness
        # (_common.effective_sizes docstring has the fuzz story)
        sizes = effective_sizes(starts, sizes, n)
    else:
        # window geometries are already clipped exactly
        nshards, S, cap, prev, nxt, n, starts, sizes, wstart = \
            window_geometry(layout, *window)
        width = prev + cap + nxt
        woff_c = jnp.asarray(wstart, jnp.int32)
    starts_c = jnp.asarray(starts, jnp.int32)
    sizes_c = jnp.asarray(sizes, jnp.int32)
    first_nz = first_nonempty(sizes)
    # BoundOp chain ops feed their scalars as TRACED trailing operands
    # (the _fused_reduce_program convention) so a streaming coefficient
    # reuses ONE compiled program instead of re-jitting per value
    nsc = sum(len(o.scalars) for o in ops if isinstance(o, _v.BoundOp))

    def body(blk, *scalars):
        r_ = lax.axis_index(axis)
        if window is None:
            x = blk[0, prev:prev + S]
        else:
            idx = jnp.clip(prev + woff_c[r_] + jnp.arange(S), 0,
                           width - 1)
            x = jnp.take(blk[0], idx)
        x = _apply_chain_ops(x, ops, iter(scalars))
        local = lax.associative_scan(op, x)
        nvalid = jnp.minimum(sizes_c[r_],
                             jnp.clip(n - starts_c[r_], 0, S))
        mine = local[jnp.clip(nvalid - 1, 0, S - 1)]
        totals = lax.all_gather(mine, axis)  # (nshards,)
        return identityless_fold(op, totals, sizes_c, nshards, first_nz)

    # check_vma=False: every shard folds the same all_gather'ed totals
    # in the same order, so the P() output IS replicated — the static
    # checker just cannot see through the fori_loop to prove it
    shm = jax.shard_map(body, mesh=mesh,
                        in_specs=(P(axis, None),) + (P(),) * nsc,
                        out_specs=P(), check_vma=False)
    prog = jax.jit(shm)
    _prog_cache[key] = prog
    return prog


def reduce_async(r, op: Callable = None):
    """Like :func:`reduce` but returns the DEVICE scalar without waiting —
    the analog of the reference's oneDPL ``reduce_async`` path
    (``shp/algorithms/reduce.hpp:42-88``): the reduction is enqueued and
    the caller folds/syncs when ready (``jax.block_until_ready`` or any
    host conversion acts as the future's ``.get()``)."""
    kind = _classify_op(op)
    chains = zip_op = None
    if kind is not None:
        chains = _resolve(r) if not isinstance(r, _v.zip_view) else None
        if chains is not None and len(chains) != 1:
            chains = None
        if chains is None:
            # transform-over-zip (the dot pipeline): fuse the zip combine
            # into the same single-pass program
            zipped = _zip_reduce_chains(r)
            if zipped is not None:
                chains, zip_op = zipped
    if chains is not None:
        p = _plan_active()
        if p is not None:
            # deferred: the reduction rides the plan's carry; callers
            # get a lazy PlanScalar resolving on host materialization
            return p.record_reduce(chains, kind, zip_op)
        val = _call_fused_reduce(chains, kind, zip_op)
        return val
    if kind is None and op is not None:
        # UNCLASSIFIED custom op over a single distributed chain:
        # native identityless program (round 5 — used to materialize
        # silently).  Zip shapes and host inputs keep the fallback.
        gchains = _resolve(r) if not isinstance(r, _v.zip_view) else None
        if gchains is not None and len(gchains) == 1 \
                and gchains[0].n > 0:
            # identityless custom-op reduce keeps its own shard_map
            # machinery; it does not fuse into a deferred run
            from ..plan import barrier as _plan_barrier
            _plan_barrier("custom-op reduce")
            c = gchains[0]
            svals = [jnp.asarray(s) for s in _chain_scalars([c])]
            return _custom_reduce_program(
                c.cont.runtime.mesh, c.cont.runtime.axis,
                c.cont.layout, op, tuple(c.ops),
                None if (c.off == 0 and c.n == len(c.cont))
                else (c.off, c.n))(c.cont._data, *svals)
        if hasattr(r, "to_array") and not (gchains is not None
                                           and len(gchains) == 1):
            # custom-op reduce over a MULTI-component distributed range
            # (e.g. transform over zip): the one distributed reduce
            # shape still materializing — announce the cliff (ADVICE
            # r5; empty single chains fall through silently, their
            # materialize is trivial)
            from ..utils.fallback import warn_fallback
            warn_fallback("reduce", "multi-component custom-op range")
    arr = r.to_array() if hasattr(r, "to_array") else jnp.asarray(r)
    assert not isinstance(arr, tuple), \
        "reduce over a zip needs a transform to combine components"
    if kind is not None:
        val = _MONOIDS[kind][0](arr)
    else:
        val = _generic_reduce(arr, op)
    return val


def reduce(r, init=None, op: Callable = None):
    """Collective reduction; returns a host scalar (valid on all ranks).
    Inside ``dr_tpu.deferred()`` it returns a lazy ``PlanScalar``
    instead: the reduction rides the fused program's carry and resolves
    (flushing the plan) on ``float()``/``item()``."""
    val = reduce_async(r, op)
    from ..plan import PlanScalar
    if isinstance(val, PlanScalar):
        if init is not None:
            pyop = op if op is not None else operator.add
            return val.with_post(lambda v: pyop(init, v))
        return val
    if init is not None:
        pyop = op if op is not None else operator.add
        return pyop(init, val.item())
    return val.item()


def _generic_reduce(arr, op):
    key = ("gred", arr.shape, str(arr.dtype), _op_key(op))
    prog = _prog_cache.get(key)
    if prog is None:
        def body(x):
            # tree fold via associative_scan keeps O(log n) depth
            return jax.lax.associative_scan(
                lambda a, b: op(a, b), x)[-1]
        prog = jax.jit(body)
        _prog_cache[key] = prog
    return prog(arr)


def _identity(x):
    return x


def _multiply2(x, y):
    return x * y


def transform_reduce(r, init=None, reduce_op=None, transform_op=None,
                     transform_args=()):
    """Spec'd transform_reduce: reduce(transform(r)).  Fuses into the same
    single program as reduce().  ``transform_args`` bind trailing TRACED
    scalars to ``transform_op`` (views.BoundOp): a per-step coefficient
    (e.g. sum((x - mu)**2) with a streaming mu) reuses one compiled
    program."""
    if transform_op is None:
        transform_op = _identity
    return reduce(_v.transform(r, transform_op, *transform_args),
                  init, reduce_op)


def transform_reduce_async(r, reduce_op=None, transform_op=None,
                           transform_args=()):
    """Async :func:`transform_reduce`: returns the device scalar."""
    if transform_op is None:
        transform_op = _identity
    return reduce_async(_v.transform(r, transform_op, *transform_args),
                        reduce_op)


def dot(a, b, init=None):
    """Dot product — the reference's headline SHP example
    (``examples/shp/dot_product.cpp:11-18``): zip | transform(*) | reduce."""
    z = _v.zip_view(a, b)
    return reduce(_v.transform(z, _multiply2), init, operator.add)


def dot_async(a, b):
    """Async dot product: the fused program's device scalar, no host sync."""
    z = _v.zip_view(a, b)
    return reduce_async(_v.transform(z, _multiply2), operator.add)


def _dot_kernel_platform_ok(rt) -> bool:
    """Mosaic compiles for TPU only; tests monkeypatch this together
    with an interpret-mode ``chunked_dot`` to cover the kernel path on
    the CPU mesh."""
    from ._common import on_tpu
    return on_tpu(rt)


def _dot_n_chains(a, b):
    chains = _resolve(_v.zip_view(a, b))
    assert chains is not None and len(chains) == 2, \
        "dot_n needs two aligned container chains"
    c0, c1 = chains
    assert c0.cont.layout == c1.cont.layout and c0.off == c1.off \
        and c0.n == c1.n
    assert not c0.ops and not c1.ops, "dot_n takes plain containers"
    return c0, c1


def _dot_kernel_eligible_chains(c0, c1) -> bool:
    from ..ops import reduce_pallas, scan_pallas
    from ._common import f32_accumulable
    nshards, seg, prev, nxt, total_n = c0.cont.layout
    return (reduce_pallas.supported()
            and reduce_pallas.use_dot_kernel()
            and _dot_kernel_platform_ok(c0.cont.runtime)
            and f32_accumulable(c0.cont.dtype)
            and c0.cont.dtype == c1.cont.dtype
            and prev == 0 and nxt == 0 and c0.off == 0
            and c0.n == total_n and nshards * seg == total_n
            and scan_pallas.pick_chunk(seg) is not None)


def dot_kernel_eligible(a, b) -> bool:
    """Whether ``dot_n(a, b)`` would actually take the Pallas streamed
    kernel (the TPU default; DR_TPU_DOT_IMPL=xla opts out) — the FULL
    gate, so callers
    (bench.py's ``dot_impl`` tag) report what ran, not what was asked
    for."""
    return _dot_kernel_eligible_chains(*_dot_n_chains(a, b))


def dot_n(a, b, iters: int):
    """``iters`` chained dot products in ONE jitted program — the
    measurement analog of ``span_halo.exchange_n`` (parallel/halo.py):
    per-op device time excludes the tunneled per-dispatch overhead.

    Each round perturbs one operand by ``carry * 1e-38`` so the WHOLE
    fused multiply+reduce depends on the loop carry — XLA can neither
    hoist the multiply out of the loop nor skip re-reading the inputs,
    keeping per-iteration HBM traffic exactly a dot's (one pass over
    both arrays, no intermediates).  The returned value differs from
    ``dot(a, b)`` by O(1e-38 * |dot| * sum(a)) — negligible.  Returns
    the final device scalar."""
    from ..plan import flush_reads
    flush_reads("dot_n")  # reads _data directly: pending writes first
    c0, c1 = _dot_n_chains(a, b)
    layout, off, n = c0.cont.layout, c0.off, c0.n
    nshards, seg, prev, nxt, total_n = layout
    # Pallas chunked-dot path (TPU default; DR_TPU_DOT_IMPL=xla opts
    # out): per-shard
    # streamed multiply+reduce + psum, salt folded inside the kernel
    from ..ops import reduce_pallas, scan_pallas
    rt = c0.cont.runtime
    use_kern = _dot_kernel_eligible_chains(c0, c1)
    key = ("dot_n", c0.key, c1.key, int(iters), use_kern,
           scan_pallas.chunk_cap() if use_kern else None)
    prog = _prog_cache.get(key)
    if prog is None:
        if use_kern:
            from jax.sharding import PartitionSpec as P

            def body(x_blk, y_blk):  # one shard: (1, seg)
                def it(_, s):
                    local = reduce_pallas.chunked_dot(
                        x_blk[0], y_blk[0], salt=s * 1e-38)
                    return jax.lax.psum(local, rt.axis)

                return jax.lax.fori_loop(0, iters, it,
                                         jnp.zeros((), jnp.float32))

            shm = jax.shard_map(body, mesh=rt.mesh,
                                in_specs=(P(rt.axis, None),
                                          P(rt.axis, None)),
                                out_specs=P(), check_vma=False)
            prog = jax.jit(shm)
        else:
            def many(d0, d1):
                mask, _gid = owned_window_mask(layout, off, n)

                def it(_, s):
                    prod = d0 * (d1 + s * jnp.asarray(1e-38, d1.dtype))
                    return jnp.sum(jnp.where(mask, prod, 0))

                return jax.lax.fori_loop(0, iters, it,
                                         jnp.zeros((), d0.dtype))

            prog = jax.jit(many)
        _prog_cache[key] = prog
    return prog(c0.cont._data, c1.cont._data)
