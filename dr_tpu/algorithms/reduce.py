"""Distributed reductions: ``reduce`` / ``transform_reduce`` / ``dot``.

Reference behavior (``mhp/algorithms/cpu_algorithms.hpp:103-140``;
``shp/algorithms/reduce.hpp:42-124``): per-segment local reduction, then a
gather of partials and a host-side fold — with the result valid only on the
root rank (a documented asymmetry).  TPU re-design: one jitted program —
masked per-shard reduction fused with the view pipeline, then ``psum``-style
cross-shard combination by XLA — and the result is a host scalar valid
everywhere (single controller), removing the root-only asymmetry.

``transform_reduce`` is the spec'd-but-unimplemented reference algorithm
(``doc/spec/source/algorithms/transform_reduce.rst``; expressed in code as
``transform_view | reduce``, ``examples/shp/dot_product.cpp:11-18``) and the
driver metric workload — so it gets a first-class fused implementation.
"""

from __future__ import annotations

import operator
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ._common import owned_window_mask
from .elementwise import _Chain, _prog_cache, _resolve
from ..views import views as _v

__all__ = ["reduce", "transform_reduce", "dot"]


# known monoids: (jnp vector-reduce, identity)
_MONOIDS = {
    "add": (jnp.sum, 0),
    "mul": (jnp.prod, 1),
    "min": (jnp.min, None),
    "max": (jnp.max, None),
}


def _classify_op(op) -> Optional[str]:
    if op is None or op is operator.add or op is jnp.add:
        return "add"
    if op is operator.mul or op is jnp.multiply:
        return "mul"
    if op is min or op is jnp.minimum:
        return "min"
    if op is max or op is jnp.maximum:
        return "max"
    return None


def _identity_for(kind: str, dtype):
    if kind == "add":
        return jnp.zeros((), dtype)
    if kind == "mul":
        return jnp.ones((), dtype)
    if kind == "min":
        return jnp.array(jnp.inf if jnp.issubdtype(dtype, jnp.floating)
                         else jnp.iinfo(dtype).max, dtype)
    if kind == "max":
        return jnp.array(-jnp.inf if jnp.issubdtype(dtype, jnp.floating)
                         else jnp.iinfo(dtype).min, dtype)
    raise ValueError(kind)


def _fused_reduce_program(chains, kind):
    """Masked fused reduce over padded shard arrays — zero reshaping,
    zero gather: XLA lowers the cross-shard combine to an all-reduce."""
    key = ("red", tuple(c.key for c in chains), kind)
    prog = _prog_cache.get(key)
    if prog is not None:
        return prog
    c0 = chains[0]
    layout, off, n = c0.cont.layout, c0.off, c0.n
    vec_reduce, _ = _MONOIDS[kind]
    all_ops = tuple(c.ops for c in chains)

    def body(*datas):
        vals = []
        for d, ops in zip(datas, all_ops):
            v = d
            for o in ops:
                v = o(v)
            vals.append(v)
        v = vals[0]
        for extra in vals[1:]:  # zipped chains already combined by ops
            v = v * extra  # pragma: no cover - only dot uses multi-chain
        mask, _gid = owned_window_mask(layout, off, n)
        ident = _identity_for(kind, v.dtype)
        return vec_reduce(jnp.where(mask, v, ident))

    prog = jax.jit(body)
    _prog_cache[key] = prog
    return prog


def reduce(r, init=None, op: Callable = None):
    """Collective reduction; returns a host scalar (valid on all ranks)."""
    kind = _classify_op(op)
    chains = None
    if kind is not None:
        # fuse transform-over-zip pipelines where the zip multiplies out
        chains = _resolve(r) if not isinstance(r, _v.zip_view) else None
    if chains is not None and len(chains) == 1:
        val = _fused_reduce_program(chains, kind)(chains[0].cont._data)
    else:
        arr = r.to_array() if hasattr(r, "to_array") else jnp.asarray(r)
        assert not isinstance(arr, tuple), \
            "reduce over a zip needs a transform to combine components"
        if kind is not None:
            val = _MONOIDS[kind][0](arr)
        else:
            val = _generic_reduce(arr, op)
    if init is not None:
        pyop = op if op is not None else operator.add
        return pyop(init, val.item())
    return val.item()


def _generic_reduce(arr, op):
    key = ("gred", arr.shape, str(arr.dtype), id(op))
    prog = _prog_cache.get(key)
    if prog is None:
        def body(x):
            # tree fold via associative_scan keeps O(log n) depth
            return jax.lax.associative_scan(
                lambda a, b: op(a, b), x)[-1]
        prog = jax.jit(body)
        _prog_cache[key] = prog
    return prog(arr)


def _identity(x):
    return x


def _multiply2(x, y):
    return x * y


def transform_reduce(r, init=None, reduce_op=None, transform_op=None):
    """Spec'd transform_reduce: reduce(transform(r)).  Fuses into the same
    single program as reduce()."""
    if transform_op is None:
        transform_op = _identity
    return reduce(_v.transform(r, transform_op), init, reduce_op)


def dot(a, b, init=None):
    """Dot product — the reference's headline SHP example
    (``examples/shp/dot_product.cpp:11-18``): zip | transform(*) | reduce."""
    z = _v.zip_view(a, b)
    return reduce(_v.transform(z, _multiply2), init, operator.add)
