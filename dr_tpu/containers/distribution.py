"""Distribution policies for ``distributed_vector``.

The reference leaves this as declared future work: ``// TODO: support
teams, distributions`` (``include/dr/shp/distributed_vector.hpp:113``) and
a disabled allocator/distribution test
(``test/gtest/mhp/distributed_vector.cpp:121-131``).  Here it is
first-class: a ``block_distribution`` gives every shard an explicit owned
size (zeros allowed — a shard with size 0 simply owns nothing, which is
the "team" case: restrict the data to a subset of ranks).

TPU realization: the physical layout stays ONE uniform padded
``(nshards, capacity)`` sharded array (pjit's equal-shard world); the
distribution only changes the *logical* metadata — per-shard owned sizes
and start offsets — which every algorithm reads through
``algorithms._common.layout_geometry``.  Uneven sizes therefore cost
padding, never resharding.
"""

from __future__ import annotations

from typing import Sequence, Tuple

__all__ = ["block_distribution", "even_sizes"]


def even_sizes(n: int, nshards: int) -> Tuple[int, ...]:
    """The default ceil-division block sizes: seg = ceil(n/p), short tail.
    (reference rule, mhp dv.hpp:190-193 / shp distributed_vector.hpp:151)."""
    seg = -(-n // nshards) if n else 1
    sizes = []
    left = n
    for _ in range(nshards):
        take = min(seg, left)
        sizes.append(take)
        left -= take
    return tuple(sizes)


class block_distribution:
    """Explicit per-shard owned sizes.  ``sizes[r]`` elements live on rank
    r, contiguously: rank r owns logical ``[starts[r], starts[r]+sizes[r])``.
    """

    def __init__(self, sizes: Sequence[int]):
        self.sizes = tuple(int(s) for s in sizes)
        if any(s < 0 for s in self.sizes):
            raise ValueError("block sizes must be >= 0")

    @property
    def n(self) -> int:
        return sum(self.sizes)

    def layout_entry(self):
        """The value stored in ``layout[1]``: an int for the uniform
        ceil-division layout (back-compat fast paths), else the tagged
        size tuple."""
        nshards = len(self.sizes)
        if self.sizes == even_sizes(self.n, nshards):
            seg = -(-self.n // nshards) if self.n else 1
            return seg
        return ("b",) + self.sizes

    def __repr__(self):
        return f"block_distribution({list(self.sizes)})"

    def __eq__(self, other):
        return (isinstance(other, block_distribution)
                and self.sizes == other.sizes)

    def __hash__(self):
        return hash(self.sizes)
