"""``distributed_mdarray`` / ``distributed_mdspan``: N-D distributed arrays.

The reference SPECIFIES these but never implemented them
(``doc/spec/source/containers/distributed_mdarray.rst:12-23``,
``views/distributed_mdspan.rst:12-23``; the not-built example
``examples/mhp/transpose-cpu.cpp:27-54``; mdspan dependency fetched but
unused — SURVEY.md §2.6).  N-D sharded arrays are native on TPU, so they
ship here as first-class:

* ``distributed_mdarray(shape)`` — an N-D ``jax.Array`` sharded over its
  leading one or two axes (1-D mesh axis or a 2-D grid), padded to the
  shard grid with logical-shape masking, exposing ``segments()`` tiles;
* ``distributed_mdspan`` — a non-owning N-D window (``submdspan``)
  that re-slices tiles and still evaluates lazily.

``transpose(out, in)`` covers the reference's planned transpose example —
under jit the transpose of a sharded array lowers to an XLA all-to-all
over the mesh.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from .partition import factor
from ..core.pinning import pinned_id
from ..parallel import runtime as _rt
from ..utils.spmd_guard import TappedCache

__all__ = ["distributed_mdarray", "distributed_mdspan", "transpose"]


class MdTileSegment:
    """One tile: an N-D box owned by one rank."""

    __slots__ = ("base", "_rank", "box")

    def __init__(self, base, rank, box: Tuple[Tuple[int, int], ...]):
        self.base = base
        self._rank = rank
        self.box = box  # per-dim (begin, end)

    def __dr_rank__(self):
        return self._rank

    def __dr_local__(self):
        return self.base._local_box(self._rank, self.box)

    @property
    def shape(self):
        return tuple(e - b for b, e in self.box)

    def __len__(self):
        n = 1
        for b, e in self.box:
            n *= e - b
        return n

    def materialize(self) -> np.ndarray:
        from ..utils.host import to_host
        sl = tuple(slice(b, e) for b, e in self.box)
        return to_host(self.base.to_array()[sl])

    def __repr__(self):
        return f"MdTileSegment(rank={self._rank}, box={self.box})"


class distributed_mdarray:
    """N-D block-distributed array over the mesh's leading axes."""

    def __init__(self, shape: Sequence[int], dtype=None, *,
                 grid: Optional[Tuple[int, int]] = None, runtime=None,
                 _data=None):
        self._rt = runtime or _rt.runtime()
        self._shape = tuple(int(s) for s in shape)
        assert len(self._shape) >= 1
        self._dtype = jnp.dtype(dtype) if dtype is not None else jnp.float32
        P = self._rt.nprocs
        ndim = len(self._shape)
        if ndim == 1:
            grid = (P,)
        elif grid is None:
            grid = factor(P)
        self._grid = tuple(grid)
        # tile sizes along the distributed leading axes
        self._tsizes = tuple(-(-self._shape[d] // self._grid[d])
                             if self._shape[d] else 1
                             for d in range(len(self._grid)))
        padded = list(self._shape)
        for d in range(len(self._grid)):
            padded[d] = self._grid[d] * self._tsizes[d]
        self._padded = tuple(padded)
        if len(self._grid) == 1:
            mesh = self._rt.mesh
            spec = PartitionSpec(self._rt.axis,
                                 *([None] * (ndim - 1)))
        else:
            mesh = self._rt.mesh2d(self._grid)
            spec = PartitionSpec("mr", "mc", *([None] * (ndim - 2)))
        self._mesh = mesh
        self._sharding = NamedSharding(mesh, spec)
        if _data is not None:
            self._data = _data
        else:
            key = ("mdz", pinned_id(mesh), self._padded, str(self._dtype))
            fn = _md_cache.get(key)
            if fn is None:
                pd, dt, sh = self._padded, self._dtype, self._sharding
                fn = jax.jit(lambda: jnp.zeros(pd, dt), out_shardings=sh)
                _md_cache[key] = fn
            self._data = fn()
        self._rt.register(self)

    # ------------------------------------------------------------------ meta
    @property
    def shape(self):
        return self._shape

    @property
    def dtype(self):
        return self._dtype

    @property
    def grid(self):
        return self._grid

    @property
    def runtime(self):
        return self._rt

    def __len__(self):
        n = 1
        for s in self._shape:
            n *= s
        return n

    # ----------------------------------------------------------- vocabulary
    def __dr_segments__(self):
        segs = []
        import itertools
        ranges = [range(g) for g in self._grid]
        for cell in itertools.product(*ranges):
            box = []
            ok = True
            for d, i in enumerate(cell):
                b = i * self._tsizes[d]
                e = min(self._shape[d], b + self._tsizes[d])
                if b >= e:
                    ok = False
                    break
                box.append((b, e))
            if not ok:
                continue
            for d in range(len(self._grid), len(self._shape)):
                box.append((0, self._shape[d]))
            rank = 0
            for d, i in enumerate(cell):
                rank = rank * self._grid[d] + i
            segs.append(MdTileSegment(self, rank, tuple(box)))
        return segs

    def _local_box(self, rank, box):
        devs = self._mesh.devices.reshape(-1)
        target = devs[rank]
        for sh in self._data.addressable_shards:
            if sh.device.id == target.id:
                sl = []
                for d, (b, e) in enumerate(box):
                    idx = sh.index[d] if d < len(sh.index) else slice(None)
                    start = idx.start or 0
                    sl.append(slice(b - start, e - start))
                return sh.data[tuple(sl)]
        sl = tuple(slice(b, e) for b, e in box)
        return self.to_array()[sl]

    # ----------------------------------------------------------- value APIs
    def to_array(self) -> jax.Array:
        sl = tuple(slice(0, s) for s in self._shape)
        return self._data[sl]

    def assign_array(self, values) -> None:
        values = jnp.asarray(values, self._dtype)
        assert values.shape == self._shape
        key = ("mdp", pinned_id(self._mesh), self._padded, self._shape,
               str(self._dtype))
        fn = _md_cache.get(key)
        if fn is None:
            pd, dt, sh = self._padded, self._dtype, self._sharding
            shp = self._shape

            def pack(v):
                out = jnp.zeros(pd, dt)
                return out.at[tuple(slice(0, s) for s in shp)].set(v)
            fn = jax.jit(pack, out_shardings=sh)
            _md_cache[key] = fn
        self._data = fn(values)

    @classmethod
    def from_array(cls, values, *, grid=None, runtime=None):
        values = jnp.asarray(values)
        md = cls(values.shape, values.dtype, grid=grid, runtime=runtime)
        md.assign_array(values)
        return md

    def materialize(self) -> np.ndarray:
        from ..utils.host import to_host
        return to_host(self.to_array())

    def mdspan(self) -> "distributed_mdspan":
        return distributed_mdspan(
            self, tuple((0, s) for s in self._shape))

    def submdspan(self, *slices) -> "distributed_mdspan":
        return self.mdspan().submdspan(*slices)

    def __getitem__(self, key):
        if isinstance(key, tuple) and any(isinstance(k, slice) for k in key):
            return self.submdspan(*key)
        idx = tuple(int(k) for k in (key if isinstance(key, tuple)
                                     else (key,)))
        for d, i in enumerate(idx):
            if not 0 <= i < self._shape[d]:
                raise IndexError(idx)
        return self._data[idx].item()

    def __setitem__(self, key, value) -> None:
        idx = tuple(int(k) for k in (key if isinstance(key, tuple)
                                     else (key,)))
        self._data = self._data.at[idx].set(jnp.asarray(value, self._dtype))

    def block_until_ready(self):
        jax.block_until_ready(self._data)
        return self

    def __repr__(self):
        return (f"distributed_mdarray(shape={self._shape}, "
                f"grid={self._grid}, dtype={self._dtype})")


class distributed_mdspan:
    """Non-owning N-D window over a distributed_mdarray
    (spec: views/distributed_mdspan.rst)."""

    def __init__(self, base: distributed_mdarray,
                 box: Tuple[Tuple[int, int], ...]):
        self.base = base
        self.box = box

    @property
    def shape(self):
        return tuple(e - b for b, e in self.box)

    def __len__(self):
        n = 1
        for s in self.shape:
            n *= s
        return n

    def submdspan(self, *slices) -> "distributed_mdspan":
        box = list(self.box)
        for d, sl in enumerate(slices):
            b, e = self.box[d]
            if isinstance(sl, slice):
                s0, s1, step = sl.indices(e - b)
                assert step == 1
                box[d] = (b + s0, b + s1)
            else:
                box[d] = (b + int(sl), b + int(sl) + 1)
        return distributed_mdspan(self.base, tuple(box))

    def __dr_segments__(self):
        out = []
        from ..core.vocabulary import rank as _rank
        for t in self.base.__dr_segments__():
            clipped = []
            ok = True
            for (tb, te), (b, e) in zip(t.box, self.box):
                lo, hi = max(tb, b), min(te, e)
                if lo >= hi:
                    ok = False
                    break
                clipped.append((lo, hi))
            if ok:
                out.append(MdTileSegment(self.base, _rank(t),
                                         tuple(clipped)))
        return out

    def to_array(self):
        sl = tuple(slice(b, e) for b, e in self.box)
        return self.base.to_array()[sl]

    def materialize(self) -> np.ndarray:
        from ..utils.host import to_host
        return to_host(self.to_array())

    def __repr__(self):
        return f"distributed_mdspan(box={self.box})"


def transpose(out: distributed_mdarray, inp: distributed_mdarray,
              axes=None) -> None:
    """out = inp permuted by ``axes`` (default: reversed — ``inp.T``) —
    the reference's planned-but-unbuilt transpose example generalized
    to N-D (examples/mhp/transpose-cpu.cpp:27-54 is the 2-D case).
    Under jit the sharded permutation lowers to an XLA all-to-all over
    the mesh."""
    nd = len(inp.shape)
    if axes is None:
        axes = tuple(range(nd - 1, -1, -1))
    else:
        # normalize negatives only; out-of-range axes are an error like
        # numpy's AxisError, not a silent wrap into another permutation
        assert all(-nd <= int(a) < nd for a in axes), \
            f"axes out of range for a {nd}-D array: {tuple(axes)}"
        axes = tuple(int(a) % nd for a in axes)
    assert sorted(axes) == list(range(nd)), \
        f"axes must permute all {nd} dimensions"
    assert out.shape == tuple(inp.shape[a] for a in axes), \
        "output shape must be the permuted input shape"
    key = ("mdT", pinned_id(inp._mesh), inp.shape, axes, str(inp.dtype))
    fn = _md_cache.get(key)
    if fn is None:
        fn = jax.jit(lambda x: jnp.transpose(x, axes))
        _md_cache[key] = fn
    out.assign_array(fn(inp.to_array()))


_md_cache: dict = TappedCache()
