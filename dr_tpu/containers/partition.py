"""Matrix partitions: how 2-D containers map onto the device mesh.

TPU re-design of the reference's only pluggable distribution point
(``shp/containers/matrix_partition.hpp:23-86`` + ``detail::factor``,
``shp/containers/detail.hpp:15-24``):

* ``matrix_partition`` — abstract placement: grid shape, tile shape,
  tile -> rank;
* ``block_cyclic`` — tiles placed round-robin over a device grid, with
  ``tile.div`` meaning "divide each dimension evenly by the grid" (the
  default, which makes block-cyclic collapse to plain 2-D block).

On TPU a partition is realized as a 2-D **mesh view** of the runtime's
devices plus a PartitionSpec: ``tile.div`` block placement shards one
``jax.Array`` over ("mr", "mc") mesh axes, so XLA lays collectives along
mesh rows/columns (tp-style 2-D sharding).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional, Tuple

from jax.sharding import Mesh

__all__ = ["tile", "matrix_partition", "block_cyclic", "row_tiles", "factor"]


def factor(n: int) -> Tuple[int, int]:
    """Near-square factorization n = p*q, p <= q (detail.hpp:15-24)."""
    p = int(math.isqrt(n))
    while n % p:
        p -= 1
    return (p, n // p)


class tile:
    """Tile-shape placeholder: ``tile.div`` = divide evenly by the grid
    (shp/containers/matrix_partition.hpp:34-45)."""
    div = -1


class matrix_partition:
    """Abstract partition (matrix_partition.hpp:23-32)."""

    def grid_shape(self) -> Tuple[int, int]:
        raise NotImplementedError

    def tile_shape(self, matrix_shape) -> Tuple[int, int]:
        raise NotImplementedError

    def tile_rank(self, i: int, j: int) -> int:
        """Mesh rank owning grid tile (i, j)."""
        raise NotImplementedError

    def clone(self) -> "matrix_partition":
        return self


@dataclass(frozen=True)
class block_cyclic(matrix_partition):
    """Round-robin tile placement over a device grid
    (matrix_partition.hpp:34-86).  With ``tile.div`` (default) each device
    owns exactly one contiguous block — the reference's default — which on
    TPU becomes a 2-D sharded array.
    """

    tile: Tuple[int, int] = (tile.div, tile.div)
    grid: Optional[Tuple[int, int]] = None

    def grid_for(self, nprocs: int) -> Tuple[int, int]:
        return self.grid if self.grid is not None else factor(nprocs)

    def grid_shape(self) -> Tuple[int, int]:
        assert self.grid is not None
        return self.grid

    def tile_shape(self, matrix_shape) -> Tuple[int, int]:
        m, n = matrix_shape
        gp, gq = self.grid_shape()
        th = -(-m // gp) if self.tile[0] == tile.div else self.tile[0]
        tw = -(-n // gq) if self.tile[1] == tile.div else self.tile[1]
        return (th, tw)

    def tile_rank(self, i: int, j: int) -> int:
        gp, gq = self.grid_shape()
        return (i % gp) * gq + (j % gq)

    def is_block(self) -> bool:
        """True when tile.div: one tile per device = plain 2-D block."""
        return self.tile == (tile.div, tile.div)


def row_tiles(nprocs: Optional[int] = None) -> block_cyclic:
    """Row-stripe partition (grid (p, 1)) — the shape the reference's gemv
    requires (shp/algorithms/gemv.hpp:21)."""
    if nprocs is None:
        from ..parallel import runtime as _rt
        nprocs = _rt.nprocs()
    return block_cyclic(grid=(nprocs, 1))
