"""``distributed_vector``: 1-D block-distributed vector on the TPU mesh.

TPU-native re-design of both reference implementations:

* ``mhp::distributed_vector`` — per-rank block + halo padding + RMA window
  (``include/dr/mhp/containers/distributed_vector.hpp:176-238``),
* ``shp::distributed_vector`` — one device segment per GPU
  (``include/dr/shp/distributed_vector.hpp:138-182``).

Design: the vector owns ONE ``jax.Array`` of shape ``(nshards, prev + seg +
next)`` sharded over the mesh axis — shard row r is rank r's local block
``[ghost_prev | owned | ghost_next]``, exactly the reference's local
allocation (dv.hpp:190-194: ``segment_size = max(ceil(n/p), prev, next)``,
alloc ``segment_size + prev + next``).  The last shard is padded; logical
size ``n`` is metadata and every collective masks the tail (SURVEY.md §7
hard-part 3).

Mutation model (hard-part 1): JAX arrays are immutable values, so the
container holds the *current version* and every algorithm rebinds it.
Element/batched access replaces the reference's per-element MPI RMA
(dv.hpp:109-122 — its known-slow path) with explicit batched gather/scatter
through ``get()``/``put()`` — host-mediated, one fused XLA program per call.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ..core.pinning import pinned_id
from ..core.segment import Segment
from ..parallel import runtime as _rt
from ..parallel.halo import halo_bounds, span_halo
from .distribution import block_distribution
from ..utils.spmd_guard import TappedCache
from ..utils import sanitize as _sanitize

__all__ = ["distributed_vector", "halo"]


def _plan_flush(reason: str, cont=None) -> None:
    """Host-visible reads/writes of container state are deferred-plan
    flush points (dr_tpu/plan.py): pending recorded ops must land
    before ``_data`` is observed or externally rebound.  Lazy import —
    the plan module builds on the algorithm layer above this one.
    With ``cont``, the flush is footprint-gated (SPEC §21.2): a queue
    that provably never touches the container skips the cliff."""
    from ..plan import flush_reads
    flush_reads(reason, cont)


def _normalize_dtype(dtype):
    if dtype is None:
        return jnp.float32
    if dtype is float:
        return jnp.float32
    if dtype is int:
        return jnp.int32
    return jnp.dtype(dtype)


class distributed_vector:
    """1-D block-distributed vector with optional halo regions."""

    def __init__(self, size: int, dtype=None, halo: Optional[halo_bounds] = None,
                 *, distribution=None, runtime=None, _data=None):
        self._n = int(size)
        self._dtype = _normalize_dtype(dtype)
        self._hb = halo or halo_bounds()
        self._rebind(runtime or _rt.runtime(), distribution, _data=_data)

    def _rebind(self, runtime, distribution, *, _data=None) -> None:
        """(Re)plan the block layout onto ``runtime``'s mesh and
        (re)allocate the sharded state.  ``__init__`` is one caller;
        the other is the elastic layer (``utils/elastic.redistribute``
        and the shrink rescue, docs/SPEC.md §16), which re-plans a LIVE
        vector in place — logical size, dtype and halo bounds survive,
        the physical layout is rebuilt for the target mesh, and the
        value (if it should survive) is re-assigned by the caller.

        Validation runs on LOCALS first and late failures (halo
        min-size, allocation) roll the attributes back: a rejected
        redistribute of a live vector must leave it exactly as it
        was — a half-rebound vector would mix two layouts silently."""
        P = runtime.nprocs
        if distribution is not None and not isinstance(distribution,
                                                       block_distribution):
            distribution = block_distribution(distribution)
        if distribution is not None:
            if len(distribution.sizes) != P:
                raise ValueError(
                    f"distribution has {len(distribution.sizes)} blocks "
                    f"for a {P}-shard mesh")
            if distribution.n != self._n:
                raise ValueError(
                    f"distribution sizes sum to {distribution.n}, "
                    f"vector size is {self._n}")
        dist_entry = (distribution.layout_entry()
                      if distribution is not None else None)
        if isinstance(dist_entry, int):
            dist_entry = None  # even sizes == default layout
        if dist_entry is not None and self._hb.width:
            raise ValueError("halo_bounds require the uniform block "
                             "distribution (the halo exchange ring assumes "
                             "equal shards)")
        if dist_entry is not None:
            sizes = np.asarray(dist_entry[1:], dtype=np.int64)
            seg = max(int(sizes.max(initial=0)), self._hb.prev,
                      self._hb.next, 1)
            starts = np.concatenate(([0], np.cumsum(sizes)[:-1]))
        else:
            # segment_size = max(ceil(n/p), prev, next)   (dv.hpp:190-193)
            seg = max(-(-self._n // P) if self._n else 1,
                      self._hb.prev, self._hb.next, 1)
            sizes = None
            starts = None
        prior = {k: self.__dict__.get(k)
                 for k in ("_rt", "_nshards", "_dist_entry", "_seg",
                           "_sizes", "_starts", "_data_arr", "_halo")}
        if prior["_rt"] is None and _sanitize._born_hook is not None:
            # container CREATION (not a live elastic rebind): tell the
            # plansan opaque watcher before the first state write, so
            # scratch containers born inside a watched thunk are exempt
            _sanitize._born_hook(self)
        try:
            self._rt = runtime
            self._nshards = P
            self._dist_entry = dist_entry
            self._seg = seg
            self._sizes = sizes
            self._starts = starts
            if _data is not None:
                self._data = _data
            else:
                self._data = _zeros(runtime.mesh, runtime.axis, P,
                                    self.block_width, self._dtype)
            self._halo = span_halo(self) if self._hb.width else None
        except BaseException:
            if prior["_rt"] is not None:  # live rebind, not __init__
                self.__dict__.update(prior)
            raise
        self._rt.register(self)

    # ---------------------------------------------------------------- state
    @property
    def _data(self):
        """The current sharded device state.  A property so the
        plansan opaque-footprint watcher
        (``utils/sanitize.watch_containers``, SPEC §23.3) observes
        every host-side read and rebind; unarmed, the cost is one
        module-global ``None`` check."""
        h = _sanitize._access_hook
        if h is not None:
            h("r", self)
        return self._data_arr

    @_data.setter
    def _data(self, value):
        h = _sanitize._access_hook
        if h is not None:
            h("w", self)
        self._data_arr = value

    # ------------------------------------------------------------------ meta
    @property
    def runtime(self):
        return self._rt

    @property
    def dtype(self):
        return self._dtype

    @property
    def halo_bounds(self) -> halo_bounds:
        return self._hb

    @property
    def segment_size(self) -> int:
        return self._seg

    @property
    def nshards(self) -> int:
        return self._nshards

    @property
    def block_width(self) -> int:
        """Per-shard row width: prev + seg + next."""
        return self._hb.prev + self._seg + self._hb.next

    @property
    def layout(self):
        """Alignment key: equal layouts => segment lists pairwise equal
        (the ``mhp::aligned`` condition, mhp/alignment.hpp:13-28).
        ``layout[1]`` is the int segment size for the default uniform
        layout, or the distribution's tagged size tuple."""
        return (self._nshards, self._dist_entry or self._seg,
                self._hb.prev, self._hb.next, self._n)

    @property
    def distribution(self):
        """The explicit block_distribution, or None for the default
        ceil-division layout."""
        if self._dist_entry is None:
            return None
        return block_distribution(self._dist_entry[1:])

    def _rank_window(self, r: int):
        """Rank r's logical [begin, end) window."""
        if self._starts is not None:
            b = int(self._starts[r])
            return b, b + int(self._sizes[r])
        b = r * self._seg
        return b, min(self._n, b + self._seg)

    def __len__(self) -> int:
        return self._n

    @property
    def size(self) -> int:
        return self._n

    # ----------------------------------------------------------- vocabulary
    def __dr_segments__(self):
        segs = []
        for r in range(self._nshards):
            begin, end = self._rank_window(r)
            if begin < end:
                segs.append(Segment(self, r, begin, end))
        return segs

    # ------------------------------------------------------------- halo API
    def halo(self) -> span_halo:
        if self._halo is None:
            raise ValueError("distributed_vector built without halo_bounds")
        return self._halo

    # ----------------------------------------------------------- value APIs
    def to_array(self) -> jax.Array:
        """Current logical value as a 1-D jax array of length n."""
        _plan_flush("to_array")
        if self._dist_entry is not None:
            return _extract_uneven(self._rt.mesh, self.layout,
                                   self._dtype)(self._data)
        return _extract(self._rt.mesh, self._rt.axis, self._nshards,
                        self._seg, self._hb.prev, self._hb.next, self._n,
                        self._dtype)(self._data)

    def assign_array(self, values) -> None:
        """Rebind the whole logical value (ghost cells reset to zero).
        Footprint-gated flush: a container the active plan's queue
        never touches (the from_array build of a FRESH operand inside
        a serve batch) assigns without the flush cliff."""
        _plan_flush("assign_array", self)
        values = jnp.asarray(values, self._dtype)
        assert values.shape == (self._n,)
        if self._dist_entry is not None:
            self._data = _pack_uneven(self._rt.mesh, self._rt.axis,
                                      self.layout, self._dtype)(values)
            return
        self._data = _pack(self._rt.mesh, self._rt.axis, self._nshards,
                           self._seg, self._hb.prev, self._hb.next, self._n,
                           self._dtype)(values)

    @classmethod
    def from_array(cls, values, halo: Optional[halo_bounds] = None, *,
                   distribution=None, runtime=None) -> "distributed_vector":
        values = jnp.asarray(values)
        dv = cls(values.shape[0], values.dtype, halo,
                 distribution=distribution, runtime=runtime)
        dv.assign_array(values)
        return dv

    # -- segment plumbing used by Segment ----------------------------------
    def _host_values(self, begin: int, end: int) -> np.ndarray:
        from ..utils.host import to_host
        return to_host(self.to_array()[begin:end])

    def _local_values(self, rank: int, begin: int, end: int):
        _plan_flush("local segment read")
        lo = self._rank_window(rank)[0]
        prev = self._hb.prev
        for sh in self._data.addressable_shards:
            idx = sh.index[0]
            start = 0 if idx.start is None else idx.start
            if start == rank and (idx.stop is None or idx.stop == rank + 1):
                row = sh.data.reshape(-1)
                return row[prev + (begin - lo): prev + (end - lo)]
        # shard not addressable from this host (multi-host): global read
        return self.to_array()[begin:end]

    # ------------------------------------------------ element/batched access
    def _locate(self, i):
        i = jnp.asarray(i)
        if self._starts is not None:
            starts = jnp.asarray(self._starts)
            r = jnp.searchsorted(starts, i, side="right") - 1
            c = self._hb.prev + i - starts[r]
            return r, c
        r = i // self._seg
        c = self._hb.prev + i % self._seg
        return r, c

    def _check_indices(self, indices):
        """Bounds-check a host-side index batch.  Negative indices follow
        the numpy convention; anything out of range raises IndexError (the
        reference's RMA would fault, not wrap)."""
        orig = np.asarray(indices)
        idx = np.where(orig < 0, orig + self._n, orig)
        bad = (idx < 0) | (idx >= self._n)
        if bad.any():
            raise IndexError(
                f"index {int(orig[bad].reshape(-1)[0])} out of range "
                f"for distributed_vector of size {self._n}")
        return jnp.asarray(idx)

    def get(self, indices):
        """Batched remote read (replaces per-element MPI_Rget,
        dv.hpp:109-116)."""
        _plan_flush("get")
        r, c = self._locate(self._check_indices(indices))
        return self._data[r, c]

    def put(self, indices, values) -> None:
        """Batched remote write (replaces per-element MPI_Put,
        dv.hpp:118-122)."""
        _plan_flush("put")
        r, c = self._locate(self._check_indices(indices))
        self._data = self._data.at[r, c].set(
            jnp.asarray(values, self._dtype))

    def __getitem__(self, key):
        if isinstance(key, slice):
            from ..views import subrange
            start, stop, step = key.indices(self._n)
            assert step == 1, "stride-1 subranges only"
            return subrange(self, start, stop)
        i = int(key)
        if i < 0:
            i += self._n
        if not 0 <= i < self._n:
            raise IndexError(i)
        _plan_flush("__getitem__")
        if self._starts is not None:
            r = int(np.searchsorted(self._starts, i, side="right")) - 1
            return self._data[r,
                              self._hb.prev + i - int(self._starts[r])].item()
        return self._data[i // self._seg,
                          self._hb.prev + i % self._seg].item()

    def __setitem__(self, key, value) -> None:
        if isinstance(key, slice):
            start, stop, step = key.indices(self._n)
            assert step == 1
            idx = jnp.arange(start, stop)
            self.put(idx, value)
            return
        i = int(key)
        if i < 0:
            i += self._n
        if not 0 <= i < self._n:
            raise IndexError(i)
        self.put(jnp.asarray([i]), jnp.asarray([value], self._dtype))

    def __iter__(self):
        return iter(self.materialize())

    def materialize(self) -> np.ndarray:
        from ..utils.host import to_host
        return to_host(self.to_array())

    def block_until_ready(self) -> "distributed_vector":
        _plan_flush("block_until_ready")
        jax.block_until_ready(self._data)
        return self

    def __repr__(self):
        return (f"distributed_vector(n={self._n}, dtype={self._dtype}, "
                f"shards={self._nshards}x{self.block_width}, hb={self._hb})")


# ---------------------------------------------------------------------------
# cached jitted layout programs
# ---------------------------------------------------------------------------

_jit_cache: dict = TappedCache()


def _cached(key, builder):
    fn = _jit_cache.get(key)
    if fn is None:
        fn = builder()
        _jit_cache[key] = fn
    return fn


def _zeros(mesh, axis, nshards, width, dtype):
    key = ("zeros", pinned_id(mesh), axis, nshards, width, str(dtype))

    def build():
        sh = NamedSharding(mesh, PartitionSpec(axis, None))
        return jax.jit(lambda: jnp.zeros((nshards, width), dtype),
                       out_shardings=sh)
    return _cached(key, build)()


def _extract(mesh, axis, nshards, seg, prev, nxt, n, dtype):
    key = ("extract", pinned_id(mesh), axis, nshards, seg, prev, nxt, n, str(dtype))

    def build():
        def fn(data):
            owned = data[:, prev:prev + seg] if (prev or nxt) else data
            return owned.reshape(nshards * seg)[:n]
        return jax.jit(fn)
    return _cached(key, build)


def _pack(mesh, axis, nshards, seg, prev, nxt, n, dtype):
    key = ("pack", pinned_id(mesh), axis, nshards, seg, prev, nxt, n, str(dtype))

    def build():
        sh = NamedSharding(mesh, PartitionSpec(axis, None))

        def fn(values):
            flat = jnp.zeros((nshards * seg,), dtype).at[:n].set(values)
            body = flat.reshape(nshards, seg)
            if prev or nxt:
                data = jnp.zeros((nshards, prev + seg + nxt), dtype)
                data = data.at[:, prev:prev + seg].set(body)
            else:
                data = body
            return data
        return jax.jit(fn, out_shardings=sh)
    return _cached(key, build)


def _uneven_phys_index(layout):
    """Static flat physical index of every logical element for an uneven
    block layout (computed once per layout with numpy)."""
    from ..algorithms._common import layout_geometry
    nshards, cap, prev, nxt, n, starts, sizes = layout_geometry(layout)
    width = prev + cap + nxt
    k = np.arange(n)
    r = np.searchsorted(starts, k, side="right") - 1
    return nshards, width, jnp.asarray(r * width + prev + (k - starts[r]))


def _extract_uneven(mesh, layout, dtype):
    key = ("extract_u", pinned_id(mesh), layout, str(dtype))

    def build():
        _nshards, _width, idx = _uneven_phys_index(layout)
        return jax.jit(lambda data: data.reshape(-1)[idx])
    return _cached(key, build)


def _pack_uneven(mesh, axis, layout, dtype):
    key = ("pack_u", pinned_id(mesh), axis, layout, str(dtype))

    def build():
        nshards, width, idx = _uneven_phys_index(layout)
        sh = NamedSharding(mesh, PartitionSpec(axis, None))

        def fn(values):
            flat = jnp.zeros((nshards * width,), dtype).at[idx].set(values)
            return flat.reshape(nshards, width)
        return jax.jit(fn, out_shardings=sh)
    return _cached(key, build)


def halo(dr) -> span_halo:
    """Fetch the halo of the distributed_vector underlying any view over it
    (reference mhp dv.hpp:240-248)."""
    obj = dr
    seen = set()
    while obj is not None and id(obj) not in seen:
        seen.add(id(obj))
        if isinstance(obj, distributed_vector):
            return obj.halo()
        obj = getattr(obj, "base", None)
    raise TypeError("halo(): no underlying distributed_vector")
