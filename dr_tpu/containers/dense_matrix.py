"""``dense_matrix``: 2-D tiled dense matrix on a 2-D TPU mesh.

TPU re-design of ``shp::dense_matrix`` (``shp/containers/dense_matrix.hpp``)
and — because N-D arrays are natural on TPU — of the documented-but-
unimplemented ``distributed_mdarray``/``distributed_mdspan`` surface
(``doc/spec/source/containers/distributed_mdarray.rst``, SURVEY.md §2.6).

Design: ONE ``jax.Array`` of padded shape ``(gp*th, gq*tw)`` sharded over a
2-D mesh view ("mr", "mc") of the runtime devices; tile (i, j) is the shard
on device ``partition.tile_rank(i, j)``.  The logical shape (m, n) is
metadata; every algorithm masks the pad (same pad-and-mask rule as the
1-D vector).  Where the reference walks tiles through per-GPU queues, here
whole-matrix expressions run under jit and GSPMD inserts any cross-tile
traffic (e.g. the shifted-slice halos of the 2-D heat stencil).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from .partition import block_cyclic, matrix_partition
from ..core.pinning import pinned_id
from ..parallel import runtime as _rt

__all__ = ["dense_matrix", "matrix_entry", "Index2D"]


class Index2D(tuple):
    """2-D index with tuple protocol (shp/containers/index.hpp:38-112)."""

    def __new__(cls, i, j=None):
        if j is None:
            i, j = i
        return super().__new__(cls, (int(i), int(j)))

    @property
    def i(self):
        return self[0]

    @property
    def j(self):
        return self[1]


class matrix_entry:
    """(index, value) pair (shp/containers/matrix_entry.hpp:14-229)."""

    __slots__ = ("index", "value")

    def __init__(self, index, value):
        self.index = Index2D(index)
        self.value = value

    def __iter__(self):  # structured bindings: (index, value)
        return iter((self.index, self.value))

    def __repr__(self):
        return f"matrix_entry({self.index}, {self.value})"


class MatrixTileSegment:
    """One tile: rows [rb, re) x cols [cb, ce) owned by ``rank`` — the
    dense_matrix_view-as-segment of the reference
    (dense_matrix.hpp:198-242)."""

    __slots__ = ("base", "_rank", "rb", "re", "cb", "ce")

    def __init__(self, base, rank, rb, re, cb, ce):
        self.base = base
        self._rank = rank
        self.rb, self.re, self.cb, self.ce = rb, re, cb, ce

    def __dr_rank__(self):
        return self._rank

    def __dr_local__(self):
        return self.base._local_tile(self._rank, self.rb, self.re,
                                     self.cb, self.ce)

    @property
    def shape(self) -> Tuple[int, int]:
        return (self.re - self.rb, self.ce - self.cb)

    @property
    def origin(self) -> Index2D:
        return Index2D(self.rb, self.cb)

    def __len__(self):
        return (self.re - self.rb) * (self.ce - self.cb)

    def materialize(self) -> np.ndarray:
        from ..utils.host import to_host
        return to_host(
            self.base.to_array()[self.rb:self.re, self.cb:self.ce])

    def __iter__(self):
        vals = self.materialize()
        for i in range(vals.shape[0]):
            for j in range(vals.shape[1]):
                yield matrix_entry((self.rb + i, self.cb + j), vals[i, j])

    def __repr__(self):
        return (f"MatrixTileSegment(rank={self._rank}, "
                f"rows=[{self.rb},{self.re}), cols=[{self.cb},{self.ce}))")


class dense_matrix:
    """Block-tiled dense matrix (one shard per grid cell)."""

    def __init__(self, shape: Tuple[int, int], dtype=None,
                 partition: Optional[matrix_partition] = None, *,
                 runtime=None, _data=None):
        self._rt = runtime or _rt.runtime()
        m, n = shape
        self._m, self._n = int(m), int(n)
        self._dtype = jnp.dtype(dtype) if dtype is not None else jnp.float32
        part = partition or block_cyclic()
        if isinstance(part, block_cyclic) and part.grid is None:
            part = block_cyclic(part.tile, part.grid_for(self._rt.nprocs))
        assert isinstance(part, block_cyclic) and part.is_block(), (
            "v1 supports block placement (tile.div); cyclic tile shapes "
            "land with the multi-tile storage mode")
        self._part = part
        gp, gq = part.grid_shape()
        th, tw = part.tile_shape((self._m, self._n))
        self._grid = (gp, gq)
        self._tshape = (th, tw)
        self._mesh = self._rt.mesh2d((gp, gq))
        self._sharding = NamedSharding(self._mesh, PartitionSpec("mr", "mc"))
        if _data is not None:
            self._data = _data
        else:
            self._data = _zeros2d(self._mesh, gp * th, gq * tw, self._dtype,
                                  self._sharding)
        self._rt.register(self)

    # ------------------------------------------------------------------ meta
    @property
    def shape(self) -> Tuple[int, int]:
        return (self._m, self._n)

    @property
    def dtype(self):
        return self._dtype

    @property
    def grid_shape(self) -> Tuple[int, int]:
        return self._grid

    @property
    def tile_shape(self) -> Tuple[int, int]:
        return self._tshape

    @property
    def partition(self) -> matrix_partition:
        return self._part

    @property
    def runtime(self):
        return self._rt

    def __len__(self):
        return self._m * self._n

    @property
    def layout(self):
        return ("dense2d", self._grid, self._tshape, self._m, self._n)

    # ----------------------------------------------------------- vocabulary
    def __dr_segments__(self):
        segs = []
        gp, gq = self._grid
        th, tw = self._tshape
        for i in range(gp):
            rb, re = i * th, min((i + 1) * th, self._m)
            if rb >= re:
                continue
            for j in range(gq):
                cb, ce = j * tw, min((j + 1) * tw, self._n)
                if cb >= ce:
                    continue
                segs.append(MatrixTileSegment(
                    self, self._part.tile_rank(i, j), rb, re, cb, ce))
        return segs

    def tiles(self):
        return self.__dr_segments__()

    def tile(self, ij) -> MatrixTileSegment:
        i, j = ij
        gp, gq = self._grid
        th, tw = self._tshape
        assert 0 <= i < gp and 0 <= j < gq
        return MatrixTileSegment(
            self, self._part.tile_rank(i, j),
            i * th, min((i + 1) * th, self._m),
            j * tw, min((j + 1) * tw, self._n))

    # ----------------------------------------------------------- value APIs
    def to_array(self) -> jax.Array:
        return self._data[:self._m, :self._n]

    def assign_array(self, values) -> None:
        values = jnp.asarray(values, self._dtype)
        assert values.shape == (self._m, self._n)
        gp, gq = self._grid
        th, tw = self._tshape
        self._data = _pack2d(self._mesh, gp * th, gq * tw, self._m, self._n,
                             self._dtype, self._sharding)(values)

    @classmethod
    def from_array(cls, values, partition=None, *, runtime=None):
        values = jnp.asarray(values)
        mat = cls(values.shape, values.dtype, partition, runtime=runtime)
        mat.assign_array(values)
        return mat

    def materialize(self) -> np.ndarray:
        from ..utils.host import to_host
        return to_host(self.to_array())

    def _local_tile(self, rank, rb, re, cb, ce):
        # block mode: each device owns exactly one shard
        target = self._mesh.devices.reshape(-1)[rank]
        for sh in self._data.addressable_shards:
            if sh.device.id == target.id:
                ri, ci = sh.index
                r0 = 0 if ri.start is None else ri.start
                c0 = 0 if ci.start is None else ci.start
                return sh.data[rb - r0:re - r0, cb - c0:ce - c0]
        return self.to_array()[rb:re, cb:ce]  # multi-host fallback

    # ------------------------------------------------ element/batched access
    def __getitem__(self, ij):
        i, j = ij
        if isinstance(i, slice) or isinstance(j, slice):
            from ..views.matrix_views import dense_matrix_view
            ri = range(*i.indices(self._m)) if isinstance(i, slice) \
                else range(i, i + 1)
            rj = range(*j.indices(self._n)) if isinstance(j, slice) \
                else range(j, j + 1)
            return dense_matrix_view(self, ri.start, ri.stop,
                                     rj.start, rj.stop)
        i, j = int(i), int(j)
        if i < 0:
            i += self._m
        if j < 0:
            j += self._n
        if not (0 <= i < self._m and 0 <= j < self._n):
            raise IndexError((i, j))
        return self._data[i, j].item()

    def __setitem__(self, ij, value) -> None:
        i, j = int(ij[0]), int(ij[1])
        if not (0 <= i < self._m and 0 <= j < self._n):
            raise IndexError((i, j))
        self._data = self._data.at[i, j].set(
            jnp.asarray(value, self._dtype))

    def get(self, rows, cols):
        """Batched element gather."""
        return self._data[jnp.asarray(rows), jnp.asarray(cols)]

    def put(self, rows, cols, values) -> None:
        self._data = self._data.at[
            jnp.asarray(rows), jnp.asarray(cols)].set(
            jnp.asarray(values, self._dtype))

    def block_until_ready(self):
        jax.block_until_ready(self._data)
        return self

    def __repr__(self):
        return (f"dense_matrix(shape={self.shape}, grid={self._grid}, "
                f"tile={self._tshape}, dtype={self._dtype})")


_cache: dict = {}


def _zeros2d(mesh, mm, nn, dtype, sharding):
    key = ("z2", pinned_id(mesh), mm, nn, str(dtype))
    fn = _cache.get(key)
    if fn is None:
        fn = jax.jit(lambda: jnp.zeros((mm, nn), dtype),
                     out_shardings=sharding)
        _cache[key] = fn
    return fn()


def _pack2d(mesh, mm, nn, m, n, dtype, sharding):
    key = ("p2", pinned_id(mesh), mm, nn, m, n, str(dtype))
    fn = _cache.get(key)
    if fn is None:
        def pack(values):
            out = jnp.zeros((mm, nn), dtype)
            return out.at[:m, :n].set(values)
        fn = jax.jit(pack, out_shardings=sharding)
        _cache[key] = fn
    return fn
