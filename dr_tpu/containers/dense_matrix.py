"""``dense_matrix``: 2-D tiled dense matrix on a 2-D TPU mesh.

TPU re-design of ``shp::dense_matrix`` (``shp/containers/dense_matrix.hpp``)
and — because N-D arrays are natural on TPU — of the documented-but-
unimplemented ``distributed_mdarray``/``distributed_mdspan`` surface
(``doc/spec/source/containers/distributed_mdarray.rst``, SURVEY.md §2.6).

Design: ONE ``jax.Array`` of padded shape ``(gp*th, gq*tw)`` sharded over a
2-D mesh view ("mr", "mc") of the runtime devices; tile (i, j) is the shard
on device ``partition.tile_rank(i, j)``.  The logical shape (m, n) is
metadata; every algorithm masks the pad (same pad-and-mask rule as the
1-D vector).  Where the reference walks tiles through per-GPU queues, here
whole-matrix expressions run under jit and GSPMD inserts any cross-tile
traffic (e.g. the shifted-slice halos of the 2-D heat stencil).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from .partition import block_cyclic, matrix_partition
from ..core.pinning import pinned_id
from ..parallel import runtime as _rt
from ..utils.spmd_guard import TappedCache

__all__ = ["dense_matrix", "matrix_entry", "Index2D"]


class Index2D(tuple):
    """2-D index with tuple protocol (shp/containers/index.hpp:38-112)."""

    def __new__(cls, i, j=None):
        if j is None:
            i, j = i
        return super().__new__(cls, (int(i), int(j)))

    @property
    def i(self):
        return self[0]

    @property
    def j(self):
        return self[1]


class matrix_entry:
    """(index, value) pair (shp/containers/matrix_entry.hpp:14-229)."""

    __slots__ = ("index", "value")

    def __init__(self, index, value):
        self.index = Index2D(index)
        self.value = value

    def __iter__(self):  # structured bindings: (index, value)
        return iter((self.index, self.value))

    def __repr__(self):
        return f"matrix_entry({self.index}, {self.value})"


class MatrixTileSegment:
    """One tile: rows [rb, re) x cols [cb, ce) owned by ``rank`` — the
    dense_matrix_view-as-segment of the reference
    (dense_matrix.hpp:198-242)."""

    __slots__ = ("base", "_rank", "rb", "re", "cb", "ce")

    def __init__(self, base, rank, rb, re, cb, ce):
        self.base = base
        self._rank = rank
        self.rb, self.re, self.cb, self.ce = rb, re, cb, ce

    def __dr_rank__(self):
        return self._rank

    def __dr_local__(self):
        return self.base._local_tile(self._rank, self.rb, self.re,
                                     self.cb, self.ce)

    @property
    def shape(self) -> Tuple[int, int]:
        return (self.re - self.rb, self.ce - self.cb)

    @property
    def origin(self) -> Index2D:
        return Index2D(self.rb, self.cb)

    def __len__(self):
        return (self.re - self.rb) * (self.ce - self.cb)

    def materialize(self) -> np.ndarray:
        from ..utils.host import to_host
        return to_host(
            self.base.to_array()[self.rb:self.re, self.cb:self.ce])

    def __iter__(self):
        vals = self.materialize()
        for i in range(vals.shape[0]):
            for j in range(vals.shape[1]):
                yield matrix_entry((self.rb + i, self.cb + j), vals[i, j])

    def __repr__(self):
        return (f"MatrixTileSegment(rank={self._rank}, "
                f"rows=[{self.rb},{self.re}), cols=[{self.cb},{self.ce}))")


class dense_matrix:
    """Block-tiled dense matrix (one shard per grid cell)."""

    def __init__(self, shape: Tuple[int, int], dtype=None,
                 partition: Optional[matrix_partition] = None, *,
                 runtime=None, _data=None):
        self._rt = runtime or _rt.runtime()
        m, n = shape
        self._m, self._n = int(m), int(n)
        self._dtype = jnp.dtype(dtype) if dtype is not None else jnp.float32
        part = partition or block_cyclic()
        if isinstance(part, block_cyclic) and part.grid is None:
            part = block_cyclic(part.tile, part.grid_for(self._rt.nprocs))
        assert isinstance(part, block_cyclic), \
            "dense_matrix distributions are block_cyclic instances"
        self._part = part
        gp, gq = part.grid_shape()
        th, tw = part.tile_shape((self._m, self._n))
        self._grid = (gp, gq)
        self._tshape = (th, tw)
        # cyclic multi-tile storage (matrix_partition.hpp:34-86): tile
        # (i, j) lives on device (i % gp, j % gq) at slot (i//gp, j//gq).
        # The shard array stores tile-rows DEVICE-major, slot-minor
        # ("folded" order), so round-robin placement is recovered by a
        # plain 2-D block sharding; block mode is slots == (1, 1), where
        # folded and logical layouts coincide.
        nti = max(1, -(-self._m // th))
        ntj = max(1, -(-self._n // tw))
        self._ntiles = (nti, ntj)
        self._slots = (-(-nti // gp), -(-ntj // gq))
        si, sj = self._slots
        self._mesh = self._rt.mesh2d((gp, gq))
        self._sharding = NamedSharding(self._mesh, PartitionSpec("mr", "mc"))
        if _data is not None:
            self._data = _data
        else:
            self._data = _zeros2d(self._mesh, gp * si * th, gq * sj * tw,
                                  self._dtype, self._sharding)
        self._rt.register(self)

    # ------------------------------------------------------------------ meta
    @property
    def shape(self) -> Tuple[int, int]:
        return (self._m, self._n)

    @property
    def dtype(self):
        return self._dtype

    @property
    def grid_shape(self) -> Tuple[int, int]:
        return self._grid

    @property
    def tile_shape(self) -> Tuple[int, int]:
        return self._tshape

    @property
    def partition(self) -> matrix_partition:
        return self._part

    @property
    def runtime(self):
        return self._rt

    def __len__(self):
        return self._m * self._n

    @property
    def is_block(self) -> bool:
        """One tile per device (folded == logical layout)."""
        return self._slots == (1, 1)

    @property
    def grid_tiles(self) -> Tuple[int, int]:
        """Tile-grid dimensions (# tiles per axis)."""
        return self._ntiles

    @property
    def layout(self):
        return ("dense2d", self._grid, self._tshape, self._slots,
                self._m, self._n)

    # ----------------------------------------------------------- vocabulary
    def __dr_segments__(self):
        segs = []
        nti, ntj = self._ntiles
        th, tw = self._tshape
        for i in range(nti):
            rb, re = i * th, min((i + 1) * th, self._m)
            if rb >= re:
                continue
            for j in range(ntj):
                cb, ce = j * tw, min((j + 1) * tw, self._n)
                if cb >= ce:
                    continue
                segs.append(MatrixTileSegment(
                    self, self._part.tile_rank(i, j), rb, re, cb, ce))
        return segs

    def tiles(self):
        return self.__dr_segments__()

    def tile(self, ij) -> MatrixTileSegment:
        i, j = ij
        nti, ntj = self._ntiles
        th, tw = self._tshape
        assert 0 <= i < nti and 0 <= j < ntj
        return MatrixTileSegment(
            self, self._part.tile_rank(i, j),
            i * th, min((i + 1) * th, self._m),
            j * tw, min((j + 1) * tw, self._n))

    # ----------------------------------------------------------- value APIs
    def to_array(self) -> jax.Array:
        if self.is_block:
            return self._data[:self._m, :self._n]
        return _unfold2d(self._mesh, self._grid, self._slots, self._tshape,
                         self._m, self._n, self._dtype)(self._data)

    def assign_array(self, values) -> None:
        values = jnp.asarray(values, self._dtype)
        assert values.shape == (self._m, self._n)
        self._data = _pack2d(self._mesh, self._grid, self._slots,
                             self._tshape, self._m, self._n,
                             self._dtype, self._sharding)(values)

    @classmethod
    def from_array(cls, values, partition=None, *, runtime=None):
        values = jnp.asarray(values)
        mat = cls(values.shape, values.dtype, partition, runtime=runtime)
        mat.assign_array(values)
        return mat

    def materialize(self) -> np.ndarray:
        from ..utils.host import to_host
        return to_host(self.to_array())

    def _stored_rc(self, r, c):
        """Logical (row, col) -> stored (folded) coordinates.  Works on
        scalars and jnp arrays alike."""
        gp, gq = self._grid
        si, sj = self._slots
        th, tw = self._tshape
        i, wr = r // th, r % th
        j, wc = c // tw, c % tw
        return (((i % gp) * si + i // gp) * th + wr,
                ((j % gq) * sj + j // gq) * tw + wc)

    def _local_tile(self, rank, rb, re, cb, ce):
        # each device owns one shard holding all its (slot-ordered) tiles
        th, tw = self._tshape
        si, sj = self._slots
        i, j = rb // th, cb // tw
        lr = (i // self._grid[0]) * th   # within-shard row of this tile
        lc = (j // self._grid[1]) * tw
        target = self._mesh.devices.reshape(-1)[rank]
        for sh in self._data.addressable_shards:
            if sh.device.id == target.id:
                return sh.data[lr:lr + (re - rb), lc:lc + (ce - cb)]
        return self.to_array()[rb:re, cb:ce]  # multi-host fallback

    # ------------------------------------------------ element/batched access
    def __getitem__(self, ij):
        i, j = ij
        if isinstance(i, slice) or isinstance(j, slice):
            from ..views.matrix_views import dense_matrix_view
            ri = range(*i.indices(self._m)) if isinstance(i, slice) \
                else range(i, i + 1)
            rj = range(*j.indices(self._n)) if isinstance(j, slice) \
                else range(j, j + 1)
            return dense_matrix_view(self, ri.start, ri.stop,
                                     rj.start, rj.stop)
        i, j = int(i), int(j)
        if i < 0:
            i += self._m
        if j < 0:
            j += self._n
        if not (0 <= i < self._m and 0 <= j < self._n):
            raise IndexError((i, j))
        si, sj = self._stored_rc(i, j)
        return self._data[si, sj].item()

    def __setitem__(self, ij, value) -> None:
        i, j = int(ij[0]), int(ij[1])
        if not (0 <= i < self._m and 0 <= j < self._n):
            raise IndexError((i, j))
        si, sj = self._stored_rc(i, j)
        self._data = self._data.at[si, sj].set(
            jnp.asarray(value, self._dtype))

    def _check_rc(self, rows, cols):
        """Numpy-convention negatives + strict bounds (same contract as
        distributed_vector.get/put: no silent wrapping — folded storage
        would alias out-of-range indices onto OTHER valid elements)."""
        rows = np.asarray(rows)
        cols = np.asarray(cols)
        rows = np.where(rows < 0, rows + self._m, rows)
        cols = np.where(cols < 0, cols + self._n, cols)
        if ((rows < 0) | (rows >= self._m)).any() or \
                ((cols < 0) | (cols >= self._n)).any():
            raise IndexError(
                f"index out of range for shape {(self._m, self._n)}")
        return rows, cols

    def get(self, rows, cols):
        """Batched element gather."""
        rows, cols = self._check_rc(rows, cols)
        sr, sc = self._stored_rc(jnp.asarray(rows), jnp.asarray(cols))
        return self._data[sr, sc]

    def put(self, rows, cols, values) -> None:
        rows, cols = self._check_rc(rows, cols)
        sr, sc = self._stored_rc(jnp.asarray(rows), jnp.asarray(cols))
        self._data = self._data.at[sr, sc].set(
            jnp.asarray(values, self._dtype))

    def block_until_ready(self):
        jax.block_until_ready(self._data)
        return self

    def __repr__(self):
        return (f"dense_matrix(shape={self.shape}, grid={self._grid}, "
                f"tile={self._tshape}, dtype={self._dtype})")


_cache: dict = TappedCache()


def _zeros2d(mesh, mm, nn, dtype, sharding):
    key = ("z2", pinned_id(mesh), mm, nn, str(dtype))
    fn = _cache.get(key)
    if fn is None:
        fn = jax.jit(lambda: jnp.zeros((mm, nn), dtype),
                     out_shardings=sharding)
        _cache[key] = fn
    return fn()


def fold_ops(grid, slots, tshape, m, n):
    """(unfold, fold) PURE fns between the FOLDED stored layout and the
    logical (m, n) array — the single home of the folding permutation
    (also used inside algorithm programs, e.g. algorithms/stencil2d.py).

    Folding permutes tile-rows/cols from logical (slot-major, device-
    minor: tile i lives at (i // gp, i % gp)) to stored (device-major,
    slot-minor) order so the cyclic placement becomes a plain 2-D block
    sharding.  With slots == (1, 1) the permutation is the identity."""
    gp, gq = grid
    si, sj = slots
    th, tw = tshape
    mm, nn = gp * si * th, gq * sj * tw

    def unfold(data):
        lg = data
        if slots != (1, 1):
            lg = (lg.reshape(gp, si, th, gq, sj, tw)
                  .transpose(1, 0, 2, 4, 3, 5).reshape(mm, nn))
        return lg[:m, :n]

    def fold(logical):
        out = jnp.zeros((mm, nn), logical.dtype).at[:m, :n].set(logical)
        if slots != (1, 1):
            out = (out.reshape(si, gp, th, sj, gq, tw)
                   .transpose(1, 0, 2, 4, 3, 5).reshape(mm, nn))
        return out

    return unfold, fold


def _pack2d(mesh, grid, slots, tshape, m, n, dtype, sharding):
    """Logical (m, n) -> padded FOLDED stored array (jitted, sharded)."""
    key = ("p2", pinned_id(mesh), grid, slots, tshape, m, n, str(dtype))
    fn = _cache.get(key)
    if fn is None:
        _, fold = fold_ops(grid, slots, tshape, m, n)
        fn = jax.jit(lambda values: fold(values.astype(dtype)),
                     out_shardings=sharding)
        _cache[key] = fn
    return fn


def _unfold2d(mesh, grid, slots, tshape, m, n, dtype):
    """Stored FOLDED array -> logical (m, n) view (jitted; inverse of
    :func:`_pack2d`'s permutation)."""
    key = ("u2", pinned_id(mesh), grid, slots, tshape, m, n, str(dtype))
    fn = _cache.get(key)
    if fn is None:
        unfold, _ = fold_ops(grid, slots, tshape, m, n)
        fn = jax.jit(unfold)
        _cache[key] = fn
    return fn
