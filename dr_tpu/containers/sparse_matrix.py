"""``sparse_matrix``: distributed sparse matrix, tiled on the mesh.

TPU re-design of ``shp::sparse_matrix`` (``shp/containers/
sparse_matrix.hpp``): the reference keeps one CSR triple
(values/rowptr/colind) per row tile on each GPU.  CSR's row-pointer
indirection is hostile to the TPU vector unit, so the device layout here is
**padded COO** — three dense arrays ``values/rows/cols`` of shape
``(nshards, K)`` sharded over the mesh axis, where K = max per-tile nnz and
padding carries value 0 (a no-op for SpMV).  ``rows`` are tile-local.  This
makes the whole SpMV one ``shard_map`` of vectorized gather + segment-sum —
no scalar loops, no dynamic shapes.

The CSR surface survives at the API level: construction from CSR triples,
``tile()/tiles()/segments()`` exposing per-tile (rowptr, cols, values)
views (csr_matrix_view parity), and ``generate_random_csr``-style random
init (sparse_matrix.hpp:286-336).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ..parallel import runtime as _rt

__all__ = ["sparse_matrix", "random_sparse_matrix", "CsrTileSegment"]


class CsrTileSegment:
    """One tile's sparse triple, with rank — the ``csr_matrix_view``
    analog (shp/views/csr_matrix_view.hpp).  Row-tiled matrices have
    ``cb = 0``; 2-D partitions carry the tile's column window too
    (sparse_matrix.hpp:344-349: tiles come from the same
    matrix_partition machinery as dense)."""

    __slots__ = ("base", "_rank", "rb", "re", "cb", "ce")

    def __init__(self, base, rank, rb, re, cb=0, ce=None):
        self.base = base
        self._rank = rank
        self.rb, self.re = rb, re
        self.cb = cb
        self.ce = base.shape[1] if ce is None else ce

    def __dr_rank__(self):
        return self._rank

    @property
    def shape(self):
        return (self.re - self.rb, self.ce - self.cb)

    def __len__(self):
        return int(self.nnz)

    @property
    def nnz(self):
        return self.base._tile_nnz[self._rank]

    def triples(self):
        """(rows, cols, values) with GLOBAL ids, host numpy."""
        k = int(self.base._tile_nnz[self._rank])
        rows = np.asarray(self.base._rows[self._rank][:k]) + self.rb
        cols = np.asarray(self.base._cols[self._rank][:k]) + self.cb
        vals = np.asarray(self.base._vals[self._rank][:k])
        return rows, cols, vals

    def csr(self):
        """(rowptr, cols, values) tile-local CSR, host numpy."""
        rows, cols, vals = self.triples()
        rows = rows - self.rb
        m = self.re - self.rb
        rowptr = np.zeros(m + 1, dtype=np.int64)
        np.add.at(rowptr[1:], rows, 1)
        rowptr = np.cumsum(rowptr)
        order = np.argsort(rows, kind="stable")
        return rowptr, cols[order], vals[order]

    def __iter__(self):
        rows, cols, vals = self.triples()
        from .dense_matrix import matrix_entry
        for r, c, v in zip(rows, cols, vals):
            yield matrix_entry((int(r), int(c)), v)

    def __repr__(self):
        return (f"CsrTileSegment(rank={self._rank}, rows=[{self.rb},"
                f"{self.re}), cols=[{self.cb},{self.ce}), "
                f"nnz={int(self.nnz)})")


class sparse_matrix:
    """Distributed sparse matrix (CSR surface, padded-COO device layout).

    Default partition is row tiles (grid (P, 1), the reference gemv's
    required shape); any ``block_cyclic`` grid with ``gp*gq == nprocs``
    and ``tile.div`` tiles gives a 2-D tiling whose SpMV reduces
    partials over mesh columns (exceeding the reference's
    ``grid_shape[1]==1`` assert, gemv.hpp:21)."""

    def __init__(self, shape: Tuple[int, int], dtype=None, *,
                 partition=None, runtime=None):
        self._rt = runtime or _rt.runtime()
        self._m, self._n = int(shape[0]), int(shape[1])
        self._dtype = jnp.dtype(dtype) if dtype is not None else jnp.float32
        P = self._rt.nprocs
        if partition is None:
            gp, gq = P, 1
        else:
            from .partition import block_cyclic, tile as _tile
            assert isinstance(partition, block_cyclic)
            gp, gq = partition.grid_for(P)
            assert gp * gq == P, \
                "sparse grids place one tile per device (gp*gq == nprocs)"
            assert partition.tile == (_tile.div, _tile.div), \
                "sparse tiles are tile.div (one block per device)"
        self._grid = (gp, gq)
        self._nshards = P
        self._th = -(-self._m // gp)  # rows per tile
        self._tw = -(-self._n // gq)  # cols per tile
        self._vals = None
        self._rows = None
        self._cols = None
        self._ell_vals = None
        self._ell_cols = None
        self._ell_width = 0
        self._bcsr_vals = None
        self._bcsr_cols = None
        self._bcsr_kb = 0
        self._bcsr_nbr = 0
        self._bcsr_state = "maybe"
        self._ring_vals = None
        self._ring_cols = None
        self._ring_kr = 0
        self._ring_bw = 0
        self._ring_state = "maybe"
        self._format = "csr"     # autoselect (round 9) refines at build
        self._row_kmax = None    # per-tile-row max nnz (ELL width hint)
        self._bcsr_scan_cached = None  # build-time pass-1 handoff
        self._tile_nnz = np.zeros(P, dtype=np.int64)
        self._nnz = 0

    # ------------------------------------------------------------- builders
    @classmethod
    def from_coo(cls, shape, rows, cols, values, *, partition=None,
                 runtime=None):
        """Build from global COO triples (any order)."""
        self = cls(shape, np.asarray(values).dtype, partition=partition,
                   runtime=runtime)
        rows = np.asarray(rows, np.int64)
        cols = np.asarray(cols, np.int64)
        values = np.asarray(values)
        P, th, tw = self._nshards, self._th, self._tw
        gp, gq = self._grid
        tile_of = (rows // th) * gq + cols // tw
        order = np.argsort(tile_of, kind="stable")
        rows, cols, values, tile_of = (rows[order], cols[order],
                                       values[order], tile_of[order])
        counts = np.bincount(tile_of, minlength=P)
        K = max(int(counts.max()), 1) if len(rows) else 1
        vals_h = np.zeros((P, K), dtype=self._dtype)
        rows_h = np.zeros((P, K), dtype=np.int32)
        cols_h = np.zeros((P, K), dtype=np.int32)
        start = 0
        for t in range(P):
            c = int(counts[t])
            sl = slice(start, start + c)
            vals_h[t, :c] = values[sl]
            rows_h[t, :c] = rows[sl] - (t // gq) * th  # tile-local rows
            cols_h[t, :c] = cols[sl] - (t % gq) * tw   # tile-local cols
            start += c
        sh = NamedSharding(self._rt.mesh, PartitionSpec(self._rt.axis, None))
        self._vals = jax.device_put(jnp.asarray(vals_h), sh)
        self._rows = jax.device_put(jnp.asarray(rows_h), sh)
        self._cols = jax.device_put(jnp.asarray(cols_h), sh)
        self._tile_nnz = counts.astype(np.int64)
        self._nnz = int(len(rows))
        self._decide_format(counts, rows_h, cols_h)
        self._rt.register(self)
        return self

    # padding blowup bound for the ELL layout: rows*kmax <= factor * K
    _ELL_FACTOR = 4

    def _decide_format(self, counts, rows_h, cols_h) -> None:
        """Measured format AUTOSELECT (round 9): pick the SpMV layout
        from the row-length distribution at build time, so an
        adversarial long-row matrix never pays the ELL ``kmax`` padding
        blowup (the scan that would discover the skew lazily is itself
        O(nnz) — deciding here reuses the host triples from_coo already
        holds).  The choice is advisory: the algorithm layer honors it
        (``DR_TPU_SPMV_FORMAT`` overrides at dispatch) and the lazy
        ``ensure_*`` gates remain the hard viability checks.

        Rule: block-structured sparsity (the ``ensure_bcsr`` gates —
        corrected occupiable-cell fill >= ``_BCSR_MIN_FILL``, bounded
        block-row skew — evaluated on the host triples) -> ``bcsr``
        (an ELL-skewed matrix with one dense BLOCK-row still keeps the
        MXU path); else ELL blowup (``th * kmax > _ELL_FACTOR * K``)
        -> ``csr`` (the padded-COO segment-sum path); else ``ell``.
        The ``ring`` (rotating-b) layout is opt-in via the env
        override / tuning ladder — its bucket padding trades compute
        for overlapped ICI, a trade only the chip can judge
        (docs/PERF.md round 9)."""
        P, th = self._nshards, self._th
        K = max(int(counts.max()), 1) if self._nnz else 1
        kmax = 1
        for t in range(P):
            c = int(counts[t])
            if c:
                kmax = max(kmax, int(np.bincount(
                    rows_h[t, :c], minlength=th).max()))
        self._row_kmax = kmax
        if self._nnz == 0:
            self._format = "csr"
            return
        scan = self._bcsr_scan(counts, rows_h, cols_h)
        bcsr_ok = scan[-1]
        if bcsr_ok:
            # hand the pass-1 result to the first ensure_bcsr build so
            # it never repeats this O(nnz log nnz) host scan; viable
            # matrices keep the tile keys small by construction
            # (fill >= 1/16 bounds tiles <= nnz/64)
            self._bcsr_scan_cached = scan
        else:
            self._bcsr_state = "no"  # the hard gate would re-reject
        if th * kmax > self._ELL_FACTOR * K:
            # remember the skew now: dispatch must not re-scan
            self._ell_width = -1
            self._ring_state = "no"
            self._format = "bcsr" if bcsr_ok else "csr"
            return
        self._format = "bcsr" if bcsr_ok else "ell"

    def _bcsr_scan(self, counts, rows_h, cols_h):
        """Pass 1 of the BCSR build — ONE home for the gate math:
        per-shard sorted tile keys (``per``), the block-ELL width
        ``kb``, block-rows per tile ``nbr``, and the viability verdict
        (occupiable-cell-corrected fill >= ``_BCSR_MIN_FILL`` AND
        block-row skew within ``_BCSR_FACTOR``).  Shared by
        :meth:`ensure_bcsr` (which builds the layout from ``per``) and
        the build-time autoselect (:meth:`_decide_format`), so the
        advisory choice and the hard gate can never drift apart."""
        P, th = self._nshards, self._th
        bh, bw = self._BCSR_BH, self._BCSR_BW
        nbr = -(-th // bh)
        gq = self._grid[1]
        per = []                            # (shard) -> {(br, cb)} maps
        kb = 1
        total_tiles = 0
        total_cells = 0
        for t in range(P):
            c = int(counts[t])
            keys = np.unique(
                (rows_h[t, :c] // bh).astype(np.int64) * (1 << 32)
                | (cols_h[t, :c] // bw).astype(np.int64))
            per.append(keys)
            total_tiles += len(keys)
            # occupiable cells only: a remainder block-row (unaligned
            # tile height) holds fewer than bh real rows, and the last
            # block-column of a narrow matrix fewer than bw real
            # columns — padding must not deflate the fill gate.  The
            # LAST tile's real height/width can be shorter than th/tw
            # too; kcb is TILE-local, so the column bound is the tile's
            # own width, not the full matrix width (round-2 advisor:
            # shape[1] here overcounts cells on 2-D grids).
            kbr = (keys >> 32).astype(np.int64)
            kcb = (keys & 0xFFFFFFFF).astype(np.int64)
            real_h = max(0, min(th, self._m - (t // gq) * th))
            real_w = max(0, min(self._tw, self._n - (t % gq) * self._tw))
            rows_in = np.maximum(np.minimum(bh, real_h - kbr * bh), 0)
            cols_in = np.maximum(np.minimum(bw, real_w - kcb * bw), 0)
            total_cells += int((rows_in * cols_in).sum())
            if c:
                kb = max(kb, int(np.bincount(kbr, minlength=nbr).max()))
        fill = self._nnz / max(total_cells, 1)
        # skew gate: the block-ELL width kb applies to EVERY block-row,
        # so one dense block-row must not balloon the allocation —
        # bound kb by the average occupancy (the _ELL_FACTOR analog).
        # Mostly empty matrices are already rejected by the fill gate.
        avg_kb = -(-total_tiles // max(P * nbr, 1))
        viable = (fill >= self._BCSR_MIN_FILL
                  and kb <= self._BCSR_FACTOR * max(avg_kb, 1))
        return per, kb, nbr, viable

    @property
    def format(self) -> str:
        """The autoselected SpMV layout (``csr``/``ell``/``bcsr``) —
        the bench artifact's chosen-format tag.  Dispatch-time env
        overrides (``DR_TPU_SPMV_FORMAT``) are not reflected here."""
        return self._format

    def ensure_ell(self) -> bool:
        """Build the row-grouped padded (ELL) device layout lazily:
        (P, th, kmax) arrays, created on the first SpMV that can use them
        (not at construction — matrices used only for iteration/views
        shouldn't pay a second device copy).

        TPU scatter-adds (segment_sum over a flat nnz stream) serialize;
        grouping each row's entries along a fixed-width axis turns SpMV
        into a dense gather + row-sum (algorithms/gemv.py).  Skipped when
        a skewed row would pad beyond _ELL_FACTOR x the COO footprint.
        Returns True when the layout is available.
        """
        if self._ell_vals is not None:
            return True
        if self._ell_width < 0 or self._vals is None:  # known-skewed / empty
            return False
        if not self._vals.is_fully_addressable:
            # multi-process SPMD: the host-side regroup would need remote
            # shards; the segment_sum path stays correct there
            return False
        counts = self._tile_nnz
        K = self._vals.shape[1]
        rows_h = np.asarray(self._rows)
        P, th = self._nshards, self._th
        # the autoselect already scanned the row-length distribution at
        # build time (every builder routes through from_coo, which runs
        # _decide_format before _vals exists)
        kmax = max(1, self._row_kmax)
        if th * kmax > self._ELL_FACTOR * max(K, 1):
            self._ell_width = -1  # remember the skew; don't retry
            return False
        self._ell_width = kmax
        vals_h = np.asarray(self._vals)
        cols_h = np.asarray(self._cols)
        ell_vals = np.zeros((P, th, kmax), dtype=self._dtype)
        ell_cols = np.zeros((P, th, kmax), dtype=np.int32)
        for t in range(P):
            c = int(counts[t])
            if not c:
                continue
            lr = rows_h[t, :c]
            idx = np.argsort(lr, kind="stable")
            lr_s = lr[idx]
            # rank of each entry within its row (first occurrence offset)
            pos = np.arange(c) - np.searchsorted(lr_s, lr_s)
            ell_vals[t, lr_s, pos] = vals_h[t, :c][idx]
            ell_cols[t, lr_s, pos] = cols_h[t, :c][idx]
        sh = NamedSharding(self._rt.mesh,
                           PartitionSpec(self._rt.axis, None, None))
        self._ell_vals = jax.device_put(jnp.asarray(ell_vals), sh)
        self._ell_cols = jax.device_put(jnp.asarray(ell_cols), sh)
        return True

    # BCSR blocks: MXU-friendly dense tiles (sublanes x lanes)
    _BCSR_BH = 8
    _BCSR_BW = 128
    # build the dense-block layout only when the blocks it creates hold
    # enough nnz that the 1024-element tiles pay for themselves
    _BCSR_MIN_FILL = 1.0 / 16.0
    # allocation skew bound: block-ELL tiles allocated <= factor x occupied
    _BCSR_FACTOR = 2

    def ensure_bcsr(self) -> bool:
        """Build the block-ELL (BCSR) device layout lazily: nnz grouped
        into dense (8, 128) tiles, tiles grouped by block-row with a
        fixed width — SpMV becomes ONE 128-slice gather of b per tile
        plus an MXU contraction (VERDICT r1 item 6; the reference's
        gemv.hpp:45-66 nnz-parallel kernel re-imagined for the MXU).

        Only viable when the sparsity is block-structured: returns False
        (and remembers) when the average tile fill is below
        ``_BCSR_MIN_FILL`` — unstructured patterns keep the ELL /
        segment-sum paths."""
        if self._bcsr_vals is not None:
            return True
        if self._bcsr_state == "no" or self._vals is None:
            return False
        if not self._vals.is_fully_addressable:
            return False
        bh, bw = self._BCSR_BH, self._BCSR_BW
        P = self._nshards
        counts = self._tile_nnz
        rows_h = np.asarray(self._rows)
        cols_h = np.asarray(self._cols)
        # pass 1 (shared gate math — :meth:`_bcsr_scan`): per-shard
        # block-row tile lists + the viability verdict; the values stay
        # on device until the gates admit the layout.  The build-time
        # autoselect already ran this scan and handed it over — consume
        # the cache (one build) instead of repeating the host sorts.
        # nbr = block-rows per shard tile; an unaligned tile height
        # gets a zero-padded remainder block-row (_bcsr_local slices
        # back to seg_out).
        scan = self._bcsr_scan_cached
        self._bcsr_scan_cached = None
        if scan is None:
            scan = self._bcsr_scan(counts, rows_h, cols_h)
        per, kb, nbr, viable = scan
        if not viable:
            self._bcsr_state = "no"
            return False
        vals_h = np.asarray(self._vals)
        # pass 2: dense tiles in block-ELL form
        bvals = np.zeros((P, nbr, kb, bh, bw), dtype=self._dtype)
        bcols = np.zeros((P, nbr, kb), dtype=np.int32)
        for t in range(P):
            c = int(counts[t])
            if not c:
                continue
            keys = per[t]
            br = (keys >> 32).astype(np.int64)
            cb = (keys & 0xFFFFFFFF).astype(np.int64)
            # slot within each block-row: keys are sorted (br, cb), so
            # slot = index - first index of the same block-row
            slot = np.arange(len(keys)) - np.searchsorted(br, br, "left")
            bcols[t, br, slot] = cb
            r = rows_h[t, :c]
            cc = cols_h[t, :c]
            key_e = ((r // bh).astype(np.int64) * (1 << 32)
                     | (cc // bw).astype(np.int64))
            pos = np.searchsorted(keys, key_e)
            np.add.at(bvals, (t, br[pos], slot[pos], r % bh, cc % bw),
                      vals_h[t, :c])
        sh = NamedSharding(self._rt.mesh,
                           PartitionSpec(self._rt.axis, *([None] * 4)))
        shc = NamedSharding(self._rt.mesh,
                            PartitionSpec(self._rt.axis, None, None))
        self._bcsr_vals = jax.device_put(jnp.asarray(bvals), sh)
        self._bcsr_cols = jax.device_put(jnp.asarray(bcols), shc)
        self._bcsr_kb = kb
        self._bcsr_nbr = nbr
        self._bcsr_state = "yes"
        return True

    # ring-bucket blowup bound: P * th * kr <= factor * K (the ELL
    # discipline applied to the per-step buckets)
    _RING_FACTOR = 4

    def ensure_ring(self) -> bool:
        """Build the RING-bucketed device layout lazily (round 9): the
        rotating-b SpMV schedule (algorithms/gemv.py ring programs over
        parallel/pipeline.py) needs each shard's entries grouped by the
        b-block held at each ring step.  b is block-sharded into
        ``nshards`` windows of ``bw = ceil(n / nshards)``; with the
        forward ring permutation, shard d holds block ``(d - t) %
        nshards`` at step t, so bucket ``[d, t]`` collects shard d's
        entries whose column falls in that window (columns stored
        BLOCK-local).  Buckets are per-row ELL-grouped — ``(P, P, th,
        kr)`` arrays with kr = max per-(shard, step, row) count — so
        each step's contraction is the same dense gather + row-sum as
        the ELL path, just against the held (1/P-sized) window.

        Viability gates: 1-D row-tiled grids with nshards > 1 only;
        the bucket padding must stay under ``_RING_FACTOR`` x the COO
        footprint (a banded matrix whose rows hit one block pays ~P x
        padding — rejected and remembered, like the ELL skew gate).
        Returns True when the layout is available."""
        if self._ring_vals is not None:
            return True
        if (self._ring_state == "no" or self._vals is None
                or self._nshards < 2 or self._grid[1] != 1):
            return False
        if not self._vals.is_fully_addressable:
            return False
        P, th = self._nshards, self._th
        bw = max(1, -(-self._n // P))
        counts = self._tile_nnz
        K = self._vals.shape[1]
        rows_h = np.asarray(self._rows)
        cols_h = np.asarray(self._cols)
        kr = 1
        for t in range(P):
            c = int(counts[t])
            if not c:
                continue
            step = (t - cols_h[t, :c] // bw) % P
            combo = step.astype(np.int64) * th + rows_h[t, :c]
            kr = max(kr, int(np.bincount(combo,
                                         minlength=P * th).max()))
        if P * th * kr > self._RING_FACTOR * max(K, 1):
            self._ring_state = "no"  # remember the skew; don't retry
            return False
        vals_h = np.asarray(self._vals)
        ring_vals = np.zeros((P, P, th, kr), dtype=self._dtype)
        ring_cols = np.zeros((P, P, th, kr), dtype=np.int32)
        for t in range(P):
            c = int(counts[t])
            if not c:
                continue
            src = cols_h[t, :c] // bw
            step = ((t - src) % P).astype(np.int64)
            rows_t = rows_h[t, :c]
            combo = step * th + rows_t
            order = np.argsort(combo, kind="stable")
            cs = combo[order]
            pos = np.arange(c) - np.searchsorted(cs, cs)
            ring_vals[t, step[order], rows_t[order], pos] = \
                vals_h[t, :c][order]
            ring_cols[t, step[order], rows_t[order], pos] = \
                (cols_h[t, :c] - src * bw)[order]
        sh = NamedSharding(self._rt.mesh,
                           PartitionSpec(self._rt.axis, None, None, None))
        self._ring_vals = jax.device_put(jnp.asarray(ring_vals), sh)
        self._ring_cols = jax.device_put(jnp.asarray(ring_cols), sh)
        self._ring_kr = kr
        self._ring_bw = bw
        self._ring_state = "yes"
        return True

    @classmethod
    def from_csr(cls, shape, rowptr, cols, values, *, partition=None,
                 runtime=None):
        """Build from a global CSR triple (the reference's construction
        path, sparse_matrix.hpp:286-336)."""
        rowptr = np.asarray(rowptr, np.int64)
        rows = np.repeat(np.arange(shape[0], dtype=np.int64),
                         np.diff(rowptr))
        return cls.from_coo(shape, rows, cols, values,
                            partition=partition, runtime=runtime)

    @classmethod
    def from_dense(cls, dense, *, partition=None, runtime=None):
        dense = np.asarray(dense)
        rows, cols = np.nonzero(dense)
        return cls.from_coo(dense.shape, rows, cols, dense[rows, cols],
                            partition=partition, runtime=runtime)

    # ------------------------------------------------------------------ meta
    @property
    def shape(self):
        return (self._m, self._n)

    @property
    def dtype(self):
        return self._dtype

    @property
    def nnz(self) -> int:
        return self._nnz

    @property
    def nshards(self):
        return self._nshards

    @property
    def tile_rows(self) -> int:
        return self._th

    @property
    def tile_cols(self) -> int:
        return self._tw

    @property
    def grid_shape(self):
        return self._grid

    @property
    def runtime(self):
        return self._rt

    def __len__(self):
        return self._nnz

    # ----------------------------------------------------------- vocabulary
    def __dr_segments__(self):
        segs = []
        gp, gq = self._grid
        for t in range(self._nshards):
            i, j = t // gq, t % gq
            rb = i * self._th
            re = min(self._m, rb + self._th)
            cb = j * self._tw
            ce = min(self._n, cb + self._tw)
            if rb < re and cb < ce and self._tile_nnz[t] > 0:
                segs.append(CsrTileSegment(self, t, rb, re, cb, ce))
        return segs

    def tiles(self):
        return self.__dr_segments__()

    def tile(self, ij) -> CsrTileSegment:
        i, j = (ij if isinstance(ij, tuple) else (ij, 0))
        gp, gq = self._grid
        assert 0 <= i < gp and 0 <= j < gq
        rb, cb = i * self._th, j * self._tw
        return CsrTileSegment(self, i * gq + j,
                              rb, min(self._m, rb + self._th),
                              cb, min(self._n, cb + self._tw))

    # ----------------------------------------------------------- value APIs
    def to_dense(self) -> np.ndarray:
        out = np.zeros((self._m, self._n), dtype=self._dtype)
        for seg in self.__dr_segments__():
            r, c, v = seg.triples()
            np.add.at(out, (r, c), v)
        return out

    def materialize(self):
        return self.to_dense()

    def block_until_ready(self):
        if self._vals is not None:
            jax.block_until_ready(self._vals)
        return self

    def __repr__(self):
        gp, gq = self._grid
        return (f"sparse_matrix(shape={self.shape}, nnz={self._nnz}, "
                f"tiles={gp}x{gq}, dtype={self._dtype})")


def random_sparse_matrix(shape, density=0.01, *, seed=0, partition=None,
                         runtime=None, dtype=np.float32):
    """Random sparse matrix (reference generate_random_csr,
    sparse_matrix.hpp:299-336)."""
    m, n = shape
    rng = np.random.default_rng(seed)
    nnz = max(1, int(m * n * density))
    flat = rng.choice(m * n, size=nnz, replace=False)
    rows, cols = flat // n, flat % n
    vals = rng.standard_normal(nnz).astype(dtype)
    return sparse_matrix.from_coo(shape, rows, cols, vals,
                                  partition=partition, runtime=runtime)
