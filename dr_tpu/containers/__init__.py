from .distributed_vector import distributed_vector, halo
from .partition import tile, matrix_partition, block_cyclic, row_tiles, factor
from .dense_matrix import dense_matrix, matrix_entry, Index2D
from .sparse_matrix import sparse_matrix, random_sparse_matrix
from .distributed_span import distributed_span
from .mdarray import distributed_mdarray, distributed_mdspan, transpose
