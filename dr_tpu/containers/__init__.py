from .distributed_vector import distributed_vector, halo
