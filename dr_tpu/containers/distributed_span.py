"""``distributed_span``: non-owning distributed range over a segment list.

TPU re-design of ``shp::distributed_span``
(``shp/distributed_span.hpp:191-225``): wraps ANY list of segments and
provides rank-preserving ``subspan/first/last`` that re-slice across
segment boundaries.  Segments keep referencing their original containers;
the span itself owns nothing.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from ..core.vocabulary import rank, segments as _segments
from ..views.views import drop_segments, take_segments

__all__ = ["distributed_span"]


class distributed_span:
    def __init__(self, segs: Sequence):
        self._segs = list(segs)

    @classmethod
    def of(cls, r) -> "distributed_span":
        return cls(_segments(r))

    def __len__(self) -> int:
        return sum(len(s) for s in self._segs)

    def __dr_segments__(self):
        return list(self._segs)

    # -- rank-preserving re-slicing (distributed_span.hpp:191-225) ---------
    def subspan(self, offset: int, count: int) -> "distributed_span":
        return distributed_span(
            take_segments(drop_segments(self._segs, offset), count))

    def first(self, count: int) -> "distributed_span":
        return self.subspan(0, count)

    def last(self, count: int) -> "distributed_span":
        return self.subspan(len(self) - count, count)

    def __getitem__(self, key):
        if isinstance(key, slice):
            start, stop, step = key.indices(len(self))
            assert step == 1
            return self.subspan(start, stop - start)
        return self.materialize()[key]

    def materialize(self) -> np.ndarray:
        if not self._segs:
            return np.array([])
        return np.concatenate([np.asarray(s.materialize())
                               for s in self._segs])

    def to_array(self):
        import jax.numpy as jnp
        return jnp.asarray(self.materialize())

    def __iter__(self):
        return iter(self.materialize())

    def __repr__(self):
        return (f"distributed_span(n={len(self)}, "
                f"segments={len(self._segs)})")
