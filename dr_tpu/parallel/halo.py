"""Structured halo (ghost-cell) exchange over the TPU mesh.

TPU-native re-design of the reference's halo engine
(``include/dr/details/halo.hpp``):

* ``halo_bounds{prev,next,periodic}``          (halo.hpp:315-331)
* ``span_halo::exchange / exchange_begin / exchange_finalize``
  (halo.hpp:55-70, 343-386)
* ghost->owner reductions with ``second/plus/max/min/multiplies`` ops
  (halo.hpp:73-110)

Where the reference packs edge spans into MPI_Isend/Irecv buffers between
ranks, here each exchange is ONE jitted ``shard_map`` program: edge slices
of every shard move to their neighbor with ``lax.ppermute`` over the mesh
axis (ICI neighbor traffic — the ring shape of context/sequence-parallel
comms), and ghost slots are written functionally.  ``exchange_begin`` is
async by construction (JAX dispatch); ``exchange_finalize`` blocks.

Layout contract (mirrors mhp::distributed_vector, dv.hpp:190-206): each
shard row is ``[ghost_prev(prev) | owned(seg) | ghost_next(next)]``; after
``exchange()``:

* ``ghost_prev`` of rank r  ==  last ``prev`` owned cells of rank r-1,
* ``ghost_next`` of rank r  ==  first ``next`` owned cells of rank r+1,

with ring wraparound iff ``periodic`` (halo.hpp:363-381); non-periodic edge
ghosts are left untouched, as in the reference.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P
from ..core.pinning import pinned_id
from ..utils import faults as _faults
from ..utils.spmd_guard import TappedCache
from ..utils.env import env_flag, env_str

__all__ = ["halo_bounds", "span_halo", "halo_ops"]


@dataclass(frozen=True)
class halo_bounds:
    """Ghost-region widths + ring flag (reference halo.hpp:315-331)."""
    prev: int = 0
    next: int = 0
    periodic: bool = False

    def __post_init__(self):
        assert self.prev >= 0 and self.next >= 0

    @property
    def width(self) -> int:
        return self.prev + self.next


def radius(r: int, periodic: bool = False) -> halo_bounds:
    return halo_bounds(r, r, periodic)


class halo_ops:
    """Fold ops for ghost->owner reduction (reference halo.hpp:92-110)."""
    second = "second"
    plus = "plus"
    max = "max"
    min = "min"
    multiplies = "multiplies"


def _combine(op: str, owned, incoming):
    if op == halo_ops.second:
        return incoming
    if op == halo_ops.plus:
        return owned + incoming
    if op == halo_ops.max:
        return jnp.maximum(owned, incoming)
    if op == halo_ops.min:
        return jnp.minimum(owned, incoming)
    if op == halo_ops.multiplies:
        return owned * incoming
    raise ValueError(f"unknown halo reduction op: {op}")


def _ring_perms(nshards: int, periodic: bool):
    """(forward, backward) ppermute pairs along the mesh axis ring."""
    fwd = [(i, i + 1) for i in range(nshards - 1)]
    bwd = [(i + 1, i) for i in range(nshards - 1)]
    if periodic:
        fwd = fwd + [(nshards - 1, 0)]
        bwd = bwd + [(0, nshards - 1)]
    return fwd, bwd


def _uniform_valid(nshards, seg, n) -> bool:
    """True when every shard's valid width equals ``seg`` (aligned sizes
    and the single-shard case).  Then ``valid`` is a PYTHON int and every
    edge slice and ghost write gets a STATIC offset XLA can fold/fuse;
    only a ragged tail pays per-shard dynamic offsets.
    ``DR_TPU_HALO_DYNAMIC=1`` forces the dynamic-offset path for A/B
    measurement (tools/tune_tpu.py halo)."""
    if env_flag("DR_TPU_HALO_DYNAMIC"):
        return False
    return n - (nshards - 1) * seg == seg


def _ghost_updates(axis, nshards, prev, nxt, periodic):
    """Per-round ghost computation shared by exchange and exchange_n:
    read the owned edges of ``blk``, ship them over the ring, combine
    with the OLD ghost values (kept on non-periodic edge shards).
    Returns ``(new_p, new_n)``; either is None when that width is 0."""
    fwd, bwd = _ring_perms(nshards, periodic)

    def compute(blk, valid, old_p, old_n):
        idx = lax.axis_index(axis)
        new_p = new_n = None
        if prev:
            # last `prev` VALID owned cells -> next rank's ghost_prev
            send = lax.dynamic_slice_in_dim(blk, prev + valid - prev, prev,
                                            axis=1)
            recv = lax.ppermute(send, axis, fwd)
            got = jnp.bool_(periodic) if (periodic or nshards == 1) \
                else idx > 0
            new_p = jnp.where(got, recv, old_p)
        if nxt:
            # first `nxt` owned cells -> prev rank's ghost_next, stored
            # IMMEDIATELY after the receiver's valid tail so every local
            # row is contiguous [ghost_prev | valid owned | ghost_next]
            # even on a short last shard
            send = blk[:, prev: prev + nxt]
            recv = lax.ppermute(send, axis, bwd)
            got = jnp.bool_(periodic) if (periodic or nshards == 1) \
                else idx < nshards - 1
            new_n = jnp.where(got, recv, old_n)
        return new_p, new_n

    return compute


def _row_valid(axis, nshards, seg, n):
    """Per-shard valid width: a PYTHON int on uniform layouts (static
    offsets everywhere), else traced from the shard index."""
    tail = n - (nshards - 1) * seg
    if _uniform_valid(nshards, seg, n):
        return lambda: seg
    return lambda: jnp.where(lax.axis_index(axis) == nshards - 1,
                             tail, seg)


def _ghost_reads(blk, valid, prev, nxt):
    """Current ghost regions of a shard row: (old_p, old_n); either is
    None when that width is 0.  ghost_next sits right after the valid
    tail (contiguous short-shard layout)."""
    old_p = blk[:, :prev] if prev else None
    old_n = lax.dynamic_slice_in_dim(blk, prev + valid, nxt, axis=1) \
        if nxt else None
    return old_p, old_n


def _ghost_writeback(blk, valid, prev, nxt, new_p, new_n):
    """Write updated ghost regions back into a shard row."""
    new = blk
    if new_p is not None:
        new = new.at[:, :prev].set(new_p)
    if new_n is not None:
        new = lax.dynamic_update_slice_in_dim(new, new_n, prev + valid,
                                              axis=1)
    return new


def _exchange_body(axis, nshards, seg, prev, nxt, periodic, n):
    """Shard-local exchange body (one padded row in, one out).

    The last shard may be logically short (pad-and-mask layout); its valid
    tail is ``n - (nshards-1)*seg``, so edge sends slice at a per-shard
    dynamic offset instead of assuming a full segment.  Uniform layouts
    (tail == seg) use static offsets throughout — see _uniform_valid.
    """
    valid_of = _row_valid(axis, nshards, seg, n)
    compute = _ghost_updates(axis, nshards, prev, nxt, periodic)

    def body(blk):  # blk: (1, prev + seg + nxt) — one shard row
        valid = valid_of()
        old_p, old_n = _ghost_reads(blk, valid, prev, nxt)
        new_p, new_n = compute(blk, valid, old_p, old_n)
        return _ghost_writeback(blk, valid, prev, nxt, new_p, new_n)

    return body


def _exchange_program(mesh, axis, nshards, seg, prev, nxt, periodic, n):
    """One jitted halo-exchange shard_map program for one layout."""
    body = _exchange_body(axis, nshards, seg, prev, nxt, periodic, n)
    shmapped = jax.shard_map(
        body, mesh=mesh, in_specs=P(axis, None), out_specs=P(axis, None))
    return jax.jit(shmapped, donate_argnums=0)


def _exchange_n_body(axis, nshards, seg, prev, nxt, periodic, n, iters):
    """Un-jitted shard-row body of ``iters`` fused exchanges — shared by
    :func:`_exchange_n_program` and the deferred-plan emitter
    (dr_tpu/plan.py), so the two paths cannot drift.

    The loop carries ONLY the ghost regions: an exchange never writes
    owned cells, so each round reads the same owned edges from the
    (closed-over) row and the full row is written ONCE after the loop.
    The row-carried variant (``DR_TPU_HALO_NCARRY=row``, kept for A/B)
    paid two full-row copies per round for the functional loop carry —
    O(row) per exchange instead of O(ghost width), which dominated the
    measured p50 (the bench halo config carries a 16 MB row for 8 KB of
    ghost traffic).  Ghost-carry matches the reference engine's cost
    model: it ships edge buffers, never the local array (halo.hpp:55-90).
    """
    if env_str("DR_TPU_HALO_NCARRY", "ghost") == "row":
        body = _exchange_body(axis, nshards, seg, prev, nxt, periodic, n)

        def loop(blk):
            return lax.fori_loop(0, iters, lambda i, x: body(x), blk)
    else:
        valid_of = _row_valid(axis, nshards, seg, n)
        compute = _ghost_updates(axis, nshards, prev, nxt, periodic)

        def loop(blk):
            valid = valid_of()
            init = [g for g in _ghost_reads(blk, valid, prev, nxt)
                    if g is not None]

            def round_(_, carry):
                it = iter(carry)
                old_p = next(it) if prev else None
                old_n = next(it) if nxt else None
                new_p, new_n = compute(blk, valid, old_p, old_n)
                return tuple(x for x in (new_p, new_n) if x is not None)

            fin = iter(lax.fori_loop(0, iters, round_, tuple(init)))
            return _ghost_writeback(blk, valid, prev, nxt,
                                    next(fin) if prev else None,
                                    next(fin) if nxt else None)

    return loop


def _exchange_n_program(mesh, axis, nshards, seg, prev, nxt, periodic, n,
                        iters):
    """``iters`` exchanges fused into ONE program (lax.fori_loop): no host
    dispatch between rounds — the device-side latency of a single ring
    exchange is this program's time / iters."""
    loop = _exchange_n_body(axis, nshards, seg, prev, nxt, periodic, n,
                            iters)
    shmapped = jax.shard_map(
        loop, mesh=mesh, in_specs=P(axis, None), out_specs=P(axis, None))
    return jax.jit(shmapped, donate_argnums=0)


def _reduce_body(axis, nshards, seg, prev, nxt, periodic, op, n):
    """Un-jitted shard-row body of the ghost->owner fold — shared by
    :func:`_reduce_program` and the deferred-plan emitter."""
    fwd, bwd = _ring_perms(nshards, periodic)
    tail = n - (nshards - 1) * seg
    uniform = _uniform_valid(nshards, seg, n)

    def body(blk):
        S = prev + seg + nxt
        new = blk
        idx = lax.axis_index(axis)
        valid = seg if uniform else \
            jnp.where(idx == nshards - 1, tail, seg)
        if prev:
            # my ghost_prev mirrors rank r-1's LAST `prev` valid owned
            # cells: ship it backward and fold there.
            send = blk[:, :prev]
            recv = lax.ppermute(send, axis, bwd)
            if periodic or nshards == 1:
                got = jnp.bool_(periodic)
            else:
                got = idx < nshards - 1
            start = prev + valid - prev
            owned = lax.dynamic_slice_in_dim(blk, start, prev, axis=1)
            folded = jnp.where(got, _combine(op, owned, recv), owned)
            new = lax.dynamic_update_slice_in_dim(new, folded, start, axis=1)
        if nxt:
            # my ghost_next (stored right after my valid tail) mirrors rank
            # r+1's FIRST `nxt` owned cells.
            send = lax.dynamic_slice_in_dim(blk, prev + valid, nxt, axis=1)
            recv = lax.ppermute(send, axis, fwd)
            if periodic or nshards == 1:
                got = jnp.bool_(periodic)
            else:
                got = idx > 0
            owned = new[:, prev: prev + nxt]
            new = new.at[:, prev: prev + nxt].set(
                jnp.where(got, _combine(op, owned, recv), owned))
        return new

    return body


def _reduce_program(mesh, axis, nshards, seg, prev, nxt, periodic, op, n):
    """Reverse path: fold ghost contributions back into their owners."""
    body = _reduce_body(axis, nshards, seg, prev, nxt, periodic, op, n)
    shmapped = jax.shard_map(
        body, mesh=mesh, in_specs=P(axis, None), out_specs=P(axis, None))
    return jax.jit(shmapped, donate_argnums=0)


_program_cache: dict = TappedCache()


def _cached(kind, mesh, axis, nshards, seg, prev, nxt, periodic, n, op=None,
            iters=1):
    # the tuning knobs select a different program body: key them so
    # in-process sweeps (tools/tune_tpu.py halo) don't reuse the other
    # arm's cached program
    knobs = (env_str("DR_TPU_HALO_NCARRY", "ghost"),
             env_str("DR_TPU_HALO_DYNAMIC"))
    key = (kind, pinned_id(mesh), axis, nshards, seg, prev, nxt, periodic, n, op,
           iters, knobs)
    prog = _program_cache.get(key)
    if prog is None:
        if kind == "exchange":
            prog = _exchange_program(mesh, axis, nshards, seg, prev, nxt,
                                     periodic, n)
        elif kind == "exchange_n":
            prog = _exchange_n_program(mesh, axis, nshards, seg, prev, nxt,
                                       periodic, n, iters)
        else:
            prog = _reduce_program(mesh, axis, nshards, seg, prev, nxt,
                                   periodic, op, n)
        _program_cache[key] = prog
    return prog


class span_halo:
    """Halo controller bound to one distributed_vector.

    API parity with the reference's ``span_halo`` / ``halo_impl``
    (halo.hpp:55-90): ``exchange()``, ``exchange_begin()/exchange_finalize()``,
    ``reduce(op)`` and per-op helpers.  The min-size check mirrors
    halo.hpp:354-356 (owned block must cover both edge sends).
    """

    def __init__(self, dv):
        self._dv = dv
        hb = dv.halo_bounds
        if hb.width and dv.segment_size < max(hb.prev, hb.next):
            raise ValueError(
                "segment smaller than halo radius "
                f"(segment_size={dv.segment_size}, halo={hb})")
        # Min-size checks (the reference's halo.hpp:354-356, generalized to
        # the padded-last-shard layout).  Every shard must be nonempty; with
        # a periodic ring the wraparound actually READS the last shard's
        # edge, so its logical tail must cover the radius.  Non-periodic
        # short tails are fine: the affected ghost cells are only adjacent
        # to out-of-range positions and are never consumed by interior
        # stencil points (same "unspecified edge ghosts" contract as the
        # reference's first/last rank).
        tail = len(dv) - (dv.nshards - 1) * dv.segment_size
        if hb.width and dv.nshards > 1 and tail < 1:
            raise ValueError(
                "halo requires every shard to own at least one "
                f"element (n={len(dv)}, shards={dv.nshards}, "
                f"segment={dv.segment_size})")
        if hb.width and hb.periodic and tail < max(hb.prev, hb.next):
            # applies at EVERY shard count: at nshards == 1 the "tail"
            # is the whole logical vector, and a ring radius wider than
            # it would need ghosts wrapping around more than once
            # (round-3 fuzz catch) — reject like halo.hpp:354-356
            raise ValueError(
                f"periodic halo: last shard owns {tail} element(s), "
                f"smaller than the radius {max(hb.prev, hb.next)}; "
                "grow the vector or shrink the mesh")

    @property
    def bounds(self) -> halo_bounds:
        return self._dv.halo_bounds

    def _run(self, kind: str, op: str | None = None) -> None:
        dv = self._dv
        hb = dv.halo_bounds
        if hb.width == 0 or dv.nshards == 0:
            return
        from ..plan import active as _plan_active
        p = _plan_active()
        if p is not None:
            # deferred region: the exchange/reduce body fuses into the
            # plan's run (the flush dispatches under the plan.flush site)
            p.record_halo(dv, kind, op)
            return
        # injection sites fire BEFORE the dispatch: a faulted exchange
        # never enqueues, so the container's value stays consistent
        _faults.fire("halo.reduce" if kind == "reduce"
                     else "halo.exchange")
        prog = _cached(kind, dv.runtime.mesh, dv.runtime.axis, dv.nshards,
                       dv.segment_size, hb.prev, hb.next, hb.periodic,
                       len(dv), op)
        dv._data = prog(dv._data)

    # -- exchange: owner edges -> neighbor ghosts ---------------------------
    def exchange(self) -> None:
        self._run("exchange")

    def exchange_n(self, iters: int) -> None:
        """``iters`` back-to-back exchanges fused in one device program —
        for multi-round patterns (and for measuring per-exchange device
        latency without per-dispatch overhead)."""
        dv = self._dv
        hb = dv.halo_bounds
        if hb.width == 0 or dv.nshards == 0 or iters <= 0:
            return
        from ..plan import active as _plan_active
        p = _plan_active()
        if p is not None:
            p.record_halo(dv, "exchange_n", None, iters)
            return
        _faults.fire("halo.exchange")
        prog = _cached("exchange_n", dv.runtime.mesh, dv.runtime.axis,
                       dv.nshards, dv.segment_size, hb.prev, hb.next,
                       hb.periodic, len(dv), None, iters)
        dv._data = prog(dv._data)

    def exchange_begin(self) -> None:
        # JAX dispatch is asynchronous; begin == enqueue the program.
        self._run("exchange")

    def exchange_finalize(self) -> None:
        from ..plan import flush_reads
        flush_reads("exchange_finalize")
        jax.block_until_ready(self._dv._data)

    # -- reduce: ghosts -> owner fold (halo.hpp:73-110) ---------------------
    def reduce(self, op: str = halo_ops.plus) -> None:
        self._run("reduce", op)

    def reduce_begin(self, op: str = halo_ops.plus) -> None:
        self._run("reduce", op)

    def reduce_finalize(self) -> None:
        from ..plan import flush_reads
        flush_reads("reduce_finalize")
        jax.block_until_ready(self._dv._data)

    def reduce_plus(self):
        self.reduce(halo_ops.plus)

    def reduce_max(self):
        self.reduce(halo_ops.max)

    def reduce_min(self):
        self.reduce(halo_ops.min)

    def reduce_multiplies(self):
        self.reduce(halo_ops.multiplies)
