"""Communicator surface: collectives + point-to-point + RMA-window analog.

TPU re-design of ``lib::communicator`` / ``lib::rma_window``
(``include/dr/details/communicator.hpp``).  The reference wraps MPI:
byte-oriented nonblocking p2p with halo tags, bcast/scatter(v)/gather(v),
barrier, and one-sided windows (per-element Rget/Put + fence).

On a single-controller TPU mesh these become:

* ``bcast``      -> replicate an array across the mesh (device_put with a
                    replicated sharding; XLA broadcast over ICI),
* ``scatter``    -> shard a host/global array over the mesh axis,
* ``gather``     -> fetch a sharded array to a host value (valid
                    everywhere — improving the reference's root-only
                    results),
* ``send/recv``  -> ring shifts: ``shift_forward/backward`` wrap
                    ``lax.ppermute`` (the halo tags' data plane),
* ``alltoall``   -> ``lax.all_to_all`` over the mesh axis,
* ``rma_window`` -> batched get/put against a distributed_vector
                    (explicit-batch replacement for per-element RMA,
                    SURVEY.md §2.5), with fence/flush as readiness
                    barriers (arrays are values; ordering is program
                    order).

Multi-host (the MHP/DCN dimension) enters through ``init_distributed``:
the same mesh abstraction spans hosts via ``jax.distributed``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from . import runtime as _rt
from ..core.pinning import pinned_id
from ..utils import faults as _faults
from ..utils.spmd_guard import TappedCache

__all__ = ["communicator", "rma_window", "default_comm", "init_distributed"]


class communicator:
    """Typed mesh communicator (communicator.hpp:7-95 analog)."""

    def __init__(self, runtime=None):
        self._rt = runtime or _rt.runtime()

    # -- topology (communicator.hpp:21-26) ---------------------------------
    @property
    def size(self) -> int:
        return self._rt.nprocs

    def first(self) -> int:
        return 0

    def last(self) -> int:
        return self.size - 1

    def prev(self, rank: int) -> int:
        return (rank - 1) % self.size

    def next(self, rank: int) -> int:
        return (rank + 1) % self.size

    # -- collectives -------------------------------------------------------
    def barrier(self) -> None:
        self._rt.barrier()

    def bcast(self, values) -> jax.Array:
        """Replicate values on every device (communicator.hpp:32)."""
        sh = NamedSharding(self._rt.mesh, P())
        return jax.device_put(jnp.asarray(values), sh)

    def scatter(self, values) -> jax.Array:
        """Shard axis 0 of ``values`` over the mesh (communicator.hpp:36-45).
        Length must divide the mesh; pad-and-mask is the container layer's
        job (distributed_vector)."""
        values = jnp.asarray(values)
        assert values.shape[0] % self.size == 0, \
            "scatter: first dim must divide the mesh (use a container for " \
            "uneven sizes)"
        sh = NamedSharding(self._rt.mesh, P(self._rt.axis))
        return jax.device_put(values, sh)

    def gather(self, arr) -> np.ndarray:
        """Collect a (sharded) array to the host (communicator.hpp:47-62).
        Result is valid on every rank: single-controller reads are plain
        host copies, and in multi-process (MHP/DCN) runs non-addressable
        shards arrive via ``process_allgather`` (utils/host.to_host) —
        ``np.asarray`` alone cannot materialize them."""
        from ..utils.host import to_host
        return to_host(arr)

    def allgather(self, arr) -> np.ndarray:
        return self.gather(arr)

    # -- ring p2p: the halo tag data plane (communicator.hpp:64-85) --------
    def shift_forward(self, arr, periodic: bool = False) -> jax.Array:
        """Every shard's slice moves to the next rank (rank r -> r+1)."""
        return self._shift(arr, +1, periodic)

    def shift_backward(self, arr, periodic: bool = False) -> jax.Array:
        return self._shift(arr, -1, periodic)

    def _shift(self, arr, direction: int, periodic: bool) -> jax.Array:
        _faults.fire("collectives.shift")
        rt = self._rt
        n = self.size
        if direction > 0:
            perm = [(i, i + 1) for i in range(n - 1)]
            if periodic:
                perm.append((n - 1, 0))
        else:
            perm = [(i + 1, i) for i in range(n - 1)]
            if periodic:
                perm.append((0, n - 1))
        key = ("shift", pinned_id(rt.mesh), direction, periodic, arr.shape[1:],
               str(arr.dtype))
        prog = _shift_cache.get(key)
        if prog is None:
            body = jax.shard_map(
                lambda x: jax.lax.ppermute(x, rt.axis, perm),
                mesh=rt.mesh, in_specs=P(rt.axis),
                out_specs=P(rt.axis))
            prog = jax.jit(body)
            _shift_cache[key] = prog
        return prog(arr)

    def alltoall(self, arr) -> jax.Array:
        """lax.all_to_all over the mesh axis: arr (nshards, nshards, ...)
        sharded on axis 0; block (i, j) moves to shard j."""
        _faults.fire("collectives.alltoall")
        rt = self._rt
        key = ("a2a", pinned_id(rt.mesh), arr.shape[1:], str(arr.dtype))
        prog = _shift_cache.get(key)
        if prog is None:
            def body(x):  # x: (1, nshards, ...)
                return jax.lax.all_to_all(x, rt.axis, split_axis=1,
                                          concat_axis=0, tiled=False)
            shm = jax.shard_map(body, mesh=rt.mesh, in_specs=P(rt.axis),
                                out_specs=P(rt.axis))
            prog = jax.jit(shm)
            _shift_cache[key] = prog
        return prog(arr)


_shift_cache: dict = TappedCache()


def default_comm() -> communicator:
    """mhp::default_comm() analog (mhp/global.hpp:35)."""
    return communicator()


class rma_window:
    """One-sided access surface over a distributed_vector
    (communicator.hpp:97-149 analog).

    The reference's per-element MPI_Rget/MPI_Put is its documented slow
    path; here get/put are EXPLICITLY batched gathers/scatters compiled to
    one program per call.  fence/flush are readiness barriers: arrays are
    values, ordering is program order (SURVEY.md §5 "windows -> values").
    """

    def __init__(self, dv):
        self._dv = dv

    def get(self, indices):
        return self._dv.get(indices)

    def put(self, indices, values) -> None:
        self._dv.put(indices, values)

    def fence(self) -> None:
        jax.block_until_ready(self._dv._data)

    def flush(self, rank: Optional[int] = None) -> None:
        jax.block_until_ready(self._dv._data)


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None, **kw):
    """Multi-host (DCN) enablement — the MHP dimension.

    Wraps ``jax.distributed.initialize``: after it, ``jax.devices()`` spans
    every host and ``dr_tpu.init()`` builds a global mesh whose collectives
    ride ICI within a slice and DCN across hosts.  All hosts must run the
    same program in the same order — the SPMD discipline the reference gets
    from MPI (SURVEY.md §7 hard-part 6).
    """
    jax.distributed.initialize(coordinator_address, num_processes,
                               process_id, **kw)
    return _rt.init()
