"""Collective redistribution engine: device-side re-layout on the ring
(docs/SPEC.md §18).

``dr_tpu.redistribute()`` v1 (round 13, utils/elastic.py) was
host-staged: every re-layout gathered the whole logical array to the
host and scattered it back through the target layout's pack program —
correct, mesh-agnostic, and the elastic rescue's workhorse, but a full
host round trip for what is physically a shard-to-shard shuffle.  This
module is the collective lowering (ROADMAP item 1): the recipe from
"Memory-efficient array redistribution through portable collective
communication" (arXiv:2112.01075) on the shared ring machinery of
:mod:`.pipeline`.

* **Planner** (:func:`plan_moves`) — a STATIC diff of the src→dst
  block layouts: for each hop distance ``t`` the (contiguous)
  intersection of src shard ``r``'s owned window with dst shard
  ``(r+t) % p``'s window gives a per-rank send window; hops that move
  nothing are dropped (the minimal-sequence property) and the bucket
  width ``B_t`` is the largest window at that distance.
* **Exchange program** (:func:`_exchange_program`) — ONE jitted
  ``shard_map`` over the container's padded row: hop 0 is the local
  src∩dst copy, every other hop one masked
  :func:`~.pipeline.ring_exchange` bucket (``lax.ppermute`` with the
  offset-``t`` permutation, statically shaped, serial/pipelined issue
  orders bit-identical).  Peak extra device memory is ONE in-flight
  bucket — bounded by the largest transfer window, never a full
  replica.  The dst row is rebuilt from zeros, so pad/halo/tail cells
  land exactly as the host-staged pack program leaves them: the two
  impls are BIT-identical physical rows.
* **Dispatcher** (:func:`redistribute_vector`) — autoselects the
  collective program when src and dst share a mesh; everything else
  (cross-runtime hops, matrices) keeps the host-staged v1 route.
  ``DR_TPU_REDISTRIBUTE`` ∈ {``auto``, ``collective``, ``host``}
  overrides; a forced ``collective`` on an ineligible move falls back
  announced (``warn_fallback``), never silently wrong.  Inside
  ``dr_tpu.deferred()`` an eligible re-layout records FUSED into the
  surrounding run (``plan.record_redistribute`` — the container's
  layout metadata flips at record time, the data moves at flush, so
  later recorded ops key on the new geometry); the host-staged route
  stays a flush point (announced non-fusible cliff).
* **Failure model** — fault site ``redistribute.exchange`` fires at
  every engine dispatch BEFORE the program-cache lookup (plus
  ``collectives.ppermute``, the ring data plane's site): a faulted
  exchange surfaces classified with the container exactly as it was
  (the metadata rebind rolls back).  Obs records a ``redistribute``
  span with plan/exchange/rebind phases and a
  ``redistribute.bytes_moved`` counter; classified errors carry the
  trace tail like every resilience path.

The cross-mesh sort/scan reshard scratch moves
(:func:`reshard_copy`) route through the engine's cross-mesh arm —
same fault site, same span, same bytes counter — so the cross-mesh
fuzz arm exercises the engine for free.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..core.pinning import pinned_id
from ..utils import faults as _faults
from ..utils.env import env_str
from ..utils.spmd_guard import TappedCache
from .pipeline import fire_ppermute, ring_exchange, schedule_mode

__all__ = ["impl_mode", "plan_moves", "fire_exchange",
           "redistribute_vector", "reshard_copy"]

#: exchange-program cache (TappedCache: dispatch.cache / device.lost
#: ride every lookup, pin eviction purges dead-mesh entries)
_prog_cache: dict = TappedCache()


def impl_mode() -> str:
    """``DR_TPU_REDISTRIBUTE`` in {``auto``, ``collective``, ``host``};
    malformed values fall back to ``auto`` (a typo in a sweep must not
    brick every re-layout)."""
    mode = env_str("DR_TPU_REDISTRIBUTE").lower()
    return mode if mode in ("auto", "collective", "host") else "auto"


def fire_exchange(**ctx) -> None:
    """Dispatch-time hook for the ``redistribute.exchange`` fault
    site: every engine dispatcher (collective exchange, cross-mesh
    reshard transport, the deferred-plan pre-dispatch hook) calls this
    before its program-cache lookup, so an armed fault surfaces
    classified with the container untouched."""
    _faults.fire("redistribute.exchange", **ctx)


def _geometry(layout):
    from ..algorithms._common import layout_geometry
    return layout_geometry(layout)


def plan_moves(src_layout, dst_layout):
    """Static src→dst diff: ``(steps, bytes_factor)`` where ``steps``
    is a list of ``(t, B_t, send_lo, send_len)`` hops — at distance
    ``t`` rank ``r`` sends logical window ``[send_lo[r], send_lo[r] +
    send_len[r])`` (the src∩dst overlap with rank ``(r+t) % p``) in a
    bucket of static width ``B_t = max(send_len)``; zero-width hops
    are dropped.  ``bytes_factor`` is the total off-shard element
    count (the bytes-moved counter scales it by the dtype size)."""
    p, s_cap, s_prev, s_nxt, n, s_starts, s_sizes = _geometry(src_layout)
    dp, d_cap, d_prev, d_nxt, dn, d_starts, d_sizes = \
        _geometry(dst_layout)
    assert p == dp and n == dn, "redistribute: src/dst shard counts " \
        "and logical sizes must match on one mesh"
    steps = []
    moved = 0
    for t in range(1, p):
        lo = np.empty(p, np.int64)
        ln = np.empty(p, np.int64)
        for r in range(p):
            d = (r + t) % p
            a = max(int(s_starts[r]), int(d_starts[d]))
            b = min(int(s_starts[r]) + int(s_sizes[r]),
                    int(d_starts[d]) + int(d_sizes[d]))
            lo[r] = a
            ln[r] = max(0, b - a)
        bt = int(ln.max(initial=0))
        if bt > 0:
            steps.append((t, bt, lo, ln))
            moved += int(ln.sum())
    return steps, moved


def _exchange_body(axis, src_layout, dst_layout, dtype):
    """The shard_map exchange body (src padded row -> dst padded row)
    — shared verbatim between the eager program below and the
    deferred-plan fused emit (``plan.record_redistribute``)."""
    p, s_cap, s_prev, s_nxt, n, s_starts, s_sizes = _geometry(src_layout)
    _, d_cap, d_prev, d_nxt, _, d_starts, d_sizes = _geometry(dst_layout)
    src_width = s_prev + s_cap + s_nxt
    dst_width = d_prev + d_cap + d_nxt
    steps, _moved = plan_moves(src_layout, dst_layout)
    s_starts_c = jnp.asarray(np.asarray(s_starts))
    s_sizes_c = jnp.asarray(np.asarray(s_sizes))
    d_starts_c = jnp.asarray(np.asarray(d_starts))
    d_sizes_c = jnp.asarray(np.asarray(d_sizes))
    hops = [t for t, _, _, _ in steps]
    widths = {t: bt for t, bt, _, _ in steps}
    los = {t: jnp.asarray(lo) for t, _, lo, _ in steps}
    lens = {t: jnp.asarray(ln) for t, _, _, ln in steps}

    def body(row):
        r = lax.axis_index(axis)
        x = row[0]                                     # (src_width,)
        col = jnp.arange(dst_width) - d_prev
        g = d_starts_c[r] + col                        # dst global ids
        owned = (col >= 0) & (col < d_sizes_c[r])
        # hop 0: the local src∩dst copy (no collective)
        have0 = owned & (g >= s_starts_c[r]) \
            & (g < s_starts_c[r] + s_sizes_c[r])
        idx0 = jnp.clip(s_prev + g - s_starts_c[r], 0, src_width - 1)
        carry = jnp.where(have0, jnp.take(x, idx0),
                          jnp.zeros((), dtype))

        def make_bucket(t):
            # my send window for hop t, gathered from my src row
            lo = los[t][r]
            k = jnp.arange(widths[t])
            sidx = jnp.clip(s_prev + (lo + k) - s_starts_c[r], 0,
                            src_width - 1)
            return jnp.where(k < lens[t][r], jnp.take(x, sidx),
                             jnp.zeros((), dtype))

        def consume(t, carry, bucket):
            # arrival from rank s = r - t: globals [lo[s], lo[s]+ln[s])
            s = (r - t) % p
            lo = los[t][s]
            have = owned & (g >= lo) & (g < lo + lens[t][s])
            bidx = jnp.clip(g - lo, 0, widths[t] - 1)
            return jnp.where(have, jnp.take(bucket, bidx), carry)

        carry = ring_exchange(axis, p, carry, make_bucket, consume,
                              steps=hops)
        return carry[None]

    return body


def _exchange_program(mesh, axis, src_layout, dst_layout, dtype):
    key = ("rdx", pinned_id(mesh), axis, src_layout, dst_layout,
           str(dtype), schedule_mode())
    prog = _prog_cache.get(key)
    if prog is not None:
        return prog
    body = _exchange_body(axis, src_layout, dst_layout, jnp.dtype(dtype))
    shm = jax.shard_map(body, mesh=mesh, in_specs=P(axis, None),
                        out_specs=P(axis, None))
    # no donation: a mid-dispatch classified fault rolls the container
    # back onto this buffer (the rebind-rollback contract below)
    prog = jax.jit(shm)
    _prog_cache[key] = prog
    return prog


def _host_staged(cont, new_dist, rt):
    """The v1 route (cross-runtime hops, forced ``host`` impl, the
    elastic rescue/grow fallback): gather the logical value to the
    host, re-plan the layout, scatter through the target pack program.
    The bit-identity contract the collective program is fuzzed
    against."""
    from .. import obs as _obs
    t0 = _obs.now()
    values = cont.materialize()
    cont._rebind(rt, new_dist)
    cont.assign_array(values)
    _obs.complete("redistribute.phase", t0, cat="redistribute",
                  phase="host_staged", n=len(cont))
    return cont


def _collective(cont, new_dist, rt):
    """The eager collective dispatcher: metadata rebind first (kept
    data — validated, self-rolling-back), then ONE exchange-program
    dispatch, then the data rebind.  Any failure past the metadata
    flip (an injected ``redistribute.exchange`` fault, a backend
    error) rolls the rebind back — the container is exactly as it
    was, the classified error carries the trace tail."""
    from .. import obs as _obs
    src_rt = cont.runtime
    src_dist = cont.distribution
    src_layout = cont.layout
    old = cont._data
    t0 = _obs.now()
    cont._rebind(rt, new_dist, _data=old)
    dst_layout = cont.layout
    try:
        fire_exchange(src=str(src_layout), dst=str(dst_layout))
        fire_ppermute(what="redistribute")
        prog = _exchange_program(rt.mesh, rt.axis, src_layout,
                                 dst_layout, cont.dtype)
        _obs.complete("redistribute.phase", t0, cat="redistribute",
                      phase="plan")
        t1 = _obs.now()
        new = prog(old)
        _obs.complete("redistribute.phase", t1, cat="redistribute",
                      phase="exchange")
        t2 = _obs.now()
        cont._data = new
        _obs.complete("redistribute.phase", t2, cat="redistribute",
                      phase="rebind")
        _, moved = plan_moves(src_layout, dst_layout)
        _obs.count("redistribute.bytes_moved",
                   moved * jnp.dtype(cont.dtype).itemsize)
        return cont
    except BaseException:
        cont._rebind(src_rt, src_dist, _data=old)
        raise


def redistribute_vector(cont, new_dist, rt):
    """Route one ``distributed_vector`` re-layout (the
    ``dr_tpu.redistribute`` vector arm): collective device-side
    exchange when src and dst share a mesh (unless ``host`` is
    forced), host-staged v1 otherwise.  Inside a deferred region an
    eligible move RECORDS into the plan (fusing with its consuming
    chain); the host route flushes announced."""
    from .. import obs as _obs
    from ..utils.fallback import warn_fallback

    impl = impl_mode()
    eligible = cont.runtime.mesh == rt.mesh
    collective = eligible and impl != "host"
    from .. import plan as _plan
    p = _plan.active()
    if p is not None:
        if collective:
            p.record_redistribute(cont, new_dist, rt)
            return cont
        p.nonfusible("redistribute (host-staged route)")
    if collective:
        sid = _obs.begin("redistribute", cat="redistribute",
                         impl="collective", n=len(cont),
                         nshards=rt.nprocs)
        try:
            return _collective(cont, new_dist, rt)
        finally:
            _obs.end(sid)
    if impl == "collective" and not eligible:
        warn_fallback(
            "redistribute",
            "collective impl forced but src and dst do not share a "
            "mesh — taking the host-staged route")
    sid = _obs.begin("redistribute", cat="redistribute", impl="host",
                     n=len(cont), nshards=rt.nprocs)
    try:
        fire_exchange(impl="host", n=len(cont))
        return _host_staged(cont, new_dist, rt)
    finally:
        _obs.end(sid)


def reshard_copy(src, dst) -> None:
    """Cross-mesh scratch move for the sort/scan reshard routes: the
    engine's cross-mesh transport arm (XLA resharding through the
    elementwise copy — the collectives stay native on each side), with
    the engine's fault site, span, and bytes counter, so the
    cross-mesh fuzz arm exercises the same failure surface as every
    other re-layout."""
    from .. import obs as _obs
    n = len(src)
    fire_exchange(impl="reshard", n=n)
    sid = _obs.begin("redistribute", cat="redistribute", impl="reshard",
                     n=n)
    try:
        from ..algorithms.elementwise import copy as _copy
        _copy(src, dst)
        base = dst
        while base is not None and not hasattr(base, "dtype"):
            base = getattr(base, "base", None)
        if base is not None:
            _obs.count("redistribute.bytes_moved",
                       n * jnp.dtype(base.dtype).itemsize)
    finally:
        _obs.end(sid)
