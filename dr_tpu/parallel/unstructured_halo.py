"""Unstructured (index-list) halo: arbitrary gather/scatter ghost exchange.

TPU re-design of the reference's ``index_group`` / ``unstructured_halo``
(``include/dr/details/halo.hpp:148-271``): each rank names, per neighbor,
the element indices it OWNS that the neighbor needs, and holds a ghost
buffer for the indices it needs from others.  The reference packs these
through index arrays into MPI messages (on-device pack via
``Memory::offload``, halo.hpp:181-203).

On TPU there is no p2p message plane — the idiomatic lowering is a global
batched gather (ghosts <- owner cells) and a global batched scatter-reduce
(owner cells <- ghost contributions), each ONE fused XLA program over the
container's sharded array.  Index plumbing is computed once at
construction (the analog of the reference's buffer carving, halo.hpp:27-51)
and baked into cached programs.

Construction mirrors the reference's ``(rank, indices)`` maps
(halo.hpp:244-271): ``owned[r]`` = my indices rank r reads;
``ghosts[r]`` = the global indices I mirror from rank r.
"""

from __future__ import annotations

from typing import Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["unstructured_halo"]


class unstructured_halo:
    """Index-list halo over a distributed_vector.

    ``ghost_indices``: per mesh rank r, the GLOBAL indices of elements
    (owned by whoever) that rank r mirrors locally.  After ``exchange()``,
    ``ghost_values(r)`` returns those mirrored values; after local
    accumulation into ghosts, ``reduce(op)`` folds contributions back into
    the owners.
    """

    def __init__(self, dv, ghost_indices: Dict[int, Sequence[int]]):
        self._dv = dv
        self._by_rank = {int(r): np.asarray(ix, np.int64)
                         for r, ix in ghost_indices.items() if len(ix)}
        # one flat index buffer, carved per rank (halo.hpp:27-51)
        self._offsets = {}
        flat = []
        pos = 0
        for r, ix in sorted(self._by_rank.items()):
            self._offsets[r] = (pos, pos + len(ix))
            flat.append(ix)
            pos += len(ix)
        self._flat = np.concatenate(flat) if flat else np.zeros(0, np.int64)
        # validate ONCE at construction (the analog of the reference's
        # buffer carving: numpy-convention negatives, out-of-range raises)
        # and bake the (shard, column) gather coordinates on device —
        # exchange() then never re-checks or re-uploads
        self._rc = dv._locate(dv._check_indices(self._flat)) \
            if len(self._flat) else None
        self._ghost = jnp.zeros((len(self._flat),), dv.dtype)

    # -- owner -> ghost (exchange, halo.hpp:55-70) -------------------------
    def exchange(self) -> None:
        """Refresh every ghost from its owner: one fused gather."""
        if self._rc is None:
            return
        r, c = self._rc
        self._ghost = self._dv._data[r, c]

    exchange_begin = exchange

    def exchange_finalize(self) -> None:
        jax.block_until_ready(self._ghost)

    def ghost_values(self, rank: int):
        a, b = self._offsets.get(int(rank), (0, 0))
        return self._ghost[a:b]

    def set_ghost_values(self, rank: int, values) -> None:
        """Write local contributions into the ghost buffer (pre-reduce)."""
        a, b = self._offsets[int(rank)]
        self._ghost = self._ghost.at[a:b].set(
            jnp.asarray(values, self._dv.dtype))

    # -- ghost -> owner (reduce, halo.hpp:73-110) --------------------------
    def reduce(self, op: str = "plus") -> None:
        """Fold ghost contributions back into owners: one fused
        scatter-reduce (duplicate indices combine, unlike the reference's
        sequential unpack loop)."""
        if self._rc is None:
            return
        dv = self._dv
        r, c = self._rc
        at = dv._data.at[r, c]
        if op == "plus":
            dv._data = at.add(self._ghost)
        elif op == "max":
            dv._data = at.max(self._ghost)
        elif op == "min":
            dv._data = at.min(self._ghost)
        elif op == "multiplies":
            dv._data = at.multiply(self._ghost)
        elif op == "second":
            dv._data = at.set(self._ghost)
        else:
            raise ValueError(f"unknown reduction op: {op}")

    reduce_begin = reduce

    def reduce_finalize(self) -> None:
        jax.block_until_ready(self._dv._data)
