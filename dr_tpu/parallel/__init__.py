from . import runtime
from .halo import halo_bounds, span_halo, halo_ops
