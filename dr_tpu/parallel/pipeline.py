"""Software-pipelined ring schedules — ONE home for the mesh ring loop.

Every ring program in the package (ring attention's K/V rotation, the
sparse gemv/spmm family's b-block rotation, the ring combine of 2-D
tile partials) is the same shape: a statically-unrolled loop of
``nshards`` steps where each step computes against the block the shard
currently holds and blocks rotate one hop around the ring via
``lax.ppermute`` between steps.  Before round 9 that loop was
hand-written per module (ops/ring_attention.py carried two copies);
this module is the shared schedule with TWO issue orders:

* ``serial`` — compute step t, THEN issue the ppermute for step t+1
  (the historical hand-unrolled order: the transfer cannot start until
  the step's compute has been scheduled).
* ``pipelined`` (default) — issue the ppermute for step t+1 FIRST,
  compute step t against the HELD buffer (double-buffered carry), and
  pair the in-flight blocks with the step's carry through
  ``lax.optimization_barrier`` so XLA cannot re-serialize the transfer
  behind the compute.  The classic communication/computation-overlap
  discipline (Mesh-TensorFlow-style SPMD; "Memory-efficient array
  redistribution through portable collective communication",
  PAPERS.md): on TPU the ICI transfer for round t+1 proceeds while the
  VPU/MXU runs round t.

The two schedules execute the SAME dataflow graph — every value is
computed from the same operands in the same reduction order — so their
results are bit-identical; only the issue order (and therefore what the
backend may overlap) differs.  ``DR_TPU_RING_SCHEDULE`` selects the
default; programs key their caches on the resolved mode so in-process
A/B sweeps rebuild instead of reusing the first-traced schedule.

Fault injection: ``collectives.ppermute`` (utils/faults) is the ring
data plane's site.  ``fire_ppermute`` is called by the dispatchers of
every ring-scheduled program (gemv ring family, ring attention) at
dispatch time — BEFORE the program cache lookup — so an armed fault
drops the dispatch with containers untouched, exactly like the
``collectives.shift`` site.
"""

from __future__ import annotations

from ..utils.env import env_str
from typing import Any, Callable, List, Optional, Tuple

import jax
from jax import lax

from ..utils import faults as _faults

__all__ = ["ring_perm", "shift_perm", "schedule_mode", "ring_pipeline",
           "ring_allgather", "ring_combine", "ring_exchange",
           "fire_ppermute"]


def ring_perm(nshards: int) -> List[Tuple[int, int]]:
    """The forward ring permutation (shard i's block moves to i+1)."""
    return [(i, (i + 1) % nshards) for i in range(nshards)]


def shift_perm(nshards: int, t: int) -> List[Tuple[int, int]]:
    """The offset-``t`` collective permutation (shard i's bucket moves
    DIRECTLY to shard i+t) — one hop distance of the
    :func:`ring_exchange` decomposition."""
    return [(i, (i + t) % nshards) for i in range(nshards)]


def schedule_mode() -> str:
    """The ring issue order: ``DR_TPU_RING_SCHEDULE`` in
    {``pipelined``, ``serial``}; malformed values fall back to the
    pipelined default (a typo in a tuning sweep must not brick every
    ring program at trace time)."""
    mode = env_str("DR_TPU_RING_SCHEDULE").lower()
    return mode if mode in ("pipelined", "serial") else "pipelined"


def fire_ppermute(**ctx) -> None:
    """Dispatch-time hook for the ``collectives.ppermute`` fault site:
    every ring-program dispatcher calls this before its program-cache
    lookup, so an armed fault surfaces classified with no partial
    dispatch behind it."""
    _faults.fire("collectives.ppermute", **ctx)


def ring_pipeline(axis: str, nshards: int, carry: Any, blocks: Any,
                  compute: Callable[[int, Any, Any], Any], *,
                  perm: Optional[List[Tuple[int, int]]] = None,
                  schedule: Optional[str] = None,
                  restore_blocks: bool = False):
    """Statically-unrolled ring loop (trace-time; call inside a
    ``shard_map`` body).

    ``carry = compute(t, carry, blocks)`` runs once per step with
    ``blocks`` (any pytree) holding the buffers that have been rotated
    ``t`` hops: at step t a shard started at rank d holds rank
    ``(d - t) % nshards``'s blocks.  Between steps the blocks rotate
    one hop via ``lax.ppermute`` over ``axis``; the issue order follows
    ``schedule`` (:func:`schedule_mode` when None).  The pipelined
    schedule issues the rotation BEFORE the step's compute and pairs
    the in-flight blocks with the carry through
    ``lax.optimization_barrier`` — bit-identical to serial (same
    dataflow, same reduction order), only the overlap differs.

    ``restore_blocks=True`` adds the final nshards-th rotation so the
    blocks return to their origin shard and returns ``(carry,
    blocks)`` — the form a fused ``*_n`` measurement loop needs so
    every iteration starts from the same placement.
    """
    sched = schedule or schedule_mode()
    p = ring_perm(nshards) if perm is None else perm

    def rotate(bs):
        return jax.tree_util.tree_map(
            lambda x: lax.ppermute(x, axis, p), bs)

    for t in range(nshards):
        rotate_after = (t + 1 < nshards) or restore_blocks
        if sched == "pipelined" and rotate_after:
            nxt = rotate(blocks)           # in flight during compute t
            carry = compute(t, carry, blocks)
            # pair transfer and compute: without the barrier XLA may
            # sink the ppermute below the step's compute (re-serialize)
            nxt, carry = lax.optimization_barrier((nxt, carry))
            blocks = nxt
        else:
            carry = compute(t, carry, blocks)
            if rotate_after:
                blocks = rotate(blocks)
    return (carry, blocks) if restore_blocks else carry


def ring_exchange(axis: str, nshards: int, carry, make_bucket,
                  consume, *, steps: Optional[List[int]] = None,
                  schedule: Optional[str] = None):
    """Offset-permute exchange (trace-time; call inside a
    ``shard_map`` body) — the collective decomposition of
    arXiv:2112.01075 on this mesh's ring: for each hop distance ``t``
    in ``steps`` (default ``1..nshards-1``), every shard sends ONE
    statically-shaped bucket (``make_bucket(t)``, any pytree) DIRECTLY
    to the shard ``t`` hops ahead via :func:`shift_perm`, and folds the
    bucket arriving from ``t`` hops behind into the carry:
    ``carry = consume(t, carry, bucket)``.

    Unlike :func:`ring_pipeline` (which FORWARDS one rotating block
    around the ring), nothing is relayed: each step's bucket goes
    point-to-point, so peak extra memory is ONE in-flight bucket — the
    largest transfer bucket, never an accumulated replica.  Callers
    drop zero-length hops from ``steps`` (a src→dst layout diff that
    moves nothing at distance t costs nothing — the minimal-sequence
    property).

    The issue orders mirror :func:`ring_pipeline`: ``serial`` sends
    and consumes hop t before issuing hop t+1; ``pipelined`` (default)
    issues hop t+1's ppermute BEFORE consuming hop t's arrival and
    pairs them through ``lax.optimization_barrier`` so the ICI
    transfer overlaps the scatter.  Each consume reads only its own
    arrival and the threaded carry — the same dataflow either way, so
    the two schedules are bit-identical.
    """
    sched = schedule or schedule_mode()
    hops = list(range(1, nshards)) if steps is None else list(steps)

    def send(t):
        p = shift_perm(nshards, t)
        return jax.tree_util.tree_map(
            lambda x: lax.ppermute(x, axis, p), make_bucket(t))

    if sched == "pipelined" and hops:
        inflight = send(hops[0])
        for i, t in enumerate(hops):
            nxt = send(hops[i + 1]) if i + 1 < len(hops) else None
            carry = consume(t, carry, inflight)
            if nxt is not None:
                # pair transfer and scatter: without the barrier XLA
                # may sink the next hop's ppermute below this hop's
                # consume (re-serialize)
                nxt, carry = lax.optimization_barrier((nxt, carry))
            inflight = nxt
        return carry
    for t in hops:
        carry = consume(t, carry, send(t))
    return carry


def ring_allgather(axis: str, nshards: int, block, *,
                   schedule: Optional[str] = None):
    """Every shard's ``block`` stacked source-rank-first:
    ``(nshards,) + block.shape``, built from nshards-1 ring rotations
    (trace-time; call inside a ``shard_map`` body).  Slot ``s`` holds
    rank s's block on EVERY shard, so any fold over axis 0 runs in the
    same canonical order everywhere — the property :func:`ring_combine`
    needs for cross-shard bitwise agreement."""
    import jax.numpy as jnp
    my = lax.axis_index(axis)
    buf = jnp.zeros((nshards,) + block.shape, block.dtype)

    def place(t, acc, blk):
        src = (my - t) % nshards
        return lax.dynamic_update_slice(
            acc, blk[None], (src,) + (0,) * block.ndim)

    return ring_pipeline(axis, nshards, buf, block, place,
                         schedule=schedule)


def ring_combine(axis: str, nshards: int, x, *,
                 schedule: Optional[str] = None):
    """Ring all-reduce (sum) of ``x`` over ``axis``: all-gather around
    the ring, then ONE canonical-order sum over the stacked sources —
    every shard folds ranks 0..nshards-1 in the same order, so the
    result is bitwise identical across shards and across the
    serial/pipelined schedules (a rotate-and-accumulate ring would sum
    in a different order on every shard).  The ``psum`` alternative is
    usually faster on TPU (the 2-D gemv/spmm programs default to it);
    this is the ring arm for the DR_TPU_SPMV_COMBINE A/B."""
    if nshards == 1:
        return x
    return ring_allgather(axis, nshards, x, schedule=schedule).sum(0)
