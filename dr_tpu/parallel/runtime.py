"""TPU runtime: device mesh lifecycle for the ``thp`` execution backend.

This is the TPU-native analog of the reference's two runtimes:

* ``mhp::init()`` — MPI SPMD context (reference
  ``include/dr/mhp/global.hpp:24-47``), and
* ``shp::init(devices)`` — one process driving multiple SYCL GPUs through a
  shared context (reference ``include/dr/shp/init.hpp:40-50``).

On TPU both collapse into one model: a single controller owning a
``jax.sharding.Mesh`` of devices.  Intra-host device-to-device traffic rides
ICI via XLA collectives; the multi-host (MHP) dimension rides DCN via
``jax.distributed`` with the *same* mesh abstraction.  Where the reference
tracks per-container MPI RMA windows and fences them globally
(``mhp/global.hpp:41-47``), JAX arrays are values: ``fence()`` maps to
``jax.block_until_ready`` on outstanding container state.
"""

from __future__ import annotations

import os
from ..utils.env import env_str
import weakref
from dataclasses import dataclass, field
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils import faults as _faults
from ..utils import resilience as _resilience

__all__ = [
    "init",
    "final",
    "finalize",
    "runtime",
    "is_initialized",
    "nprocs",
    "devices",
    "mesh",
    "barrier",
    "fence",
    "probe_devices",
    "probe_recovered",
    "setup_compile_cache",
    "Runtime",
    "get_duplicated_devices",
]


@dataclass
class Runtime:
    """Global execution context: the device mesh and its shardings.

    ``axis`` is the 1-D vector-distribution axis (the analog of MPI rank
    space / the SHP device list); matrices tile over a 2-D view of the same
    devices (see ``dr_tpu.containers.partition``).
    """

    mesh: Mesh
    axis: str = "x"
    #: containers register here so ``fence()`` can sync them, mirroring the
    #: reference's active-window set (mhp/global.hpp:26).  Weak references:
    #: dropped containers (and their device arrays) stay collectable.
    _live: "weakref.WeakSet" = field(default_factory=weakref.WeakSet)

    @property
    def nprocs(self) -> int:
        return self.mesh.shape[self.axis]

    @property
    def devices(self):
        return list(self.mesh.devices.reshape(-1))

    def sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))

    def mesh2d(self, grid, names=("mr", "mc")) -> Mesh:
        """2-D mesh view over (a prefix of) the same devices — the
        substrate for tiled matrices (tp-style 2-D sharding).  Cached per
        grid shape."""
        gp, gq = grid
        if gp * gq > len(self.devices):
            raise ValueError(
                f"grid {grid} needs {gp*gq} devices, mesh has "
                f"{len(self.devices)}")
        cache = self.__dict__.setdefault("_mesh2d_cache", {})
        key = (gp, gq, names)
        m = cache.get(key)
        if m is None:
            devs = np.asarray(self.devices[:gp * gq]).reshape(gp, gq)
            m = Mesh(devs, names)
            cache[key] = m
        return m

    @property
    def block_sharding(self) -> NamedSharding:
        """Sharding for the canonical (nprocs, segment) container layout."""
        return NamedSharding(self.mesh, P(self.axis))

    @property
    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def register(self, container) -> None:
        self._live.add(container)

    def live_containers(self) -> list:
        """Snapshot of the registered live containers — the population
        the elastic shrink rescue walks (utils/elastic.py, SPEC §16).
        Weak registration: only containers the user still holds appear."""
        return list(self._live)

    def fence(self) -> None:
        """Block until every registered container's current value is ready.

        The reference fences all active RMA windows (mhp/global.hpp:41-47);
        here array versions are values, so a fence is a readiness barrier.
        """
        from ..plan import flush_reads
        flush_reads("fence")
        for c in list(self._live):
            data = getattr(c, "_data", None)
            if data is not None:
                jax.block_until_ready(data)

    def barrier(self) -> None:
        # Single-controller: program order is the barrier and fence()
        # drains dispatched work.  Multi-process: a REAL rendezvous
        # (the reference's mhp::barrier is MPI_Barrier) — device
        # collectives synchronize devices, not host-side progress, so
        # host effects (checkpoint writes, logs) need this to order
        # across processes (round-3 4-proc checkpoint race).
        self.fence()
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("dr_tpu_barrier")


_runtime: Optional[Runtime] = None

_compile_cache_wired = False


def setup_compile_cache() -> Optional[str]:
    """Wire the jax PERSISTENT compilation cache from
    ``DR_TPU_COMPILE_CACHE_DIR`` (idempotent; called by :func:`init`).

    Tunneled sessions are one process per bench/tune/entry run, and the
    remote compiler re-pays every program's compile per process — tens
    of seconds for the blocked-stencil and sort programs.  Pointing the
    cache at a directory makes later processes load the serialized
    executables instead.  Thresholds drop to zero: on this backend the
    dispatch constant alone dwarfs a cache read, so even cheap programs
    are worth persisting.  Returns the wired directory, or None when
    the variable is unset or wiring failed (wiring failure warns and
    degrades to the in-memory default — never blocks init)."""
    global _compile_cache_wired
    path = env_str("DR_TPU_COMPILE_CACHE_DIR")
    if not path or _compile_cache_wired:
        return path or None
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        for opt, val in (
                ("jax_persistent_cache_min_compile_time_secs", 0.0),
                ("jax_persistent_cache_min_entry_size_bytes", -1)):
            try:
                jax.config.update(opt, val)
            # drlint: ok[R5] capability probe: older jax lacks the knob and the cache still works without it
            except Exception:  # pragma: no cover - older jax knob set
                pass
        _compile_cache_wired = True
        return path
    except Exception as e:  # pragma: no cover - defensive
        from ..utils.fallback import warn_fallback
        warn_fallback(
            "runtime",
            f"DR_TPU_COMPILE_CACHE_DIR={path!r}: persistent compile "
            f"cache not wired ({e!r}); continuing with the in-memory "
            "cache")
        return None


def probe_devices(timeout_s: float):
    """First backend touch behind a watchdog (resilience.with_deadline):
    ``(devices, None)`` on success, ``(None, error_repr_or_timeout)``
    otherwise.

    A wedged tunnel relay makes ``jax.devices()`` block forever inside
    the PJRT client (observed when an earlier client died mid-claim and
    the chip's server-side grant had not expired).  Callers decide the
    policy — fail fast, record an error artifact, or fall back to a
    virtual mesh; this helper only guarantees the probe terminates.
    Injection site ``runtime.probe`` (utils/faults) makes both failure
    legs exercisable on the CPU mesh."""
    try:
        _faults.fire("runtime.probe")
        # dump=False: a probe timeout is a ROUTED decision (retry / CPU
        # fallback), not a hang needing a dispatch postmortem — no
        # guard is active this early anyway
        return _resilience.with_deadline(
            jax.devices, timeout_s, site="runtime.probe",
            dump=False), None
    except _resilience.DeadlineExpired:
        return None, (f"device init exceeded {timeout_s:.0f}s "
                      "(wedged tunnel relay?)")
    except Exception as e:  # pragma: no cover - backend specific
        return None, repr(e)[:200]


def probe_recovered(timeout_s: float = 30.0):
    """Devices the backend exposes BEYOND the current mesh — the
    grow-back candidates (utils/elastic.grow_session, docs/SPEC.md
    §16.6).  Fires the ``device.recover`` injection site, so a chaos
    spec can fail any recovery probe classified; the device listing
    runs under the deadline watchdog, so a half-returned relay costs at
    most ``timeout_s``, never a hang.  Returns ``[]`` when the runtime
    is uninitialized (nothing to grow back onto) or every visible
    device is already meshed.

    Claim-free relative to OTHER processes: this only re-lists the
    devices the CURRENT process's backend client already owns — it
    must be called from the claim holder between batches/flushes (the
    one-TPU-process rule), which is exactly where the grow supervisor
    polls it."""
    _faults.fire("device.recover")
    if not is_initialized():
        return []
    have = {d.id for d in _runtime.devices}
    devs = _resilience.with_deadline(jax.devices, timeout_s,
                                     site="device.recover", dump=False)
    return [d for d in devs if d.id not in have]


def get_duplicated_devices(n: int, devices: Optional[Sequence] = None):
    """Pad the device list by repetition to reach ``n`` entries.

    Port of the reference's multi-device faking used to test an N-GPU node
    on fewer GPUs (``shp/util.hpp:119-136``).  On TPU the preferred fake is
    ``--xla_force_host_platform_device_count`` (see tests/conftest.py), but
    duplication is kept for API parity and for oversubscribing one real chip.
    """
    devices = list(devices if devices is not None else jax.devices())
    if not devices:
        raise RuntimeError("no JAX devices visible")
    return [devices[i % len(devices)] for i in range(n)]


def init(
    devices: Optional[Sequence] = None,
    *,
    nprocs: Optional[int] = None,
    axis: str = "x",
) -> Runtime:
    """Initialize the global runtime over a 1-D device mesh.

    Analog of ``mhp::init()`` / ``shp::init(devices)``.  A jax Mesh cannot
    repeat a physical device, so ``nprocs`` must be <= the device count;
    to fake a larger mesh use ``--xla_force_host_platform_device_count``
    (the TPU analog of the reference's device duplication,
    shp/util.hpp:119-136 — see tests/conftest.py).
    """
    global _runtime
    _faults.fire("runtime.init")
    setup_compile_cache()
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if nprocs is not None:
        if nprocs > len(devices):
            raise ValueError(
                f"nprocs={nprocs} exceeds the {len(devices)} visible "
                "devices; a TPU mesh cannot repeat a device — fake a "
                "larger mesh with "
                "XLA_FLAGS=--xla_force_host_platform_device_count=N")
        devices = devices[:nprocs]
    if len({d.id for d in devices}) != len(devices):
        raise ValueError("device list contains duplicates; a mesh needs "
                         "distinct devices")
    mesh = Mesh(np.asarray(devices), (axis,))
    _runtime = Runtime(mesh=mesh, axis=axis)
    return _runtime


def runtime() -> Runtime:
    if _runtime is None:
        init()
    return _runtime  # type: ignore[return-value]


def is_initialized() -> bool:
    return _runtime is not None


def final() -> None:
    """Tear down the global context (``mhp::final``, mhp/global.hpp:30-33)."""
    global _runtime
    if _runtime is not None:
        _runtime.fence()
    _runtime = None


finalize = final


def nprocs() -> int:
    return runtime().nprocs


def devices():
    return runtime().devices


def mesh() -> Mesh:
    return runtime().mesh


def barrier() -> None:
    runtime().barrier()


def fence() -> None:
    runtime().fence()
